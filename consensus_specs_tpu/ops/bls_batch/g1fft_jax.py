"""Batched G1 FFT butterflies + the FK20 circulant MSM — the producer
kernels behind `das/compute.py`'s all-proofs path.

FK20 (the polynomial-multiproofs route) factors the 128 cell proofs of
one blob through three linear stages over the order-128 root-of-unity
domain:

    hext_j = sum_c  FFT_fr(B^c)_j * X_fft^c_j      (the one MSM)
    C      = IFFT_G1(hext);  E_d = C_{127-d} (d<63), infinity otherwise
    proofs = brp( FFT_G1(E) )

where X_fft^c = FFT_G1 of the residue-c trusted-setup vector — the
bit-reversed Toeplitz/circulant extended-setup tables, computed here as
ONE batched 64-lane G1 FFT at first use and pinned device-resident for
the life of the process (`das/compute.py` owns the cache; this module
owns the kernels).

A G1 FFT is the field FFT with the butterfly's twiddle multiply lifted
to scalar-times-point: log2(n) butterfly rounds, each one windowed
scalar multiplication of the v half (the twiddles are HOST-KNOWN
constants per (n, stage), so each lane's digit schedule bakes into the
kernel and the multiply costs ~64 window steps instead of a 255-step
generic double-and-add) and two point additions.  Shapes ride a pow2
rung ladder (`g1fft_rung`) so jit caches stay tiny; padded lanes are
the point at infinity, which the branchless `curve_jax` formulas
absorb — zero-padding a coefficient vector just evaluates the same
polynomial on the larger domain.

The hext stage is a per-output-position MSM (for each j, a 64-point
sum over the residue classes) run as `pt_msm_pippenger` vmapped over
the 128 positions — digits enter host-side (the field FFT settles to
canonical ints first), points stay device-resident.
"""

from __future__ import annotations

import functools

import numpy as np

from ... import telemetry
from ..bls import curve as _pycurve
from ..fr_batch import R_MODULUS
from . import curve_jax as cj
from . import fq as _fq

# primitive root of the scalar field (the KZG PRIMITIVE_ROOT_OF_UNITY);
# the domain derivation must match `das.ciphersuite.roots_of_unity`
_PRIMITIVE_ROOT = 7

# windowed twiddle multiply: 4-bit windows are the sweet spot for a
# 16-entry shared table per butterfly lane (evens by doubling, odds by
# one add) against ceil(255/4) = 64 window steps
_TW_WINDOW = 4

# FK20 hext MSMs are 64 points each (one per residue class): 16 buckets
# keep the scatter phase at 64 steps and the suffix reduction tiny
_FK20_WINDOW = 4

# point-vector shape ladder: the bottom rung covers the tiny parity
# domains the unit tests drive, the top rung IS the FK20 extended
# domain (CELLS_PER_EXT_BLOB); larger vectors fall back to powers of two
_G1FFT_STEPS = (8, 128)


def _jnp():
    import jax.numpy as jnp
    return jnp


def g1fft_rung(n: int) -> int:
    """Padded point-vector shape for an n-point transform (the
    compile-key launderer the analyzer recognizes, like `_bucket` /
    `das_rung`)."""
    b = 1 if n <= 1 else 1 << (n - 1).bit_length()
    for step in _G1FFT_STEPS:
        if b <= step:
            return step
    return b


@functools.lru_cache(maxsize=8)
def fft_domain(n: int) -> tuple:
    """Order-n roots of unity (w^0 .. w^(n-1)) — same derivation as
    `das.ciphersuite.roots_of_unity` (pinned by tests)."""
    assert n and n & (n - 1) == 0
    w = pow(_PRIMITIVE_ROOT, (R_MODULUS - 1) // n, R_MODULUS)
    return tuple(pow(w, i, R_MODULUS) for i in range(n))


@functools.lru_cache(maxsize=8)
def _bitrev_perm(n: int) -> tuple:
    bits = n.bit_length() - 1
    return tuple(int(f"{i:0{bits}b}"[::-1], 2) if bits else 0
                 for i in range(n))


@functools.lru_cache(maxsize=8)
def _stage_plan(n: int, inverse: bool) -> tuple:
    """Shape-uniform butterfly schedule: every round pairs the same
    n/2 lane count, so the rounds ride ONE `lax.scan` (one compiled
    stage body regardless of log n — per-round shapes would compile
    log n bodies).  Returns (u_idx, v_idx, digits) stacked over the
    log2(n) rounds: round s (half-width h = 2^s) pairs positions
    (b*2h + i, b*2h + h + i) and multiplies the v half by
    roots[i * n/(2h)], encoded as MSB-first window digits."""
    roots = list(fft_domain(n))
    if inverse:
        roots = [roots[0]] + roots[:0:-1]
    half = n // 2
    u_rows, v_rows, d_rows = [], [], []
    h = 1
    while h < n:
        stride = n // (2 * h)
        u_idx = np.empty(half, dtype=np.int32)
        v_idx = np.empty(half, dtype=np.int32)
        tw = []
        for lane in range(half):
            b, i = divmod(lane, h)
            u_idx[lane] = b * 2 * h + i
            v_idx[lane] = u_idx[lane] + h
            tw.append(roots[i * stride])
        u_rows.append(u_idx)
        v_rows.append(v_idx)
        d_rows.append(cj.scalars_to_digits(tw, 255, _TW_WINDOW))
        h *= 2
    return (np.stack(u_rows), np.stack(v_rows), np.stack(d_rows))


def _windowed_mul(v, digs):
    """p -> k*p for per-lane scalars known as window digits: a
    16-entry multiple table (built once per round over every lane) and
    one scan over the MSB-first windows — 4 doublings and one
    table-gather add per step.  Digit 0 gathers the infinity entry,
    which `pt_add` absorbs."""
    import jax
    jnp = _jnp()

    table_n = 1 << _TW_WINDOW
    T = [cj.pt_infinity(cj.F1, v), v]
    for d in range(2, table_n):
        T.append(cj.pt_double(cj.F1, T[d // 2]) if d % 2 == 0
                 else cj.pt_add(cj.F1, T[d - 1], v))
    # (table_n, ..., h, 33) per coordinate
    table = tuple(jnp.stack([t[i] for t in T]) for i in range(3))
    lane = jnp.arange(v[0].shape[-2])

    def step(acc, d):
        for _ in range(_TW_WINDOW):
            acc = cj.pt_double(cj.F1, acc)
        sel = tuple(jnp.moveaxis(tc[d, ..., lane, :], 0, -2)
                    for tc in table)
        return cj.pt_add(cj.F1, acc, sel), None

    acc0 = cj.pt_infinity(cj.F1, v)
    acc, _ = jax.lax.scan(step, acc0, jnp.moveaxis(digs, -1, 0))
    return acc


@functools.lru_cache(maxsize=8)
def _g1_fft_kernel(n: int, batch: int, inverse: bool):
    """Jitted batched G1 FFT: coords (B, n, 33) int32 Jacobian limbs in
    BIT-REVERSED order (Z == 0 encodes infinity), natural-order output.
    One scan over the log2(n) butterfly rounds — each round gathers its
    (u, v) pairs, windowed-multiplies v by its twiddle, and scatters
    u + t / u - t back in place.  The inverse transform runs the
    reversed-root rounds then one fixed scalar multiply by 1/n
    (`pt_scalar_mul_const` — the bit schedule rides the scan's xs)."""
    import jax
    jnp = _jnp()

    plan = _stage_plan(n, inverse)
    inv_bits = None
    if inverse:
        inv_n = pow(n, R_MODULUS - 2, R_MODULUS)
        inv_bits = np.array([int(b) for b in bin(inv_n)[2:]],
                            dtype=np.int32)

    def stage(p, xs):
        u_idx, v_idx, digs = xs
        u = tuple(c[:, u_idx] for c in p)
        v = tuple(c[:, v_idx] for c in p)
        t = _windowed_mul(v, digs)
        plus = cj.pt_add(cj.F1, u, t)
        minus = cj.pt_add(cj.F1, u, cj.pt_neg(cj.F1, t))
        p = tuple(c.at[:, ui].set(pl).at[:, vi].set(mi)
                  for c, ui, vi, pl, mi in zip(
                      p, (u_idx,) * 3, (v_idx,) * 3, plus, minus))
        return p, None

    def run(x, y, z):
        xs = tuple(jnp.asarray(a) for a in plan)
        p, _ = jax.lax.scan(stage, (x, y, z), xs)
        if inv_bits is not None:
            p = cj.pt_scalar_mul_const(cj.F1, p, inv_bits)
        return p

    return jax.jit(run)


@functools.lru_cache(maxsize=4)
def _fk20_hext_kernel(n_residues: int, width: int):
    """Jitted FK20 circulant MSM: for each of the `width` extended
    positions j, sum the `n_residues` scalar-point products — one
    `pt_msm_pippenger` per position, vmapped over j.  Points carry a Z
    coordinate so the setup tables' infinity lanes pass through (they
    land in buckets but add nothing); zero digits land in bucket 0,
    which the reduction skips."""
    import jax

    def run(x, y, z, digits):
        # x/y/z: (n_residues, width, 33); digits: (n_residues, width, W)
        def one(xx, yy, zz, dd):
            return cj.pt_msm_pippenger(cj.F1, (xx, yy, zz), dd,
                                       _FK20_WINDOW)

        return jax.vmap(one, in_axes=(1, 1, 1, 1))(x, y, z, digits)

    return jax.jit(run)


# --- host conversions --------------------------------------------------------


def points_to_limbs(points, pad_to: int | None = None):
    """Oracle Jacobian points -> (x, y, z) Montgomery limb stacks with
    infinity SUPPORT (unlike `g1_affine_to_limbs`): infinities map to
    (1, 1, 0), the branchless kernels' canonical encoding.  `pad_to`
    appends infinity lanes up to the rung."""
    n = pad_to if pad_to is not None else len(points)
    one = _fq.to_mont(1)
    xs = np.zeros((n, _fq.N_LIMBS), dtype=np.int32)
    ys = np.zeros((n, _fq.N_LIMBS), dtype=np.int32)
    zs = np.zeros((n, _fq.N_LIMBS), dtype=np.int32)
    xs[:], ys[:] = one, one
    for i, p in enumerate(points):
        aff = _pycurve.g1.to_affine(p)
        if aff is None:
            continue
        xs[i] = _fq.to_mont(aff[0])
        ys[i] = _fq.to_mont(aff[1])
        zs[i] = one
    return xs, ys, zs


def limbs_to_oracle_list(p) -> list:
    """Device Jacobian coord stacks (..., n, 33) -> list of oracle
    Jacobian tuples (leading axes flattened away, n preserved)."""
    X, Y, Z = (np.asarray(c).reshape(-1, _fq.N_LIMBS) for c in p)
    return [(_fq.from_mont(x), _fq.from_mont(y), _fq.from_mont(z))
            for x, y, z in zip(X, Y, Z)]


# --- entry points ------------------------------------------------------------


def g1_fft_device(x, y, z, inverse: bool = False, block: bool = True):
    """Device-level G1 (I)FFT: coords (B, n, 33) int32 in NATURAL
    order, returns device coords (B, n, 33) — the FK20 chain's internal
    hop (points never leave the device between stages).  Host-side
    bit-reversal is an index permutation on the way in."""
    from ..bls_batch import _dispatch

    jnp = _jnp()
    batch, n = int(x.shape[0]), int(x.shape[1])
    perm = np.array(_bitrev_perm(n))
    with telemetry.span("bls.g1_fft_device", n=n, batch=batch,
                        inverse=bool(inverse)):
        telemetry.count("g1fft.device_calls")
        telemetry.count("g1fft.butterfly_rounds", n.bit_length() - 1)
        args = tuple(jnp.asarray(c)[:, perm] for c in (x, y, z))
        tag = "i" if inverse else "f"
        out = _dispatch(
            f"g1_fft@{n}x{batch}{tag}",
            # cst: allow(recompile-unbucketed-dim): n is g1fft_rung-
            # laundered by every caller and batch is the FK20 residue
            # count (64) or a single vector — a handful of compiles
            # per process
            _g1_fft_kernel(n, batch, bool(inverse)),
            args, block=block)
    return out


def g1_fft_async(points, inverse: bool = False, block: bool = True):
    """G1 FFT of an oracle point vector over the order-`g1fft_rung(n)`
    root-of-unity domain (short vectors are zero-padded — i.e. the
    same polynomial evaluated on the rung domain).  Settles to a list
    of oracle Jacobian points.

    The transform matches the field `_fft` shape exactly: out_i =
    sum_j w^(i*j) * P_j with w the rung-order primitive root — parity
    vs naive per-point evaluation is pinned by tests/test_das.py."""
    from ...serve.futures import value_future
    from .. import bls_batch as _bb

    n_live = len(points)
    assert n_live >= 1
    rung = g1fft_rung(n_live)
    with telemetry.span("bls.g1_fft", live=n_live, padded=rung,
                        inverse=bool(inverse)):
        telemetry.count("g1fft.calls")
        _bb._count_lanes(n_live, rung)
        x, y, z = points_to_limbs(points, pad_to=rung)
        out = g1_fft_device(x[None], y[None], z[None],
                            inverse=inverse, block=block)
    return value_future(out, convert=limbs_to_oracle_list)


def g1_fft(points, inverse: bool = False) -> list:
    """Synchronous facade over `g1_fft_async`."""
    return g1_fft_async(points, inverse=inverse).result()


def fk20_hext_device(x, y, z, scalars, block: bool = True):
    """The FK20 'one MSM': device setup-table coords (n_residues,
    width, 33) against host canonical scalar rows (n_residues x width
    ints, the settled field-FFT outputs) -> device coords (width, 33)
    of hext_j = sum_c scalars[c][j] * X[c][j]."""
    from ..bls_batch import _dispatch

    jnp = _jnp()
    n_res, width = int(x.shape[0]), int(x.shape[1])
    flat = [int(s) % R_MODULUS for row in scalars for s in row]
    assert len(flat) == n_res * width
    with telemetry.span("bls.fk20_hext", residues=n_res, width=width):
        telemetry.count("g1fft.hext_calls")
        digits = cj.scalars_to_digits(flat, 255, _FK20_WINDOW).reshape(
            n_res, width, -1)
        out = _dispatch(
            f"fk20_hext@{n_res}x{width}",
            # cst: allow(recompile-unbucketed-dim): (n_residues, width)
            # is the FK20 circulant shape — preset-fixed at (64, 128) —
            # so the kernel compiles once per process
            _fk20_hext_kernel(n_res, width),
            (jnp.asarray(x), jnp.asarray(y), jnp.asarray(z),
             jnp.asarray(digits)), block=block)
    return out
