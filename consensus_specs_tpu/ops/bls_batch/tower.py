"""Batched Fq2/Fq6/Fq12 tower arithmetic on 12-bit-limb Fq vectors.

Mirrors the pure-Python oracle's Karatsuba formulas (`ops/bls/fields.py`)
but flattens every multiplication level into ONE stacked `fq_mul` call, so
an Fq12 product is a single 33-step Montgomery scan over an 18x-wider
batch instead of 18 small scans — the shape XLA/TPU wants.

Representations (batch-first, int32):
    Fq2  : (..., 2, 33)
    Fq6  : (..., 3, 2, 33)
    Fq12 : (..., 2, 3, 2, 33)
"""

from __future__ import annotations

import numpy as np

from ..bls import fields as _f
from .fq import (
    N_LIMBS,
    fq_add,
    fq_canon,
    fq_inv,
    fq_mul,
    fq_mul_small,
    fq_neg,
    fq_sub,
    to_mont,
    from_mont,
)


def _jnp():
    import jax.numpy as jnp
    return jnp


# --- host conversions -------------------------------------------------------


def fq2_from_oracle(a: _f.Fq2) -> np.ndarray:
    return np.stack([to_mont(a.c0), to_mont(a.c1)])


def fq6_from_oracle(a: _f.Fq6) -> np.ndarray:
    return np.stack([fq2_from_oracle(a.c0), fq2_from_oracle(a.c1),
                     fq2_from_oracle(a.c2)])


def fq12_from_oracle(a: _f.Fq12) -> np.ndarray:
    return np.stack([fq6_from_oracle(a.c0), fq6_from_oracle(a.c1)])


def fq2_to_oracle(a) -> _f.Fq2:
    a = np.asarray(a).reshape(2, N_LIMBS)
    return _f.Fq2(from_mont(a[0]), from_mont(a[1]))


def fq6_to_oracle(a) -> _f.Fq6:
    a = np.asarray(a).reshape(3, 2, N_LIMBS)
    return _f.Fq6(*(fq2_to_oracle(c) for c in a))


def fq12_to_oracle(a) -> _f.Fq12:
    a = np.asarray(a).reshape(2, 3, 2, N_LIMBS)
    return _f.Fq12(*(fq6_to_oracle(c) for c in a))


FQ2_ONE_L = fq2_from_oracle(_f.FQ2_ONE)
FQ2_ZERO_L = fq2_from_oracle(_f.FQ2_ZERO)
FQ6_ONE_L = fq6_from_oracle(_f.FQ6_ONE)
FQ12_ONE_L = fq12_from_oracle(_f.FQ12_ONE)
_GAMMA_L = [fq2_from_oracle(g) for g in _f._GAMMA]


# --- Fq2 --------------------------------------------------------------------


def fq2_add(a, b):
    return fq_add(a, b)


def fq2_sub(a, b):
    return fq_sub(a, b)


def fq2_neg(a):
    return fq_neg(a)


def fq2_conj(a):
    jnp = _jnp()
    return jnp.stack([a[..., 0, :], fq_neg(a[..., 1, :])], axis=-2)


def fq2_mul(a, b):
    """Karatsuba: one stacked fq_mul of 3 products."""
    jnp = _jnp()
    a0, a1 = a[..., 0, :], a[..., 1, :]
    b0, b1 = b[..., 0, :], b[..., 1, :]
    pa = jnp.stack([a0, a1, fq_add(a0, a1)])
    pb = jnp.stack([b0, b1, fq_add(b0, b1)])
    t = fq_mul(pa, pb)
    t0, t1, t2 = t[0], t[1], t[2]
    return jnp.stack([fq_sub(t0, t1), fq_sub(t2, fq_add(t0, t1))], axis=-2)


def fq2_sqr(a):
    """(a+b)(a-b) + 2ab u — one stacked fq_mul of 2 products."""
    jnp = _jnp()
    a0, a1 = a[..., 0, :], a[..., 1, :]
    pa = jnp.stack([fq_add(a0, a1), a0])
    pb = jnp.stack([fq_sub(a0, a1), a1])
    t = fq_mul(pa, pb)
    return jnp.stack([t[0], fq_mul_small(t[1], 2)], axis=-2)


def fq2_mul_fq(a, s):
    """Fq2 * Fq scalar (s: (..., 33))."""
    jnp = _jnp()
    return fq_mul(a, s[..., None, :])


def fq2_mul_small(a, k: int):
    return fq_mul_small(a, k)


def fq2_mul_xi(a):
    """* (1 + u):  (c0 - c1, c0 + c1)."""
    jnp = _jnp()
    a0, a1 = a[..., 0, :], a[..., 1, :]
    return jnp.stack([fq_sub(a0, a1), fq_add(a0, a1)], axis=-2)


def fq2_inv(a):
    """Norm-based inverse; 0 maps to 0 (RFC 9380 inv0 semantics — the
    device SVDW map in `h2c_jax` relies on this)."""
    jnp = _jnp()
    a0, a1 = a[..., 0, :], a[..., 1, :]
    t = fq_mul(jnp.stack([a0, a1]), jnp.stack([a0, a1]))
    d = fq_inv(fq_add(t[0], t[1]))
    out = fq_mul(jnp.stack([a0, fq_neg(a1)]), d[None])
    return jnp.moveaxis(out, 0, -2)


def fq2_is_zero(a):
    jnp = _jnp()
    return jnp.all(fq_canon(a) == 0, axis=(-1, -2))


def fq2_eq(a, b):
    jnp = _jnp()
    return jnp.all(fq_canon(a) == fq_canon(b), axis=(-1, -2))


# --- Fq6 --------------------------------------------------------------------


def fq6_add(a, b):
    return fq_add(a, b)


def fq6_sub(a, b):
    return fq_sub(a, b)


def fq6_mul(a, b):
    """Toom/Karatsuba (oracle formula): 6 fq2 products in one stacked call."""
    jnp = _jnp()
    a0, a1, a2 = a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]
    b0, b1, b2 = b[..., 0, :, :], b[..., 1, :, :], b[..., 2, :, :]
    pa = jnp.stack([a0, a1, a2, fq_add(a1, a2), fq_add(a0, a1),
                    fq_add(a0, a2)])
    pb = jnp.stack([b0, b1, b2, fq_add(b1, b2), fq_add(b0, b1),
                    fq_add(b0, b2)])
    t = fq2_mul(pa, pb)
    t0, t1, t2, s12, s01, s02 = (t[i] for i in range(6))
    c0 = fq_add(t0, fq2_mul_xi(fq_sub(s12, fq_add(t1, t2))))
    c1 = fq_add(fq_sub(s01, fq_add(t0, t1)), fq2_mul_xi(t2))
    c2 = fq_add(fq_sub(s02, fq_add(t0, t2)), t1)
    return jnp.stack([c0, c1, c2], axis=-3)


def fq6_sqr(a):
    return fq6_mul(a, a)


def fq6_mul_by_v(a):
    """v * (a + bv + cv^2) = c*xi + a v + b v^2."""
    jnp = _jnp()
    return jnp.stack([fq2_mul_xi(a[..., 2, :, :]), a[..., 0, :, :],
                      a[..., 1, :, :]], axis=-3)


def fq6_mul_fq2(a, s):
    return fq2_mul(a, s[..., None, :, :])


def fq6_neg(a):
    return fq_neg(a)


def fq6_inv(a):
    """Oracle formula: t0 = a0^2 - a1*a2*xi, etc., then one fq2 inverse."""
    jnp = _jnp()
    a0, a1, a2 = a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]
    sq = fq2_mul(jnp.stack([a0, a2, a1, a1, a0]),
                 jnp.stack([a0, a2, a1, a2, a1]))
    a0s, a2s, a1s, bc, ab = (sq[i] for i in range(5))
    ac = fq2_mul(a0, a2)
    t0 = fq2_sub(a0s, fq2_mul_xi(bc))
    t1 = fq2_sub(fq2_mul_xi(a2s), ab)
    t2 = fq2_sub(a1s, ac)
    inner = fq2_mul(jnp.stack([a0, a2, a1]), jnp.stack([t0, t1, t2]))
    d = fq2_inv(fq2_add(inner[0],
                        fq2_mul_xi(fq2_add(inner[1], inner[2]))))
    out = fq2_mul(jnp.stack([t0, t1, t2]), d[None])
    return jnp.moveaxis(out, 0, -3)


# --- Fq12 -------------------------------------------------------------------


def fq12_mul(a, b):
    """Karatsuba over Fq6: 3 fq6 products in one stacked call (=> a single
    54-wide fq_mul scan)."""
    jnp = _jnp()
    a0, a1 = a[..., 0, :, :, :], a[..., 1, :, :, :]
    b0, b1 = b[..., 0, :, :, :], b[..., 1, :, :, :]
    pa = jnp.stack([a0, a1, fq_add(a0, a1)])
    pb = jnp.stack([b0, b1, fq_add(b0, b1)])
    t = fq6_mul(pa, pb)
    t0, t1, t2 = t[0], t[1], t[2]
    c0 = fq6_add(t0, fq6_mul_by_v(t1))
    c1 = fq6_sub(t2, fq_add(t0, t1))
    return jnp.stack([c0, c1], axis=-4)


def fq12_sqr(a):
    """Oracle's complex squaring: 2 fq6 products."""
    jnp = _jnp()
    a0, a1 = a[..., 0, :, :, :], a[..., 1, :, :, :]
    pa = jnp.stack([a0, fq_add(a0, a1)])
    pb = jnp.stack([a1, fq_add(a0, fq6_mul_by_v(a1))])
    t = fq6_mul(pa, pb)
    t0, s = t[0], t[1]
    c0 = fq6_sub(s, fq6_add(t0, fq6_mul_by_v(t0)))
    c1 = fq_add(t0, t0)
    return jnp.stack([c0, c1], axis=-4)


def fq12_conj(a):
    jnp = _jnp()
    return jnp.stack([a[..., 0, :, :, :], fq6_neg(a[..., 1, :, :, :])],
                     axis=-4)


def fq12_inv(a):
    jnp = _jnp()
    a0, a1 = a[..., 0, :, :, :], a[..., 1, :, :, :]
    t = fq6_mul(jnp.stack([a0, a1]), jnp.stack([a0, a1]))
    d = fq6_inv(fq6_sub(t[0], fq6_mul_by_v(t[1])))
    out = fq6_mul(jnp.stack([a0, fq6_neg(a1)]), d[None])
    return jnp.moveaxis(out, 0, -4)


def fq12_eq(a, b):
    jnp = _jnp()
    return jnp.all(fq_canon(a) == fq_canon(b), axis=(-1, -2, -3, -4))


def fq12_is_one(a):
    jnp = _jnp()
    one = jnp.asarray(FQ12_ONE_L, dtype=jnp.int32)
    return fq12_eq(a, jnp.broadcast_to(one, a.shape))


def _w_coeffs(a):
    """Fq12 -> list of 6 Fq2 coefficients in w-power order (w^0..w^5)."""
    c0, c1 = a[..., 0, :, :, :], a[..., 1, :, :, :]
    return [c0[..., 0, :, :], c1[..., 0, :, :], c0[..., 1, :, :],
            c1[..., 1, :, :], c0[..., 2, :, :], c1[..., 2, :, :]]


def _from_w_coeffs(coeffs):
    jnp = _jnp()
    c0 = jnp.stack([coeffs[0], coeffs[2], coeffs[4]], axis=-3)
    c1 = jnp.stack([coeffs[1], coeffs[3], coeffs[5]], axis=-3)
    return jnp.stack([c0, c1], axis=-4)


def fq12_frobenius(a, power: int = 1):
    """x -> x^(q^power): conjugate Fq2 coefficients, scale w^i basis by
    gamma_i^...; implemented as `power` applications of the q-map, like the
    oracle (power is a small static int)."""
    jnp = _jnp()
    for _ in range(power % 12):
        coeffs = _w_coeffs(a)
        stacked = jnp.stack([fq2_conj(c) for c in coeffs])
        gammas = jnp.stack(
            [jnp.broadcast_to(jnp.asarray(_GAMMA_L[i], dtype=jnp.int32),
                              coeffs[i].shape)
             for i in range(6)])
        mapped = fq2_mul(stacked, gammas)
        a = _from_w_coeffs([mapped[i] for i in range(6)])
    return a
