"""Batched BLS12-381 base-field arithmetic for TPU.

Fq elements are vectors of 33 x 12-bit limbs held in int32 lanes — sized so
every intermediate (Montgomery-multiply column sums, lazy add/sub chains)
stays inside native int32 with headroom: no 64-bit emulation anywhere.
Values live in the Montgomery domain (R = 2**396) and are *signed-lazy*:
limbs may be negative and values range over (-64p, 64p) between
multiplications — subtraction is plain limb subtraction (arithmetic-shift
carries), and every Montgomery product collapses the magnitude back under
2p.  Only equality/canonicalization fully normalizes.

This is the device-side replacement for the native BLS backends behind the
reference's `eth2spec/utils/bls.py` (milagro/arkworks); the pure-Python
sibling `ops/bls/fields.py` is the correctness oracle.

Shapes are batch-first: an Fq element is an int32 array `(..., 33)`; all
ops broadcast over leading axes, so vmap is never required for batching.

Safety budget (why these bounds hold):
  - CIOS step value: |t + a_i*b + m*p| per limb < 2**15*2**15 + 2**15
    + 2**12*2**12 < 2**31.
  - Montgomery bound: inputs |x| < 2**388 (= 64p and far beyond) give
    |out| = |(ab + mN)/R| < p + |ab|/R < 2p.
  - Lazy chains between muls are <= ~5 adds/subs of fresh (<2p) products,
    so values stay well under 2**388 and limbs under 2**15.
"""

from __future__ import annotations

import numpy as np

from ..bls.fields import Q

LIMB_BITS = 12
N_LIMBS = 33                      # 33 * 12 = 396 bits of capacity
LIMB_MASK = (1 << LIMB_BITS) - 1
R_BITS = LIMB_BITS * N_LIMBS      # Montgomery R = 2**396
R_MONT = pow(2, R_BITS, Q)
# -Q^-1 mod 2**12 (the CIOS per-step multiplier)
Q_INV_NEG = (-pow(Q, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)


def int_to_limbs(x: int) -> np.ndarray:
    """Python int (non-negative) -> (33,) int32 limb vector."""
    assert 0 <= x < (1 << R_BITS)
    return np.array([(x >> (LIMB_BITS * i)) & LIMB_MASK
                     for i in range(N_LIMBS)], dtype=np.int32)


def limbs_to_int(limbs) -> int:
    """(..., 33) limb vector -> python int (single element; signed limbs)."""
    arr = np.asarray(limbs).reshape(-1, N_LIMBS)
    assert arr.shape[0] == 1
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(arr[0]))


def to_mont(x: int) -> np.ndarray:
    """Canonical int -> Montgomery-domain limb vector (host-side)."""
    return int_to_limbs((x % Q) * R_MONT % Q)


def from_mont(limbs) -> int:
    """Montgomery-domain limb vector -> canonical int (host-side)."""
    return limbs_to_int(limbs) * pow(R_MONT, -1, Q) % Q


# Device constants (plain numpy; jnp closes over them at trace time)
P_LIMBS = int_to_limbs(Q)
TWO_P_LIMBS = int_to_limbs(2 * Q)
ONE_MONT = to_mont(1)
# multiplying by the PLAIN one under Montgomery mul maps x*R -> x: the
# device-side from-Montgomery conversion (h2c sgn0 needs canonical parity)
ONE_PLAIN = int_to_limbs(1)

# p - 2 bits, MSB first (Fermat inversion exponent)
_P_MINUS_2_BITS = np.array(
    [int(b) for b in bin(Q - 2)[2:]], dtype=np.int32)


def _jnp():
    import jax.numpy as jnp
    return jnp


def fq_carry(x, passes: int = 1):
    """Redistribute limb overflow: vectorized lo/hi passes with signed
    (arithmetic-shift) carries.  The TOP limb is never split — it absorbs
    the incoming carry raw (splitting it would drop a signed carry out of
    the representation; mid-Montgomery intermediates reach ±2**395, so the
    top limb legitimately holds a few signed bits)."""
    jnp = _jnp()
    for _ in range(passes):
        lo = x & LIMB_MASK
        hi = x >> LIMB_BITS          # arithmetic shift = floor division
        y = lo + jnp.concatenate(
            [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1)
        x = jnp.concatenate(
            [y[..., :-1], (x[..., -1:] + hi[..., -2:-1])], axis=-1)
    return x


def fq_add(a, b):
    return fq_carry(a + b)


def fq_sub(a, b):
    return fq_carry(a - b)


def fq_neg(a):
    return fq_carry(-a)


def fq_mul_small(a, k: int):
    """Multiply by a small python int (|k| <= ~16)."""
    return fq_carry(a * k, passes=2)


def fq_mul(a, b):
    """Montgomery product ab/R mod p (CIOS over a lax.scan).

    Inputs may be signed-lazy (|value| < 2**388, |limbs| < 2**15); output
    magnitude is < 2p with limbs ~2**12.  Each scan step is O(batch * 33)
    vector work: t += a_i * b;  m = -t0/p mod 2**12;  t = (t + m*p) >> 12.
    """
    import jax
    jnp = _jnp()

    p = jnp.asarray(P_LIMBS, dtype=jnp.int32)
    a_steps = jnp.moveaxis(a, -1, 0)          # (33, ...) scan over a's limbs

    def step(t, a_i):
        u = t + a_i[..., None] * b
        m = (u[..., 0] * Q_INV_NEG) & LIMB_MASK
        u = u + m[..., None] * p
        c0 = u[..., 0] >> LIMB_BITS            # u0 ≡ 0 mod 2**12 (exact)
        t = jnp.concatenate(
            [u[..., 1:], jnp.zeros_like(u[..., :1])], axis=-1)
        t = t.at[..., 0].add(c0)
        return fq_carry(t), None

    t0 = jnp.zeros(jnp.broadcast_shapes(a.shape, b.shape), dtype=jnp.int32)
    t, _ = jax.lax.scan(step, t0, a_steps)
    return fq_carry(t)


def fq_sqr(a):
    return fq_mul(a, a)


def fq_canon(x):
    """Fully reduce to the canonical representative in [0, p), exact limbs.

    Only needed at comparison boundaries (eq / is_one); the hot path stays
    in the redundant signed representation."""
    import jax
    jnp = _jnp()

    # collapse magnitude to (-2p, 2p), then shift positive into (0, 4p)
    x = fq_mul(x, jnp.asarray(ONE_MONT, dtype=jnp.int32))
    x = fq_carry(x + jnp.asarray(TWO_P_LIMBS, dtype=jnp.int32), passes=2)

    # exact sequential carry (value in (0, 4p) ⊂ [0, 2**396))
    def carry_step(c, xi):
        v = xi + c
        return v >> LIMB_BITS, v & LIMB_MASK

    _, limbs = jax.lax.scan(carry_step,
                            jnp.zeros(x.shape[:-1], dtype=jnp.int32),
                            jnp.moveaxis(x, -1, 0))
    x = jnp.moveaxis(limbs, 0, -1)

    # conditional subtract p three times (value < 4p)
    p = jnp.asarray(P_LIMBS, dtype=jnp.int32)
    for _ in range(3):
        d = x - p

        def borrow_step(c, di):
            v = di + c
            return v >> LIMB_BITS, v & LIMB_MASK

        bo, dl = jax.lax.scan(borrow_step,
                              jnp.zeros(x.shape[:-1], dtype=jnp.int32),
                              jnp.moveaxis(d, -1, 0))
        dsub = jnp.moveaxis(dl, 0, -1)
        ge = (bo == 0)                       # no final borrow => x >= p
        x = jnp.where(ge[..., None], dsub, x)
    return x


def fq_eq(a, b):
    jnp = _jnp()
    return jnp.all(fq_canon(a) == fq_canon(b), axis=-1)


def fq_is_zero(a):
    jnp = _jnp()
    return jnp.all(fq_canon(a) == 0, axis=-1)


def fq_pow_const(a, bits):
    """a**e for a fixed exponent given as an MSB-first int32 bit array
    (numpy, host constant): square-and-multiply over a lax.scan."""
    import jax
    jnp = _jnp()

    def step(acc, bit):
        acc = fq_sqr(acc)
        acc_mul = fq_mul(acc, a)
        return jnp.where(bit, acc_mul, acc), None

    one = jnp.broadcast_to(jnp.asarray(ONE_MONT, dtype=jnp.int32), a.shape)
    acc, _ = jax.lax.scan(step, one, jnp.asarray(bits, dtype=jnp.int32))
    return acc


def fq_inv(a):
    """Fermat inversion a**(p-2); zero maps to zero."""
    return fq_pow_const(a, _P_MINUS_2_BITS)
