"""Batched hash-to-G2 on device — RFC 9380 structure end to end.

The oracle keeps `ops/bls/hash_to_curve.py` (pure Python, per message);
this module reproduces it bit-for-bit as ONE device program over a batch
of 32-byte message roots (the shape every consensus signing root has):

  expand_message_xmd  sha256 compression (`ops/sha256_jax.py` kernel)
                      over host-templated block layouts: only the message
                      words and the chained digests are device data, all
                      padding/DST bytes are trace-time constants.
  hash_to_field       512-bit big-endian draws reduced into Montgomery Fq
                      limbs with two constant multiplies (no big-int
                      arithmetic: a + b*2^396 folds through the CIOS
                      Montgomery kernel).
  map_to_curve        the oracle's Shallue–van de Woestijne straight line
                      (`hash_to_curve.py:168`), made branchless: all
                      three x-candidates and their Fq2 square roots are
                      computed, candidate selection is by masked select
                      with the same priority order as the oracle.
  clear_cofactor      fixed-scalar double-and-add by the derived G2
                      cofactor (`curve_jax.pt_scalar_mul_const`).

Fq2 square roots run the same norm-based construction as the oracle
(`fields.py:99`): every exponentiation is a fixed-schedule scan, the
first-phase (norm, x, -x) and second-phase (t+, t-) chains are stacked so
the whole map costs two pow scans + one inversion scan regardless of how
many candidates end up used.  All selects mirror the oracle's branch
order, so device and host outputs are identical points, not just
equivalent ones.
"""

from __future__ import annotations

import numpy as np

from ..bls import curve as _pycurve
from ..bls.fields import Q
from ..bls.hash_to_curve import DST_G2, _SVDW_G2
from . import curve_jax as cj
from . import fq as _fq
from . import tower as tw


def _jnp():
    import jax.numpy as jnp
    return jnp


# --- host-side templates and constants --------------------------------------

MSG_BYTES = 32                    # consensus signing roots are 32 bytes
_L = 64                           # bytes per hash_to_field draw
_COUNT = 2                        # two Fq2 elements (random-oracle map)
_LEN_IN_BYTES = _COUNT * 2 * _L   # 256
_ELL = _LEN_IN_BYTES // 32        # sha256 draws
_DST_PRIME = DST_G2 + bytes([len(DST_G2)])


def _pad_sha(data: bytes) -> bytes:
    """Append SHA-256 Merkle–Damgård padding (length must be static)."""
    rem = (len(data) + 9) % 64
    zeros = (64 - rem) % 64
    return (data + b"\x80" + b"\x00" * zeros
            + (len(data) * 8).to_bytes(8, "big"))


def _words(data: bytes) -> np.ndarray:
    """Padded byte string -> (n_blocks, 16) big-endian uint32 words."""
    return np.frombuffer(data, dtype=">u4").astype(np.uint32).reshape(-1, 16)


# b0 input: Z_pad(64) || msg(32) || len(2) || 0x00 || DST'  — the message
# occupies exactly words 0..8 of block 1
_B0_TPL = _words(_pad_sha(
    b"\x00" * 64 + b"\x00" * MSG_BYTES
    + _LEN_IN_BYTES.to_bytes(2, "big") + b"\x00" + _DST_PRIME))
# b_i inputs: digest(32) || i(1) || DST' — digest is words 0..8 of block 0
_BI_TPLS = [_words(_pad_sha(b"\x00" * 32 + bytes([i]) + _DST_PRIME))
            for i in range(1, _ELL + 1)]

# Montgomery folding constants for the 512-bit draw u = a + b*2^396:
# mont_mul(a, 2^792) = a*2^396 = a*R and mont_mul(b, 2^1188) = b*2^396*R
_C_LO = _fq.int_to_limbs(pow(2, 2 * _fq.R_BITS, Q))
_C_HI = _fq.int_to_limbs(pow(2, 3 * _fq.R_BITS, Q))

# (Q+1)/4 bits, MSB first: the q = 3 mod 4 square-root exponent
_P14_BITS = np.array([int(b) for b in bin((Q + 1) // 4)[2:]], dtype=np.int32)
_INV2_MONT = _fq.to_mont(pow(2, -1, Q))

# SVDW constants, derived by the oracle at import (no transcription)
_C1_L = tw.fq2_from_oracle(_SVDW_G2.c1)
_C2_L = tw.fq2_from_oracle(_SVDW_G2.c2)
_C3_L = tw.fq2_from_oracle(_SVDW_G2.c3)
_C4_L = tw.fq2_from_oracle(_SVDW_G2.c4)
_Z_L = tw.fq2_from_oracle(_SVDW_G2.Z)
_B2_L = tw.fq2_from_oracle(_pycurve.B2)

# G2 cofactor bits, MSB first (derived in ops/bls/curve.py)
_H2_BITS = np.array([int(b) for b in bin(_pycurve.H2)[2:]], dtype=np.int32)


def msgs_to_words(msgs) -> np.ndarray:
    """32-byte messages -> (B, 8) big-endian uint32 word matrix."""
    out = []
    for m in msgs:
        m = bytes(m)
        assert len(m) == MSG_BYTES, "device h2c is fixed to 32-byte roots"
        out.append(np.frombuffer(m, dtype=">u4"))
    return np.stack(out).astype(np.uint32)


# --- expand_message_xmd ------------------------------------------------------


def _sha_blocks(blocks):
    """SHA-256 over a fixed block sequence (each (..., 16) words)."""
    from .. import sha256_jax as sha
    jnp = _jnp()
    state = jnp.broadcast_to(jnp.asarray(sha._IV_np, dtype=jnp.uint32),
                             blocks[0].shape[:-1] + (8,))
    for blk in blocks:
        state = sha._compress(state, blk)
    return state


def expand_message_xmd_dev(msg_words):
    """RFC 9380 §5.3 expand_message_xmd(SHA-256) for fixed 32-byte
    messages and the module DST: (B, 8) words -> (B, 64) words (256
    uniform bytes)."""
    jnp = _jnp()
    B = msg_words.shape[0]

    def bc(w):
        return jnp.broadcast_to(jnp.asarray(w, dtype=jnp.uint32),
                                (B,) + w.shape)

    blocks = [bc(_B0_TPL[0]),
              jnp.concatenate([msg_words, bc(_B0_TPL[1][8:])], axis=-1)]
    blocks += [bc(row) for row in _B0_TPL[2:]]
    b0 = _sha_blocks(blocks)

    outs = []
    bi = None
    for i in range(_ELL):
        first = b0 if i == 0 else b0 ^ bi
        tpl = _BI_TPLS[i]
        blks = [jnp.concatenate([first, bc(tpl[0][8:])], axis=-1)]
        blks += [bc(row) for row in tpl[1:]]
        bi = _sha_blocks(blks)
        outs.append(bi)
    return jnp.concatenate(outs, axis=-1)


# --- hash_to_field -----------------------------------------------------------


def _words512_to_fq_mont(chunk):
    """(..., 16) big-endian words of one 512-bit draw -> Montgomery Fq
    limbs of (value mod Q): 12-bit limb extraction by static shifts, then
    the two-constant Montgomery fold (u = a + b*2^396)."""
    jnp = _jnp()
    lw = chunk[..., ::-1]          # little-endian word order
    limbs = []
    for j in range((16 * 32 + 11) // 12):
        lo = 12 * j
        t0, off = divmod(lo, 32)
        v = lw[..., t0] >> np.uint32(off)
        if off > 20 and t0 + 1 < 16:
            v = v | (lw[..., t0 + 1] << np.uint32(32 - off))
        limbs.append(v & np.uint32(0xFFF))
    x = jnp.stack(limbs, axis=-1).astype(jnp.int32)
    n = _fq.N_LIMBS
    lo33 = x[..., :n]
    hi = x[..., n:]
    hi33 = jnp.concatenate(
        [hi, jnp.zeros(hi.shape[:-1] + (2 * n - x.shape[-1],), jnp.int32)],
        axis=-1)
    return _fq.fq_add(
        _fq.fq_mul(lo33, jnp.asarray(_C_LO, dtype=jnp.int32)),
        _fq.fq_mul(hi33, jnp.asarray(_C_HI, dtype=jnp.int32)))


def hash_to_field_fq2_dev(msg_words):
    """RFC 9380 §5.2 hash_to_field, count=2: (B, 8) message words ->
    (u0, u1) each (B, 2, 33) Montgomery Fq2 limbs."""
    jnp = _jnp()
    uniform = expand_message_xmd_dev(msg_words)      # (B, 64) words
    els = [_words512_to_fq_mont(uniform[..., 16 * k:16 * (k + 1)])
           for k in range(2 * _COUNT)]
    u0 = jnp.stack([els[0], els[1]], axis=-2)
    u1 = jnp.stack([els[2], els[3]], axis=-2)
    return u0, u1


# --- branchless Fq2 square root / sgn0 --------------------------------------


def fq2_sqrt_dev(a):
    """Batched Fq2 square root with the oracle's exact branch priority
    (`fields.py:99` Fq2.sqrt), branchless.  Returns (root, is_square);
    root is garbage where is_square is False."""
    jnp = _jnp()
    x, y = a[..., 0, :], a[..., 1, :]
    sq = _fq.fq_mul(jnp.stack([x, y]), jnp.stack([x, y]))
    norm = _fq.fq_add(sq[0], sq[1])

    # phase 1: candidate roots of norm, x, and -x in one stacked scan
    ph1 = _fq.fq_pow_const(jnp.stack([norm, x, _fq.fq_neg(x)]), _P14_BITS)
    n, rx, rnx = ph1[0], ph1[1], ph1[2]

    # phase 2: c± = sqrt((x ± n)/2) candidates, one stacked scan
    inv2 = jnp.asarray(_INV2_MONT, dtype=jnp.int32)
    ts = jnp.stack([_fq.fq_mul(_fq.fq_add(x, n), inv2),
                    _fq.fq_mul(_fq.fq_sub(x, n), inv2)])
    cs = _fq.fq_pow_const(ts, _P14_BITS)
    wy = _fq.fq_mul(_fq.fq_inv(_fq.fq_mul_small(cs, 2)), y[None])

    zero = jnp.zeros_like(x)
    cands = jnp.stack([
        jnp.stack([rx, zero], axis=-2),      # y == 0, x a QR
        jnp.stack([zero, rnx], axis=-2),     # y == 0, x a non-QR
        jnp.stack([cs[0], wy[0]], axis=-2),  # general, + sign
        jnp.stack([cs[1], wy[1]], axis=-2),  # general, - sign
    ])
    ok = tw.fq2_eq(tw.fq2_sqr(cands), a[None])
    y_zero = _fq.fq_is_zero(y)

    def e(m):
        return m[..., None, None]

    gen = jnp.where(e(ok[2]), cands[2], cands[3])
    yz = jnp.where(e(ok[0]), cands[0], cands[1])
    root = jnp.where(e(y_zero), yz, gen)
    is_sq = jnp.where(y_zero, ok[0] | ok[1], ok[2] | ok[3])
    return root, is_sq


def sgn0_fq2_dev(a):
    """RFC 9380 sgn0 for Montgomery Fq2 limbs: convert to the plain
    domain on device (multiply by the non-Montgomery one), canonicalize,
    take lexicographic parity."""
    jnp = _jnp()
    stacked = jnp.stack([a[..., 0, :], a[..., 1, :]])
    plain = _fq.fq_canon(_fq.fq_mul(
        stacked, jnp.asarray(_fq.ONE_PLAIN, dtype=jnp.int32)))
    s0 = (plain[0][..., 0] & 1) == 1
    z0 = jnp.all(plain[0] == 0, axis=-1)
    s1 = (plain[1][..., 0] & 1) == 1
    return s0 | (z0 & s1)


# --- Shallue–van de Woestijne map -------------------------------------------


def _bc2(const, like):
    jnp = _jnp()
    return jnp.broadcast_to(jnp.asarray(const, dtype=jnp.int32),
                            like.shape)


def svdw_map_g2_dev(u):
    """RFC 9380 §6.6.1 straight line on (..., 2, 33) Fq2 limbs ->
    affine (x, y) on the twist, bit-identical to the oracle map."""
    jnp = _jnp()
    one = _bc2(tw.FQ2_ONE_L, u)
    tv1 = tw.fq2_mul(tw.fq2_sqr(u), _bc2(_C1_L, u))
    tv2 = tw.fq2_add(one, tv1)
    tv1 = tw.fq2_sub(one, tv1)
    tv3 = tw.fq2_inv(tw.fq2_mul(tv1, tv2))           # inv0: 0 -> 0
    tv4 = tw.fq2_mul(tw.fq2_mul(u, tv1),
                     tw.fq2_mul(tv3, _bc2(_C3_L, u)))
    x1 = tw.fq2_sub(_bc2(_C2_L, u), tv4)
    x2 = tw.fq2_add(_bc2(_C2_L, u), tv4)
    t = tw.fq2_sqr(tw.fq2_mul(tw.fq2_sqr(tv2), tv3))
    x3 = tw.fq2_add(tw.fq2_mul(t, _bc2(_C4_L, u)), _bc2(_Z_L, u))

    xs = jnp.stack([x1, x2, x3])
    gx = tw.fq2_add(tw.fq2_mul(tw.fq2_sqr(xs), xs), _bc2(_B2_L, xs))
    roots, ok = fq2_sqrt_dev(gx)

    def e(m):
        return m[..., None, None]

    x = jnp.where(e(ok[0]), x1, jnp.where(e(ok[1]), x2, x3))
    y = jnp.where(e(ok[0]), roots[0],
                  jnp.where(e(ok[1]), roots[1], roots[2]))
    flip = sgn0_fq2_dev(u) != sgn0_fq2_dev(y)
    y = jnp.where(e(flip), tw.fq2_neg(y), y)
    return x, y


# --- hash_to_curve -----------------------------------------------------------


def hash_to_g2_dev(msg_words):
    """Device hash_to_g2 (random-oracle construction): (B, 8) message
    words -> batched Jacobian G2 point (X, Y, Z limb arrays).  Matches
    `ops/bls/hash_to_curve.py:hash_to_g2` exactly (same DST, same map,
    same cofactor)."""
    jnp = _jnp()
    B = msg_words.shape[0]
    u0, u1 = hash_to_field_fq2_dev(msg_words)
    mx, my = svdw_map_g2_dev(jnp.concatenate([u0, u1], axis=0))
    one2 = jnp.broadcast_to(jnp.asarray(tw.FQ2_ONE_L, dtype=jnp.int32),
                            (B, 2, _fq.N_LIMBS))
    q = cj.pt_add(cj.F2, (mx[:B], my[:B], one2), (mx[B:], my[B:], one2))
    return cj.pt_scalar_mul_const(cj.F2, q, _H2_BITS)
