"""Batched Jacobian point arithmetic on G1/G2 for TPU.

Generic over the coordinate field (Fq for G1, Fq2 for G2) via a small
field-ops namespace, mirroring the oracle's `_Group` parametrization
(`ops/bls/curve.py:42-130`) — but branchless: infinity / doubling /
cancellation cases are resolved with masked selects so the whole batch
runs as straight-line vector code under jit.

Points are (X, Y, Z) tuples of limb arrays; Z == 0 encodes infinity.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from ..bls import curve as _pycurve
from . import fq as _fq
from . import tower as _tw


def _jnp():
    import jax.numpy as jnp
    return jnp


@dataclass(frozen=True)
class FieldOps:
    name: str
    add: Callable
    sub: Callable
    mul: Callable
    sqr: Callable
    neg: Callable
    mul_small: Callable
    is_zero: Callable          # exact (canonicalizing) zero test -> (...)
    one: Any                   # numpy constant, element shape
    zero: Any
    expand: Callable           # mask (...) -> broadcastable over element


F1 = FieldOps(
    name="fq",
    add=_fq.fq_add,
    sub=_fq.fq_sub,
    mul=_fq.fq_mul,
    sqr=_fq.fq_sqr,
    neg=_fq.fq_neg,
    mul_small=_fq.fq_mul_small,
    is_zero=_fq.fq_is_zero,
    one=_fq.ONE_MONT,
    zero=np.zeros(_fq.N_LIMBS, dtype=np.int32),
    expand=lambda m: m[..., None],
)

F2 = FieldOps(
    name="fq2",
    add=_tw.fq2_add,
    sub=_tw.fq2_sub,
    mul=_tw.fq2_mul,
    sqr=_tw.fq2_sqr,
    neg=_tw.fq2_neg,
    mul_small=_tw.fq2_mul_small,
    is_zero=_tw.fq2_is_zero,
    one=_tw.FQ2_ONE_L,
    zero=np.zeros((2, _fq.N_LIMBS), dtype=np.int32),
    expand=lambda m: m[..., None, None],
)


def pt_infinity(F: FieldOps, like):
    jnp = _jnp()
    one = jnp.broadcast_to(jnp.asarray(F.one, dtype=jnp.int32),
                           like[0].shape)
    zero = jnp.zeros_like(like[0])
    return (one, one, zero)


def pt_select(F: FieldOps, mask, p, q):
    jnp = _jnp()
    m = F.expand(mask)
    return tuple(jnp.where(m, a, b) for a, b in zip(p, q))


def pt_neg(F: FieldOps, p):
    return (p[0], F.neg(p[1]), p[2])


def pt_is_inf(F: FieldOps, p):
    return F.is_zero(p[2])


def pt_double(F: FieldOps, p):
    """dbl-2007-bl (the oracle's formula, `curve.py:82-98`); Z=0 and Y=0
    both land on Z3=0, so infinity needs no special-casing."""
    X, Y, Z = p
    A = F.sqr(X)
    B = F.sqr(Y)
    C = F.sqr(B)
    t = F.sub(F.sqr(F.add(X, B)), F.add(A, C))
    D = F.add(t, t)
    E = F.mul_small(A, 3)
    Fv = F.sqr(E)
    X3 = F.sub(Fv, F.add(D, D))
    eight_c = F.mul_small(C, 8)
    Y3 = F.sub(F.mul(E, F.sub(D, X3)), eight_c)
    Z3 = F.mul(F.add(Y, Y), Z)
    return (X3, Y3, Z3)


def pt_add(F: FieldOps, p, q):
    """add-2007-bl with masked resolution of the special cases."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = F.sqr(Z1)
    Z2Z2 = F.sqr(Z2)
    U1 = F.mul(X1, Z2Z2)
    U2 = F.mul(X2, Z1Z1)
    S1 = F.mul(Y1, F.mul(Z2Z2, Z2))
    S2 = F.mul(Y2, F.mul(Z1Z1, Z1))
    H = F.sub(U2, U1)
    rr = F.sub(S2, S1)
    rr2 = F.add(rr, rr)
    I = F.sqr(F.add(H, H))
    J = F.mul(H, I)
    V = F.mul(U1, I)
    X3 = F.sub(F.sub(F.sqr(rr2), J), F.add(V, V))
    SJ = F.mul(S1, J)
    Y3 = F.sub(F.mul(rr2, F.sub(V, X3)), F.add(SJ, SJ))
    Z3 = F.mul(F.mul(F.add(Z1, Z2), F.add(Z1, Z2)), H)
    Z3 = F.sub(Z3, F.mul(Z1Z1, H))
    Z3 = F.sub(Z3, F.mul(Z2Z2, H))
    out = (X3, Y3, Z3)

    h_zero = F.is_zero(H)
    r_zero = F.is_zero(rr)
    # same x, same y -> doubling; same x, different y -> infinity
    out = pt_select(F, h_zero & r_zero, pt_double(F, p), out)
    out = pt_select(F, h_zero & ~r_zero, pt_infinity(F, p), out)
    out = pt_select(F, pt_is_inf(F, p), q, out)
    out = pt_select(F, pt_is_inf(F, q), p, out)
    return out


def pt_scalar_mul(F: FieldOps, p, scalar_bits):
    """Batched double-and-add, MSB first.

    scalar_bits: int32 (..., nbits) per batch element (leading dims must
    match p's batch dims).  Runs as a lax.scan of nbits steps; the add is
    always computed and masked in (bits differ across the batch)."""
    import jax
    jnp = _jnp()

    bits = jnp.moveaxis(scalar_bits, -1, 0)     # (nbits, ...)

    def step(acc, bit):
        acc = pt_double(F, acc)
        cand = pt_add(F, acc, p)
        return pt_select(F, bit.astype(bool), cand, acc), None

    acc0 = pt_infinity(F, p)
    acc, _ = jax.lax.scan(step, acc0, bits)
    return acc


def pt_scalar_mul_const(F: FieldOps, p, bits_np):
    """Double-and-add by ONE fixed host-known scalar (MSB-first int32
    numpy bit array) applied to the whole batch — the cofactor-clearing
    shape: the bit schedule rides the scan's xs, so the add executes only
    on set bits at runtime while the HLO stays one small step body."""
    import jax
    jnp = _jnp()

    def step(acc, bit):
        acc = pt_double(F, acc)
        acc = jax.lax.cond(bit == 1,
                           lambda a: pt_add(F, a, p),
                           lambda a: a, acc)
        return acc, None

    acc0 = pt_infinity(F, p)
    acc, _ = jax.lax.scan(step, acc0, jnp.asarray(bits_np, dtype=jnp.int32))
    return acc


def pt_msm_pippenger(F: FieldOps, p, digits, c: int):
    """Bucketed (Pippenger) multiscalar multiplication over the batch.

    p: (x, y, one) batched points (B leading axis); digits: (B, W) int32
    window digits, MOST-significant window first (`scalars_to_digits`);
    c: static window bit width, W = ceil(nbits / c).

    Phase 1 scans the B points once, scattering each into its bucket in
    every window simultaneously (the W axis is the vectorized one — a
    point has exactly one bucket per window, so all windows update in
    parallel).  Phase 2 reduces each window's 2^c buckets with the
    classic suffix-sum (sum_k k*B_k), still W-wide.  Phase 3 combines
    windows MSB-first with c doublings each.  Bucket 0 is never read, so
    zero digits — including padding lanes — contribute nothing and no
    mask is needed."""
    import jax
    jnp = _jnp()

    B, W = digits.shape
    nb = 1 << c
    elem = p[0].shape[1:]

    one = jnp.broadcast_to(jnp.asarray(F.one, dtype=jnp.int32),
                           (W, nb) + elem)
    zero = jnp.zeros((W, nb) + elem, jnp.int32)
    buckets = (one, one, zero)          # grid of infinities
    widx = jnp.arange(W, dtype=jnp.int32)

    def scatter_step(bk, xs):
        px, py, pz, d = xs
        cur = tuple(b[widx, d] for b in bk)
        pt = tuple(jnp.broadcast_to(co[None], (W,) + elem).astype(jnp.int32)
                   for co in (px, py, pz))
        new = pt_add(F, cur, pt)
        bk = tuple(b.at[widx, d].set(nc) for b, nc in zip(bk, new))
        return bk, None

    buckets, _ = jax.lax.scan(scatter_step, buckets,
                              (p[0], p[1], p[2], digits))

    # suffix-sum reduction: iterate k = nb-1 .. 1 (bucket 0 skipped)
    rev = tuple(jnp.moveaxis(b[:, :0:-1], 1, 0) for b in buckets)
    inf_w = pt_infinity(F, tuple(b[0] for b in rev))

    def red_step(carry, bk):
        running, acc = carry
        running = pt_add(F, running, bk)
        acc = pt_add(F, acc, running)
        return (running, acc), None

    (_, win_sums), _ = jax.lax.scan(red_step, (inf_w, inf_w), rev)

    # window combine, MSB-first: c doublings then add the window sum
    res0 = pt_infinity(F, tuple(a[:1] for a in win_sums))

    def comb_step(res, acc_w):
        for _ in range(c):
            res = pt_double(F, res)
        return pt_add(F, res, tuple(a[None] for a in acc_w)), None

    res, _ = jax.lax.scan(comb_step, res0, win_sums)
    return tuple(co[0] for co in res)


def pt_sum(F: FieldOps, p, n: int):
    """Sum a batch of n points (leading axis) with a log-depth add tree."""
    jnp = _jnp()
    m = 1
    while m < n:
        m *= 2
    if m != n:
        pad = pt_infinity(F, tuple(c[:1] for c in p))
        p = tuple(jnp.concatenate(
            [c, jnp.broadcast_to(pc, (m - n,) + c.shape[1:])])
            for c, pc in zip(p, pad))
    while m > 1:
        m //= 2
        p = pt_add(F, tuple(c[:m] for c in p), tuple(c[m:2 * m] for c in p))
    return tuple(c[0] for c in p)


# --- host conversions -------------------------------------------------------


def scalars_to_bits(scalars, nbits: int) -> np.ndarray:
    """Python ints -> (B, nbits) int32 bit matrix, MSB first."""
    out = np.zeros((len(scalars), nbits), dtype=np.int32)
    for i, s in enumerate(scalars):
        s = int(s)
        assert 0 <= s < (1 << nbits)
        for j in range(nbits):
            out[i, nbits - 1 - j] = (s >> j) & 1
    return out


def scalars_to_digits(scalars, nbits: int, c: int) -> np.ndarray:
    """Python ints -> (B, ceil(nbits/c)) int32 c-bit window digits,
    most-significant window first (Pippenger layout)."""
    n_windows = -(-nbits // c)
    out = np.zeros((len(scalars), n_windows), dtype=np.int32)
    m = (1 << c) - 1
    for i, s in enumerate(scalars):
        s = int(s)
        assert 0 <= s < (1 << nbits)
        for w in range(n_windows):
            out[i, n_windows - 1 - w] = (s >> (c * w)) & m
    return out


def g1_affine_to_limbs(points) -> tuple[np.ndarray, np.ndarray]:
    """Oracle G1 Jacobian points -> (x, y) Montgomery limb stacks.
    Points must not be at infinity (filter on host first)."""
    xs, ys = [], []
    for p in points:
        aff = _pycurve.g1.to_affine(p)
        assert aff is not None, "infinity must be filtered host-side"
        xs.append(_fq.to_mont(aff[0]))
        ys.append(_fq.to_mont(aff[1]))
    return np.stack(xs), np.stack(ys)


def g2_affine_to_limbs(points) -> tuple[np.ndarray, np.ndarray]:
    xs, ys = [], []
    for p in points:
        aff = _pycurve.g2.to_affine(p)
        assert aff is not None, "infinity must be filtered host-side"
        xs.append(_tw.fq2_from_oracle(aff[0]))
        ys.append(_tw.fq2_from_oracle(aff[1]))
    return np.stack(xs), np.stack(ys)


def g1_limbs_to_oracle(p):
    """Device Jacobian G1 point (single element) -> oracle tuple."""
    X, Y, Z = (np.asarray(c).reshape(_fq.N_LIMBS) for c in p)
    return (_fq.from_mont(X), _fq.from_mont(Y), _fq.from_mont(Z))


def g2_limbs_to_oracle(p):
    X, Y, Z = p
    return (_tw.fq2_to_oracle(np.asarray(X).reshape(2, _fq.N_LIMBS)),
            _tw.fq2_to_oracle(np.asarray(Y).reshape(2, _fq.N_LIMBS)),
            _tw.fq2_to_oracle(np.asarray(Z).reshape(2, _fq.N_LIMBS)))
