"""Batched TPU BLS verification — the north star's hot path.

Public surface:

  pairing_check_device(pairs)      drop-in for the oracle's pairing_check
                                   (`ops/bls/pairing.py:160`): product of
                                   pairings == 1, one shared final exp,
                                   computed on device with HOST-precomputed
                                   fixed-argument Miller lines (the G2
                                   points of a pairing check are always
                                   host-known), so the device program has
                                   no G2 Jacobian arithmetic at all.
  batch_verify(tasks)              random-linear-combination batch of
                                   FastAggregateVerify-style checks: B
                                   signatures verified with B+1 pairings
                                   and ONE final exponentiation; the G1/G2
                                   scalar multiplications AND the message
                                   hash-to-curve (sha256 xmd + SVDW map +
                                   cofactor clearing, `h2c_jax`) also run
                                   on device, so the whole statement batch
                                   is device-resident end to end.
  g1_multi_exp_device(pts, ks)     G1 multiscalar multiplication via a
                                   windowed bucketed (Pippenger) kernel.

Every entry point also has an `_async` variant returning a
`serve.futures.DeviceFuture` (the deferred-result contract): host prep +
kernel dispatch happen eagerly, the device→host transfer happens once at
`result()` — the serve executor pipelines batches through these, and the
synchronous names above are thin `.result()` facades kept for the spec /
block-executor call sites.

Host keeps parsing and subgroup checks (the oracle code); the device does
every pairing, scalar multiplication, and hash-to-curve.  Batch shapes are
padded to a 4-step bucket ladder so each jit entry point compiles at most
4 executables (`_bucket`).

Multi-pairing soundness (why ONE shared Fq12 accumulator and 128-bit RLC
scalars keep the forgery probability negligible, ~2^-127): the batch
check accepts iff
prod_i e(r_i PK_i, H_i) * e(-G1, sum_i r_i S_i) == 1, i.e. iff
prod_i e(PK_i, H_i)^{r_i} == prod_i e(G1, S_i)^{r_i}.  Writing
d_i = e(PK_i, H_i) / e(G1, S_i) (elements of the order-r multiplicative
group mu_r), acceptance means prod_i d_i^{r_i} == 1.  The sampling pins
r_0 = 1 and draws the other r_i as random ODD 128-bit values (2^127
possibilities each; odd => nonzero mod r).  A single false statement
with all others true is rejected deterministically when it sits at slot
0, else: conditioning on every other coefficient, at most one of the
2^127 values of r_i mod ord(d_i) can collapse the product to 1, so the
acceptance probability of any forged batch is at most 2^-127 — one bit
under the nominal 2^-RLC_SCALAR_BITS from the odd-only restriction, and
far below any feasible attack budget.  Folding the B Miller values into
one shared accumulator (f <- f^2 * prod_b line_b, `pairing_jax
.miller_product_batch`) computes exactly the same product of pairings —
conjugation and squaring are field automorphisms/homomorphisms, so the
algebraic predicate (and hence the bound) is unchanged; only the schedule
of Fq12 squarings differs (1 per loop bit instead of B).  See
`tests/formats/README.md` for the vector formats that pin the
accept/reject parity between this path and the oracle.

Replaces the reference's native backends behind
`eth2spec/utils/bls.py:141-296` (milagro `Verify`/`FastAggregateVerify`,
arkworks point ops).
"""

from __future__ import annotations

import functools
import os
import secrets
import time

import numpy as np

from ... import telemetry
from ...resilience import faults
from ...serve.futures import DeviceFuture, bool_future, value_future
from ...telemetry import costmodel, occupancy
from ..bls import curve as _pycurve
from ..bls.hash_to_curve import DST_G2, hash_to_g2
from . import curve_jax as cj
from . import fq as _fq
from . import pairing_jax as pj
from . import tower as tw

RLC_SCALAR_BITS = 128     # soundness 2^-127 per forged batch (odd draws)

# batch-shape ladder: every entry point compiles at most these 4 shapes
# for realistic batch sizes (larger batches fall back to powers of two).
# Ratio-4 rungs bound padding waste at 4x while landing the BASELINE
# config shapes exactly (attestation batch 128+1 lanes, sync pairing 2->8)
_BUCKET_STEPS = (8, 32, 128, 512)


def _jnp():
    import jax.numpy as jnp
    return jnp


def _bucket(n: int) -> int:
    """Padded batch shape for n live lanes: the next power of two,
    quantized UP to the 4-step ladder so jit caches stay tiny.  n <= 1
    (including the n == 0 never-dispatched case) maps to the bottom rung;
    padded lanes are masked out, so correctness never depends on n."""
    b = 1 if n <= 1 else 1 << (n - 1).bit_length()
    for step in _BUCKET_STEPS:
        if b <= step:
            return step
    return b


# --- telemetry-aware kernel dispatch ----------------------------------------


def _dispatch(kernel: str, fn, args, block: bool = True):
    """Run a jitted kernel, attributing its wall time to compile vs run:
    the FIRST dispatch of a given (kernel, padded-shape) key pays
    trace + XLA compile (or a persistent-cache load — visible as an
    anomalously cheap first call), later dispatches are pure run.  Off
    (the default) this is a flag check and a tail call — no sync, no
    timing.

    `block=False` is the pipelined-caller contract (the serve executor
    threads it through the `*_async` entry points): after the first
    call of a (kernel, shape) key — which still blocks, the compile
    attribution and AOT cost capture need the built executable — later
    dispatches enqueue WITHOUT syncing and observe `dispatch_s` (host
    enqueue wall) instead of `run_s`, so an instrumented serve round
    keeps overlapping host prep with device execution instead of
    serializing the batch pipeline on every dispatch.

    This is also the cost-capture seam: on CST_COSTMODEL rounds the
    first dispatch of each (kernel, shape) additionally records XLA's
    cost/memory analysis for the compiled executable and samples the
    per-device memory watermark (both no-op flag checks otherwise).

    And it is the resilience fault seam (`resilience.faults`, OFF by
    default — one module-global read): an installed fault plan can
    raise here (dispatch exception / compile-fail-on-first-call /
    mesh-device loss, keyed by kernel name), inject latency, or corrupt
    the dispatched output (bit-flip/NaN, applied on device) — the
    deterministic chaos machinery the serve executor's recovery
    policies are tested against."""
    if faults.active():
        faults.maybe_inject("dispatch", kernel)
    if not telemetry.enabled():
        # the occupancy ledger has its own gate (CST_OCCUPANCY) — a
        # serve round can measure device busy without paying for the
        # full telemetry registry.  Without a sync we can't tell
        # enqueue from execute, so the span opens at enqueue and the
        # next future settle on this device closes it.
        if occupancy.enabled():
            t0 = time.perf_counter()
            out = fn(*args)
            occupancy.note_kernel_dispatched(kernel, t0=t0)
        else:
            out = fn(*args)
        return faults.corrupt("dispatch", kernel, out) \
            if faults.active() else out
    import jax

    first = telemetry.first_call(f"kernel.{kernel}")
    t0 = time.perf_counter()
    if first or block:
        out = jax.block_until_ready(fn(*args))
        which = "compile_first_s" if first else "run_s"
    else:
        out = fn(*args)
        which = "dispatch_s"
    dt = time.perf_counter() - t0
    if block or first:
        # blocking dispatch: the measured wall IS device busy
        occupancy.note_kernel_busy(kernel, t0, t0 + dt)
    else:
        # pipelined dispatch: busy opens at enqueue, the next future
        # settle on this device closes it (in-order stream)
        occupancy.note_kernel_dispatched(kernel, t0=t0)
    telemetry.observe(f"kernel.{which}", dt)
    telemetry.observe(f"kernel.{kernel}.{which}", dt)
    telemetry.count(f"kernel.{kernel}.calls")
    if first:
        # after the timing window: the AOT analysis pass must not
        # contaminate the compile-vs-run attribution above
        costmodel.capture(kernel, fn, args)
    costmodel.sample_watermark(f"kernel.{kernel}")
    if faults.active():
        out = faults.corrupt("dispatch", kernel, out)
    return out


def _count_lanes(live: int, padded: int) -> None:
    """Bucket-padding accounting: live lanes actually carrying a
    statement vs the `_bucket`-padded shape the kernel compiled for."""
    telemetry.count("bls.lanes.live", live)
    telemetry.count("bls.lanes.padded", padded)


# --- device helpers ---------------------------------------------------------


def g1_to_affine_dev(p):
    """Batched Jacobian -> affine on device; returns (x, y, inf_mask)."""
    X, Y, Z = p
    inf = _fq.fq_is_zero(Z)
    zi = _fq.fq_inv(Z)
    zi2 = _fq.fq_sqr(zi)
    return _fq.fq_mul(X, zi2), _fq.fq_mul(Y, _fq.fq_mul(zi2, zi)), inf


def g2_to_affine_dev(p):
    X, Y, Z = p
    inf = tw.fq2_is_zero(Z)
    zi = tw.fq2_inv(Z)
    zi2 = tw.fq2_sqr(zi)
    return tw.fq2_mul(X, zi2), tw.fq2_mul(Y, tw.fq2_mul(zi2, zi)), inf


# --- pairing check ----------------------------------------------------------


@functools.lru_cache(maxsize=16)
def _pairing_check_precomp_fn(batch: int):
    import jax

    def run(xp, yp, lines, mask):
        return pj.multi_pairing_check_precomp(xp, yp, lines, mask)

    return jax.jit(run)


def pairing_check_device_async(pairs, block: bool = True) -> DeviceFuture:
    """pairs: [(g1_jacobian, g2_jacobian)] oracle points.  Infinity pairs
    contribute the identity (matching the oracle's skip).  Returns a
    `DeviceFuture[bool]`: the kernel is dispatched asynchronously and
    the accept/reject bool crosses to the host only at `result()` —
    callers (the serve executor above all) keep feeding the pipeline
    instead of stalling on every check.

    The G2 arguments are host points by construction, so their Miller
    line coefficients are precomputed once per point on the host
    (`pj.precompute_g2_lines`, lru-cached) and shipped as scan constants:
    the device program is just the shared-accumulator line evaluation and
    one final exponentiation."""
    live = [(p, q) for p, q in pairs
            if not _pycurve.g1.is_inf(p) and not _pycurve.g2.is_inf(q)]
    if not live:
        return DeviceFuture.settled(True)
    jnp = _jnp()
    B = _bucket(len(live))
    with telemetry.span("bls.pairing_check_device", live=len(live),
                        padded=B):
        telemetry.count("bls.pairing_check.calls")
        _count_lanes(len(live), B)
        xp, yp = cj.g1_affine_to_limbs([p for p, _ in live])
        # (n_bits, B_live, 6, 2, 33): per-bit line coefficients per pair
        lines = np.stack([pj.precompute_g2_lines(q) for _, q in live],
                         axis=1)
        pad = B - len(live)
        if pad:
            xp = np.concatenate([xp, np.repeat(xp[:1], pad, 0)])
            yp = np.concatenate([yp, np.repeat(yp[:1], pad, 0)])
            lines = np.concatenate(
                [lines, np.repeat(lines[:, :1], pad, 1)], axis=1)
        mask = np.arange(B) < len(live)
        out = _dispatch(f"pairing_check@{B}", _pairing_check_precomp_fn(B),
                        (jnp.asarray(xp), jnp.asarray(yp),
                         jnp.asarray(lines), jnp.asarray(mask)),
                        block=block)
    return bool_future(out)


def pairing_check_device(pairs) -> bool:
    """Synchronous facade over `pairing_check_device_async` (the oracle
    `pairing_check` drop-in); the settle happens in `serve.futures`."""
    return pairing_check_device_async(pairs).result()


# --- RLC batch verify -------------------------------------------------------


def _rlc_pairing_core(pk_x, pk_y, sig_x, sig_y, h_x, h_y, h_ok,
                      r_bits, mask):
    """Traced body shared by the host-hash and device-hash RLC kernels:
    scalar-mul the B pubkeys and signatures by the random coefficients,
    sum the signature side, run the B+1 pairing product with the shared
    Fq12 accumulator."""
    jnp = _jnp()
    B = pk_x.shape[0]
    neg_g1 = cj.g1_affine_to_limbs([_pycurve.g1.neg(_pycurve.G1_GEN)])
    one1 = jnp.broadcast_to(jnp.asarray(_fq.ONE_MONT),
                            pk_x.shape).astype(jnp.int32)
    one2 = jnp.broadcast_to(jnp.asarray(tw.FQ2_ONE_L),
                            sig_x.shape).astype(jnp.int32)

    r_pk = cj.pt_scalar_mul(cj.F1, (pk_x, pk_y, one1), r_bits)
    r_sig = cj.pt_scalar_mul(cj.F2, (sig_x, sig_y, one2), r_bits)
    # padding lanes -> infinity so they vanish from the signature sum
    r_sig = cj.pt_select(cj.F2, mask, r_sig,
                         cj.pt_infinity(cj.F2, r_sig))
    sum_sig = cj.pt_sum(cj.F2, r_sig, B)

    apx, apy, a_inf = g1_to_affine_dev(r_pk)
    sx, sy, s_inf = g2_to_affine_dev(tuple(c[None] for c in sum_sig))

    # pairing lanes: (r_i PK_i, H_i) for live i, plus (-G1, sum_sig)
    xp = jnp.concatenate([apx, jnp.asarray(neg_g1[0])])
    yp = jnp.concatenate([apy, jnp.asarray(neg_g1[1])])
    xq = jnp.concatenate([h_x, sx])
    yq = jnp.concatenate([h_y, sy])
    lane_mask = jnp.concatenate([mask & ~a_inf & h_ok, ~s_inf])
    return pj.multi_pairing_check(xp, yp, xq, yq, lane_mask)


@functools.lru_cache(maxsize=16)
def _rlc_kernel(batch: int):
    """Jitted RLC kernel, message hashes computed on host."""
    import jax
    jnp = _jnp()

    def run(pk_x, pk_y, sig_x, sig_y, h_x, h_y, r_bits, mask):
        h_ok = jnp.ones(pk_x.shape[0], dtype=bool)
        return _rlc_pairing_core(pk_x, pk_y, sig_x, sig_y, h_x, h_y,
                                 h_ok, r_bits, mask)

    return jax.jit(run)


@functools.lru_cache(maxsize=16)
def _rlc_kernel_h2c(batch: int):
    """Jitted RLC kernel with DEVICE hash-to-curve: the 32-byte message
    roots enter as uint32 words and the whole statement batch —
    expand_message_xmd, SVDW map, cofactor clearing, scalar muls,
    pairings — runs in one device program."""
    import jax
    jnp = _jnp()
    from . import h2c_jax as h2c

    def run(pk_x, pk_y, sig_x, sig_y, msg_words, r_bits, mask):
        H = h2c.hash_to_g2_dev(msg_words)
        h_x, h_y, h_inf = g2_to_affine_dev(H)
        return _rlc_pairing_core(pk_x, pk_y, sig_x, sig_y, h_x, h_y,
                                 ~h_inf, r_bits, mask)

    return jax.jit(run)


@functools.lru_cache(maxsize=16)
def _msm_kernel(batch: int):
    """Jitted G1 MSM: batched 255-step double-and-add over all points at
    once, then a log-depth tree sum.  Fully uniform control flow; kept as
    the reference kernel and the `CST_MSM_ALGO=double-add` fallback."""
    import jax
    jnp = _jnp()

    def run(x, y, bits, mask):
        B = x.shape[0]
        one1 = jnp.broadcast_to(jnp.asarray(_fq.ONE_MONT),
                                x.shape).astype(jnp.int32)
        muls = cj.pt_scalar_mul(cj.F1, (x, y, one1), bits)
        muls = cj.pt_select(cj.F1, mask, muls,
                            cj.pt_infinity(cj.F1, muls))
        return cj.pt_sum(cj.F1, muls, B)

    return jax.jit(run)


@functools.lru_cache(maxsize=16)
def _msm_pippenger_kernel(batch: int, c: int):
    """Jitted G1 Pippenger MSM: one scan over the points scatters each
    into its per-window bucket (all ceil(255/c) windows in parallel),
    then suffix-sum bucket reduction and the windowed combine — total
    point-add work B + 2^(c+1) + 255/c instead of 255 doubles + adds per
    scalar.  Zero scalars (and padding lanes) land in bucket 0, which the
    reduction skips, so no mask input is needed."""
    import jax
    jnp = _jnp()

    def run(x, y, digits):
        one1 = jnp.broadcast_to(jnp.asarray(_fq.ONE_MONT),
                                x.shape).astype(jnp.int32)
        return cj.pt_msm_pippenger(cj.F1, (x, y, one1), digits, c)

    return jax.jit(run)


SCALAR_BITS = 255  # BLS12-381 subgroup order is 255 bits


def _msm_window(n: int) -> int:
    """Pippenger window size for an n-point batch (2^c buckets must stay
    well under n for the bucket phase to amortize)."""
    if n < 32:
        return 4
    if n < 256:
        return 6
    if n < 2048:
        return 8
    return 10


# Pippenger's bucket scatter is sequential in B while double-and-add is
# sequential only in the 255 scalar bits (B-wide each step): bucketed
# wins while the batch is latency-bound, the uniform kernel wins once B
# is wide enough to saturate the vector units.  Crossover set at the
# bucket ladder's top shape; CST_MSM_ALGO=pippenger|double-add forces one.
_MSM_PIPPENGER_MAX = 512


def _msm_algo(batch: int) -> str:
    algo = os.environ.get("CST_MSM_ALGO", "auto")
    if algo == "auto":
        return "pippenger" if batch <= _MSM_PIPPENGER_MAX else "double-add"
    return algo


def g1_multi_exp_device_async(points, scalars,
                              block: bool = True) -> DeviceFuture:
    """Device G1 multiscalar multiplication (bucketed Pippenger below
    the width crossover, batched double-and-add above it — see
    `_msm_algo`).

    points: oracle Jacobian G1 points; scalars: ints (reduced mod r).
    Returns a `DeviceFuture` settling to an oracle Jacobian point (the
    limb→oracle conversion runs host-side at settle time).  The KZG
    batch path's `g1_lincomb` (`specs/deneb/polynomial-commitments.md
    :415-460` algorithms) lands here when the jax backend is active."""
    import jax.numpy as jnp

    assert len(points) == len(scalars) and len(points) > 0
    live = []
    for p, s in zip(points, scalars):
        s = int(s) % _pycurve.R
        if s == 0 or _pycurve.g1.is_inf(p):
            continue
        live.append((p, s))
    if not live:
        return DeviceFuture.settled(_pycurve.g1.infinity())

    B = _bucket(len(live))
    algo = _msm_algo(B)
    with telemetry.span("bls.g1_multi_exp_device", live=len(live),
                        padded=B, algo=algo):
        telemetry.count("msm.device.calls")
        telemetry.count(f"msm.algo.{algo}")
        telemetry.observe("msm.device.n", len(live))
        _count_lanes(len(live), B)
        x, y = cj.g1_affine_to_limbs([p for p, _ in live])
        pad = B - len(live)
        if pad:
            x = np.concatenate([x, np.repeat(x[:1], pad, 0)])
            y = np.concatenate([y, np.repeat(y[:1], pad, 0)])

        if algo == "pippenger":
            c = _msm_window(B)
            digits = cj.scalars_to_digits([s for _, s in live],
                                          SCALAR_BITS, c)
            if pad:
                digits = np.concatenate(
                    [digits, np.zeros((pad,) + digits.shape[1:], np.int32)])
            out = _dispatch(f"msm_pippenger@{B}w{c}",
                            _msm_pippenger_kernel(B, c),
                            (jnp.asarray(x), jnp.asarray(y),
                             jnp.asarray(digits)), block=block)
        else:
            bits = cj.scalars_to_bits([s for _, s in live], SCALAR_BITS)
            if pad:
                bits = np.concatenate(
                    [bits, np.zeros((pad, SCALAR_BITS), np.int32)])
            mask = np.arange(B) < len(live)
            out = _dispatch(f"msm_double_add@{B}", _msm_kernel(B),
                            (jnp.asarray(x), jnp.asarray(y),
                             jnp.asarray(bits), jnp.asarray(mask)),
                            block=block)
    # the point leaves the device at settle time, once, in serve.futures
    return value_future(out, convert=cj.g1_limbs_to_oracle)


def g1_multi_exp_device(points, scalars):
    """Synchronous facade over `g1_multi_exp_device_async`; returns the
    oracle Jacobian point."""
    return g1_multi_exp_device_async(points, scalars).result()


@functools.lru_cache(maxsize=16)
def _msm_sharded_kernel(n_devices: int, per_shard: int, c: int,
                        axis: str, device_ids: tuple | None = None):
    """shard_map'd Pippenger MSM over a `Mesh` (built by the shared
    partition-registry builder): each device runs the bucket
    accumulation + window combine over its own point shard, the D
    partial points ride one `all_gather` across the mesh (the
    psum-style final fold — point addition has no hardware psum, so the
    log-depth `pt_sum` tree over the gathered partials is the exact
    group-sum equivalent), replicated output.  Zero digits (padding
    lanes) land in bucket 0 which the reduction skips, so no mask
    crosses the mesh.

    `device_ids` pins the mesh to the surviving-device subset
    (`resilience.mesh` form), same contract as `_rlc_kernel_sharded`."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ...parallel.partition import build_mesh
    jnp = _jnp()

    mesh = build_mesh(n_devices=n_devices, device_ids=device_ids,
                      axis=axis)

    def local(x, y, digits):
        one1 = jnp.broadcast_to(jnp.asarray(_fq.ONE_MONT),
                                x.shape).astype(jnp.int32)
        partial = cj.pt_msm_pippenger(cj.F1, (x, y, one1), digits, c)
        gathered = jax.tree_util.tree_map(
            lambda co: jax.lax.all_gather(co, axis), partial)
        return cj.pt_sum(cj.F1, gathered, n_devices)

    from ...utils.jaxtools import shard_map_compat
    sharded = shard_map_compat(
        local, mesh=mesh, in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P())
    return jax.jit(sharded)


def g1_multi_exp_sharded_async(points, scalars,
                               n_devices: int | None = None,
                               axis: str = "data",
                               device_ids=None,
                               block: bool = True) -> DeviceFuture:
    """`g1_multi_exp_device_async` distributed over the device mesh:
    points shard across `n_devices`, each device accumulates its own
    Pippenger buckets, and one all_gather + log-depth point-sum fold
    combines the partial results.  The settled oracle point is
    identical to the single-chip path (group addition is associative —
    only the summation schedule differs).

    `device_ids` pins the mesh to specific `jax.devices()` indices (the
    resilience layer's surviving-device set); when given it overrides
    `n_devices`.  A one-device request degrades to the single-chip
    path."""
    import jax
    import jax.numpy as jnp

    assert len(points) == len(scalars) and len(points) > 0
    available = len(jax.devices())
    if device_ids is not None:
        device_ids = tuple(int(i) for i in device_ids)
        assert device_ids and max(device_ids) < available, device_ids
        n_devices = len(device_ids)
    if n_devices is None:
        n_devices = available
    n_devices = min(n_devices, available)
    if n_devices <= 1 and device_ids is None:
        return g1_multi_exp_device_async(points, scalars, block=block)

    live = []
    for p, s in zip(points, scalars):
        s = int(s) % _pycurve.R
        if s == 0 or _pycurve.g1.is_inf(p):
            continue
        live.append((p, s))
    if not live:
        return DeviceFuture.settled(_pycurve.g1.infinity())

    per_shard = _bucket((len(live) + n_devices - 1) // n_devices)
    lanes = n_devices * per_shard
    c = _msm_window(per_shard)
    with telemetry.span("bls.g1_multi_exp_sharded", live=len(live),
                        devices=n_devices, per_shard=per_shard):
        telemetry.count("msm.sharded.calls")
        _count_lanes(len(live), lanes)
        x, y = cj.g1_affine_to_limbs([p for p, _ in live])
        digits = cj.scalars_to_digits([s for _, s in live],
                                      SCALAR_BITS, c)
        pad = lanes - len(live)
        if pad:
            # padded lanes repeat point 0 with ZERO digits: bucket 0 is
            # never read, so they contribute nothing — no mask needed
            x = np.concatenate([x, np.repeat(x[:1], pad, 0)])
            y = np.concatenate([y, np.repeat(y[:1], pad, 0)])
            digits = np.concatenate(
                [digits, np.zeros((pad,) + digits.shape[1:], np.int32)])
        # cst: allow(recompile-unbucketed-dim): the device count keys
        # the executable — one value per host topology, not per batch
        kernel = _msm_sharded_kernel(n_devices, per_shard, c, axis,
                                     device_ids)
        out = _dispatch(f"msm_sharded@{n_devices}x{per_shard}w{c}",
                        kernel,
                        (jnp.asarray(x), jnp.asarray(y),
                         jnp.asarray(digits)), block=block)
    return value_future(out, convert=cj.g1_limbs_to_oracle)


def g1_multi_exp_sharded(points, scalars, n_devices: int | None = None,
                         axis: str = "data", device_ids=None):
    """Synchronous facade over `g1_multi_exp_sharded_async`."""
    return g1_multi_exp_sharded_async(
        points, scalars, n_devices=n_devices, axis=axis,
        device_ids=device_ids).result()


def _prepare_rlc_inputs(tasks, rand, lanes: int, device_h2c: bool = False):
    """Host-side prep shared by the single-device and sharded RLC paths:
    drop trivial pairs, hash messages (host) or pack them as uint32 words
    (device h2c), build limb arrays padded to `lanes` (or the bucket
    ladder shape when `lanes` is None).

    Returns (arrays, n_live) with arrays None when a degenerate path
    already decided the answer (n_live then carries the bool).  With
    device_h2c the h_x/h_y slots of the array tuple are replaced by one
    (B, 8) big-endian message-word matrix."""
    live = []
    for pk, msg, sig in tasks:
        if _pycurve.g1.is_inf(pk) and _pycurve.g2.is_inf(sig):
            continue          # 1 == 1 trivially; mirrors oracle skip
        live.append((pk, bytes(msg), sig))
    if not live:
        return None, True

    # infinity on only one side cannot go through the affine kernels —
    # fall back to per-task device checks (rare, adversarial-only)
    if any(_pycurve.g1.is_inf(pk) or _pycurve.g2.is_inf(sig)
           for pk, _, sig in live):
        ok = all(
            pairing_check_device([(pk, hash_to_g2(msg, DST_G2)),
                                  (_pycurve.g1.neg(_pycurve.G1_GEN), s)])
            for pk, msg, s in live)
        return None, ok

    B = _bucket(len(live)) if lanes is None else lanes
    assert B >= len(live)
    pk_x, pk_y = cj.g1_affine_to_limbs([t[0] for t in live])
    if device_h2c:
        from . import h2c_jax as h2c
        h_arrays = (h2c.msgs_to_words([t[1] for t in live]),)
    else:
        h_arrays = cj.g2_affine_to_limbs(
            [hash_to_g2(t[1], DST_G2) for t in live])
    sig_x, sig_y = cj.g2_affine_to_limbs([t[2] for t in live])
    scalars = [1] + [rand.getrandbits(RLC_SCALAR_BITS) | 1
                     for _ in range(len(live) - 1)]
    r_bits = cj.scalars_to_bits(scalars, RLC_SCALAR_BITS)

    pad = B - len(live)
    if pad:
        def _p(a):
            return np.concatenate([a, np.repeat(a[:1], pad, 0)])
        pk_x, pk_y = _p(pk_x), _p(pk_y)
        h_arrays = tuple(_p(a) for a in h_arrays)
        sig_x, sig_y = _p(sig_x), _p(sig_y)
        r_bits = np.concatenate(
            [r_bits, np.zeros((pad, RLC_SCALAR_BITS), np.int32)])
    mask = np.arange(B) < len(live)
    return ((pk_x, pk_y, sig_x, sig_y) + h_arrays + (r_bits, mask),
            len(live))


def batch_verify_async(tasks, rng=None, device_h2c: bool | None = None,
                       block: bool = True) -> DeviceFuture:
    """tasks: [(g1_pubkey_jacobian, message_bytes, g2_sig_jacobian)].

    Verifies all FastAggregateVerify-style statements
    e(PK_i, H(m_i)) == e(G1, S_i) at once: random 128-bit coefficients
    r_i collapse them into   prod e(r_i PK_i, H_i) · e(-G1, Σ r_i S_i) == 1.
    Returns a `DeviceFuture[bool]`: host prep + dispatch happen here,
    the verdict crosses to the host only at `result()` — the serve
    executor dispatches the NEXT batch while this one executes.

    With device_h2c (the default for 32-byte message roots; opt out with
    CST_BLS_DEVICE_H2C=0) the message hashing runs on device too, so the
    host only parses points and draws coefficients."""
    if not tasks:
        return DeviceFuture.settled(True)
    rand = rng if rng is not None else secrets.SystemRandom()
    if device_h2c is None:
        device_h2c = os.environ.get("CST_BLS_DEVICE_H2C", "1") != "0"
    # the device xmd kernel is specialized to 32-byte signing roots
    device_h2c = device_h2c and all(
        len(bytes(m)) == 32 for _, m, _ in tasks)
    with telemetry.span("bls.batch_verify", tasks=len(tasks),
                        device_h2c=device_h2c):
        telemetry.count("bls.batch_verify.calls")
        arrays, n = _prepare_rlc_inputs(tasks, rand, None,
                                        device_h2c=device_h2c)
        if arrays is None:
            # degenerate path: trivial skip or the per-task host
            # fallback — no statements reached the batched kernel
            return DeviceFuture.settled(bool(n))
        jnp = _jnp()
        # lanes=None above means _prepare_rlc_inputs padded to the
        # ladder shape for n live lanes — recompute it rather than
        # reading arrays[0].shape (a raw dim the analyzer would flag)
        B = _bucket(n)
        # h2c routing counted per LIVE lane, after prepare: the
        # degenerate paths above hash on the host (or not at all)
        telemetry.count("bls.h2c.device" if device_h2c else "bls.h2c.host",
                        n)
        _count_lanes(n, B)
        kernel = _rlc_kernel_h2c if device_h2c else _rlc_kernel
        name = f"rlc_{'h2c' if device_h2c else 'host_hash'}@{B}"
        out = _dispatch(name, kernel(B),
                        tuple(jnp.asarray(a) for a in arrays), block=block)
    return bool_future(out)


def batch_verify(tasks, rng=None, device_h2c: bool | None = None) -> bool:
    """Synchronous facade over `batch_verify_async` (the block
    executor's settle call); the bool fetch lives in `serve.futures`."""
    return batch_verify_async(tasks, rng=rng,
                              device_h2c=device_h2c).result()


@functools.lru_cache(maxsize=16)
def _rlc_kernel_sharded(n_devices: int, per_shard: int, axis: str,
                        device_ids: tuple | None = None):
    """shard_map'd RLC batch over a `Mesh`: every device scalar-muls and
    Miller-loops its own lane shard, partial signature sums and partial
    Miller products ride one `all_gather` each across the mesh (ICI, not
    host), and the single final exponentiation runs replicated.  The
    multi-chip form of `_rlc_kernel` — same predicate, same soundness.

    `device_ids` (a tuple of `jax.devices()` indices) builds the mesh
    from exactly those devices instead of the first `n_devices` — the
    mesh-resilience layer's shrunken-mesh form (`resilience.mesh`): a
    lost shard's statements re-bucket across the SURVIVING devices, not
    a renumbered prefix that might include the dead one.  The mesh
    itself comes from the shared partition-registry builder
    (`parallel.partition.build_mesh`) — one mesh-construction path for
    the RLC batch, the sharded MSM, the epoch step, and the sharded
    forests."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ...parallel.partition import build_mesh
    jnp = _jnp()

    mesh = build_mesh(n_devices=n_devices, device_ids=device_ids,
                      axis=axis)
    neg_g1 = cj.g1_affine_to_limbs([_pycurve.g1.neg(_pycurve.G1_GEN)])

    def local(pk_x, pk_y, sig_x, sig_y, h_x, h_y, r_bits, mask):
        B = pk_x.shape[0]   # per-shard lanes
        one1 = jnp.broadcast_to(jnp.asarray(_fq.ONE_MONT),
                                pk_x.shape).astype(jnp.int32)
        one2 = jnp.broadcast_to(jnp.asarray(tw.FQ2_ONE_L),
                                sig_x.shape).astype(jnp.int32)

        r_pk = cj.pt_scalar_mul(cj.F1, (pk_x, pk_y, one1), r_bits)
        r_sig = cj.pt_scalar_mul(cj.F2, (sig_x, sig_y, one2), r_bits)
        r_sig = cj.pt_select(cj.F2, mask, r_sig,
                             cj.pt_infinity(cj.F2, r_sig))
        # local signature partial sum, then combine shards' partials
        local_sum = cj.pt_sum(cj.F2, r_sig, B)
        gathered = jax.tree_util.tree_map(
            lambda c: jax.lax.all_gather(c, axis), local_sum)
        sum_sig = cj.pt_sum(cj.F2, gathered, n_devices)

        # local pairing lanes (r_i PK_i, H_i): shared-accumulator Miller
        # product per shard (one Fq12 squaring per bit per device)
        apx, apy, a_inf = g1_to_affine_dev(r_pk)
        partial = pj.miller_product_batch(apx, apy, h_x, h_y,
                                          mask & ~a_inf)
        partials = jax.lax.all_gather(partial, axis)    # (D, <fq12>)
        total = pj._product_tree(partials, n_devices)

        # the shared (-G1, Σ r_i S_i) lane, multiplied in exactly once
        sx, sy, s_inf = g2_to_affine_dev(
            tuple(c[None] for c in sum_sig))
        f_extra = pj.miller_product_batch(
            jnp.asarray(neg_g1[0]), jnp.asarray(neg_g1[1]), sx, sy,
            ~s_inf)
        total = tw.fq12_mul(total, f_extra)
        return tw.fq12_is_one(pj.final_exponentiate(total))

    from ...utils.jaxtools import shard_map_compat
    sharded = shard_map_compat(
        local, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P(axis),
                  P(axis), P(axis)),
        out_specs=P(),
    )
    return jax.jit(sharded)


def batch_verify_sharded_async(tasks, n_devices: int | None = None,
                               rng=None, axis: str = "data",
                               device_ids=None) -> DeviceFuture:
    """`batch_verify_async` distributed over the device mesh: lanes
    shard across `n_devices`, cross-device combination is two
    all_gathers (partial G2 sums, partial Miller products), one
    replicated final exponentiation.  Accept/reject is bit-identical to
    `batch_verify`.

    `device_ids` pins the mesh to specific `jax.devices()` indices (the
    resilience layer's surviving-device set after a `device_loss`);
    when given it overrides `n_devices`.  A one-device set degrades to
    the single-chip `batch_verify_async` path."""
    import jax

    if not tasks:
        return DeviceFuture.settled(True)
    available = len(jax.devices())
    if device_ids is not None:
        device_ids = tuple(int(i) for i in device_ids)
        assert device_ids and max(device_ids) < available, device_ids
        n_devices = len(device_ids)
    if n_devices is None:
        n_devices = available
    n_devices = min(n_devices, available)
    if n_devices <= 1 and device_ids is None:
        # a 1-wide IMPLICIT request degrades to the single-chip path;
        # an explicit one-survivor device set must keep the mesh form —
        # batch_verify_async has no device pinning, and the default
        # device may be exactly the dead one the caller is avoiding
        return batch_verify_async(tasks, rng=rng)
    rand = rng if rng is not None else secrets.SystemRandom()
    # pad lanes to devices x power-of-two per-shard bucket
    n_tasks = len(tasks)
    per_shard = _bucket((n_tasks + n_devices - 1) // n_devices)
    # resilience fault seam (one module-global read when idle): the
    # mesh chaos rounds inject `device_loss` here — the same boundary a
    # real XlaRuntimeError from a dead mesh device surfaces at
    if faults.active():
        faults.maybe_inject("dispatch",
                            f"rlc_sharded@{n_devices}x{per_shard}")
    arrays, n = _prepare_rlc_inputs(tasks, rand,
                                    n_devices * per_shard)
    if arrays is None:
        return DeviceFuture.settled(bool(n))
    jnp = _jnp()
    with telemetry.span("bls.batch_verify_sharded", tasks=n_tasks,
                        devices=n_devices, per_shard=per_shard):
        telemetry.count("bls.batch_verify_sharded.calls")
        _count_lanes(n, n_devices * per_shard)
        jargs = tuple(jnp.asarray(a) for a in arrays)
        # cst: allow(recompile-unbucketed-dim): the device count keys
        # the executable — one value per host topology, not per batch
        kernel = _rlc_kernel_sharded(n_devices, per_shard, axis,
                                     device_ids)
        out = kernel(*jargs)
    # cost-capture seam, outside the span so the AOT analysis pass does
    # not contaminate the measured wall (capture degrades to an error
    # record if the backend cannot analyze the mesh-sharded executable)
    costmodel.capture(f"rlc_sharded@{n_devices}x{per_shard}",
                      kernel, jargs)
    costmodel.sample_watermark("bls.batch_verify_sharded")
    return bool_future(out)


def batch_verify_sharded(tasks, n_devices: int | None = None,
                         rng=None, axis: str = "data",
                         device_ids=None) -> bool:
    """Synchronous facade over `batch_verify_sharded_async`."""
    return batch_verify_sharded_async(tasks, n_devices=n_devices,
                                      rng=rng, axis=axis,
                                      device_ids=device_ids).result()
