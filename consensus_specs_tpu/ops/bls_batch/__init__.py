"""Batched TPU BLS verification — the north star's hot path.

Public surface:

  pairing_check_device(pairs)      drop-in for the oracle's pairing_check
                                   (`ops/bls/pairing.py:160`): product of
                                   pairings == 1, one shared final exp,
                                   computed on device.
  batch_verify(tasks)              random-linear-combination batch of
                                   FastAggregateVerify-style checks: B
                                   signatures verified with B+1 pairings
                                   and ONE final exponentiation, with the
                                   G1/G2 scalar multiplications also on
                                   device.

Host keeps parsing/subgroup checks/hash-to-curve (the oracle code); the
device does every pairing and scalar multiplication.  Batch shapes are
padded to power-of-two buckets so jit caches a handful of executables.

Replaces the reference's native backends behind
`eth2spec/utils/bls.py:141-296` (milagro `Verify`/`FastAggregateVerify`,
arkworks point ops).
"""

from __future__ import annotations

import functools
import secrets

import numpy as np

from ..bls import curve as _pycurve
from ..bls.hash_to_curve import DST_G2, hash_to_g2
from . import curve_jax as cj
from . import fq as _fq
from . import pairing_jax as pj
from . import tower as tw

RLC_SCALAR_BITS = 128     # soundness 2^-128 per forged batch


def _jnp():
    import jax.numpy as jnp
    return jnp


def _bucket(n: int) -> int:
    m = 1
    while m < n:
        m *= 2
    return m


# --- device helpers ---------------------------------------------------------


def g1_to_affine_dev(p):
    """Batched Jacobian -> affine on device; returns (x, y, inf_mask)."""
    X, Y, Z = p
    inf = _fq.fq_is_zero(Z)
    zi = _fq.fq_inv(Z)
    zi2 = _fq.fq_sqr(zi)
    return _fq.fq_mul(X, zi2), _fq.fq_mul(Y, _fq.fq_mul(zi2, zi)), inf


def g2_to_affine_dev(p):
    X, Y, Z = p
    inf = tw.fq2_is_zero(Z)
    zi = tw.fq2_inv(Z)
    zi2 = tw.fq2_sqr(zi)
    return tw.fq2_mul(X, zi2), tw.fq2_mul(Y, tw.fq2_mul(zi2, zi)), inf


# --- pairing check ----------------------------------------------------------


@functools.lru_cache(maxsize=16)
def _pairing_check_fn(batch: int):
    import jax

    def run(xp, yp, xq, yq, mask):
        return pj.multi_pairing_check(xp, yp, xq, yq, mask)

    return jax.jit(run)


def pairing_check_device(pairs) -> bool:
    """pairs: [(g1_jacobian, g2_jacobian)] oracle points.  Infinity pairs
    contribute the identity (matching the oracle's skip)."""
    live = [(p, q) for p, q in pairs
            if not _pycurve.g1.is_inf(p) and not _pycurve.g2.is_inf(q)]
    if not live:
        return True
    jnp = _jnp()
    B = _bucket(len(live))
    xp, yp = cj.g1_affine_to_limbs([p for p, _ in live])
    xq, yq = cj.g2_affine_to_limbs([q for _, q in live])
    pad = B - len(live)
    if pad:
        xp = np.concatenate([xp, np.repeat(xp[:1], pad, 0)])
        yp = np.concatenate([yp, np.repeat(yp[:1], pad, 0)])
        xq = np.concatenate([xq, np.repeat(xq[:1], pad, 0)])
        yq = np.concatenate([yq, np.repeat(yq[:1], pad, 0)])
    mask = np.arange(B) < len(live)
    out = _pairing_check_fn(B)(jnp.asarray(xp), jnp.asarray(yp),
                               jnp.asarray(xq), jnp.asarray(yq),
                               jnp.asarray(mask))
    return bool(out)


# --- RLC batch verify -------------------------------------------------------


@functools.lru_cache(maxsize=16)
def _rlc_kernel(batch: int):
    """Jitted kernel: scalar-mul the B pubkeys and signatures by the random
    coefficients, sum the signature side, run the B+1 pairing product."""
    import jax
    jnp = _jnp()

    neg_g1 = cj.g1_affine_to_limbs([_pycurve.g1.neg(_pycurve.G1_GEN)])

    def run(pk_x, pk_y, sig_x, sig_y, h_x, h_y, r_bits, mask):
        B = pk_x.shape[0]
        one1 = jnp.broadcast_to(jnp.asarray(_fq.ONE_MONT),
                                pk_x.shape).astype(jnp.int32)
        one2 = jnp.broadcast_to(jnp.asarray(tw.FQ2_ONE_L),
                                sig_x.shape).astype(jnp.int32)

        r_pk = cj.pt_scalar_mul(cj.F1, (pk_x, pk_y, one1), r_bits)
        r_sig = cj.pt_scalar_mul(cj.F2, (sig_x, sig_y, one2), r_bits)
        # padding lanes -> infinity so they vanish from the signature sum
        r_sig = cj.pt_select(cj.F2, mask, r_sig,
                             cj.pt_infinity(cj.F2, r_sig))
        sum_sig = cj.pt_sum(cj.F2, r_sig, B)

        apx, apy, a_inf = g1_to_affine_dev(r_pk)
        sx, sy, s_inf = g2_to_affine_dev(tuple(c[None] for c in sum_sig))

        # pairing lanes: (r_i PK_i, H_i) for live i, plus (-G1, sum_sig)
        xp = jnp.concatenate([apx, jnp.asarray(neg_g1[0])])
        yp = jnp.concatenate([apy, jnp.asarray(neg_g1[1])])
        xq = jnp.concatenate([h_x, sx])
        yq = jnp.concatenate([h_y, sy])
        lane_mask = jnp.concatenate([mask & ~a_inf, ~s_inf])
        return pj.multi_pairing_check(xp, yp, xq, yq, lane_mask)

    return jax.jit(run)


@functools.lru_cache(maxsize=16)
def _msm_kernel(batch: int):
    """Jitted G1 MSM: batched 255-step double-and-add over all points at
    once, then a log-depth tree sum.  Uniform control flow — the
    TPU-idiomatic MSM (bucketed Pippenger's data-dependent gathers do not
    vectorize onto the MXU)."""
    import jax
    jnp = _jnp()

    def run(x, y, bits, mask):
        B = x.shape[0]
        one1 = jnp.broadcast_to(jnp.asarray(_fq.ONE_MONT),
                                x.shape).astype(jnp.int32)
        muls = cj.pt_scalar_mul(cj.F1, (x, y, one1), bits)
        muls = cj.pt_select(cj.F1, mask, muls,
                            cj.pt_infinity(cj.F1, muls))
        return cj.pt_sum(cj.F1, muls, B)

    return jax.jit(run)


SCALAR_BITS = 255  # BLS12-381 subgroup order is 255 bits


def g1_multi_exp_device(points, scalars):
    """Device G1 multiscalar multiplication.

    points: oracle Jacobian G1 points; scalars: ints (reduced mod r).
    Returns an oracle Jacobian point.  The KZG batch path's `g1_lincomb`
    (`specs/deneb/polynomial-commitments.md:415-460` algorithms) lands
    here when the jax backend is active."""
    import jax.numpy as jnp

    assert len(points) == len(scalars) and len(points) > 0
    live = []
    for p, s in zip(points, scalars):
        s = int(s) % _pycurve.R
        if s == 0 or _pycurve.g1.is_inf(p):
            continue
        live.append((p, s))
    if not live:
        return _pycurve.g1.infinity()

    B = _bucket(len(live))
    x, y = cj.g1_affine_to_limbs([p for p, _ in live])
    bits = cj.scalars_to_bits([s for _, s in live], SCALAR_BITS)
    pad = B - len(live)
    if pad:
        x = np.concatenate([x, np.repeat(x[:1], pad, 0)])
        y = np.concatenate([y, np.repeat(y[:1], pad, 0)])
        bits = np.concatenate([bits,
                               np.zeros((pad, SCALAR_BITS), np.int32)])
    mask = np.arange(B) < len(live)

    out = _msm_kernel(B)(jnp.asarray(x), jnp.asarray(y),
                         jnp.asarray(bits), jnp.asarray(mask))
    return cj.g1_limbs_to_oracle(tuple(np.asarray(c) for c in out))


def _prepare_rlc_inputs(tasks, rand, lanes: int):
    """Host-side prep shared by the single-device and sharded RLC paths:
    hash messages, drop trivial pairs, build limb arrays padded to
    `lanes` (or the power-of-two bucket when `lanes` is None).

    Returns (arrays, n_live) with arrays None when a degenerate path
    already decided the answer (n_live then carries the bool)."""
    live = []
    for pk, msg, sig in tasks:
        if _pycurve.g1.is_inf(pk) and _pycurve.g2.is_inf(sig):
            continue          # 1 == 1 trivially; mirrors oracle skip
        live.append((pk, hash_to_g2(bytes(msg), DST_G2), sig))
    if not live:
        return None, True

    # infinity on only one side cannot go through the affine kernels —
    # fall back to per-task device checks (rare, adversarial-only)
    if any(_pycurve.g1.is_inf(pk) or _pycurve.g2.is_inf(sig)
           for pk, _, sig in live):
        ok = all(
            pairing_check_device([(pk, h),
                                  (_pycurve.g1.neg(_pycurve.G1_GEN), s)])
            for pk, h, s in live)
        return None, ok

    B = _bucket(len(live)) if lanes is None else lanes
    assert B >= len(live)
    pk_x, pk_y = cj.g1_affine_to_limbs([t[0] for t in live])
    h_x, h_y = cj.g2_affine_to_limbs([t[1] for t in live])
    sig_x, sig_y = cj.g2_affine_to_limbs([t[2] for t in live])
    scalars = [1] + [rand.getrandbits(RLC_SCALAR_BITS) | 1
                     for _ in range(len(live) - 1)]
    r_bits = cj.scalars_to_bits(scalars, RLC_SCALAR_BITS)

    pad = B - len(live)
    if pad:
        def _p(a):
            return np.concatenate([a, np.repeat(a[:1], pad, 0)])
        pk_x, pk_y = _p(pk_x), _p(pk_y)
        h_x, h_y = _p(h_x), _p(h_y)
        sig_x, sig_y = _p(sig_x), _p(sig_y)
        r_bits = np.concatenate(
            [r_bits, np.zeros((pad, RLC_SCALAR_BITS), np.int32)])
    mask = np.arange(B) < len(live)
    return (pk_x, pk_y, sig_x, sig_y, h_x, h_y, r_bits, mask), len(live)


def batch_verify(tasks, rng=None) -> bool:
    """tasks: [(g1_pubkey_jacobian, message_bytes, g2_sig_jacobian)].

    Verifies all FastAggregateVerify-style statements
    e(PK_i, H(m_i)) == e(G1, S_i) at once: random 128-bit coefficients
    r_i collapse them into   prod e(r_i PK_i, H_i) · e(-G1, Σ r_i S_i) == 1.
    Host does hashing/aggregation; device does everything elliptic."""
    if not tasks:
        return True
    rand = rng if rng is not None else secrets.SystemRandom()
    arrays, n = _prepare_rlc_inputs(tasks, rand, None)
    if arrays is None:
        return bool(n)
    jnp = _jnp()
    pk_x, pk_y, sig_x, sig_y, h_x, h_y, r_bits, mask = arrays
    out = _rlc_kernel(pk_x.shape[0])(
        jnp.asarray(pk_x), jnp.asarray(pk_y), jnp.asarray(sig_x),
        jnp.asarray(sig_y), jnp.asarray(h_x), jnp.asarray(h_y),
        jnp.asarray(r_bits), jnp.asarray(mask))
    return bool(out)


@functools.lru_cache(maxsize=16)
def _rlc_kernel_sharded(n_devices: int, per_shard: int, axis: str):
    """shard_map'd RLC batch over a `Mesh`: every device scalar-muls and
    Miller-loops its own lane shard, partial signature sums and partial
    Miller products ride one `all_gather` each across the mesh (ICI, not
    host), and the single final exponentiation runs replicated.  The
    multi-chip form of `_rlc_kernel` — same predicate, same soundness."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    jnp = _jnp()

    mesh_devs = jax.devices()[:n_devices]
    mesh = Mesh(np.array(mesh_devs), (axis,))
    neg_g1 = cj.g1_affine_to_limbs([_pycurve.g1.neg(_pycurve.G1_GEN)])

    def local(pk_x, pk_y, sig_x, sig_y, h_x, h_y, r_bits, mask):
        B = pk_x.shape[0]   # per-shard lanes
        one1 = jnp.broadcast_to(jnp.asarray(_fq.ONE_MONT),
                                pk_x.shape).astype(jnp.int32)
        one2 = jnp.broadcast_to(jnp.asarray(tw.FQ2_ONE_L),
                                sig_x.shape).astype(jnp.int32)

        r_pk = cj.pt_scalar_mul(cj.F1, (pk_x, pk_y, one1), r_bits)
        r_sig = cj.pt_scalar_mul(cj.F2, (sig_x, sig_y, one2), r_bits)
        r_sig = cj.pt_select(cj.F2, mask, r_sig,
                             cj.pt_infinity(cj.F2, r_sig))
        # local signature partial sum, then combine shards' partials
        local_sum = cj.pt_sum(cj.F2, r_sig, B)
        gathered = jax.tree_util.tree_map(
            lambda c: jax.lax.all_gather(c, axis), local_sum)
        sum_sig = cj.pt_sum(cj.F2, gathered, n_devices)

        # local pairing lanes (r_i PK_i, H_i)
        apx, apy, a_inf = g1_to_affine_dev(r_pk)
        f_local = pj.miller_batch(apx, apy, h_x, h_y)
        one12 = jnp.broadcast_to(jnp.asarray(tw.FQ12_ONE_L),
                                 f_local.shape).astype(jnp.int32)
        live = mask & ~a_inf
        f_local = jnp.where(live[:, None, None, None, None], f_local,
                            one12)
        partial = pj._product_tree(f_local, B)          # unbatched <fq12>
        partials = jax.lax.all_gather(partial, axis)    # (D, <fq12>)
        total = pj._product_tree(partials, n_devices)

        # the shared (-G1, Σ r_i S_i) lane, multiplied in exactly once
        sx, sy, s_inf = g2_to_affine_dev(
            tuple(c[None] for c in sum_sig))
        f_extra = pj.miller_batch(
            jnp.asarray(neg_g1[0]), jnp.asarray(neg_g1[1]), sx, sy)
        one_extra = jnp.broadcast_to(
            jnp.asarray(tw.FQ12_ONE_L), f_extra.shape).astype(jnp.int32)
        f_extra = jnp.where((~s_inf)[:, None, None, None, None],
                            f_extra, one_extra)
        total = tw.fq12_mul(total, f_extra[0])
        return tw.fq12_is_one(pj.final_exponentiate(total))

    sharded = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P(axis),
                  P(axis), P(axis)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)


def batch_verify_sharded(tasks, n_devices: int | None = None,
                         rng=None, axis: str = "data") -> bool:
    """`batch_verify` distributed over the device mesh: lanes shard
    across `n_devices`, cross-device combination is two all_gathers
    (partial G2 sums, partial Miller products), one replicated final
    exponentiation.  Accept/reject is bit-identical to `batch_verify`."""
    import jax

    if not tasks:
        return True
    available = len(jax.devices())
    if n_devices is None:
        n_devices = available
    n_devices = min(n_devices, available)
    if n_devices <= 1:
        return batch_verify(tasks, rng=rng)
    rand = rng if rng is not None else secrets.SystemRandom()
    # pad lanes to devices x power-of-two per-shard bucket
    n_tasks = len(tasks)
    per_shard = _bucket((n_tasks + n_devices - 1) // n_devices)
    arrays, n = _prepare_rlc_inputs(tasks, rand,
                                    n_devices * per_shard)
    if arrays is None:
        return bool(n)
    jnp = _jnp()
    out = _rlc_kernel_sharded(n_devices, per_shard, axis)(
        *(jnp.asarray(a) for a in arrays))
    return bool(out)
