"""Batched optimal-ate pairing on TPU.

The Miller loop runs on the sextic twist in Fq2 with Jacobian T and
*inversion-free* line coefficients.  All B pairings advance in lockstep
through a lax.scan over the fixed 64-bit BLS parameter and share ONE Fq12
accumulator: because every caller consumes a *product* of pairings, the
per-bit recurrence is  f <- f^2 * prod_b line_b  — a single unbatched Fq12
squaring per loop bit regardless of B (`miller_product_batch`), instead of
B per-pairing squarings product-reduced at the end.  One shared final
exponentiation finishes the batch — the random-linear-combination batching
trick of the KZG spec (`specs/deneb/polynomial-commitments.md:415`
`verify_kzg_proof_batch`) applied inside the pairing layer itself.

For pairings whose G2 argument is known on the host (every
`pairing_check_device` call: verify/aggregate-verify hashes, KZG setup
points), `precompute_g2_lines` runs the whole T-update schedule in oracle
Fq2 arithmetic ONCE per point and ships the line coefficients as scan
constants; the device program then contains no G2 Jacobian arithmetic at
all (`miller_product_precomp`) — the classical fixed-argument pairing
optimization.  Any per-line Fq2 scale factor introduced by representative
choices is killed by the easy part of the final exponentiation, so the
host and device T-update formulas need not match step for step.

Line equations (derived, not transcribed; scaling by Fq2 factors is free
because any Fq2 element is killed by the easy part of the final
exponentiation — a^(q^6-1) = 1 for a in Fq2):

  tangent at T=(X,Y,Z):  L(x,y) = 2YZ^3·y − 3X^2Z^2·x + (3X^3 − 2Y^2)
  chord T,(x2,y2):       L(x,y) = ZH·y − I·x + (I·x2 − ZH·y2)
                          with H = X − x2·Z^2, I = Y − y2·Z^3

evaluated at the untwist preimage of P, i.e. x = x_P·cx⁻¹, y = y_P·cy⁻¹
where (cx, cy) are the oracle's derived untwist constants
(`ops/bls/pairing.py:39-53`) — each a single w-power, so the line is a
3-term sparse Fq12 element with fixed basis slots.

The final exponentiation uses the BLS12 x-structure of the hard part:
3·(q⁴−q²+1)/r = (x−1)²·(x+q)·(x²+q²−1) + 3, verified at import; the extra
factor 3 is harmless for pairing *checks* (μ_r has prime order r ∤ 3).
"""

from __future__ import annotations

import functools

import numpy as np

from ..bls import curve as _pycurve
from ..bls import pairing as _pyp
from ..bls.fields import BLS_X, Q, R, Fq2
from . import curve_jax as cj
from . import fq as _fq
from . import tower as tw

# --- derived constants (host) ----------------------------------------------

assert 3 * ((Q**4 - Q**2 + 1) // R) == \
    (BLS_X - 1) ** 2 * (BLS_X + Q) * (BLS_X**2 + Q**2 - 1) + 3

# |x| bits MSB-first, skipping the leading 1 (Miller loop schedule)
_X_BITS = np.array([int(b) for b in bin(abs(BLS_X))[3:]], dtype=np.int32)
# |x| bits MSB-first including the leading 1 (final-exp pow_x schedule)
_X_BITS_FULL = np.array([int(b) for b in bin(abs(BLS_X))[2:]], dtype=np.int32)


def _w_slot(e12) -> tuple[int, Fq2]:
    """Decompose an Fq12 that is a single w-power multiple: (index, coeff)."""
    coeffs = [e12.c0.c0, e12.c1.c0, e12.c0.c1, e12.c1.c1, e12.c0.c2,
              e12.c1.c2]
    nz = [(i, c) for i, c in enumerate(coeffs) if not c.is_zero()]
    assert len(nz) == 1, "untwist constant is not a pure w-power"
    return nz[0]


# untwist preimage of P scales: x_P·cx⁻¹, y_P·cy⁻¹
_JX, _SX = _w_slot(_pyp._fq2_to_fq12(Fq2(1, 0)) * _pyp._UNTWIST_CX.inv())
_JY, _SY = _w_slot(_pyp._fq2_to_fq12(Fq2(1, 0)) * _pyp._UNTWIST_CY.inv())
_SX_L = tw.fq2_from_oracle(_SX)
_SY_L = tw.fq2_from_oracle(_SY)


def _jnp():
    import jax.numpy as jnp
    return jnp


def _line_to_fq12(c0, cx_xp, cy_yp):
    """Place the three Fq2 line terms into their fixed w-power slots."""
    jnp = _jnp()
    slots = [None] * 6
    slots[0] = c0
    slots[_JX] = tw.fq2_mul(cx_xp, jnp.broadcast_to(
        jnp.asarray(_SX_L, dtype=jnp.int32), cx_xp.shape))
    slots[_JY] = tw.fq2_mul(cy_yp, jnp.broadcast_to(
        jnp.asarray(_SY_L, dtype=jnp.int32), cy_yp.shape))
    zero = jnp.zeros_like(c0)
    slots = [zero if s is None else s for s in slots]
    return tw._from_w_coeffs(slots)


def _dbl_step(T, xp, yp):
    """Tangent-line coefficients at T, evaluated at P; then T <- 2T."""
    X, Y, Z = T
    XX = tw.fq2_sqr(X)
    YY = tw.fq2_sqr(Y)
    ZZ = tw.fq2_sqr(Z)
    cy = tw.fq2_mul_small(tw.fq2_mul(tw.fq2_mul(Y, Z), ZZ), 2)      # 2YZ^3
    cx = tw.fq2_neg(tw.fq2_mul_small(tw.fq2_mul(XX, ZZ), 3))        # -3X^2Z^2
    c0 = tw.fq2_sub(tw.fq2_mul_small(tw.fq2_mul(XX, X), 3),
                    tw.fq2_mul_small(YY, 2))                        # 3X^3-2Y^2
    line = _line_to_fq12(c0, tw.fq2_mul_fq(cx, xp), tw.fq2_mul_fq(cy, yp))
    return cj.pt_double(cj.F2, T), line


def _add_step(T, xq, yq, xp, yp):
    """Chord-line coefficients through T and affine Q; then T <- T + Q."""
    X, Y, Z = T
    ZZ = tw.fq2_sqr(Z)
    H = tw.fq2_sub(X, tw.fq2_mul(xq, ZZ))
    I = tw.fq2_sub(Y, tw.fq2_mul(yq, tw.fq2_mul(ZZ, Z)))
    ZH = tw.fq2_mul(Z, H)
    cy = ZH
    cx = tw.fq2_neg(I)
    c0 = tw.fq2_sub(tw.fq2_mul(I, xq), tw.fq2_mul(ZH, yq))
    line = _line_to_fq12(c0, tw.fq2_mul_fq(cx, xp), tw.fq2_mul_fq(cy, yp))
    jnp = _jnp()
    one = jnp.broadcast_to(jnp.asarray(tw.FQ2_ONE_L, dtype=jnp.int32),
                           xq.shape)
    Tn = cj.pt_add(cj.F2, T, (xq, yq, one))
    return Tn, line


def miller_product_batch(xp, yp, xq, yq, mask):
    """prod_b f_{|x|,Q_b}(P_b)^(mask_b) with a SHARED Fq12 accumulator.

    Since conjugation is a field automorphism,
    prod_b conj(f_b) = conj(prod_b f_b), and each per-bit update
    f_b <- f_b^2 * line_b folds into  F <- F^2 * prod_b line_b:  one
    unbatched Fq12 squaring per Miller-loop bit independent of B, plus a
    log-depth product tree over the (sparse) lines.  Masked-out lanes
    contribute the identity line every step.  Returns a single (<fq12>)
    value (conjugated; NOT final-exponentiated)."""
    import jax
    jnp = _jnp()

    B = xp.shape[0]
    one2 = jnp.broadcast_to(jnp.asarray(tw.FQ2_ONE_L, dtype=jnp.int32),
                            xq.shape)
    T0 = (xq, yq, one2)
    f0 = jnp.asarray(tw.FQ12_ONE_L, dtype=jnp.int32)
    one_b = jnp.broadcast_to(jnp.asarray(tw.FQ12_ONE_L, dtype=jnp.int32),
                             (B,) + tw.FQ12_ONE_L.shape)
    mask_e = mask[:, None, None, None, None]

    def step(carry, bit):
        f, T = carry
        f = tw.fq12_sqr(f)                       # ONE square, unbatched
        T, line = _dbl_step(T, xp, yp)
        line = jnp.where(mask_e, line, one_b)
        f = tw.fq12_mul(f, _product_tree(line, B))

        def with_add(op):
            f_, T_ = op
            T2, line2 = _add_step(T_, xq, yq, xp, yp)
            line2 = jnp.where(mask_e, line2, one_b)
            return tw.fq12_mul(f_, _product_tree(line2, B)), T2

        f, T = jax.lax.cond(bit == 1, with_add, lambda op: op, (f, T))
        return (f, T), None

    (f, _), _ = jax.lax.scan(step, (f0, T0),
                             jnp.asarray(_X_BITS, dtype=jnp.int32))
    return tw.fq12_conj(f)       # negative BLS parameter


# --- fixed-argument (host-known G2) line precomputation ---------------------


def _host_line_coeffs_dbl(T):
    """Oracle-Fq2 tangent coefficients at Jacobian T (same formula as
    `_dbl_step`, host side)."""
    X, Y, Z = T
    XX = X.square()
    YY = Y.square()
    ZZ = Z.square()
    cy = Y * Z * ZZ * 2
    cx = -(XX * ZZ * 3)
    c0 = XX * X * 3 - YY * 2
    return c0, cx, cy


def _host_line_coeffs_add(T, xq, yq):
    """Oracle-Fq2 chord coefficients through T and affine (xq, yq)."""
    X, Y, Z = T
    ZZ = Z.square()
    H = X - xq * ZZ
    I = Y - yq * ZZ * Z
    ZH = Z * H
    return I * xq - ZH * yq, -I, ZH


@functools.lru_cache(maxsize=64)
def _g2_lines_from_affine(x0: int, x1: int, y0: int, y1: int) -> np.ndarray:
    """Miller line coefficients for a fixed affine G2 point, as one
    (n_bits, 6, 2, N_LIMBS) int32 array of Montgomery Fq2 limbs in the
    order [dbl_c0, dbl_cx, dbl_cy, add_c0, add_cx, add_cy] (add slots are
    identity filler on 0 bits; the device consumer guards them with the
    same lax.cond schedule)."""
    xq, yq = Fq2(x0, x1), Fq2(y0, y1)
    T = _pycurve.g2.from_affine(xq, yq)
    rows = []
    filler = (Fq2(1, 0), Fq2(0, 0), Fq2(0, 0))
    for bit in _X_BITS:
        dbl = _host_line_coeffs_dbl(T)
        T = _pycurve.g2.double(T)
        if bit:
            add = _host_line_coeffs_add(T, xq, yq)
            T = _pycurve.g2.add(T, _pycurve.g2.from_affine(xq, yq))
        else:
            add = filler
        rows.append(np.stack([tw.fq2_from_oracle(c) for c in dbl + add]))
    return np.stack(rows).astype(np.int32)


def precompute_g2_lines(q_pt) -> np.ndarray:
    """Host-side fixed-argument precompute for a (non-infinity) oracle
    Jacobian G2 point; cached per affine point."""
    aff = _pycurve.g2.to_affine(q_pt)
    assert aff is not None, "cannot precompute lines for infinity"
    x, y = aff
    return _g2_lines_from_affine(x.c0, x.c1, y.c0, y.c1)


def miller_product_precomp(xp, yp, lines, mask):
    """Shared-accumulator Miller product with HOST-precomputed lines.

    xp/yp (B,33) G1 affine limbs; lines (n_bits, B, 6, 2, 33) from
    `precompute_g2_lines` stacked over the batch; mask (B,).  The scan
    body contains no G2 arithmetic — only the sparse line placement, the
    product tree, and the single accumulator square/multiply."""
    import jax
    jnp = _jnp()

    B = xp.shape[0]
    f0 = jnp.asarray(tw.FQ12_ONE_L, dtype=jnp.int32)
    one_b = jnp.broadcast_to(jnp.asarray(tw.FQ12_ONE_L, dtype=jnp.int32),
                             (B,) + tw.FQ12_ONE_L.shape)
    mask_e = mask[:, None, None, None, None]

    def _line(c0, cx, cy):
        line = _line_to_fq12(c0, tw.fq2_mul_fq(cx, xp),
                             tw.fq2_mul_fq(cy, yp))
        return jnp.where(mask_e, line, one_b)

    def step(f, xs):
        bit, L = xs
        f = tw.fq12_sqr(f)
        f = tw.fq12_mul(
            f, _product_tree(_line(L[:, 0], L[:, 1], L[:, 2]), B))

        def with_add(f_):
            return tw.fq12_mul(
                f_, _product_tree(_line(L[:, 3], L[:, 4], L[:, 5]), B))

        f = jax.lax.cond(bit == 1, with_add, lambda f_: f_, f)
        return f, None

    f, _ = jax.lax.scan(step, f0,
                        (jnp.asarray(_X_BITS, dtype=jnp.int32), lines))
    return tw.fq12_conj(f)


def multi_pairing_check_precomp(xp, yp, lines, mask):
    """`multi_pairing_check` with fixed-argument precomputed lines."""
    total = miller_product_precomp(xp, yp, lines, mask)
    return tw.fq12_is_one(final_exponentiate(total))


def fq12_pow_x_abs(g):
    """g^|x| via square-and-multiply over the fixed 64-bit parameter."""
    import jax
    jnp = _jnp()

    def step(acc, bit):
        acc = tw.fq12_sqr(acc)
        acc = jax.lax.cond(bit == 1, lambda a: tw.fq12_mul(a, g),
                           lambda a: a, acc)
        return acc, None

    one = jnp.broadcast_to(jnp.asarray(tw.FQ12_ONE_L, dtype=jnp.int32),
                           g.shape)
    acc, _ = jax.lax.scan(step, one,
                          jnp.asarray(_X_BITS_FULL, dtype=jnp.int32))
    return acc


def final_exponentiate(f):
    """f^(3·(q^12-1)/r) — x-structured hard part, cyclotomic inverses as
    conjugates."""
    # easy part: f^((q^6-1)(q^2+1))
    f1 = tw.fq12_mul(tw.fq12_conj(f), tw.fq12_inv(f))
    m = tw.fq12_mul(tw.fq12_frobenius(f1, 2), f1)

    def pow_x(g):                      # g^x  (x negative)
        return tw.fq12_conj(fq12_pow_x_abs(g))

    def pow_xm1(g):                    # g^(x-1)
        return tw.fq12_mul(pow_x(g), tw.fq12_conj(g))

    t1 = pow_xm1(pow_xm1(m))                              # m^((x-1)^2)
    t2 = tw.fq12_mul(pow_x(t1), tw.fq12_frobenius(t1, 1))  # ^(x+q)
    t3 = tw.fq12_mul(
        tw.fq12_mul(pow_x(pow_x(t2)), tw.fq12_frobenius(t2, 2)),
        tw.fq12_conj(t2))                                 # ^(x^2+q^2-1)
    return tw.fq12_mul(t3, tw.fq12_mul(tw.fq12_sqr(m), m))  # · m^3


def _product_tree(f, n: int):
    """Product over the leading batch axis: exactly n-1 Fq12 multiplies in
    ceil(log2 n) levels (odd level sizes carry their tail element instead
    of padding with identities)."""
    jnp = _jnp()
    assert f.shape[0] == n and n >= 1
    while n > 1:
        half = n // 2
        prod = tw.fq12_mul(f[:half], f[half:2 * half])
        if n % 2:
            f = jnp.concatenate([prod, f[2 * half:]])
            n = half + 1
        else:
            f, n = prod, half
    return f[0]


def multi_pairing_check(xp, yp, xq, yq, mask):
    """prod_i e(P_i, Q_i)^(mask_i) == 1 with one final exponentiation.

    mask (B,) bool lets callers pad the batch to a fixed shape (padded
    lanes contribute the identity).  Runs the shared-accumulator Miller
    product: one Fq12 squaring per loop bit for the whole batch."""
    total = miller_product_batch(xp, yp, xq, yq, mask)
    return tw.fq12_is_one(final_exponentiate(total))
