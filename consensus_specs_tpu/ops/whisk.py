"""Whisk (EIP-7441) proof backends.

The reference delegates both proof systems to the external
`curdleproofs` package (`specs/_features/eip7441/beacon-chain.md:98-133`).
This module provides self-contained equivalents over this repo's own
BLS12-381 G1 arithmetic:

- **Tracker (opening) proofs** — a REAL Chaum-Pedersen discrete-log
  equality proof, Fiat-Shamir transformed: prove knowledge of `k` with
  `k_r_G == k * r_G` and `k_commitment == k * G` without revealing `k`.
  Same security claim as the curdleproofs tracker proof.

- **Shuffle proofs** — a TRANSPARENT (non-zero-knowledge) shuffle
  argument: the proof reveals the permutation and the rerandomization
  scalar, and the verifier recomputes the shuffle.  The verified
  relation is exactly curdleproofs' (post is a rerandomized permutation
  of pre); what is deliberately dropped is the hiding property, which
  only matters for live privacy, not for spec state-transition
  correctness.  The wire format is versioned so a hiding backend can
  slot in.
"""

from __future__ import annotations

import hashlib

from .bls import ciphersuite as cs
from .bls.curve import g1

SHUFFLE_PROOF_VERSION = b"\x01"  # transparent argument


def _order() -> int:
    from .bls import curve

    return curve.R


def _point(b: bytes):
    """Deserialize + subgroup-check a compressed G1 point."""
    return cs.g1_from_bytes(bytes(b))


def _scalar_from_hash(*parts: bytes) -> int:
    h = hashlib.sha256()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "big") % _order()


# --- tracker (opening) proofs ----------------------------------------------


def generate_whisk_tracker_proof(tracker_r_g: bytes, tracker_k_r_g: bytes,
                                 k_commitment: bytes, k: int,
                                 nonce: bytes = b"") -> bytes:
    """Chaum-Pedersen DLEQ proof for (G, k_commitment) ~ (r_G, k_r_G)."""
    order = _order()
    r_g = _point(tracker_r_g)
    u = _scalar_from_hash(b"whisk-nonce", int(k).to_bytes(32, "big"),
                          bytes(tracker_r_g), nonce) or 1
    a1 = cs.g1_to_bytes(g1.mul(cs.G1_GEN, u))
    a2 = cs.g1_to_bytes(g1.mul(r_g, u))
    c = _scalar_from_hash(b"whisk-dleq", bytes(tracker_r_g),
                          bytes(tracker_k_r_g), bytes(k_commitment),
                          a1, a2)
    z = (u + c * int(k)) % order
    return a1 + a2 + z.to_bytes(32, "big")


def is_valid_whisk_tracker_proof(tracker_r_g: bytes, tracker_k_r_g: bytes,
                                 k_commitment: bytes,
                                 proof: bytes) -> bool:
    """Verify the DLEQ proof: z*G == A1 + c*k_commitment and
    z*r_G == A2 + c*k_r_G."""
    try:
        proof = bytes(proof)
        if len(proof) != 128:
            return False
        a1_b, a2_b, z_b = proof[:48], proof[48:96], proof[96:]
        a1, a2 = _point(a1_b), _point(a2_b)
        r_g = _point(tracker_r_g)
        k_r_g = _point(tracker_k_r_g)
        commitment = _point(k_commitment)
    except Exception:
        return False
    z = int.from_bytes(z_b, "big")
    if z >= _order():
        return False
    c = _scalar_from_hash(b"whisk-dleq", bytes(tracker_r_g),
                          bytes(tracker_k_r_g), bytes(k_commitment),
                          a1_b, a2_b)
    lhs1 = g1.mul(cs.G1_GEN, z)
    rhs1 = g1.add(a1, g1.mul(commitment, c))
    if not g1.eq_points(lhs1, rhs1):
        return False
    lhs2 = g1.mul(r_g, z)
    rhs2 = g1.add(a2, g1.mul(k_r_g, c))
    return g1.eq_points(lhs2, rhs2)


# --- shuffle proofs ---------------------------------------------------------


def generate_whisk_shuffle_proof(pre_trackers, permutation, r: int):
    """Shuffle + transparent proof.  Returns (post_trackers, proof);
    trackers are (r_G_bytes, k_r_G_bytes) pairs."""
    order = _order()
    r = int(r) % order
    assert r > 1
    assert sorted(permutation) == list(range(len(pre_trackers)))
    post = []
    for src in permutation:
        r_g, k_r_g = pre_trackers[src]
        post.append((cs.g1_to_bytes(g1.mul(_point(r_g), r)),
                     cs.g1_to_bytes(g1.mul(_point(k_r_g), r))))
    proof = (SHUFFLE_PROOF_VERSION
             + len(permutation).to_bytes(2, "big")
             + b"".join(int(i).to_bytes(2, "big") for i in permutation)
             + r.to_bytes(32, "big"))
    return post, proof


def is_valid_whisk_shuffle_proof(pre_trackers, post_trackers,
                                 proof: bytes) -> bool:
    """Verify post == rerandomized permutation of pre under the revealed
    (permutation, r)."""
    try:
        proof = bytes(proof)
        if len(proof) < 3 or proof[0:1] != SHUFFLE_PROOF_VERSION:
            return False
        n = int.from_bytes(proof[1:3], "big")
        if n != len(pre_trackers) or n != len(post_trackers):
            return False
        if len(proof) != 3 + 2 * n + 32:
            return False
        permutation = [int.from_bytes(proof[3 + 2 * i:5 + 2 * i], "big")
                       for i in range(n)]
        r = int.from_bytes(proof[3 + 2 * n:], "big")
        if sorted(permutation) != list(range(n)):
            return False
        if not 1 < r < _order():
            return False
        for (post_r_g, post_k_r_g), src in zip(post_trackers, permutation):
            pre_r_g, pre_k_r_g = pre_trackers[src]
            if bytes(post_r_g) != cs.g1_to_bytes(
                    g1.mul(_point(pre_r_g), r)):
                return False
            if bytes(post_k_r_g) != cs.g1_to_bytes(
                    g1.mul(_point(pre_k_r_g), r)):
                return False
        return True
    except Exception:
        return False
