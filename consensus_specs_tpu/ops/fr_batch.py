"""Batched BLS12-381 *scalar*-field (Fr) arithmetic + the KZG
barycentric-evaluation kernel for TPU.

`ops/bls_batch/fq.py` holds the base-field (Fq) limb machinery; this is
its scalar-field sibling, built as a parametric field kernel with the
SAME representation and safety budget (33 x 12-bit limbs in int32 lanes,
Montgomery R = 2**396, signed-lazy values < 2**388).  The generous limb
count for a 255-bit modulus buys headroom: a 4096-term lazy accumulation
(value < 2**269) stays far inside the budget, so the barycentric sum
needs no mid-stream collapses.

The flagship kernel evaluates blob polynomials in evaluation form at
out-of-domain points (polynomial-commitments.md
`evaluate_polynomial_in_evaluation_form` — the host-side hot path of
`verify_blob_kzg_proof_batch`, one modular inversion per field element):

    f(z) = (z^W - 1)/W * sum_i f_i * w_i / (z - w_i)

All W denominators invert simultaneously via Fermat exponentiation
(fixed 255-bit square-and-multiply — uniform control flow, every lane
busy), the per-element products ride one fused multiply pass, and the
final reduction is a single log-depth tree sum.
"""

from __future__ import annotations

import functools

import numpy as np

from .. import telemetry
from ..telemetry import costmodel

LIMB_BITS = 12
N_LIMBS = 33
LIMB_MASK = (1 << LIMB_BITS) - 1
R_BITS = LIMB_BITS * N_LIMBS

# BLS12-381 subgroup order (the KZG BLS_MODULUS)
R_MODULUS = 0x73eda753299d7d483339d80809a1d80553bda402fffe5bfeffffffff00000001


def _jnp():
    import jax.numpy as jnp
    return jnp


class PrimeFieldKernel:
    """Device limb arithmetic for an odd prime modulus < 2**300.

    Same algorithms as `bls_batch/fq.py` (carries, CIOS Montgomery
    multiply, Fermat inversion) with the constants instance-bound so any
    prime can reuse them."""

    def __init__(self, modulus: int):
        assert modulus % 2 == 1 and modulus.bit_length() <= 300
        self.modulus = modulus
        self.r_mont = pow(2, R_BITS, modulus)
        self.q_inv_neg = (-pow(modulus, -1, 1 << LIMB_BITS)) \
            % (1 << LIMB_BITS)
        self.p_limbs = self.int_to_limbs(modulus)
        self.two_p_limbs = self.int_to_limbs(2 * modulus)
        self.one_mont = self.to_mont(1)
        self._p_minus_2_bits = np.array(
            [int(b) for b in bin(modulus - 2)[2:]], dtype=np.int32)

    # --- host conversions --------------------------------------------------

    def int_to_limbs(self, x: int) -> np.ndarray:
        assert 0 <= x < (1 << R_BITS)
        return np.array([(x >> (LIMB_BITS * i)) & LIMB_MASK
                         for i in range(N_LIMBS)], dtype=np.int32)

    def limbs_to_int(self, limbs) -> int:
        arr = np.asarray(limbs).reshape(-1, N_LIMBS)
        assert arr.shape[0] == 1
        return sum(int(v) << (LIMB_BITS * i)
                   for i, v in enumerate(arr[0]))

    def to_mont(self, x: int) -> np.ndarray:
        return self.int_to_limbs((x % self.modulus) * self.r_mont
                                 % self.modulus)

    def to_mont_batch(self, xs) -> np.ndarray:
        """Vectorized int batch -> Montgomery limb matrix: the big-int
        reduction stays per-element, limb extraction rides numpy
        (bytes -> bits -> 12-bit groups)."""
        m, r = self.modulus, self.r_mont
        n_bytes = (R_BITS + 7) // 8
        raw = b"".join(((int(x) % m) * r % m).to_bytes(n_bytes, "little")
                       for x in xs)
        as_bytes = np.frombuffer(raw, dtype=np.uint8).reshape(
            len(xs), n_bytes)
        bits = np.unpackbits(as_bytes, axis=1, bitorder="little")
        bits = bits[:, :N_LIMBS * LIMB_BITS].reshape(
            len(xs), N_LIMBS, LIMB_BITS)
        weights = (1 << np.arange(LIMB_BITS)).astype(np.int32)
        return (bits * weights).sum(axis=2).astype(np.int32)

    def from_mont(self, limbs) -> int:
        return (self.limbs_to_int(limbs)
                * pow(self.r_mont, -1, self.modulus)) % self.modulus

    # --- device ops (shapes (..., 33); broadcast over leading axes) --------

    def carry(self, x, passes: int = 1):
        jnp = _jnp()
        for _ in range(passes):
            lo = x & LIMB_MASK
            hi = x >> LIMB_BITS
            y = lo + jnp.concatenate(
                [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1)
            x = jnp.concatenate(
                [y[..., :-1], (x[..., -1:] + hi[..., -2:-1])], axis=-1)
        return x

    def add(self, a, b):
        return self.carry(a + b)

    def sub(self, a, b):
        return self.carry(a - b)

    def mul(self, a, b):
        """CIOS Montgomery product ab/R mod p (same budget as fq_mul)."""
        import jax
        jnp = _jnp()

        p = jnp.asarray(self.p_limbs)
        a_steps = jnp.moveaxis(a, -1, 0)

        def step(t, a_i):
            u = t + a_i[..., None] * b
            m = (u[..., 0] * self.q_inv_neg) & LIMB_MASK
            u = u + m[..., None] * p
            c0 = u[..., 0] >> LIMB_BITS
            t = jnp.concatenate(
                [u[..., 1:], jnp.zeros_like(u[..., :1])], axis=-1)
            t = t.at[..., 0].add(c0)
            return self.carry(t), None

        t0 = jnp.zeros(jnp.broadcast_shapes(a.shape, b.shape),
                       dtype=jnp.int32)
        t, _ = jax.lax.scan(step, t0, a_steps)
        return self.carry(t)

    def inv(self, a):
        """Fermat inversion a**(p-2); zero maps to zero."""
        import jax
        jnp = _jnp()

        bits = jnp.asarray(self._p_minus_2_bits)

        def step(acc, bit):
            acc = self.mul(acc, acc)
            acc_mul = self.mul(acc, a)
            return jnp.where(bit, acc_mul, acc), None

        one = jnp.broadcast_to(jnp.asarray(self.one_mont),
                               a.shape).astype(jnp.int32)
        acc, _ = jax.lax.scan(step, one, bits)
        return acc

    def pow_uint(self, a, exponent: int):
        """a**exponent for a fixed python-int exponent."""
        import jax
        jnp = _jnp()

        bits = jnp.asarray(
            np.array([int(b) for b in bin(exponent)[2:]],
                     dtype=np.int32))

        def step(acc, bit):
            acc = self.mul(acc, acc)
            acc_mul = self.mul(acc, a)
            return jnp.where(bit, acc_mul, acc), None

        one = jnp.broadcast_to(jnp.asarray(self.one_mont),
                               a.shape).astype(jnp.int32)
        acc, _ = jax.lax.scan(step, one, bits)
        return acc

    def tree_sum(self, x, n: int):
        """Lazy sum over the leading axis (log depth).  Value magnitude
        grows to n * 2p — callers keep n under ~2**120 so the signed
        budget (< 2**388) holds; one final Montgomery collapse
        renormalizes."""
        jnp = _jnp()
        m = 1
        while m < n:
            m *= 2
        if m != n:
            pad = jnp.zeros((m - n,) + x.shape[1:], dtype=jnp.int32)
            x = jnp.concatenate([x, pad])
        while m > 1:
            m //= 2
            x = self.carry(x[:m] + x[m:2 * m])
        return x[0]


FR = PrimeFieldKernel(R_MODULUS)

# batch-shape ladder for the DAS coset-interpolation kernel: rungs land
# the sampling-matrix shapes exactly (a single sampled cell, one full
# 128-column row, the 128x8 sampling matrix); larger batches fall back
# to powers of two like `bls_batch._bucket`
_DAS_STEPS = (16, 128, 1024)


def das_rung(n: int) -> int:
    """Padded cell-batch shape for n live statements (the compile-key
    launderer the analyzer recognizes, like `_bucket`/`mesh_rung`)."""
    b = 1 if n <= 1 else 1 << (n - 1).bit_length()
    for step in _DAS_STEPS:
        if b <= step:
            return step
    return b


@functools.lru_cache(maxsize=4)
def _barycentric_kernel(width: int):
    """Jitted f(z) for one (poly, z) pair over a width-W multiplicative
    domain h*G (h enters as the Montgomery limbs of h^W and of
    1/(W*h^W), both host-known): with domain points x_i,

        f(z) = (z^W - h^W) / (W * h^W) * sum_i f_i * x_i / (z - x_i)

    (vanishing polynomial X^W - h^W, Z'(x_i) = W * h^W / x_i).  The
    classic roots-of-unity formula is the h = 1 instance, so the one
    kernel serves both the blob-domain callers and the DAS coset
    evaluations — the sum is order-agnostic, so callers pass the domain
    exactly as stored (bit-reversed slices included, no re-sort)."""
    import jax
    jnp = _jnp()

    def run(poly, roots, z, h_pow_w, inv_scale):
        # poly/roots: (W, 33) Montgomery; z/h_pow_w/inv_scale: (33,)
        a = FR.mul(poly, roots)                     # f_i * x_i
        b = FR.sub(jnp.broadcast_to(z, roots.shape), roots)  # z - x_i
        d = FR.inv(b)                                # all lanes at once
        terms = FR.mul(a, d)
        total = FR.tree_sum(terms, width)            # value < W * 2p

        z_pow = FR.pow_uint(z, width)
        factor = FR.sub(z_pow, h_pow_w)
        total = FR.mul(total, factor)                # collapses magnitude
        total = FR.mul(total, inv_scale)
        return total

    return jax.jit(run)


@functools.lru_cache(maxsize=2)
def _roots_mont(roots_key):
    return FR.to_mont_batch(list(roots_key))


def barycentric_eval_async(poly_ints, domain_ints, z_int,
                           shift_int: int = 1):
    """Device evaluation of an evaluation-form polynomial at an
    out-of-domain z, deferred: returns a `serve.futures.DeviceFuture`
    settling to a canonical python int — the field element returns to
    the host (and leaves Montgomery form) only at `result()`, so a
    batch of blob evaluations pipelines instead of serializing on each
    element.

    `domain_ints` is the evaluation domain in THE SAME ORDER as
    `poly_ints` — any order works (the barycentric sum commutes), so
    coset slices stay in their stored bit-reversed order.  For a coset
    domain h*G pass `shift_int=h`; the default 1 is the classic
    roots-of-unity formula, bit-compatible with every existing caller."""
    from ..serve.futures import value_future

    width = len(poly_ints)
    assert width == len(domain_ints)
    h = int(shift_int) % R_MODULUS
    assert h != 0
    h_pow_w = pow(h, width, R_MODULUS)
    inv_scale = pow(width * h_pow_w % R_MODULUS, R_MODULUS - 2,
                    R_MODULUS)
    jnp = _jnp()
    # cst: allow(recompile-unbucketed-dim): width is a KZG evaluation
    # domain size — fixed per preset (4096 blob / 64 DAS cell coset /
    # 4 minimal), so the lru-cached kernel compiles a handful of times
    # per process, never per batch; the coset shift is a traced INPUT,
    # not a compile key
    kfn = _barycentric_kernel(width)
    with telemetry.span("fr.barycentric_eval", width=width):
        telemetry.count("fr.barycentric_eval.calls")
        poly = jnp.asarray(FR.to_mont_batch([int(v) for v in poly_ints]))
        roots = jnp.asarray(_roots_mont(tuple(int(r)
                                              for r in domain_ints)))
        z = jnp.asarray(FR.to_mont(int(z_int)))
        hw = jnp.asarray(FR.to_mont(h_pow_w))
        scale = jnp.asarray(FR.to_mont(inv_scale))
        out = kfn(poly, roots, z, hw, scale)
    # cost-capture seam (CST_COSTMODEL rounds), outside the span: the
    # AOT analysis pass must not contaminate the measured wall
    costmodel.capture(f"barycentric@{width}", kfn,
                      (poly, roots, z, hw, scale))
    return value_future(out, convert=FR.from_mont)


def barycentric_eval(poly_ints, domain_ints, z_int,
                     shift_int: int = 1) -> int:
    """Synchronous facade over `barycentric_eval_async` (the host KZG
    library's call shape); the fetch lives in `serve.futures`."""
    return barycentric_eval_async(poly_ints, domain_ints, z_int,
                                  shift_int=shift_int).result()


# --- DAS coset interpolation (the RLI term's field work) --------------------


@functools.lru_cache(maxsize=4)
def _coset_interpolate_kernel(batch: int, width: int):
    """Jitted sum_k I_k coefficients for a cell batch: evals (B, W, 33)
    in stored coset order, the rev-folded inverse-DFT matrix
    (W, W, 33), and per-(cell, coefficient) weights (B, W, 33) carrying
    r^k * h_k^-j.  One scan over the W input positions accumulates the
    lazy matrix product (W * 2p stays far inside the signed budget),
    one Montgomery multiply applies the weights, one log-depth tree sum
    folds the batch — O(B*W^2) lane multiplies, zero host round trips."""
    import jax
    jnp = _jnp()

    def run(evals, idft, weights):
        ev_steps = jnp.moveaxis(evals, 1, 0)         # (W, B, 33)

        def step(acc, x):
            e_i, m_i = x                             # (B, 33), (W, 33)
            return FR.add(acc, FR.mul(e_i[:, None, :], m_i[None])), None

        acc0 = jnp.zeros((evals.shape[0], width, N_LIMBS),
                         dtype=jnp.int32)
        acc, _ = jax.lax.scan(step, acc0, (ev_steps, idft))
        weighted = FR.mul(acc, weights)              # r^k * h_k^-j * c
        return FR.tree_sum(weighted, batch)          # (W, 33)

    return jax.jit(run)


@functools.lru_cache(maxsize=2)
def _idft_mont(matrix_key):
    return FR.to_mont_batch(
        [v for row in matrix_key for v in row]).reshape(
            len(matrix_key), len(matrix_key), N_LIMBS)


def _from_mont_rows(host):
    return [FR.from_mont(row) for row in np.asarray(host)]


def coset_interpolate_sum_async(evals_rows, idft_matrix, weight_rows):
    """Device-resident interpolation-coefficient fold for a DAS cell
    batch: settles to the `width` canonical field elements

        S_j = sum_k weights[k][j] * (IDFT(evals[k]))_j

    — with weights r^k * h_k^-j this IS the coefficient vector of
    sum_k r^k I_k(X), the batched verification equation's RLI scalars
    (`das.verify`).  `evals_rows` stay in stored (bit-reversed coset)
    order; the permutation is folded into `idft_matrix`
    (`das.ciphersuite.coset_idft_matrix`), so there is no host-side
    re-sort.  Batch shapes ride the `das_rung` ladder; padded rows
    carry zero weights and vanish from the fold."""
    from ..serve.futures import value_future

    n = len(evals_rows)
    assert n == len(weight_rows) and n >= 1
    width = len(idft_matrix)
    b = das_rung(n)
    jnp = _jnp()
    # cst: allow(recompile-unbucketed-dim): width is the cell coset
    # size — FIELD_ELEMENTS_PER_CELL, preset-fixed at 64 — so only the
    # das_rung-laundered batch axis varies across calls
    kfn = _coset_interpolate_kernel(b, width)
    with telemetry.span("fr.coset_interpolate", cells=n, padded=b,
                        width=width):
        telemetry.count("fr.coset_interpolate.calls")
        flat = [int(v) for row in evals_rows for v in row]
        flat += [0] * ((b - n) * width)
        evals = jnp.asarray(
            FR.to_mont_batch(flat).reshape(b, width, N_LIMBS))
        wflat = [int(v) for row in weight_rows for v in row]
        wflat += [0] * ((b - n) * width)        # zero weight = dead lane
        weights = jnp.asarray(
            FR.to_mont_batch(wflat).reshape(b, width, N_LIMBS))
        idft = jnp.asarray(_idft_mont(
            tuple(tuple(int(v) for v in row) for row in idft_matrix)))
        out = kfn(evals, idft, weights)
    # cost-capture seam, outside the span (same contract as barycentric)
    costmodel.capture(f"coset_interp@{b}", kfn, (evals, idft, weights))
    return value_future(out, convert=_from_mont_rows)


def coset_interpolate_sum(evals_rows, idft_matrix, weight_rows):
    """Synchronous facade over `coset_interpolate_sum_async`."""
    return coset_interpolate_sum_async(evals_rows, idft_matrix,
                                       weight_rows).result()


# --- radix-2 field FFT (the DAS coefficient/evaluation transform) -----------
#
# The host recursive `_fft` in `das/compute.py` is the oracle shape:
# natural-order input, natural-order output, twiddles taken from the
# caller's root list.  The device kernel is the same arithmetic as ONE
# dispatch — bit-reverse the input on host (free: an index permutation
# before the Montgomery conversion), then log2(n) butterfly stages of
# lazy adds around one CIOS multiply per v-lane.  Magnitudes grow by
# ~2p per stage (u rides adds only), far inside the signed 2**388
# budget even at n = 8192 (13 stages); the final scale multiply
# (inv_n for the inverse, 1 for the forward) collapses everything back
# under 2p, so outputs feed elementwise follow-ups directly.


@functools.lru_cache(maxsize=8)
def _fr_fft_kernel(n: int, batch: int):
    """Jitted batched radix-2 DIT FFT over an order-n multiplicative
    domain: x (B, n, 33) Montgomery in BIT-REVERSED order, per-stage
    twiddle tables ((1,33), (2,33), ..., (n/2,33)), one scale limb
    (33,).  Natural-order output, value-identical to the recursive
    host `_fft` (exact mod-p arithmetic: any correct FFT bracketing
    computes the same field elements)."""
    import jax
    jnp = _jnp()

    def run(x, tws, scale):
        for tw in tws:
            h = tw.shape[0]
            blocks = x.reshape(batch, n // (2 * h), 2, h, N_LIMBS)
            u = blocks[:, :, 0]
            v = blocks[:, :, 1]
            t = FR.mul(v, tw[None, None])
            x = jnp.stack([FR.add(u, t), FR.sub(u, t)],
                          axis=2).reshape(batch, n, N_LIMBS)
        return FR.mul(x, scale[None, None])

    return jax.jit(run)


@functools.lru_cache(maxsize=8)
def _fft_twiddles_mont(roots_key: tuple):
    """Per-stage Montgomery twiddle tables for a root tuple: stage with
    half-width h multiplies lane i by roots[i * n/(2h)]."""
    n = len(roots_key)
    tws, h = [], 1
    while h < n:
        stride = n // (2 * h)
        tws.append(FR.to_mont_batch(
            [roots_key[i * stride] for i in range(h)]))
        h *= 2
    return tuple(tws)


@functools.lru_cache(maxsize=8)
def _bitrev_perm(n: int) -> tuple:
    bits = n.bit_length() - 1
    return tuple(int(f"{i:0{bits}b}"[::-1], 2) if bits else 0
                 for i in range(n))


def _from_mont_matrix(host):
    arr = np.asarray(host)
    return [[FR.from_mont(row) for row in block] for block in arr]


def fr_fft_async(rows, roots, inverse: bool = False):
    """Device FFT of a batch of field-element rows over the domain the
    caller supplies (the same contract as the host `_fft`/`_ifft` in
    `das/compute.py`: natural-order values in, natural-order out,
    `inverse=True` runs the reversed-root transform and scales by
    1/n).  Settles to a list of rows of canonical ints.

    One dispatch replaces the O(n log n) host recursion — the FK20
    producer calls this at n=128 (64 circulant columns in one batch),
    n=4096 (coefficient extraction) and n=8192 (cell evaluation /
    erasure-decode round trips)."""
    from ..serve.futures import value_future

    n = len(roots)
    assert n and n & (n - 1) == 0
    batch = len(rows)
    # cst: allow(recompile-traced-branch): rows is the HOST input list
    # (the device array is built further down) — this is argument
    # validation, not a branch on a traced value
    assert batch >= 1 and all(len(r) == n for r in rows)
    roots_key = tuple(int(r) % R_MODULUS for r in roots)
    if inverse:
        roots_key = (roots_key[0],) + roots_key[:0:-1]
        scale_int = pow(n, R_MODULUS - 2, R_MODULUS)
    else:
        scale_int = 1
    jnp = _jnp()
    # cst: allow(recompile-unbucketed-dim): n is a KZG domain order —
    # preset-fixed (128 / 4096 / 8192 on mainnet) — and batch is the
    # FK20 residue count (64) or a single blob, so the lru-cached
    # kernel compiles a handful of shapes per process, never per call
    kfn = _fr_fft_kernel(n, batch)
    perm = _bitrev_perm(n)
    with telemetry.span("fr.fft", n=n, batch=batch,
                        inverse=bool(inverse)):
        telemetry.count("fr.fft.calls")
        flat = [int(row[j]) for row in rows for j in perm]
        x = jnp.asarray(FR.to_mont_batch(flat).reshape(batch, n,
                                                       N_LIMBS))
        tws = tuple(jnp.asarray(t)
                    for t in _fft_twiddles_mont(roots_key))
        scale = jnp.asarray(FR.to_mont(scale_int))
        out = kfn(x, tws, scale)
    # cost-capture seam, outside the span (same contract as barycentric)
    costmodel.capture(f"fr_fft@{n}x{batch}", kfn, (x, tws, scale))
    return value_future(out, convert=_from_mont_matrix)


def fr_fft(rows, roots, inverse: bool = False):
    """Synchronous facade over `fr_fft_async`."""
    return fr_fft_async(rows, roots, inverse=inverse).result()
