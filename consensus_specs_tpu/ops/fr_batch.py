"""Batched BLS12-381 *scalar*-field (Fr) arithmetic + the KZG
barycentric-evaluation kernel for TPU.

`ops/bls_batch/fq.py` holds the base-field (Fq) limb machinery; this is
its scalar-field sibling, built as a parametric field kernel with the
SAME representation and safety budget (33 x 12-bit limbs in int32 lanes,
Montgomery R = 2**396, signed-lazy values < 2**388).  The generous limb
count for a 255-bit modulus buys headroom: a 4096-term lazy accumulation
(value < 2**269) stays far inside the budget, so the barycentric sum
needs no mid-stream collapses.

The flagship kernel evaluates blob polynomials in evaluation form at
out-of-domain points (polynomial-commitments.md
`evaluate_polynomial_in_evaluation_form` — the host-side hot path of
`verify_blob_kzg_proof_batch`, one modular inversion per field element):

    f(z) = (z^W - 1)/W * sum_i f_i * w_i / (z - w_i)

All W denominators invert simultaneously via Fermat exponentiation
(fixed 255-bit square-and-multiply — uniform control flow, every lane
busy), the per-element products ride one fused multiply pass, and the
final reduction is a single log-depth tree sum.
"""

from __future__ import annotations

import functools

import numpy as np

from .. import telemetry
from ..telemetry import costmodel

LIMB_BITS = 12
N_LIMBS = 33
LIMB_MASK = (1 << LIMB_BITS) - 1
R_BITS = LIMB_BITS * N_LIMBS

# BLS12-381 subgroup order (the KZG BLS_MODULUS)
R_MODULUS = 0x73eda753299d7d483339d80809a1d80553bda402fffe5bfeffffffff00000001


def _jnp():
    import jax.numpy as jnp
    return jnp


class PrimeFieldKernel:
    """Device limb arithmetic for an odd prime modulus < 2**300.

    Same algorithms as `bls_batch/fq.py` (carries, CIOS Montgomery
    multiply, Fermat inversion) with the constants instance-bound so any
    prime can reuse them."""

    def __init__(self, modulus: int):
        assert modulus % 2 == 1 and modulus.bit_length() <= 300
        self.modulus = modulus
        self.r_mont = pow(2, R_BITS, modulus)
        self.q_inv_neg = (-pow(modulus, -1, 1 << LIMB_BITS)) \
            % (1 << LIMB_BITS)
        self.p_limbs = self.int_to_limbs(modulus)
        self.two_p_limbs = self.int_to_limbs(2 * modulus)
        self.one_mont = self.to_mont(1)
        self._p_minus_2_bits = np.array(
            [int(b) for b in bin(modulus - 2)[2:]], dtype=np.int32)

    # --- host conversions --------------------------------------------------

    def int_to_limbs(self, x: int) -> np.ndarray:
        assert 0 <= x < (1 << R_BITS)
        return np.array([(x >> (LIMB_BITS * i)) & LIMB_MASK
                         for i in range(N_LIMBS)], dtype=np.int32)

    def limbs_to_int(self, limbs) -> int:
        arr = np.asarray(limbs).reshape(-1, N_LIMBS)
        assert arr.shape[0] == 1
        return sum(int(v) << (LIMB_BITS * i)
                   for i, v in enumerate(arr[0]))

    def to_mont(self, x: int) -> np.ndarray:
        return self.int_to_limbs((x % self.modulus) * self.r_mont
                                 % self.modulus)

    def to_mont_batch(self, xs) -> np.ndarray:
        """Vectorized int batch -> Montgomery limb matrix: the big-int
        reduction stays per-element, limb extraction rides numpy
        (bytes -> bits -> 12-bit groups)."""
        m, r = self.modulus, self.r_mont
        n_bytes = (R_BITS + 7) // 8
        raw = b"".join(((int(x) % m) * r % m).to_bytes(n_bytes, "little")
                       for x in xs)
        as_bytes = np.frombuffer(raw, dtype=np.uint8).reshape(
            len(xs), n_bytes)
        bits = np.unpackbits(as_bytes, axis=1, bitorder="little")
        bits = bits[:, :N_LIMBS * LIMB_BITS].reshape(
            len(xs), N_LIMBS, LIMB_BITS)
        weights = (1 << np.arange(LIMB_BITS)).astype(np.int32)
        return (bits * weights).sum(axis=2).astype(np.int32)

    def from_mont(self, limbs) -> int:
        return (self.limbs_to_int(limbs)
                * pow(self.r_mont, -1, self.modulus)) % self.modulus

    # --- device ops (shapes (..., 33); broadcast over leading axes) --------

    def carry(self, x, passes: int = 1):
        jnp = _jnp()
        for _ in range(passes):
            lo = x & LIMB_MASK
            hi = x >> LIMB_BITS
            y = lo + jnp.concatenate(
                [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1)
            x = jnp.concatenate(
                [y[..., :-1], (x[..., -1:] + hi[..., -2:-1])], axis=-1)
        return x

    def add(self, a, b):
        return self.carry(a + b)

    def sub(self, a, b):
        return self.carry(a - b)

    def mul(self, a, b):
        """CIOS Montgomery product ab/R mod p (same budget as fq_mul)."""
        import jax
        jnp = _jnp()

        p = jnp.asarray(self.p_limbs)
        a_steps = jnp.moveaxis(a, -1, 0)

        def step(t, a_i):
            u = t + a_i[..., None] * b
            m = (u[..., 0] * self.q_inv_neg) & LIMB_MASK
            u = u + m[..., None] * p
            c0 = u[..., 0] >> LIMB_BITS
            t = jnp.concatenate(
                [u[..., 1:], jnp.zeros_like(u[..., :1])], axis=-1)
            t = t.at[..., 0].add(c0)
            return self.carry(t), None

        t0 = jnp.zeros(jnp.broadcast_shapes(a.shape, b.shape),
                       dtype=jnp.int32)
        t, _ = jax.lax.scan(step, t0, a_steps)
        return self.carry(t)

    def inv(self, a):
        """Fermat inversion a**(p-2); zero maps to zero."""
        import jax
        jnp = _jnp()

        bits = jnp.asarray(self._p_minus_2_bits)

        def step(acc, bit):
            acc = self.mul(acc, acc)
            acc_mul = self.mul(acc, a)
            return jnp.where(bit, acc_mul, acc), None

        one = jnp.broadcast_to(jnp.asarray(self.one_mont),
                               a.shape).astype(jnp.int32)
        acc, _ = jax.lax.scan(step, one, bits)
        return acc

    def pow_uint(self, a, exponent: int):
        """a**exponent for a fixed python-int exponent."""
        import jax
        jnp = _jnp()

        bits = jnp.asarray(
            np.array([int(b) for b in bin(exponent)[2:]],
                     dtype=np.int32))

        def step(acc, bit):
            acc = self.mul(acc, acc)
            acc_mul = self.mul(acc, a)
            return jnp.where(bit, acc_mul, acc), None

        one = jnp.broadcast_to(jnp.asarray(self.one_mont),
                               a.shape).astype(jnp.int32)
        acc, _ = jax.lax.scan(step, one, bits)
        return acc

    def tree_sum(self, x, n: int):
        """Lazy sum over the leading axis (log depth).  Value magnitude
        grows to n * 2p — callers keep n under ~2**120 so the signed
        budget (< 2**388) holds; one final Montgomery collapse
        renormalizes."""
        jnp = _jnp()
        m = 1
        while m < n:
            m *= 2
        if m != n:
            pad = jnp.zeros((m - n,) + x.shape[1:], dtype=jnp.int32)
            x = jnp.concatenate([x, pad])
        while m > 1:
            m //= 2
            x = self.carry(x[:m] + x[m:2 * m])
        return x[0]


FR = PrimeFieldKernel(R_MODULUS)


@functools.lru_cache(maxsize=4)
def _barycentric_kernel(width: int):
    """Jitted f(z) for one (poly, z) pair over a width-W domain."""
    import jax
    jnp = _jnp()

    inv_width_mont = FR.to_mont(pow(width, R_MODULUS - 2, R_MODULUS))

    def run(poly, roots, z):
        # poly/roots: (W, 33) Montgomery; z: (33,)
        a = FR.mul(poly, roots)                     # f_i * w_i
        b = FR.sub(jnp.broadcast_to(z, roots.shape), roots)  # z - w_i
        d = FR.inv(b)                                # all lanes at once
        terms = FR.mul(a, d)
        total = FR.tree_sum(terms, width)            # value < W * 2p

        z_pow = FR.pow_uint(z, width)
        factor = FR.sub(z_pow, jnp.asarray(FR.one_mont))
        total = FR.mul(total, factor)                # collapses magnitude
        total = FR.mul(total, jnp.asarray(inv_width_mont))
        return total

    return jax.jit(run)


@functools.lru_cache(maxsize=2)
def _roots_mont(roots_key):
    return FR.to_mont_batch(list(roots_key))


def barycentric_eval_async(poly_ints, roots_brp_ints, z_int):
    """Device evaluation of an evaluation-form polynomial at an
    out-of-domain z, deferred: returns a `serve.futures.DeviceFuture`
    settling to a canonical python int — the field element returns to
    the host (and leaves Montgomery form) only at `result()`, so a
    batch of blob evaluations pipelines instead of serializing on each
    element."""
    from ..serve.futures import value_future

    width = len(poly_ints)
    assert width == len(roots_brp_ints)
    jnp = _jnp()
    # cst: allow(recompile-unbucketed-dim): width is the KZG evaluation
    # domain size — fixed per preset (4096 mainnet / 4 minimal), so the
    # lru-cached kernel compiles once per process in practice
    kfn = _barycentric_kernel(width)
    with telemetry.span("fr.barycentric_eval", width=width):
        telemetry.count("fr.barycentric_eval.calls")
        poly = jnp.asarray(FR.to_mont_batch([int(v) for v in poly_ints]))
        roots = jnp.asarray(_roots_mont(tuple(int(r)
                                              for r in roots_brp_ints)))
        z = jnp.asarray(FR.to_mont(int(z_int)))
        out = kfn(poly, roots, z)
    # cost-capture seam (CST_COSTMODEL rounds), outside the span: the
    # AOT analysis pass must not contaminate the measured wall
    costmodel.capture(f"barycentric@{width}", kfn, (poly, roots, z))
    return value_future(out, convert=FR.from_mont)


def barycentric_eval(poly_ints, roots_brp_ints, z_int) -> int:
    """Synchronous facade over `barycentric_eval_async` (the host KZG
    library's call shape); the fetch lives in `serve.futures`."""
    return barycentric_eval_async(poly_ints, roots_brp_ints,
                                  z_int).result()
