"""Batched SHA-256 + Merkle reduction on TPU via JAX/XLA.

Same data layout as `ops.sha256_np` (chunks as (N, 8) big-endian uint32
words) so results are bit-identical across the host and device paths.

Compile-time design: the 64 compression rounds run as a `lax.fori_loop`
with a 16-word rolling message schedule, so the HLO for one Merkle level is
a small loop regardless of batch size, and a full tree reduction (one level
per tree depth) stays cheap to trace/compile even at validator-registry
depths (2**21+ leaves).  An `unroll=True` variant is kept for
runtime-critical fixed shapes (bench path) where XLA's cross-round fusion
buys throughput at the cost of compile time.

This is the TPU replacement for remerkleable's per-node Python hashing
(reference: `eth2spec/utils/ssz/ssz_impl.py:25` calling
`.get_backing().merkle_root()`).
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import telemetry
from ..resilience import faults
from ..telemetry import costmodel
from .sha256_np import _IV, _K, _PAD64, ZERO_HASH_WORDS
from .sha256_np import sha256_64B_words as _host_sha256_64B

# Device constants stay PLAIN NUMPY at module level (the `fq.py`
# convention): materializing jnp arrays at import time leaks tracers
# when the first import of this module happens inside an active jit
# trace — `h2c_jax._sha_blocks` imports us lazily from traced code, so
# an import-time `jnp.asarray` there would bind these names to that
# trace's tracers and crash every later host-side use (found live by a
# batch_verify-then-merkleize drive; the analyzer's
# device-const-at-import rule now pins this).  jnp closes over numpy
# constants at trace time instead.
_K_np = np.asarray(_K)
_IV_np = np.asarray(_IV)
_PAD_np = np.asarray(_PAD64)


def _rotr(x, n):
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _round(a, b, c, d, e, f, g, h, kt, wt):
    s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
    ch = (e & f) ^ (~e & g)
    t1 = h + s1 + ch + kt + wt
    s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
    maj = (a & b) ^ (a & c) ^ (b & c)
    t2 = s0 + maj
    return t1 + t2, a, b, c, d + t1, e, f, g


def _schedule_next(w):
    """Given rolling 16-word window w (..., 16), compute w[t+16] and roll."""
    s0 = _rotr(w[..., 1], 7) ^ _rotr(w[..., 1], 18) ^ (w[..., 1] >> jnp.uint32(3))
    s1 = _rotr(w[..., 14], 17) ^ _rotr(w[..., 14], 19) ^ (w[..., 14] >> jnp.uint32(10))
    nxt = w[..., 0] + s0 + w[..., 9] + s1
    return jnp.concatenate([w[..., 1:], nxt[..., None]], axis=-1)


def _compress_loop(state, block):
    """Compression as a lax.fori_loop over 64 rounds (small HLO)."""
    Kj = jnp.asarray(_K_np, dtype=jnp.uint32)   # t is traced: need jnp

    def body(t, carry):
        regs, w = carry
        regs = _round(*regs, Kj[t], w[..., 0])
        w = _schedule_next(w)
        return regs, w

    regs0 = tuple(state[..., i] for i in range(8))
    (regs, _) = lax.fori_loop(0, 64, body, (regs0, block))
    return state + jnp.stack(regs, axis=-1)


def _compress_unrolled(state, block):
    """Fully unrolled compression (max fusion; expensive to compile)."""
    w = [block[..., t] for t in range(16)]
    for t in range(16, 64):
        s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> jnp.uint32(3))
        s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> jnp.uint32(10))
        w.append(w[t - 16] + s0 + w[t - 7] + s1)
    regs = tuple(state[..., i] for i in range(8))
    for t in range(64):
        regs = _round(*regs, jnp.uint32(_K_np[t]), w[t])
    return state + jnp.stack(regs, axis=-1)


def _compress(state, block, unroll=False):
    return _compress_unrolled(state, block) if unroll else _compress_loop(state, block)


def sha256_64B_words(blocks, unroll=False):
    """SHA-256 of (..., 16)-word 64-byte messages -> (..., 8)-word digests."""
    state = jnp.broadcast_to(jnp.asarray(_IV_np, dtype=jnp.uint32),
                             blocks.shape[:-1] + (8,))
    state = _compress(state, blocks, unroll)
    state = _compress(state,
                      jnp.broadcast_to(jnp.asarray(_PAD_np,
                                                   dtype=jnp.uint32),
                                       blocks.shape[:-1] + (16,)),
                      unroll)
    return state


def hash_pairs(words, unroll=False):
    """One Merkle level: (2N, 8) chunk words -> (N, 8) parent words."""
    return sha256_64B_words(words.reshape(-1, 16), unroll)


@partial(jax.jit, static_argnames=("depth", "unroll"))
def merkle_root_pow2(words, depth: int, unroll: bool = False):
    """Root of a full 2**depth-leaf tree given as (2**depth, 8) uint32 words.

    One level per loop iteration; each level's compression is itself a small
    rounds-loop, so trace/compile cost grows only mildly with depth and the
    whole reduction is a single device dispatch.
    """
    assert words.shape[0] == 1 << depth
    level = words
    for _ in range(depth):
        level = hash_pairs(level, unroll)
    return level[0]


def _fold_zero_levels(root: np.ndarray, depth: int,
                      limit_depth: int) -> np.ndarray:
    """Host-side tail of a merkleization: fold precomputed zero-subtree
    hashes over a (8,) uint32 root up to `limit_depth`.  Runs at settle
    time on the fetched root."""
    for lvl in range(depth, limit_depth):
        blk = np.concatenate([root, ZERO_HASH_WORDS[lvl]]).astype(np.uint32)
        root = _host_sha256_64B(blk[None, :])[0]
    return root


def merkleize_words_jax_async(words: np.ndarray, limit_depth: int,
                              unroll: bool = False):
    """Device-side equivalent of sha256_np.merkleize_words, deferred.

    Pads the actual chunks to the next power of two on host (zero
    chunks), dispatches the device reduction, and returns a
    `serve.futures.DeviceFuture` settling to (8,) uint32 root words —
    the root crosses to the host (and the zero-subtree fold runs) only
    at `result()`, so callers can merkleize many subtrees back-to-back
    without serializing the dispatch pipeline."""
    from ..serve.futures import DeviceFuture, value_future

    n = words.shape[0]
    assert n <= (1 << limit_depth)
    if n == 0:
        return DeviceFuture.settled(
            np.array(ZERO_HASH_WORDS[limit_depth], copy=True))
    d = max(n - 1, 0).bit_length()
    # resilience fault seam (same contract as bls_batch._dispatch —
    # this module dispatches its own kernel, so it hooks its own key)
    if faults.active():
        faults.maybe_inject("dispatch", f"sha256_merkle@d{d}")
    padded = np.zeros((1 << d, 8), dtype=np.uint32)
    padded[:n] = words
    with telemetry.span("sha256.merkleize_words", depth=d):
        dev_words = jnp.asarray(padded)
        # cst: allow(recompile-unbucketed-dim): the static tree depth keys
        # the executable — log-bounded (<= limit_depth distinct compiles),
        # and each depth's program is a small rolled loop
        root = merkle_root_pow2(dev_words, d, unroll)
    # cost-capture seam (CST_COSTMODEL rounds): flop/byte budget of the
    # depth-d reduction, once per depth per process — outside the span
    # so the AOT analysis pass does not contaminate the measured wall
    costmodel.capture(f"sha256_merkle@d{d}", merkle_root_pow2,
                      (dev_words, d, unroll))
    if faults.active():
        root = faults.corrupt("dispatch", f"sha256_merkle@d{d}", root)
    return value_future(
        root, convert=lambda host: _fold_zero_levels(host, d, limit_depth))


def merkleize_words_jax(words: np.ndarray, limit_depth: int,
                        unroll: bool = False) -> np.ndarray:
    """Synchronous facade over `merkleize_words_jax_async` (the host
    API boundary of the device reduction); the root fetch lives in
    `serve.futures`."""
    return merkleize_words_jax_async(words, limit_depth, unroll).result()
