"""Live SLO watchdog — continuous rule evaluation over the serve fleet.

`metrics_export.py` makes the registry scrapeable; this module makes it
WATCHED.  A declarative rule set (the `CST_SLO_RULES` knob — JSON / file
path / compact spec string, the same source forms as `CST_FAULTS`) is
evaluated on a daemon tick against rolling windows of the live signals,
with breach→clear hysteresis, and every transition is a typed, counted
`SloBreach` event carrying the evidence the pod round needs: the
offending value, the margin past the threshold, the worst-N reqtrace
exemplars at the moment of breach, and (opt-in, `CST_PROFILE_ON_BREACH`,
at most once per rule per round) a bounded `jax.profiler` trace grab.

Signals (`SIGNALS`) the evaluator resolves per tick:

    serve.p50_ms / serve.p99_ms   rolling-window request latency, per
                                  kind (`{kind=...}`) or worst-kind
    serve.throughput_rps          completed requests/s over the rule's
                                  window (per kind or overall)
    serve.queue_depth             live executor queue depth
    serve.queue_age_s             age of the oldest queued request
    serve.inflight_batches        batches in flight
    breaker.flaps                 breaker state transitions inside the
                                  rule's window (flap-rate alarm)
    mem.slope_mb_s                per-device memory-watermark slope
                                  over the window, worst device (leak
                                  detection)
    counter.<name>                rate/s of any telemetry counter

Rule grammar (compact spec form; segments joined by `;`):

    serve.p99_ms{kind=verify}<500:for=2:clear=3
    serve.throughput_rps>=100:window_s=10
    mem.slope_mb_s<8:name=leak-watch

`op` ∈ {<, <=, >, >=} states the HEALTHY condition — a rule breaches
when the comparison FAILS for `for` consecutive ticks and clears after
`clear` consecutive healthy ticks (hysteresis is what keeps a noisy
signal from flapping the alarm).  JSON form:

    {"tick_s": 1.0, "rules": [{"metric": "serve.p99_ms",
      "kind": "verify", "op": "<", "threshold": 500,
      "for": 2, "clear": 3, "window_s": 10.0, "name": "p99-verify"}]}

Gating contract (the faults pattern): OFF until `install()`, `active()`
is one module-global read, `install_from_env()` rejects a malformed
`CST_SLO_RULES` with a counted warning instead of killing the round
(`load_rules()` raises, listing every problem, for programmatic use).
Stdlib-only; jax is only read out of `sys.modules` for the breach
profiler grab (a telemetry layer must not initialize a backend).
"""

from __future__ import annotations

import collections
import json
import os
import re
import sys
import threading
import time

from . import core, costmodel, flightrec, metrics_export, occupancy, \
    reqtrace

OPS = ("<", "<=", ">", ">=")
SIGNALS = ("serve.p50_ms", "serve.p99_ms", "serve.throughput_rps",
           "serve.queue_depth", "serve.queue_age_s",
           "serve.inflight_batches", "breaker.flaps", "mem.slope_mb_s",
           "serve.busy_frac")
# signals that accept a {kind=...} label
_KIND_SIGNALS = ("serve.p50_ms", "serve.p99_ms", "serve.throughput_rps")

_MAX_EVENTS = 2_000          # breach/clear event log cap; drops counted
_HIST_LEN = 512              # per-signal rolling-history samples
_PROFILE_GRAB_S = 2.0        # bounded breach profiler capture

_OP_FNS = {
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
}
# margin past the threshold, positive while breaching
_MARGINS = {
    "<": lambda v, t: v - t,
    "<=": lambda v, t: v - t,
    ">": lambda v, t: t - v,
    ">=": lambda v, t: t - v,
}


class SloBreach:
    """One SLO transition: a rule entering (`phase="breach"`) or
    leaving (`phase="clear"`) the breaching state.  Breaches carry the
    worst-N reqtrace exemplars captured at the transition tick."""

    __slots__ = ("ts", "phase", "rule", "metric", "kind", "op",
                 "threshold", "value", "margin", "exemplars")

    def __init__(self, ts, phase, rule, metric, kind, op, threshold,
                 value, margin, exemplars=None):
        self.ts = ts
        self.phase = phase
        self.rule = rule
        self.metric = metric
        self.kind = kind
        self.op = op
        self.threshold = threshold
        self.value = value
        self.margin = margin
        self.exemplars = exemplars

    def as_dict(self) -> dict:
        out = {"ts": round(self.ts, 6), "phase": self.phase,
               "rule": self.rule, "metric": self.metric, "op": self.op,
               "threshold": self.threshold,
               "value": round(self.value, 6),
               "margin": round(self.margin, 6)}
        if self.kind:
            out["kind"] = self.kind
        if self.exemplars:
            out["exemplars"] = self.exemplars
        return out


def validate_rules(obj) -> list[str]:
    """Schema check for an SLO rule-set object; returns a list of
    problems (empty == valid) — the contract `load_rules` enforces and
    tests/test_monitor.py pins."""
    problems: list[str] = []
    if not isinstance(obj, dict):
        return [f"slo rules are {type(obj).__name__}, not dict"]
    tick = obj.get("tick_s", 1.0)
    if not isinstance(tick, (int, float)) or isinstance(tick, bool) \
            or tick <= 0:
        problems.append(f"'tick_s' must be a positive number, "
                        f"got {tick!r}")
    rules = obj.get("rules")
    if not isinstance(rules, list) or not rules:
        return problems + ["'rules' must be a non-empty list"]
    names: set[str] = set()
    for i, r in enumerate(rules):
        where = f"rules[{i}]"
        if not isinstance(r, dict):
            problems.append(f"{where}: not a dict")
            continue
        metric = r.get("metric")
        if not (metric in SIGNALS
                or (isinstance(metric, str)
                    and metric.startswith("counter.")
                    and len(metric) > len("counter."))):
            problems.append(f"{where}: 'metric' must be one of "
                            f"{SIGNALS} or 'counter.<name>', got "
                            f"{metric!r}")
        kind = r.get("kind")
        if kind is not None:
            if not isinstance(kind, str) or not kind:
                problems.append(f"{where}: 'kind' must be a non-empty "
                                f"string, got {kind!r}")
            elif metric in SIGNALS and metric not in _KIND_SIGNALS:
                problems.append(f"{where}: metric {metric!r} does not "
                                f"take a kind label")
        if r.get("op") not in OPS:
            problems.append(f"{where}: 'op' must be one of {OPS}, got "
                            f"{r.get('op')!r}")
        thr = r.get("threshold")
        if not isinstance(thr, (int, float)) or isinstance(thr, bool):
            problems.append(f"{where}: 'threshold' must be a number, "
                            f"got {thr!r}")
        for field, lo in (("for", 1), ("clear", 1)):
            v = r.get(field, 1)
            if not isinstance(v, int) or isinstance(v, bool) or v < lo:
                problems.append(f"{where}: '{field}' must be an int "
                                f">= {lo}, got {v!r}")
        win = r.get("window_s", 10.0)
        if not isinstance(win, (int, float)) or isinstance(win, bool) \
                or win <= 0:
            problems.append(f"{where}: 'window_s' must be a positive "
                            f"number, got {win!r}")
        name = r.get("name")
        if name is not None and (not isinstance(name, str) or not name):
            problems.append(f"{where}: 'name' must be a non-empty "
                            f"string, got {name!r}")
        resolved = name or _default_name(metric, kind) \
            if isinstance(metric, str) else None
        if resolved:
            if resolved in names:
                problems.append(f"{where}: duplicate rule name "
                                f"{resolved!r}")
            names.add(resolved)
        unknown = set(r) - {"metric", "kind", "op", "threshold", "for",
                            "clear", "window_s", "name"}
        if unknown:
            problems.append(f"{where}: unknown field(s) "
                            f"{sorted(unknown)}")
    return problems


def _default_name(metric: str, kind) -> str:
    return f"{metric}@{kind}" if kind else metric


_SPEC_RE = re.compile(
    r"^(?P<metric>[a-z0-9_.]+)"
    r"(?:\{kind=(?P<kind>[a-z0-9_]+)\})?"
    r"\s*(?P<op><=|>=|<|>)\s*"
    r"(?P<thr>-?[0-9]+(?:\.[0-9]+)?)"
    r"(?P<opts>(?::[a-z_]+=[^:;]+)*)$")


def _parse_spec(text: str) -> dict:
    """Compact spec string -> rule-set dict (see module docstring)."""
    plan: dict = {"rules": []}
    for seg in text.split(";"):
        seg = seg.strip()
        if not seg:
            continue
        if seg.startswith("tick_s="):
            try:
                plan["tick_s"] = float(seg[len("tick_s="):])
            except ValueError:
                raise ValueError(f"slo spec: bad tick segment {seg!r}")
            continue
        m = _SPEC_RE.match(seg)
        if not m:
            raise ValueError(
                f"slo spec segment {seg!r} is not "
                f"metric[{{kind=k}}]<op>threshold[:opt=v...]")
        rule: dict = {"metric": m.group("metric"), "op": m.group("op"),
                      "threshold": float(m.group("thr"))}
        if m.group("kind"):
            rule["kind"] = m.group("kind")
        for opt in filter(None, (m.group("opts") or "").split(":")):
            k, _, v = opt.partition("=")
            if k in ("for", "clear"):
                try:
                    rule[k] = int(v)
                except ValueError:
                    raise ValueError(f"slo spec: {k}={v!r} not an int")
            elif k == "window_s":
                try:
                    rule[k] = float(v)
                except ValueError:
                    raise ValueError(f"slo spec: {k}={v!r} not a number")
            elif k == "name":
                rule[k] = v
            else:
                raise ValueError(f"slo spec: unknown option {k!r}")
        plan["rules"].append(rule)
    return plan


def load_rules(source) -> dict:
    """Build a validated rule-set dict from a dict, a JSON string, a
    JSON file path, or a compact spec string.  Raises ValueError (with
    every schema problem listed) — a pod round must not half-run a
    typo'd SLO set."""
    if isinstance(source, dict):
        obj = source
    elif isinstance(source, str):
        text = source.strip()
        if text.startswith("{"):
            obj = json.loads(text)
        elif os.path.exists(text):
            with open(text) as f:
                obj = json.load(f)
        else:
            obj = _parse_spec(text)
    else:
        raise ValueError(f"cannot load slo rules from "
                         f"{type(source).__name__}")
    problems = validate_rules(obj)
    if problems:
        raise ValueError("invalid slo rules: " + "; ".join(problems))
    return obj


class _RuleState:
    __slots__ = ("name", "metric", "kind", "op", "threshold",
                 "for_ticks", "clear_ticks", "window_s", "breaching",
                 "bad_streak", "ok_streak", "breaches", "clears",
                 "worst_margin", "last_value", "ticks", "profiled",
                 "dumped")

    def __init__(self, r: dict):
        self.metric = r["metric"]
        self.kind = r.get("kind")
        self.name = r.get("name") or _default_name(self.metric,
                                                   self.kind)
        self.op = r["op"]
        self.threshold = float(r["threshold"])
        self.for_ticks = int(r.get("for", 1))
        self.clear_ticks = int(r.get("clear", 1))
        self.window_s = float(r.get("window_s", 10.0))
        self.breaching = False
        self.bad_streak = 0
        self.ok_streak = 0
        self.breaches = 0
        self.clears = 0
        self.worst_margin = None
        self.last_value = None
        self.ticks = 0
        self.profiled = False
        self.dumped = False

    def describe(self) -> dict:
        out = {"name": self.name, "metric": self.metric, "op": self.op,
               "threshold": self.threshold, "for": self.for_ticks,
               "clear": self.clear_ticks, "window_s": self.window_s}
        if self.kind:
            out["kind"] = self.kind
        return out


class Watchdog:
    """The rule evaluator.  `tick()` is the whole engine — the daemon
    thread just calls it on an interval, and tests drive it directly
    with a fake clock (`clock=` plus explicit `tick(now=...)`).  The
    signal providers are injectable for the same reason; defaults read
    the live registry."""

    def __init__(self, rules, tick_s: float | None = None,
                 clock=time.monotonic, status_provider=None,
                 summary_provider=None, counter_provider=None,
                 watermark_provider=None, profile_dir: str | None = None,
                 window: int = 2048):
        obj = load_rules(rules)
        self.rules = [_RuleState(r) for r in obj["rules"]]
        self.tick_s = float(tick_s if tick_s is not None
                            else obj.get("tick_s", 1.0))
        self._clock = clock
        self._status = status_provider or metrics_export.get_status
        self._summary = summary_provider or reqtrace.rolling_summary
        self._counters = counter_provider or core.counter_value
        self._watermarks = watermark_provider or costmodel.watermark_bytes
        self._profile_dir = profile_dir
        self._window = int(window)
        self._lock = threading.Lock()
        self._events: list[SloBreach] = []
        self._events_dropped = 0
        self._ticks = 0
        self._profiles: list[str] = []
        self._profile_until: float | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # rolling histories for the rate/slope/flap signals
        self._tp_hist: collections.deque = collections.deque(
            maxlen=_HIST_LEN)           # (ts, total, by_kind)
        self._ctr_hist: dict[str, collections.deque] = {
            r.metric[len("counter."):]: collections.deque(maxlen=_HIST_LEN)
            for r in self.rules if r.metric.startswith("counter.")}
        self._breaker_prev: dict | None = None
        self._flap_hist: collections.deque = collections.deque(
            maxlen=_HIST_LEN)           # (ts, transitions)
        self._wm_hist: dict[str, collections.deque] = {}
        self._incidents: list[str] = []
        self._occ_prev: float | None = None

    # --- the tick ------------------------------------------------------------

    def tick(self, now: float | None = None) -> list[SloBreach]:
        """Evaluate every rule once; returns the transitions this tick
        emitted (breaches and clears)."""
        now = self._clock() if now is None else now
        self._maybe_stop_profile(now)
        frame = self._frame(now)
        emitted: list[SloBreach] = []
        for st in self.rules:
            value = self._signal(st, frame, now)
            st.ticks += 1
            if value is None:
                continue        # no observation: streaks hold
            st.last_value = float(value)
            healthy = _OP_FNS[st.op](value, st.threshold)
            margin = _MARGINS[st.op](value, st.threshold)
            if not healthy:
                st.bad_streak += 1
                st.ok_streak = 0
                if st.worst_margin is None or margin > st.worst_margin:
                    st.worst_margin = margin
                if not st.breaching and st.bad_streak >= st.for_ticks:
                    st.breaching = True
                    st.breaches += 1
                    ev = self._emit(now, "breach", st, value, margin,
                                    exemplars=self._exemplars())
                    emitted.append(ev)
                    self._maybe_profile(st, now)
                    self._maybe_flightrec(st)
            else:
                st.ok_streak += 1
                st.bad_streak = 0
                if st.breaching and st.ok_streak >= st.clear_ticks:
                    st.breaching = False
                    st.clears += 1
                    emitted.append(self._emit(now, "clear", st, value,
                                              margin))
        with self._lock:
            self._ticks += 1
        core.count("slo.ticks")
        return emitted

    def _emit(self, now, phase, st, value, margin,
              exemplars=None) -> SloBreach:
        ev = SloBreach(now, phase, st.name, st.metric, st.kind, st.op,
                       st.threshold, float(value), float(margin),
                       exemplars)
        with self._lock:
            if len(self._events) < _MAX_EVENTS:
                self._events.append(ev)
            else:
                self._events_dropped += 1
        core.count("slo.breaches" if phase == "breach" else "slo.clears")
        core.count(f"slo.{phase}.{st.name}")
        flightrec.record(f"slo_{phase}", rule=st.name, metric=st.metric,
                         value=round(float(value), 6),
                         threshold=st.threshold,
                         margin=round(float(margin), 6))
        return ev

    def _exemplars(self, n: int = 5) -> list[dict]:
        try:
            return reqtrace.attribution(worst_n=n)["worst"]
        except Exception:
            return []

    # --- signal resolution ---------------------------------------------------

    def _frame(self, now: float) -> dict:
        """One tick's shared signal reads (each live surface is read at
        most once per tick, whatever the rule count)."""
        frame: dict = {"summary": None, "status": None}
        if any(r.metric in _KIND_SIGNALS for r in self.rules):
            try:
                frame["summary"] = self._summary(self._window)
            except TypeError:
                frame["summary"] = self._summary()
            except Exception:
                frame["summary"] = None
        if any(r.metric.startswith(("serve.queue", "serve.inflight",
                                    "breaker.")) for r in self.rules):
            frame["status"] = self._status()
        # throughput history
        if any(r.metric == "serve.throughput_rps" for r in self.rules):
            total, by_kind, _ = reqtrace.completed_totals()
            self._tp_hist.append((now, total, dict(by_kind)))
        for cname, hist in self._ctr_hist.items():
            hist.append((now, self._counters(cname)))
        if any(r.metric == "breaker.flaps" for r in self.rules):
            self._note_flaps(frame.get("status"), now)
        if any(r.metric == "mem.slope_mb_s" for r in self.rules):
            try:
                for dev, last in (self._watermarks() or {}).items():
                    self._wm_hist.setdefault(
                        dev, collections.deque(maxlen=_HIST_LEN)
                    ).append((now, last))
            except Exception:
                pass
        return frame

    def _note_flaps(self, status, now: float) -> None:
        breakers = (status or {}).get("breakers") or {}
        states = {k: (b.get("state") if isinstance(b, dict) else b)
                  for k, b in breakers.items()}
        flips = 0
        if self._breaker_prev is not None:
            for k, s in states.items():
                if self._breaker_prev.get(k, s) != s:
                    flips += 1
        self._breaker_prev = states
        self._flap_hist.append((now, flips))

    def _signal(self, st: _RuleState, frame: dict, now: float):
        m = st.metric
        if m in ("serve.p50_ms", "serve.p99_ms"):
            summary = frame.get("summary") or {}
            key = "p50_ms" if m == "serve.p50_ms" else "p99_ms"
            if st.kind:
                s = summary.get(st.kind)
                return s[key] if s else None
            vals = [s[key] for s in summary.values()]
            return max(vals) if vals else None
        if m == "serve.throughput_rps":
            return self._rate(self._tp_hist, st, now,
                              lambda e: (e[2].get(st.kind, 0)
                                         if st.kind else e[1]))
        if m.startswith("counter."):
            hist = self._ctr_hist.get(m[len("counter."):])
            return self._rate(hist, st, now, lambda e: e[1])
        if m == "serve.queue_depth":
            status = frame.get("status")
            return None if status is None \
                else status.get("queue", {}).get("depth", 0)
        if m == "serve.queue_age_s":
            status = frame.get("status")
            if status is None:
                return None
            return status.get("queue", {}).get("oldest_age_s") or 0.0
        if m == "serve.inflight_batches":
            status = frame.get("status")
            return None if status is None \
                else status.get("inflight", {}).get("batches", 0)
        if m == "breaker.flaps":
            cut = now - st.window_s
            return float(sum(n for ts, n in self._flap_hist if ts > cut))
        if m == "serve.busy_frac":
            value = occupancy.live_busy_frac(st.window_s)
            # occupancy-collapse edge: a pipeline that WAS keeping the
            # device busy falling off a cliff is flight-recorder news
            # even before the rule's `for=` streak confirms the breach
            if value is not None:
                prev, self._occ_prev = self._occ_prev, value
                if prev is not None and prev >= 0.2 and value < 0.05:
                    flightrec.record("occupancy_collapse",
                                     prev=round(prev, 6),
                                     value=round(value, 6),
                                     rule=st.name)
            return value
        if m == "mem.slope_mb_s":
            slopes = []
            for hist in self._wm_hist.values():
                base = None
                for ts, b in hist:
                    if ts >= now - st.window_s:
                        base = (ts, b)
                        break
                if base is None or not hist:
                    continue
                t1, b1 = hist[-1]
                if t1 - base[0] <= 0:
                    continue
                slopes.append((b1 - base[1]) / (t1 - base[0]) / 1e6)
            return max(slopes) if slopes else None
        return None

    @staticmethod
    def _rate(hist, st: _RuleState, now: float, get):
        """Rate/s of a monotone total over the rule's window: current
        sample vs the oldest sample inside the window.  None until two
        samples exist (a rate needs a baseline)."""
        if not hist or len(hist) < 2:
            return None
        base = None
        for entry in hist:
            if entry[0] >= now - st.window_s:
                base = entry
                break
        if base is None or base is hist[-1]:
            base = hist[-2]
        dt = hist[-1][0] - base[0]
        if dt <= 0:
            return None
        return (get(hist[-1]) - get(base)) / dt

    # --- breach profiler grab ------------------------------------------------

    def _maybe_profile(self, st: _RuleState, now: float) -> None:
        if not self._profile_dir or st.profiled \
                or self._profile_until is not None:
            return
        jax = sys.modules.get("jax")
        if jax is None:
            return
        path = os.path.join(self._profile_dir, st.name)
        try:
            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
        except Exception:
            core.count("slo.profile_failed")
            return
        st.profiled = True
        self._profile_until = now + _PROFILE_GRAB_S
        self._profiles.append(path)
        core.count("slo.profiles")

    def _maybe_flightrec(self, st: _RuleState) -> None:
        """Breach-triggered incident dump (CST_FLIGHTREC_ON_BREACH) —
        once per rule per watchdog install, the same gating discipline
        as the CST_PROFILE_ON_BREACH grab: the first breach is the
        incident, repeats are the same incident still happening."""
        if st.dumped or not flightrec.dump_on_breach():
            return
        st.dumped = True
        try:
            path = flightrec.dump_bundle(reason=f"slo-{st.name}",
                                         rule=st.name)
        except Exception:
            core.count("slo.incident_dump_failed")
            return
        self._incidents.append(path)
        core.count("slo.incident_bundles")

    def _maybe_stop_profile(self, now: float) -> None:
        if self._profile_until is None or now < self._profile_until:
            return
        self._profile_until = None
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                jax.profiler.stop_trace()
            except Exception:
                core.count("slo.profile_failed")

    # --- daemon loop ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        t = threading.Thread(target=self._loop, name="cst-slo-watchdog",
                             daemon=True)
        self._thread = t
        t.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:
                core.count("slo.tick_error")

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        # never leave a profiler trace open past the round
        self._maybe_stop_profile(float("inf"))

    # --- read surfaces -------------------------------------------------------

    def events(self) -> list[dict]:
        with self._lock:
            return [e.as_dict() for e in self._events]

    def breaching(self) -> list[str]:
        """Names of the rules currently in breach."""
        return [st.name for st in self.rules if st.breaching]

    def slo_block(self) -> dict:
        """The round-summary sub-object (rides the serve/resilience
        bench block; mined into `slo::*` history records)."""
        with self._lock:
            events = [e.as_dict() for e in self._events]
            dropped = self._events_dropped
            ticks = self._ticks
        rules = []
        for st in self.rules:
            row = st.describe()
            row.update({"ticks": st.ticks, "breaches": st.breaches,
                        "clears": st.clears, "breaching": st.breaching})
            if st.worst_margin is not None:
                row["worst_margin"] = round(st.worst_margin, 6)
            if st.last_value is not None:
                row["last_value"] = round(st.last_value, 6)
            rules.append(row)
        # bound the block: only the LAST 5 breaches keep their exemplar
        # payloads (the freshest evidence), older events keep the
        # transition facts only
        breach_idx = [i for i, e in enumerate(events)
                      if e["phase"] == "breach"]
        keep = set(breach_idx[-5:])
        bounded = []
        for i, e in enumerate(events):
            if "exemplars" in e and i not in keep:
                e = {k: v for k, v in e.items() if k != "exemplars"}
            bounded.append(e)
        total = sum(st.breaches for st in self.rules)
        return {"ticks": ticks, "breaches": total,
                "clean": total == 0,
                "breaching_now": self.breaching(),
                "rules": rules,
                "events": bounded,
                "events_dropped": dropped,
                "profiles": list(self._profiles),
                "incidents": list(self._incidents)}

    def exposition_rows(self):
        """Metric families for the exposition endpoint:
        (name, type, help, [(labels, value), ...])."""
        labels = [({"rule": st.name}, st) for st in self.rules]
        return [
            ("cst_slo_breaches_total", "counter",
             "SLO breach transitions per rule",
             [(lb, st.breaches) for lb, st in labels]),
            ("cst_slo_breaching", "gauge",
             "1 while the rule is in breach",
             [(lb, 1 if st.breaching else 0) for lb, st in labels]),
            ("cst_slo_last_value", "gauge",
             "last evaluated signal value per rule",
             [(lb, st.last_value) for lb, st in labels
              if st.last_value is not None]),
            ("cst_slo_ticks_total", "counter",
             "watchdog evaluation ticks", [({}, self._ticks)]),
        ]


# --- the gate (the faults `active()` pattern) --------------------------------

_watchdog: Watchdog | None = None


def active() -> bool:
    """True while a watchdog is installed — one module-global read."""
    return _watchdog is not None


def current() -> Watchdog | None:
    return _watchdog


def install(rules, *, autostart: bool = True, **kwargs) -> Watchdog:
    """Build, install and (by default) start a watchdog over `rules`
    (any `load_rules` source form).  Replaces a previous watchdog
    (stopping its thread)."""
    global _watchdog
    if _watchdog is not None:
        _watchdog.stop()
    wd = Watchdog(rules, **kwargs)
    _watchdog = wd
    if autostart:
        wd.start()
    return wd


def clear() -> dict | None:
    """Stop and uninstall the watchdog; returns its final `slo_block()`
    (the round-summary evidence), or None when none was installed."""
    global _watchdog
    wd, _watchdog = _watchdog, None
    if wd is None:
        return None
    wd.stop()
    return wd.slo_block()


def profile_dir_from_env() -> str | None:
    """The `CST_PROFILE_ON_BREACH` capture directory: unset/"0" = off,
    "1" = the default `out/slo_profiles`, anything else is the path."""
    raw = os.environ.get("CST_PROFILE_ON_BREACH", "")
    if raw in ("", "0"):
        return None
    return "out/slo_profiles" if raw == "1" else raw


def install_from_env(status_provider=None,
                     autostart: bool = True) -> Watchdog | None:
    """Install the `CST_SLO_RULES` watchdog when the knob is set.  A
    malformed rule set is rejected with a counted warning
    (`slo.rules_invalid`) instead of an exception — a typo'd knob must
    not kill a serve round.  Also starts the `CST_METRICS_PORT`
    exposition endpoint (the two arm together on the pod checklist).
    Call sites: loadgen / bench_serve / the chaos harness — never at
    import."""
    metrics_export.start_from_env()
    if status_provider is not None:
        metrics_export.set_status_provider(status_provider)
    source = os.environ.get("CST_SLO_RULES")
    if not source:
        return _watchdog
    try:
        rules = load_rules(source)
    except (ValueError, json.JSONDecodeError) as exc:
        core.count("slo.rules_invalid")
        print(f"slo: ignoring invalid CST_SLO_RULES: {exc}",
              file=sys.stderr)
        return None
    return install(rules, autostart=autostart,
                   profile_dir=profile_dir_from_env())


def _reset_state() -> None:
    """Full test-isolation reset (telemetry.reset(full=True) hook)."""
    global _watchdog
    wd, _watchdog = _watchdog, None
    if wd is not None:
        wd.stop()
