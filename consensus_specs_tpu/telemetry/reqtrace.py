"""Request-scoped tracing — per-request lifecycle + tail-latency
attribution across the serve pipeline.

The serve path carries nine request kinds through queue → batch fold →
device dispatch → settle → retry/breaker/oracle-fallback, and the
production claim is gated on per-request tail latency (`serve-p99`).
Kernel/batch telemetry (PRs 2/5) cannot say WHERE a p99 miss lives:
queue wait, batch formation, device wall, settle, or a resilience
detour.  This module closes that gap — the request→batch lineage
problem every batched-inference server solves:

- `RequestContext`: minted at every `ServeExecutor.submit_*` (analyzer
  rule `reqtrace-uncovered-submit` makes that a lint invariant),
  carried on the request AND its `DeviceFuture` handle, stamped at
  every pipeline phase transition.  Timestamps: submit / enqueue /
  dispatch (first) / complete; cumulative per-component wall in
  `components` — the phases are CONTIGUOUS (each stamp closes the
  interval since the previous one), so the components sum to the
  end-to-end latency exactly:

      queue_wait   submit → first dispatch attempt
      batch_form   dispatch entry → batch in flight (host prep:
                   point→limb conversion, RLC draws, transfers)
      device_wall  in flight → device answer fetched
      settle       answer → handle settled (verdict split, mask split)
      detour       everything the resilience ladder adds: failed
                   attempts, retry backoff, per-statement recheck,
                   oracle-fallback compute

- outcome ∈ {ok, recheck, retry, fallback, shed, poisoned, timeout}:
  the request's final disposition.  `timeout` is PROVISIONAL and
  handle-level only: a bounded wait that ran out leaves the handle
  pending (read it via `fut.ctx.outcome`), and the eventual settle
  overwrites it — so completed-record aggregates (`records()`,
  `attribution()` outcome counts, `raw_snapshot()`) never contain it;
  the vocabulary keeps the value so schemas stay stable if an
  abandoned-handle publisher ever lands.
- batch spans: every device dispatch gets a batch id linking its member
  trace ids (N queued → 1 dispatch → N contexts share the id) — the
  lineage the Chrome-trace flow events render as arrows.
- `attribution()`: per-kind p50/p90/p99 decomposed into the five
  components, worst-N exemplar traces retained — the serve block's
  `latency_attribution` sub-object, mined into `latency::*` history
  records and rendered as the report's "Tail latency" section.
- `chrome_events()`: request lifecycle 'X' spans + 's'/'t'/'f' flow
  events (submit → batch → settle arrows) + batch 'X' spans, appended
  to the existing Perfetto export by `telemetry.export.chrome_trace`.
- `rolling_summary()`: per-kind rolling p50/p99 + mean components over
  the freshest records — the live `ServeExecutor.status()` surface.

Gating contract (the telemetry pattern): OFF unless `CST_TRACE_REQUESTS`
is set non-"0" (or `configure(enabled=True)`), `mint()` while disabled
is ONE module-global read returning None — the no-op bound is pinned by
tests/test_reqtrace.py.  Registry capped at `_MAX_RECORDS` completed
records / `_MAX_BATCHES` batch spans; drops are counted, never silent.

Stdlib-only; never imports jax or numpy — safe from anywhere, including
before backend pinning (same discipline as the rest of `telemetry/`).
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time

COMPONENTS = ("queue_wait", "batch_form", "device_wall", "settle",
              "detour")
OUTCOMES = ("ok", "recheck", "retry", "fallback", "shed", "poisoned",
            "timeout")

# bounded registries: ~200 bytes/record keeps the worst case ~20 MB on
# a sustained round; drops are counted, never silent
_MAX_RECORDS = 100_000
_MAX_BATCHES = 50_000

# the live-summary ring: `rolling_summary()` (the ServeExecutor.status()
# dump and the SLO watchdog tick) reads ONLY this fixed-size window of
# the freshest completions, so its cost is O(window) however large the
# full registry grows — and it keeps rolling after the registry cap
# stops admitting records
_WINDOW_CAP = 4096

_lock = threading.Lock()


def _env_enabled() -> bool:
    return os.environ.get("CST_TRACE_REQUESTS", "0") not in ("", "0")


_enabled = _env_enabled()
# id counters are itertools.count — next() is atomic under the GIL, so
# the enabled per-request path takes NO lock (the registry lock guards
# only copies/resets; list.append is likewise atomic)
_trace_seq = itertools.count(1)
_batch_seq = itertools.count(1)
# the record registry stores completed RequestContext OBJECTS; the
# dict view materializes at read time (`records()`), keeping the
# per-request completion cost to an append
_records: list = []
_records_dropped = 0
_batches: list[dict] = []
_batches_dropped = 0
# the rolling live window + monotone completion totals (never reset by
# the registry cap; the watchdog's throughput signal is a delta of
# these).  deque.append with maxlen is atomic under the GIL, so the
# per-completion path stays lock-free like `_publish`.
_window: collections.deque = collections.deque(maxlen=_WINDOW_CAP)
_completed_total = 0
_completed_by_kind: dict[str, int] = {}
_completed_by_outcome: dict[str, int] = {}


def enabled() -> bool:
    """True when request contexts are being minted (CST_TRACE_REQUESTS
    or an explicit `configure(enabled=True)`)."""
    return _enabled


def configure(enabled: bool | None = None) -> None:
    """Programmatic override of the env gate (benches, chaos rounds,
    tests)."""
    global _enabled
    if enabled is not None:
        _enabled = enabled


def reset() -> None:
    """Clear completed records and batch spans (id counters keep
    monotone so records from before/after a reset can never collide).
    How the loadgen scopes a measured run's records to itself."""
    global _records_dropped, _batches_dropped, _completed_total
    with _lock:
        _records.clear()
        _batches.clear()
        _records_dropped = 0
        _batches_dropped = 0
        _window.clear()
        _completed_total = 0
        _completed_by_kind.clear()
        _completed_by_outcome.clear()


def _reset_state() -> None:
    """Full test-isolation reset (telemetry.reset(full=True) hook):
    records AND the id counters."""
    global _trace_seq, _batch_seq
    reset()
    with _lock:
        _trace_seq = itertools.count(1)
        _batch_seq = itertools.count(1)


def _publish(ctx: "RequestContext") -> None:
    # lock-free: append is atomic, and the cap check racing a
    # concurrent append can overshoot by at most a few records — the
    # bound is a memory guard, not an exact count
    global _records_dropped, _completed_total
    if len(_records) < _MAX_RECORDS:
        _records.append(ctx)
    else:
        _records_dropped += 1
    # the live window and the monotone totals admit EVERY completion
    # (capped registry or not) — the rolling summary and the watchdog's
    # throughput delta must track the service, not the memory guard
    _window.append(ctx)
    _completed_total += 1
    _completed_by_kind[ctx.kind] = _completed_by_kind.get(ctx.kind, 0) + 1
    if ctx.outcome is not None:
        _completed_by_outcome[ctx.outcome] = \
            _completed_by_outcome.get(ctx.outcome, 0) + 1


class RequestContext:
    """One request's lifecycle through the serve pipeline.  Created via
    `mint()`; the serve executor drives the `mark_*`/`note_*`/`complete`
    transitions (see the module docstring for the phase → component
    mapping).  All timestamps are `time.perf_counter()` values."""

    # the five component accumulators live as PLAIN FLOAT SLOTS (not a
    # dict) — the enabled path runs per request on the serve hot loop,
    # and slot adds keep the per-event cost to a perf_counter() call
    # plus an attribute write.  `components` materializes the dict view.
    __slots__ = ("trace_id", "kind", "batch_id", "outcome", "attempts",
                 "faulted", "rechecked", "t_submit", "t_enqueue",
                 "t_dispatch", "t_complete", "_mark", "done") \
        + COMPONENTS

    def __init__(self, trace_id: int, kind: str):
        now = time.perf_counter()
        self.trace_id = trace_id
        self.kind = kind
        self.batch_id = None
        self.outcome = None
        self.attempts = 0
        self.faulted = False
        self.rechecked = False
        self.t_submit = now
        self.t_enqueue = now
        self.t_dispatch = None
        self.t_complete = None
        self.queue_wait = 0.0
        self.batch_form = 0.0
        self.device_wall = 0.0
        self.settle = 0.0
        self.detour = 0.0
        self._mark = now
        self.done = False

    @property
    def components(self) -> dict:
        return {c: getattr(self, c) for c in COMPONENTS}

    # --- phase accounting ----------------------------------------------------

    def _advance(self, component: str) -> float:
        """Close the interval since the previous stamp into `component`;
        contiguity is what makes the components sum to end-to-end."""
        now = time.perf_counter()
        setattr(self, component, getattr(self, component)
                + (now - self._mark))
        self._mark = now
        return now

    def mark_enqueue(self) -> None:
        """Queued on the executor (the submit→enqueue sliver lands in
        queue_wait at the next stamp)."""
        self.t_enqueue = time.perf_counter()

    def mark_dispatch(self, batch_id) -> None:
        """A dispatch attempt begins.  First attempt closes queue_wait;
        re-dispatches (retry ladder) close the failure+backoff interval
        into detour."""
        now = self._advance("queue_wait" if self.attempts == 0
                            else "detour")
        self.attempts += 1
        self.batch_id = batch_id
        if self.t_dispatch is None:
            self.t_dispatch = now

    def mark_inflight(self) -> None:
        """Host prep done, batch handed to the device (first attempt →
        batch_form; a retry's re-prep is detour)."""
        self._advance("batch_form" if self.attempts <= 1 else "detour")

    def mark_device_done(self) -> None:
        """The batch's device answer arrived (the successful attempt's
        in-flight wait + blocking fetch is device_wall)."""
        self._advance("device_wall")

    def mark_attempt_failed(self, faulted: bool = False) -> None:
        """This attempt raised (host prep or device settle); the failed
        wait is a detour.  `faulted` marks an injected-fault victim —
        the chaos harness's blast-radius correlation."""
        self._advance("detour")
        if faulted:
            self.faulted = True

    def mark_fallback_begin(self) -> None:
        """Entering the oracle-fallback path: close the preceding phase
        (queue if the breaker short-circuited dispatch, detour after a
        failure)."""
        self._advance("queue_wait" if self.attempts == 0 else "detour")

    def note_recheck(self) -> None:
        """The batch verdict was False and per-statement rechecks ran;
        the recheck wall is a detour and the outcome label upgrades."""
        self._advance("detour")
        self.rechecked = True

    def note_timeout(self) -> None:
        """A bounded wait on this handle ran out.  Provisional — the
        handle is still pending and a later settle overwrites it."""
        if not self.done:
            self.outcome = "timeout"

    # --- completion ----------------------------------------------------------

    def complete(self, outcome: str | None = None,
                 final_component: str = "settle") -> None:
        """Settle the context: close the last interval into
        `final_component`, resolve the outcome label (None = auto:
        recheck > retry > ok), publish the lifecycle record."""
        if self.done:
            return
        self.t_complete = self._advance(final_component)
        if outcome is None:
            outcome = ("recheck" if self.rechecked
                       else "retry" if self.attempts > 1 else "ok")
        self.outcome = outcome
        self.done = True
        _publish(self)

    def end_to_end_s(self) -> float | None:
        if self.t_complete is None:
            return None
        return self.t_complete - self.t_submit

    def record(self) -> dict:
        """The compact lifecycle record (what the registry keeps)."""
        rec = {
            "trace_id": self.trace_id,
            "kind": self.kind,
            "outcome": self.outcome,
            "batch": self.batch_id,
            "attempts": self.attempts,
            "t_submit": self.t_submit,
            "t_enqueue": self.t_enqueue,
            "t_dispatch": self.t_dispatch,
            "t_complete": self.t_complete,
            "e2e_s": self.end_to_end_s(),
            "components": self.components,
        }
        if self.faulted:
            rec["faulted"] = True
        return rec


def mint(kind: str) -> RequestContext | None:
    """A fresh context, or None while tracing is off (the executor's
    stamp sites all guard on None — disabled cost is this one global
    read)."""
    if not _enabled:
        return None
    return RequestContext(next(_trace_seq), kind)


def new_batch_id() -> int:
    return next(_batch_seq)


def note_batch(batch_id: int, kind: str, trace_ids: list[int],
               attempt: int, requests: int) -> None:
    """Record one dispatched batch's span + member lineage (lock-free,
    like `_publish` — the cap is a memory guard)."""
    global _batches_dropped
    rec = {"batch_id": batch_id, "kind": kind, "attempt": attempt,
           "requests": requests, "trace_ids": list(trace_ids),
           "t_dispatch": time.perf_counter()}
    if len(_batches) < _MAX_BATCHES:
        _batches.append(rec)
    else:
        _batches_dropped += 1


def records() -> list[dict]:
    """The completed lifecycle records as dicts, materialized at read
    time (does not clear — use `reset()` to scope a run)."""
    with _lock:
        done = list(_records)
    return [c.record() for c in done]


def batches() -> list[dict]:
    with _lock:
        return [dict(b) for b in _batches]


def dropped() -> tuple[int, int]:
    with _lock:
        return _records_dropped, _batches_dropped


# --- tail-latency attribution ------------------------------------------------


ANSWERED = frozenset({"ok", "recheck", "retry", "fallback"})


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted sample (the loadgen
    convention)."""
    idx = min(len(sorted_vals) - 1,
              int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _component_means(recs: list[dict]) -> dict:
    out = dict.fromkeys(COMPONENTS, 0.0)
    for r in recs:
        for c in COMPONENTS:
            out[c] += r["components"].get(c, 0.0)
    n = len(recs) or 1
    return {c: round(v / n * 1e3, 3) for c, v in out.items()}


def _tail(recs: list[dict], q: float = 0.99) -> list[dict]:
    """The slowest ceil((1-q) * n) records — the exemplar set the p99
    decomposition averages over (at least one record)."""
    ordered = sorted(recs, key=lambda r: r["e2e_s"], reverse=True)
    n = max(1, len(ordered) - int(round(q * (len(ordered) - 1))))
    return ordered[:n]


def _exemplar(rec: dict) -> dict:
    return {
        "trace_id": rec["trace_id"],
        "kind": rec["kind"],
        "outcome": rec["outcome"],
        "batch": rec["batch"],
        "attempts": rec["attempts"],
        "e2e_ms": round(rec["e2e_s"] * 1e3, 3),
        "components_ms": {c: round(rec["components"].get(c, 0.0) * 1e3, 3)
                          for c in COMPONENTS},
    }


def attribution(trace_records: list[dict] | None = None,
                worst_n: int = 5) -> dict:
    """The tail-latency attribution block (the serve block's
    `latency_attribution` sub-object): per-kind p50/p90/p99 with mean
    and p99-tail component decompositions, outcome counts, the overall
    p99 queue-wait fraction, and the worst-N exemplar traces.

    Only ANSWERED requests (ok/recheck/retry/fallback) enter the
    percentile base — shed and poisoned requests failed, and a deadline
    shed's latency measures the deadline, not the service."""
    recs = trace_records if trace_records is not None else records()
    done = [r for r in recs if r.get("e2e_s") is not None]
    answered = [r for r in done if r.get("outcome") in ANSWERED]
    by_kind: dict[str, list[dict]] = {}
    for r in answered:
        by_kind.setdefault(r["kind"], []).append(r)

    kinds = {}
    for kind, krecs in sorted(by_kind.items()):
        e2e = sorted(r["e2e_s"] for r in krecs)
        tail = _tail(krecs)
        outcomes: dict[str, int] = {}
        for r in krecs:
            outcomes[r["outcome"]] = outcomes.get(r["outcome"], 0) + 1
        tail_e2e = sum(r["e2e_s"] for r in tail) or 1e-12
        tail_queue = sum(r["components"].get("queue_wait", 0.0)
                         for r in tail)
        kinds[kind] = {
            "count": len(krecs),
            "p50_ms": round(_percentile(e2e, 0.50) * 1e3, 3),
            "p90_ms": round(_percentile(e2e, 0.90) * 1e3, 3),
            "p99_ms": round(_percentile(e2e, 0.99) * 1e3, 3),
            "mean_components_ms": _component_means(krecs),
            "p99_components_ms": _component_means(tail),
            "p99_queue_frac": round(tail_queue / tail_e2e, 4),
            "outcomes": outcomes,
        }

    worst = [_exemplar(r) for r in sorted(
        answered, key=lambda r: r["e2e_s"], reverse=True)[:worst_n]]
    overall_frac = None
    if answered:
        tail = _tail(answered)
        tail_e2e = sum(r["e2e_s"] for r in tail) or 1e-12
        overall_frac = round(sum(r["components"].get("queue_wait", 0.0)
                                 for r in tail) / tail_e2e, 4)
    return {
        "kinds": kinds,
        "requests": len(done),
        "answered": len(answered),
        "p99_queue_frac": overall_frac,
        "worst": worst,
        "records_dropped": dropped()[0],
    }


def completed_totals() -> tuple[int, dict, dict]:
    """(total, by_kind, by_outcome) completion counts: monotone past
    the registry cap (every completion counts, admitted or dropped),
    zeroed by `reset()` so a measured run owns its counts.  The
    exposition endpoint's lifetime series and the watchdog's
    throughput-delta baseline."""
    with _lock:
        return (_completed_total, dict(_completed_by_kind),
                dict(_completed_by_outcome))


def rolling_summary(window: int = 2048) -> dict:
    """Per-kind rolling p50/p99 + mean components over the freshest
    `window` completed records — the live `ServeExecutor.status()`
    surface and the SLO watchdog's latency signal.  Reads the fixed
    `_WINDOW_CAP` ring, never the full registry: O(window) per call
    under sustained load (bound pinned by tests/test_monitor.py)."""
    with _lock:
        tail_ctxs = list(_window)
    if window < len(tail_ctxs):
        tail_ctxs = tail_ctxs[-window:]
    tail = [c.record() for c in tail_ctxs]
    by_kind: dict[str, list[dict]] = {}
    for r in tail:
        if r.get("e2e_s") is not None and r.get("outcome") in ANSWERED:
            by_kind.setdefault(r["kind"], []).append(r)
    out = {}
    for kind, krecs in sorted(by_kind.items()):
        e2e = sorted(r["e2e_s"] for r in krecs)
        out[kind] = {
            "count": len(krecs),
            "p50_ms": round(_percentile(e2e, 0.50) * 1e3, 3),
            "p99_ms": round(_percentile(e2e, 0.99) * 1e3, 3),
            "mean_components_ms": _component_means(krecs),
        }
    return out


# --- exports -----------------------------------------------------------------


def raw_snapshot() -> dict:
    """The `reqtrace` sub-object of `telemetry.snapshot()`: summary
    counts + the current attribution (bounded — per-request records
    stay in the registry / the Chrome trace, not the snapshot)."""
    with _lock:
        ctxs = list(_records)
        n_batches = len(_batches)
        rd, bd = _records_dropped, _batches_dropped
    recs = [c.record() for c in ctxs]
    by_outcome: dict[str, int] = {}
    by_kind: dict[str, int] = {}
    for r in recs:
        by_outcome[r["outcome"]] = by_outcome.get(r["outcome"], 0) + 1
        by_kind[r["kind"]] = by_kind.get(r["kind"], 0) + 1
    return {
        "enabled": _enabled,
        "completed": len(recs),
        "batches": n_batches,
        "records_dropped": rd,
        "batches_dropped": bd,
        "by_kind": by_kind,
        "by_outcome": by_outcome,
        "attribution": attribution(recs, worst_n=3) if recs else None,
    }


def chrome_events(pid: int, t0: float) -> list[dict]:
    """Trace-event JSON for the Perfetto export: one 'X' span per
    completed request (submit → complete) and per dispatched batch,
    plus the 's'/'t'/'f' flow triplet drawing the submit → batch →
    settle arrow for each request.  `t0` is the process trace origin
    (`telemetry.core._T0`); timestamps convert to µs relative to it.
    Requests ride per-kind synthetic tids so the request tracks stack
    by kind instead of interleaving one row."""
    out: list[dict] = []
    with _lock:
        ctxs = list(_records)
        brecs = [dict(b) for b in _batches]
    recs = [c.record() for c in ctxs]

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 3)

    kind_tid = {}

    def tid_for(kind: str) -> int:
        if kind not in kind_tid:
            kind_tid[kind] = 0x52510000 + len(kind_tid)   # 'RQ' tracks
        return kind_tid[kind]

    for b in brecs:
        out.append({
            "name": f"batch.{b['kind']}", "ph": "X", "cat": "req",
            "pid": pid, "tid": 0x42510000,                # batch track
            "ts": us(b["t_dispatch"]), "dur": 1.0,
            "args": {"batch": b["batch_id"], "requests": b["requests"],
                     "attempt": b["attempt"],
                     "trace_ids": b["trace_ids"][:32]},
        })
    for r in recs:
        if r.get("t_complete") is None:
            continue
        tid = tid_for(r["kind"])
        name = f"req.{r['kind']}"
        out.append({
            "name": name, "ph": "X", "cat": "req", "pid": pid,
            "tid": tid, "ts": us(r["t_submit"]),
            "dur": round(r["e2e_s"] * 1e6, 3),
            "args": {"trace_id": r["trace_id"], "outcome": r["outcome"],
                     "batch": r["batch"], "attempts": r["attempts"],
                     "components_ms": {
                         c: round(r["components"].get(c, 0.0) * 1e3, 3)
                         for c in COMPONENTS}},
        })
        # the flow arrow: submit -> dispatch (on the batch track) ->
        # settle, tied by the trace id
        flow = {"cat": "req", "name": name, "id": r["trace_id"],
                "pid": pid}
        out.append(dict(flow, ph="s", tid=tid, ts=us(r["t_submit"])))
        if r.get("t_dispatch") is not None:
            out.append(dict(flow, ph="t", tid=0x42510000,
                            ts=us(r["t_dispatch"])))
        out.append(dict(flow, ph="f", bp="e", tid=tid,
                        ts=us(r["t_complete"])))
    return out
