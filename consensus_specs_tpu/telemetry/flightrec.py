"""Incident flight recorder — a bounded structured event ring spanning
the whole stack, plus a self-contained incident-bundle dump.

The chaos and mesh rounds run on pods nobody can re-attach to: when a
rule breaches there, the evidence today is scattered across stderr,
`out/slo_breaches.json`, and the in-memory trace ring — all gone, or
unreadable, by the time a human looks.  This module keeps a bounded
ring of the *rare, causally interesting* events every subsystem already
knows about at the moment they happen:

    breaker_transition   resilience.policies CircuitBreaker._transition
    fault_injected       resilience.faults maybe_inject / corrupt
    mesh_device_lost     resilience.mesh MeshState.mark_lost
    mesh_device_back     resilience.mesh MeshState.record_probe readmit
    checkpoint_snapshot  resilience.checkpoint snapshot()
    checkpoint_restore   resilience.checkpoint restore()
    batch_poisoned       serve.executor _batch_failed poison path
    slo_breach / slo_clear   telemetry.monitor rule transitions
    occupancy_collapse   telemetry.monitor busy_frac falling off a cliff
    dump                 every bundle dump records itself

and `dump_bundle()` freezes the ring together with everything needed to
read an incident offline into ONE directory:

    manifest.json    format/schema tag, wall+mono timestamps, reason,
                     breached rule, git sha, CST_* env-knob snapshot,
                     fault-plan description (seed + rules) and fired
                     injections, file inventory
    events.jsonl     the ring, one JSON object per line, oldest first
    exemplars.json   reqtrace worst-N exemplar traces + attribution
    metrics.txt      a Prometheus exposition scrape (text format)
    state.json       serve status (breakers, queues), SLO block,
                     occupancy block — the live state at dump time

Every file is plain JSON / Prometheus text: the bundle loads with no
repo imports (pinned by tests/test_flightrec.py).

Trigger matrix:
    watchdog breach      CST_FLIGHTREC_ON_BREACH=1 — once per rule per
                         watchdog install (rides the same once-gating
                         discipline as CST_PROFILE_ON_BREACH)
    poison storm         CST_FLIGHTREC_POISON_N=N — the executor dumps
                         once after its N-th poisoned batch (0=off)
    on demand            python -m consensus_specs_tpu.telemetry.flightrec
                         (or `make incident`)

Gating: the ring itself is ON by default (`CST_FLIGHTREC=0` disables) —
these events fire a handful of times per run, never per request, so the
recorder must not miss the incident nobody predicted.  The ring is a
`deque(maxlen=CST_FLIGHTREC_CAP)` (default 4096): bounded memory,
oldest events evicted, evictions counted.  Stdlib-only at module level;
the dump's reads of sibling subsystems are lazy and individually
fault-tolerant (a broken reader degrades that file, never the dump).
"""

from __future__ import annotations

import io
import json
import os
import subprocess
import sys
import threading
import time
from collections import deque

MANIFEST_FORMAT = "cst-incident"
MANIFEST_SCHEMA = 1
DEFAULT_CAP = 4096
DEFAULT_DIR = os.path.join("out", "incidents")

EVENT_KINDS = (
    "breaker_transition", "fault_injected", "mesh_device_lost",
    "mesh_device_back", "checkpoint_snapshot", "checkpoint_restore",
    "batch_poisoned", "slo_breach", "slo_clear", "occupancy_collapse",
    "dump",
)

_lock = threading.Lock()


def _env_enabled() -> bool:
    return os.environ.get("CST_FLIGHTREC", "1") not in ("", "0")


def _env_cap() -> int:
    try:
        cap = int(os.environ.get("CST_FLIGHTREC_CAP", str(DEFAULT_CAP)))
    except ValueError:
        return DEFAULT_CAP
    return max(1, cap)


_enabled = _env_enabled()
_ring: deque = deque(maxlen=_env_cap())
_seq = 0
_evicted = 0
_dumps = 0


def enabled() -> bool:
    """True while the recorder accepts events (default on —
    `CST_FLIGHTREC=0` disables)."""
    return _enabled


def configure(enabled: bool | None = None,
              cap: int | None = None) -> None:
    """Programmatic override of the env gates (tests, benches).  A cap
    change rebuilds the ring, keeping the newest events."""
    global _enabled, _ring
    if enabled is not None:
        _enabled = enabled
    if cap is not None:
        with _lock:
            _ring = deque(_ring, maxlen=max(1, cap))


def _reset_state() -> None:
    """Full test-isolation reset (telemetry.reset(full=True) hook)."""
    global _enabled, _ring, _seq, _evicted, _dumps
    with _lock:
        _enabled = _env_enabled()
        _ring = deque(maxlen=_env_cap())
        _seq = 0
        _evicted = 0
        _dumps = 0


def record(kind: str, /, **fields) -> None:
    """Append one structured event to the ring.  `kind` is one of
    EVENT_KINDS (unknown kinds are recorded too — the ring must not
    drop the event a future subsystem invents); `fields` must be
    JSON-serializable scalars/containers.  `kind` is positional-only so
    a caller-supplied `kind=` field cannot collide with it (the event
    kind always wins the dict slot).  Disabled cost: one global read."""
    global _seq, _evicted
    if not _enabled:
        return
    ev = {"seq": 0, "ts": round(time.time(), 6),
          "t_mono": round(time.perf_counter(), 6)}
    ev.update(fields)
    ev["kind"] = kind
    with _lock:
        _seq += 1
        ev["seq"] = _seq
        if len(_ring) == _ring.maxlen:
            _evicted += 1
        _ring.append(ev)


def events() -> list[dict]:
    """Ring contents, oldest first (copies)."""
    with _lock:
        return [dict(ev) for ev in _ring]


def stats() -> dict:
    with _lock:
        return {"enabled": _enabled, "events": len(_ring),
                "cap": _ring.maxlen, "recorded": _seq,
                "evicted": _evicted, "dumps": _dumps}


# --- bundle dump -------------------------------------------------------------


def _git_sha() -> str | None:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True,
            text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else None
    except Exception:
        return None


def _env_knobs() -> dict:
    return {k: v for k, v in sorted(os.environ.items())
            if k.startswith("CST_")}


def _fault_plan() -> dict | None:
    try:
        from ..resilience import faults
        plan = faults.current()
        if plan is None:
            return None
        desc = plan.describe()
        desc["injections"] = faults.injections()
        return desc
    except Exception:
        return None


def _exemplars() -> dict:
    try:
        from . import reqtrace
        att = reqtrace.attribution()
        return {"worst": att.get("worst", []),
                "attribution": att}
    except Exception as exc:
        return {"error": f"{type(exc).__name__}: {exc}"}


def _metrics_text() -> str:
    try:
        from . import metrics_export
        return metrics_export.render_exposition()
    except Exception as exc:
        return f"# flightrec: exposition unavailable: {exc}\n"


def _state() -> dict:
    state: dict = {}
    try:
        from . import metrics_export
        state["serve_status"] = metrics_export.get_status()
    except Exception:
        state["serve_status"] = None
    try:
        from . import monitor
        wd = monitor.current()
        state["slo"] = wd.slo_block() if wd is not None else None
    except Exception:
        state["slo"] = None
    try:
        from . import occupancy
        state["occupancy"] = (occupancy.block()
                              if occupancy.enabled() else None)
    except Exception:
        state["occupancy"] = None
    return state


def validate_manifest(obj) -> list[str]:
    """Schema check for a bundle manifest; returns a list of problems
    (empty == valid).  The contract tests/test_flightrec.py and the
    chaos smoke pin — an incident bundle a pod ships home must be
    readable without guessing."""
    problems: list[str] = []
    if not isinstance(obj, dict):
        return ["manifest: not an object"]
    if obj.get("format") != MANIFEST_FORMAT:
        problems.append(f"format: {obj.get('format')!r} != "
                        f"{MANIFEST_FORMAT!r}")
    if obj.get("schema") != MANIFEST_SCHEMA:
        problems.append(f"schema: {obj.get('schema')!r} != "
                        f"{MANIFEST_SCHEMA}")
    for key, typ in (("created_unix", (int, float)),
                     ("reason", str), ("events", int),
                     ("env", dict), ("files", list)):
        if not isinstance(obj.get(key), typ):
            problems.append(f"{key}: missing or wrong type")
    if "rule" in obj and obj["rule"] is not None \
            and not isinstance(obj["rule"], str):
        problems.append("rule: not a string")
    if "git_sha" in obj and obj["git_sha"] is not None \
            and not isinstance(obj["git_sha"], str):
        problems.append("git_sha: not a string")
    fp = obj.get("fault_plan")
    if fp is not None:
        if not isinstance(fp, dict):
            problems.append("fault_plan: not an object")
        else:
            if not isinstance(fp.get("seed"), int):
                problems.append("fault_plan.seed: missing int")
            if not isinstance(fp.get("faults"), list):
                problems.append("fault_plan.faults: missing list")
    if isinstance(obj.get("files"), list):
        for want in ("events.jsonl", "exemplars.json", "metrics.txt",
                     "state.json"):
            if want not in obj["files"]:
                problems.append(f"files: {want} missing")
    return problems


def dump_bundle(directory: str | None = None, reason: str = "manual",
                rule: str | None = None) -> str:
    """Write a self-contained incident directory and return its path.

    `directory` is the PARENT incidents dir (default
    `CST_FLIGHTREC_DIR` or `out/incidents`); each dump creates a fresh
    `incident-<n>-<reason>` inside it.  Never raises for a degraded
    sub-reader — a bundle with a broken metrics scrape still carries
    the ring and the manifest."""
    global _dumps
    parent = directory or os.environ.get("CST_FLIGHTREC_DIR",
                                         DEFAULT_DIR)
    with _lock:
        _dumps += 1
        n = _dumps
    slug = "".join(c if c.isalnum() or c in "-_" else "-"
                   for c in reason)[:48] or "manual"
    path = os.path.join(parent, f"incident-{n:03d}-{slug}")
    os.makedirs(path, exist_ok=True)

    record("dump", reason=reason, rule=rule, path=path)
    evs = events()

    with io.open(os.path.join(path, "events.jsonl"), "w",
                 encoding="utf-8") as fh:
        for ev in evs:
            fh.write(json.dumps(ev, sort_keys=True) + "\n")

    def _write_json(name: str, obj) -> None:
        with io.open(os.path.join(path, name), "w",
                     encoding="utf-8") as fh:
            json.dump(obj, fh, indent=2, sort_keys=True, default=str)
            fh.write("\n")

    _write_json("exemplars.json", _exemplars())
    with io.open(os.path.join(path, "metrics.txt"), "w",
                 encoding="utf-8") as fh:
        fh.write(_metrics_text())
    _write_json("state.json", _state())

    st = stats()
    manifest = {
        "format": MANIFEST_FORMAT,
        "schema": MANIFEST_SCHEMA,
        "created_unix": round(time.time(), 6),
        "reason": reason,
        "rule": rule,
        "git_sha": _git_sha(),
        "env": _env_knobs(),
        "fault_plan": _fault_plan(),
        "events": len(evs),
        "events_evicted": st["evicted"],
        "files": ["manifest.json", "events.jsonl", "exemplars.json",
                  "metrics.txt", "state.json"],
    }
    _write_json("manifest.json", manifest)
    return path


# --- env-gated triggers (read by monitor / executor) -------------------------


def dump_on_breach() -> bool:
    """Whether the watchdog should dump a bundle on a rule's first
    breach (`CST_FLIGHTREC_ON_BREACH`, default off — smoke and pod
    rounds arm it)."""
    return os.environ.get("CST_FLIGHTREC_ON_BREACH", "0") \
        not in ("", "0")


def poison_dump_threshold() -> int:
    """Poisoned-batch count after which the executor dumps a bundle
    once (`CST_FLIGHTREC_POISON_N`, 0 = off)."""
    try:
        n = int(os.environ.get("CST_FLIGHTREC_POISON_N", "0"))
    except ValueError:
        return 0
    return max(0, n)


def main(argv: list[str] | None = None) -> int:
    """`python -m consensus_specs_tpu.telemetry.flightrec` — on-demand
    incident dump.  Prints the bundle path; exit 0 on a written
    bundle, 2 on bad usage, 1 on failure."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="consensus_specs_tpu.telemetry.flightrec",
        description="dump a self-contained incident bundle")
    parser.add_argument("--dir", default=None,
                        help="parent incidents directory "
                             f"(default: CST_FLIGHTREC_DIR or "
                             f"{DEFAULT_DIR})")
    parser.add_argument("--reason", default="manual",
                        help="reason slug recorded in the manifest")
    try:
        args = parser.parse_args(argv)
    except SystemExit as exc:
        return 2 if exc.code not in (0, None) else 0
    try:
        path = dump_bundle(directory=args.dir, reason=args.reason)
    except Exception as exc:
        print(f"flightrec: dump failed: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        return 1
    manifest = os.path.join(path, "manifest.json")
    try:
        with io.open(manifest, "r", encoding="utf-8") as fh:
            problems = validate_manifest(json.load(fh))
    except Exception as exc:
        print(f"flightrec: manifest unreadable: {exc}",
              file=sys.stderr)
        return 1
    if problems:
        print("flightrec: manifest invalid: " + "; ".join(problems),
              file=sys.stderr)
        return 1
    print(path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
