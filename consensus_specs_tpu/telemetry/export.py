"""Telemetry exporters: JSON-lines, Chrome trace-event, bench sub-object.

Three consumers, three formats:

- `write_jsonl(path)`     one JSON object per line (spans as emitted),
                          greppable / `jq`-able post-hoc.
- `write_chrome_trace(path)`  the Trace Event Format JSON object
                          (https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU)
                          loadable in Perfetto / chrome://tracing.
                          Armed automatically at process exit when
                          `CST_TRACE_FILE` is set.
- `bench_block()`         the `"telemetry"` sub-object embedded in the
                          bench JSON contract (`bench.py` / `bench_bls
                          .py`): the flagship split into compile_s vs
                          run_s, bucket-padding waste, and MSM/h2c
                          routing counts.  `validate_bench_block` pins
                          the schema for `bench_smoke.py` and the tests.
"""

from __future__ import annotations

import json
import os

from . import core


def write_jsonl(path: str) -> int:
    """Write every buffered span event as one JSON line; returns the
    number of lines written."""
    events, _ = core._events_copy()
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return len(events)


def chrome_trace() -> dict:
    """The trace-event JSON object: buffered spans as 'X' (complete)
    events; cost-model watermark samples, per-kernel cost records, and
    gauge samples (serve queue depth / in-flight batches) as 'C'
    (counter) events — so the Perfetto timeline shows device-memory
    pressure, kernel flop/byte budgets, and the serve pipeline's
    breathing alongside the span track — plus process/thread metadata,
    all on one pid."""
    from . import costmodel

    events, dropped = core._events_copy()
    pid = os.getpid()
    out = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "consensus_specs_tpu"},
    }]
    for e in events:
        out.append({
            "name": e["name"], "ph": "X", "cat": "cst",
            "pid": pid, "tid": e["tid"],
            "ts": round(e["ts"], 3), "dur": round(e["dur"], 3),
            "args": e["args"],
        })
    wm_events, wm_dropped = costmodel._wm_events_copy()
    for w in wm_events:
        # one counter series per device: Perfetto renders each args key
        # as its own track under the "device_memory_bytes" counter
        out.append({
            "name": "device_memory_bytes", "ph": "C", "cat": "cst",
            "pid": pid, "tid": 0, "ts": round(w["ts"], 3),
            "args": {dev: b for dev, b in w["bytes"].items()},
        })
    for c in costmodel._cost_events_copy():
        if "error" in c:
            continue
        out.append({
            "name": f"cost.{c['kernel']}", "ph": "C", "cat": "cst",
            "pid": pid, "tid": 0,
            "ts": round(c.get("ts_rel_us", 0.0), 3),
            "args": {"flops": c.get("flops", 0.0),
                     "bytes_accessed": c.get("bytes_accessed", 0.0)},
        })
    gauge_events, g_dropped = core._gauge_events_copy()
    for g in gauge_events:
        # one counter track per gauge name (serve.queue_depth,
        # serve.inflight_batches, ...) next to device_memory_bytes
        out.append({
            "name": g["name"], "ph": "C", "cat": "cst",
            "pid": pid, "tid": 0, "ts": round(g["ts"], 3),
            "args": {"value": g["value"]},
        })
    # request tracing (CST_TRACE_REQUESTS): per-request lifecycle 'X'
    # spans + 's'/'t'/'f' flow arrows (submit → batch → settle) + batch
    # spans, on per-kind request tracks next to the span timeline
    from . import reqtrace

    out.extend(reqtrace.chrome_events(pid, core._T0))
    # device-occupancy busy tracks (CST_OCCUPANCY): one 'C' counter per
    # device rising to 1 over each merged busy span, so pipeline
    # bubbles are visible as flat-zero stretches next to the request
    # and gauge tracks
    from . import occupancy

    out.extend(occupancy.chrome_events(pid, core._T0))
    trace = {"traceEvents": out, "displayTimeUnit": "ms"}
    if dropped or wm_dropped or g_dropped:
        trace["otherData"] = {
            "events_dropped": dropped + wm_dropped + g_dropped}
    return trace


def write_chrome_trace(path: str) -> None:
    # serialize fully before touching the file, and never raise:
    # exporting must not fail (or truncate) at process exit — but a
    # skipped export is announced, not silent, the file IS the output
    try:
        data = json.dumps(chrome_trace())
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        with open(path, "w") as f:
            f.write(data)
    except Exception as e:
        import sys

        print(f"telemetry: chrome trace not written to {path}: {e}",
              file=sys.stderr)


# --- bench contract ---------------------------------------------------------


def bench_block(compile_s: float | None = None,
                run_s: float | None = None) -> dict:
    """Assemble the `"telemetry"` sub-object for a bench JSON line from
    the live registry.  compile_s/run_s default to the kernel-dispatch
    histograms (`kernel.compile_first_s` / `kernel.run_s` — see
    `core.first_call`); a bench that times its own jit entry point
    (bench.py's epoch `step`) passes explicit values instead."""
    from . import costmodel

    snap = core.snapshot()
    h = snap["histograms"]
    c = snap["counters"]
    if compile_s is None:
        compile_s = h.get("kernel.compile_first_s", {}).get("total", 0.0)
    if run_s is None:
        run_s = h.get("kernel.run_s", {}).get("total", 0.0)
    live = c.get("bls.lanes.live", 0)
    padded = c.get("bls.lanes.padded", 0)
    cm = costmodel.block(h)
    out = {
        "compile_s": round(float(compile_s), 4),
        "run_s": round(float(run_s), 4),
        # process-level meta (compile-cache dir + entry count, ...) —
        # survives per-config resets, see core.reset
        "meta": snap["meta"],
        "padding": {
            "live_lanes": live,
            "padded_lanes": padded,
            "waste_frac": round(1.0 - live / padded, 4) if padded else 0.0,
        },
        "routing": {
            "msm_host": c.get("msm.route.host", 0),
            "msm_device": c.get("msm.route.device", 0),
            "msm_pippenger": c.get("msm.algo.pippenger", 0),
            "msm_double_add": c.get("msm.algo.double-add", 0),
            "h2c_device": c.get("bls.h2c.device", 0),
            "h2c_host": c.get("bls.h2c.host", 0),
        },
        "counters": snap["counters"],
    }
    if cm is not None:   # CST_COSTMODEL rounds: joined roofline records
        out["costmodel"] = cm
    return out


def validate_bench_block(obj) -> list[str]:
    """Schema check for a bench `"telemetry"` sub-object; returns a list
    of problems (empty == valid).  Used by `bench_smoke.py` and
    `tests/test_telemetry.py` so the bench contract cannot silently
    drop or malform the block."""
    problems: list[str] = []
    if not isinstance(obj, dict):
        return [f"telemetry block is {type(obj).__name__}, not dict"]
    for key in ("compile_s", "run_s"):
        v = obj.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
            problems.append(f"{key!r} must be a non-negative number, "
                            f"got {v!r}")
    pad = obj.get("padding")
    if not isinstance(pad, dict):
        problems.append("'padding' must be a dict")
    else:
        for key in ("live_lanes", "padded_lanes"):
            if not isinstance(pad.get(key), int):
                problems.append(f"padding[{key!r}] must be an int")
        wf = pad.get("waste_frac")
        if not isinstance(wf, (int, float)) or not (0.0 <= wf <= 1.0):
            problems.append("padding['waste_frac'] must be in [0, 1]")
    routing = obj.get("routing")
    if not isinstance(routing, dict):
        problems.append("'routing' must be a dict")
    else:
        for key in ("msm_host", "msm_device", "msm_pippenger",
                    "msm_double_add", "h2c_device", "h2c_host"):
            v = routing.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(f"routing[{key!r}] must be a "
                                f"non-negative int, got {v!r}")
    if not isinstance(obj.get("counters"), dict):
        problems.append("'counters' must be a dict")
    if not isinstance(obj.get("meta", {}), dict):
        problems.append("'meta' must be a dict when present")
    cm = obj.get("costmodel")
    if cm is not None:
        problems.extend(validate_costmodel_block(cm))
    return problems


_BOUNDS = ("compute", "memory", "launch", "unknown")


def validate_costmodel_block(cm) -> list[str]:
    """Schema check for the `"costmodel"` sub-object (CST_COSTMODEL
    rounds); returns problems (empty == valid).  Error records (capture
    failed, reason attached) are valid by design — a kernel the backend
    cannot analyze must stay visible, not break the contract."""
    problems: list[str] = []
    if not isinstance(cm, dict):
        return [f"costmodel block is {type(cm).__name__}, not dict"]
    kernels = cm.get("kernels")
    if not isinstance(kernels, dict):
        problems.append("costmodel['kernels'] must be a dict")
        kernels = {}
    for name, rec in kernels.items():
        if not isinstance(rec, dict):
            problems.append(f"costmodel kernel {name!r} must be a dict")
            continue
        if "error" in rec:
            continue
        for key in ("flops", "bytes_accessed"):
            v = rec.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0:
                problems.append(f"costmodel kernel {name!r}: {key!r} "
                                f"must be a non-negative number, got {v!r}")
        if rec.get("bound") not in _BOUNDS:
            problems.append(f"costmodel kernel {name!r}: 'bound' must "
                            f"be one of {_BOUNDS}, got {rec.get('bound')!r}")
    wms = cm.get("watermarks")
    if not isinstance(wms, dict):
        problems.append("costmodel['watermarks'] must be a dict")
        wms = {}
    for dev, wm in wms.items():
        if not isinstance(wm, dict) or not isinstance(
                wm.get("high_water_bytes"), int):
            problems.append(f"costmodel watermark {dev!r} must carry an "
                            f"int 'high_water_bytes'")
        elif isinstance(wm.get("last_bytes"), int) \
                and wm["last_bytes"] > wm["high_water_bytes"]:
            problems.append(f"costmodel watermark {dev!r}: high water "
                            f"below last sample")
    return problems


def validate_serve_block(obj) -> list[str]:
    """Schema check for the bench `"serve"` sub-object (the sustained-
    load block `serve.loadgen.run_load` returns and `bench_serve.py`
    embeds); returns problems (empty == valid).  Pinned by
    `bench_smoke.py`'s serve round and `tests/test_serve.py`."""
    problems: list[str] = []
    if not isinstance(obj, dict):
        return [f"serve block is {type(obj).__name__}, not dict"]
    vps = obj.get("verifies_per_s")
    if not isinstance(vps, (int, float)) or isinstance(vps, bool) \
            or vps < 0:
        problems.append(f"'verifies_per_s' must be a non-negative "
                        f"number, got {vps!r}")
    for key in ("p50_ms", "p99_ms"):
        v = obj.get(key)
        if v is not None and (not isinstance(v, (int, float))
                              or isinstance(v, bool) or v < 0):
            problems.append(f"{key!r} must be a non-negative number or "
                            f"null, got {v!r}")
    p50, p99 = obj.get("p50_ms"), obj.get("p99_ms")
    if isinstance(p50, (int, float)) and isinstance(p99, (int, float)) \
            and p99 < p50:
        problems.append(f"p99_ms ({p99}) below p50_ms ({p50})")
    if not isinstance(obj.get("steady"), bool):
        problems.append("'steady' must be a bool")
    windows = obj.get("windows")
    if not isinstance(windows, list) or not all(
            isinstance(w, (int, float)) and not isinstance(w, bool)
            for w in windows):
        problems.append("'windows' must be a list of numbers")
    for key in ("submitted", "settled", "failed"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            problems.append(f"{key!r} must be a non-negative int, "
                            f"got {v!r}")
    qd = obj.get("queue_depth")
    if not isinstance(qd, dict) or not isinstance(qd.get("hist"), dict) \
            or not isinstance(qd.get("max"), int):
        problems.append("'queue_depth' must carry an int 'max' and a "
                        "'hist' dict")
    elif not all(isinstance(k, str) and isinstance(v, int)
                 for k, v in qd["hist"].items()):
        problems.append("queue_depth['hist'] must map str buckets to "
                        "int counts")
    if obj.get("mode") not in ("open", "closed"):
        problems.append(f"'mode' must be 'open' or 'closed', "
                        f"got {obj.get('mode')!r}")
    # request-tracing surface (PR 15): `latency_source` names the
    # percentile basis — "reqtrace" = per-request submit→complete
    # lifecycle records (queue wait + detours included), "executor" =
    # the legacy enqueue→batch-settle sample.  Optional for
    # backward-compat with pre-tracing blocks; a traced block must also
    # carry a schema-valid `latency_attribution` sub-object.
    src = obj.get("latency_source")
    if src is not None and src not in ("reqtrace", "executor"):
        problems.append(f"'latency_source' must be 'reqtrace' or "
                        f"'executor', got {src!r}")
    la = obj.get("latency_attribution")
    if src == "reqtrace" and la is None:
        problems.append("'latency_source' is 'reqtrace' but "
                        "'latency_attribution' is missing")
    if la is not None:
        problems.extend(validate_latency_attribution(la))
    # live-monitoring surface (SLO watchdog): optional — present on
    # rounds armed with CST_SLO_RULES
    slo = obj.get("slo")
    if slo is not None:
        problems.extend(validate_slo_block(slo))
    # device-occupancy surface: optional — present on rounds armed with
    # CST_OCCUPANCY
    occ = obj.get("occupancy")
    if occ is not None:
        problems.extend(validate_occupancy_block(occ))
    return problems


_SLO_PHASES = ("breach", "clear")


def validate_slo_block(obj) -> list[str]:
    """Schema check for the serve block's `"slo"` sub-object
    (`telemetry.monitor.Watchdog.slo_block`); returns problems (empty
    == valid).  Pinned by `bench_smoke.py`'s serve/chaos rounds and
    tests/test_monitor.py."""
    if not isinstance(obj, dict):
        return [f"slo block is {type(obj).__name__}, not dict"]
    problems: list[str] = []
    for key in ("ticks", "breaches", "events_dropped"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            problems.append(f"slo[{key!r}] must be a non-negative int, "
                            f"got {v!r}")
    if not isinstance(obj.get("clean"), bool):
        problems.append("slo['clean'] must be a bool")
    elif isinstance(obj.get("breaches"), int) \
            and obj["clean"] != (obj["breaches"] == 0):
        problems.append("slo['clean'] must equal (breaches == 0)")
    bn = obj.get("breaching_now")
    if not isinstance(bn, list) or not all(isinstance(n, str)
                                           for n in bn):
        problems.append("slo['breaching_now'] must be a list of rule "
                        "names")
    rules = obj.get("rules")
    if not isinstance(rules, list) or not rules:
        problems.append("slo['rules'] must be a non-empty list")
        rules = []
    for i, r in enumerate(rules):
        if not isinstance(r, dict) or not isinstance(r.get("name"), str):
            problems.append(f"slo rules[{i}] must be a dict with a "
                            f"str 'name'")
            continue
        for key in ("ticks", "breaches", "clears"):
            v = r.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(f"slo rules[{i}][{key!r}] must be a "
                                f"non-negative int, got {v!r}")
        if not isinstance(r.get("breaching"), bool):
            problems.append(f"slo rules[{i}]['breaching'] must be a "
                            f"bool")
        thr = r.get("threshold")
        if not isinstance(thr, (int, float)) or isinstance(thr, bool):
            problems.append(f"slo rules[{i}]['threshold'] must be a "
                            f"number, got {thr!r}")
    events = obj.get("events")
    if not isinstance(events, list):
        problems.append("slo['events'] must be a list")
        events = []
    for i, e in enumerate(events):
        if not isinstance(e, dict) or e.get("phase") not in _SLO_PHASES \
                or not isinstance(e.get("rule"), str) \
                or not isinstance(e.get("ts"), (int, float)):
            problems.append(f"slo events[{i}] must carry phase in "
                            f"{_SLO_PHASES}, a str 'rule' and a "
                            f"numeric 'ts'")
            break
    profiles = obj.get("profiles")
    if not isinstance(profiles, list) or not all(
            isinstance(p, str) for p in profiles):
        problems.append("slo['profiles'] must be a list of paths")
    return problems


_BUBBLE_CAUSES = ("host_prep", "queue_starved", "settle_serialized",
                  "drain")


def validate_occupancy_block(obj) -> list[str]:
    """Schema check for the serve block's `"occupancy"` sub-object
    (`telemetry.occupancy.block`); returns problems (empty == valid).
    Enforces the contiguity contract: busy plus the four bubble
    components must sum to the measured wall within 1e-6 relative.
    Pinned by `bench_smoke.py`'s serve round and
    tests/test_occupancy.py."""
    if not isinstance(obj, dict):
        return [f"occupancy block is {type(obj).__name__}, not dict"]
    problems: list[str] = []
    if not isinstance(obj.get("enabled"), bool):
        problems.append("occupancy['enabled'] must be a bool")
    for key in ("wall_s", "busy_s", "busy_frac"):
        v = obj.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or v < 0:
            problems.append(f"occupancy[{key!r}] must be a non-negative "
                            f"number, got {v!r}")
    for key in ("events", "events_dropped"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            problems.append(f"occupancy[{key!r}] must be a non-negative "
                            f"int, got {v!r}")
    bub = obj.get("bubbles_s")
    if not isinstance(bub, dict) or set(bub) != set(_BUBBLE_CAUSES):
        problems.append(f"occupancy['bubbles_s'] must map exactly the "
                        f"causes {_BUBBLE_CAUSES}")
        bub = None
    elif not all(isinstance(v, (int, float)) and not isinstance(v, bool)
                 and v >= -1e-9 for v in bub.values()):
        problems.append("occupancy bubble components must be "
                        "non-negative numbers")
        bub = None
    wall, busy = obj.get("wall_s"), obj.get("busy_s")
    if bub is not None and isinstance(wall, (int, float)) \
            and isinstance(busy, (int, float)) and wall > 0:
        total = busy + sum(bub.values())
        if abs(total - wall) > 1e-6 * max(wall, 1e-12):
            problems.append(f"occupancy busy+bubbles ({total}) != "
                            f"wall_s ({wall}) beyond 1e-6 relative")
    devs = obj.get("devices")
    if not isinstance(devs, dict):
        problems.append("occupancy['devices'] must be a dict")
        devs = {}
    for dev, blk in devs.items():
        if not isinstance(blk, dict) \
                or not isinstance(blk.get("busy_s"), (int, float)) \
                or not isinstance(blk.get("busy_frac"), (int, float)) \
                or not isinstance(blk.get("spans"), int) \
                or not isinstance(blk.get("bubbles_s"), dict):
            problems.append(f"occupancy device {dev!r} must carry "
                            f"busy_s, busy_frac, spans, bubbles_s")
    byk = obj.get("device_seconds_by_kind")
    if not isinstance(byk, dict) or not all(
            isinstance(k, str) and isinstance(v, (int, float))
            and not isinstance(v, bool) for k, v in byk.items()):
        problems.append("occupancy['device_seconds_by_kind'] must map "
                        "str kinds to numbers")
    ov = obj.get("overlap")
    if not isinstance(ov, dict) \
            or not isinstance(ov.get("prep_s"), (int, float)) \
            or not isinstance(ov.get("hidden_s"), (int, float)):
        problems.append("occupancy['overlap'] must carry numeric "
                        "prep_s and hidden_s")
    else:
        score = ov.get("score")
        if score is not None and (not isinstance(score, (int, float))
                                  or isinstance(score, bool)
                                  or not -1e-9 <= score <= 1 + 1e-9):
            problems.append(f"occupancy overlap score must be in "
                            f"[0, 1] or null, got {score!r}")
        if ov["prep_s"] > 0 and score is None:
            problems.append("occupancy overlap score must be present "
                            "when prep_s > 0")
    depth = obj.get("depth")
    if depth is not None and (not isinstance(depth, int)
                              or isinstance(depth, bool) or depth < 1):
        problems.append(f"occupancy['depth'] must be a positive int or "
                        f"null, got {depth!r}")
    return problems


_LATENCY_COMPONENTS = ("queue_wait", "batch_form", "device_wall",
                       "settle", "detour")
_LATENCY_OUTCOMES = ("ok", "recheck", "retry", "fallback", "shed",
                     "poisoned", "timeout")


def validate_latency_attribution(obj) -> list[str]:
    """Schema check for the serve block's `latency_attribution`
    sub-object (`telemetry.reqtrace.attribution`); returns problems
    (empty == valid).  Pinned by `bench_smoke.py`'s traced serve round
    and tests/test_reqtrace.py."""
    if not isinstance(obj, dict):
        return [f"latency_attribution is {type(obj).__name__}, not dict"]
    problems: list[str] = []
    kinds = obj.get("kinds")
    if not isinstance(kinds, dict):
        problems.append("latency_attribution['kinds'] must be a dict")
        kinds = {}
    for kind, blk in kinds.items():
        if not isinstance(blk, dict):
            problems.append(f"latency kind {kind!r} must be a dict")
            continue
        n = blk.get("count")
        if not isinstance(n, int) or isinstance(n, bool) or n < 1:
            problems.append(f"latency kind {kind!r}: 'count' must be a "
                            f"positive int, got {n!r}")
        for key in ("p50_ms", "p90_ms", "p99_ms"):
            v = blk.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0:
                problems.append(f"latency kind {kind!r}: {key!r} must "
                                f"be a non-negative number, got {v!r}")
        p50, p99 = blk.get("p50_ms"), blk.get("p99_ms")
        if isinstance(p50, (int, float)) and isinstance(p99, (int, float)) \
                and p99 < p50:
            problems.append(f"latency kind {kind!r}: p99_ms ({p99}) "
                            f"below p50_ms ({p50})")
        for key in ("mean_components_ms", "p99_components_ms"):
            comp = blk.get(key)
            if not isinstance(comp, dict) or not all(
                    c in comp and isinstance(comp[c], (int, float))
                    and not isinstance(comp[c], bool) and comp[c] >= 0
                    for c in _LATENCY_COMPONENTS):
                problems.append(
                    f"latency kind {kind!r}: {key!r} must map every "
                    f"component {_LATENCY_COMPONENTS} to a non-negative "
                    f"number")
        oc = blk.get("outcomes")
        if not isinstance(oc, dict) or not all(
                k in _LATENCY_OUTCOMES and isinstance(v, int)
                for k, v in oc.items()):
            problems.append(f"latency kind {kind!r}: 'outcomes' must "
                            f"map outcomes in {_LATENCY_OUTCOMES} to "
                            f"int counts")
    frac = obj.get("p99_queue_frac")
    if frac is not None and (not isinstance(frac, (int, float))
                             or isinstance(frac, bool)
                             or not 0.0 <= frac <= 1.0):
        problems.append(f"'p99_queue_frac' must be in [0, 1] or null, "
                        f"got {frac!r}")
    worst = obj.get("worst")
    if not isinstance(worst, list):
        problems.append("'worst' must be a list of exemplar traces")
    else:
        for i, ex in enumerate(worst):
            if not isinstance(ex, dict) \
                    or not isinstance(ex.get("trace_id"), int) \
                    or not isinstance(ex.get("e2e_ms"), (int, float)) \
                    or not isinstance(ex.get("components_ms"), dict):
                problems.append(f"worst[{i}] must carry trace_id / "
                                f"e2e_ms / components_ms")
                break
    return problems


def validate_resilience_block(obj) -> list[str]:
    """Schema check for a chaos round's `"resilience"` sub-object
    (`resilience.chaos.run_chaos_load`); returns problems (empty ==
    valid).  Pinned by `bench_smoke.py --chaos` and
    tests/test_resilience.py."""
    problems: list[str] = []
    if not isinstance(obj, dict):
        return [f"resilience block is {type(obj).__name__}, not dict"]
    if not isinstance(obj.get("chaos"), bool):
        problems.append("'chaos' must be a bool")
    for key in ("faults_injected", "wrong_results", "failed_requests",
                "checked_results", "retries", "fallbacks", "shed"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            problems.append(f"{key!r} must be a non-negative int, "
                            f"got {v!r}")
    for key in ("degraded_verifies_per_s", "recovery_latency_s",
                "baseline_verifies_per_s"):
        v = obj.get(key)
        if v is not None and (not isinstance(v, (int, float))
                              or isinstance(v, bool) or v < 0):
            problems.append(f"{key!r} must be a non-negative number or "
                            f"null, got {v!r}")
    if not isinstance(obj.get("recovered"), bool):
        problems.append("'recovered' must be a bool")
    if obj.get("recovered") and obj.get("recovery_latency_s") is None:
        problems.append("'recovered' is true but 'recovery_latency_s' "
                        "is null")
    br = obj.get("breaker")
    if not isinstance(br, dict) or not isinstance(
            br.get("transitions"), list) \
            or not isinstance(br.get("states"), dict):
        problems.append("'breaker' must carry a 'transitions' list and "
                        "a 'states' dict")
    else:
        for t in br["transitions"]:
            if not isinstance(t, dict) or not {"key", "from",
                                               "to"} <= set(t):
                problems.append(f"breaker transition {t!r} must carry "
                                f"key/from/to")
                break
    heal = obj.get("heal")
    if heal is not None:
        if not isinstance(heal, dict) \
                or not isinstance(heal.get("diverged"), bool):
            problems.append("'heal' must be a dict with a bool "
                            "'diverged'")
        elif heal["diverged"]:
            rs = heal.get("recovery_s")
            if not isinstance(rs, (int, float)) or isinstance(rs, bool) \
                    or rs < 0:
                problems.append("heal['recovery_s'] must be a "
                                "non-negative number when diverged")
    plan = obj.get("plan")
    if plan is not None and (not isinstance(plan, dict)
                             or not isinstance(plan.get("faults"), list)):
        problems.append("'plan' must be a fault-plan summary dict")
    fv = obj.get("fault_victims")
    if fv is not None:
        # blast-radius correlation (request tracing): which trace ids a
        # fault hit and how each settled.  `clean_ok` counts victims
        # that settled with a clean 'ok' — always zero by construction
        # (a fault-hit batch recovers as retry/fallback or poisons)
        if not isinstance(fv, dict) \
                or not isinstance(fv.get("count"), int) \
                or not isinstance(fv.get("trace_ids"), list) \
                or not isinstance(fv.get("outcomes"), dict):
            problems.append("'fault_victims' must carry int 'count', a "
                            "'trace_ids' list and an 'outcomes' dict")
        elif not all(isinstance(t, int) for t in fv["trace_ids"]):
            problems.append("fault_victims['trace_ids'] must be ints")
    problems.extend(validate_checkpoint_block(obj.get("checkpoint")))
    problems.extend(validate_mesh_block(obj.get("mesh")))
    fl = obj.get("flagship")
    if fl is not None:
        if not isinstance(fl, dict) \
                or not isinstance(fl.get("degraded_steps"), int) \
                or not isinstance(fl.get("wrong_results"), int):
            problems.append("'flagship' must carry int degraded_steps "
                            "and wrong_results")
    return problems


def validate_checkpoint_block(cp) -> list[str]:
    """Schema check for the chaos round's `"checkpoint"` sub-object
    (`resilience.chaos._checkpoint_segment`).  None is valid — the
    segment is part of chaos rounds only."""
    if cp is None:
        return []
    if not isinstance(cp, dict):
        return [f"checkpoint block is {type(cp).__name__}, not dict"]
    problems: list[str] = []
    for key in ("n_chunks", "journal_entries", "snapshot_bytes"):
        v = cp.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            problems.append(f"checkpoint[{key!r}] must be a "
                            f"non-negative int, got {v!r}")
    for key in ("restore_s", "rebuild_s", "journal_frac"):
        v = cp.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or v < 0:
            problems.append(f"checkpoint[{key!r}] must be a "
                            f"non-negative number, got {v!r}")
    if not isinstance(cp.get("parity"), bool):
        problems.append("checkpoint['parity'] must be a bool")
    sp = cp.get("speedup")
    if sp is not None and (not isinstance(sp, (int, float))
                           or isinstance(sp, bool) or sp < 0):
        problems.append(f"checkpoint['speedup'] must be a non-negative "
                        f"number or null, got {sp!r}")
    return problems


def validate_mesh_block(mesh) -> list[str]:
    """Schema check for the chaos round's `"mesh"` sub-object
    (`resilience.mesh.MeshVerifier.block` + the segment's correctness
    counters).  None and a `skipped` block (too few devices) are
    valid."""
    if mesh is None:
        return []
    if not isinstance(mesh, dict):
        return [f"mesh block is {type(mesh).__name__}, not dict"]
    if "skipped" in mesh:
        return []
    problems: list[str] = []
    for key in ("devices", "degraded_lanes", "max_degraded_lanes",
                "device_lost_events", "readmissions", "redispatches",
                "verified_statements", "lost_statements",
                "wrong_results", "checked_statements"):
        v = mesh.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            problems.append(f"mesh[{key!r}] must be a non-negative "
                            f"int, got {v!r}")
    rl = mesh.get("recovery_latency_s")
    if rl is not None and (not isinstance(rl, (int, float))
                           or isinstance(rl, bool) or rl < 0):
        problems.append(f"mesh['recovery_latency_s'] must be a "
                        f"non-negative number or null, got {rl!r}")
    return problems


def validate_scaling_block(obj) -> list[str]:
    """Schema check for the bench `"scaling"` sub-object (the
    mesh-sharded flagship rung ladder `bench.py --worker scaling`
    emits); returns problems (empty == valid).  Pinned by
    `bench_smoke.py --shard`."""
    if not isinstance(obj, dict):
        return [f"scaling block is {type(obj).__name__}, not dict"]
    problems: list[str] = []
    nd = obj.get("n_devices")
    if not isinstance(nd, int) or isinstance(nd, bool) or nd < 1:
        problems.append(f"'n_devices' must be a positive int, got {nd!r}")
    rungs = obj.get("rungs")
    if not isinstance(rungs, list) or not rungs:
        return problems + ["'rungs' must be a non-empty list"]
    for i, r in enumerate(rungs):
        if not isinstance(r, dict):
            problems.append(f"rungs[{i}] is not a dict")
            continue
        for key in ("n_validators", "n_devices"):
            v = r.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                problems.append(f"rungs[{i}][{key!r}] must be a "
                                f"positive int, got {v!r}")
        for key in ("wall_s", "per_chip_vps", "single_chip_wall_s",
                    "single_chip_vps", "efficiency"):
            v = r.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0:
                problems.append(f"rungs[{i}][{key!r}] must be a "
                                f"non-negative number, got {v!r}")
    ok8 = obj.get("ok_8m")
    if ok8 is not None and not isinstance(ok8, bool):
        problems.append(f"'ok_8m' must be a bool or null, got {ok8!r}")
    return problems


def validate_das_block(obj) -> list[str]:
    """Schema check for the bench `"das"` sub-object (the PeerDAS
    cell-proof sampling-matrix sweep `bench.py --worker das` emits);
    returns problems (empty == valid).  Pinned by `bench_smoke.py
    --das` and tests/test_das.py."""
    if not isinstance(obj, dict):
        return [f"das block is {type(obj).__name__}, not dict"]
    problems: list[str] = []
    matrix = obj.get("matrix")
    if not isinstance(matrix, dict):
        problems.append("'matrix' must be a dict")
    else:
        for key in ("columns", "blobs", "cells"):
            v = matrix.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                problems.append(f"matrix[{key!r}] must be a positive "
                                f"int, got {v!r}")
        if (isinstance(matrix.get("columns"), int)
                and isinstance(matrix.get("blobs"), int)
                and isinstance(matrix.get("cells"), int)
                and matrix["cells"] !=
                matrix["columns"] * matrix["blobs"]):
            problems.append("matrix['cells'] must equal columns * blobs")
    for key in ("verify_wall_s", "cells_per_s", "oracle_wall_s",
                "speedup"):
        v = obj.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or v <= 0:
            problems.append(f"{key!r} must be a positive number, "
                            f"got {v!r}")
    for key in ("oracle_cells_measured", "rung"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            problems.append(f"{key!r} must be a positive int, got {v!r}")
    if obj.get("batch_verdict") is not True:
        problems.append("'batch_verdict' must be True (the swept "
                        "matrix is valid by construction)")
    iso = obj.get("isolate")
    if not isinstance(iso, dict) or not isinstance(
            iso.get("isolated"), bool):
        problems.append("'isolate' must carry a bool 'isolated' (the "
                        "mixed-invalid recheck arc)")
    if not isinstance(obj.get("eval_crosscheck"), bool):
        problems.append("'eval_crosscheck' must be a bool (the coset "
                        "barycentric agreement check)")
    return problems


def validate_das_producer_block(obj) -> list[str]:
    """Schema check for the bench `"das_producer"` sub-object (the FK20
    producer + erasure-recovery sweep `bench.py --worker das` emits);
    returns problems (empty == valid).  Pinned by `bench_smoke.py
    --das` and tests/test_das.py."""
    if not isinstance(obj, dict):
        return [f"das_producer block is {type(obj).__name__}, not dict"]
    problems: list[str] = []
    for key in ("produce_wall_s", "produce_first_s", "proofs_per_s",
                "du_wall_s", "producer_speedup"):
        v = obj.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or v <= 0:
            problems.append(f"{key!r} must be a positive number, "
                            f"got {v!r}")
    v = obj.get("du_msms_measured")
    if not isinstance(v, int) or isinstance(v, bool) or v < 1:
        problems.append(f"'du_msms_measured' must be a positive int, "
                        f"got {v!r}")
    if obj.get("parity") is not True:
        problems.append("'parity' must be True (FK20 proofs byte-equal "
                        "the closed-form ground truth)")
    rec = obj.get("recover")
    if not isinstance(rec, dict):
        problems.append("'recover' must be a dict")
        return problems
    for key in ("wall_s", "oracle_wall_s", "speedup"):
        v = rec.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or v <= 0:
            problems.append(f"recover[{key!r}] must be a positive "
                            f"number, got {v!r}")
    for key in ("cells_in", "missing", "oracle_cosets_measured"):
        v = rec.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            problems.append(f"recover[{key!r}] must be a positive int, "
                            f"got {v!r}")
    if isinstance(rec.get("cells_in"), int) and rec["cells_in"] < 64:
        problems.append("recover['cells_in'] must be >= 64 (half the "
                        "extended blob — below that nothing is "
                        "recoverable)")
    if rec.get("roundtrip") is not True:
        problems.append("'recover.roundtrip' must be True (recovered "
                        "cells and proofs byte-equal the originals)")
    return problems


def validate_forkchoice_block(obj) -> list[str]:
    """Schema check for the bench `"forkchoice"` sub-object (the
    device LMD-GHOST sweep `bench.py --worker forkchoice` emits);
    returns problems (empty == valid).  Pinned by `bench_smoke.py
    --forkchoice` and tests/test_forkchoice.py."""
    if not isinstance(obj, dict):
        return [f"forkchoice block is {type(obj).__name__}, not dict"]
    problems: list[str] = []
    tree = obj.get("tree")
    if not isinstance(tree, dict):
        problems.append("'tree' must be a dict")
    else:
        for key in ("blocks", "validators", "messages"):
            v = tree.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                problems.append(f"tree[{key!r}] must be a positive "
                                f"int, got {v!r}")
    for key in ("apply_wall_s", "head_wall_s", "heads_per_s",
                "oracle_head_wall_s", "speedup"):
        v = obj.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or v <= 0:
            problems.append(f"{key!r} must be a positive number, "
                            f"got {v!r}")
    if not isinstance(obj.get("oracle_validators_measured"), int) \
            or isinstance(obj.get("oracle_validators_measured"), bool) \
            or obj.get("oracle_validators_measured") < 1:
        problems.append("'oracle_validators_measured' must be a "
                        "positive int")
    rungs = obj.get("rungs")
    if not isinstance(rungs, dict) or not all(
            isinstance(rungs.get(k), int) and not
            isinstance(rungs.get(k), bool) and rungs.get(k) >= 1
            for k in ("blocks", "validators", "batch")):
        problems.append("'rungs' must carry positive int "
                        "blocks/validators/batch ladder shapes")
    if obj.get("parity") is not True:
        problems.append("'parity' must be True (the device head must "
                        "match the spec oracle's on the swept tree)")
    return problems


def embed_bench_block(record: dict) -> dict:
    """The shared per-config bench protocol: attach the current
    `"telemetry"` block to a metric record and reset the per-config
    aggregates so the next config's counters start clean.  No-op while
    telemetry is off.  Used by both `bench.py` and `bench_bls.py` — one
    copy of the protocol."""
    if core.enabled():
        record["telemetry"] = bench_block()
        core.reset()
    return record
