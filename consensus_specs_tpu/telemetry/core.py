"""Process-global telemetry registry: spans, counters, histograms.

Gating contract: everything here is OFF unless `CST_TELEMETRY` is set to
a non-empty value other than "0" (or `CST_TRACE_FILE` names an output
path, which implies collection), and the disabled paths are engineered
to stay off the profile — `span()` returns a shared no-op context
manager and `count()`/`observe()` are a single global-flag check.  The
hot path (per-kernel dispatch in `ops.bls_batch`) therefore instruments
unconditionally and lets this module decide.

Enabled, the registry is a process singleton guarded by one lock:

- spans     nestable wall-time sections (thread-local nesting stack),
            aggregated by name and appended to a bounded trace-event
            buffer for the Chrome/Perfetto exporter; when jax is already
            imported, each span also enters a
            `jax.profiler.TraceAnnotation` so the same names line up in
            XLA device profiles (we never import jax ourselves — a
            telemetry layer must not initialize a backend).
- counters  monotonically increasing ints (routing decisions, lane
            accounting, cache stats).
- histograms count/total/min/max summaries of float samples (kernel
            compile-vs-run latencies, MSM sizes).

`first_call(key)` backs the compile-vs-run attribution: the first
dispatch of a given (kernel, padded-shape) pair pays trace+XLA-compile
(or a persistent-cache load), every later dispatch is pure run — so the
instrumentation routes the first wall sample to `kernel.compile_first_s`
and the rest to `kernel.run_s`, which is exactly the split the bench
JSON contract reports (`export.bench_block`).
"""

from __future__ import annotations

import os
import sys
import threading
import time

# trace-event buffer cap: ~100 bytes/event keeps worst case ~20 MB and
# bounds a runaway span loop; drops are counted, never silent
_MAX_EVENTS = 200_000

_lock = threading.Lock()
_tls = threading.local()

_T0 = time.perf_counter()   # chrome-trace timestamp origin (process)

_counters: dict[str, int] = {}
_hists: dict[str, dict] = {}
_spans: dict[str, dict] = {}
_events: list[dict] = []
_events_dropped = 0
_meta: dict[str, object] = {}
_first_keys: set[str] = set()
_gauges: dict[str, dict] = {}
_gauge_events: list[dict] = []
_gauge_events_dropped = 0


def _env_enabled() -> bool:
    if os.environ.get("CST_TELEMETRY", "0") not in ("", "0"):
        return True
    return bool(os.environ.get("CST_TRACE_FILE"))


_enabled = _env_enabled()
_trace_file = os.environ.get("CST_TRACE_FILE") or None
_atexit_registered = False


def _register_atexit() -> None:
    global _atexit_registered
    if _atexit_registered or not _trace_file:
        return
    _atexit_registered = True
    import atexit

    from .export import write_chrome_trace

    atexit.register(lambda: write_chrome_trace(_trace_file))


if _trace_file:
    _register_atexit()


def enabled() -> bool:
    """True when the registry is collecting (CST_TELEMETRY / CST_TRACE_FILE
    or an explicit `configure(enabled=True)`)."""
    return _enabled


def configure(enabled: bool | None = None,
              trace_file: str | None = None) -> None:
    """Programmatic override of the env gate (benches and tests).
    `trace_file` arms the atexit Chrome-trace writer and implies
    collection."""
    global _enabled, _trace_file
    if trace_file is not None:
        _trace_file = trace_file
        _enabled = True
        _register_atexit()
    if enabled is not None:
        _enabled = enabled


def reset(full: bool = False) -> None:
    """Clear the per-config aggregates (counters, histograms, span
    stats) — how the benches isolate per-config telemetry blocks.
    Process-level state survives by default: the trace-event timeline
    (the whole-process CST_TRACE_FILE export), the first-call keys
    (compile attribution is per-process — a kernel compiled during one
    config must not be re-counted as a compile by the next), and the
    meta entries (cache dir etc., recorded once at setup and owed to
    every config's export).  `full=True` wipes those too (test
    isolation).  The enabled flag and trace-file arming are always
    unaffected."""
    global _events_dropped, _gauge_events_dropped
    with _lock:
        _counters.clear()
        _hists.clear()
        _spans.clear()
        _gauges.clear()
        if full:
            _meta.clear()
            _events.clear()
            _first_keys.clear()
            _events_dropped = 0
            _gauge_events.clear()
            _gauge_events_dropped = 0
    if full:
        # cost records and watermarks are process-level facts (like the
        # first-call keys they attribute against): per-config resets
        # keep them, full test-isolation resets wipe them too
        from . import costmodel
        costmodel._reset_state()
        # request-trace lifecycle records follow the same rule: they
        # survive per-config resets (the Chrome-trace export is
        # whole-process), full resets wipe them and their id counters
        from . import reqtrace
        reqtrace._reset_state()
        # the live-monitoring layer is process-level too: a full reset
        # stops the SLO watchdog and the exposition endpoint so one
        # test's daemon threads never observe the next test's registry
        from . import metrics_export, monitor
        monitor._reset_state()
        metrics_export._reset_state()
        # the occupancy ledger and the incident event ring follow the
        # process-level rule as well: per-config resets keep them (a
        # run's incident evidence must survive its config loop), full
        # resets restore the env-derived gates and empty both
        from . import flightrec, occupancy
        occupancy._reset_state()
        flightrec._reset_state()


# --- recording primitives ---------------------------------------------------


def count(name: str, n: int = 1) -> None:
    if not _enabled:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def observe(name: str, value: float) -> None:
    if not _enabled:
        return
    v = float(value)
    with _lock:
        h = _hists.get(name)
        if h is None:
            _hists[name] = {"count": 1, "total": v, "min": v, "max": v}
        else:
            h["count"] += 1
            h["total"] += v
            if v < h["min"]:
                h["min"] = v
            if v > h["max"]:
                h["max"] = v


def gauge(name: str, value) -> None:
    """Point-in-time level sample (queue depth, in-flight batches):
    unlike `count` it can go DOWN, and unlike `observe` each sample is
    also a timeline event — the Chrome-trace exporter renders gauges as
    'C' (counter) tracks next to the device-memory watermarks, so a
    Perfetto capture of a serve run shows the queue breathing against
    the span timeline.  Aggregates (last/min/max/count) land in
    `snapshot()["gauges"]`."""
    if not _enabled:
        return
    v = float(value)
    t = time.perf_counter()
    global _gauge_events_dropped
    with _lock:
        g = _gauges.get(name)
        if g is None:
            _gauges[name] = {"last": v, "min": v, "max": v, "count": 1}
        else:
            g["last"] = v
            g["count"] += 1
            if v < g["min"]:
                g["min"] = v
            if v > g["max"]:
                g["max"] = v
        if len(_gauge_events) < _MAX_EVENTS:
            _gauge_events.append({"name": name, "value": v,
                                  "ts": (t - _T0) * 1e6})
        else:
            _gauge_events_dropped += 1


def set_meta(key: str, value) -> None:
    if not _enabled:
        return
    with _lock:
        _meta[key] = value


def counter_value(name: str, default: int = 0) -> int:
    """One counter's current value — cheap point read, no registry
    copy (use `snapshot()` for the full picture)."""
    with _lock:
        return _counters.get(name, default)


def span_seconds(name: str, default: float = 0.0) -> float:
    """One span aggregate's cumulative `total_s` — cheap point read.
    Backs delta accounting (tests/conftest.py reads `spec.build` before
    and after each test to split its wall into phases)."""
    with _lock:
        s = _spans.get(name)
        return s["total_s"] if s else default


def add_event(name: str, dur_s: float, **attrs) -> None:
    """Record an already-measured duration as if a span of that length
    just closed: aggregates under `name` and (buffer permitting) a
    trace event ending now, carrying `attrs` as args.  For derived
    timings that were never a live `span()` — e.g. the per-test
    spec-build/test-body phase split, computed from deltas after the
    test ran."""
    if not _enabled:
        return
    dur = max(float(dur_s), 0.0)
    t1 = time.perf_counter()
    global _events_dropped
    with _lock:
        s = _spans.get(name)
        if s is None:
            _spans[name] = {"count": 1, "total_s": dur,
                            "min_s": dur, "max_s": dur}
        else:
            s["count"] += 1
            s["total_s"] += dur
            if dur < s["min_s"]:
                s["min_s"] = dur
            if dur > s["max_s"]:
                s["max_s"] = dur
        if len(_events) < _MAX_EVENTS:
            _events.append({
                "name": name,
                "ts": (t1 - dur - _T0) * 1e6,   # µs, process-relative
                "dur": dur * 1e6,
                "tid": threading.get_ident() & 0x7FFFFFFF,
                "args": dict(attrs),
            })
        else:
            _events_dropped += 1


def first_call(key: str) -> bool:
    """True exactly once per key per process (per `reset(full=True)`):
    the compile-vs-run discriminator for jitted kernel dispatches.
    Disabled mode is a flag check returning False — no lock, no key
    growth — like every other recording primitive."""
    if not _enabled:
        return False
    with _lock:
        if key in _first_keys:
            return False
        _first_keys.add(key)
        return True


# --- spans ------------------------------------------------------------------


class _NullSpan:
    """Shared no-op context manager handed out while disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def _span_stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _trace_annotation(name: str):
    """A `jax.profiler.TraceAnnotation` when jax is ALREADY imported in
    this process, else None.  Importing jax from telemetry is forbidden:
    on the TPU image, first import can claim a pooled device."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return None


class _Span:
    __slots__ = ("name", "attrs", "t0", "ann", "parent")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.ann = None

    def __enter__(self):
        stack = _span_stack()
        self.parent = stack[-1] if stack else None
        stack.append(self.name)
        self.ann = _trace_annotation(self.name)
        if self.ann is not None:
            try:
                self.ann.__enter__()
            except Exception:
                self.ann = None
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        if self.ann is not None:
            try:
                self.ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        stack = _span_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        dur = t1 - self.t0
        global _events_dropped
        with _lock:
            s = _spans.get(self.name)
            if s is None:
                _spans[self.name] = {"count": 1, "total_s": dur,
                                     "min_s": dur, "max_s": dur}
            else:
                s["count"] += 1
                s["total_s"] += dur
                if dur < s["min_s"]:
                    s["min_s"] = dur
                if dur > s["max_s"]:
                    s["max_s"] = dur
            if len(_events) < _MAX_EVENTS:
                args = dict(self.attrs)
                if self.parent:
                    args["parent"] = self.parent
                if exc_type is not None:
                    args["error"] = exc_type.__name__
                _events.append({
                    "name": self.name,
                    "ts": (self.t0 - _T0) * 1e6,    # µs, process-relative
                    "dur": dur * 1e6,
                    "tid": threading.get_ident() & 0x7FFFFFFF,
                    "args": args,
                })
            else:
                _events_dropped += 1
        return False    # never swallow the exception


def span(name: str, **attrs):
    """Nestable wall-time section.  Usage:

        with telemetry.span("bls.batch_verify", lanes=128):
            ...

    Disabled mode returns one shared no-op object (no allocation)."""
    if not _enabled:
        return _NULL_SPAN
    return _Span(name, attrs)


# --- snapshot ---------------------------------------------------------------


def snapshot() -> dict:
    """Point-in-time copy of the whole registry.  Schema (stable; pinned
    by tests/test_telemetry.py):

        {"enabled": bool,
         "meta":       {str: json-able},
         "counters":   {str: int},
         "histograms": {str: {"count","total","min","max"}},
         "spans":      {str: {"count","total_s","min_s","max_s"}},
         "gauges":     {str: {"last","min","max","count"}},
         "events": int, "events_dropped": int,
         "costmodel": {"kernels": {...}, "watermarks": {...},
                       "wm_events": int, "wm_events_dropped": int},
         "occupancy": {"enabled","events","open_spans",
                       "events_dropped","live"}}
    """
    with _lock:
        snap = {
            "enabled": _enabled,
            "meta": dict(_meta),
            "counters": dict(_counters),
            "histograms": {k: dict(v) for k, v in _hists.items()},
            "spans": {k: dict(v) for k, v in _spans.items()},
            "gauges": {k: dict(v) for k, v in _gauges.items()},
            "events": len(_events),
            "events_dropped": _events_dropped,
        }
    # outside _lock: the cost-model and request-trace registries have
    # their own locks, and their snapshots must not nest under ours
    # (lock-order discipline)
    from . import costmodel, occupancy, reqtrace
    snap["costmodel"] = costmodel.raw_snapshot()
    snap["reqtrace"] = reqtrace.raw_snapshot()
    snap["occupancy"] = occupancy.raw_snapshot()
    return snap


def _events_copy() -> tuple[list[dict], int]:
    with _lock:
        return [dict(e) for e in _events], _events_dropped


def _gauge_events_copy() -> tuple[list[dict], int]:
    """Timeline gauge samples for the Chrome-trace exporter."""
    with _lock:
        return [dict(e) for e in _gauge_events], _gauge_events_dropped


def _save_state():
    """Deep copy of the whole registry (test support: the telemetry
    suite must reset the process-global registry without destroying the
    session-wide data a CST_TELEMETRY CI run is accumulating)."""
    with _lock:
        return (dict(_counters),
                {k: dict(v) for k, v in _hists.items()},
                {k: dict(v) for k, v in _spans.items()},
                [dict(e) for e in _events],
                dict(_meta),
                set(_first_keys),
                _events_dropped,
                {k: dict(v) for k, v in _gauges.items()},
                [dict(e) for e in _gauge_events],
                _gauge_events_dropped)


def _restore_state(state) -> None:
    global _events_dropped, _gauge_events_dropped
    (counters, hists, spans, events, meta, first_keys, dropped,
     gauges, gauge_events, gauge_dropped) = state
    with _lock:
        _counters.clear()
        _counters.update(counters)
        _hists.clear()
        _hists.update(hists)
        _spans.clear()
        _spans.update(spans)
        _events.clear()
        _events.extend(events)
        _meta.clear()
        _meta.update(meta)
        _first_keys.clear()
        _first_keys.update(first_keys)
        _events_dropped = dropped
        _gauges.clear()
        _gauges.update(gauges)
        _gauge_events.clear()
        _gauge_events.extend(gauge_events)
        _gauge_events_dropped = gauge_dropped
