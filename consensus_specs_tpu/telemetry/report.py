"""Benchwatch reporter: trends, ROADMAP threshold gates, attribution.

`python -m consensus_specs_tpu.telemetry.report` ingests every perf
artifact in the repo (BENCH/MULTICHIP round wrappers, oracle baselines,
the optional pytest telemetry snapshot), folds it into the longitudinal
store (`out/bench_history.jsonl`, see `telemetry.history`), and renders
one markdown dashboard:

- per-metric trend tables across rounds (value, speedup vs the
  pure-Python oracle, delta vs the previous round);
- the declarative ROADMAP threshold table (attestation >= 30x, sync
  aggregate >= 5x, `verify_blob_kzg_proof_batch` >= 2x, compile+first
  < 40s, tier-1 wall < 870s, multichip dryrun ok, serve steady-state
  throughput >= 10k verifies/s and p99 batch latency < 500ms — the
  sustained-load `serve::*` records `bench_serve.py` emits — plus the
  chaos-round gates: fault-stop → steady-state recovery < 60s and zero
  wrong results, from the `resilience::*` records; the mesh shard-loss
  gates — recovery < 60s, zero lost/wrong statements — from the
  `mesh::*` records; checkpoint restore+replay >= 5x over a full
  rebuild from the `checkpoint::*` records; and the mesh-sharded
  flagship gates — >= 70% per-chip throughput retention at the full
  mesh and the 8M-validator rung completing, from the `scaling::*`
  records; and the SLO watchdog gates — a zero-breach non-chaos serve
  round (`slo::clean_round`) and the chaos breach→clear arc
  (`resilience::slo_arc_ok`)) evaluated against the latest data;
- a generic round-over-round regression rule (no TPU metric may
  regress more than CST_BENCHWATCH_MAX_REGRESS_PCT percent);
- the `_MSM_DEVICE_MIN` break-even recommendation from the
  `g1_msm_breakeven_probe` rows;
- the Utilization section (CST_COSTMODEL rounds): per-kernel roofline
  table from the XLA cost/memory analysis records — flops, bytes,
  arithmetic intensity, achieved-vs-peak, compute/memory/launch-bound
  classification — plus the attestation compile-vs-execute verdict and
  per-device memory high-water marks;
- the tier-1 wall-time attribution table, split spec-build vs
  test-body per test (the conftest phase spans), naming the trim
  targets the ROADMAP asks for.

Exit code contract (what CI gates on): nonzero iff a round-over-round
regression fired, or — with `--strict` / CST_BENCHWATCH_STRICT=1 — any
ROADMAP threshold FAILs.  Without strict mode the threshold column is
advisory: the ROADMAP targets are acceptance criteria for the *next*
TPU round ("re-open per config if not met"), and several checked-in
rounds predate the kernels that are meant to meet them, so hard-gating
every CI run on them would just mean a permanently red gate.

Adding a threshold for a new metric = one entry in `THRESHOLDS`
(regex over metric names, field, op, target); the README's Benchwatch
section documents the columns.

Stdlib-only; safe to run anywhere, never imports jax.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
from pathlib import Path

from . import history

# --- declarative threshold table --------------------------------------------
#
# field: which record field is compared ("vs_baseline" | "value").
# tpu_only: evaluate only against TPU-platform records (the ROADMAP
#   speedup targets are TPU acceptance criteria; a CPU smoke round must
#   read "no data", not FAIL).
# op: ">=" (bigger is better) or "<" (smaller is better).

THRESHOLDS = (
    {"id": "attestation-speedup",
     "title": "#2 attestation batch vs oracle",
     "metric": r"attestation_batch_\d+x\d+_verify_wall",
     "field": "vs_baseline", "op": ">=", "target": 30.0, "tpu_only": True},
    {"id": "sync-aggregate-speedup",
     "title": "#3 sync aggregate vs oracle",
     "metric": r"sync_aggregate_\d+_verify_wall",
     "field": "vs_baseline", "op": ">=", "target": 5.0, "tpu_only": True},
    {"id": "kzg-batch-speedup",
     "title": "#5 verify_blob_kzg_proof_batch vs oracle",
     "metric": r"blob_kzg_proof_batch_\d+_verify_wall",
     "field": "vs_baseline", "op": ">=", "target": 2.0, "tpu_only": True},
    {"id": "attestation-compile-first",
     "title": "attestation compile+first wall",
     "metric": r"attestation_batch_compile_first_s",
     "field": "value", "op": "<", "target": 40.0, "tpu_only": True},
    {"id": "tier1-wall",
     "title": "tier-1 suite wall budget",
     "metric": r"tier1_wall_s",
     "field": "value", "op": "<", "target": 870.0, "tpu_only": False},
    {"id": "multichip",
     "title": "multichip dryrun healthy",
     "metric": r"multichip_dryrun_ok",
     "field": "value", "op": ">=", "target": 1.0, "tpu_only": False},
    # the serving subsystem's production claim (ROADMAP sustained-load
    # item): steady-state throughput orders of magnitude past the
    # EdDSA-vs-BLS per-core baseline, with bounded tail latency.  TPU
    # acceptance criteria — the CPU smoke's closed-loop rate reads
    # "no data" here, not FAIL.
    {"id": "serve-throughput",
     "title": "serve steady-state verifies/sec",
     "metric": r"serve::verifies_per_s",
     "field": "value", "op": ">=", "target": 10000.0, "tpu_only": True},
    {"id": "serve-p99",
     "title": "serve p99 batch latency (ms)",
     "metric": r"serve::p99_ms",
     "field": "value", "op": "<", "target": 500.0, "tpu_only": True},
    # tail-latency attribution (request tracing, CST_TRACE_REQUESTS):
    # the advisory decomposition row behind serve-p99 — if more than
    # half of the p99 tail's wall is QUEUE WAIT, the service is
    # under-batched/under-pumped (an arrival/scheduling problem), not
    # device-bound, and kernel work won't move the p99.  TPU-gated like
    # the serve rows: the CPU smoke's closed-loop drive intentionally
    # saturates the queue, so its queue fraction is a property of the
    # drive, not the service.
    {"id": "serve-p99-queue-frac",
     "title": "serve p99 tail: queue-wait fraction (advisory)",
     "metric": r"latency::p99_queue_frac",
     "field": "value", "op": "<", "target": 0.5, "tpu_only": True},
    # incremental merkleization (ROADMAP stateless-client item): the
    # persisted-layer dirty-path re-hash must beat a full re-merkleize
    # by >= 5x at 1% dirty — measurable on the CPU smoke (the ratio is
    # shape-, not platform-, bound), so not TPU-gated.
    {"id": "merkle-incremental-speedup",
     "title": "incremental vs full re-merkleize @ 1% dirty",
     "metric": r"merkle_incr::update@frac0\.01",
     "field": "vs_baseline", "op": ">=", "target": 5.0, "tpu_only": False},
    # resilience (chaos rounds, CST_SERVE_CHAOS=1): after an active
    # fault plan stops firing, the service must return to steady state
    # within a bounded wall — and must have answered every checked
    # request correctly while degraded (the breaker/oracle-fallback
    # path).  Shape-, not platform-, bound: evaluated on the CPU chaos
    # smoke too.
    {"id": "chaos-recovered",
     "title": "chaos round: service returned to steady state",
     "metric": r"resilience::recovered",
     "field": "value", "op": ">=", "target": 1.0, "tpu_only": False},
    {"id": "chaos-recovery",
     "title": "chaos round: fault-stop → steady-state recovery (s)",
     "metric": r"resilience::recovery_latency_s",
     "field": "value", "op": "<", "target": 60.0, "tpu_only": False},
    {"id": "chaos-correctness",
     "title": "chaos round: wrong verification results",
     "metric": r"resilience::wrong_results",
     "field": "value", "op": "<", "target": 1.0, "tpu_only": False},
    # the live SLO watchdog (CST_SLO_RULES): a healthy serve round must
    # end with ZERO breaches (the slo::clean_round 0/1 record is only
    # mined from NON-chaos rounds — a chaos round breaches by design),
    # and a chaos round must walk the full arc: breach inside the fault
    # window, clear after recovery (resilience::slo_arc_ok).  Both are
    # shape-, not platform-, bound.
    {"id": "slo-clean-round",
     "title": "SLO watchdog: clean serve round (zero breaches)",
     "metric": r"slo::clean_round",
     "field": "value", "op": ">=", "target": 1.0, "tpu_only": False},
    {"id": "chaos-slo-arc",
     "title": "SLO watchdog: chaos breach→clear arc completed",
     "metric": r"resilience::slo_arc_ok",
     "field": "value", "op": ">=", "target": 1.0, "tpu_only": False},
    # mesh resilience (PR 9): a device_loss against the sharded verify
    # path must re-bucket onto the survivors within a bounded wall and
    # lose ZERO statements — CI-testable on the 8-host-device simulated
    # mesh (`make chaos-mesh-smoke`), so not TPU-gated.
    {"id": "mesh-recovered",
     "title": "mesh chaos: every shard loss produced a recovered verdict",
     "metric": r"mesh::recovered",
     "field": "value", "op": ">=", "target": 1.0, "tpu_only": False},
    {"id": "mesh-recovery",
     "title": "mesh chaos: shard-loss → recovered verdict (s)",
     "metric": r"mesh::recovery_latency_s",
     "field": "value", "op": "<", "target": 60.0, "tpu_only": False},
    # two rows, not one alternation: the threshold engine evaluates
    # ONE latest record per row, and the two metrics are emitted by the
    # same round with the same timestamp — an alternation would gate
    # whichever record happened to sort first and silently ignore the
    # other
    {"id": "mesh-lost-statements",
     "title": "mesh chaos: statements dropped by a shard loss",
     "metric": r"mesh::lost_statements",
     "field": "value", "op": "<", "target": 1.0, "tpu_only": False},
    {"id": "mesh-wrong-results",
     "title": "mesh chaos: statements answered wrong while degraded",
     "metric": r"mesh::wrong_results",
     "field": "value", "op": "<", "target": 1.0, "tpu_only": False},
    # mesh-sharded flagship scaling (the partition-registry epoch
    # pipeline): per-chip throughput at the full mesh must retain >=
    # 70% of the single-chip per-chip throughput at the same per-chip
    # shard size (weak scaling), and the 8M-validator rung must
    # complete without OOM.  TPU acceptance criteria — the CPU shard
    # smoke's simulated 8-host-device numbers read "no data" here.
    {"id": "scaling-efficiency",
     "title": "per-chip throughput retention at full mesh",
     "metric": r"scaling::efficiency",
     "field": "value", "op": ">=", "target": 0.70, "tpu_only": True},
    {"id": "flagship-8m",
     "title": "8M-validator flagship rung completes (no OOM)",
     "metric": r"scaling::flagship_8m_ok",
     "field": "value", "op": ">=", "target": 1.0, "tpu_only": True},
    # DAS / PeerDAS (the batched cell-proof workload): the device
    # route over a full 128-column sampling matrix must beat the
    # pure-Python fulu oracle >= 2x — the oracle pays a Lagrange
    # interpolation per cell, so the ratio is shape-bound and
    # CPU-evaluable (the smoke measures it at 128x8).  Absolute
    # throughput is a chip number: cells/s stays TPU-gated for the
    # next round.
    {"id": "das-speedup",
     "title": "DAS cell-proof batch vs pure-Python oracle",
     "metric": r"das::speedup",
     "field": "value", "op": ">=", "target": 2.0, "tpu_only": False},
    {"id": "das-throughput",
     "title": "DAS sampling-matrix throughput (cells/s)",
     "metric": r"das::cells_per_s",
     "field": "value", "op": ">=", "target": 20000.0, "tpu_only": True},
    # the producer side (PR 16): FK20 must beat the D_u partial route
    # >= 4x on full-matrix proof production, and the device erasure
    # decode + re-prove must beat the pure-Python oracle >= 2x.  Both
    # ratios are shape-bound (the D_u route pays ~64 large MSMs the
    # FK20 FFTs collapse; the oracle re-proves 128 cosets in python),
    # so both rows are CPU-evaluable.
    {"id": "das-producer-speedup",
     "title": "FK20 proof producer vs the D_u MSM route",
     "metric": r"das::producer_speedup",
     "field": "value", "op": ">=", "target": 4.0, "tpu_only": False},
    {"id": "das-recover-speedup",
     "title": "device erasure recovery vs pure-Python oracle",
     "metric": r"das::recover_speedup",
     "field": "value", "op": ">=", "target": 2.0, "tpu_only": False},
    # fork choice (the device LMD-GHOST proto-array store): batched
    # latest-message folding + pointer-jumping head selection must
    # beat the phase0 spec oracle's get_head >= 2x — the oracle pays a
    # python walk over every validator per child, so the ratio is
    # shape-bound and CPU-evaluable (the fc smoke measures it at the
    # tiny matrix).  Absolute head throughput is a chip number: the
    # heads/s row stays TPU-gated for the next round.
    {"id": "fc-speedup",
     "title": "fork-choice head vs phase0 spec oracle",
     "metric": r"forkchoice::speedup",
     "field": "value", "op": ">=", "target": 2.0, "tpu_only": False},
    {"id": "fc-head-throughput",
     "title": "fork-choice head polls per second",
     "metric": r"forkchoice::heads_per_s",
     "field": "value", "op": ">=", "target": 100.0, "tpu_only": True},
    # checkpoint restore (PR 9): snapshot + journal replay must beat
    # the full O(N) re-merkleize >= 5x at <= 1% journal depth (the
    # speedup rides the restore record's vs_baseline).  Shape-, not
    # platform-, bound — evaluated on the CPU chaos smoke.
    {"id": "checkpoint-restore",
     "title": "checkpoint restore+replay vs full rebuild",
     "metric": r"checkpoint::restore",
     "field": "vs_baseline", "op": ">=", "target": 5.0,
     "tpu_only": False},
    # device occupancy (PR 20): the depth-pipelined serve loop must
    # keep the chip busy >= 70% of the measured wall on the pod round —
    # the complementary fleet-side number to the per-kernel roofline
    # table.  A CPU smoke's busy_frac measures interpreter overhead,
    # not pipeline health, so the row is TPU-gated; the smoke instead
    # pins the ledger's accounting (busy + bubbles == wall).
    {"id": "serve-occupancy",
     "title": "serve device busy fraction under sustained load",
     "metric": r"pipeline::busy_frac",
     "field": "value", "op": ">=", "target": 0.70, "tpu_only": True},
)

FLAGSHIP = "mainnet_epoch_sweep_1m_validators_wall"


def _platform_group(rec: dict) -> str:
    """Records from the historical TPU driver rounds predate the
    `platform` field — group them with explicit TPU records."""
    p = rec.get("platform")
    if p is None or str(p).startswith("tpu"):
        return "tpu"
    return str(p)


def _order_key(rec: dict):
    """Rounds first (by number), then live emissions (by timestamp) —
    'latest' and 'previous' mean the same thing everywhere."""
    rnd = rec.get("round")
    return (0, rnd, 0.0) if isinstance(rnd, int) \
        else (1, 0, float(rec.get("ts") or 0.0))


def _by_metric(records) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    for rec in records:
        out.setdefault(rec["metric"], []).append(rec)
    for series in out.values():
        series.sort(key=_order_key)
    return out


def _where(rec: dict) -> str:
    if isinstance(rec.get("round"), int):
        return f"round {rec['round']}"
    if rec.get("ts"):
        return time.strftime("%Y-%m-%d %H:%M",
                             time.localtime(rec["ts"]))
    return rec.get("file", "?")


# --- threshold evaluation ----------------------------------------------------


def evaluate_thresholds(records) -> list[dict]:
    """One row per THRESHOLDS entry: the latest eligible measurement
    and its PASS / FAIL / 'no data' status."""
    rows = []
    for th in THRESHOLDS:
        pattern = re.compile(th["metric"] + r"\Z")
        candidates = [
            r for r in records
            if pattern.match(r["metric"])
            and isinstance(r.get(th["field"]), (int, float))
            and (not th["tpu_only"] or _platform_group(r) == "tpu")
        ]
        row = dict(th, status="no data", observed=None, where=None)
        if candidates:
            latest = max(candidates, key=_order_key)
            observed = float(latest[th["field"]])
            ok = observed >= th["target"] if th["op"] == ">=" \
                else observed < th["target"]
            row.update(status="PASS" if ok else "FAIL",
                       observed=observed, where=_where(latest),
                       metric_name=latest["metric"])
        rows.append(row)
    return rows


# --- round-over-round regression rule ----------------------------------------


def _comparable_oracles(prev: dict, cur: dict) -> bool:
    """vs_baseline numbers only compare across rounds when both divided
    by the same kind of oracle measurement.  The flagship rounds carry
    the oracle fingerprint (us/validator) mined from the round tail;
    fingerprints within 2x mean 'same oracle', a missing fingerprint on
    one side means the baseline was re-measured or the tail was
    truncated — fall back to raw wall."""
    fa = prev.get("baseline_us_per_validator")
    fb = cur.get("baseline_us_per_validator")
    if fa and fb:
        return 0.5 <= fa / fb <= 2.0
    return fa is None and fb is None


def find_regressions(records, max_regress_pct: float) -> list[dict]:
    """Latest-vs-previous comparison per TPU metric.  A drop in
    vs_baseline (comparable oracles) or a rise in wall seconds beyond
    `max_regress_pct` is a regression.  <= 0 disables the rule."""
    if max_regress_pct <= 0:
        return []
    regressions = []
    for metric, series in sorted(_by_metric(records).items()):
        series = [r for r in series
                  if _platform_group(r) == "tpu"
                  and r.get("unit") != "bool"
                  and isinstance(r.get("value"), (int, float))]
        if len(series) < 2:
            continue
        prev, cur = series[-2], series[-1]
        pv, cv = prev.get("vs_baseline"), cur.get("vs_baseline")
        if isinstance(pv, (int, float)) and isinstance(cv, (int, float)) \
                and pv > 0 and _comparable_oracles(prev, cur):
            change_pct = (cv - pv) / pv * 100.0
            if change_pct < -max_regress_pct:
                regressions.append({
                    "metric": metric,
                    "kind": "vs_baseline",
                    "prev": pv, "cur": cv,
                    "change_pct": round(change_pct, 1),
                    "prev_where": _where(prev), "cur_where": _where(cur),
                })
            continue
        if prev["value"] > 0:
            change_pct = (cur["value"] - prev["value"]) / prev["value"] * 100.0
            if change_pct > max_regress_pct:
                regressions.append({
                    "metric": metric,
                    "kind": "wall",
                    "prev": prev["value"], "cur": cur["value"],
                    "change_pct": round(change_pct, 1),
                    "prev_where": _where(prev), "cur_where": _where(cur),
                })
    return regressions


# --- _MSM_DEVICE_MIN recommendation ------------------------------------------


def msm_recommendation(records) -> dict:
    """Close the ROADMAP measurement loop: from the latest
    `g1_msm_breakeven_probe` detail rows, the smallest batch size where
    the device kernel beats the host oracle (host_over_device > 1), or
    'keep the current threshold' when no size wins."""
    probes = [r for r in records
              if r["metric"].startswith("g1_msm_breakeven_probe")
              and isinstance(r.get("detail"), dict)]
    if not probes:
        return {"status": "no data",
                "text": ("no `g1_msm_breakeven_probe` rows ingested yet — "
                         "run `bench_bls.py` with CST_TELEMETRY=1 on the "
                         "TPU to produce them")}
    # the routing decision is for the TPU: a real-chip probe always
    # outranks a CPU smoke probe, however recent the smoke run
    tpu_probes = [r for r in probes if _platform_group(r) == "tpu"]
    latest = max(tpu_probes or probes, key=_order_key)
    current = latest.get("msm_device_min", 16)
    sizes = []
    for n, d in latest["detail"].items():
        try:
            n = int(n)
        except (TypeError, ValueError):
            continue
        ratio = d.get("host_over_device") if isinstance(d, dict) else None
        if isinstance(ratio, (int, float)):
            sizes.append((n, float(ratio), d.get("routed")))
    sizes.sort()
    wins = [n for n, ratio, _ in sizes if ratio > 1.0]
    if wins:
        # assuming win/loss is monotone in n, the right threshold is the
        # smallest winning size — below current means small MSMs are
        # being left on the host that the device would win, ABOVE
        # current means sizes in [current, suggested) are routed to a
        # device that measurably loses there
        suggested = min(wins)
        if suggested < current:
            status = "lower"
            verdict = (f"suggest `_MSM_DEVICE_MIN = {suggested}` — "
                       f"device beats host from n={suggested} "
                       f"(currently {current})")
        elif suggested == current:
            status = "keep"
            verdict = (f"keep {current} — device wins from exactly "
                       f"n={current}, the threshold is right")
        else:
            status = "raise"
            verdict = (f"suggest `_MSM_DEVICE_MIN = {suggested}` — "
                       f"device only wins from n={suggested}, but "
                       f"n>={current} already routes to the device "
                       f"where the host measures faster")
    else:
        suggested = None
        verdict = (f"keep {current} — no device win observed at any "
                   f"probed size")
        status = "keep"
    if _platform_group(latest) != "tpu":
        verdict += (" (CPU probe only — the routing decision needs a "
                    "TPU round to confirm)")
    return {"status": status, "suggested": suggested, "current": current,
            "where": _where(latest), "platform": _platform_group(latest),
            "sizes": [{"n": n, "host_over_device": r, "routed": routed}
                      for n, r, routed in sizes],
            "text": verdict}


# --- kernel utilization (cost model) -----------------------------------------


_ATT_METRIC_RE = re.compile(r"attestation_batch_\d+x\d+_verify_wall\Z")


def collect_utilization(records) -> dict:
    """The cost-model read side: latest joined roofline record per
    kernel (`costmodel`-source records; TPU rounds outrank CPU smoke,
    same precedence as the MSM probe), latest per-device memory
    high-water marks, and the attestation compile-vs-execute verdict
    rendered from the latest attestation round's measured split.
    Malformed costmodel fields are skipped with a counted warning
    (`warnings` key), never a crash — CST_COSTMODEL rounds must degrade
    like every other benchwatch input."""
    warnings: list[str] = []
    by_kernel: dict[str, list[dict]] = {}
    watermarks: dict[str, list[dict]] = {}
    for r in records:
        if r.get("source") != "costmodel":
            continue
        metric = r["metric"]
        if metric.startswith("costmodel::"):
            cm = r.get("costmodel")
            if not isinstance(cm, dict) or not isinstance(
                    cm.get("flops"), (int, float)):
                warnings.append(
                    f"costmodel record {metric!r} has a malformed "
                    f"cost block — skipped")
                continue
            by_kernel.setdefault(metric[len("costmodel::"):],
                                 []).append(r)
        elif metric.startswith("device_mem_high_water::"):
            watermarks.setdefault(metric[len("device_mem_high_water::"):],
                                  []).append(r)

    def latest_preferring_tpu(series):
        series.sort(key=_order_key)
        tpu = [r for r in series if _platform_group(r) == "tpu"]
        return (tpu or series)[-1]

    kernels = {}
    for kernel, series in sorted(by_kernel.items()):
        rec = latest_preferring_tpu(series)
        kernels[kernel] = dict(rec["costmodel"],
                               where=_where(rec),
                               platform=_platform_group(rec))
    wm_rows = {}
    for dev, series in sorted(watermarks.items()):
        rec = latest_preferring_tpu(series)
        wm_rows[dev] = {"high_water_bytes": rec.get("value"),
                        "samples": rec.get("samples"),
                        "where": _where(rec)}

    # compile-vs-execute verdict for the attestation path (the ROADMAP's
    # "is the 81s compile- or execute-bound?" question), from the latest
    # attestation record that embeds the measured split — TPU rounds
    # outrank the CI CPU smoke here too, else the smoke round appended
    # before every report would always override the real chip's answer
    verdict = None
    att = [r for r in records
           if _ATT_METRIC_RE.match(r.get("metric", ""))
           and isinstance(r.get("telemetry"), dict)
           and isinstance(r["telemetry"].get("compile_s"), (int, float))
           and isinstance(r["telemetry"].get("run_s"), (int, float))]
    if att:
        latest = latest_preferring_tpu(att)
        tel = latest["telemetry"]
        c, x = float(tel["compile_s"]), float(tel["run_s"])
        if x > 0 and c > 0:
            ratio = c / x
            kind = "compile-bound" if ratio >= 2.0 else (
                "execute-bound" if ratio <= 0.5 else "balanced")
            verdict = {
                "kind": kind, "compile_s": c, "run_s": x,
                "ratio": round(ratio, 1), "where": _where(latest),
                "platform": _platform_group(latest),
                "text": (f"{kind}: trace+XLA-compile {c:g}s vs "
                         f"steady-state execute {x:g}s per round "
                         f"({ratio:.1f}x) at {_where(latest)}"),
            }
    return {"kernels": kernels, "watermarks": wm_rows,
            "verdict": verdict, "warnings": warnings}


# --- markdown rendering ------------------------------------------------------


def _fmt(v, nd=4) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{round(v, nd):g}"
    return str(v)


def _cell(rec: dict | None) -> str:
    if rec is None:
        return "—"
    if rec.get("value") is None:
        return "fail" if rec.get("error") else "—"
    s = f"{_fmt(rec['value'])}{'' if rec.get('unit') == 'bool' else ' s'}"
    if isinstance(rec.get("vs_baseline"), (int, float)):
        s += f" ({_fmt(rec['vs_baseline'], 1)}x)"
    return s


def render_trend_tables(records) -> list[str]:
    lines: list[str] = []
    round_recs = [r for r in records
                  if r["source"] in ("bench_round", "multichip_round")]
    rounds = sorted({r["round"] for r in round_recs
                     if isinstance(r.get("round"), int)})
    if rounds:
        lines.append("## Round trends\n")
        lines.append("Cells are `wall (speedup vs the pure-Python "
                     "oracle)`; Δ compares the last two measured "
                     "rounds.\n")
        header = "| metric | " + " | ".join(f"r{n:02d}" for n in rounds) \
            + " | Δ last |"
        lines.append(header)
        lines.append("|---" * (len(rounds) + 2) + "|")
        for metric, series in sorted(_by_metric(round_recs).items()):
            per_round = {r["round"]: r for r in series
                         if isinstance(r.get("round"), int)}
            cells = [_cell(per_round.get(n)) for n in rounds]
            measured = [per_round[n] for n in rounds
                        if n in per_round
                        and isinstance(per_round[n].get("value"),
                                       (int, float))]
            delta = "—"
            if len(measured) >= 2 and series[0].get("unit") != "bool":
                prev, cur = measured[-2], measured[-1]
                pv, cv = prev.get("vs_baseline"), cur.get("vs_baseline")
                if isinstance(pv, (int, float)) \
                        and isinstance(cv, (int, float)) and pv > 0 \
                        and _comparable_oracles(prev, cur):
                    delta = f"{(cv - pv) / pv * 100.0:+.1f}% speedup"
                elif prev["value"] > 0:
                    pct = ((cur["value"] - prev["value"])
                           / prev["value"] * 100.0)
                    delta = f"{pct:+.1f}% wall"
            lines.append(f"| `{metric}` | " + " | ".join(cells)
                         + f" | {delta} |")
        lines.append("")

    emits = [r for r in records if r["source"] == "bench_emit"]
    if emits:
        lines.append("## Live emissions (CST_BENCHWATCH_HISTORY)\n")
        lines.append("| metric | platform | latest | when |")
        lines.append("|---|---|---|---|")
        for metric, series in sorted(_by_metric(emits).items()):
            latest = series[-1]
            lines.append(f"| `{metric}` | {latest.get('platform', '—')} "
                         f"| {_cell(latest)} | {_where(latest)} |")
        lines.append("")

    oracles = [r for r in records if r["source"] == "baseline"]
    if oracles:
        lines.append("## Oracle baselines (pure-Python costs the "
                     "speedups divide by)\n")
        lines.append("| metric | value | measured |")
        lines.append("|---|---|---|")
        for rec in sorted(oracles, key=lambda r: r["metric"]):
            lines.append(f"| `{rec['metric']}` | {_fmt(rec['value'])} "
                         f"{rec['unit']} | {rec.get('measured_at', '—')} |")
        lines.append("")
    return lines


def render_thresholds(rows, strict: bool) -> list[str]:
    lines = ["## ROADMAP thresholds\n"]
    mode = ("**strict** — any FAIL fails the run" if strict
            else "advisory — only regressions gate the exit code "
                 "(promote with CST_BENCHWATCH_STRICT=1)")
    lines.append(f"Gate mode: {mode}.\n")
    lines.append("| threshold | target | observed | where | status |")
    lines.append("|---|---|---|---|---|")
    for row in rows:
        target = (f"{row['field']} {row['op']} {_fmt(row['target'], 1)}")
        observed = "—" if row["observed"] is None \
            else _fmt(row["observed"], 2)
        mark = {"PASS": "✅ PASS", "FAIL": "❌ FAIL",
                "no data": "— no data"}[row["status"]]
        lines.append(f"| {row['title']} | {target} | {observed} "
                     f"| {row['where'] or '—'} | {mark} |")
    lines.append("")
    return lines


def render_regressions(regressions, max_regress_pct) -> list[str]:
    lines = ["## Round-over-round regressions\n"]
    if max_regress_pct <= 0:
        lines.append("Regression rule disabled "
                     "(CST_BENCHWATCH_MAX_REGRESS_PCT <= 0).\n")
        return lines
    if not regressions:
        lines.append(f"None — no TPU metric regressed more than "
                     f"{_fmt(max_regress_pct, 1)}% against its previous "
                     f"round.\n")
        return lines
    lines.append("| metric | compared | previous | current | change |")
    lines.append("|---|---|---|---|---|")
    for r in regressions:
        lines.append(
            f"| `{r['metric']}` | {r['kind']} "
            f"({r['prev_where']} → {r['cur_where']}) "
            f"| {_fmt(r['prev'], 2)} | {_fmt(r['cur'], 2)} "
            f"| {r['change_pct']:+.1f}% |")
    lines.append("")
    return lines


def _si(v, unit="") -> str:
    """1234567 -> '1.23 M'; keeps the roofline table readable."""
    if v is None:
        return "—"
    v = float(v)
    for thresh, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"),
                           (1e3, "k")):
        if abs(v) >= thresh:
            return f"{v / thresh:.2f} {suffix}{unit}"
    return f"{v:g} {unit}".rstrip()


def render_utilization(util: dict, msm: dict) -> list[str]:
    lines = ["## Utilization (kernel cost model)\n"]
    kernels = util["kernels"]
    if not kernels:
        lines.append("No cost-model data — run a bench round with "
                     "`CST_TELEMETRY=1 CST_COSTMODEL=1` to capture "
                     "per-kernel XLA cost/memory analysis and re-run "
                     "the report.\n")
        return lines
    advisory = any("advisory" in str(k.get("peak_source", ""))
                   for k in kernels.values())
    lines.append("Per-kernel roofline: XLA `cost_analysis()` flop/byte "
                 "budgets joined with the measured steady-state wall; "
                 "achieved-vs-peak against the per-backend peak "
                 "registry (`BASELINE.json` `\"peaks\"`)."
                 + ("  CPU peaks are ADVISORY — utilization ranks "
                    "kernels against each other, not the hardware."
                    if advisory else "") + "\n")
    lines.append("| kernel | flops | bytes | AI (flop/B) | "
                 "FLOP/s (% peak) | B/s (% peak) | run (mean) | "
                 "bound | where |")
    lines.append("|---|---|---|---|---|---|---|---|---|")
    for name, k in sorted(kernels.items()):
        fl = _si(k.get("achieved_flops_per_s"))
        bw = _si(k.get("achieved_bytes_per_s"), "B")
        uf = k.get("util_flops_pct")
        ub = k.get("util_bw_pct")
        run = k.get("run_s_mean")
        lines.append(
            f"| `{name}` | {_si(k.get('flops'))} "
            f"| {_si(k.get('bytes_accessed'), 'B')} "
            f"| {_fmt(k.get('arithmetic_intensity'), 2)} "
            f"| {fl}{'' if uf is None else f' ({uf:g}%)'} "
            f"| {bw}{'' if ub is None else f' ({ub:g}%)'} "
            f"| {'—' if run is None else f'{run:g} s'} "
            f"| **{k.get('bound', 'unknown')}** "
            f"| {k.get('where', '—')} |")
    lines.append("")

    verdict = util["verdict"]
    lines.append("### Attestation compile-vs-execute\n")
    if verdict:
        lines.append(f"**{verdict['text']}** (platform "
                     f"{verdict['platform']}).\n")
    else:
        lines.append("No attestation round with an embedded "
                     "compile_s/run_s split ingested yet.\n")

    launch_msms = [n for n, k in sorted(kernels.items())
                   if ("msm" in n.lower() and k.get("bound") == "launch")]
    lines.append("### `_MSM_DEVICE_MIN` cross-check\n")
    if launch_msms:
        names = ", ".join(f"`{n}`" for n in launch_msms)
        lines.append(
            f"{names}: launch-overhead-bound at the probed shape — the "
            f"kernel's roofline legs explain almost none of its wall, "
            f"so small-n routing is a dispatch-overhead question, not "
            f"a throughput one.  Read together with the break-even "
            f"probe above (status: {msm.get('status', 'no data')}).\n")
    elif any("msm" in n.lower() for n in kernels):
        lines.append("No MSM kernel classifies launch-bound at the "
                     "captured shapes — the break-even probe's "
                     "host/device walls are the deciding signal.\n")
    else:
        lines.append("No MSM kernel cost records captured yet.\n")

    if util["watermarks"]:
        lines.append("### Device-memory watermarks\n")
        lines.append("| device | high water | samples | where |")
        lines.append("|---|---|---|---|")
        for dev, wm in sorted(util["watermarks"].items()):
            lines.append(f"| `{dev}` | {_si(wm['high_water_bytes'], 'B')} "
                         f"| {wm.get('samples') or '—'} "
                         f"| {wm.get('where', '—')} |")
        lines.append("")
    return lines


def render_resilience(records) -> list[str]:
    """The chaos-round read side: latest `resilience::*` records (one
    row per metric) plus the latest round's breaker/heal summary from
    the compact block riding the recovery-latency record."""
    lines = ["## Resilience (chaos rounds)\n"]
    recs = [r for r in records
            if r.get("source") in ("resilience", "mesh", "checkpoint")]
    if not recs:
        lines.append("No resilience records — run a chaos round "
                     "(`CST_SERVE_CHAOS=1 make serve` / "
                     "`make chaos-smoke`, mesh arc: "
                     "`make chaos-mesh-smoke`) to exercise fault "
                     "injection, breaker/fallback degraded mode, "
                     "shard-loss recovery, checkpoint restore, and "
                     "recovery-to-steady.\n")
        return lines
    lines.append("| metric | latest | where |")
    lines.append("|---|---|---|")
    latest_by_metric = {}
    for metric, series in sorted(_by_metric(recs).items()):
        latest = series[-1]
        latest_by_metric[metric] = latest
        val = "—" if latest.get("value") is None else \
            f"{_fmt(latest['value'])} {latest.get('unit', '')}".rstrip()
        lines.append(f"| `{metric}` | {val} | {_where(latest)} |")
    lines.append("")
    rec = latest_by_metric.get("resilience::recovery_latency_s")
    compact = rec.get("resilience") if rec else None
    if isinstance(compact, dict):
        recovered = compact.get("recovered")
        sites = ", ".join(f"{k}: {v}" for k, v in sorted(
            (compact.get("injected_sites") or {}).items())) or "—"
        lines.append(
            f"Latest chaos round: {compact.get('faults_injected', '?')} "
            f"fault(s) injected ({sites}), "
            f"{compact.get('wrong_results', '?')} wrong result(s) over "
            f"{compact.get('checked_results', '?')} checked, "
            f"{compact.get('retries', 0)} retried / "
            f"{compact.get('fallbacks', 0)} oracle-fallback / "
            f"{compact.get('shed', 0)} shed; breaker trips: "
            f"{compact.get('breaker_trips', 0)}, final states: "
            f"{compact.get('breaker_states') or {}}; "
            f"{'recovered' if recovered else 'DID NOT RECOVER'}.\n")
        fv = compact.get("fault_victims")
        if isinstance(fv, dict):
            lines.append(
                f"Blast radius (request tracing): {fv.get('count', 0)} "
                f"victim request(s) — outcomes "
                f"{fv.get('outcomes') or {}}; "
                f"{fv.get('clean_ok', 0)} settled clean "
                f"(must be 0 — a fault-hit handle recovers as "
                f"retry/fallback or poisons, never silently).\n")
    mrec = latest_by_metric.get("mesh::recovery_latency_s")
    mesh = mrec.get("mesh") if mrec else None
    if isinstance(mesh, dict):
        lines.append(
            f"Latest mesh segment: {mesh.get('devices', '?')} devices, "
            f"{mesh.get('device_lost_events', 0)} lost "
            f"(max {mesh.get('max_degraded_lanes', 0)} degraded "
            f"lane(s)), {mesh.get('redispatches', 0)} re-bucketed "
            f"re-dispatch(es), {mesh.get('readmissions', 0)} "
            f"re-admission(s); {mesh.get('lost_statements', 0)} lost / "
            f"{mesh.get('wrong_results', 0)} wrong of "
            f"{mesh.get('checked_statements', '?')} checked "
            f"statements.\n")
    crec = latest_by_metric.get("checkpoint::restore")
    cp = crec.get("checkpoint") if crec else None
    if isinstance(cp, dict):
        sp = crec.get("vs_baseline")
        lines.append(
            f"Latest checkpoint restore: {cp.get('n_chunks', '?')} "
            f"chunks, {cp.get('journal_entries', 0)} journal "
            f"entr(ies) at {cp.get('journal_frac', '?')} depth, "
            f"restore {_fmt(crec.get('value'), 4)} s vs rebuild "
            f"{_fmt(cp.get('rebuild_s'), 4)} s "
            f"({_fmt(sp, 1)}x), parity "
            f"{'OK' if cp.get('parity') else 'FAILED'}.\n")
    return lines


def render_slo(records) -> list[str]:
    """The live-watchdog read side: latest `slo::*` records (one row
    per metric), the latest round's per-rule summary from the compact
    block riding the `slo::breaches` record, and the latest chaos
    round's breach→clear arc verdict."""
    lines = ["## SLO (live watchdog)\n"]
    recs = [r for r in records if r.get("source") == "slo"]
    arcs = [r for r in records
            if r.get("metric") == "resilience::slo_arc_ok"]
    if not recs and not arcs:
        lines.append("No SLO records — arm the watchdog on a serve "
                     "round (`CST_SLO_RULES=... CST_METRICS_PORT=9464 "
                     "make serve` / `make serve-smoke`) to evaluate "
                     "rules against the live fleet and produce "
                     "`slo::*` records.\n")
        return lines
    if recs:
        lines.append("| metric | latest | where |")
        lines.append("|---|---|---|")
        latest_by_metric = {}
        for metric, series in sorted(_by_metric(recs).items()):
            latest = series[-1]
            latest_by_metric[metric] = latest
            val = "—" if latest.get("value") is None else \
                f"{_fmt(latest['value'])} {latest.get('unit', '')}".rstrip()
            lines.append(f"| `{metric}` | {val} | {_where(latest)} |")
        lines.append("")
        rec = latest_by_metric.get("slo::breaches")
        compact = rec.get("slo") if rec else None
        if isinstance(compact, dict):
            now = ", ".join(compact.get("breaching_now") or []) or "none"
            lines.append(
                f"Latest armed round: {compact.get('ticks', '?')} "
                f"tick(s), {compact.get('breaches', '?')} breach(es), "
                f"currently breaching: {now}"
                + (f", {compact['events_dropped']} event(s) dropped at "
                   f"the cap" if compact.get("events_dropped") else "")
                + (f"; profiler grabs: "
                   f"{len(compact['profiles'])}"
                   if compact.get("profiles") else "")
                + ".\n")
            rules = [r for r in compact.get("rules", [])
                     if isinstance(r, dict)]
            if rules:
                lines.append("| rule | metric | breaches | clears | "
                             "breaching | worst margin | last value |")
                lines.append("|---|---|---|---|---|---|---|")
                for r in rules:
                    lines.append(
                        f"| `{r.get('name', '—')}` "
                        f"| `{r.get('metric', '—')}` "
                        f"| {r.get('breaches', '—')} "
                        f"| {r.get('clears', '—')} "
                        f"| {'yes' if r.get('breaching') else 'no'} "
                        f"| {_fmt(r.get('worst_margin'), 3)} "
                        f"| {_fmt(r.get('last_value'), 3)} |")
                lines.append("")
    if arcs:
        latest = max(arcs, key=_order_key)
        arc = latest.get("slo_arc") or {}
        lines.append(
            ("Latest chaos arc: breached inside the fault window and "
             "cleared after recovery — the watchdog saw the incident "
             "both ways"
             if latest.get("value") else
             f"Latest chaos arc: INCOMPLETE — breached in window: "
             f"{arc.get('breached_in_fault_window')}, cleared after "
             f"recovery: {arc.get('cleared_after_recovery')}")
            + f" (rule `{arc.get('rule', '?')}`, {_where(latest)}).\n")
    return lines


def render_tail_latency(records) -> list[str]:
    """The request-tracing read side: latest per-kind
    `latency::p99_ms@<kind>` records (the compact attribution block
    rides each — p50/p90/p99 + the p99 tail's component decomposition),
    the overall p99 queue-wait fraction, and the worst-N exemplar
    traces riding the `latency::p99_queue_frac` record."""
    lines = ["## Tail latency (request tracing)\n"]
    recs = [r for r in records if r.get("source") == "latency"]
    if not recs:
        lines.append("No latency records — run a serve round with "
                     "`CST_TRACE_REQUESTS=1` (`make serve` / "
                     "`make serve-smoke`) to mint per-request contexts "
                     "and produce `latency::*` attribution records.\n")
        return lines
    by_kind: dict[str, dict] = {}
    for r in sorted((r for r in recs
                     if r["metric"].startswith("latency::p99_ms@")
                     and isinstance(r.get("latency"), dict)),
                    key=_order_key):
        by_kind[r["metric"][len("latency::p99_ms@"):]] = r
    if by_kind:
        lines.append("Per-kind percentiles are per-REQUEST "
                     "(submit→complete, queue wait and resilience "
                     "detours included); the component columns "
                     "decompose the p99 tail's wall.\n")
        lines.append("| kind | n | p50 | p90 | p99 | queue | batch | "
                     "device | settle | detour | platform | where |")
        lines.append("|---|---|---|---|---|---|---|---|---|---|---|---|")
        for kind, r in sorted(by_kind.items()):
            blk = r["latency"]
            comp = blk.get("p99_components_ms") or {}
            lines.append(
                f"| `{kind}` | {blk.get('count', '—')} "
                f"| {_fmt(blk.get('p50_ms'), 2)} "
                f"| {_fmt(blk.get('p90_ms'), 2)} "
                f"| {_fmt(r.get('value'), 2)} ms "
                f"| {_fmt(comp.get('queue_wait'), 2)} "
                f"| {_fmt(comp.get('batch_form'), 2)} "
                f"| {_fmt(comp.get('device_wall'), 2)} "
                f"| {_fmt(comp.get('settle'), 2)} "
                f"| {_fmt(comp.get('detour'), 2)} "
                f"| {_platform_group(r)} | {_where(r)} |")
        lines.append("")
    frac_recs = [r for r in recs
                 if r["metric"] == "latency::p99_queue_frac"]
    if frac_recs:
        latest = max(frac_recs, key=_order_key)
        frac = latest.get("value")
        lines.append(
            f"Overall p99 tail queue-wait fraction: "
            f"{'—' if frac is None else f'{float(frac) * 100:.0f}%'} "
            f"({_where(latest)}, platform {_platform_group(latest)}) — "
            f"above 50% the tail is an arrival/scheduling problem, not "
            f"a device one (the `serve-p99-queue-frac` advisory row).\n")
        worst = (latest.get("latency") or {}).get("worst") or []
        if worst:
            lines.append("Worst exemplar traces:\n")
            lines.append("| trace | kind | outcome | attempts | e2e | "
                         "queue | device | detour |")
            lines.append("|---|---|---|---|---|---|---|---|")
            for ex in worst:
                comp = ex.get("components_ms") or {}
                lines.append(
                    f"| {ex.get('trace_id', '—')} "
                    f"| `{ex.get('kind', '—')}` "
                    f"| {ex.get('outcome', '—')} "
                    f"| {ex.get('attempts', '—')} "
                    f"| {_fmt(ex.get('e2e_ms'), 2)} ms "
                    f"| {_fmt(comp.get('queue_wait'), 2)} "
                    f"| {_fmt(comp.get('device_wall'), 2)} "
                    f"| {_fmt(comp.get('detour'), 2)} |")
            lines.append("")
    return lines


def render_occupancy(records) -> list[str]:
    """The device-occupancy read side: latest `pipeline::*` records
    (busy fraction, per-cause bubble seconds, overlap score) plus the
    bubble-attribution and per-device summaries from the compact block
    riding the `pipeline::busy_frac` record."""
    lines = ["## Pipeline occupancy\n"]
    recs = [r for r in records if r.get("source") == "pipeline"]
    if not recs:
        lines.append("No occupancy records — arm the device-occupancy "
                     "ledger on a serve round (`CST_OCCUPANCY=1 make "
                     "serve` / `make serve-smoke`) to measure device "
                     "busy fraction and pipeline bubbles and produce "
                     "`pipeline::*` records.\n")
        return lines
    lines.append("| metric | latest | where |")
    lines.append("|---|---|---|")
    latest_by_metric = {}
    for metric, series in sorted(_by_metric(recs).items()):
        latest = series[-1]
        latest_by_metric[metric] = latest
        val = "—" if latest.get("value") is None else \
            f"{_fmt(latest['value'])} {latest.get('unit', '')}".rstrip()
        lines.append(f"| `{metric}` | {val} | {_where(latest)} |")
    lines.append("")
    rec = latest_by_metric.get("pipeline::busy_frac")
    compact = rec.get("occupancy") if rec else None
    if isinstance(compact, dict):
        frac = compact.get("busy_frac")
        lines.append(
            f"Latest armed round: device busy "
            f"{'—' if frac is None else f'{float(frac) * 100:.1f}%'} "
            f"of a {_fmt(compact.get('wall_s'), 2)} s wall at pipeline "
            f"depth {compact.get('depth', '—')}"
            + (f", {compact['events_dropped']} interval(s) dropped at "
               f"the cap" if compact.get("events_dropped") else "")
            + ".\n")
        bub = compact.get("bubbles_s")
        if isinstance(bub, dict) and bub:
            lines.append("Idle-gap attribution (busy + bubbles sum to "
                         "the wall — see the bubble-cause definitions "
                         "in the README):\n")
            lines.append("| bubble cause | seconds |")
            lines.append("|---|---|")
            for cause, v in sorted(bub.items()):
                lines.append(f"| `{cause}` | {_fmt(v, 3)} |")
            lines.append("")
        devs = compact.get("devices")
        if isinstance(devs, dict) and len(devs) > 1:
            lines.append("| device | busy | spans |")
            lines.append("|---|---|---|")
            for dev, blk in sorted(devs.items()):
                if not isinstance(blk, dict):
                    continue
                bf = blk.get("busy_frac")
                lines.append(
                    f"| `{dev}` "
                    f"| {'—' if bf is None else f'{float(bf) * 100:.1f}%'} "
                    f"| {blk.get('spans', '—')} |")
            lines.append("")
    score_rec = latest_by_metric.get("pipeline::overlap_score")
    if score_rec is not None and score_rec.get("value") is not None:
        ov = score_rec.get("overlap") or {}
        lines.append(
            f"Pipeline overlap score: "
            f"{float(score_rec['value']) * 100:.0f}% of host prep hid "
            f"under device busy ({_fmt(ov.get('hidden_s'), 3)} s of "
            f"{_fmt(ov.get('prep_s'), 3)} s, {_where(score_rec)}) — "
            f"low scores mean the depth knob is not covering host "
            f"prep, the `host_prep` bubble's complement.\n")
    return lines


def render_scaling(records) -> list[str]:
    """The mesh-sharded flagship read side: per-rung × per-n_devices
    trend table from the latest `scaling::flagship@<n>` records (the
    compact rung block rides each record), plus the latest efficiency
    summary."""
    lines = ["## Scaling (mesh-sharded flagship)\n"]
    recs = [r for r in records if r.get("source") == "scaling"]
    if not recs:
        lines.append("No scaling records — run the sharded flagship "
                     "rungs (`python bench.py --worker scaling` on the "
                     "mesh, or `make shard-smoke` for the simulated "
                     "8-host-device contract check) to produce "
                     "`scaling::*` records.\n")
        return lines
    # latest rung record per (n_validators, n_devices) — the
    # per-n_devices trend: the same rung re-measured on a wider mesh
    # lands its own row instead of overwriting the narrow one
    rows: dict[tuple[int, int], dict] = {}
    for r in sorted((r for r in recs
                     if r["metric"].startswith("scaling::flagship@")
                     and isinstance(r.get("scaling"), dict)),
                    key=_order_key):
        blk = r["scaling"]
        n = blk.get("n_validators")
        d = blk.get("n_devices")
        if isinstance(n, int) and isinstance(d, int):
            rows[(n, d)] = r
    if rows:
        lines.append("| validators | devices | step wall | "
                     "per-chip vps | single-chip vps | efficiency | "
                     "platform | where |")
        lines.append("|---|---|---|---|---|---|---|---|")
        for (n, d), r in sorted(rows.items()):
            blk = r["scaling"]
            eff = blk.get("efficiency")
            lines.append(
                f"| {n} | {d} | {_fmt(r.get('value'), 4)} s "
                f"| {_si(blk.get('per_chip_vps'))} "
                f"| {_si(blk.get('single_chip_vps'))} "
                f"| {'—' if eff is None else f'{eff * 100:.0f}%'} "
                f"| {_platform_group(r)} | {_where(r)} |")
        lines.append("")
    eff_recs = [r for r in recs if r["metric"] == "scaling::efficiency"]
    if eff_recs:
        latest = max(eff_recs, key=_order_key)
        blk = latest.get("scaling") or {}
        lines.append(
            f"Latest full-mesh efficiency: "
            f"{float(latest['value']) * 100:.0f}% per-chip throughput "
            f"retention at {blk.get('n_validators', '?')} validators "
            f"over {blk.get('n_devices', '?')} device(s) "
            f"({_where(latest)}, platform "
            f"{_platform_group(latest)}).\n")
    ok8 = [r for r in recs if r["metric"] == "scaling::flagship_8m_ok"]
    if ok8:
        latest = max(ok8, key=_order_key)
        lines.append(
            ("8M-validator rung: completed.\n"
             if latest.get("value") else
             "8M-validator rung: ATTEMPTED AND FAILED (OOM or crash — "
             "see the round log).\n"))
    return lines


def render_das(records) -> list[str]:
    """The PeerDAS read side: per-matrix verification walls from the
    latest `das::verify_wall@<cols>x<blobs>` records (the compact
    block rides each), plus the latest speedup/throughput summary."""
    lines = ["## DAS (PeerDAS cell-proof sampling)\n"]
    recs = [r for r in records if r.get("source") == "das"]
    if not recs:
        lines.append("No das records — run the sampling-matrix sweep "
                     "(`python bench.py --worker das` on the chip, or "
                     "`make das-smoke` for the CPU contract check) to "
                     "produce `das::*` records.\n")
        return lines
    rows: dict[tuple[int, int], dict] = {}
    for r in sorted((r for r in recs
                     if r["metric"].startswith("das::verify_wall@")
                     and isinstance(r.get("das"), dict)),
                    key=_order_key):
        m = (r["das"].get("matrix") or {})
        c, b = m.get("columns"), m.get("blobs")
        if isinstance(c, int) and isinstance(b, int):
            rows[(c, b)] = r
    if rows:
        lines.append("| matrix | cells | verify wall | vs oracle | "
                     "rung | platform | where |")
        lines.append("|---|---|---|---|---|---|---|")
        for (c, b), r in sorted(rows.items()):
            blk = r["das"]
            cells = (blk.get("matrix") or {}).get("cells")
            vs = r.get("vs_baseline")
            lines.append(
                f"| {c}x{b} | {cells} | {_fmt(r.get('value'), 4)} s "
                f"| {'—' if vs is None else f'{_fmt(vs, 1)}x'} "
                f"| {blk.get('rung', '—')} | {_platform_group(r)} "
                f"| {_where(r)} |")
        lines.append("")
    sp = [r for r in recs if r["metric"] == "das::speedup"]
    if sp:
        latest = max(sp, key=_order_key)
        lines.append(
            f"Latest speedup over the pure-Python oracle: "
            f"{_fmt(latest['value'], 1)}x ({_where(latest)}, platform "
            f"{_platform_group(latest)}).\n")
    cps = [r for r in recs if r["metric"] == "das::cells_per_s"]
    if cps:
        latest = max(cps, key=_order_key)
        lines.append(
            f"Latest throughput: {_si(latest['value'])} cells/s "
            f"({_where(latest)}, platform "
            f"{_platform_group(latest)}).\n")
    # the producer side: FK20 full-matrix proof production + erasure
    # recovery (the super-node path)
    pw = [r for r in recs if r["metric"] == "das::produce_wall"]
    if pw:
        latest = max(pw, key=_order_key)
        blk = latest.get("das_producer") or {}
        vs = latest.get("vs_baseline")
        lines.append(
            f"FK20 producer: {_fmt(latest.get('value'), 2)} s per blob "
            f"(all 128 proofs"
            + (f", {_fmt(vs, 1)}x vs the D_u MSM route" if vs is not None
               else "")
            + (", byte-parity OK" if blk.get("parity") else "")
            + f") — {_where(latest)}, platform "
            f"{_platform_group(latest)}.\n")
    rw = [r for r in recs if r["metric"] == "das::recover_wall"]
    if rw:
        latest = max(rw, key=_order_key)
        blk = latest.get("das_recover") or {}
        vs = latest.get("vs_baseline")
        lines.append(
            f"Erasure recovery: {_fmt(latest.get('value'), 2)} s "
            f"({blk.get('cells_in', '—')} surviving cells -> full "
            f"reconstruction + re-prove"
            + (f", {_fmt(vs, 1)}x vs the pure-Python oracle"
               if vs is not None else "")
            + (", roundtrip OK" if blk.get("roundtrip") else "")
            + f") — {_where(latest)}, platform "
            f"{_platform_group(latest)}.\n")
    pps = [r for r in recs if r["metric"] == "das::proofs_per_s"]
    if pps:
        latest = max(pps, key=_order_key)
        lines.append(
            f"Latest producer throughput: {_si(latest['value'])} "
            f"proofs/s ({_where(latest)}, platform "
            f"{_platform_group(latest)}).\n")
    return lines


def render_forkchoice(records) -> list[str]:
    """The fork-choice read side: per-shape head walls from the latest
    `forkchoice::head_wall@<blocks>x<validators>` records (the compact
    block rides each), plus the latest speedup/throughput summary."""
    lines = ["## Fork choice (device LMD-GHOST)\n"]
    recs = [r for r in records if r.get("source") == "forkchoice"]
    if not recs:
        lines.append("No forkchoice records — run the tree sweep "
                     "(`python bench.py --worker forkchoice` on the "
                     "chip, or `make fc-smoke` for the CPU contract "
                     "check) to produce `forkchoice::*` records.\n")
        return lines
    rows: dict[tuple[int, int], dict] = {}
    for r in sorted((r for r in recs
                     if r["metric"].startswith("forkchoice::head_wall@")
                     and isinstance(r.get("forkchoice"), dict)),
                    key=_order_key):
        t = (r["forkchoice"].get("tree") or {})
        b, v = t.get("blocks"), t.get("validators")
        if isinstance(b, int) and isinstance(v, int):
            rows[(b, v)] = r
    if rows:
        lines.append("| tree | head wall | apply wall | vs oracle | "
                     "rungs | platform | where |")
        lines.append("|---|---|---|---|---|---|---|")
        for (b, v), r in sorted(rows.items()):
            blk = r["forkchoice"]
            vs = r.get("vs_baseline")
            rungs = blk.get("rungs") or {}
            rung_s = (f"{rungs.get('blocks', '—')}/"
                      f"{rungs.get('validators', '—')}/"
                      f"{rungs.get('batch', '—')}")
            lines.append(
                f"| {b}x{v} | {_fmt(r.get('value'), 5)} s "
                f"| {_fmt(blk.get('apply_wall_s'), 5)} s "
                f"| {'—' if vs is None else f'{_fmt(vs, 1)}x'} "
                f"| {rung_s} | {_platform_group(r)} | {_where(r)} |")
        lines.append("")
    sp = [r for r in recs if r["metric"] == "forkchoice::speedup"]
    if sp:
        latest = max(sp, key=_order_key)
        lines.append(
            f"Latest head speedup over the phase0 spec oracle: "
            f"{_fmt(latest['value'], 1)}x ({_where(latest)}, platform "
            f"{_platform_group(latest)}).\n")
    hps = [r for r in recs if r["metric"] == "forkchoice::heads_per_s"]
    if hps:
        latest = max(hps, key=_order_key)
        lines.append(
            f"Latest head throughput: {_si(latest['value'])} heads/s "
            f"({_where(latest)}, platform "
            f"{_platform_group(latest)}).\n")
    return lines


def render_msm(msm: dict) -> list[str]:
    lines = ["## `_MSM_DEVICE_MIN` break-even\n", msm["text"] + "\n"]
    if msm.get("sizes"):
        lines.append(f"Latest probe: {msm['where']} "
                     f"(platform {msm.get('platform', '?')}).\n")
        lines.append("| n | host/device wall | routed |")
        lines.append("|---|---|---|")
        for s in msm["sizes"]:
            lines.append(f"| {s['n']} | {_fmt(s['host_over_device'], 2)} "
                         f"| {s.get('routed') or '—'} |")
        lines.append("")
    return lines


def render_attribution(attribution, durations, top_n: int) -> list[str]:
    lines = ["## Tier-1 wall-time attribution\n"]
    if attribution:
        total = sum(r["total_s"] for r in attribution)
        build = sum(r["spec_build_s"] for r in attribution)
        body = sum(r["test_body_s"] for r in attribution)
        lines.append(
            f"{len(attribution)} tests, {total:.1f}s in-test wall; "
            f"phase split {build:.1f}s spec-build vs {body:.1f}s "
            f"test-body.  Spec-build-dominated rows are the ROADMAP's "
            f"trim targets (session compile-cache reuse / redundant "
            f"spec builds).\n")
        lines.append(f"Top {min(top_n, len(attribution))} time sinks:\n")
        lines.append("| test | total | spec-build | test-body | "
                     "build share |")
        lines.append("|---|---|---|---|---|")
        for row in attribution[:top_n]:
            share = (row["spec_build_s"] / row["total_s"] * 100.0
                     if row["total_s"] else 0.0)
            lines.append(
                f"| `{row['test']}` | {row['total_s']:.2f}s "
                f"| {row['spec_build_s']:.2f}s "
                f"| {row['test_body_s']:.2f}s | {share:.0f}% |")
        lines.append("")
    elif durations:
        lines.append("No telemetry snapshot with phase spans; falling "
                     "back to pytest --durations rows (no spec-build "
                     "split).\n")
        lines.append("| test | phase | wall |")
        lines.append("|---|---|---|")
        for row in sorted(durations, key=lambda r: -r["dur_s"])[:top_n]:
            lines.append(f"| `{row['test']}` | {row['phase']} "
                         f"| {row['dur_s']:.2f}s |")
        lines.append("")
    else:
        lines.append("No attribution data — run the suite with "
                     "CST_TELEMETRY=1 CST_TELEMETRY_OUT=out/"
                     "telemetry_snapshot.json (CI does) and re-run the "
                     "report.\n")
    return lines


def render_report(result: dict) -> str:
    lines = ["# Benchwatch report\n"]
    lines.append(
        f"{result['n_records']} history records "
        f"({result['n_new_records']} new this run) from "
        f"{result['repo']}; store: `{result['history_path']}`.\n")
    lines.extend(render_thresholds(result["thresholds"], result["strict"]))
    lines.extend(render_regressions(result["regressions"],
                                    result["max_regress_pct"]))
    lines.extend(render_tail_latency(result["records"]))
    lines.extend(render_occupancy(result["records"]))
    lines.extend(render_slo(result["records"]))
    lines.extend(render_resilience(result["records"]))
    lines.extend(render_scaling(result["records"]))
    lines.extend(render_das(result["records"]))
    lines.extend(render_forkchoice(result["records"]))
    lines.extend(render_msm(result["msm"]))
    lines.extend(render_utilization(result["utilization"], result["msm"]))
    lines.extend(render_trend_tables(result["records"]))
    lines.extend(render_attribution(result["attribution"],
                                    result["durations"],
                                    result["top_n"]))
    if result["warnings"]:
        lines.append("## Ingest warnings\n")
        lines.append(f"{len(result['warnings'])} input(s) skipped "
                     "(malformed / truncated / unknown schema):\n")
        for w in result["warnings"]:
            lines.append(f"- {w}")
        lines.append("")
    verdict = result["verdict"]
    lines.append(f"---\n\n**Verdict: {verdict}**\n")
    return "\n".join(lines)


# --- orchestration -----------------------------------------------------------


def build_report(repo: Path, history_path: Path,
                 snapshots: list[Path], durations_path: Path | None,
                 top_n: int, strict: bool, max_regress_pct: float,
                 update_history: bool = True) -> dict:
    records, warnings = history.ingest_repo(repo)

    attribution: list[dict] = []
    for snap in snapshots:
        recs, attr, warns = history.parse_telemetry_snapshot(snap)
        records.extend(recs)
        warnings.extend(warns)
        if attr:
            attribution = attr   # latest snapshot wins
    durations: list[dict] = []
    if durations_path is not None:
        try:
            durations = history.parse_durations(
                Path(durations_path).read_text())
        except (OSError, UnicodeDecodeError) as e:
            warnings.append(f"{durations_path}: unreadable durations "
                            f"file ({type(e).__name__}) — skipped")

    # one pass over the store: load, diff the freshly parsed records
    # against it, optionally persist the new ones, and report over the
    # union either way
    stored, skipped, hist_warns = history.load_history(history_path)
    warnings.extend(hist_warns)
    seen = {history._canonical_line(r) for r in stored}
    fresh = [r for r in records
             if not history.validate_record(r)
             and history._canonical_line(r) not in seen]
    n_new = history.append_records(history_path, fresh) \
        if update_history else 0
    stored.extend(fresh)

    thresholds = evaluate_thresholds(stored)
    regressions = find_regressions(stored, max_regress_pct)
    msm = msm_recommendation(stored)
    utilization = collect_utilization(stored)
    warnings.extend(utilization.pop("warnings"))
    # a CST_COSTMODEL round that produced no costmodel block is a
    # counted warning, never a crash/exit — matching history.py's
    # malformed-input policy
    from . import costmodel
    if costmodel._env_enabled() and not utilization["kernels"]:
        warnings.append(
            "CST_COSTMODEL is set but no costmodel records were "
            "ingested — the round's telemetry block is missing its "
            "costmodel sub-object (bench run without CST_TELEMETRY, "
            "or a pre-costmodel bench build?)")

    failed = [t for t in thresholds if t["status"] == "FAIL"]
    gate_failures = list(regressions)
    if strict:
        gate_failures.extend(failed)
    if regressions:
        verdict = ("REGRESSION — " + ", ".join(
            f"`{r['metric']}` {r['change_pct']:+.1f}% ({r['kind']})"
            for r in regressions))
    elif strict and failed:
        verdict = ("THRESHOLD FAIL — " + ", ".join(
            t["id"] for t in failed))
    else:
        unmet = ", ".join(t["id"] for t in failed) or "none"
        verdict = f"clean (no regressions; unmet targets: {unmet})"

    return {
        "repo": str(repo),
        "history_path": str(history_path),
        "n_records": len(stored),
        "n_new_records": n_new,
        "records": stored,
        "thresholds": thresholds,
        "regressions": regressions,
        "msm": msm,
        "utilization": utilization,
        "attribution": attribution,
        "durations": durations,
        "warnings": warnings,
        "skipped_history_lines": skipped,
        "strict": strict,
        "max_regress_pct": max_regress_pct,
        "top_n": top_n,
        "verdict": verdict,
        "exit_code": 1 if gate_failures else 0,
    }


def _default_repo() -> Path:
    return Path(__file__).resolve().parents[2]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m consensus_specs_tpu.telemetry.report",
        description="Benchwatch: longitudinal perf dashboard + "
                    "regression gate over bench/telemetry rounds.")
    parser.add_argument("--repo", type=Path, default=_default_repo(),
                        help="repo root holding BENCH_r*/MULTICHIP_r* "
                             "round files (default: this checkout)")
    parser.add_argument("--history", type=Path, default=None,
                        help="history store path (default: "
                             "<repo>/out/bench_history.jsonl)")
    parser.add_argument("--out", type=Path, default=None,
                        help="markdown report path (default: "
                             "<repo>/out/bench_report.md)")
    parser.add_argument("--snapshot", type=Path, action="append",
                        default=None,
                        help="telemetry snapshot file(s) for tier-1 "
                             "attribution (default: <repo>/out/"
                             "telemetry_snapshot.json when present)")
    parser.add_argument("--durations", type=Path, default=None,
                        help="saved pytest --durations output "
                             "(attribution fallback)")
    parser.add_argument("--top", type=int, default=None,
                        help="rows in the attribution table (default "
                             "CST_BENCHWATCH_TOP or 15)")
    parser.add_argument("--strict", action="store_true",
                        help="FAILing ROADMAP thresholds also gate the "
                             "exit code (same as CST_BENCHWATCH_STRICT=1)")
    parser.add_argument("--no-update", action="store_true",
                        help="do not append newly ingested records to "
                             "the history store")
    parser.add_argument("--json", type=Path, default=None,
                        help="also write the machine-readable result "
                             "(thresholds/regressions/msm) as JSON")
    args = parser.parse_args(argv)

    repo = args.repo.resolve()
    history_path = args.history or repo / "out" / "bench_history.jsonl"
    out_path = args.out or repo / "out" / "bench_report.md"
    snapshots = args.snapshot
    if snapshots is None:
        default_snap = repo / "out" / "telemetry_snapshot.json"
        snapshots = [default_snap] if default_snap.exists() else []
    strict = args.strict or \
        os.environ.get("CST_BENCHWATCH_STRICT", "0") not in ("", "0")
    try:
        max_regress_pct = float(
            os.environ.get("CST_BENCHWATCH_MAX_REGRESS_PCT", "20"))
    except ValueError:
        max_regress_pct = 20.0
    if args.top is not None:
        top_n = args.top
    else:
        try:
            top_n = int(os.environ.get("CST_BENCHWATCH_TOP", "15") or 15)
        except ValueError:
            top_n = 15

    result = build_report(
        repo=repo, history_path=history_path, snapshots=snapshots,
        durations_path=args.durations, top_n=top_n, strict=strict,
        max_regress_pct=max_regress_pct,
        update_history=not args.no_update)

    text = render_report(result)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    out_path.write_text(text)
    print(text)
    if args.json:
        slim = {k: v for k, v in result.items()
                if k not in ("records", "attribution", "durations")}
        args.json.parent.mkdir(parents=True, exist_ok=True)
        args.json.write_text(json.dumps(slim, indent=1) + "\n")
    print(f"benchwatch: {result['verdict']} -> {out_path}",
          file=sys.stderr)
    return result["exit_code"]


if __name__ == "__main__":
    sys.exit(main())
