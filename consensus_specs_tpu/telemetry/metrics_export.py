"""Prometheus text-format exposition of the live telemetry registry.

Every observability layer before this one ends at an artifact read
AFTER a round finishes (bench blocks, history records, Chrome traces).
This module is the live surface: a zero-dependency HTTP endpoint
(stdlib `http.server`, daemon thread, armed by `CST_METRICS_PORT`)
rendering the whole registry in the Prometheus text exposition format
(version 0.0.4) on every scrape — counters, gauges, histogram and span
summaries, per-device memory watermarks from the cost model, the
request-trace rolling window (per-kind p50/p99 quantiles + lifetime
outcome totals), the serve executor's queue/in-flight/breaker state
(via a registered status provider), and the SLO watchdog's breach
counters (`monitor.py`).

Naming contract: registry names are dotted (`serve.submitted`,
`kernel.run_s`); exposition names are the `cst_`-prefixed sanitized
form (`.` and every other non-metric character -> `_`), so
`serve.submitted` scrapes as `cst_serve_submitted_total`.  Sanitization
must be collision-free — two registry names that sanitize to the same
exposition name would silently merge series, so collisions are dropped
and counted (`metrics.name_collision`), and the analyzer rule
`metric-name-invalid` makes the source-level invariant a lint check.

`render_exposition()` is pure (registry snapshot -> text) and
`parse_exposition()` is its validating inverse — the scrape artifact
check in bench_smoke and the round-trip test both go through it.

Gating contract (the telemetry pattern): the server only starts when
`CST_METRICS_PORT` is set (or `start()` is called explicitly); nothing
here runs on any hot path — cost is paid per scrape, by the scraper's
request thread.  Stdlib-only; never imports jax or numpy (same
discipline as the rest of `telemetry/`).
"""

from __future__ import annotations

import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import core, costmodel, occupancy, reqtrace

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# the Prometheus data-model charsets (exposition-format spec)
METRIC_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*\Z")

_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")

_lock = threading.Lock()
_server: ThreadingHTTPServer | None = None
_thread: threading.Thread | None = None
_status_provider = None     # callable -> ServeExecutor.status()-shaped dict


def sanitize_name(name: str) -> str:
    """Registry name -> exposition metric-name stem: every character
    outside the metric charset (dots, `@`, dashes) becomes `_`, and a
    leading digit gets a `_` prefix.  The `cst_` family prefix is added
    by the renderer."""
    out = _SANITIZE_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(value) -> str:
    v = float(value)
    if v != v:
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


class _Lines:
    """Exposition builder: tracks emitted metric names so a sanitization
    collision (two registry names -> one exposition name) is dropped and
    counted instead of silently merging series."""

    def __init__(self):
        self.out: list[str] = []
        self._typed: dict[str, str] = {}
        self.collisions = 0

    def family(self, name: str, mtype: str, help_text: str) -> bool:
        prev = self._typed.get(name)
        if prev is not None:
            if prev != mtype:
                self.collisions += 1
                return False
            return True
        if not METRIC_NAME_RE.match(name):
            self.collisions += 1
            return False
        self._typed[name] = mtype
        self.out.append(f"# HELP {name} {help_text}")
        self.out.append(f"# TYPE {name} {mtype}")
        return True

    def sample(self, name: str, value, labels: dict | None = None) -> None:
        if labels:
            body = ",".join(f'{k}="{_escape_label(v)}"'
                            for k, v in sorted(labels.items()))
            self.out.append(f"{name}{{{body}}} {_fmt(value)}")
        else:
            self.out.append(f"{name} {_fmt(value)}")


def set_status_provider(fn) -> None:
    """Register the live serve-status callable (`ServeExecutor.status`)
    so scrapes — and the SLO watchdog — see queue depth, in-flight
    counts and breaker states.  Pass None to unregister (executor
    close)."""
    global _status_provider
    _status_provider = fn


def get_status() -> dict | None:
    """The registered provider's current status dict, or None (no
    provider / provider raised — a dying executor must not kill a
    scrape)."""
    fn = _status_provider
    if fn is None:
        return None
    try:
        return fn()
    except Exception:
        core.count("metrics.status_provider_error")
        return None


_BREAKER_STATES = {"closed": 0, "half_open": 1, "half-open": 1, "open": 2}


def render_exposition(snap: dict | None = None,
                      status: dict | None = None) -> str:
    """The whole registry as Prometheus exposition text.  Deterministic
    given the snapshot (sorted families, sorted labels) so tests can pin
    the format line-by-line."""
    if snap is None:
        snap = core.snapshot()
    if status is None:
        status = get_status()
    L = _Lines()

    L.family("cst_telemetry_enabled", "gauge",
             "1 while the telemetry registry is collecting")
    L.sample("cst_telemetry_enabled", 1 if snap.get("enabled") else 0)

    for name, v in sorted(snap.get("counters", {}).items()):
        m = f"cst_{sanitize_name(name)}_total"
        if L.family(m, "counter", f"telemetry counter {name}"):
            L.sample(m, v)
    for name, g in sorted(snap.get("gauges", {}).items()):
        m = f"cst_{sanitize_name(name)}"
        if L.family(m, "gauge", f"telemetry gauge {name} (last sample)"):
            L.sample(m, g["last"])
    for name, h in sorted(snap.get("histograms", {}).items()):
        stem = f"cst_{sanitize_name(name)}"
        if L.family(stem, "summary", f"telemetry histogram {name}"):
            L.sample(f"{stem}_count", h["count"])
            L.sample(f"{stem}_sum", h["total"])
            L.sample(f"{stem}_min", h["min"])
            L.sample(f"{stem}_max", h["max"])
    for name, s in sorted(snap.get("spans", {}).items()):
        stem = f"cst_{sanitize_name(name)}_seconds"
        if L.family(stem, "summary", f"telemetry span {name}"):
            L.sample(f"{stem}_count", s["count"])
            L.sample(f"{stem}_sum", s["total_s"])
            L.sample(f"{stem}_min", s["min_s"])
            L.sample(f"{stem}_max", s["max_s"])

    # per-device memory watermarks (cost model)
    wms = snap.get("costmodel", {}).get("watermarks", {})
    if wms:
        L.family("cst_device_memory_bytes", "gauge",
                 "live device buffer bytes (last watermark sample)")
        for dev, wm in sorted(wms.items()):
            L.sample("cst_device_memory_bytes", wm["last_bytes"],
                     {"device": dev})
        L.family("cst_device_memory_high_water_bytes", "gauge",
                 "device buffer high-water mark")
        for dev, wm in sorted(wms.items()):
            L.sample("cst_device_memory_high_water_bytes",
                     wm["high_water_bytes"], {"device": dev})

    # request tracing: rolling-window quantiles + lifetime totals
    rolling = reqtrace.rolling_summary()
    if rolling:
        L.family("cst_serve_request_latency_ms", "summary",
                 "per-kind rolling-window request latency quantiles")
        for kind, s in sorted(rolling.items()):
            for q, key in (("0.5", "p50_ms"), ("0.99", "p99_ms")):
                L.sample("cst_serve_request_latency_ms", s[key],
                         {"kind": kind, "quantile": q})
        L.family("cst_serve_request_window_count", "gauge",
                 "answered requests in the rolling summary window")
        for kind, s in sorted(rolling.items()):
            L.sample("cst_serve_request_window_count", s["count"],
                     {"kind": kind})
    total, by_kind, by_outcome = reqtrace.completed_totals()
    if total:
        L.family("cst_serve_requests_total", "counter",
                 "completed requests by kind (process lifetime)")
        for kind, n in sorted(by_kind.items()):
            L.sample("cst_serve_requests_total", n, {"kind": kind})
        L.family("cst_serve_outcomes_total", "counter",
                 "completed requests by outcome (process lifetime)")
        for outcome, n in sorted(by_outcome.items()):
            L.sample("cst_serve_outcomes_total", n, {"outcome": outcome})

    if status:
        # `cst_serve_live_*`: read from ServeExecutor.status() at scrape
        # time — the `cst_serve_queue_depth`-style names stay reserved
        # for the registry's own sampled gauges (same source, different
        # timing), so the two surfaces never collide
        queue = status.get("queue", {})
        L.family("cst_serve_live_queue_depth", "gauge",
                 "serve executor queued requests (at scrape)")
        L.sample("cst_serve_live_queue_depth", queue.get("depth", 0))
        L.family("cst_serve_live_queue_oldest_age_seconds", "gauge",
                 "age of the oldest queued request (at scrape)")
        L.sample("cst_serve_live_queue_oldest_age_seconds",
                 queue.get("oldest_age_s") or 0.0)
        by_kind_q = queue.get("by_kind") or {}
        if by_kind_q:
            L.family("cst_serve_live_queue_by_kind", "gauge",
                     "serve executor queued requests by kind (at scrape)")
            for kind, n in sorted(by_kind_q.items()):
                L.sample("cst_serve_live_queue_by_kind", n,
                         {"kind": kind})
        inflight = status.get("inflight", {})
        L.family("cst_serve_live_inflight_batches", "gauge",
                 "serve executor batches in flight (at scrape)")
        L.sample("cst_serve_live_inflight_batches",
                 inflight.get("batches", 0))
        L.family("cst_serve_live_inflight_requests", "gauge",
                 "serve executor requests in flight (at scrape)")
        L.sample("cst_serve_live_inflight_requests",
                 inflight.get("requests", 0))
        ctrs = status.get("counters") or {}
        if ctrs:
            L.family("cst_serve_executor_events_total", "counter",
                     "serve executor lifecycle counters")
            for key, n in sorted(ctrs.items()):
                L.sample("cst_serve_executor_events_total", n,
                         {"event": key})
        breakers = status.get("breakers") or {}
        if breakers:
            L.family("cst_serve_breaker_state", "gauge",
                     "circuit breaker state (0=closed 1=half-open 2=open)")
            for key, b in sorted(breakers.items()):
                state = b.get("state") if isinstance(b, dict) else b
                L.sample("cst_serve_breaker_state",
                         _BREAKER_STATES.get(str(state), 0), {"key": key})

    # device occupancy (CST_OCCUPANCY): live busy fraction per device +
    # cumulative bubble attribution over the ledger's extent
    occ = occupancy.live_summary()
    if occ is not None:
        L.family("cst_serve_device_busy_frac", "gauge",
                 "device busy fraction from the occupancy ledger "
                 "(at scrape)")
        L.sample("cst_serve_device_busy_frac", occ["busy_frac"])
        for dev, frac in sorted((occ.get("devices") or {}).items()):
            L.sample("cst_serve_device_busy_frac", frac,
                     {"device": dev})
        L.family("cst_serve_bubble_seconds_total", "counter",
                 "idle device wall attributed per pipeline-bubble "
                 "cause")
        for cause, v in sorted(occ["bubbles_s"].items()):
            L.sample("cst_serve_bubble_seconds_total", v,
                     {"cause": cause})

    # SLO watchdog (lazy import: monitor imports this module)
    from . import monitor
    wd = monitor.current()
    if wd is not None:
        for name, mtype, help_text, rows in wd.exposition_rows():
            if L.family(name, mtype, help_text):
                for labels, value in rows:
                    L.sample(name, value, labels)

    if L.collisions:
        core.count("metrics.name_collision", L.collisions)
        L.family("cst_metrics_name_collisions_total", "counter",
                 "registry names dropped from exposition (sanitization "
                 "collision)")
        L.sample("cst_metrics_name_collisions_total", L.collisions)
    return "\n".join(L.out) + "\n"


# --- the validating inverse --------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>-?(?:[0-9]+(?:\.[0-9]+)?(?:[eE][-+]?[0-9]+)?"
    r"|NaN|[+-]?Inf))$")
_LABEL_PAIR_RE = re.compile(
    r'^(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"$')


def parse_exposition(text: str) -> dict:
    """Parse (and strictly validate) exposition text, returning
    `{metric_name: [(labels_dict, value), ...]}`.  Raises ValueError
    naming the first malformed line — the line-by-line format check the
    bench-smoke scrape validation and the round-trip test share."""
    out: dict[str, list] = {}
    typed: set[str] = set()
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"line {lineno}: malformed comment "
                                 f"{line!r}")
            if not METRIC_NAME_RE.match(parts[2]):
                raise ValueError(f"line {lineno}: invalid metric name "
                                 f"{parts[2]!r}")
            if parts[1] == "TYPE":
                if parts[2] in typed:
                    raise ValueError(f"line {lineno}: duplicate TYPE "
                                     f"for {parts[2]!r}")
                typed.add(parts[2])
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        labels = {}
        body = m.group("labels")
        if body:
            for pair in _split_label_pairs(body, lineno):
                pm = _LABEL_PAIR_RE.match(pair)
                if not pm:
                    raise ValueError(f"line {lineno}: malformed label "
                                     f"pair {pair!r}")
                labels[pm.group("k")] = pm.group("v")
        out.setdefault(m.group("name"), []).append(
            (labels, float(m.group("value"))))
    return out


def _split_label_pairs(body: str, lineno: int) -> list[str]:
    """Split `k="v",k2="v2"` on commas outside quotes."""
    pairs, cur, in_q, esc = [], [], False, False
    for ch in body:
        if esc:
            cur.append(ch)
            esc = False
        elif ch == "\\" and in_q:
            cur.append(ch)
            esc = True
        elif ch == '"':
            cur.append(ch)
            in_q = not in_q
        elif ch == "," and not in_q:
            pairs.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if in_q:
        raise ValueError(f"line {lineno}: unterminated label quote")
    if cur:
        pairs.append("".join(cur))
    return pairs


# --- the endpoint ------------------------------------------------------------


class _MetricsHandler(BaseHTTPRequestHandler):
    def do_GET(self):          # noqa: N802 (http.server API)
        if self.path.split("?")[0] not in ("/", "/metrics"):
            self.send_error(404)
            return
        try:
            body = render_exposition().encode("utf-8")
        except Exception as exc:   # a scrape must never crash the server
            core.count("metrics.render_error")
            self.send_error(500, explain=str(exc))
            return
        core.count("metrics.scrapes")
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):   # silence per-request stderr noise
        pass


def start(port: int | None = None) -> int:
    """Start the exposition endpoint on `port` (0 = ephemeral; default
    from CST_METRICS_PORT) and return the bound port.  Idempotent — a
    second start returns the running server's port."""
    global _server, _thread
    with _lock:
        if _server is not None:
            return _server.server_address[1]
        if port is None:
            port = int(os.environ.get("CST_METRICS_PORT", "0") or "0")
        srv = ThreadingHTTPServer(("127.0.0.1", port), _MetricsHandler)
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever, name="cst-metrics",
                             daemon=True)
        t.start()
        _server, _thread = srv, t
        bound = srv.server_address[1]
    core.set_meta("metrics_port", bound)
    return bound


def stop() -> None:
    global _server, _thread
    with _lock:
        srv, _server, _thread = _server, None, None
    if srv is not None:
        srv.shutdown()
        srv.server_close()


def serving_port() -> int | None:
    """The live endpoint's port, or None while stopped."""
    srv = _server
    return srv.server_address[1] if srv is not None else None


def start_from_env() -> int | None:
    """Start the endpoint when `CST_METRICS_PORT` is set (non-"0");
    returns the bound port or None.  Call sites: loadgen / bench_serve /
    the chaos harness — never at import."""
    raw = os.environ.get("CST_METRICS_PORT", "")
    if raw in ("", "0"):
        return serving_port()
    return start(int(raw))


def _reset_state() -> None:
    """Full test-isolation reset (telemetry.reset(full=True) hook):
    stop the server and drop the status provider."""
    global _status_provider
    stop()
    _status_provider = None
