"""Device-occupancy ledger — pipeline-bubble attribution for the serve
fleet.

The depth-pipelined serve design exists to keep the device busy: host
prep of batch N+1 is supposed to hide under device execution of batch
N.  Request tracing (`reqtrace.py`) says where a REQUEST's wall went;
nothing says whether the DEVICE was busy, and when it was not, why not.
This module closes that gap with a per-device interval ledger fed from
the existing sanctioned seams:

- `ServeExecutor._dispatch_one` / `_settle_batch` mint a `BatchSpan`
  per dispatched batch: host-prep begin → in flight (device busy
  opens) → device answer (busy closes) → settle end.
- `ops.bls_batch._dispatch` stamps kernel-level busy: a blocking
  dispatch records its [t0, t1] directly; a `block=False` enqueue
  opens a span that `serve.futures._settle_from_device` closes
  (`note_settled` — the device stream executes in order, so a settle
  means everything enqueued before it has finished; a span truncated
  early by a pipelined neighbour's settle is recovered by the
  union-merge with the executor-level interval for the same batch).

`block(window)` merges the busy intervals per device (union across
sources, so the two seams never double-count), computes `busy_frac`
and per-kind device-seconds, scores pipeline overlap (how much host
prep actually hid under device busy), and attributes every idle gap in
the union-busy timeline to exactly one cause:

    host_prep          the gap overlaps recorded host-prep intervals —
                       prep that did NOT hide under device work
                       (pipeline depth too shallow, or serialized)
    settle_serialized  the remaining gap overlaps recorded settle
                       intervals — result distribution blocking the
                       next dispatch
    drain              residual idle after the LAST busy span — the
                       tail where in-flight work finished and nothing
                       was dispatched again
    queue_starved      everything else — the device sat idle with no
                       host work recorded: arrivals were too slow

The partition is exact interval arithmetic, so `busy_s` plus the four
bubble components sums to the measured wall to float round-off (the
same contiguity contract as reqtrace's five latency components; pinned
to 1e-6 relative by tests/test_occupancy.py).

Read sides: the serve block's `"occupancy"` sub-object
(`telemetry.export.validate_occupancy_block`), `pipeline::*` history
records, the report's "Pipeline occupancy" section + `serve-occupancy`
threshold row, Chrome-trace per-device busy counter tracks, the
`cst_serve_device_busy_frac` / `cst_serve_bubble_seconds_total{cause=}`
exposition families, `ServeExecutor.status()["occupancy"]`, and the
watchdog's `serve.busy_frac` signal.

Gating contract (the telemetry pattern): OFF unless `CST_OCCUPANCY` is
set non-"0" (or `configure(enabled=True)`); every note-site guards on
ONE module-global read (no-op bound pinned by tests).  Registry capped
at `_MAX_EVENTS`; drops are counted, never silent.  Stdlib-only; never
imports jax or numpy (same discipline as the rest of `telemetry/`).
"""

from __future__ import annotations

import os
import threading
import time

BUBBLE_CAUSES = ("host_prep", "queue_starved", "settle_serialized",
                 "drain")

# interval classes the ledger stores (one flat event list keeps the
# note-site cost to a tuple append)
_BUSY, _PREP, _SETTLE = 0, 1, 2

_MAX_EVENTS = 200_000

_lock = threading.Lock()


def _env_enabled() -> bool:
    return os.environ.get("CST_OCCUPANCY", "0") not in ("", "0")


_enabled = _env_enabled()
# completed intervals: (class, device, label, t0, t1); appends are
# atomic under the GIL so the enabled note path takes no lock (the
# lock guards reads/resets, like reqtrace's registry)
_events: list[tuple] = []
_events_dropped = 0
# open kernel busy spans per device: [(label, t0), ...] — closed by
# `note_settled` (FIFO device stream) or clamped to the window end by
# `block()` for work still executing at read time
_open: dict[str, list] = {}


def enabled() -> bool:
    """True while the ledger is recording (CST_OCCUPANCY or an explicit
    `configure(enabled=True)`)."""
    return _enabled


def configure(enabled: bool | None = None) -> None:
    """Programmatic override of the env gate (benches, smoke, tests)."""
    global _enabled
    if enabled is not None:
        _enabled = enabled


def reset() -> None:
    """Clear the ledger (how loadgen scopes a measured window to
    itself).  Open kernel spans clear too — work dispatched before the
    window re-enters through its executor-level interval."""
    global _events_dropped
    with _lock:
        _events.clear()
        _open.clear()
        _events_dropped = 0


def _reset_state() -> None:
    """Full test-isolation reset (telemetry.reset(full=True) hook):
    ledger AND the env-derived gate."""
    global _enabled
    reset()
    _enabled = _env_enabled()


def _push(cls: int, device: str, label: str, t0: float,
          t1: float) -> None:
    global _events_dropped
    if t1 <= t0:
        return
    if len(_events) < _MAX_EVENTS:
        _events.append((cls, device, label, t0, t1))
    else:
        _events_dropped += 1


# --- the executor seam -------------------------------------------------------


class BatchSpan:
    """One dispatched serve batch's occupancy lifecycle.  Minted by
    `begin_batch()` at `_dispatch_one` entry; the executor drives the
    transitions.  Publishes three intervals on completion: host prep
    [mint, dispatch], device busy [dispatch, answer], settle [answer,
    settled]."""

    __slots__ = ("kind", "device", "t_prep0", "t_dispatch", "t_answer",
                 "done")

    def __init__(self, kind: str, device: str = "0"):
        self.kind = kind
        self.device = device
        self.t_prep0 = time.perf_counter()
        self.t_dispatch = None
        self.t_answer = None
        self.done = False

    def mark_dispatch(self) -> None:
        """Host prep done, batch handed to the device — busy opens."""
        now = time.perf_counter()
        if self.t_dispatch is None:
            self.t_dispatch = now
            _push(_PREP, self.device, self.kind, self.t_prep0, now)

    def mark_answer(self) -> None:
        """The batch's device answer arrived — busy closes."""
        now = time.perf_counter()
        if self.t_answer is None and self.t_dispatch is not None:
            self.t_answer = now
            _push(_BUSY, self.device, self.kind, self.t_dispatch, now)

    def mark_settled(self) -> None:
        """Results distributed to the member handles — settle closes.
        Idempotent; an answerless settle (prep failed after dispatch
        bookkeeping) closes what it has."""
        if self.done:
            return
        self.done = True
        now = time.perf_counter()
        if self.t_answer is not None:
            _push(_SETTLE, self.device, self.kind, self.t_answer, now)

    def abandon(self) -> None:
        """Host prep failed before dispatch: record the prep wall (work
        that hid nothing) and finish the span."""
        if self.done:
            return
        self.done = True
        now = time.perf_counter()
        if self.t_dispatch is None:
            _push(_PREP, self.device, self.kind, self.t_prep0, now)
        elif self.t_answer is None:
            # failed between dispatch and answer: the wait was still
            # device wall from the ledger's point of view
            _push(_BUSY, self.device, self.kind, self.t_dispatch, now)


def begin_batch(kind: str, device: str = "0") -> BatchSpan | None:
    """A fresh batch span, or None while the ledger is off (stamp
    sites guard on None — disabled cost is this one global read).
    `device` is a caller-supplied label (telemetry never imports jax);
    the single-stream serve path uses the default "0"."""
    if not _enabled:
        return None
    return BatchSpan(kind, device)


# --- the kernel seam ---------------------------------------------------------


def note_kernel_busy(kernel: str, t0: float, t1: float,
                     device: str = "0") -> None:
    """A blocking kernel dispatch's measured device wall [t0, t1] (the
    `_dispatch` first-call / `block=True` path)."""
    if not _enabled:
        return
    _push(_BUSY, device, f"kernel:{kernel}", t0, t1)


def note_kernel_dispatched(kernel: str, t0: float | None = None,
                           device: str = "0") -> None:
    """A non-blocking kernel enqueue: opens a busy span closed by the
    next `note_settled` on the same device (the device stream executes
    in order)."""
    if not _enabled:
        return
    t = time.perf_counter() if t0 is None else t0
    with _lock:
        _open.setdefault(device, []).append((f"kernel:{kernel}", t))


def note_settled(device: str = "0") -> None:
    """A device→host settle completed: everything enqueued on this
    device before it has finished executing — close every open span.
    (A pipelined neighbour's span closed early here is recovered by the
    union-merge with its executor-level busy interval.)"""
    if not _enabled:
        return
    now = time.perf_counter()
    with _lock:
        spans = _open.pop(device, [])
    for label, t0 in spans:
        _push(_BUSY, device, label, t0, now)


# --- interval arithmetic -----------------------------------------------------


def _merge(intervals: list) -> list:
    """Sorted disjoint union of [t0, t1) intervals."""
    out: list = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1][1] = b
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def _clip(intervals: list, w0: float, w1: float) -> list:
    out = []
    for a, b in intervals:
        a, b = max(a, w0), min(b, w1)
        if b > a:
            out.append((a, b))
    return out


def _intersect(xs: list, ys: list) -> list:
    """Intersection of two sorted disjoint interval lists."""
    out, i, j = [], 0, 0
    while i < len(xs) and j < len(ys):
        a = max(xs[i][0], ys[j][0])
        b = min(xs[i][1], ys[j][1])
        if b > a:
            out.append((a, b))
        if xs[i][1] <= ys[j][1]:
            i += 1
        else:
            j += 1
    return out


def _subtract(xs: list, ys: list) -> list:
    """xs minus ys, both sorted disjoint."""
    out = []
    j = 0
    for a, b in xs:
        cur = a
        while j < len(ys) and ys[j][1] <= cur:
            j += 1
        k = j
        while k < len(ys) and ys[k][0] < b:
            ya, yb = ys[k]
            if ya > cur:
                out.append((cur, ya))
            cur = max(cur, yb)
            k += 1
        if cur < b:
            out.append((cur, b))
    return out


def _total(intervals: list) -> float:
    return sum(b - a for a, b in intervals)


def _snapshot_events(clamp_open_to: float | None):
    with _lock:
        events = list(_events)
        dropped = _events_dropped
        if clamp_open_to is not None:
            for dev, spans in _open.items():
                for label, t0 in spans:
                    if clamp_open_to > t0:
                        events.append((_BUSY, dev, label, t0,
                                       clamp_open_to))
    return events, dropped


def _attribute(busy_u: list, prep_m: list, settle_m: list,
               w0: float, w1: float) -> dict:
    """Partition the idle gaps of one busy timeline over [w0, w1] into
    the four bubble causes.  Exact: busy + bubbles == w1 - w0."""
    gaps = _subtract([(w0, w1)], busy_u)
    host_prep = _intersect(gaps, prep_m)
    rem = _subtract(gaps, host_prep)
    settle = _intersect(rem, settle_m)
    rem = _subtract(rem, settle)
    last_busy_end = busy_u[-1][1] if busy_u else w0
    drain = _intersect(rem, [(last_busy_end, w1)]) \
        if last_busy_end < w1 else []
    starved = _subtract(rem, drain)
    return {
        "host_prep": _total(host_prep),
        "queue_starved": _total(starved),
        "settle_serialized": _total(settle),
        "drain": _total(drain),
    }


# --- read sides --------------------------------------------------------------


def block(window: tuple | None = None, depth: int | None = None) -> dict:
    """The `"occupancy"` serve-block sub-object over `window`
    (perf_counter (W0, W1); default = the ledger's own extent, end
    clamped to now).  `depth` is the caller's pipeline depth knob,
    carried for the overlap-score read side."""
    now = time.perf_counter()
    events, dropped = _snapshot_events(clamp_open_to=(
        window[1] if window is not None else now))
    if window is not None:
        w0, w1 = float(window[0]), float(window[1])
    elif events:
        w0 = min(e[3] for e in events)
        w1 = min(now, max(e[4] for e in events))
    else:
        w0 = w1 = now
    out = {
        "enabled": _enabled,
        "wall_s": max(0.0, w1 - w0),
        "depth": depth,
        "events": len(events),
        "events_dropped": dropped,
        "busy_s": 0.0,
        "busy_frac": 0.0,
        "bubbles_s": dict.fromkeys(BUBBLE_CAUSES, 0.0),
        "devices": {},
        "device_seconds_by_kind": {},
        "overlap": {"prep_s": 0.0, "hidden_s": 0.0, "score": None},
    }
    if w1 <= w0:
        out["wall_s"] = 0.0
        out["bubbles_s"]["queue_starved"] = 0.0
        return out
    wall = w1 - w0

    busy_by_dev: dict[str, list] = {}
    preps, settles = [], []
    by_kind: dict[str, float] = {}
    for cls, dev, label, t0, t1 in events:
        a, b = max(t0, w0), min(t1, w1)
        if b <= a:
            continue
        if cls == _BUSY:
            busy_by_dev.setdefault(dev, []).append((a, b))
            by_kind[label] = by_kind.get(label, 0.0) + (b - a)
        elif cls == _PREP:
            preps.append((a, b))
        else:
            settles.append((a, b))

    prep_m = _merge(preps)
    settle_m = _merge(settles)
    all_busy: list = []
    for dev, iv in sorted(busy_by_dev.items()):
        dev_busy = _merge(iv)
        all_busy.extend(dev_busy)
        out["devices"][dev] = {
            "busy_s": round(_total(dev_busy), 9),
            "busy_frac": round(_total(dev_busy) / wall, 6),
            "spans": len(dev_busy),
            "bubbles_s": {c: round(v, 9) for c, v in _attribute(
                dev_busy, prep_m, settle_m, w0, w1).items()},
        }
    busy_u = _merge(all_busy)
    busy_s = _total(busy_u)
    out["busy_s"] = busy_s
    out["busy_frac"] = round(busy_s / wall, 6)
    out["bubbles_s"] = _attribute(busy_u, prep_m, settle_m, w0, w1)
    out["device_seconds_by_kind"] = {
        k: round(v, 9) for k, v in sorted(by_kind.items())}
    prep_s = _total(prep_m)
    hidden = _total(_intersect(prep_m, busy_u))
    out["overlap"] = {
        "prep_s": round(prep_s, 9),
        "hidden_s": round(hidden, 9),
        "score": round(hidden / prep_s, 6) if prep_s > 0 else None,
    }
    return out


def live_summary(window_s: float | None = None) -> dict | None:
    """A compact live view for `ServeExecutor.status()`, the watchdog's
    `serve.busy_frac` signal, and the exposition families: busy_frac +
    per-cause bubble seconds over the trailing `window_s` (default: the
    ledger's whole extent).  None while disabled or empty."""
    if not _enabled:
        return None
    now = time.perf_counter()
    events, _ = _snapshot_events(clamp_open_to=now)
    if not events:
        return None
    w1 = now
    w0 = (w1 - window_s) if window_s else min(e[3] for e in events)
    if w1 <= w0:
        return None
    b = block(window=(w0, w1))
    return {
        "busy_frac": b["busy_frac"],
        "bubbles_s": {c: round(v, 6)
                      for c, v in b["bubbles_s"].items()},
        "devices": {d: v["busy_frac"]
                    for d, v in b["devices"].items()},
        "window_s": round(w1 - w0, 6),
    }


def live_busy_frac(window_s: float | None = None) -> float | None:
    """The watchdog signal: union-busy fraction, or None while the
    ledger is off / empty (None holds a rule's streak, per monitor's
    hysteresis contract)."""
    s = live_summary(window_s)
    return None if s is None else s["busy_frac"]


def raw_snapshot() -> dict:
    """The `occupancy` sub-object of `telemetry.snapshot()`: summary
    counts + the live view (bounded — intervals stay in the ledger)."""
    with _lock:
        n, dropped = len(_events), _events_dropped
        n_open = sum(len(v) for v in _open.values())
    return {
        "enabled": _enabled,
        "events": n,
        "open_spans": n_open,
        "events_dropped": dropped,
        "live": live_summary(),
    }


def chrome_events(pid: int, t0: float) -> list[dict]:
    """Per-device busy counter tracks for the Perfetto export: a 'C'
    sample rising to 1 at each merged busy-span start and falling to 0
    at its end.  `t0` is the process trace origin (`core._T0`)."""
    now = time.perf_counter()
    events, _ = _snapshot_events(clamp_open_to=now)
    busy_by_dev: dict[str, list] = {}
    for cls, dev, _label, a, b in events:
        if cls == _BUSY:
            busy_by_dev.setdefault(dev, []).append((a, b))
    out = []

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 3)

    for dev, iv in sorted(busy_by_dev.items()):
        name = f"pipeline.device_busy.{dev}"
        for a, b in _merge(iv):
            out.append({"name": name, "ph": "C", "cat": "cst",
                        "pid": pid, "tid": 0, "ts": us(a),
                        "args": {"busy": 1}})
            out.append({"name": name, "ph": "C", "cat": "cst",
                        "pid": pid, "tid": 0, "ts": us(b),
                        "args": {"busy": 0}})
    return out
