"""Env-gated telemetry for the device hot path — spans, counters,
histograms, and three exporters.

The observability backbone the ROADMAP's perf items read: every open
question there (compile-vs-execute split of the 81s attestation
first-call, the `_MSM_DEVICE_MIN=16` host/device break-even, bucket
padding waste, tier-1 wall-time attribution) is answered from this
registry rather than a single end-to-end number — the decomposition-
first methodology of the committee-signature measurement literature
(arXiv:2302.00418, arXiv:2602.06655).

Gates (all collection OFF by default, disabled paths are a flag check):

    CST_TELEMETRY=1       collect spans/counters/histograms in-process
    CST_TRACE_FILE=f.json also write a Chrome trace-event file at exit
                          (Perfetto / chrome://tracing loadable)

Surface:

    span(name, **attrs)   nestable wall-clock section (ctx manager);
                          passes through to jax.profiler.TraceAnnotation
                          when jax is live, so the same names appear in
                          XLA device profiles
    count(name, n=1)      monotonic counter
    observe(name, v)      histogram sample (count/total/min/max)
    gauge(name, v)        level sample (serve queue depth, in-flight
                          batches): can go down, and each sample is a
                          Chrome-trace 'C' counter event
    set_meta(k, v)        one-shot string/num metadata (cache dir, ...)
    add_event(name, dur)  record an externally-measured duration as a
                          closed span (derived phase accounting)
    span_seconds(name)    one span's cumulative total_s — point read
    first_call(key)       True once per key — compile-vs-run attribution
    snapshot()            the whole registry as a dict (stable schema)
    reset(), configure(), enabled()
    write_jsonl(path), write_chrome_trace(path), chrome_trace()
    bench_block(), validate_bench_block()   the bench JSON sub-object

Cost model (`costmodel` submodule, gated CST_TELEMETRY + CST_COSTMODEL):
per-kernel XLA cost/memory analysis (`costmodel.capture`), roofline
utilization + compute/memory/launch-bound classification against the
per-backend peak registry (`costmodel.block`), and per-device live-
buffer watermarks sampled at span boundaries
(`costmodel.sample_watermark`).  Flows into `snapshot()["costmodel"]`,
the bench `"telemetry"` sub-object, the Chrome trace ('C' counter
events), and the benchwatch report's Utilization section.

Benchwatch (longitudinal layer, not re-exported here): `history.py`
ingests bench/telemetry rounds into the schema-versioned
`out/bench_history.jsonl` store, and `python -m
consensus_specs_tpu.telemetry.report` renders the trend/threshold/
attribution dashboard and gates on regressions.

Live monitoring (`metrics_export` + `monitor` submodules): a zero-dep
Prometheus text-exposition endpoint (`CST_METRICS_PORT`) publishing the
registry/reqtrace/costmodel/serve-status surfaces per scrape, and the
declarative SLO watchdog (`CST_SLO_RULES` rules, rolling windows,
breach→clear hysteresis, typed `SloBreach` events with worst-N reqtrace
exemplars and an optional `CST_PROFILE_ON_BREACH` profiler grab).  The
watchdog's round summary rides the serve block (`"slo"` sub-object,
`validate_slo_block`), is mined into `slo::*` history records, and
renders as the report's "SLO" section.

Occupancy + flight recorder (`occupancy` + `flightrec` submodules):
the per-device busy/bubble interval ledger (`CST_OCCUPANCY`) that
attributes every idle gap in the serve pipeline to {host_prep,
queue_starved, settle_serialized, drain} and scores how much host prep
hid under device wall (the serve block's `"occupancy"` sub-object,
`pipeline::*` history records, the report's "Pipeline occupancy"
section, per-device Chrome busy tracks, `cst_serve_device_busy_frac`
exposition), and the bounded cross-stack incident event ring whose
`dump_bundle()` freezes breaker/fault/mesh/SLO/occupancy evidence into
one self-contained directory on watchdog breach, poison storm, or
`python -m consensus_specs_tpu.telemetry.flightrec`.

Zero dependencies (stdlib only); never imports jax, numpy, or any spec
module — safe to import from anywhere, including before backend pinning.
"""

from . import costmodel, flightrec, metrics_export, monitor, occupancy, reqtrace
from .core import (
    add_event,
    configure,
    count,
    counter_value,
    enabled,
    first_call,
    gauge,
    observe,
    reset,
    set_meta,
    snapshot,
    span,
    span_seconds,
)
from .export import (
    bench_block,
    chrome_trace,
    embed_bench_block,
    validate_bench_block,
    validate_checkpoint_block,
    validate_costmodel_block,
    validate_das_block,
    validate_das_producer_block,
    validate_forkchoice_block,
    validate_latency_attribution,
    validate_mesh_block,
    validate_occupancy_block,
    validate_resilience_block,
    validate_scaling_block,
    validate_serve_block,
    validate_slo_block,
    write_chrome_trace,
    write_jsonl,
)

__all__ = [
    "add_event", "configure", "costmodel", "count", "counter_value",
    "enabled", "first_call", "flightrec", "gauge", "metrics_export",
    "monitor", "observe", "occupancy", "reqtrace", "reset",
    "set_meta",
    "snapshot", "span", "span_seconds", "bench_block", "chrome_trace",
    "embed_bench_block", "validate_bench_block",
    "validate_checkpoint_block", "validate_costmodel_block",
    "validate_das_block", "validate_das_producer_block",
    "validate_forkchoice_block",
    "validate_latency_attribution",
    "validate_mesh_block", "validate_occupancy_block",
    "validate_resilience_block", "validate_scaling_block",
    "validate_serve_block", "validate_slo_block",
    "write_chrome_trace", "write_jsonl",
]
