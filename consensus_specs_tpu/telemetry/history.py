"""Benchwatch ingester: bench/telemetry rounds -> one longitudinal store.

Every perf measurement this repo produces lands somewhere different —
`BENCH_r*.json` / `MULTICHIP_r*.json` driver-round wrappers (a stdout
tail with JSON metric lines buried between log lines), the persisted
pure-Python oracle baselines (`bench_baseline.json`,
`bench_bls_baseline.json`), the pytest end-of-session telemetry
snapshot (`CST_TELEMETRY_OUT`, per-test spans), and live bench
emissions.  This module parses all of them into ONE schema-versioned
record shape and appends it to a JSON-lines history store
(`out/bench_history.jsonl`), so `telemetry.report` can compute trends
instead of a human re-reading raw tails.

Record schema (version `SCHEMA`; one JSON object per line):

    {"schema": 1,
     "source": "bench_round" | "multichip_round" | "baseline"
               | "bench_emit" | "pytest_snapshot" | "costmodel",
     "metric": str,              # e.g. "attestation_batch_128x64_verify_wall"
     "value":  float | None,     # the measurement (unit below)
     "unit":   str,              # "s", "us", "bool", ...
     # optional provenance / context:
     "vs_baseline": float,       # speedup over the pure-Python oracle
     "round": int,               # BENCH_rNN / MULTICHIP_rNN round number
     "file": str,                # basename the record was parsed from
     "rc": int,                  # driver wrapper return code
     "platform": str,            # "tpu" | "cpu" | "cpu-fallback" | ...
     "baseline_us_per_validator": float,   # oracle fingerprint (flagship)
     "telemetry": dict,          # compact compile_s/run_s/padding/routing
     "detail": dict,             # msm break-even per-size table
     "msm_device_min": int,
     "costmodel": dict,          # one kernel's joined roofline record
                                 # (source "costmodel" only; metric
                                 # "costmodel::<kernel>" per kernel plus
                                 # "device_mem_high_water::<device>")
     "serve": dict,              # compacted sustained-load block
                                 # (source "serve" only; metric
                                 # "serve::<metric>" — verifies/sec,
                                 # p50/p99, queue-depth histogram,
                                 # steady flag, window rates)
     "latency": dict,            # compacted tail-latency attribution
                                 # (source "latency"; per kind
                                 # "latency::p99_ms@<kind>" carrying the
                                 # component decomposition, plus
                                 # "latency::p99_queue_frac" — the
                                 # serve-p99-queue-frac advisory row's
                                 # surface, carrying the worst-N
                                 # exemplar traces)
     "slo": dict,                # compacted SLO-watchdog round summary
                                 # (source "slo"; metric
                                 # "slo::breaches[@<rule>]" /
                                 # "slo::worst_margin@<rule>" /
                                 # "slo::clean_round" — the watchdog's
                                 # breach counts, per-rule worst
                                 # margins, and the non-chaos
                                 # clean-round 0/1 gate)
     "occupancy": dict,          # compacted device-occupancy block
                                 # (source "pipeline"; metric
                                 # "pipeline::busy_frac" — the
                                 # serve-occupancy threshold row's
                                 # surface — plus
                                 # "pipeline::bubble@<cause>" seconds
                                 # and "pipeline::overlap_score")
     "resilience": dict,         # compacted chaos-round block (source
                                 # "resilience" only; metric
                                 # "resilience::<metric>" — recovery
                                 # latency, wrong-result count, degraded
                                 # throughput, breaker transitions,
                                 # Merkle heal wall)
     "mesh": dict,               # compacted shard-loss recovery block
                                 # (source "mesh"; metric
                                 # "mesh::<metric>" — recovery latency,
                                 # lost/wrong statements, degraded
                                 # lanes, re-admissions)
     "checkpoint": dict,         # compacted restore block (source
                                 # "checkpoint"; metric
                                 # "checkpoint::<metric>" — restore wall
                                 # w/ restore-vs-rebuild speedup as
                                 # vs_baseline, journal depth, snapshot
                                 # bytes)
     "das": dict,                # compacted PeerDAS sampling-matrix
                                 # block (source "das"; metric
                                 # "das::verify_wall@<cols>x<blobs>"
                                 # per swept matrix + "das::speedup"
                                 # vs the pure-Python oracle +
                                 # "das::cells_per_s" throughput)
     "forkchoice": dict,         # compacted device LMD-GHOST tree
                                 # block (source "forkchoice"; metric
                                 # "forkchoice::head_wall@<b>x<v>" per
                                 # swept tree + "forkchoice::speedup"
                                 # vs the phase0 spec oracle +
                                 # "forkchoice::heads_per_s")
     "scaling": dict,            # compacted mesh-sharded flagship rung
                                 # (source "scaling"; metric
                                 # "scaling::flagship@<n>" per rung wall
                                 # + "scaling::efficiency[@<n>]" per-chip
                                 # throughput retention +
                                 # "scaling::flagship_8m_ok")
     "ts": float}                # wall-clock stamp (live emissions only)

Robustness contract (pinned by tests/test_benchwatch.py): malformed or
truncated inputs — a round that timed out before printing JSON, a
traceback tail, a non-JSON file, a history line with an unknown schema
version — are SKIPPED with a counted warning, never a crash.  A perf
dashboard that dies on the exact rounds that failed would be useless on
the rounds that matter most.

Stdlib-only, like the rest of the telemetry package: importing this
never touches jax, numpy, or a spec build.
"""

from __future__ import annotations

import json
import os
import re
from pathlib import Path

SCHEMA = 1

SOURCES = ("bench_round", "multichip_round", "baseline", "bench_emit",
           "pytest_snapshot", "costmodel", "serve", "resilience",
           "mesh", "checkpoint", "scaling", "das", "forkchoice",
           "latency", "slo", "pipeline")

_ROUND_FILE_RE = re.compile(r"(?:BENCH|MULTICHIP)_r(\d+)\.json$")

# stderr log lines worth mining from a round tail: the oracle-baseline
# fingerprint (tells the trend engine whether two rounds' vs_baseline
# numbers are even comparable) and the per-config compile+first walls
# (the ROADMAP's "< 40s" acceptance target predates the telemetry
# sub-object, so old rounds only carry them as log lines)
# two baseline log formats: fresh measure puts us/validator in parens
# ("baseline: 77.6s @ 1024 validators (75802.3 us/validator)"),
# persisted loads print it after the paren group ("baseline (persisted
# 2026-07-29): 244.6 us/validator @ 1024 validators")
_BASELINE_LINE_RE = re.compile(
    r"\(([0-9.]+)\s*us/validator\)"
    r"|baseline\s*\([^)]*\):\s*([0-9.]+)\s*us/validator")
_COMPILE_FIRST_RES = (
    (re.compile(r"compile\+first run ([0-9.]+)s"),
     "epoch_sweep_compile_first_s"),
    (re.compile(r"attestation batch compile\+first: ([0-9.]+)s"),
     "attestation_batch_compile_first_s"),
    (re.compile(r"sync aggregate compile\+first: ([0-9.]+)s"),
     "sync_aggregate_compile_first_s"),
    (re.compile(r"kzg batch device compile\+first: ([0-9.]+)s"),
     "blob_kzg_batch_compile_first_s"),
)


# --- record shape ------------------------------------------------------------


def make_record(source: str, metric: str, value, unit: str = "s",
                **extra) -> dict:
    """One normalized history record.  `extra` keys with value None are
    dropped so the JSONL stays compact and byte-stable (dedup hashes
    the canonical line)."""
    rec = {"schema": SCHEMA, "source": source, "metric": metric,
           "value": value, "unit": unit}
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


def validate_record(rec) -> list[str]:
    """Problems with one history record (empty == valid).  The contract
    `bench_smoke.py` asserts on every live emission."""
    problems: list[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not dict"]
    if rec.get("schema") != SCHEMA:
        problems.append(f"schema must be {SCHEMA}, got {rec.get('schema')!r}")
    if rec.get("source") not in SOURCES:
        problems.append(f"unknown source {rec.get('source')!r}")
    if not isinstance(rec.get("metric"), str) or not rec.get("metric"):
        problems.append(f"metric must be a non-empty str, "
                        f"got {rec.get('metric')!r}")
    v = rec.get("value")
    if v is not None and (not isinstance(v, (int, float))
                          or isinstance(v, bool)):
        problems.append(f"value must be a number or null, got {v!r}")
    if not isinstance(rec.get("unit"), str):
        problems.append(f"unit must be a str, got {rec.get('unit')!r}")
    vb = rec.get("vs_baseline")
    if vb is not None and (not isinstance(vb, (int, float))
                           or isinstance(vb, bool)):
        problems.append(f"vs_baseline must be a number, got {vb!r}")
    return problems


def _canonical_line(rec: dict) -> str:
    return json.dumps(rec, sort_keys=True, separators=(",", ":"))


def _compact_telemetry(tel) -> dict | None:
    """The compile/run + padding + routing core of a bench telemetry
    sub-object; the full counter registry stays in the round file.  The
    `costmodel` watermark summary rides along compactly (per-kernel
    cost records become their own `costmodel`-source records instead —
    see `costmodel_records`)."""
    if not isinstance(tel, dict):
        return None
    out = {k: tel[k] for k in ("compile_s", "run_s", "padding", "routing")
           if k in tel}
    cm = tel.get("costmodel")
    if isinstance(cm, dict) and isinstance(cm.get("watermarks"), dict) \
            and cm["watermarks"]:
        out["watermarks"] = cm["watermarks"]
    return out or None


def serve_records(metric: str, serve, chaos: bool = False,
                  **context) -> list[dict]:
    """`serve`-source history records mined from one metric line's
    sustained-load `"serve"` sub-object (`serve.loadgen.run_load`'s
    block): one scalar record each for the steady-state throughput and
    the latency percentiles — the threshold-gate surface — with the
    compacted block (steady flag, window rates, queue-depth histogram,
    mode/shape knobs) riding on the throughput record.  `chaos` marks a
    chaos round (bench_serve hoists the `"resilience"` sub-object to
    the metric line's top level, so the caller must pass the flag) —
    it gates `slo::clean_round` off.  Malformed blocks yield zero
    records, never an exception."""
    vps = serve.get("verifies_per_s") if isinstance(serve, dict) else None
    if not isinstance(vps, (int, float)) or isinstance(vps, bool):
        return []
    compact = {k: serve[k] for k in (
        "steady", "windows", "window_s", "duration_s", "mode",
        "rate_multiple", "max_batch", "depth", "submitted", "settled",
        "failed", "rechecks", "batches", "queue_depth", "inflight_max",
        "retries", "fallbacks", "shed")
        if k in serve}
    if isinstance(serve.get("latency_source"), str):
        compact["latency_source"] = serve["latency_source"]
    records = [make_record(
        "serve", "serve::verifies_per_s", serve["verifies_per_s"],
        unit="verifies/s", serve=compact, via_metric=metric, **context)]
    for key, unit in (("p50_ms", "ms"), ("p99_ms", "ms")):
        v = serve.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            records.append(make_record(
                "serve", f"serve::{key}", v, unit=unit,
                via_metric=metric, **context))
    records.extend(latency_records(
        metric, serve.get("latency_attribution"), **context))
    records.extend(slo_records(
        metric, serve.get("slo"),
        chaos=chaos or isinstance(serve.get("resilience"), dict),
        **context))
    records.extend(occupancy_records(
        metric, serve.get("occupancy"), **context))
    return records


def occupancy_records(metric: str, occ, **context) -> list[dict]:
    """`pipeline`-source history records mined from a serve block's
    `"occupancy"` sub-object (`telemetry.occupancy.block`, rounds armed
    with CST_OCCUPANCY): the `pipeline::busy_frac` record — the
    `serve-occupancy` threshold row's surface — carrying the compacted
    block (wall, per-device busy, bubble attribution, depth), one
    `pipeline::bubble@<cause>` seconds record per bubble cause, and
    `pipeline::overlap_score` when any host prep was recorded.
    Malformed blocks yield zero records, never an exception."""
    if not isinstance(occ, dict):
        return []
    frac = occ.get("busy_frac")
    if not isinstance(frac, (int, float)) or isinstance(frac, bool):
        return []
    compact = {k: occ[k] for k in (
        "wall_s", "busy_s", "busy_frac", "bubbles_s", "depth",
        "events", "events_dropped", "device_seconds_by_kind")
        if k in occ}
    devs = occ.get("devices")
    if isinstance(devs, dict):
        compact["devices"] = {
            d: {k: b[k] for k in ("busy_s", "busy_frac", "spans")
                if isinstance(b, dict) and k in b}
            for d, b in devs.items()}
    records = [make_record(
        "pipeline", "pipeline::busy_frac", frac, unit="frac",
        occupancy=compact, via_metric=metric, **context)]
    bub = occ.get("bubbles_s")
    if isinstance(bub, dict):
        for cause, v in sorted(bub.items()):
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                records.append(make_record(
                    "pipeline", f"pipeline::bubble@{cause}", v,
                    unit="s", via_metric=metric, **context))
    ov = occ.get("overlap")
    if isinstance(ov, dict):
        score = ov.get("score")
        if isinstance(score, (int, float)) and not isinstance(score, bool):
            records.append(make_record(
                "pipeline", "pipeline::overlap_score", score,
                unit="frac", overlap=ov, via_metric=metric, **context))
    return records


def slo_records(metric: str, slo, chaos: bool = False,
                **context) -> list[dict]:
    """`slo`-source history records mined from a serve block's `"slo"`
    sub-object (`telemetry.monitor.Watchdog.slo_block`, armed rounds
    only): one `slo::breaches` total carrying the compact block, per
    rule a `slo::breaches@<rule>` count plus `slo::worst_margin@<rule>`
    when the rule ever failed a tick, and — on NON-chaos rounds only —
    the `slo::clean_round` 0/1 record the `slo-clean-round` threshold
    row gates on (a chaos round breaches BY DESIGN; its arc is asserted
    in the round itself and mined as `resilience::slo_arc_ok`).
    Malformed blocks yield zero records, never an exception."""
    if not isinstance(slo, dict):
        return []
    breaches = slo.get("breaches")
    ticks = slo.get("ticks")
    if not isinstance(breaches, int) or isinstance(breaches, bool) \
            or not isinstance(ticks, int) or isinstance(ticks, bool):
        return []
    compact = {k: slo[k] for k in (
        "ticks", "breaches", "clean", "breaching_now", "events_dropped")
        if k in slo}
    compact["rules"] = [
        {k: r[k] for k in ("name", "metric", "breaches", "clears",
                           "breaching", "worst_margin", "last_value")
         if k in r}
        for r in slo.get("rules", []) if isinstance(r, dict)]
    if slo.get("profiles"):
        compact["profiles"] = slo["profiles"]
    records = [make_record(
        "slo", "slo::breaches", breaches, unit="count", slo=compact,
        via_metric=metric, **context)]
    for r in slo.get("rules", []):
        if not isinstance(r, dict) or not isinstance(r.get("name"), str) \
                or not r.get("name"):
            continue
        rb = r.get("breaches")
        if isinstance(rb, int) and not isinstance(rb, bool):
            records.append(make_record(
                "slo", f"slo::breaches@{r['name']}", rb, unit="count",
                via_metric=metric, **context))
        wm = r.get("worst_margin")
        if isinstance(wm, (int, float)) and not isinstance(wm, bool):
            records.append(make_record(
                "slo", f"slo::worst_margin@{r['name']}", wm,
                unit="margin", via_metric=metric, **context))
    if not chaos and isinstance(slo.get("clean"), bool):
        records.append(make_record(
            "slo", "slo::clean_round", 1.0 if slo["clean"] else 0.0,
            unit="bool", via_metric=metric, **context))
    return records


def latency_records(metric: str, la, **context) -> list[dict]:
    """`latency`-source history records mined from a serve block's
    `latency_attribution` sub-object (`telemetry.reqtrace.attribution`,
    traced rounds only): one `latency::p99_ms@<kind>` record per
    request kind carrying the compacted per-kind block (p50/p90/p99,
    component decomposition, outcome counts), and one
    `latency::p99_queue_frac` record — the `serve-p99-queue-frac`
    advisory threshold row's surface — carrying the worst-N exemplar
    traces.  Malformed blocks yield zero records, never an
    exception."""
    if not isinstance(la, dict) or not isinstance(la.get("kinds"), dict):
        return []
    records: list[dict] = []
    for kind, blk in sorted(la["kinds"].items()):
        if not isinstance(blk, dict):
            continue
        p99 = blk.get("p99_ms")
        if not isinstance(p99, (int, float)) or isinstance(p99, bool):
            continue
        compact = {k: blk[k] for k in (
            "count", "p50_ms", "p90_ms", "p99_ms", "mean_components_ms",
            "p99_components_ms", "p99_queue_frac", "outcomes")
            if k in blk}
        records.append(make_record(
            "latency", f"latency::p99_ms@{kind}", p99, unit="ms",
            latency=compact, via_metric=metric, **context))
    frac = la.get("p99_queue_frac")
    if isinstance(frac, (int, float)) and not isinstance(frac, bool):
        records.append(make_record(
            "latency", "latency::p99_queue_frac", frac, unit="frac",
            latency={"worst": la.get("worst") or [],
                     "requests": la.get("requests"),
                     "answered": la.get("answered")},
            via_metric=metric, **context))
    return records


def resilience_records(metric: str, res, **context) -> list[dict]:
    """`resilience`-source history records mined from one metric line's
    chaos-round `"resilience"` sub-object
    (`resilience.chaos.run_chaos_load`): one scalar record per recovery
    metric — `resilience::recovery_latency_s` (the `chaos-recovery`
    threshold row's surface, carrying the compacted block),
    `resilience::wrong_results` (the correctness gate),
    degraded/baseline throughput, fault/transition counts, and the
    Merkle heal wall.  Malformed blocks yield zero records, never an
    exception."""
    if not isinstance(res, dict) or not isinstance(res.get("chaos"), bool):
        return []
    compact = {k: res[k] for k in (
        "chaos", "faults_injected", "injected_sites", "fault_victims",
        "wrong_results",
        "failed_requests", "checked_results", "recovered", "retries",
        "fallbacks", "shed") if k in res}
    br = res.get("breaker")
    if isinstance(br, dict):
        compact["breaker_states"] = br.get("states")
        compact["breaker_trips"] = br.get("trips")
    records = [make_record(
        "resilience", "resilience::recovery_latency_s",
        res.get("recovery_latency_s"), unit="s", resilience=compact,
        via_metric=metric, **context)]

    def scalar(key, name, unit):
        v = res.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            records.append(make_record(
                "resilience", name, v, unit=unit, via_metric=metric,
                **context))

    # recovered as its own 0/1 record (the chaos-recovered threshold
    # row): the latency record above carries value None on an
    # unrecovered round, which a numeric threshold cannot see — without
    # this, a failed round would silently leave the previous successful
    # round's PASS on the dashboard
    if isinstance(res.get("recovered"), bool):
        records.append(make_record(
            "resilience", "resilience::recovered",
            1.0 if res["recovered"] else 0.0, unit="bool",
            via_metric=metric, **context))
    scalar("wrong_results", "resilience::wrong_results", "count")
    scalar("degraded_verifies_per_s",
           "resilience::degraded_verifies_per_s", "verifies/s")
    scalar("baseline_verifies_per_s",
           "resilience::baseline_verifies_per_s", "verifies/s")
    scalar("faults_injected", "resilience::faults_injected", "count")
    if isinstance(br, dict) and isinstance(br.get("transitions"), list):
        records.append(make_record(
            "resilience", "resilience::breaker_transitions",
            len(br["transitions"]), unit="count", via_metric=metric,
            **context))
    heal = res.get("heal")
    if isinstance(heal, dict) and isinstance(heal.get("recovery_s"),
                                             (int, float)):
        records.append(make_record(
            "resilience", "resilience::merkle_heal_s",
            heal["recovery_s"], unit="s", via_metric=metric,
            heal_path=heal.get("path"), **context))
    fl = res.get("flagship")
    if isinstance(fl, dict) and isinstance(fl.get("degraded_steps"), int) \
            and not isinstance(fl.get("degraded_steps"), bool):
        records.append(make_record(
            "resilience", "resilience::flagship_degraded_steps",
            fl["degraded_steps"], unit="count", via_metric=metric,
            flagship={k: fl[k] for k in ("wrong_results",
                                         "checked_settles", "recovered")
                      if k in fl},
            **context))
    # the chaos round's watchdog arc as a 0/1 gate record: breached
    # inside the fault window AND cleared after recovery (the inverse
    # of slo::clean_round — a chaos round that stayed clean means the
    # watchdog missed a live incident)
    arc = res.get("slo_arc")
    if isinstance(arc, dict) \
            and isinstance(arc.get("breached_in_fault_window"), bool) \
            and isinstance(arc.get("cleared_after_recovery"), bool):
        ok = (arc["breached_in_fault_window"]
              and arc["cleared_after_recovery"])
        records.append(make_record(
            "resilience", "resilience::slo_arc_ok",
            1.0 if ok else 0.0, unit="bool", slo_arc=arc,
            via_metric=metric, **context))
    records.extend(mesh_records(metric, res.get("mesh"), **context))
    records.extend(checkpoint_records(metric, res.get("checkpoint"),
                                      **context))
    return records


def mesh_records(metric: str, mesh, **context) -> list[dict]:
    """`mesh`-source history records mined from a chaos round's
    `"mesh"` sub-object (`resilience.mesh.MeshVerifier.block` plus the
    segment's correctness counters): the shard-loss recovery latency
    (carrying the compact block — the `mesh-recovery` threshold row's
    surface), lost/wrong statement counts (the zero-loss gate), and
    the degradation/re-admission counters.  Skipped segments (too few
    devices) and malformed blocks yield zero records."""
    if not isinstance(mesh, dict) or "skipped" in mesh \
            or not isinstance(mesh.get("devices"), int):
        return []
    compact = {k: mesh[k] for k in (
        "devices", "degraded_lanes", "max_degraded_lanes",
        "device_lost_events", "readmissions", "retrips", "redispatches",
        "recoveries", "verified_statements", "lost_statements",
        "wrong_results", "checked_statements", "readmitted",
        "recovered") if k in mesh}
    records = [make_record(
        "mesh", "mesh::recovery_latency_s",
        mesh.get("recovery_latency_s"), unit="s", mesh=compact,
        via_metric=metric, **context)]
    # recovered as its own 0/1 record (the mesh-recovered threshold
    # row): an unrecovered round's latency record carries value null,
    # which a numeric threshold skips — without this the previous
    # round's PASS would stand (same fix as resilience::recovered)
    if isinstance(mesh.get("recovered"), bool):
        records.append(make_record(
            "mesh", "mesh::recovered",
            1.0 if mesh["recovered"] else 0.0, unit="bool",
            via_metric=metric, **context))

    def scalar(key, name, unit="count"):
        v = mesh.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            records.append(make_record(
                "mesh", name, v, unit=unit, via_metric=metric,
                **context))

    scalar("lost_statements", "mesh::lost_statements")
    scalar("wrong_results", "mesh::wrong_results")
    scalar("max_degraded_lanes", "mesh::degraded_lanes")
    scalar("device_lost_events", "mesh::device_lost_events")
    scalar("readmissions", "mesh::readmissions")
    return records


def checkpoint_records(metric: str, cp, **context) -> list[dict]:
    """`checkpoint`-source history records mined from a chaos round's
    `"checkpoint"` sub-object (`resilience.chaos._checkpoint_segment`):
    the restore wall with the restore-vs-rebuild speedup as its
    `vs_baseline` (the `checkpoint-restore` threshold row evaluates
    that field), plus journal depth and snapshot size.  Malformed
    blocks yield zero records."""
    if not isinstance(cp, dict) \
            or not isinstance(cp.get("restore_s"), (int, float)) \
            or isinstance(cp.get("restore_s"), bool):
        return []
    compact = {k: cp[k] for k in (
        "n_chunks", "journal_entries", "journal_replayed",
        "journal_frac", "snapshot_bytes", "rebuild_s", "parity")
        if k in cp}
    speedup = cp.get("speedup")
    records = [make_record(
        "checkpoint", "checkpoint::restore", cp["restore_s"], unit="s",
        vs_baseline=(speedup if isinstance(speedup, (int, float))
                     and not isinstance(speedup, bool) else None),
        checkpoint=compact, via_metric=metric, **context)]
    for key, name, unit in (
            ("journal_entries", "checkpoint::journal_entries", "count"),
            ("snapshot_bytes", "checkpoint::snapshot_bytes", "bytes")):
        v = cp.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            records.append(make_record(
                "checkpoint", name, v, unit=unit, via_metric=metric,
                **context))
    return records


def scaling_records(metric: str, sc, **context) -> list[dict]:
    """`scaling`-source history records mined from one metric line's
    mesh-sharded flagship `"scaling"` sub-object (`bench.py --worker
    scaling`): per rung a `scaling::flagship@<n_validators>` wall
    record (carrying the compact rung block — n_devices, per-chip and
    single-chip throughput) and a `scaling::efficiency@<n>` per-chip
    retention record; one `scaling::efficiency` summary record (the
    LARGEST completed rung at the widest mesh — the threshold-gate
    surface, so a small rung's tie can never outrank it) and a
    `scaling::flagship_8m_ok` 0/1 record when an 8M-validator rung was
    attempted.  Malformed blocks yield zero records, never an
    exception."""
    if not isinstance(sc, dict) or not isinstance(sc.get("rungs"), list):
        return []
    records: list[dict] = []
    best = None
    for r in sc["rungs"]:
        if not isinstance(r, dict):
            continue
        n = r.get("n_validators")
        wall = r.get("wall_s")
        if not isinstance(n, int) or isinstance(n, bool) \
                or not isinstance(wall, (int, float)) \
                or isinstance(wall, bool):
            continue
        compact = {k: r[k] for k in (
            "n_validators", "n_devices", "per_chip_vps", "total_vps",
            "single_chip_wall_s", "single_chip_vps", "efficiency")
            if k in r}
        records.append(make_record(
            "scaling", f"scaling::flagship@{n}", wall, unit="s",
            scaling=compact, via_metric=metric, **context))
        eff = r.get("efficiency")
        if isinstance(eff, (int, float)) and not isinstance(eff, bool):
            records.append(make_record(
                "scaling", f"scaling::efficiency@{n}", eff,
                unit="ratio", via_metric=metric, **context))
            key = (n, r.get("n_devices") or 0)
            if best is None or key > best[0]:
                best = (key, eff, compact)
    if best is not None:
        records.append(make_record(
            "scaling", "scaling::efficiency", best[1], unit="ratio",
            scaling=best[2], via_metric=metric, **context))
    if isinstance(sc.get("ok_8m"), bool):
        records.append(make_record(
            "scaling", "scaling::flagship_8m_ok",
            1.0 if sc["ok_8m"] else 0.0, unit="bool",
            via_metric=metric, **context))
    return records


def das_records(metric: str, das, **context) -> list[dict]:
    """`das`-source history records mined from one metric line's
    PeerDAS `"das"` sub-object (`bench.py --worker das` /
    `bench_smoke.py --das`): the verification wall for the swept
    sampling matrix (carrying the compact block, speedup as
    `vs_baseline`), the `das::speedup` record the CPU-evaluated
    `das-speedup` threshold row gates on, and the `das::cells_per_s`
    throughput record the TPU-gated `das-throughput` row reads.
    Malformed blocks yield zero records, never an exception."""
    if not isinstance(das, dict):
        return []
    matrix = das.get("matrix")
    wall = das.get("verify_wall_s")
    if not isinstance(matrix, dict) \
            or not isinstance(wall, (int, float)) \
            or isinstance(wall, bool):
        return []
    cols, blobs = matrix.get("columns"), matrix.get("blobs")
    if not isinstance(cols, int) or not isinstance(blobs, int) \
            or isinstance(cols, bool) or isinstance(blobs, bool):
        return []
    compact = {k: das[k] for k in (
        "matrix", "rung", "oracle_wall_s", "oracle_cells_measured",
        "compile_first_s", "batch_verdict", "isolate",
        "eval_crosscheck") if k in das}
    speedup = das.get("speedup")
    speedup = speedup if isinstance(speedup, (int, float)) \
        and not isinstance(speedup, bool) else None
    records = [make_record(
        "das", f"das::verify_wall@{cols}x{blobs}", wall, unit="s",
        vs_baseline=speedup, das=compact, via_metric=metric,
        **context)]
    if speedup is not None:
        records.append(make_record(
            "das", "das::speedup", speedup, unit="x",
            via_metric=metric, **context))
    cps = das.get("cells_per_s")
    if isinstance(cps, (int, float)) and not isinstance(cps, bool):
        records.append(make_record(
            "das", "das::cells_per_s", cps, unit="cells/s",
            via_metric=metric, **context))
    return records


def das_producer_records(metric: str, prod, **context) -> list[dict]:
    """`das`-source history records mined from one metric line's
    `"das_producer"` sub-object (the FK20 producer + erasure-recovery
    sweep `bench.py --worker das` emits): `das::produce_wall` (carrying
    the compact block, producer speedup as `vs_baseline`),
    `das::proofs_per_s`, and the `das::producer_speedup` record the
    CPU-evaluated `das-producer-speedup` threshold row gates on; when
    the recovery sub-object is present, `das::recover_wall` plus the
    `das::recover_speedup` record behind `das-recover-speedup`.
    Malformed blocks yield zero records, never an exception."""
    if not isinstance(prod, dict):
        return []

    def _num(v):
        return v if isinstance(v, (int, float)) \
            and not isinstance(v, bool) else None

    wall = _num(prod.get("produce_wall_s"))
    if wall is None:
        return []
    speedup = _num(prod.get("producer_speedup"))
    compact = {k: prod[k] for k in (
        "produce_first_s", "du_wall_s", "du_msms_measured",
        "parity") if k in prod}
    records = [make_record(
        "das", "das::produce_wall", wall, unit="s",
        vs_baseline=speedup, das_producer=compact, via_metric=metric,
        **context)]
    if speedup is not None:
        records.append(make_record(
            "das", "das::producer_speedup", speedup, unit="x",
            via_metric=metric, **context))
    pps = _num(prod.get("proofs_per_s"))
    if pps is not None:
        records.append(make_record(
            "das", "das::proofs_per_s", pps, unit="proofs/s",
            via_metric=metric, **context))
    rec = prod.get("recover")
    if isinstance(rec, dict):
        rwall = _num(rec.get("wall_s"))
        rspeed = _num(rec.get("speedup"))
        if rwall is not None:
            records.append(make_record(
                "das", "das::recover_wall", rwall, unit="s",
                vs_baseline=rspeed,
                das_recover={k: rec[k] for k in (
                    "cells_in", "missing", "oracle_wall_s",
                    "oracle_cosets_measured", "roundtrip") if k in rec},
                via_metric=metric, **context))
        if rspeed is not None:
            records.append(make_record(
                "das", "das::recover_speedup", rspeed, unit="x",
                via_metric=metric, **context))
    return records


def forkchoice_records(metric: str, fc, **context) -> list[dict]:
    """`forkchoice`-source history records mined from one metric
    line's `"forkchoice"` sub-object (`bench.py --worker forkchoice` /
    `bench_smoke.py --forkchoice`): the per-shape head wall (carrying
    the compact block, speedup as `vs_baseline`), the
    `forkchoice::speedup` record the CPU-evaluated `fc-speedup`
    threshold row gates on, and the `forkchoice::heads_per_s` record
    the TPU-gated `fc-head-throughput` row reads.  Malformed blocks
    yield zero records, never an exception."""
    if not isinstance(fc, dict):
        return []
    tree = fc.get("tree")
    wall = fc.get("head_wall_s")
    if not isinstance(tree, dict) \
            or not isinstance(wall, (int, float)) \
            or isinstance(wall, bool):
        return []
    blocks, validators = tree.get("blocks"), tree.get("validators")
    if not isinstance(blocks, int) or not isinstance(validators, int) \
            or isinstance(blocks, bool) or isinstance(validators, bool):
        return []
    compact = {k: fc[k] for k in (
        "tree", "rungs", "apply_wall_s", "oracle_head_wall_s",
        "oracle_validators_measured", "compile_first_s", "parity")
        if k in fc}
    speedup = fc.get("speedup")
    speedup = speedup if isinstance(speedup, (int, float)) \
        and not isinstance(speedup, bool) else None
    records = [make_record(
        "forkchoice", f"forkchoice::head_wall@{blocks}x{validators}",
        wall, unit="s", vs_baseline=speedup, forkchoice=compact,
        via_metric=metric, **context)]
    if speedup is not None:
        records.append(make_record(
            "forkchoice", "forkchoice::speedup", speedup, unit="x",
            via_metric=metric, **context))
    hps = fc.get("heads_per_s")
    if isinstance(hps, (int, float)) and not isinstance(hps, bool):
        records.append(make_record(
            "forkchoice", "forkchoice::heads_per_s", hps,
            unit="heads/s", via_metric=metric, **context))
    return records


def costmodel_records(metric: str, tel, **context) -> list[dict]:
    """Per-kernel `costmodel`-source history records mined from one
    metric line's telemetry sub-object (joined roofline records from
    `telemetry.costmodel.block`).  Malformed blocks yield zero records,
    never an exception — same degradation policy as every other parser
    here.  `context` carries provenance (round/file/rc/platform/ts)."""
    if not isinstance(tel, dict):
        return []
    cm = tel.get("costmodel")
    if not isinstance(cm, dict) or not isinstance(cm.get("kernels"), dict):
        return []
    records = []
    for kernel, rec in sorted(cm["kernels"].items()):
        if not isinstance(rec, dict) or "error" in rec:
            continue
        run_s = rec.get("run_s_mean")
        records.append(make_record(
            "costmodel", f"costmodel::{kernel}",
            run_s if isinstance(run_s, (int, float)) else None,
            unit="s", costmodel=rec, via_metric=metric, **context))
    wms = cm.get("watermarks")
    if isinstance(wms, dict):
        for dev, wm in sorted(wms.items()):
            if isinstance(wm, dict) and isinstance(
                    wm.get("high_water_bytes"), int):
                records.append(make_record(
                    "costmodel", f"device_mem_high_water::{dev}",
                    wm["high_water_bytes"], unit="bytes",
                    samples=wm.get("samples"), **context))
    return records


# --- bench round tails -------------------------------------------------------


def _tail_json_lines(tail: str) -> list[dict]:
    out = []
    for line in tail.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue    # truncated mid-line — the enclosing round warns
        if isinstance(obj, dict) and "metric" in obj:
            out.append(obj)
    return out


def _merge_metric_lines(lines: list[dict]) -> dict[str, dict]:
    """bench.py re-prints the flagship line as a growing superset after
    each extras worker; later occurrences win, and the `extra` map is
    flattened into per-metric records (each already carries its own
    value/unit/vs_baseline/telemetry)."""
    merged: dict[str, dict] = {}
    for obj in lines:
        flat = dict(obj)
        extras = flat.pop("extra", None) or {}
        merged[flat["metric"]] = flat
        platform = flat.get("platform")
        for name, sub in extras.items():
            if not isinstance(sub, dict):
                continue
            sub = dict(sub)
            sub.setdefault("metric", name)
            if platform is not None:
                sub.setdefault("platform", platform)
            merged[name] = sub
    return merged


def parse_bench_round(path) -> tuple[list[dict], list[str]]:
    """All history records extractable from one BENCH_rNN.json wrapper.
    A round whose tail has no parseable metric line (timeout, crash)
    yields zero metric records and one warning — never an exception."""
    path = Path(path)
    warnings: list[str] = []
    m = _ROUND_FILE_RE.search(path.name)
    rnd = int(m.group(1)) if m else None
    try:
        wrapper = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        return [], [f"{path.name}: unreadable round wrapper "
                    f"({type(e).__name__}: {e})"]
    if not isinstance(wrapper, dict):
        return [], [f"{path.name}: round wrapper is not a JSON object"]
    rnd = wrapper.get("n", rnd) if isinstance(wrapper.get("n"), int) else rnd
    rc = wrapper.get("rc")
    tail = wrapper.get("tail") or ""
    if not isinstance(tail, str):
        return [], [f"{path.name}: round tail is not a string"]

    fingerprint = None
    fm = _BASELINE_LINE_RE.search(tail)
    if fm:
        fingerprint = float(fm.group(1) or fm.group(2))

    records: list[dict] = []
    # cost records are cumulative per-process facts, so every metric
    # line in a round carries (a superset of) the previous line's
    # costmodel block — keep ONE record per kernel/device, last line
    # wins (it has the most dispatches joined in)
    cost_by_metric: dict[str, dict] = {}
    merged = _merge_metric_lines(_tail_json_lines(tail))
    for name, obj in merged.items():
        rec = make_record(
            "bench_round", name, obj.get("value"),
            unit=obj.get("unit", "s"),
            vs_baseline=obj.get("vs_baseline"),
            round=rnd, file=path.name, rc=rc,
            platform=obj.get("platform"),
            telemetry=_compact_telemetry(obj.get("telemetry")),
            detail=obj.get("detail"),
            msm_device_min=obj.get("msm_device_min"),
            error=obj.get("error"),
        )
        if name == "mainnet_epoch_sweep_1m_validators_wall" and fingerprint:
            rec["baseline_us_per_validator"] = fingerprint
        records.append(rec)
        records.extend(serve_records(
            name, obj.get("serve"),
            chaos=isinstance(obj.get("resilience"), dict),
            round=rnd, file=path.name,
            rc=rc, platform=obj.get("platform")))
        records.extend(resilience_records(
            name, obj.get("resilience"), round=rnd, file=path.name,
            rc=rc, platform=obj.get("platform")))
        records.extend(scaling_records(
            name, obj.get("scaling"), round=rnd, file=path.name,
            rc=rc, platform=obj.get("platform")))
        records.extend(das_records(
            name, obj.get("das"), round=rnd, file=path.name,
            rc=rc, platform=obj.get("platform")))
        records.extend(das_producer_records(
            name, obj.get("das_producer"), round=rnd, file=path.name,
            rc=rc, platform=obj.get("platform")))
        records.extend(forkchoice_records(
            name, obj.get("forkchoice"), round=rnd, file=path.name,
            rc=rc, platform=obj.get("platform")))
        for crec in costmodel_records(
                name, obj.get("telemetry"), round=rnd, file=path.name,
                rc=rc, platform=obj.get("platform")):
            cost_by_metric[crec["metric"]] = crec
    records.extend(cost_by_metric.values())

    # compile+first walls from the stderr log lines; a metric record's
    # telemetry block is the second source when the log line is gone
    for cf_re, cf_metric in _COMPILE_FIRST_RES:
        cm = cf_re.search(tail)
        if cm:
            records.append(make_record(
                "bench_round", cf_metric, float(cm.group(1)),
                round=rnd, file=path.name, rc=rc))

    if not merged:
        warnings.append(
            f"{path.name}: no parseable metric line in round tail "
            f"(rc={rc}) — skipped")
    return records, warnings


def parse_multichip_round(path) -> tuple[list[dict], list[str]]:
    path = Path(path)
    m = _ROUND_FILE_RE.search(path.name)
    rnd = int(m.group(1)) if m else None
    try:
        wrapper = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        return [], [f"{path.name}: unreadable round wrapper "
                    f"({type(e).__name__}: {e})"]
    if not isinstance(wrapper, dict) or "ok" not in wrapper:
        return [], [f"{path.name}: not a multichip round wrapper"]
    rec = make_record(
        "multichip_round", "multichip_dryrun_ok",
        1.0 if wrapper.get("ok") else 0.0, unit="bool",
        round=rnd, file=path.name, rc=wrapper.get("rc"),
        n_devices=wrapper.get("n_devices"),
        skipped=bool(wrapper.get("skipped")) or None)
    return [rec], []


# --- oracle baselines --------------------------------------------------------


def parse_baseline_file(path) -> tuple[list[dict], list[str]]:
    """bench_baseline.json / bench_bls_baseline.json -> oracle metric
    records (the pure-Python costs every vs_baseline divides by)."""
    path = Path(path)
    try:
        data = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        return [], [f"{path.name}: unreadable baseline "
                    f"({type(e).__name__}: {e})"]
    if not isinstance(data, dict):
        return [], [f"{path.name}: baseline is not a JSON object"]
    mapping = (
        ("seconds_per_validator", "oracle_epoch_us_per_validator",
         "us", 1e6),
        ("oracle_seconds_per_fast_aggregate_verify",
         "oracle_fast_aggregate_verify_s", "s", 1.0),
        ("oracle_seconds_per_sync_aggregate_verify",
         "oracle_sync_aggregate_verify_s", "s", 1.0),
    )
    records = []
    for key, metric, unit, scale in mapping:
        v = data.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            records.append(make_record(
                "baseline", metric, round(v * scale, 6), unit=unit,
                file=path.name, measured_at=data.get("measured_at")))
    if not records:
        return [], [f"{path.name}: no known baseline keys — skipped"]
    return records, []


# --- pytest telemetry snapshot (CST_TELEMETRY_OUT) ---------------------------

# per-test phase aggregates written by tests/conftest.py:
#   "<nodeid> [spec-build]" / "<nodeid> [test-body]"
_PHASE_SUFFIX_RE = re.compile(r"^(?P<test>.+) \[(?P<phase>spec-build|"
                              r"test-body)\]$")


def parse_telemetry_snapshot(path) -> tuple[list[dict], list[dict],
                                            list[str]]:
    """(history_records, per_test_attribution, warnings) from one
    `telemetry.snapshot()` JSON file (the CST_TELEMETRY_OUT artifact).

    History gets the small stuff (tier-1 session wall, spec-build
    total); the per-test attribution rows — one per test nodeid, with
    `total_s` split into `spec_build_s` vs `test_body_s` — go straight
    to the report's top-N table rather than ballooning the store with
    thousands of per-test lines."""
    path = Path(path)
    try:
        snap = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        return [], [], [f"{path.name}: unreadable snapshot "
                        f"({type(e).__name__}: {e})"]
    if not isinstance(snap, dict) or not isinstance(snap.get("spans"), dict):
        return [], [], [f"{path.name}: not a telemetry snapshot — skipped"]

    # the snapshot file's mtime is the record timestamp: snapshots carry
    # no round number, and without a ts every stored tier1_wall_s would
    # tie in the report's latest-wins ordering (the FIRST-ever value
    # would be evaluated forever)
    try:
        ts = round(path.stat().st_mtime, 1)
    except OSError:
        ts = None

    records: list[dict] = []
    meta = snap.get("meta") or {}
    wall = meta.get("tier1.session_wall_s")
    if isinstance(wall, (int, float)) and not isinstance(wall, bool):
        # platform-stamped "cpu": the tier-1 suite always runs on the
        # CPU backend (tests/conftest.py pins it), and an unstamped
        # record would be grouped with the TPU rounds by the report's
        # regression gate — noisy pytest walls must not read as TPU
        # perf regressions
        records.append(make_record(
            "pytest_snapshot", "tier1_wall_s", round(float(wall), 3),
            file=path.name, tests=meta.get("tier1.tests"),
            platform="cpu", ts=ts))

    tests: dict[str, dict] = {}
    spec_build_total = 0.0
    for name, agg in snap["spans"].items():
        if not isinstance(agg, dict):
            continue
        total = agg.get("total_s")
        if not isinstance(total, (int, float)):
            continue
        if name == "spec.build":
            spec_build_total = float(total)
            continue
        pm = _PHASE_SUFFIX_RE.match(name)
        if pm:
            row = tests.setdefault(
                pm.group("test"),
                {"test": pm.group("test"), "total_s": 0.0,
                 "spec_build_s": 0.0, "test_body_s": 0.0})
            key = ("spec_build_s" if pm.group("phase") == "spec-build"
                   else "test_body_s")
            row[key] += float(total)
        elif "::" in name:
            row = tests.setdefault(
                name, {"test": name, "total_s": 0.0,
                       "spec_build_s": 0.0, "test_body_s": 0.0})
            row["total_s"] += float(total)
    for row in tests.values():
        if not row["total_s"]:
            row["total_s"] = row["spec_build_s"] + row["test_body_s"]
    if spec_build_total:
        records.append(make_record(
            "pytest_snapshot", "tier1_spec_build_total_s",
            round(spec_build_total, 3), file=path.name, platform="cpu",
            ts=ts))
    attribution = sorted(tests.values(), key=lambda r: -r["total_s"])
    return records, attribution, []


# pytest `--durations` report lines: "0.52s call tests/foo.py::test_x"
_DURATION_LINE_RE = re.compile(
    r"^\s*([0-9.]+)s\s+(call|setup|teardown)\s+(\S+::\S+)\s*$")


def parse_durations(text: str) -> list[dict]:
    """pytest --durations output -> [{test, phase, dur_s}] rows (a
    second, coarser source for the tier-1 attribution table when no
    telemetry snapshot is available)."""
    rows = []
    for line in text.splitlines():
        m = _DURATION_LINE_RE.match(line)
        if m:
            rows.append({"test": m.group(3), "phase": m.group(2),
                         "dur_s": float(m.group(1))})
    return rows


# --- the store ---------------------------------------------------------------


def append_records(path, records) -> int:
    """Append records as JSON lines (creating parent dirs); returns the
    number written.  No dedup — use `sync_records` for idempotence."""
    path = Path(path)
    records = [r for r in records if not validate_record(r)]
    if not records:
        return 0
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "a") as f:
        for rec in records:
            f.write(_canonical_line(rec) + "\n")
    return len(records)


def load_history(path) -> tuple[list[dict], int, list[str]]:
    """(records, skipped_count, warnings).  Lines that are not valid
    JSON, not schema-`SCHEMA` records, or otherwise malformed are
    skipped and counted — an old or future store must degrade, not
    crash the reporter."""
    path = Path(path)
    records: list[dict] = []
    warnings: list[str] = []
    skipped = 0
    if not path.exists():
        return records, skipped, warnings
    try:
        text = path.read_text()
    except (OSError, UnicodeDecodeError) as e:
        return [], 1, [f"{path.name}: unreadable history "
                       f"({type(e).__name__}: {e})"]
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            skipped += 1
            warnings.append(f"{path.name}:{i}: malformed history line "
                            f"— skipped")
            continue
        if not isinstance(rec, dict) or rec.get("schema") != SCHEMA:
            skipped += 1
            warnings.append(
                f"{path.name}:{i}: unknown schema version "
                f"{rec.get('schema') if isinstance(rec, dict) else '?'!r} "
                f"(this reader is v{SCHEMA}) — skipped")
            continue
        problems = validate_record(rec)
        if problems:
            skipped += 1
            warnings.append(f"{path.name}:{i}: invalid record "
                            f"({problems[0]}) — skipped")
            continue
        records.append(rec)
    return records, skipped, warnings


def sync_records(path, records) -> int:
    """Append only records whose canonical line is not already in the
    store — re-running the reporter over the same checked-in rounds is
    a no-op on the second pass.  Returns the number appended."""
    existing, _, _ = load_history(path)
    seen = {_canonical_line(r) for r in existing}
    fresh = [r for r in records
             if not validate_record(r) and _canonical_line(r) not in seen]
    return append_records(path, fresh)


# --- live bench emissions ----------------------------------------------------


def emission_platform() -> str:
    """Best-effort platform stamp for a live bench emission: an explicit
    JAX_PLATFORMS pin (the CPU smoke path sets `cpu`) wins; otherwise
    the pooled TPU the benches default to."""
    return os.environ.get("JAX_PLATFORMS") or "tpu"


# live-emission costmodel dedupe: a bench process emits one metric line
# per config, but cost records are cumulative per-process facts — each
# later line carries (a superset of) the previous block, and the fresh
# `ts`/`via_metric` stamps would defeat the store's canonical-line
# dedupe.  Re-emit a kernel/watermark record only when its payload
# actually changed (more dispatches joined in, high-water moved).
_emitted_cost_payloads: dict[str, str] = {}


def emission_records(metric_line: dict, ts: float | None = None
                     ) -> list[dict]:
    """Normalize one live bench stdout line (a bench_bls metric record,
    or bench.py's flagship superset line with `extra`) into history
    records, stamped with the wall clock so distinct runs stay
    distinct."""
    records = []
    for name, obj in _merge_metric_lines([metric_line]).items():
        platform = obj.get("platform") or emission_platform()
        records.append(make_record(
            "bench_emit", name, obj.get("value"),
            unit=obj.get("unit", "s"),
            vs_baseline=obj.get("vs_baseline"),
            platform=platform,
            telemetry=_compact_telemetry(obj.get("telemetry")),
            detail=obj.get("detail"),
            msm_device_min=obj.get("msm_device_min"),
            error=obj.get("error"),
            ts=round(ts, 1) if ts is not None else None))
        for srec in serve_records(
                name, obj.get("serve"),
                chaos=isinstance(obj.get("resilience"), dict),
                platform=platform,
                ts=round(ts, 1) if ts is not None else None):
            records.append(srec)
        for rrec in resilience_records(
                name, obj.get("resilience"), platform=platform,
                ts=round(ts, 1) if ts is not None else None):
            records.append(rrec)
        for srec in scaling_records(
                name, obj.get("scaling"), platform=platform,
                ts=round(ts, 1) if ts is not None else None):
            records.append(srec)
        for drec in das_records(
                name, obj.get("das"), platform=platform,
                ts=round(ts, 1) if ts is not None else None):
            records.append(drec)
        for drec in das_producer_records(
                name, obj.get("das_producer"), platform=platform,
                ts=round(ts, 1) if ts is not None else None):
            records.append(drec)
        for frec in forkchoice_records(
                name, obj.get("forkchoice"), platform=platform,
                ts=round(ts, 1) if ts is not None else None):
            records.append(frec)
        for crec in costmodel_records(
                name, obj.get("telemetry"), platform=platform,
                ts=round(ts, 1) if ts is not None else None):
            payload = _canonical_line(
                {k: v for k, v in crec.items()
                 if k not in ("ts", "via_metric")})
            if _emitted_cost_payloads.get(crec["metric"]) == payload:
                continue
            _emitted_cost_payloads[crec["metric"]] = payload
            records.append(crec)
    return records


def append_emission(metric_line: dict, ts: float | None = None) -> int:
    """The bench-side hook: when CST_BENCHWATCH_HISTORY names a path,
    append this emission's normalized records there.  Disabled (the
    default) it is a single env read — the bench JSON contract on
    stdout is unchanged either way."""
    path = os.environ.get("CST_BENCHWATCH_HISTORY")
    if not path or not isinstance(metric_line, dict) \
            or "metric" not in metric_line:
        return 0
    try:
        return append_records(path, emission_records(metric_line, ts=ts))
    except OSError:
        return 0    # history is an observability side-channel, never fatal


# --- repo-wide ingest --------------------------------------------------------


def ingest_repo(root) -> tuple[list[dict], list[str]]:
    """Every record extractable from the checked-in perf artifacts under
    `root`: BENCH_r*.json, MULTICHIP_r*.json, and the two persisted
    oracle baselines."""
    root = Path(root)
    records: list[dict] = []
    warnings: list[str] = []
    for path in sorted(root.glob("BENCH_r*.json")):
        recs, warns = parse_bench_round(path)
        records.extend(recs)
        warnings.extend(warns)
    for path in sorted(root.glob("MULTICHIP_r*.json")):
        recs, warns = parse_multichip_round(path)
        records.extend(recs)
        warnings.extend(warns)
    for name in ("bench_baseline.json", "bench_bls_baseline.json"):
        path = root / name
        if path.exists():
            recs, warns = parse_baseline_file(path)
            records.extend(recs)
            warnings.extend(warns)
    return records, warnings
