"""Kernel cost model: XLA cost/memory analysis + roofline + watermarks.

Telemetry (core.py) answers *how long* each kernel takes; this layer
answers *why*.  Per compiled kernel it captures XLA's own static
analyses — `lowered.compile().cost_analysis()` (flops, bytes accessed,
transcendentals) and `memory_analysis()` (argument / output / temp /
generated-code bytes) — joins them with the measured `run_s` from the
compile-vs-run split, and derives the roofline numbers the ROADMAP's
open perf questions need: achieved FLOP/s, achieved bytes/s, arithmetic
intensity, and a compute- / memory- / launch-bound classification
against a small per-backend peak registry (TPU peaks read from
`BASELINE.json`'s `"peaks"` section; CPU peaks are built-in and marked
advisory).  It also samples per-device live-buffer bytes at span
boundaries (device-memory watermarks, high-water mark kept per device).

Gating contract, strictly additive to core.py's: everything here is OFF
unless BOTH the telemetry registry is collecting AND `CST_COSTMODEL` is
set to a non-empty value other than "0" (cost capture without the run_s
histograms to join against would be numbers with no denominator).  The
disabled paths are a single flag check — `capture()` and
`sample_watermark()` return before touching their arguments, so the hot
path instruments unconditionally, exactly like `telemetry.span`.

Capture cost: `capture()` runs once per kernel key per process.  The
AOT `lower().compile()` pass usually lands in the jit/XLA compile cache
the kernel's real dispatch already populated; the one timed re-run that
gives every cost record a steady-state wall sample is a real extra
kernel execution — acceptable for an explicitly-enabled cost round,
never paid otherwise.

Zero dependencies: stdlib only at import time.  jax is never imported
here — `capture()` only uses the jit object it is handed, and
`sample_watermark()` reads jax out of `sys.modules` (a telemetry layer
must not initialize a backend; same rule as core.py).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from pathlib import Path

from . import core

# watermark trace-event buffer cap (counter events are ~80 bytes each);
# drops are counted, never silent — mirrors core._MAX_EVENTS
_MAX_WM_EVENTS = 50_000

# a kernel whose roofline-predicted time (max of compute / memory legs)
# covers less than this fraction of its measured wall is dominated by
# dispatch overhead, not by the work XLA counted: launch-bound
LAUNCH_BOUND_FRAC = 0.05

# built-in per-backend peaks; `BASELINE.json`'s "peaks" section
# overrides per key (the README documents provenance and how to correct
# them per TPU generation).  CPU entries are advisory: a portable CI
# host has no single honest peak, so its utilization numbers rank
# kernels against each other rather than against the hardware.
_DEFAULT_PEAKS = {
    "tpu": {"flops_per_s": 1.97e14, "bytes_per_s": 8.19e11,
            "advisory": False,
            "note": "TPU v5e published bf16 peak / HBM bandwidth"},
    "cpu": {"flops_per_s": 5.0e10, "bytes_per_s": 2.0e10,
            "advisory": True,
            "note": "generic CI-host estimate — advisory only"},
}

_lock = threading.Lock()

_costs: dict[str, dict] = {}          # kernel key -> raw capture record
_watermarks: dict[str, dict] = {}     # device -> last/high-water/samples
_wm_events: list[dict] = []           # chrome-trace counter samples
_wm_events_dropped = 0
_peaks_cache: dict | None = None


def _env_enabled() -> bool:
    return os.environ.get("CST_COSTMODEL", "0") not in ("", "0")


_env_on = _env_enabled()
_override: bool | None = None


def enabled() -> bool:
    """True when cost capture is armed: the telemetry registry is
    collecting AND CST_COSTMODEL is set (or `configure(enabled=True)`
    overrode the env gate)."""
    if not core.enabled():
        return False
    return _env_on if _override is None else _override


def configure(enabled: bool | None = None) -> None:
    """Programmatic override of the CST_COSTMODEL env gate (tests and
    benches); the telemetry-registry gate still applies on top."""
    global _override
    _override = enabled


def _reset_state() -> None:
    """Full wipe — called by `core.reset(full=True)` so test isolation
    clears cost records and watermarks along with the first-call keys
    they attribute against.  Per-config `core.reset()` does NOT clear
    this registry: a kernel's cost is a per-process fact (like the
    compile attribution keys), owed to every config's export."""
    global _wm_events_dropped, _peaks_cache
    with _lock:
        _costs.clear()
        _watermarks.clear()
        _wm_events.clear()
        _wm_events_dropped = 0
        _peaks_cache = None


# --- peak registry -----------------------------------------------------------


def _baseline_path() -> Path:
    return Path(__file__).resolve().parents[2] / "BASELINE.json"


def peaks() -> dict:
    """The per-backend peak registry: built-in defaults overlaid with
    `BASELINE.json`'s `"peaks"` section (per backend, per key).  A
    missing or malformed file degrades to the defaults — the cost model
    must never crash the path it observes."""
    global _peaks_cache
    with _lock:
        if _peaks_cache is not None:
            return _peaks_cache
    merged = {k: dict(v) for k, v in _DEFAULT_PEAKS.items()}
    try:
        data = json.loads(_baseline_path().read_text())
        overlay = data.get("peaks")
        if isinstance(overlay, dict):
            for backend, entry in overlay.items():
                if not isinstance(entry, dict):
                    continue
                merged.setdefault(str(backend), {}).update(entry)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        pass
    with _lock:
        _peaks_cache = merged
    return merged


def peaks_for(platform: str | None) -> dict | None:
    """Peak entry for a jax platform name ('tpu', 'cpu', 'tpu v5', ...);
    None when the registry has nothing applicable."""
    if not platform:
        return None
    reg = peaks()
    p = str(platform).lower()
    for backend in sorted(reg, key=len, reverse=True):
        if p.startswith(backend):
            entry = dict(reg[backend])
            entry["backend"] = backend
            return entry
    return None


# --- classification ----------------------------------------------------------


def classify(flops: float, bytes_accessed: float, run_s: float | None,
             peak: dict | None) -> dict:
    """Roofline-derive one kernel's utilization numbers.

    Returns {arithmetic_intensity, achieved_flops_per_s,
    achieved_bytes_per_s, util_flops_pct, util_bw_pct, bound}; `bound`
    is "compute" | "memory" | "launch" | "unknown".  The classification
    compares the two roofline legs (flops/peak_flops vs
    bytes/peak_bandwidth): whichever leg is longer binds — unless both
    together explain under LAUNCH_BOUND_FRAC of the measured wall, in
    which case dispatch overhead dominates and the kernel is
    launch-bound (the `_MSM_DEVICE_MIN` small-n regime)."""
    out: dict = {
        "arithmetic_intensity":
            round(flops / bytes_accessed, 4) if bytes_accessed else None,
        "achieved_flops_per_s": None,
        "achieved_bytes_per_s": None,
        "util_flops_pct": None,
        "util_bw_pct": None,
        "bound": "unknown",
    }
    if run_s and run_s > 0:
        out["achieved_flops_per_s"] = round(flops / run_s, 1)
        out["achieved_bytes_per_s"] = round(bytes_accessed / run_s, 1)
    if peak is None or not run_s or run_s <= 0:
        return out
    t_compute = flops / peak["flops_per_s"] if peak.get("flops_per_s") \
        else 0.0
    t_memory = bytes_accessed / peak["bytes_per_s"] \
        if peak.get("bytes_per_s") else 0.0
    out["util_flops_pct"] = round(t_compute / run_s * 100.0, 2)
    out["util_bw_pct"] = round(t_memory / run_s * 100.0, 2)
    if max(t_compute, t_memory) < LAUNCH_BOUND_FRAC * run_s:
        out["bound"] = "launch"
    elif t_compute >= t_memory:
        out["bound"] = "compute"
    else:
        out["bound"] = "memory"
    return out


# --- capture -----------------------------------------------------------------


def _normalize_cost(ca) -> dict:
    """`compiled.cost_analysis()` is a dict on new jax, a list of dicts
    (one per computation) on 0.4.x — normalize to one flat dict."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca if isinstance(ca, dict) else {}


def _memory_dict(compiled) -> dict | None:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for key in ("argument_size_in_bytes", "output_size_in_bytes",
                "temp_size_in_bytes", "alias_size_in_bytes",
                "generated_code_size_in_bytes"):
        v = getattr(ma, key, None)
        if isinstance(v, int):
            out[key] = v
    return out or None


def capture(kernel: str, fn, args, kwargs=None) -> dict | None:
    """AOT cost/memory analysis for one jitted kernel, once per kernel
    key per process.  `fn` is the jit-wrapped callable the seam just
    dispatched (its jit cache is warm, so the timed re-run below is a
    steady-state sample); `args` are the exact call arguments.

    Never raises: a backend that cannot lower/analyze (mesh-sharded
    executables, exotic platforms) stores an error record and bumps the
    `costmodel.capture_errors` counter instead — the kernel stays
    visible, with the reason attached.  Disabled mode is a flag check
    returning None."""
    if not enabled():
        return None
    with _lock:
        if kernel in _costs:
            return _costs[kernel]
    t_cap = time.perf_counter()
    rec: dict = {"kernel": kernel,
                 "ts_rel_us": round((t_cap - core._T0) * 1e6, 1)}
    try:
        jax = sys.modules.get("jax")
        lowered = fn.lower(*args, **(kwargs or {}))
        compiled = lowered.compile()
        ca = _normalize_cost(compiled.cost_analysis())
        rec["flops"] = float(ca.get("flops", 0.0))
        rec["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
        rec["transcendentals"] = float(ca.get("transcendentals", 0.0))
        mem = _memory_dict(compiled)
        if mem:
            rec["memory"] = mem
        if jax is not None:
            rec["platform"] = jax.devices()[0].platform
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args, **(kwargs or {})))
            rec["run_s_probe"] = round(time.perf_counter() - t0, 6)
    except Exception as e:
        rec["error"] = f"{type(e).__name__}: {e}"[:300]
        core.count("costmodel.capture_errors")
    with _lock:
        _costs.setdefault(kernel, rec)
    core.count("costmodel.captured")
    return rec


def record_cost(kernel: str, flops: float, bytes_accessed: float,
                transcendentals: float = 0.0, platform: str = "cpu",
                run_s_probe: float | None = None,
                memory: dict | None = None) -> None:
    """Direct cost-record injection (tests and synthetic report rounds);
    same gating and once-per-key semantics as `capture`."""
    if not enabled():
        return
    rec = {"kernel": kernel, "flops": float(flops),
           "bytes_accessed": float(bytes_accessed),
           "transcendentals": float(transcendentals),
           "platform": platform,
           "ts_rel_us": round((time.perf_counter() - core._T0) * 1e6, 1)}
    if run_s_probe is not None:
        rec["run_s_probe"] = float(run_s_probe)
    if memory:
        rec["memory"] = dict(memory)
    with _lock:
        _costs.setdefault(kernel, rec)


# --- device-memory watermarks ------------------------------------------------


def _device_live_bytes(jax) -> dict[str, int]:
    """Per-device live-buffer bytes.  `memory_stats()` (TPU: allocator
    truth incl. fragmentation) wins; backends without it (XLA:CPU) fall
    back to summing `jax.live_arrays()` per committed device — a sharded
    array counts fully on each of its devices."""
    out: dict[str, int] = {}
    try:
        devices = jax.devices()
    except Exception:
        return out
    stats_seen = False
    for d in devices:
        label = f"{d.platform}:{d.id}"
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if isinstance(stats, dict) and "bytes_in_use" in stats:
            out[label] = int(stats["bytes_in_use"])
            stats_seen = True
    if stats_seen:
        return out
    # live-array fallback: seed every device at 0 so a sample taken
    # while nothing is resident still records (an idle device IS at
    # zero live bytes — dropping the sample would hide exactly the
    # moments the watermark timeline needs between kernel bursts)
    for d in devices:
        out[f"{d.platform}:{d.id}"] = 0
    try:
        for a in jax.live_arrays():
            for d in a.devices():
                label = f"{d.platform}:{d.id}"
                out[label] = out.get(label, 0) + int(a.nbytes)
    except Exception:
        pass
    return out


def sample_watermark(tag: str = "") -> dict[str, int]:
    """Sample per-device live-buffer bytes NOW, update the per-device
    high-water mark, and buffer a Chrome-trace counter sample.  Called
    at span boundaries (executor phases, kernel dispatch); a no-op flag
    check while disabled or before jax ever imported."""
    if not enabled():
        return {}
    jax = sys.modules.get("jax")
    if jax is None:
        return {}
    sample = _device_live_bytes(jax)
    if not sample:
        return {}
    ts_rel_us = round((time.perf_counter() - core._T0) * 1e6, 1)
    global _wm_events_dropped
    with _lock:
        for dev, nbytes in sample.items():
            wm = _watermarks.get(dev)
            if wm is None:
                _watermarks[dev] = {"last_bytes": nbytes,
                                    "high_water_bytes": nbytes,
                                    "samples": 1}
            else:
                wm["last_bytes"] = nbytes
                if nbytes > wm["high_water_bytes"]:
                    wm["high_water_bytes"] = nbytes
                wm["samples"] += 1
        if len(_wm_events) < _MAX_WM_EVENTS:
            _wm_events.append({"ts": ts_rel_us, "tag": tag,
                               "bytes": dict(sample)})
        else:
            _wm_events_dropped += 1
    return sample


# --- snapshot / join ---------------------------------------------------------


def watermark_bytes() -> dict[str, int]:
    """`{device: last_bytes}` — the cheap point read behind the SLO
    watchdog's memory-slope signal and the exposition endpoint's
    per-device gauges (no kernel-record copy, unlike `raw_snapshot`)."""
    with _lock:
        return {dev: wm["last_bytes"] for dev, wm in _watermarks.items()}


def raw_snapshot() -> dict:
    """The captured state as-is (no derived metrics): what
    `telemetry.snapshot()["costmodel"]` carries.  Schema:

        {"kernels":    {key: raw capture record},
         "watermarks": {device: {"last_bytes", "high_water_bytes",
                                 "samples"}},
         "wm_events": int, "wm_events_dropped": int}
    """
    with _lock:
        return {
            "kernels": {k: dict(v) for k, v in _costs.items()},
            "watermarks": {k: dict(v) for k, v in _watermarks.items()},
            "wm_events": len(_wm_events),
            "wm_events_dropped": _wm_events_dropped,
        }


def _wm_events_copy() -> tuple[list[dict], int]:
    with _lock:
        return ([dict(e) for e in _wm_events], _wm_events_dropped)


def _cost_events_copy() -> list[dict]:
    with _lock:
        return [dict(v) for v in _costs.values()]


def join_record(raw: dict, hists: dict) -> dict:
    """One kernel's raw capture record joined with the measured run_s
    from the telemetry compile-vs-run split and classified against the
    peak registry.  `hists` is `snapshot()["histograms"]`; the
    per-kernel `kernel.<key>.run_s` mean (real steady-state iterations)
    outranks the capture-time probe sample."""
    rec = dict(raw)
    if "error" in rec:
        return rec
    key = rec.get("kernel", "")
    run_hist = hists.get(f"kernel.{key}.run_s")
    if isinstance(run_hist, dict) and run_hist.get("count"):
        rec["run_s_mean"] = round(
            run_hist["total"] / run_hist["count"], 6)
        rec["run_source"] = "dispatch"
    elif rec.get("run_s_probe") is not None:
        rec["run_s_mean"] = rec["run_s_probe"]
        rec["run_source"] = "probe"
    else:
        rec["run_s_mean"] = None
        rec["run_source"] = "none"
    comp_hist = hists.get(f"kernel.{key}.compile_first_s")
    if isinstance(comp_hist, dict) and comp_hist.get("count"):
        rec["compile_first_s"] = round(comp_hist["total"], 4)
    peak = peaks_for(rec.get("platform"))
    rec.update(classify(rec.get("flops", 0.0),
                        rec.get("bytes_accessed", 0.0),
                        rec["run_s_mean"], peak))
    if peak is not None:
        rec["peak_source"] = peak["backend"] + (
            " (advisory)" if peak.get("advisory") else "")
    return rec


def block(hists: dict | None = None) -> dict | None:
    """The `"costmodel"` sub-object for the bench `"telemetry"` block:
    every captured kernel joined + classified, plus the watermark
    summary and the peak registry actually used.  None while disabled
    (the bench contract omits the key)."""
    if not enabled():
        return None
    if hists is None:
        hists = core.snapshot()["histograms"]
    raw = raw_snapshot()
    return {
        "kernels": {k: join_record(v, hists)
                    for k, v in raw["kernels"].items()},
        "watermarks": raw["watermarks"],
        "peaks": peaks(),
    }
