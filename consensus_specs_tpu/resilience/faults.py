"""Deterministic fault injection at the sanctioned device-path seams.

A fault plan is a seeded, schema-validated description of WHICH faults
fire WHERE: every injection is driven by one `random.Random(seed)` and
per-rule counters, so a chaos round replays bit-for-bit and a test can
assert the exact blast radius (every fired fault is logged with its
injection site and key).

Sites (`SITES`) — the four seams the hooks live at:

    dispatch        `ops.bls_batch._dispatch` (key = kernel name, e.g.
                    `rlc_h2c@8`), the mesh-sharded entry point
                    `batch_verify_sharded_async` (key =
                    `rlc_sharded@<devices>x<per_shard>` — the
                    `device_loss` chaos target `resilience.mesh`
                    recovers from), `ops.sha256_jax` (key =
                    `sha256_merkle@d<depth>`), and the fork-choice
                    store's kernels (keys `fc_weights@b<B>v<V>` /
                    `fc_head@<NB>` — the serve `head` lane's
                    breaker→spec-oracle chaos target) — the
                    jitted-kernel dispatch boundary
    future_settle   `serve.futures.DeviceFuture` device-backed settle
                    (key = "device") — the device→host transfer
    serve_pump      `ServeExecutor._dispatch_one` (key = request kind:
                    verify/pairing/msm/sha256/fr/proof) — the serving
                    batch boundary
    merkle_update   `parallel.incremental.update_dirty` (key =
                    `u<rung>d<depth>`) — the persistent-layer re-hash

Kinds (`KINDS`):

    raise           raise `FaultInjected` at the seam (a dispatch/prep
                    exception)
    latency         sleep `latency_ms` at the seam (slow device /
                    saturated interconnect)
    compile_fail    raise on the FIRST sighting of each matching key
                    (a kernel whose XLA compile dies); later calls of
                    the same key pass — the "first call per shape"
                    failure mode
    corrupt         corrupt the seam's output value (bit-flip the low
                    bit of integer/bool lanes, NaN float lanes; tuples
                    corrupt their LAST element — the root layer of a
                    Merkle update, the Z limb of a point)
    device_loss     raise `MeshDeviceLost` (a mesh device dropping out
                    mid-round)

Plan forms accepted by `load_plan` / the `CST_FAULTS` env knob:

    a JSON object   {"seed": 7, "faults": [{"site": "dispatch",
                     "kind": "raise", "key": "rlc_*", "count": 3}]}
    a file path     containing that JSON
    a spec string   "seed=7;dispatch:raise:key=rlc_*:count=3;
                     serve_pump:latency:latency_ms=20:p=0.5"

Rule fields: `key` (fnmatch glob over the seam key, default "*"), `p`
(fire probability, seeded — default 1.0), `count` (max fires, default
unlimited), `after` (skip the first N matching events), `latency_ms`,
`mode` ("bitflip" | "nan" for corrupt).

Gating contract (the telemetry pattern): everything is OFF until a plan
is `install()`ed, `active()` is one module-global read, and every hook
guards with it — the disabled hot path stays provably free of fault
machinery (no-op bound test in tests/test_resilience.py).  Stdlib-only
at import; numpy is imported lazily inside `corrupt` (which only runs
with a plan installed and jax already live).
"""

from __future__ import annotations

import fnmatch
import json
import os
import random
import threading
import time

from .. import telemetry
from ..telemetry import flightrec

SITES = ("dispatch", "future_settle", "serve_pump", "merkle_update")
KINDS = ("raise", "latency", "compile_fail", "corrupt", "device_loss")
MODES = ("bitflip", "nan")

_lock = threading.Lock()


class FaultInjected(RuntimeError):
    """A fault fired at a sanctioned seam.  Carries the injection site,
    the seam key, and the fault kind so tests (and the serve executor's
    failure accounting) can assert exact blast radius."""

    def __init__(self, site: str, key: str, kind: str):
        super().__init__(f"injected {kind} fault at {site}:{key}")
        self.site = site
        self.key = key
        self.kind = kind


class MeshDeviceLost(FaultInjected):
    """A `device_loss` fault — models a mesh device dropping out (the
    failure XLA surfaces as a dead-executable error mid-round)."""


class _Rule:
    __slots__ = ("site", "kind", "key", "p", "count", "after",
                 "latency_ms", "mode", "fired", "seen", "hit_keys")

    def __init__(self, site, kind, key="*", p=1.0, count=None, after=0,
                 latency_ms=0.0, mode=None):
        self.site = site
        self.kind = kind
        self.key = key
        self.p = float(p)
        self.count = count
        self.after = int(after)
        self.latency_ms = float(latency_ms)
        self.mode = mode
        self.fired = 0
        self.seen = 0
        self.hit_keys: set[str] = set()   # compile_fail: first-per-key

    def describe(self) -> dict:
        out = {"site": self.site, "kind": self.kind, "key": self.key}
        if self.p < 1.0:
            out["p"] = self.p
        if self.count is not None:
            out["count"] = self.count
        if self.after:
            out["after"] = self.after
        if self.latency_ms:
            out["latency_ms"] = self.latency_ms
        if self.mode:
            out["mode"] = self.mode
        return out


class FaultPlan:
    """A validated set of fault rules plus the seeded RNG and the
    injection log.  Build via `load_plan`; activate via `install`."""

    def __init__(self, rules: list[_Rule], seed: int = 0):
        self.rules = list(rules)
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.log: list[dict] = []

    def describe(self) -> dict:
        """Compact JSON-able summary (rides the resilience bench block)."""
        return {"seed": self.seed,
                "faults": [r.describe() for r in self.rules]}

    def _take(self, site: str, key: str, kinds: tuple) -> list[_Rule]:
        """Consume one seam event: advance matching rules' counters and
        return the ones that fire (deterministic given the seed and the
        event order)."""
        fired = []
        with _lock:
            for rule in self.rules:
                if rule.kind not in kinds or rule.site != site:
                    continue
                if not fnmatch.fnmatchcase(key, rule.key):
                    continue
                rule.seen += 1
                if rule.seen <= rule.after:
                    continue
                if rule.count is not None and rule.fired >= rule.count:
                    continue
                if rule.kind == "compile_fail":
                    if key in rule.hit_keys:
                        continue
                    rule.hit_keys.add(key)
                if rule.p < 1.0 and self.rng.random() >= rule.p:
                    continue
                rule.fired += 1
                self.log.append({"site": site, "key": key,
                                 "kind": rule.kind})
                fired.append(rule)
        return fired


def validate_plan(obj) -> list[str]:
    """Schema check for a fault-plan JSON object; returns a list of
    problems (empty == valid) — the contract `load_plan` enforces and
    tests/test_resilience.py pins."""
    problems: list[str] = []
    if not isinstance(obj, dict):
        return [f"fault plan is {type(obj).__name__}, not dict"]
    seed = obj.get("seed", 0)
    if not isinstance(seed, int) or isinstance(seed, bool):
        problems.append(f"'seed' must be an int, got {seed!r}")
    faults = obj.get("faults")
    if not isinstance(faults, list) or not faults:
        return problems + ["'faults' must be a non-empty list"]
    for i, f in enumerate(faults):
        where = f"faults[{i}]"
        if not isinstance(f, dict):
            problems.append(f"{where}: not a dict")
            continue
        if f.get("site") not in SITES:
            problems.append(f"{where}: 'site' must be one of {SITES}, "
                            f"got {f.get('site')!r}")
        if f.get("kind") not in KINDS:
            problems.append(f"{where}: 'kind' must be one of {KINDS}, "
                            f"got {f.get('kind')!r}")
        key = f.get("key", "*")
        if not isinstance(key, str) or not key:
            problems.append(f"{where}: 'key' must be a non-empty glob "
                            f"string, got {key!r}")
        p = f.get("p", 1.0)
        if not isinstance(p, (int, float)) or isinstance(p, bool) \
                or not (0.0 < p <= 1.0):
            problems.append(f"{where}: 'p' must be in (0, 1], got {p!r}")
        count = f.get("count")
        if count is not None and (not isinstance(count, int)
                                  or isinstance(count, bool) or count < 1):
            problems.append(f"{where}: 'count' must be a positive int "
                            f"or absent, got {count!r}")
        after = f.get("after", 0)
        if not isinstance(after, int) or isinstance(after, bool) \
                or after < 0:
            problems.append(f"{where}: 'after' must be a non-negative "
                            f"int, got {after!r}")
        lat = f.get("latency_ms", 0.0)
        if not isinstance(lat, (int, float)) or isinstance(lat, bool) \
                or lat < 0:
            problems.append(f"{where}: 'latency_ms' must be a "
                            f"non-negative number, got {lat!r}")
        if f.get("kind") == "latency" and not lat:
            problems.append(f"{where}: a 'latency' fault needs a "
                            f"positive 'latency_ms'")
        mode = f.get("mode")
        if mode is not None and mode not in MODES:
            problems.append(f"{where}: 'mode' must be one of {MODES} "
                            f"or absent, got {mode!r}")
        unknown = set(f) - {"site", "kind", "key", "p", "count", "after",
                            "latency_ms", "mode"}
        if unknown:
            problems.append(f"{where}: unknown field(s) "
                            f"{sorted(unknown)}")
    return problems


def _parse_spec(text: str) -> dict:
    """Compact spec string -> plan dict (see module docstring)."""
    plan: dict = {"faults": []}
    for seg in text.split(";"):
        seg = seg.strip()
        if not seg:
            continue
        if seg.startswith("seed="):
            try:
                plan["seed"] = int(seg[len("seed="):])
            except ValueError:
                raise ValueError(f"fault spec: bad seed segment {seg!r}")
            continue
        parts = seg.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"fault spec segment {seg!r} needs at least site:kind")
        fault: dict = {"site": parts[0], "kind": parts[1]}
        for opt in parts[2:]:
            k, eq, v = opt.partition("=")
            if not eq:
                raise ValueError(f"fault spec option {opt!r} is not k=v")
            if k in ("key", "mode"):
                fault[k] = v
            elif k in ("count", "after"):
                try:
                    fault[k] = int(v)
                except ValueError:
                    raise ValueError(f"fault spec: {k}={v!r} not an int")
            elif k in ("p", "latency_ms"):
                try:
                    fault[k] = float(v)
                except ValueError:
                    raise ValueError(f"fault spec: {k}={v!r} not a number")
            else:
                raise ValueError(f"fault spec: unknown option {k!r}")
        plan["faults"].append(fault)
    return plan


def load_plan(source) -> FaultPlan:
    """Build a validated `FaultPlan` from a dict, a JSON string, a JSON
    file path, or a compact spec string.  Raises ValueError (with every
    schema problem listed) on an invalid plan — a chaos round must not
    half-run a typo'd plan."""
    if isinstance(source, FaultPlan):
        return source
    if isinstance(source, dict):
        obj = source
    elif isinstance(source, str):
        text = source.strip()
        if text.startswith("{"):
            obj = json.loads(text)
        elif os.path.exists(text):
            with open(text) as f:
                obj = json.load(f)
        else:
            obj = _parse_spec(text)
    else:
        raise ValueError(f"cannot load a fault plan from "
                         f"{type(source).__name__}")
    problems = validate_plan(obj)
    if problems:
        raise ValueError("invalid fault plan: " + "; ".join(problems))
    rules = [_Rule(**f) for f in obj["faults"]]
    return FaultPlan(rules, seed=obj.get("seed", 0))


# --- the gate (the telemetry `enabled()` pattern) ----------------------------

_plan: FaultPlan | None = None


def active() -> bool:
    """True while a fault plan is installed.  The ONE check every seam
    hook guards with — disabled mode is this module-global read."""
    return _plan is not None


def current() -> FaultPlan | None:
    return _plan


def install(plan) -> FaultPlan:
    """Activate a fault plan (any `load_plan` source form)."""
    global _plan
    plan = load_plan(plan)
    _plan = plan
    return plan


def clear() -> None:
    """Deactivate fault injection (the recovery phase of a chaos round)."""
    global _plan
    _plan = None


def plan_from_env_source() -> str | None:
    """The raw CST_FAULTS plan source (not yet loaded), or None when
    the knob is unset — the chaos harness's plan-precedence read."""
    return os.environ.get("CST_FAULTS") or None


def install_from_env() -> bool:
    """Install the `CST_FAULTS` plan when the knob is set; returns
    whether injection is now active.  Call sites: bench_serve / the
    chaos harness — never at import."""
    source = os.environ.get("CST_FAULTS")
    if not source:
        return active()
    install(source)
    return True


def injections() -> list[dict]:
    """The fired-fault log (site/key/kind per injection) — the blast-
    radius assertion surface."""
    return list(_plan.log) if _plan is not None else []


# --- the seam hooks ----------------------------------------------------------


def maybe_inject(site: str, key: str = "") -> None:
    """The raise/latency/compile_fail/device_loss seam hook.  No-op
    without a plan; with one, consumes a (site, key) event and applies
    every firing rule — latency sleeps, the raising kinds raise (tagged
    with site/key/kind)."""
    plan = _plan
    if plan is None:
        return
    for rule in plan._take(site, key, ("raise", "latency",
                                       "compile_fail", "device_loss")):
        telemetry.count(f"faults.injected.{site}")
        flightrec.record("fault_injected", site=site, key=key,
                         fault=rule.kind)
        if rule.kind == "latency":
            time.sleep(rule.latency_ms / 1e3)
        elif rule.kind == "device_loss":
            raise MeshDeviceLost(site, key, rule.kind)
        else:
            raise FaultInjected(site, key, rule.kind)


def corrupt(site: str, key: str, value):
    """The corrupted-output seam hook: returns `value`, possibly with a
    firing corrupt rule applied (bit-flip integer/bool lanes, NaN float
    lanes; tuples/lists corrupt their last element).  Device arrays stay
    on device — the corruption is expressed through the array's own
    operators, so a jnp value corrupts via one extra fused op."""
    plan = _plan
    if plan is None:
        return value
    for rule in plan._take(site, key, ("corrupt",)):
        telemetry.count(f"faults.injected.{site}")
        flightrec.record("fault_injected", site=site, key=key,
                         fault="corrupt", mode=rule.mode)
        value = _corrupt_value(value, rule.mode)
    return value


def _corrupt_value(value, mode):
    if isinstance(value, (tuple, list)):
        if not value:
            return value
        head, last = list(value[:-1]), _corrupt_value(value[-1], mode)
        return type(value)(head + [last]) if isinstance(value, list) \
            else tuple(head) + (last,)
    import numpy as np

    dt = getattr(value, "dtype", None)
    if dt is None:
        if isinstance(value, bool):
            return not value
        if isinstance(value, int):
            return value ^ 1
        if isinstance(value, float):
            return float("nan")
        return value
    if np.issubdtype(dt, np.bool_):
        return ~value
    if np.issubdtype(dt, np.floating):
        return value * float("nan")
    if np.issubdtype(dt, np.integer):
        if mode == "nan":
            # integer lanes have no NaN — bit-flip is the only honest
            # corruption there
            pass
        return value ^ np.asarray(1, dtype=dt)
    return value
