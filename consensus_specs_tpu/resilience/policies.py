"""Recovery policies: retry, circuit breaker, deadline types.

The serve executor consumes these (opt-in — a plain `ServeExecutor`
keeps PR 6's fail-fast poisoning semantics):

- `RetryPolicy`: per-batch retry with capped exponential backoff.  A
  failed device batch re-dispatches up to `max_attempts` times before
  the failure is final; backoff is deterministic (no jitter — chaos
  rounds must replay).
- `CircuitBreaker` / `BreakerRegistry`: per-(kind, rung) breaker.
  `threshold` consecutive failures trip CLOSED→OPEN; while OPEN the
  executor routes matching batches to the pure-Python oracle fallback
  (correct-but-slow degraded mode) instead of the device.  After
  `cooldown_s` the next `allow()` transitions OPEN→HALF_OPEN and admits
  exactly ONE device probe; the probe's outcome re-closes
  (HALF_OPEN→CLOSED) or re-trips (HALF_OPEN→OPEN).  Every transition is
  logged (the `resilience` record's breaker-transition surface) and
  counted in telemetry.
- `DeadlineExceeded`: the typed error a shed request settles with when
  it ages past the executor's per-request deadline
  (`CST_SERVE_DEADLINE_MS`) — the queue fails its oldest entries
  instead of growing unboundedly.

Stdlib-only (+ telemetry): importable from the executor without pulling
numpy/jax.
"""

from __future__ import annotations

import time

from .. import telemetry
from ..telemetry import flightrec

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class DeadlineExceeded(RuntimeError):
    """A request aged past the executor's per-request deadline and was
    shed before dispatch.  Typed so callers can tell load shedding from
    a device failure.  On traced rounds (CST_TRACE_REQUESTS) the error
    carries the shed request's `trace_id`, so a caller holding the
    exception can find its lifecycle record in the reqtrace registry."""

    def __init__(self, kind: str, age_s: float, deadline_s: float,
                 trace_id: int | None = None):
        super().__init__(
            f"{kind} request shed: queued {age_s:.3f}s, deadline "
            f"{deadline_s:.3f}s"
            + (f" (trace {trace_id})" if trace_id is not None else ""))
        self.kind = kind
        self.age_s = age_s
        self.deadline_s = deadline_s
        self.trace_id = trace_id


class RetryPolicy:
    """Capped exponential backoff: attempt k (1-based) that fails waits
    `min(max_backoff_s, base_backoff_s * 2**(k-1))` before re-dispatch,
    up to `max_attempts` total attempts."""

    __slots__ = ("max_attempts", "base_backoff_s", "max_backoff_s")

    def __init__(self, max_attempts: int = 3, base_backoff_s: float = 0.05,
                 max_backoff_s: float = 1.0):
        assert max_attempts >= 1 and base_backoff_s >= 0
        self.max_attempts = int(max_attempts)
        self.base_backoff_s = float(base_backoff_s)
        self.max_backoff_s = float(max_backoff_s)

    def should_retry(self, attempt: int) -> bool:
        return attempt < self.max_attempts

    def backoff_s(self, attempt: int) -> float:
        return min(self.max_backoff_s,
                   self.base_backoff_s * (2 ** (attempt - 1)))


class CircuitBreaker:
    """One key's breaker; see the module docstring for the state
    machine.  `clock` is injectable so tests drive the cooldown without
    sleeping."""

    __slots__ = ("key", "threshold", "cooldown_s", "_clock", "_state",
                 "_failures", "_opened_at", "_probe_inflight",
                 "_on_transition", "trips")

    def __init__(self, key: str, threshold: int = 3,
                 cooldown_s: float = 1.0, clock=time.monotonic,
                 on_transition=None):
        assert threshold >= 1 and cooldown_s >= 0
        self.key = key
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._on_transition = on_transition
        self.trips = 0

    @property
    def state(self) -> str:
        return self._state

    def _transition(self, to: str) -> None:
        frm, self._state = self._state, to
        if to == OPEN:
            self.trips += 1
            self._opened_at = self._clock()
            self._probe_inflight = False
        telemetry.count(f"resilience.breaker.{to}")
        flightrec.record("breaker_transition", key=self.key,
                         frm=frm, to=to)
        if self._on_transition is not None:
            self._on_transition({"key": self.key, "from": frm, "to": to,
                                 "t": self._clock()})

    def allow(self) -> bool:
        """May the next batch for this key go to the DEVICE?  False
        means degrade (oracle fallback).  OPEN past its cooldown admits
        exactly one half-open probe."""
        if self._state is CLOSED:
            return True
        if self._state is OPEN:
            if self._clock() - self._opened_at >= self.cooldown_s:
                self._transition(HALF_OPEN)
                self._probe_inflight = True
                return True
            return False
        # HALF_OPEN: one probe at a time
        if not self._probe_inflight:
            self._probe_inflight = True
            return True
        return False

    def record_success(self) -> None:
        self._failures = 0
        if self._state is not CLOSED:
            self._transition(CLOSED)
        self._probe_inflight = False

    def record_failure(self) -> None:
        self._failures += 1
        if self._state is HALF_OPEN:
            self._transition(OPEN)
        elif self._state is CLOSED and self._failures >= self.threshold:
            self._transition(OPEN)


class BreakerRegistry:
    """Per-key breakers sharing one config and one transition log (the
    `resilience` record's `breaker` block)."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 1.0,
                 clock=time.monotonic):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._breakers: dict[str, CircuitBreaker] = {}
        self.transitions: list[dict] = []

    def get(self, key: str) -> CircuitBreaker:
        br = self._breakers.get(key)
        if br is None:
            br = self._breakers[key] = CircuitBreaker(
                key, threshold=self.threshold, cooldown_s=self.cooldown_s,
                clock=self._clock, on_transition=self.transitions.append)
        return br

    def states(self) -> dict[str, str]:
        return {k: b.state for k, b in sorted(self._breakers.items())}

    def trips(self) -> int:
        return sum(b.trips for b in self._breakers.values())

    def summary(self) -> dict:
        """JSON-able block for the bench `resilience` sub-object."""
        return {
            "states": self.states(),
            "trips": self.trips(),
            "transitions": [
                {"key": t["key"], "from": t["from"], "to": t["to"]}
                for t in self.transitions],
        }
