"""Chaos rounds: the mainnet arrival mix under an active fault plan.

`run_chaos_load` (armed by `CST_SERVE_CHAOS=1` in `bench_serve.py`)
drives the serve executor through three phases and requires the service
to come back:

    baseline    closed-loop windows until throughput is steady — the
                healthy-rate reference.
    chaos       the fault plan (CST_FAULTS, or the canned
                `DEFAULT_CHAOS_SPEC` injecting dispatch failures into
                the RLC kernel) is installed; the executor runs with
                the recovery policies armed (retry + per-(kind, rung)
                breakers + oracle fallback), so every request still
                answers CORRECTLY — degraded throughput is measured,
                wrong answers are counted (and must be zero).
    recovery    the plan is cleared; the run continues until throughput
                is steady again.  `recovery_latency_s` — fault stop to
                steady-state re-detection — is the `chaos-recovery`
                benchwatch threshold row's metric.

Every submitted request is tracked with its EXPECTED outcome (the pool
statements are valid → True; sha256/fr expectations precomputed on the
host oracle), so "zero wrong verification results" is a measured
property of the whole round, not an assumption.  A final self-healing
segment corrupts a `MerkleForest` update under a corrupt fault and
drives the detect→quarantine→rebuild loop (`healing.heal_forest`),
recording its recovery wall.

Returns `serve.loadgen.run_load`'s block shape (schema:
`telemetry.export.validate_serve_block`) plus a `"resilience"`
sub-object (schema: `validate_resilience_block`) that `bench_serve.py`
embeds and `telemetry.history` mines into `resilience::*` records.
"""

from __future__ import annotations

import time

from .. import telemetry
from . import faults, healing
from .policies import BreakerRegistry, RetryPolicy

# the canned plan (used when CST_FAULTS is unset): four dispatch
# failures into the RLC verify kernel — enough to trip a threshold-2
# breaker through retry, exercise the oracle-fallback degraded mode,
# fail at least one half-open probe, and then let the device recover
DEFAULT_CHAOS_SPEC = "seed=1234;dispatch:raise:key=rlc_*:count=4"

# chaos-round policy shape: trip fast, probe fast — the smoke must see
# the full open→half-open→closed arc inside a handful of windows
CHAOS_RETRY = dict(max_attempts=2, base_backoff_s=0.01, max_backoff_s=0.1)
CHAOS_BREAKER = dict(threshold=2, cooldown_s=0.5)

_TRACK_CAP = 200_000     # correctness-tracking memory bound


def _expectations(payloads):
    """Host-oracle expected values for the checkable request kinds."""
    import numpy as np

    from ..ops.sha256_np import merkleize_words
    from ..serve.executor import _oracle_barycentric

    words, limit_depth = payloads["sha256"]
    return {
        "sha256": merkleize_words(np.asarray(words, dtype=np.uint32),
                                  limit_depth),
        "fr": _oracle_barycentric(*payloads["fr"]),
    }


def _check_results(tracked, expected) -> dict:
    """Settle accounting over the tracked (kind, future) pairs: wrong
    values vs the oracle expectations, and exception-settled requests
    (typed failures — visible, but not wrong answers)."""
    import numpy as np

    wrong = 0
    failed = 0
    checked = 0
    for kind, fut in tracked:
        exc = fut.exception()
        if exc is not None:
            failed += 1
            continue
        value = fut.result()
        checked += 1
        if kind in ("verify", "pairing"):
            if value is not True:
                wrong += 1
        elif kind == "sha256":
            if not np.array_equal(np.asarray(value),
                                  expected["sha256"]):
                wrong += 1
        elif kind == "fr":
            if int(value) != expected["fr"]:
                wrong += 1
        elif kind == "proof":
            if not isinstance(value, list) or not value:
                wrong += 1
    return {"wrong": wrong, "failed": failed, "checked": checked}


def _heal_segment() -> dict:
    """The self-healing Merkle arc, run deterministically: one update
    under a corrupt fault diverges a small forest; the detector
    quarantines it, the rebuild re-serves, the recovery wall is
    recorded."""
    import numpy as np

    from ..parallel.incremental import MerkleForest

    rng = np.random.RandomState(97)
    n = 256
    words = rng.randint(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32)
    forest = MerkleForest(words, 10, n)
    faults.install({"seed": 5, "faults": [
        {"site": "merkle_update", "kind": "corrupt", "count": 1}]})
    try:
        forest.update([3], rng.randint(0, 2**32, (1, 8),
                                       dtype=np.uint64).astype(np.uint32))
    finally:
        faults.clear()
    detected = healing.forest_diverged(forest)
    report = healing.heal_forest(forest)
    return {
        "detected": bool(detected),
        "diverged": bool(report.diverged),
        "recovery_s": (round(report.recovery_s, 6)
                       if report.recovery_s is not None else None),
        "n_chunks": n,
    }


def run_chaos_load(cfg=None, plan=None) -> dict:
    """See the module docstring.  `cfg` is a `serve.loadgen.LoadConfig`
    (env defaults otherwise); chaos rounds are always closed-loop (an
    open-loop clock under faults measures the clock, not the service).
    `plan` overrides CST_FAULTS / the canned default."""
    from ..serve.executor import ServeExecutor
    from ..serve.loadgen import (
        _fr_payload,
        _pairing_payload,
        _proof_payload,
        _sha_payload,
        _warm_kernels,
        build_statement_pool,
        config_from_env,
        drive_closed_loop,
        make_submitter,
        percentile_ms,
        steady_state,
    )

    cfg = cfg if cfg is not None else config_from_env()
    if plan is None:
        plan = faults.plan_from_env_source() or DEFAULT_CHAOS_SPEC
    plan = faults.load_plan(plan)

    pool = build_statement_pool(cfg.pool, cfg.committee)
    payloads = {"pairing": _pairing_payload(pool[0]),
                "fr": _fr_payload(), "sha256": _sha_payload(),
                "proof": _proof_payload()}
    expected = _expectations(payloads)
    warm_s = _warm_kernels(cfg, pool, payloads)

    breakers = BreakerRegistry(**CHAOS_BREAKER)
    ex = ServeExecutor(max_batch=cfg.max_batch, depth=cfg.depth,
                       retry=RetryPolicy(**CHAOS_RETRY),
                       breakers=breakers)
    tracked: list[tuple] = []

    def track(kind, fut):
        if len(tracked) < _TRACK_CAP:
            tracked.append((kind, fut))

    # the shared mainnet arrival mix + closed-loop drive (loadgen owns
    # both — the chaos round must measure the same traffic shape
    # run_load does, just phased around the fault plan)
    submit_next, kinds_submitted = make_submitter(ex, pool, payloads,
                                                  track=track)
    target_outstanding = cfg.max_batch * (cfg.depth + 1)
    window_s = cfg.duration_s / cfg.windows
    rates: list[float] = []
    settled_prev = 0

    def run_window():
        nonlocal settled_prev
        win_t0 = time.perf_counter()
        drive_closed_loop(ex, submit_next, target_outstanding,
                          win_t0 + window_s)
        settled_now = ex.stats()["settled"]
        rates.append((settled_now - settled_prev)
                     / (time.perf_counter() - win_t0))
        settled_prev = settled_now

    t0 = time.perf_counter()
    with telemetry.span("resilience.chaos_round"):
        # phase 1: healthy baseline, until steady (≤3x extension)
        for _ in range(3 * cfg.windows):
            run_window()
            if len(rates) >= 3 and steady_state(rates):
                break
        baseline_rate = (sum(rates[-3:]) / 3.0 if len(rates) >= 3
                         else (rates[-1] if rates else 0.0))
        baseline_windows = len(rates)

        # phase 2: the fault plan is live
        faults.install(plan)
        try:
            for _ in range(cfg.windows):
                run_window()
        finally:
            injected = faults.injections()
            faults.clear()
        chaos_rates = rates[baseline_windows:]
        degraded_rate = (min(chaos_rates) if chaos_rates else None)

        # phase 3: recovery — run until steady again
        t_clear = time.perf_counter()
        recovery_latency_s = None
        for _ in range(3 * cfg.windows):
            run_window()
            if steady_state(rates):
                recovery_latency_s = time.perf_counter() - t_clear
                break
    measured_s = time.perf_counter() - t0
    ex.drain()

    heal = _heal_segment()
    check = _check_results(tracked, expected)
    st = ex.stats()
    recovered = recovery_latency_s is not None
    steady = recovered and steady_state(rates)
    steady_rate = (sum(rates[-3:]) / 3.0 if len(rates) >= 3 else 0.0)

    by_site: dict[str, int] = {}
    for rec in injected:
        by_site[rec["site"]] = by_site.get(rec["site"], 0) + 1

    block = {
        "verifies_per_s": round(steady_rate, 2),
        "p50_ms": percentile_ms(ex.latencies_s, 0.50),
        "p99_ms": percentile_ms(ex.latencies_s, 0.99),
        "steady": steady,
        "windows": [round(r, 2) for r in rates],
        "window_s": round(window_s, 3),
        "duration_s": round(measured_s, 3),
        "warmup_s": round(warm_s, 3),
        "mode": "closed",
        "rate_multiple": 0.0,
        "offered_per_s": None,
        "pool": cfg.pool,
        "committee": cfg.committee,
        "max_batch": cfg.max_batch,
        "depth": cfg.depth,
        "kinds": kinds_submitted,
        "submitted": st["submitted"],
        "settled": st["settled"],
        "failed": st["failed"],
        "rechecks": st["rechecks"],
        "batches": st["batches"],
        "queue_depth": st["queue_depth"],
        "inflight_max": st["inflight_max"],
        "resilience": {
            "chaos": True,
            "plan": plan.describe(),
            "faults_injected": len(injected),
            "injected_sites": by_site,
            "wrong_results": check["wrong"],
            "failed_requests": check["failed"],
            "checked_results": check["checked"],
            "baseline_verifies_per_s": round(baseline_rate, 2),
            "degraded_verifies_per_s": (round(degraded_rate, 2)
                                        if degraded_rate is not None
                                        else None),
            "recovery_latency_s": (round(recovery_latency_s, 3)
                                   if recovered else None),
            "recovered": recovered,
            "breaker": breakers.summary(),
            "retries": st["retries"],
            "fallbacks": st["fallbacks"],
            "shed": st["shed"],
            "heal": heal,
        },
    }
    return block
