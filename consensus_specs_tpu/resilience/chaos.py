"""Chaos rounds: the mainnet arrival mix under an active fault plan.

`run_chaos_load` (armed by `CST_SERVE_CHAOS=1` in `bench_serve.py`)
drives the serve executor through three phases and requires the service
to come back:

    baseline    closed-loop windows until throughput is steady — the
                healthy-rate reference.
    chaos       the fault plan (CST_FAULTS, or the canned
                `DEFAULT_CHAOS_SPEC` injecting dispatch failures into
                the RLC kernel) is installed; the executor runs with
                the recovery policies armed (retry + per-(kind, rung)
                breakers + oracle fallback), so every request still
                answers CORRECTLY — degraded throughput is measured,
                wrong answers are counted (and must be zero).
    recovery    the plan is cleared; the run continues until throughput
                is steady again.  `recovery_latency_s` — fault stop to
                steady-state re-detection — is the `chaos-recovery`
                benchwatch threshold row's metric.

Every submitted request is tracked with its EXPECTED outcome (the pool
statements are valid → True; sha256/fr expectations precomputed on the
host oracle), so "zero wrong verification results" is a measured
property of the whole round, not an assumption.

Request tracing is armed for the whole round (telemetry.reqtrace,
regardless of CST_TRACE_REQUESTS): the serve block carries per-request
p50/p99 + the `latency_attribution` decomposition, and the
`"resilience"` block's `fault_victims` correlates every injected fault
with the trace ids it hit and their final outcomes — pinning the blast
radius to exactly the retried/fallback-answered/poisoned handles (a
fault victim can never settle with a clean `ok`).

The SLO watchdog (`telemetry.monitor`) is armed for the whole round on
a deterministic per-window tick: the canned `CHAOS_SLO_RULE` watches
the fired-fault count (plus any `CST_SLO_RULES` the operator set), and
the round ASSERTS the arc both ways — the rule breaches inside the
fault window and clears after recovery — so every chaos run regression-
tests the watchdog itself.  The evidence rides the block as the `"slo"`
sub-object plus `resilience["slo_arc"]`.

Deterministic closing segments (each oracle-checked, each feeding its
own sub-block of the `"resilience"` object):

    heal        corrupts a `MerkleForest` update under a corrupt fault
                and drives detect→quarantine→recover
                (`healing.heal_forest`) — now through CHECKPOINT
                RESTORE when a valid snapshot exists (`heal["path"]`
                records which recovery ran).
    checkpoint  kills and resurrects a forest mid-round: snapshot →
                journaled updates → drop the live stack → restore
                (snapshot + journal replay, checksum-verified) vs a
                full rebuild, root parity against the independent
                host-oracle rebuild — the `checkpoint-restore`
                threshold row's measurement (≥5x at ≤1% journal
                depth).
    flagship    the block executor's breaker ladder
                (`executor.settle_deferred`): a dispatch fault trips
                the settle breaker to the pure-Python spec oracle,
                degraded steps are counted, the half-open probe
                re-closes — `flagship::degraded_steps`.
    mesh        (CST_CHAOS_MESH=1, needs ≥2 devices — the simulated
                8-host-device CI lane or a real mesh) `device_loss`
                into `batch_verify_sharded`: the lost shard's
                statements re-bucket over the surviving devices
                (`resilience.mesh.MeshVerifier`), an invalid statement
                still rejects while degraded, and the re-admission
                probe restores the full mesh — the
                `mesh-recovery`/`mesh-lost-statements` rows.

Returns `serve.loadgen.run_load`'s block shape (schema:
`telemetry.export.validate_serve_block`) plus a `"resilience"`
sub-object (schema: `validate_resilience_block`) that `bench_serve.py`
embeds and `telemetry.history` mines into `resilience::*` records.
"""

from __future__ import annotations

import time

from .. import telemetry
from ..telemetry import metrics_export, monitor, reqtrace
from . import faults, healing
from .policies import BreakerRegistry, RetryPolicy

# the canned plan (used when CST_FAULTS is unset): four dispatch
# failures into the RLC verify kernel — enough to trip a threshold-2
# breaker through retry, exercise the oracle-fallback degraded mode,
# fail at least one half-open probe, and then let the device recover
DEFAULT_CHAOS_SPEC = "seed=1234;dispatch:raise:key=rlc_*:count=4"

# the chaos round's canned SLO rule: the fired-fault count is the one
# signal that is 1:1 with the plan being live (whatever traffic shape
# the round measured), so the watchdog arc — breach INSIDE the fault
# window, clear after recovery — is deterministic.  The round asserts
# both directions, which regression-tests the watchdog itself.
CHAOS_SLO_RULE = {"metric": "counter.faults.injected", "op": "<=",
                  "threshold": 0.0, "for": 1, "clear": 2,
                  "name": "chaos-fault-injections"}

# chaos-round policy shape: trip fast, probe fast — the smoke must see
# the full open→half-open→closed arc inside a handful of windows
CHAOS_RETRY = dict(max_attempts=2, base_backoff_s=0.01, max_backoff_s=0.1)
CHAOS_BREAKER = dict(threshold=2, cooldown_s=0.5)

_TRACK_CAP = 200_000     # correctness-tracking memory bound
_VICTIM_IDS_CAP = 64     # trace ids listed verbatim in the block


def _fault_victims() -> dict:
    """Blast-radius correlation (request tracing): the trace ids whose
    dispatch/settle hit an injected fault, with their final outcomes.
    The pin the chaos smoke asserts — a fault-hit request may recover
    (retry) or degrade (fallback) or poison, but it can never settle
    with a clean 'ok': the executor marks every member of a
    FaultInjected batch, so blast radius == exactly these handles."""
    victims = [r for r in reqtrace.records() if r.get("faulted")]
    outcomes: dict[str, int] = {}
    for r in victims:
        outcomes[r["outcome"]] = outcomes.get(r["outcome"], 0) + 1
    return {
        "count": len(victims),
        "trace_ids": [r["trace_id"] for r in victims[:_VICTIM_IDS_CAP]],
        "outcomes": outcomes,
        "clean_ok": outcomes.get("ok", 0),   # must stay 0
    }


def _expectations(payloads):
    """Host-oracle expected values for the checkable request kinds."""
    import numpy as np

    from ..ops.sha256_np import merkleize_words
    from ..serve.executor import _oracle_barycentric

    words, limit_depth = payloads["sha256"]
    return {
        "sha256": merkleize_words(np.asarray(words, dtype=np.uint32),
                                  limit_depth),
        "fr": _oracle_barycentric(*payloads["fr"]),
    }


def _check_results(tracked, expected) -> dict:
    """Settle accounting over the tracked (kind, future) pairs: wrong
    values vs the oracle expectations, and exception-settled requests
    (typed failures — visible, but not wrong answers)."""
    import numpy as np

    wrong = 0
    failed = 0
    checked = 0
    for kind, fut in tracked:
        exc = fut.exception()
        if exc is not None:
            failed += 1
            continue
        value = fut.result()
        checked += 1
        if kind in ("verify", "pairing"):
            if value is not True:
                wrong += 1
        elif kind == "sha256":
            if not np.array_equal(np.asarray(value),
                                  expected["sha256"]):
                wrong += 1
        elif kind == "fr":
            if int(value) != expected["fr"]:
                wrong += 1
        elif kind == "proof":
            if not isinstance(value, list) or not value:
                wrong += 1
        elif kind == "das":
            # every lane sample is a valid closed-form column: the
            # only correct verdict is True (the oracle fallback's
            # host route included)
            if value is not True:
                wrong += 1
        elif kind == "fc_atts":
            # an accepted-count outside [0, batch] is impossible on
            # both routes (the store mutates under load, so the exact
            # count is schedule-dependent, not a fixed expectation)
            if not isinstance(value, int) or value < 0:
                wrong += 1
        elif kind == "head":
            # both routes answer a 32-byte block root out of the store
            if not (isinstance(value, bytes) and len(value) == 32):
                wrong += 1
    return {"wrong": wrong, "failed": failed, "checked": checked}


def _heal_segment() -> dict:
    """The self-healing Merkle arc, run deterministically: one update
    under a corrupt fault diverges a small forest; the detector
    quarantines it and recovery re-serves — via CHECKPOINT RESTORE
    (snapshot taken before the corruption, the corrupt update's honest
    delta in the journal) when the snapshot is valid, else the full
    rebuild.  The taken path is recorded (`heal["path"]`)."""
    import tempfile

    import numpy as np

    from ..parallel.incremental import MerkleForest
    from . import checkpoint as ckpt

    rng = np.random.RandomState(97)
    n = 256
    words = rng.randint(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32)
    forest = MerkleForest(words, 10, n)
    with tempfile.TemporaryDirectory(prefix="cst_heal_ckpt_") as tmp:
        mgr = ckpt.CheckpointManager(ckpt.env_dir() or tmp, name="heal")
        forest.checkpoint = mgr
        mgr.snapshot(forest)
        faults.install({"seed": 5, "faults": [
            {"site": "merkle_update", "kind": "corrupt", "count": 1}]})
        try:
            # the corrupt fault damages the dispatched interior layers;
            # the journal records the HONEST delta, so the checkpoint
            # path restores exactly the reference state
            forest.update([3], rng.randint(
                0, 2**32, (1, 8), dtype=np.uint64).astype(np.uint32))
        finally:
            faults.clear()
        detected = healing.forest_diverged(forest)
        report = healing.heal_forest(forest)
    return {
        "detected": bool(detected),
        "diverged": bool(report.diverged),
        "recovery_s": (round(report.recovery_s, 6)
                       if report.recovery_s is not None else None),
        "path": report.path,
        "n_chunks": n,
    }


def _checkpoint_segment(n_log2: int = 20, update_chunks: int = 256,
                        updates: int = 2) -> dict:
    """Kill-and-resurrect: snapshot a forest, journal a ≤1% dirty
    stream, drop the live layer stack, then race checkpoint restore
    (snapshot load + journal replay, zero full re-hash) against the
    full O(N) rebuild.  Root parity is asserted against both the live
    pre-kill root and the independent pure-host oracle rebuild.  Feeds
    the `checkpoint-restore` benchwatch threshold row (speedup =
    rebuild/restore, best-of-2 each so first-touch I/O noise cancels).

    2^20 chunks is the acceptance shape (the merkle bench's): big
    enough that the O(N) rebuild dominates restore's fixed I/O +
    root-fetch floor — the CPU smoke measures ~8x there, vs ~4x at
    2^17 where a rebuild is only ~0.7s."""
    import tempfile

    import numpy as np

    from ..parallel.incremental import MerkleForest
    from . import checkpoint as ckpt

    rng = np.random.RandomState(53)
    n = 1 << n_log2
    limit_depth = n_log2 + 2
    words = rng.randint(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32)
    with telemetry.span("resilience.chaos.checkpoint_segment", n=n):
        forest = MerkleForest(words, limit_depth, n)
        with tempfile.TemporaryDirectory(prefix="cst_ckpt_") as tmp:
            mgr = ckpt.CheckpointManager(ckpt.env_dir() or tmp,
                                         name="chaos")
            forest.checkpoint = mgr
            mgr.snapshot(forest)
            for _ in range(updates):
                idx = np.unique(rng.choice(n, update_chunks,
                                           replace=False))
                leaves = rng.randint(0, 2**32, (idx.shape[0], 8),
                                     dtype=np.uint64).astype(np.uint32)
                forest.update(idx, leaves)      # journaled via the hook
            expected = forest.root_bytes()
            reference = healing._reference_root_bytes(forest)
            final_leaves = np.asarray(forest.layers[0])[:n]
            journal_frac = mgr.journal_depth_frac(n)
            del forest                          # the "process death"

            restore_s = None
            parity = True
            replayed = 0
            for _ in range(2):                  # best-of-2
                t0 = time.perf_counter()
                restored = mgr.restore()
                root = restored.root_bytes()
                dt = time.perf_counter() - t0
                restore_s = dt if restore_s is None else min(restore_s, dt)
                replayed = restored.restored_journal_entries
                parity = parity and root == expected == reference
            rebuild_s = None
            for _ in range(2):
                t0 = time.perf_counter()
                rebuilt = MerkleForest(final_leaves, limit_depth, n)
                root = rebuilt.root_bytes()
                dt = time.perf_counter() - t0
                rebuild_s = dt if rebuild_s is None else min(rebuild_s, dt)
                parity = parity and root == expected
            speedup = rebuild_s / restore_s if restore_s else None
    telemetry.observe("checkpoint.restore_s", restore_s)
    return {
        "n_chunks": n,
        "journal_entries": mgr.journal_entries,
        "journal_replayed": replayed,
        "journal_frac": round(journal_frac, 5),
        "snapshot_bytes": mgr.snapshot_bytes,
        "restore_s": round(restore_s, 6),
        "rebuild_s": round(rebuild_s, 6),
        "speedup": round(speedup, 2) if speedup is not None else None,
        "parity": bool(parity),
    }


def _flagship_segment() -> dict:
    """The block executor's breaker arc: a healthy device settle, a
    dispatch fault that trips the settle breaker onto the pure-Python
    spec oracle (verdicts stay correct), an OPEN-breaker settle served
    entirely by the oracle, then the half-open probe re-closing on the
    recovered device.  Counts `flagship::degraded_steps`."""
    from .. import executor as flagship
    from ..ops import bls
    from ..serve.loadgen import build_statement_pool
    from .policies import BreakerRegistry

    pool = build_statement_pool(2, 2, seed_base=9100)
    # injected clock: the pure-Python oracle settle takes seconds, so a
    # wall-clock cooldown would silently elapse mid-arc and turn the
    # OPEN-breaker settle into the probe — the arc must be deterministic
    clk = [0.0]
    registry = BreakerRegistry(threshold=1, cooldown_s=0.5,
                               clock=lambda: clk[0])
    flagship.reset_degraded_steps()
    wrong = 0
    checked = 0

    def one_settle(expect: bool = True) -> None:
        nonlocal wrong, checked
        batch = bls.DeferredBatch()
        batch.tasks = list(pool)
        ok = flagship.settle_deferred(batch, device=True,
                                      breakers=registry)
        checked += 1
        if bool(ok) is not expect:
            wrong += 1

    with telemetry.span("resilience.chaos.flagship_segment"):
        one_settle()                    # healthy: device settle
        faults.install({"seed": 9, "faults": [
            {"site": "dispatch", "kind": "raise", "key": "rlc_*",
             "count": 1}]})
        try:
            one_settle()                # device fails → trip → oracle
            one_settle()                # breaker OPEN → oracle directly
        finally:
            faults.clear()
        clk[0] = 1.0                    # past the cooldown
        one_settle()                    # half-open probe → re-close
    states = registry.states()
    return {
        "degraded_steps": flagship.degraded_steps(),
        "wrong_results": wrong,
        "checked_settles": checked,
        "breaker": registry.summary(),
        "recovered": all(s == "closed" for s in states.values()),
    }


def mesh_enabled() -> bool:
    """The CST_CHAOS_MESH knob: arm the simulated-mesh shard-loss
    segment (needs ≥2 devices; the chaos-mesh CI lane forces 8 host
    devices via XLA_FLAGS)."""
    import os

    return os.environ.get("CST_CHAOS_MESH", "0") not in ("", "0")


def _mesh_segment() -> dict:
    """The shard-loss recovery arc on a real (or simulated) mesh:
    healthy full-mesh verifies, one injected `device_loss` at the
    sharded dispatch seam → the verifier re-buckets the SAME statements
    over the surviving n-1 devices (degraded mode, zero wrong/dropped),
    an INVALID statement still rejects while degraded, and after the
    cooldown the half-open probe re-admits the full mesh.  Every
    verdict is checked against the host-oracle expectation."""
    import jax

    from ..serve.loadgen import build_statement_pool
    from .mesh import MeshVerifier

    available = len(jax.devices())
    if available < 2:
        return {"skipped": f"{available} device(s) — mesh segment "
                           f"needs >= 2", "devices": available}

    pool = build_statement_pool(4, 2, seed_base=8200)
    # an invalid statement: statement 0's message with statement 1's
    # signature — FastAggregateVerify must reject it, degraded or not
    bad = (pool[0][0], pool[0][1], pool[1][2])
    # offset clock: recovery latency must be REAL wall (the n-1
    # re-dispatch compiles a fresh executable — that IS the recovery
    # cost), but the re-admission probe must fire exactly when the
    # segment says so — a wall-clock cooldown would elapse during that
    # same compile and silently turn the degraded-mode checks below
    # into full-mesh ones
    offset = [0.0]

    def clock():
        return time.monotonic() + offset[0]

    verifier = MeshVerifier(n_devices=available,
                            readmit_cooldown_s=3600.0, clock=clock)
    wrong = 0
    dropped = 0
    checked = 0

    def check(tasks, expect: bool) -> None:
        nonlocal wrong, dropped, checked
        try:
            ok = verifier.verify(list(tasks))
        # cst: allow(exc-swallow-device): the segment's contract IS counting dropped statements; the verifier already classified and recorded the failure
        except Exception:
            dropped += len(tasks)
            return
        checked += len(tasks)
        if bool(ok) is not expect:
            wrong += len(tasks)

    with telemetry.span("resilience.chaos.mesh_segment",
                        devices=available):
        check(pool, True)               # healthy full-mesh baseline
        faults.install({"seed": 77, "faults": [
            {"site": "dispatch", "kind": "device_loss",
             "key": "rlc_sharded@*", "count": 1}]})
        try:
            check(pool, True)           # loss fires → recover on n-1
        finally:
            faults.clear()
        check(pool, True)               # still degraded (cooldown held)
        check(pool + [bad], False)      # invalid rejects while degraded
        assert verifier.state.degraded(), (
            "degraded-mode checks must run on the shrunken mesh")
        offset[0] += 3600.0             # cooldown elapses, on our terms
        check(pool, True)               # probe re-admits the full mesh
    block = verifier.block()
    block.update({
        "wrong_results": wrong,
        "dropped_statements": dropped,
        "checked_statements": checked,
        "readmitted": not verifier.state.degraded(),
    })
    return block


def _chaos_slo_rules(window_s: float) -> dict:
    """The chaos watchdog's rule set: the canned injection-rate rule
    (rate window spanning a few load windows, so the breach clears
    within the recovery phase) plus any `CST_SLO_RULES` the operator
    armed — those are evaluated on the same deterministic ticks.  A
    malformed env set is skipped with the counted warning
    (`install_from_env`'s contract), never killing the round; an env
    rule whose name collides with an already-merged one is dropped."""
    import os
    import sys

    rule = dict(CHAOS_SLO_RULE)
    rule["window_s"] = max(3.0 * window_s, 0.5)
    rules = {"rules": [rule]}
    source = os.environ.get("CST_SLO_RULES")
    if source:
        try:
            extra = monitor.load_rules(source)
        except ValueError as exc:       # json.JSONDecodeError included
            telemetry.count("slo.rules_invalid")
            print(f"slo: ignoring invalid CST_SLO_RULES: {exc}",
                  file=sys.stderr)
            return rules
        seen = {r.get("name") or monitor._default_name(r["metric"],
                                                       r.get("kind"))
                for r in rules["rules"]}
        for r in extra["rules"]:
            name = r.get("name") or monitor._default_name(r["metric"],
                                                          r.get("kind"))
            if name not in seen:
                seen.add(name)
                rules["rules"].append(r)
        if "tick_s" in extra:
            rules["tick_s"] = extra["tick_s"]
    return rules


def run_chaos_load(cfg=None, plan=None) -> dict:
    """See the module docstring.  `cfg` is a `serve.loadgen.LoadConfig`
    (env defaults otherwise); chaos rounds are always closed-loop (an
    open-loop clock under faults measures the clock, not the service).
    `plan` overrides CST_FAULTS / the canned default."""
    from ..serve.loadgen import config_from_env

    cfg = cfg if cfg is not None else config_from_env()
    if plan is None:
        plan = faults.plan_from_env_source() or DEFAULT_CHAOS_SPEC
    plan = faults.load_plan(plan)

    # request tracing is part of the chaos contract: the blast-radius
    # correlation (which trace ids a fault hit, and how each settled)
    # needs per-request contexts, so the round arms them regardless of
    # CST_TRACE_REQUESTS and restores the prior state afterwards
    was_tracing = reqtrace.enabled()
    reqtrace.configure(enabled=True)
    try:
        return _run_chaos_load(cfg, plan)
    finally:
        reqtrace.configure(enabled=was_tracing)


def _run_chaos_load(cfg, plan) -> dict:
    from ..serve.executor import ServeExecutor
    from ..serve.loadgen import (
        DAS_SAMPLES_PER_SLOT,
        FC_ATTS_PER_SLOT,
        _das_payloads,
        _fc_payload,
        _fr_payload,
        _pairing_payload,
        _proof_payload,
        _sha_payload,
        _warm_kernels,
        build_statement_pool,
        drive_closed_loop,
        latency_block,
        make_submitter,
        steady_state,
    )

    pool = build_statement_pool(cfg.pool, cfg.committee)
    payloads = {"pairing": _pairing_payload(pool[0]),
                "fr": _fr_payload(), "sha256": _sha_payload(),
                "proof": _proof_payload(),
                "das": (_das_payloads() if DAS_SAMPLES_PER_SLOT
                        else []),
                "fc": (_fc_payload() if FC_ATTS_PER_SLOT else None)}
    expected = _expectations(payloads)
    warm_s = _warm_kernels(cfg, pool, payloads)
    # scope the lifecycle records to THIS round's three phases (warmup
    # settles are setup, not served traffic)
    reqtrace.reset()

    breakers = BreakerRegistry(**CHAOS_BREAKER)
    ex = ServeExecutor(max_batch=cfg.max_batch, depth=cfg.depth,
                       retry=RetryPolicy(**CHAOS_RETRY),
                       breakers=breakers)
    tracked: list[tuple] = []

    def track(kind, fut):
        if len(tracked) < _TRACK_CAP:
            tracked.append((kind, fut))

    # the shared mainnet arrival mix + closed-loop drive (loadgen owns
    # both — the chaos round must measure the same traffic shape
    # run_load does, just phased around the fault plan)
    submit_next, kinds_submitted = make_submitter(ex, pool, payloads,
                                                  track=track)
    target_outstanding = cfg.max_batch * (cfg.depth + 1)
    window_s = cfg.duration_s / cfg.windows
    rates: list[float] = []
    settled_prev = 0

    # the SLO watchdog is part of the chaos contract: ticked once per
    # load window (the daemon's wall-clock cadence would race the phase
    # boundaries), it must breach while the plan is live and clear
    # during recovery — asserted below, on the same clock the ticks use.
    # The exposition endpoint is armed too, so a chaos pod round is
    # scrapeable mid-fault.
    metrics_export.start_from_env()
    metrics_export.set_status_provider(ex.status)

    def injected_total(name: str) -> float:
        # the chaos rule's signal: fired faults so far (site-agnostic —
        # a CST_FAULTS override may target any seam)
        if name == "faults.injected":
            return float(len(faults.injections()))
        return telemetry.counter_value(name)

    wd = monitor.install(_chaos_slo_rules(window_s), autostart=False,
                         status_provider=ex.status,
                         counter_provider=injected_total,
                         profile_dir=monitor.profile_dir_from_env())

    def run_window():
        nonlocal settled_prev
        win_t0 = time.perf_counter()
        drive_closed_loop(ex, submit_next, target_outstanding,
                          win_t0 + window_s)
        settled_now = ex.stats()["settled"]
        rates.append((settled_now - settled_prev)
                     / (time.perf_counter() - win_t0))
        settled_prev = settled_now
        wd.tick()

    t0 = time.perf_counter()
    with telemetry.span("resilience.chaos_round"):
        # phase 1: healthy baseline, until steady (≤3x extension)
        for _ in range(3 * cfg.windows):
            run_window()
            if len(rates) >= 3 and steady_state(rates):
                break
        baseline_rate = (sum(rates[-3:]) / 3.0 if len(rates) >= 3
                         else (rates[-1] if rates else 0.0))
        baseline_windows = len(rates)

        # phase 2: the fault plan is live
        t_fault0 = time.monotonic()
        faults.install(plan)
        try:
            for _ in range(cfg.windows):
                run_window()
        finally:
            injected = faults.injections()
            faults.clear()
        t_fault1 = time.monotonic()
        chaos_rates = rates[baseline_windows:]
        degraded_rate = (min(chaos_rates) if chaos_rates else None)

        # phase 3: recovery — run until steady again
        t_clear = time.perf_counter()
        recovery_latency_s = None
        for _ in range(3 * cfg.windows):
            run_window()
            if steady_state(rates):
                recovery_latency_s = time.perf_counter() - t_clear
                break
    measured_s = time.perf_counter() - t0
    ex.drain()
    # let the clear hysteresis drain: the recovery loop may have hit
    # its steady-state break before `clear` consecutive healthy ticks
    # ran (the plan is gone, so every extra tick is healthy)
    for _ in range(2 * CHAOS_SLO_RULE["clear"] + 2):
        if not wd.breaching():
            break
        wd.tick()
    metrics_export.set_status_provider(None)
    slo_block = monitor.clear()

    # the watchdog arc, asserted both ways (it is only required when
    # faults actually fired — a CST_FAULTS plan keyed off this round's
    # traffic never breaches, correctly)
    arc_name = CHAOS_SLO_RULE["name"]
    breached_in_window = any(
        e["phase"] == "breach" and t_fault0 <= e["ts"] <= t_fault1
        for e in slo_block["events"] if e["rule"] == arc_name)
    arc_cleared = arc_name not in slo_block["breaching_now"]
    if injected:
        assert breached_in_window, (
            f"{len(injected)} fault(s) fired but the "
            f"{arc_name!r} SLO rule never breached inside the fault "
            f"window — the watchdog missed a live incident")
        assert arc_cleared, (
            f"the {arc_name!r} SLO rule is still breaching after "
            f"recovery — the clear hysteresis never released")

    # per-request latency basis + tail attribution + the fault→victim
    # correlation, all from the round's lifecycle records (before the
    # closing segments run — they own their own fault plans)
    p50_ms, p99_ms, latency_attribution = latency_block(ex)
    victims = _fault_victims()

    heal = _heal_segment()
    ckpt_block = _checkpoint_segment()
    flagship = _flagship_segment()
    mesh = _mesh_segment() if mesh_enabled() else None
    check = _check_results(tracked, expected)
    st = ex.stats()
    recovered = recovery_latency_s is not None
    steady = recovered and steady_state(rates)
    steady_rate = (sum(rates[-3:]) / 3.0 if len(rates) >= 3 else 0.0)

    by_site: dict[str, int] = {}
    for rec in injected:
        by_site[rec["site"]] = by_site.get(rec["site"], 0) + 1

    block = {
        "verifies_per_s": round(steady_rate, 2),
        "p50_ms": p50_ms,
        "p99_ms": p99_ms,
        "latency_source": "reqtrace",
        "steady": steady,
        "windows": [round(r, 2) for r in rates],
        "window_s": round(window_s, 3),
        "duration_s": round(measured_s, 3),
        "warmup_s": round(warm_s, 3),
        "mode": "closed",
        "rate_multiple": 0.0,
        "offered_per_s": None,
        "pool": cfg.pool,
        "committee": cfg.committee,
        "max_batch": cfg.max_batch,
        "depth": cfg.depth,
        "kinds": kinds_submitted,
        "submitted": st["submitted"],
        "settled": st["settled"],
        "failed": st["failed"],
        "rechecks": st["rechecks"],
        "batches": st["batches"],
        "queue_depth": st["queue_depth"],
        "inflight_max": st["inflight_max"],
        "resilience": {
            "chaos": True,
            "plan": plan.describe(),
            "faults_injected": len(injected),
            "injected_sites": by_site,
            "fault_victims": victims,
            "wrong_results": check["wrong"],
            "failed_requests": check["failed"],
            "checked_results": check["checked"],
            "baseline_verifies_per_s": round(baseline_rate, 2),
            "degraded_verifies_per_s": (round(degraded_rate, 2)
                                        if degraded_rate is not None
                                        else None),
            "recovery_latency_s": (round(recovery_latency_s, 3)
                                   if recovered else None),
            "recovered": recovered,
            "breaker": breakers.summary(),
            "retries": st["retries"],
            "fallbacks": st["fallbacks"],
            "shed": st["shed"],
            "heal": heal,
            "checkpoint": ckpt_block,
            "flagship": flagship,
            "slo_arc": {
                "rule": arc_name,
                "breached_in_fault_window": breached_in_window,
                "cleared_after_recovery": arc_cleared,
            },
        },
    }
    block["slo"] = slo_block
    if latency_attribution is not None:
        block["latency_attribution"] = latency_attribution
    if mesh is not None:
        block["resilience"]["mesh"] = mesh
    return block
