"""Self-healing Merkle state — divergence detection, quarantine, rebuild.

PR 7's flagship asserts incremental-vs-full-rebuild root parity and then
CRASHES on mismatch; a serving system must instead detect the
divergence, stop serving from the poisoned state, rebuild, and resume.
This module promotes that parity check into exactly that loop for a
`parallel.incremental.MerkleForest`:

    detect      `forest_diverged(forest)`: recompute the data-tree root
                from the PERSISTED LEAF LAYER with an independent
                rebuild and compare against the incremental stack's
                root — a corrupted interior layer (bit-flipped device
                output, a lost scatter) shows up as a mismatch.
    quarantine  `heal_forest` marks the forest quarantined (serving
                code must not emit proofs/roots from a quarantined
                stack) for the duration of the rebuild.
    rebuild     the layer stack is rebuilt from the leaves (or from
                caller-supplied authoritative `leaf_words` when the
                leaf layer itself is suspect), the forest re-serves,
                and the recovery latency is recorded
                (`resilience.heal` span + the returned `HealReport` —
                the chaos round's `heal` block).

The detector is leaf-layer-trusting by design: interior layers are
DERIVED state (re-derivable at O(N) sha cost), leaves are SOURCE state —
when the source itself may be corrupt, pass the authority through
`leaf_words` and the rebuild heals both.  Roots verified against the
SSZ oracle in tests/test_resilience.py.

Heavy imports (numpy, the incremental module, and through it jax) stay
inside the functions: importing the resilience package must not
initialize a backend.
"""

from __future__ import annotations

import time
from typing import NamedTuple

from .. import telemetry


class HealReport(NamedTuple):
    """Outcome of one detect/quarantine/rebuild pass."""

    diverged: bool
    recovery_s: float | None     # rebuild wall when diverged, else None
    root: bytes                  # the (healed) full SSZ list root
    path: str = "none"           # which recovery ran: "none" (clean),
                                 # "checkpoint" (snapshot+journal
                                 # restore), or "rebuild" (full
                                 # re-merkleize from persisted leaves)


def _reference_root_bytes(forest, leaf_words=None) -> bytes:
    """The root an honest stack would serve: an independent rebuild
    from the leaf layer (host side, the pure-numpy sha path — it must
    not share the possibly-faulted device path it is checking)."""
    import numpy as np

    from ..ops.sha256_np import merkleize_words
    from ..parallel.incremental import (
        _length_chunk,
        _words_to_bytes,
    )
    from ..ops.sha256_np import sha256_64B_words as _host_sha256

    if leaf_words is None:
        leaf_words = np.asarray(forest.layers[0])[:forest.n_chunks]
    leaf_words = np.asarray(leaf_words, dtype=np.uint32)
    data_root = merkleize_words(leaf_words, forest.limit_depth)
    tail = np.frombuffer(_length_chunk(forest.length),
                         dtype=">u4").astype(np.uint32)
    blk = np.concatenate([data_root, tail]).astype(np.uint32)
    return _words_to_bytes(_host_sha256(blk[None, :])[0])


def forest_diverged(forest, leaf_words=None) -> bool:
    """The divergence detector: does the incremental stack's root
    disagree with an independent rebuild from the leaves?"""
    return forest.root_bytes() != _reference_root_bytes(forest, leaf_words)


def heal_forest(forest, leaf_words=None, checkpoint=None) -> HealReport:
    """Detect / quarantine / rebuild / re-serve, returning the
    `HealReport` (recovery latency is the quarantine wall).  A clean
    forest returns immediately with `diverged=False`.  `leaf_words`
    optionally supplies authoritative leaves when the persisted leaf
    layer itself is suspect.

    `checkpoint` (a `resilience.checkpoint.CheckpointManager`, or the
    forest's attached one by default) makes recovery try snapshot +
    journal-replay restore FIRST — O(journal · log N) instead of the
    O(N) rebuild — falling back to the rebuild when the checkpoint is
    missing/corrupt or its restored root disagrees with the reference
    (a stale snapshot must never win over the leaves).  The taken path
    is recorded in `HealReport.path` (the resilience block's `heal`
    surface).  With authoritative `leaf_words` the checkpoint is
    bypassed: the caller asserted the persisted state — snapshot
    included — is suspect."""
    import numpy as np

    reference = _reference_root_bytes(forest, leaf_words)
    if forest.root_bytes() == reference:
        forest.quarantined = False
        return HealReport(False, None, reference, "none")

    telemetry.count("resilience.heal.diverged")
    forest.quarantined = True
    if checkpoint is None:
        checkpoint = getattr(forest, "checkpoint", None)
    t0 = time.perf_counter()
    path = "rebuild"
    with telemetry.span("resilience.heal", chunks=forest.n_chunks):
        from ..parallel.incremental import MerkleForest

        restored = None
        if checkpoint is not None and leaf_words is None:
            restored = checkpoint.restore_or_none()
            if restored is not None \
                    and restored.root_bytes() == reference:
                forest.layers = restored.layers
                path = "checkpoint"
                telemetry.count("resilience.heal.from_checkpoint")
            else:
                restored = None     # corrupt/stale — fall back
        if restored is None:
            if leaf_words is None:
                leaf_words = np.asarray(forest.layers[0])[:forest.n_chunks]
            rebuilt = MerkleForest(
                np.asarray(leaf_words, dtype=np.uint32),
                forest.limit_depth, forest.length)
            forest.layers = rebuilt.layers
            telemetry.count("resilience.heal.from_rebuild")
        root = forest.root_bytes()
    recovery_s = time.perf_counter() - t0
    forest.quarantined = False
    telemetry.observe("resilience.heal.recovery_s", recovery_s)
    assert root == reference, "rebuild did not converge to the oracle root"
    return HealReport(True, recovery_s, root, path)
