"""Mesh-resilient sharded verification — per-shard loss recovery.

`ops.bls_batch.batch_verify_sharded` spreads one RLC statement batch
over the device mesh; before this module, a single dead device (a real
`XlaRuntimeError` from a lost chip, or the injected `MeshDeviceLost` of
a chaos round) killed the whole batch with no recovery story — the one
un-recovered execution surface the ROADMAP's resilience item named.
This module closes it:

    detect      `MeshVerifier.verify` settles the sharded future and
                classifies failures: a device failure (`MeshDeviceLost`
                or an `XlaRuntimeError`) enters the recovery ladder,
                anything else propagates untouched (a malformed batch
                must not masquerade as a dead chip).
    degrade     the lost shard is marked (`MeshState`), and the SAME
                statements re-dispatch over the surviving devices — the
                per-shard bucket ladder re-buckets them automatically,
                so degraded n-1 (n-2, ...) mode loses capacity, never
                statements.  A one-device remainder degrades to the
                single-chip `batch_verify` path; zero survivors is the
                only case that surfaces the failure.
    re-admit    after `readmit_cooldown_s` the next verify becomes a
                HALF-OPEN probe on the full original mesh: success
                re-admits every lost device (one transition, like the
                breaker's half-open close), failure re-trips and
                restarts the cooldown.

Accounting: `mesh::recovery_latency_s` (first failure → recovered
verdict), `mesh.device_lost` / `mesh.readmitted` counters, and
`block()` — the `"mesh"` sub-object of the chaos round's resilience
block that `telemetry.history` mines into `mesh::*` records (the
`mesh-recovery` / `mesh-lost-statements` benchwatch threshold rows).

Zero wrong or dropped statements is the contract the chaos mesh
segment (`resilience.chaos._mesh_segment`) measures against the
host-oracle expectation, exactly like the serve chaos rounds.

Which physical lane died: XLA does not attribute a dead-executable
error to a device index, so `MeshState.mark_lost` retires the
highest-index surviving device by default (deterministic; correctness
never depends on WHICH lane is dropped — every statement re-buckets
over whatever survives).  Callers with better attribution may pass the
index explicitly.

Stdlib-only at import (the resilience contract): jax and the ops
modules load lazily inside the dispatch path.
"""

from __future__ import annotations

import time

from .. import telemetry
from ..telemetry import flightrec
from .faults import MeshDeviceLost

# exception class names that mean "the device/runtime died", as opposed
# to a caller bug — jaxlib's XlaRuntimeError is matched by name so this
# module never imports jax at module scope
_DEVICE_ERROR_TYPES = ("XlaRuntimeError", "JaxRuntimeError")


def is_device_failure(exc: BaseException) -> bool:
    """Does this exception mean a mesh device failed (recoverable by
    re-bucketing onto the survivors), rather than a caller bug?"""
    if isinstance(exc, MeshDeviceLost):
        return True
    return any(t.__name__ in _DEVICE_ERROR_TYPES
               for t in type(exc).__mro__)


class MeshState:
    """Which logical devices of an n-wide mesh are currently trusted,
    plus the half-open re-admission state machine.  `clock` is
    injectable so tests drive the cooldown without sleeping."""

    __slots__ = ("n_devices", "readmit_cooldown_s", "_clock", "lost",
                 "_tripped_at", "lost_events", "readmissions", "retrips")

    def __init__(self, n_devices: int, readmit_cooldown_s: float = 1.0,
                 clock=time.monotonic):
        assert n_devices >= 1
        self.n_devices = int(n_devices)
        self.readmit_cooldown_s = float(readmit_cooldown_s)
        self._clock = clock
        self.lost: set[int] = set()
        self._tripped_at = 0.0
        self.lost_events = 0
        self.readmissions = 0
        self.retrips = 0

    def surviving(self) -> tuple[int, ...]:
        return tuple(i for i in range(self.n_devices)
                     if i not in self.lost)

    def degraded(self) -> bool:
        return bool(self.lost)

    def probe_due(self) -> bool:
        """May the next dispatch probe the FULL mesh again?"""
        return (self.degraded()
                and self._clock() - self._tripped_at
                >= self.readmit_cooldown_s)

    def mark_lost(self, device: int | None = None) -> None:
        """Retire one device (highest surviving index when the failure
        carries no attribution) and restart the re-admission cooldown."""
        survivors = self.surviving()
        if not survivors:
            return
        device = int(device) if device is not None else survivors[-1]
        self.lost.add(device)
        self._tripped_at = self._clock()
        self.lost_events += 1
        telemetry.count("mesh.device_lost")
        telemetry.gauge("mesh.degraded_lanes", len(self.lost))
        flightrec.record("mesh_device_lost", device=device,
                         degraded_lanes=len(self.lost))

    def record_probe(self, ok: bool) -> None:
        """Outcome of a full-mesh half-open probe: success re-admits
        every lost device, failure re-trips and restarts the cooldown."""
        if ok:
            if self.lost:
                self.readmissions += 1
                telemetry.count("mesh.readmitted", len(self.lost))
                flightrec.record("mesh_device_back",
                                 devices=sorted(self.lost))
            self.lost.clear()
            telemetry.gauge("mesh.degraded_lanes", 0)
        else:
            self.retrips += 1
            self._tripped_at = self._clock()
            telemetry.count("mesh.probe_retrip")


class MeshVerifier:
    """`batch_verify_sharded` wrapped in the recovery ladder (module
    docstring).  `dispatch_fn(tasks, rng, device_ids)` is injectable so
    the tier-1 state-machine tests run without compiling mesh
    executables; the default is the real sharded entry point."""

    def __init__(self, n_devices: int | None = None,
                 readmit_cooldown_s: float = 1.0, clock=time.monotonic,
                 dispatch_fn=None, available_fn=None, result_cast=bool):
        self._requested = n_devices
        self._clock = clock
        self._cooldown = float(readmit_cooldown_s)
        self._dispatch_fn = dispatch_fn
        self._available_fn = available_fn
        # what a settled verdict is coerced to: bool for the RLC verify
        # path (the default), identity (None) for payload dispatchers
        # whose result is structured — e.g. the sharded epoch step's
        # (balances, eff, roots) tuple via `sharded_epoch_verifier`
        self._result_cast = result_cast if result_cast is not None \
            else (lambda out: out)
        self._state: MeshState | None = None
        self.redispatches = 0
        self.verified_statements = 0
        self.lost_statements = 0
        self.max_degraded_lanes = 0
        self.recovery_latencies: list[float] = []

    # --- lazies (no jax before the first verify) -----------------------------

    def _available(self) -> int:
        if self._available_fn is not None:
            return int(self._available_fn())
        from ..parallel.partition import available_devices

        return available_devices()

    @property
    def state(self) -> MeshState:
        if self._state is None:
            n = self._requested or self._available()
            self._state = MeshState(min(n, self._available()),
                                    readmit_cooldown_s=self._cooldown,
                                    clock=self._clock)
        return self._state

    def _dispatch(self, tasks, rng, device_ids):
        if self._dispatch_fn is not None:
            return self._dispatch_fn(tasks, rng, device_ids)
        from ..ops import bls_batch

        return bls_batch.batch_verify_sharded_async(
            tasks, rng=rng, device_ids=device_ids)

    # --- the recovery ladder -------------------------------------------------

    def verify_async(self, tasks, rng=None):
        """Dispatch over the current (possibly shrunken) mesh and return
        a `DeviceFuture` whose settle runs the recovery ladder: device
        failures re-bucket the SAME statements over the survivors until
        a verdict lands or no device remains.  A due re-admission
        cooldown turns this dispatch into the full-mesh probe."""
        from ..serve.futures import DeviceFuture, FutureTimeout

        state = self.state
        probing = state.probe_due()
        ids = (tuple(range(state.n_devices)) if probing
               else state.surviving())
        if not ids:
            # every device is lost and the re-admission cooldown has
            # not elapsed: these statements are dropped, and that must
            # be COUNTED (the mesh-lost-statements gate) and surfaced
            # as the typed device failure, not a dispatch-layer assert
            self.lost_statements += len(tasks)
            telemetry.count("mesh.lost_statements", len(tasks))
            return DeviceFuture.failed(MeshDeviceLost(
                "dispatch", "mesh-exhausted", "device_loss"))
        attempt = {"fut": None, "ids": ids, "probing": probing,
                   "t_fail0": None}
        try:
            attempt["fut"] = self._dispatch(tasks, rng, ids)
        except Exception as exc:
            if not is_device_failure(exc):
                return DeviceFuture.failed(exc)
            self._on_device_failure(attempt, exc)

        def settle(fut, timeout=None):
            # bounded-wait contract: the remaining budget is threaded
            # into each inner settle; an exhausted budget returns with
            # `fut` still pending (the future raises the typed
            # FutureTimeout, the attempt state survives for a retry)
            deadline = (None if timeout is None
                        else time.perf_counter() + float(timeout))
            while True:
                if attempt["fut"] is None:      # re-dispatch after a loss
                    ids2 = attempt["ids"]
                    if not ids2:
                        self.lost_statements += len(tasks)
                        telemetry.count("mesh.lost_statements",
                                        len(tasks))
                        fut.set_exception(attempt["exc"])
                        return
                    self.redispatches += 1
                    telemetry.count("mesh.redispatch")
                    try:
                        attempt["fut"] = self._dispatch(tasks, rng, ids2)
                    except Exception as exc:
                        if not is_device_failure(exc):
                            fut.set_exception(exc)
                            return
                        self._on_device_failure(attempt, exc)
                        continue
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        return              # budget gone, still pending
                try:
                    ok = attempt["fut"].result(timeout=remaining)
                except FutureTimeout:
                    # inner wait ran out — re-loop so an early inner
                    # timeout still consumes the caller's full budget
                    # (the outer future then raises the typed
                    # FutureTimeout, still pending; retry is legal)
                    continue
                except Exception as exc:
                    if not is_device_failure(exc):
                        fut.set_exception(exc)
                        return
                    attempt["fut"] = None
                    self._on_device_failure(attempt, exc)
                    continue
                self._on_success(attempt, len(tasks))
                fut.set_result(self._result_cast(ok))
                return

        return DeviceFuture(waiter=settle)

    def verify(self, tasks, rng=None) -> bool:
        """Synchronous facade over `verify_async`."""
        return self.verify_async(tasks, rng=rng).result()

    def dispatch(self, payload):
        """Payload-shaped facade over the same recovery ladder for
        non-RLC dispatchers (the sharded epoch step): `payload` is
        whatever the injected `dispatch_fn` consumes, and the settled
        value passes through `result_cast` (identity for structured
        results).  Statement accounting counts payload items."""
        return self.verify_async(payload, rng=None).result()

    def _on_device_failure(self, attempt: dict, exc: BaseException) -> None:
        state = self.state
        now = self._clock()
        if attempt["t_fail0"] is None:
            attempt["t_fail0"] = now
        if attempt["probing"]:
            state.record_probe(False)
            attempt["probing"] = False
        else:
            state.mark_lost()
        self.max_degraded_lanes = max(self.max_degraded_lanes,
                                      len(state.lost))
        attempt["ids"] = state.surviving()
        attempt["fut"] = None
        attempt["exc"] = exc

    def _on_success(self, attempt: dict, n_tasks: int) -> None:
        state = self.state
        if attempt["probing"]:
            state.record_probe(True)
        if attempt["t_fail0"] is not None:
            dt = self._clock() - attempt["t_fail0"]
            self.recovery_latencies.append(dt)
            telemetry.observe("mesh.recovery_latency_s", dt)
            # cost seam presence for the recovery arc: the re-dispatch
            # lands on a fresh (n_devices, per_shard) executable, so a
            # CST_COSTMODEL round should see the post-loss memory state
            from ..telemetry import costmodel

            costmodel.sample_watermark("mesh.recovered")
        self.verified_statements += n_tasks

    # --- accounting (the "mesh" resilience sub-block) ------------------------

    def block(self) -> dict:
        """JSON-able `"mesh"` sub-object for the resilience bench block
        (mined by `telemetry.history.mesh_records`).  `recovered` is
        the 0/1 gate surface: every observed loss produced a recovered
        verdict and nothing was dropped — emitted as its own record so
        an UNRECOVERED round FAILs the `mesh-recovered` threshold row
        instead of leaving the previous round's latency PASS standing
        (the recovery-latency record carries value null then, which a
        numeric threshold cannot see)."""
        state = self.state
        last = (self.recovery_latencies[-1]
                if self.recovery_latencies else None)
        recovered = (self.lost_statements == 0
                     and (state.lost_events == 0
                          or len(self.recovery_latencies) >= 1))
        return {
            "recovered": recovered,
            "devices": state.n_devices,
            "degraded_lanes": len(state.lost),
            "max_degraded_lanes": self.max_degraded_lanes,
            "device_lost_events": state.lost_events,
            "readmissions": state.readmissions,
            "retrips": state.retrips,
            "redispatches": self.redispatches,
            "recoveries": len(self.recovery_latencies),
            "recovery_latency_s": (round(last, 6)
                                   if last is not None else None),
            "verified_statements": self.verified_statements,
            "lost_statements": self.lost_statements,
        }


def sharded_epoch_verifier(params, n_devices: int | None = None,
                           axis: str = "data", **kw) -> MeshVerifier:
    """`MeshVerifier` over the partition-registry sharded epoch step:
    the `device_ids`-subset fallback covers the flagship step, not just
    the RLC batch.  `verify_async`/`dispatch` takes the epoch-step
    payload `(reg, sc, length, pubkey_root, credentials)` (host/global
    arrays) and settles to the host `(new_bal, new_eff, balances_root,
    registry_root)` tuple; a lost device re-shards the SAME state over
    the surviving `mesh_rung` power-of-two subset
    (`parallel.partition.epoch_step_dispatcher`)."""
    from ..parallel.partition import epoch_step_dispatcher

    return MeshVerifier(n_devices=n_devices,
                        dispatch_fn=epoch_step_dispatcher(params,
                                                          axis=axis),
                        result_cast=None, **kw)
