"""Resilience layer — deterministic fault injection, recovery policies,
self-healing Merkle state.

The serving stack (PR 6) poisons the handles of a failed batch and the
incremental flagship (PR 7) asserts Merkle parity — but nothing HEALS:
one failed compile, a poisoned-batch storm, or a silently diverged
`MerkleForest` has no recovery story.  Production committee-consensus
measurement work (arXiv:2302.00418) and censorship-resilient
million-scale aggregation (Wonderboom, arXiv:2602.06655) both treat
verification as a service that must keep answering *correctly* under
partial failure; this package gives the repo that property and the
machinery to prove it:

    faults.py    deterministic, seeded, schema-validated fault injection
                 at the four sanctioned seams (`ops.bls_batch._dispatch`,
                 `serve.futures.DeviceFuture` settle,
                 `ServeExecutor._dispatch_one`, `incremental.update_dirty`)
                 — dispatch exceptions, injected latency, compile failure
                 on first call, corrupted device output (bit-flip/NaN),
                 mesh-device loss.  OFF by default; the disabled path is
                 one module-global read (no-op bound pinned by
                 tests/test_resilience.py, the telemetry pattern).
    policies.py  per-kernel retry with capped exponential backoff, a
                 per-(kernel, rung) circuit breaker that trips to the
                 pure-Python oracle fallback (correct-but-slow degraded
                 mode, half-open probes to re-close), and typed
                 `DeadlineExceeded` request shedding.
    healing.py   divergence detector + quarantine/recovery for a
                 `parallel.incremental.MerkleForest` (recovery latency
                 recorded); recovery routes through checkpoint restore
                 when a valid snapshot exists, else a full rebuild —
                 the taken path rides the `heal` block.
    mesh.py      per-shard recovery for `batch_verify_sharded`: a lost
                 mesh device (`MeshDeviceLost` or a real
                 XlaRuntimeError) re-buckets the SAME statements over
                 the surviving devices (degraded n-1 mode), with a
                 half-open re-admission probe once the device answers
                 again — zero wrong or dropped statements.
    checkpoint.py versioned, checksummed host-side snapshots of
                 `MerkleForest` layer stacks plus a leaf-delta journal
                 appended at the `update_dirty` seam; restore = load
                 snapshot + replay journal instead of the O(N)
                 re-merkleize (`CST_CHECKPOINT_DIR` /
                 `CST_CHECKPOINT_EVERY`).
    chaos.py     the chaos-round harness (`CST_SERVE_CHAOS=1`): mainnet
                 arrival mix under an active fault plan, requiring the
                 service to return to steady state — emits the
                 `resilience` benchwatch record kind the `chaos-recovery`
                 threshold row gates on.

Import discipline: `faults`, `policies`, `mesh` and `checkpoint` are
stdlib-only at import (+ telemetry, itself stdlib-only) so the hot-path
seams can import them eagerly without touching numpy/jax; `healing` and
`chaos` import the heavy modules lazily, at call time.
"""

from . import checkpoint, faults, mesh
from .checkpoint import CheckpointCorrupt, CheckpointManager
from .faults import FaultInjected, FaultPlan, MeshDeviceLost
from .mesh import MeshState, MeshVerifier
from .policies import (
    BreakerRegistry,
    CircuitBreaker,
    DeadlineExceeded,
    RetryPolicy,
)

__all__ = [
    "BreakerRegistry", "CheckpointCorrupt", "CheckpointManager",
    "CircuitBreaker", "DeadlineExceeded", "FaultInjected", "FaultPlan",
    "MeshDeviceLost", "MeshState", "MeshVerifier", "RetryPolicy",
    "checkpoint", "faults", "mesh",
]
