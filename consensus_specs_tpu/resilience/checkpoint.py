"""MerkleForest checkpoint/restore — snapshots + a leaf-delta journal.

A process restart loses every device-resident `MerkleForest` layer
stack, forcing an O(N) re-merkleize of the million-validator trees the
flagship depends on.  This module makes the forest durable the way
training stacks make optimizer state durable for elastic restart:

    snapshot    `CheckpointManager.snapshot(forest)` persists EVERY
                interior layer host-side — versioned (`FORMAT`,
                monotone `seq`) and checksummed (one sha256 over the
                concatenated layer bytes, stored in the manifest).
                Writes are tmp-file + `os.replace`, so a crash
                mid-snapshot leaves the previous checkpoint intact.
    journal     a leaf-delta journal appended at the `update_dirty`
                seam (`MerkleForest.update` calls `on_update` when a
                manager is attached): one JSON line per update —
                live dirty indices + leaf chunk words (base64), the
                list length, and a per-line sha256.  Snapshots
                truncate it (baked-in deltas).
    restore     load snapshot (checksum-verified) -> rebuild the layer
                stack with ZERO hashing (`MerkleForest.from_layers` is
                device puts only) -> replay the journal's dirty
                updates (O(journal · log N) hash lanes).  At <=1%
                journal depth this beats the full O(N) re-merkleize
                >=5x — the `checkpoint-restore` benchwatch threshold
                row, measured by the chaos checkpoint segment.

Corruption policy: any checksum / format / truncation problem raises
the typed `CheckpointCorrupt`; `restore_or_none` maps it (and I/O
errors) to None so callers — `healing.heal_forest` above all — FALL
BACK TO A FULL REBUILD instead of serving from a damaged checkpoint.

Concurrency contract: journal appends and snapshots serialize on one
re-entrant lock; `restore()` reads a consistent journal prefix under
that lock and replays it outside — an update arriving mid-restore is
safe (never corrupts the files) and lands in the journal for the NEXT
restore.  Pinned by tests/test_checkpoint.py.

Knobs: `CST_CHECKPOINT_DIR` (arming: a directory makes
`manager_from_env` return a live manager), `CST_CHECKPOINT_EVERY`
(auto-snapshot after that many journaled updates; 0 disables
auto-snapshots).  See README "Mesh resilience & checkpointing" and
tests/formats/README.md for the file format.

numpy loads lazily inside the methods (importing the resilience
package must stay stdlib-only); jax enters only through
`MerkleForest.from_layers` at restore time.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import threading
import time
import zipfile
from pathlib import Path

from .. import telemetry
from ..telemetry import flightrec

FORMAT = 1


class CheckpointCorrupt(RuntimeError):
    """A snapshot or journal line failed its checksum/format check.
    Typed so restore callers can fall back to a full rebuild instead of
    serving from damaged state."""


def env_dir() -> str | None:
    """The CST_CHECKPOINT_DIR knob (None == checkpointing disarmed)."""
    return os.environ.get("CST_CHECKPOINT_DIR") or None


def env_every(default: int = 64) -> int:
    """The CST_CHECKPOINT_EVERY knob: auto-snapshot cadence in journaled
    updates (0 disables auto-snapshots)."""
    try:
        return int(os.environ.get("CST_CHECKPOINT_EVERY", default))
    except ValueError:
        return default


def manager_from_env(name: str = "forest") -> "CheckpointManager | None":
    """A live manager when CST_CHECKPOINT_DIR is set, else None — the
    one arming read call sites guard with."""
    d = env_dir()
    if not d:
        return None
    return CheckpointManager(d, name=name, every=env_every())


def _line_digest(idx_bytes: bytes, leaf_bytes: bytes, length: int) -> str:
    h = hashlib.sha256()
    h.update(idx_bytes)
    h.update(leaf_bytes)
    h.update(str(int(length)).encode())
    return h.hexdigest()


class CheckpointManager:
    """One forest's checkpoint state under `directory` (see module
    docstring).  `every=None/0` disables auto-snapshots; `name` keys
    the three files so several forests can share a directory."""

    def __init__(self, directory, name: str = "forest",
                 every: int | None = None):
        self.dir = Path(directory)
        self.name = name
        self.every = int(every) if every else 0
        self._lock = threading.RLock()
        self.journal_entries = 0
        self.journal_chunks = 0
        self.snapshot_bytes = 0
        self.last_error: BaseException | None = None
        self._updates_since_snapshot = 0
        self._seq = self._existing_seq()

    # --- paths ---------------------------------------------------------------

    @property
    def manifest_path(self) -> Path:
        return self.dir / f"{self.name}.manifest.json"

    @property
    def layers_path(self) -> Path:
        return self.dir / f"{self.name}.layers.npz"

    @property
    def journal_path(self) -> Path:
        return self.dir / f"{self.name}.journal.jsonl"

    def _existing_seq(self) -> int:
        try:
            manifest = json.loads(self.manifest_path.read_text())
            return int(manifest.get("seq", 0))
        except (OSError, json.JSONDecodeError, ValueError, TypeError):
            return 0

    # --- snapshot ------------------------------------------------------------

    def snapshot(self, forest) -> Path:
        """Persist the forest's full layer stack (versioned, checksummed,
        atomic) and truncate the journal.  Returns the manifest path."""
        import numpy as np

        with self._lock, telemetry.span("resilience.checkpoint.snapshot",
                                        chunks=forest.n_chunks):
            self.dir.mkdir(parents=True, exist_ok=True)
            host_layers = [np.asarray(lay, dtype=np.uint32)
                           for lay in forest.layers]
            digest = hashlib.sha256()
            for lay in host_layers:
                digest.update(lay.tobytes())
            tmp = self.layers_path.with_name(self.layers_path.name + ".tmp")
            with open(tmp, "wb") as f:
                np.savez(f, **{f"layer_{i}": lay
                               for i, lay in enumerate(host_layers)})
            os.replace(tmp, self.layers_path)
            seq = self._seq + 1
            manifest = {
                "format": FORMAT,
                "seq": seq,
                "n_chunks": int(forest.n_chunks),
                "data_depth": int(forest.data_depth),
                "limit_depth": int(forest.limit_depth),
                "length": int(forest.length),
                "sha256": digest.hexdigest(),
                "layers_file": self.layers_path.name,
                "created_at": round(time.time(), 3),
            }
            mtmp = self.manifest_path.with_name(
                self.manifest_path.name + ".tmp")
            mtmp.write_text(json.dumps(manifest, sort_keys=True))
            os.replace(mtmp, self.manifest_path)
            # journal entries predate this snapshot: baked in, truncate
            # — and the counters mean PENDING (replayable) depth, so
            # they reset with the file (journal_depth_frac must report
            # what a restore would replay, not lifetime totals)
            with open(self.journal_path, "w"):
                pass
            self.journal_entries = 0
            self.journal_chunks = 0
            self._seq = seq
            self._updates_since_snapshot = 0
            self.snapshot_bytes = self.layers_path.stat().st_size
            telemetry.count("checkpoint.snapshots")
            flightrec.record("checkpoint_snapshot", seq=seq,
                             n_chunks=int(forest.n_chunks),
                             bytes=int(self.snapshot_bytes))
        return self.manifest_path

    # --- journal (the update_dirty seam's hook) ------------------------------

    def on_update(self, forest, dirty_idx, new_leaf_words) -> None:
        """Journal one dirty-set update (live rows only — sentinel-pad
        rows beyond the forest's capacity are dropped).  Called by
        `MerkleForest.update` while a manager is attached; materializes
        the leaf words host-side (the one sync checkpointing costs —
        opt-in by construction)."""
        import numpy as np

        idx = np.asarray(dirty_idx, dtype=np.uint32)
        leaves = np.asarray(new_leaf_words, dtype=np.uint32)
        m = min(idx.shape[0], leaves.shape[0])
        idx, leaves = idx[:m], leaves[:m]
        live = idx < forest.capacity
        idx, leaves = idx[live], leaves[live]
        if idx.shape[0] == 0:
            return
        with self._lock:
            if self.every and self._updates_since_snapshot >= self.every:
                # pre-update snapshot, so this delta lands in the fresh
                # journal and replay stays exact
                self.snapshot(forest)
            idx_b, leaf_b = idx.tobytes(), leaves.tobytes()
            entry = {
                "seq": self._seq,
                "n": int(idx.shape[0]),
                "idx": base64.b64encode(idx_b).decode(),
                "leaves": base64.b64encode(leaf_b).decode(),
                "length": int(forest.length),
                "sha256": _line_digest(idx_b, leaf_b, forest.length),
            }
            self.dir.mkdir(parents=True, exist_ok=True)
            with open(self.journal_path, "a") as f:
                f.write(json.dumps(entry, sort_keys=True) + "\n")
            self.journal_entries += 1
            self.journal_chunks += int(idx.shape[0])
            self._updates_since_snapshot += 1
            telemetry.count("checkpoint.journal_appends")

    def journal_depth_frac(self, n_chunks: int) -> float:
        """Journaled chunk rows as a fraction of the tree width — the
        <=1% regime the restore-speedup threshold is stated at."""
        return self.journal_chunks / max(1, int(n_chunks))

    # --- restore -------------------------------------------------------------

    def _read_journal_lines(self) -> list[str]:
        with self._lock:
            try:
                return self.journal_path.read_text().splitlines()
            except OSError:
                return []

    def restore(self):
        """Snapshot + journal replay -> a fresh `MerkleForest` (no full
        re-merkleize: layer puts + O(journal · log N) dirty re-hash).
        Raises `CheckpointCorrupt` on any checksum/format problem and
        `FileNotFoundError` when no snapshot exists."""
        import numpy as np

        from ..parallel.incremental import MerkleForest

        with telemetry.span("resilience.checkpoint.restore"):
            # manifest + layers + journal are read as ONE locked unit:
            # a concurrent snapshot() (same lock) rewrites all three,
            # and unsynchronized reads could checksum seq-N+1 layer
            # bytes against the seq-N manifest — a spurious corrupt
            # verdict that would force an unnecessary O(N) rebuild.
            # The replay itself (device work) runs outside the lock.
            with self._lock:
                try:
                    manifest = json.loads(self.manifest_path.read_text())
                except json.JSONDecodeError as exc:
                    raise CheckpointCorrupt(
                        f"unreadable manifest: {exc}") from exc
                if not isinstance(manifest, dict) \
                        or manifest.get("format") != FORMAT:
                    raise CheckpointCorrupt(
                        f"manifest format {manifest.get('format')!r} != "
                        f"{FORMAT}")
                depth = int(manifest["data_depth"])
                digest = hashlib.sha256()
                try:
                    with np.load(self.layers_path) as z:
                        layers = [np.asarray(z[f"layer_{i}"],
                                             dtype=np.uint32)
                                  for i in range(depth + 1)]
                except (OSError, KeyError, ValueError, EOFError,
                        zipfile.BadZipFile) as exc:
                    # a damaged npz surfaces as BadZipFile/EOFError
                    # before the sha256 even runs — same corrupt verdict
                    raise CheckpointCorrupt(
                        f"unreadable layer archive: {exc}") from exc
                for lay in layers:
                    digest.update(lay.tobytes())
                if digest.hexdigest() != manifest.get("sha256"):
                    raise CheckpointCorrupt(
                        "layer-stack checksum mismatch — snapshot is "
                        "corrupt, fall back to a full rebuild")
                lines = self._read_journal_lines()
            forest = MerkleForest.from_layers(
                layers, manifest["limit_depth"], manifest["length"],
                manifest["n_chunks"])
            replayed = self._replay(forest, lines, int(manifest["seq"]))
            telemetry.count("checkpoint.restores")
            flightrec.record("checkpoint_restore",
                             seq=int(manifest["seq"]),
                             replayed_entries=int(replayed))
            forest.restored_journal_entries = replayed
            return forest

    def _replay(self, forest, lines: list[str], seq: int) -> int:
        import numpy as np

        replayed = 0
        for i, line in enumerate(lines, 1):
            line = line.strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
            except json.JSONDecodeError as exc:
                raise CheckpointCorrupt(
                    f"journal line {i} is not JSON") from exc
            if not isinstance(entry, dict):
                raise CheckpointCorrupt(f"journal line {i}: not a dict")
            if entry.get("seq") != seq:
                continue            # stale: predates the loaded snapshot
            try:
                idx_b = base64.b64decode(entry["idx"])
                leaf_b = base64.b64decode(entry["leaves"])
                length = int(entry["length"])
            except (KeyError, ValueError, TypeError) as exc:
                raise CheckpointCorrupt(
                    f"journal line {i}: malformed fields") from exc
            if _line_digest(idx_b, leaf_b, length) != entry.get("sha256"):
                raise CheckpointCorrupt(
                    f"journal line {i}: checksum mismatch")
            idx = np.frombuffer(idx_b, dtype=np.uint32)
            leaves = np.frombuffer(leaf_b,
                                   dtype=np.uint32).reshape(-1, 8)
            if leaves.shape[0] != idx.shape[0]:
                raise CheckpointCorrupt(
                    f"journal line {i}: {idx.shape[0]} indices vs "
                    f"{leaves.shape[0]} leaf rows")
            forest.length = length
            forest.update(idx, leaves)
            replayed += 1
        return replayed

    def restore_or_none(self):
        """`restore()`, with the fallback contract folded in: a missing,
        corrupt, or unreadable checkpoint returns None (and records the
        reason in `last_error`) so the caller rebuilds instead."""
        try:
            return self.restore()
        except (CheckpointCorrupt, OSError, ValueError, KeyError,
                TypeError) as exc:
            self.last_error = exc
            telemetry.count("checkpoint.restore_rejected")
            return None

    # --- accounting ----------------------------------------------------------

    def describe(self) -> dict:
        """Compact JSON-able summary (rides the resilience block)."""
        return {
            "dir": str(self.dir),
            "seq": self._seq,
            "journal_entries": self.journal_entries,
            "journal_chunks": self.journal_chunks,
            "snapshot_bytes": self.snapshot_bytes,
        }
