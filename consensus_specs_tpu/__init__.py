"""consensus_specs_tpu — a TPU-native executable-specification framework for
the Ethereum proof-of-stake consensus layer.

Re-designed from scratch for TPU (JAX/XLA/Pallas) with the same capabilities
as the reference executable-spec system (ethereum/consensus-specs):

- ``utils/``     SSZ engine (chunk-array merkleization), hashing, YAML/snappy IO
- ``ops/``       compute kernels: batched SHA-256 (numpy + JAX/TPU), BLS12-381
                 (pure-Python oracle + batched JAX limb arithmetic), KZG, FFT
- ``models/``    the fork specs (phase0 .. fulu) + the spec build pipeline that
                 assembles flat per-(fork, preset) executable spec namespaces
- ``parallel/``  jax.sharding mesh layouts and collective sweeps for the
                 validator-registry and attestation-batch scale axes

Layer map mirrors SURVEY.md §1: L0 = utils+ops, L2 = models/builder,
L3 = built spec namespaces, L4 = tests/ DSL, L5 = generator stack.
"""

__version__ = "1.6.0a3+tpu0"
