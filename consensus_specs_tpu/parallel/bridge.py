"""Host↔device bridge: spec `BeaconState` ⇄ struct-of-arrays registry.

The executable spec stays Python/SSZ (exact integer semantics, data-dependent
validity asserts); the per-validator epoch sweep and the registry-scale
merkleization dispatch to the device kernels.  This module does the
committee-expansion of PendingAttestations into per-validator participation
flags (the only O(attestations·committee) host loop, once per epoch) and the
array extraction.
"""

from __future__ import annotations

import numpy as np

from .epoch import EpochScalars, RegistryArrays


def participation_from_pending(spec, state):
    """Expand previous-epoch PendingAttestations into per-validator
    source/target/head flags + min inclusion delay + its proposer.

    Mirrors `get_unslashed_attesting_indices` / `get_inclusion_delay_deltas`
    matching rules (specs/phase0/beacon-chain.md epoch processing)."""
    n = len(state.validators)
    is_source = np.zeros(n, dtype=bool)
    is_target = np.zeros(n, dtype=bool)
    is_head = np.zeros(n, dtype=bool)
    inclusion_delay = np.full(n, np.iinfo(np.uint64).max, dtype=np.uint64)
    proposer = np.zeros(n, dtype=np.int32)

    prev = spec.get_previous_epoch(state)
    atts = spec.get_matching_source_attestations(state, prev)
    target_root = spec.get_block_root(state, prev)
    for a in atts:
        indices = list(spec.get_attesting_indices(state, a))
        matching_target = a.data.target.root == target_root
        matching_head = (
            matching_target
            and a.data.beacon_block_root
            == spec.get_block_root_at_slot(state, a.data.slot))
        for i in indices:
            i = int(i)
            is_source[i] = True
            if matching_target:
                is_target[i] = True
            if matching_head:
                is_head[i] = True
            if int(a.inclusion_delay) < int(inclusion_delay[i]):
                inclusion_delay[i] = int(a.inclusion_delay)
                proposer[i] = int(a.proposer_index)
    inclusion_delay[~is_source] = 1
    return is_source, is_target, is_head, inclusion_delay, proposer


def registry_arrays_from_state(spec, state) -> tuple[RegistryArrays,
                                                     EpochScalars]:
    """Extract the sweep inputs from a (pre-epoch-processing) BeaconState."""
    n = len(state.validators)
    balance = np.fromiter((int(b) for b in state.balances), np.uint64, n)
    eff = np.fromiter((int(v.effective_balance) for v in state.validators),
                      np.uint64, n)
    slashed = np.fromiter((bool(v.slashed) for v in state.validators),
                          bool, n)
    act_el = np.fromiter(
        (int(v.activation_eligibility_epoch) for v in state.validators),
        np.uint64, n)
    act = np.fromiter((int(v.activation_epoch) for v in state.validators),
                      np.uint64, n)
    exit_e = np.fromiter((int(v.exit_epoch) for v in state.validators),
                         np.uint64, n)
    wd = np.fromiter((int(v.withdrawable_epoch) for v in state.validators),
                     np.uint64, n)
    src, tgt, head, delay, prop = participation_from_pending(spec, state)

    reg = RegistryArrays(
        balance=balance, effective_balance=eff, slashed=slashed,
        activation_eligibility_epoch=act_el,
        activation_epoch=act, exit_epoch=exit_e, withdrawable_epoch=wd,
        is_source=src, is_target=tgt, is_head=head,
        inclusion_delay=delay, proposer_index=prop)

    cur = int(spec.get_current_epoch(state))
    prev = int(spec.get_previous_epoch(state))
    sc = EpochScalars(
        current_epoch=np.uint64(cur),
        finality_delay=np.uint64(prev - int(state.finalized_checkpoint.epoch)),
        slashings_sum=np.uint64(sum(int(s) for s in state.slashings)))
    return reg, sc


def pad_pow2(arr: np.ndarray, multiple_of: int = 1) -> np.ndarray:
    """Pad (N, ...) to the next power-of-two length that is also a multiple
    of `multiple_of` (shard count; must itself be a power of two), with
    zeros."""
    assert multiple_of & (multiple_of - 1) == 0, \
        "shard count must be a power of two"
    n = arr.shape[0]
    target = 1
    while target < max(n, multiple_of):
        target *= 2
    if target == n:
        return arr
    pad = np.zeros((target - n,) + arr.shape[1:], dtype=arr.dtype)
    return np.concatenate([arr, pad])


def validator_static_leaf_words(spec, state):
    """Precompute the static per-validator leaves (pubkey root, withdrawal
    credentials) as (N, 8) big-endian uint32 words for the registry tree."""
    from ..ops.sha256_np import chunks_to_words, sha256_64B_words

    n = len(state.validators)
    pk_bytes = np.zeros((n, 64), dtype=np.uint8)
    cred_bytes = np.zeros((n, 32), dtype=np.uint8)
    for i, v in enumerate(state.validators):
        pk_bytes[i, :48] = np.frombuffer(bytes(v.pubkey), dtype=np.uint8)
        cred_bytes[i] = np.frombuffer(
            bytes(v.withdrawal_credentials), dtype=np.uint8)
    pk_words = chunks_to_words(pk_bytes.reshape(-1, 32)).reshape(n, 16)
    pubkey_root = sha256_64B_words(pk_words)
    cred = chunks_to_words(cred_bytes)
    return pubkey_root, cred
