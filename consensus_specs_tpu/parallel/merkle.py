"""Device-side SSZ merkleization of the registry-scale lists.

The reference amortizes `hash_tree_root(state)` with remerkleable's cached
pointer-tree (`eth2spec/utils/ssz/ssz_impl.py:25`).  The TPU redesign keeps
the big lists (balances, validators) as flat arrays and re-hashes them as a
batched tree reduction on device — at 1M validators the whole balances tree
is ~19 SHA-256 levels of perfectly regular (N, 16)-word batches, exactly the
shape `ops.sha256_jax` wants.

Sharded form: each device reduces its local contiguous sub-tree, the (tiny)
per-device roots are `all_gather`ed over the mesh axis and folded on every
device (replicated), then the zero-subtree ladder up to the SSZ limit depth
and the length mix-in finish the root.  Collectives ride the ICI: one
all_gather of n_dev×32 bytes per list.

Parity oracle: `utils.ssz.ssz_impl.hash_tree_root` on the spec containers
(`tests/test_parallel_merkle.py`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import telemetry
from ..ops.sha256_jax import hash_pairs, sha256_64B_words
from ..ops.sha256_np import ZERO_HASH_WORDS

# uint64 packing needs x64; entry points enable it (see parallel.require_x64)

# plain numpy at module level (jnp closes over it at trace time):
# import-time jnp arrays leak tracers if this module's first import
# happens inside a jit trace — the device-const-at-import rule
_ZEROS = np.stack(ZERO_HASH_WORDS[:64])  # (64, 8) uint32


def _bswap32(x):
    x = x.astype(jnp.uint32)
    return ((x & jnp.uint32(0xFF)) << 24) | ((x & jnp.uint32(0xFF00)) << 8) \
        | ((x >> 8) & jnp.uint32(0xFF00)) | (x >> 24)


def pack_u64_chunks(values):
    """(N,) uint64 -> (ceil(N/4), 8) big-endian uint32 chunk words with SSZ
    little-endian byte layout (4 uint64 per 32-byte chunk)."""
    n = values.shape[0]
    pad = (-n) % 4
    if pad:
        values = jnp.concatenate([values, jnp.zeros((pad,), dtype=jnp.uint64)])
    v = values.reshape(-1, 4)
    lo = _bswap32(v & jnp.uint64(0xFFFFFFFF))
    hi = _bswap32(v >> jnp.uint64(32))
    return jnp.stack([lo[:, 0], hi[:, 0], lo[:, 1], hi[:, 1],
                      lo[:, 2], hi[:, 2], lo[:, 3], hi[:, 3]], axis=-1)


def u64_leaf_words(values):
    """(N,) uint64 -> (N, 8) chunk words: each value alone in a 32B chunk
    (an SSZ uint64 field leaf)."""
    lo = _bswap32(values & jnp.uint64(0xFFFFFFFF))
    hi = _bswap32(values >> jnp.uint64(32))
    z = jnp.zeros_like(lo)
    return jnp.stack([lo, hi, z, z, z, z, z, z], axis=-1)


def subtree_root(words, depth: int):
    """Root of the 2**depth-leaf subtree containing `words` (N, 8), with the
    tail padded by zero-subtree hashes.  N must be a power of two <= 2**depth
    (pad on host); levels above the data fold against the zero ladder."""
    n = words.shape[0]
    assert n & (n - 1) == 0 and n >= 1
    data_depth = n.bit_length() - 1
    level = words
    for _ in range(data_depth):
        level = hash_pairs(level)
    root = level[0]
    for d in range(data_depth, depth):
        blk = jnp.concatenate([root, _ZEROS[d]])
        root = sha256_64B_words(blk[None, :])[0]
    return root


def mix_in_length(root_words, length):
    """H(root || le64(length) || zeros) — SSZ list length mix-in."""
    lo = _bswap32(length.astype(jnp.uint64) & jnp.uint64(0xFFFFFFFF))
    hi = _bswap32(length.astype(jnp.uint64) >> jnp.uint64(32))
    z = jnp.zeros((), dtype=jnp.uint32)
    tail = jnp.stack([lo, hi, z, z, z, z, z, z])
    blk = jnp.concatenate([root_words, tail])
    return sha256_64B_words(blk[None, :])[0]


def balances_list_root(balances, length, limit_depth: int = 38,
                       axis_name: str | None = None):
    """hash_tree_root of `List[uint64, 2**40]` (SSZ packed, limit 2**40
    values -> 2**38 chunks).  `balances` is the (padded, pow2) local shard;
    `length` the true global element count."""
    if axis_name is not None:
        # shard boundaries must be 32-byte-chunk-aligned, or pack_u64_chunks
        # would zero-pad mid-stream and silently corrupt the root
        assert balances.shape[0] % 4 == 0, (
            f"sharded balances_list_root needs a chunk-aligned shard "
            f"(multiple of 4 uint64), got {balances.shape[0]}")
    with telemetry.span("parallel.balances_list_root.trace",
                        n=int(balances.shape[0])), \
            jax.named_scope("cst.balances_list_root"):
        chunks = pack_u64_chunks(balances)
        if axis_name is None:
            root = subtree_root(chunks, limit_depth)
        else:
            root = _sharded_list_root(chunks, limit_depth, axis_name)
        return mix_in_length(root, length)


def _sharded_list_root(local_chunks, limit_depth: int, axis_name: str):
    """Each shard holds a contiguous power-of-two run of data chunks: reduce
    it to its local root, all_gather the shard roots, finish the data tree,
    THEN fold the zero-subtree ladder (padding sits above the whole data
    tree, not inside each shard)."""
    n_local = local_chunks.shape[0]
    assert n_local & (n_local - 1) == 0
    local_depth = n_local.bit_length() - 1
    local = subtree_root(local_chunks, local_depth)
    roots = lax.all_gather(local, axis_name)  # (n_dev, 8) on every device
    n_dev = roots.shape[0]
    assert n_dev & (n_dev - 1) == 0, (
        f"sharded list root needs a power-of-two mesh, got {n_dev} devices")
    shard_depth = (n_dev - 1).bit_length()
    level = roots
    for _ in range(shard_depth):
        level = hash_pairs(level)
    root = level[0]
    for d in range(local_depth + shard_depth, limit_depth):
        blk = jnp.concatenate([root, _ZEROS[d]])
        root = sha256_64B_words(blk[None, :])[0]
    return root


class ValidatorLeaves:
    """Precomputed per-validator leaf words for the registry tree.

    A `Validator` container has 8 field leaves
    (`specs/phase0/beacon-chain.md` `Validator`): [pubkey_root,
    withdrawal_credentials, effective_balance, slashed, act_eligibility,
    activation, exit, withdrawable].  pubkey_root and credentials are static
    per validator (change only on deposit) and are precomputed host-side;
    the dynamic uint64/bool fields come straight from the sweep arrays.
    """

    def __init__(self, pubkey_root_words, credentials_words):
        self.pubkey_root = jnp.asarray(pubkey_root_words)    # (N, 8) uint32
        self.credentials = jnp.asarray(credentials_words)    # (N, 8) uint32


def validator_records_root(leaves: ValidatorLeaves, effective_balance,
                           slashed, activation_eligibility_epoch,
                           activation_epoch, exit_epoch, withdrawable_epoch):
    """(N,) arrays -> (N, 8) root words of each Validator container (a full
    depth-3 reduction over the 8 field leaves, batched over validators)."""
    with telemetry.span("parallel.validator_records_root.trace",
                        n=int(effective_balance.shape[0])), \
            jax.named_scope("cst.validator_records_root"):
        f = [leaves.pubkey_root,
             leaves.credentials,
             u64_leaf_words(effective_balance),
             u64_leaf_words(slashed.astype(jnp.uint64)),
             u64_leaf_words(activation_eligibility_epoch),
             u64_leaf_words(activation_epoch),
             u64_leaf_words(exit_epoch),
             u64_leaf_words(withdrawable_epoch)]
        level = jnp.stack(f, axis=1)        # (N, 8 leaves, 8 words)
        for _ in range(3):
            half = level.shape[1] // 2
            level = sha256_64B_words(
                level.reshape(level.shape[0], half, 16))
        return level[:, 0, :]


def validator_registry_root(record_roots, length, limit_depth: int = 40,
                            axis_name: str | None = None):
    """hash_tree_root of `List[Validator, 2**40]` given the (padded, pow2)
    local shard of per-record roots.

    Pad rows (global index >= `length`) are masked to zero chunks here:
    SSZ pads the List's leaf level with 32-byte zero chunks, NOT with the
    record root of an all-zero Validator."""
    n_local = record_roots.shape[0]
    with telemetry.span("parallel.validator_registry_root.trace",
                        n=n_local), \
            jax.named_scope("cst.validator_registry_root"):
        idx = jnp.arange(n_local, dtype=jnp.uint64)
        if axis_name is not None:
            idx = idx + (lax.axis_index(axis_name).astype(jnp.uint64)
                         * jnp.uint64(n_local))
        in_range = idx < jnp.asarray(length, dtype=jnp.uint64)
        record_roots = jnp.where(in_range[:, None], record_roots,
                                 jnp.zeros_like(record_roots))
        if axis_name is None:
            root = subtree_root(record_roots, limit_depth)
        else:
            root = _sharded_list_root(record_roots, limit_depth,
                                      axis_name)
        return mix_in_length(root, length)
