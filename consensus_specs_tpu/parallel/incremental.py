"""Incremental device-resident merkleization — persistent layer stacks.

`parallel.merkle` re-hashes the full registry tree from the leaves on
every epoch step, yet an epoch transition dirties a small, known subset
of validators: the dominant sha256 cost of the flagship hot loop is
redundant.  The reference amortizes exactly this with remerkleable's
cached pointer-tree (`eth2spec/utils/ssz/ssz_impl.py:25` — unchanged
subtrees keep their cached roots); this module is the TPU-native
equivalent over flat arrays.

`MerkleForest` persists EVERY interior layer of one SSZ List tree as a
flat device array (layer k holds the 2**(data_depth-k) node words of
level k), so two operations become cheap:

- `update_dirty(layers, dirty_idx, new_leaf_words, depth)`: scatter the
  new leaf words, then per level deduplicate the dirty indices
  (`idx >> 1` cascade: sort, mask repeats to the level's sentinel),
  gather the touched sibling pairs, re-hash ONLY those nodes with the
  batched sha256 kernel, and scatter them back — O(dirty · log N)
  hashing instead of O(N).  Dirty counts are padded on the `_bucket`
  ladder so compiled shapes stay bounded.
- `gather_proof_paths(layers, indices, depth)`: batch-gather the
  root-to-leaf sibling paths for a set of leaf indices; the host-side
  settle assembles full SSZ single-proofs (zero-subtree ladder up to
  the List limit depth + the length mix-in chunk) verifiable by the
  `utils.ssz` oracle's `is_valid_merkle_branch` — the stateless-client
  / light-client proof-serving workload.

Layer-stack layout (data_depth = 3 example, shapes in chunks):

    layer 0   (8, 8) uint32   leaf chunk words
    layer 1   (4, 8)          H(leaf 2i ‖ leaf 2i+1)
    layer 2   (2, 8)
    layer 3   (1, 8)          data-subtree root
    ── above the stack, at result(): zero-subtree fold to limit_depth,
       then the SSZ length mix-in (both host-side, log-bounded)

Settle contract: entry points return `serve.futures.DeviceFuture`
handles (`*_async`); the one blocking fetch happens at `result()`,
matching the analyzer's `host-sync-outside-settle` rule.  Updates
themselves never sync — they replace the layer stack with freshly
dispatched device arrays.

Parity oracles: `parallel.merkle.balances_list_root` /
`validator_registry_root` (device full rebuild) and
`utils.ssz.ssz_impl.hash_tree_root` + `utils.ssz.gindex`
(`tests/test_incremental_merkle.py`).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..ops.sha256_jax import _fold_zero_levels, sha256_64B_words
from ..resilience import faults
from ..ops.sha256_np import ZERO_HASH_WORDS
from ..ops.sha256_np import sha256_64B_words as _host_sha256_64B
from ..telemetry import costmodel
from .merkle import pack_u64_chunks

# uint64 packing needs x64; entry points enable it (see parallel.require_x64)

# dirty-count ladder: every update/proof batch compiles at most these
# shapes for realistic dirty sets (larger sets fall back to powers of
# two).  Ratio-16 rungs: the rung cost is log N hash batches of M lanes,
# so over-padding is cheap sha work, and the flagship's 1% regime
# (10k dirty chunks @ 2**18) lands on the 16384 rung exactly.
_DIRTY_STEPS = (64, 1024, 16384)


def _bucket(n: int) -> int:
    """Padded dirty-count shape for n live indices: next power of two,
    quantized UP to the ladder so jit caches stay tiny.  Padded lanes
    carry the out-of-range sentinel and are dropped by the scatters, so
    correctness never depends on n."""
    b = 1 if n <= 1 else 1 << (n - 1).bit_length()
    for step in _DIRTY_STEPS:
        if b <= step:
            return step
    return b


def pad_dirty_idx(dirty_idx, capacity: int) -> np.ndarray:
    """Sentinel-pad a host-known dirty index set to its `_bucket` rung:
    rows beyond the live count carry `capacity` (out of range for the
    tree, dropped by the device scatters).  The ONE definition of the
    pad convention — `MerkleForest.update` and callers that pre-pad
    (the flagship keeps its padded index arrays device-resident) must
    agree on rung and sentinel, so both go through here."""
    idx = np.asarray(dirty_idx, dtype=np.uint32)
    out = np.full((_bucket(idx.shape[0]),), capacity, dtype=np.uint32)
    out[:idx.shape[0]] = idx
    return out


def _hash_blocks(blocks):
    """The one sha256 seam of this module — tests monkeypatch it to
    count hash invocations per traced update (the hashes-per-update
    scaling contract)."""
    return sha256_64B_words(blocks)


def _build_layers_impl(leaves, depth: int):
    """Full reduction that KEEPS every level: (2**depth, 8) leaf words
    -> tuple of depth+1 layers (leaves first, data root last).
    Unjitted body, so the tests' `_hash_blocks` lane counter sees it."""
    layers = [leaves]
    for _ in range(depth):
        layers.append(_hash_blocks(layers[-1].reshape(-1, 16)))
    return tuple(layers)


_build_layers = jax.jit(_build_layers_impl, static_argnames=("depth",))


def _update_dirty_impl(layers, dirty_idx, new_leaf_words, depth: int):
    """See `update_dirty`.  Unjitted body, traceable by the tests'
    hashes-per-update check.

    Two regimes per level, chosen statically from the padded dirty
    rung M (the `idx >> 1` cascade deduplicates dirty paths in both):

    - sparse (level wider than M): gather the M touched sibling pairs,
      hash M lanes, scatter the parents back.  Duplicate parents (two
      dirty children) gather the same pair and scatter the same hash —
      the cascade collapses them by idempotence, no sort needed; the
      sentinel index cascades out of range and is dropped.
    - dense (level no wider than M): re-hash the WHOLE level from its
      (already updated) children.  Cheaper than gather/scatter at that
      width, needs no index bookkeeping, and makes the all-dirty case
      degrade to ~full-rebuild cost (2N lanes) instead of depth*N.

    Total hash lanes: M per sparse level + the dense-tail geometric sum
    (< 2M) — O(dirty * log N), vs 2N for a full rebuild.
    """
    rung = dirty_idx.shape[0]
    out = [layers[0].at[dirty_idx].set(new_leaf_words, mode="drop")]
    cur = dirty_idx
    for lvl in range(depth):
        size = 1 << (depth - lvl - 1)       # nodes in level lvl+1
        if size <= rung:
            # dense tail: every level from here up is narrower than
            # the rung — once dense, always dense
            out.append(_hash_blocks(out[lvl].reshape(-1, 16)))
            continue
        # idx >> 1 cascade: each parent's (left ‖ right) children are
        # contiguous in the child layer, so reshaping to (size, 16)
        # makes the sibling-pair gather one row read per dirty path
        parents = cur >> jnp.uint32(1)
        pairs = out[lvl].reshape(-1, 16)
        blk = pairs[jnp.minimum(parents, jnp.uint32(size - 1))]
        hashed = _hash_blocks(blk)
        out.append(layers[lvl + 1].at[parents].set(hashed, mode="drop"))
        cur = parents
    return tuple(out)


_update_dirty_jit = jax.jit(_update_dirty_impl,
                            static_argnames=("depth",))


def update_dirty(layers, dirty_idx, new_leaf_words, depth: int):
    """Re-hash the dirty root-to-leaf paths of a persisted layer stack.

    layers: tuple of depth+1 device arrays (`_build_layers` shape);
    dirty_idx: (M,) uint32 leaf indices, padded with the sentinel
    2**depth (out-of-range rows are dropped); new_leaf_words: (M, 8)
    uint32 chunk words.  Returns the new layer tuple — a pure O(M·depth)
    device dispatch, no host sync."""
    m = int(dirty_idx.shape[0])
    # resilience fault seam: an installed plan can fail/slow the dirty
    # re-hash or corrupt its output layers (the self-healing detector's
    # chaos input) — one module-global read when no plan is active
    if faults.active():
        faults.maybe_inject("merkle_update", f"u{m}d{depth}")
    with telemetry.span("parallel.merkle_incr.update_dirty",
                        rung=m, depth=depth):
        out = _update_dirty_jit(layers, dirty_idx, new_leaf_words, depth)
    # cost-capture seam (CST_COSTMODEL rounds): the dirty-rung kernel's
    # flop/byte budget, once per (rung, depth) per process — outside the
    # span so the AOT analysis pass does not contaminate the wall
    costmodel.capture(f"merkle_incr@u{m}d{depth}", _update_dirty_jit,
                      (out, dirty_idx, new_leaf_words, depth))
    if faults.active():
        out = faults.corrupt("merkle_update", f"u{m}d{depth}", out)
    return out


@partial(jax.jit, static_argnames=("depth",))
def _gather_proof_paths(layers, idx, depth: int):
    """(M,) leaf indices -> ((M, 8) leaf words, (M, depth, 8) sibling
    words bottom-up) gathered from the persisted layers."""
    leaves = layers[0][jnp.minimum(idx, jnp.uint32(layers[0].shape[0] - 1))]
    sibs = []
    cur = idx
    for lvl in range(depth):
        size = 1 << (depth - lvl)           # nodes in level lvl
        sib = jnp.minimum(cur ^ jnp.uint32(1), jnp.uint32(size - 1))
        sibs.append(layers[lvl][sib])
        cur = cur >> jnp.uint32(1)
    if not sibs:
        path = jnp.zeros((idx.shape[0], 0, 8), jnp.uint32)
    else:
        path = jnp.stack(sibs, axis=1)
    return leaves, path


def gather_proof_paths(layers, idx, depth: int):
    """Instrumented facade over the proof-path gather kernel (the
    device half of `emit_proofs`)."""
    m = int(idx.shape[0])
    with telemetry.span("parallel.merkle_incr.gather_proofs",
                        rung=m, depth=depth):
        out = _gather_proof_paths(layers, idx, depth)
    costmodel.capture(f"merkle_proof@p{m}d{depth}", _gather_proof_paths,
                      (layers, idx, depth))
    return out


# --- host-side finishing (runs at DeviceFuture settle time) ------------------


def _words_to_bytes(words: np.ndarray) -> bytes:
    """(8,) big-endian uint32 chunk words -> 32 bytes."""
    return np.asarray(words, dtype=np.uint32).astype(">u4").tobytes()


def _length_chunk(length: int) -> bytes:
    return int(length).to_bytes(8, "little") + b"\x00" * 24


def _finish_root(data_root: np.ndarray, data_depth: int,
                 limit_depth: int, length: int) -> np.ndarray:
    """Zero-subtree fold + SSZ length mix-in over a fetched (8,) uint32
    data root — the log-bounded host tail of a list merkleization."""
    root = _fold_zero_levels(data_root, data_depth, limit_depth)
    tail = np.frombuffer(_length_chunk(length), dtype=">u4").astype(np.uint32)
    blk = np.concatenate([root, tail]).astype(np.uint32)
    return _host_sha256_64B(blk[None, :])[0]


class SSZProof(NamedTuple):
    """One SSZ single-proof for a leaf chunk of a List tree.

    `branch` runs bottom-up: `limit_depth` data-tree siblings followed
    by the length mix-in chunk, so the proof verifies with the spec's
    `is_valid_merkle_branch(leaf, branch, limit_depth + 1, index,
    root)` — `gindex` is the generalized index of the chunk within the
    List type (`utils.ssz.gindex` algebra: data tree at gindex 2)."""

    index: int
    gindex: int
    leaf: bytes
    branch: tuple[bytes, ...]

    @property
    def depth(self) -> int:
        return len(self.branch)


def _assemble_proofs(host, indices, data_depth: int, limit_depth: int,
                     length: int) -> list[SSZProof]:
    """Device gather (leaves, sibling paths) -> full SSZProofs: the
    persisted-path siblings, then the zero-subtree ladder up to
    `limit_depth`, then the length chunk."""
    leaves, paths = host
    zero_tail = [_words_to_bytes(ZERO_HASH_WORDS[lvl])
                 for lvl in range(data_depth, limit_depth)]
    len_chunk = _length_chunk(length)
    proofs = []
    for row, i in enumerate(indices):
        branch = [_words_to_bytes(paths[row, lvl])
                  for lvl in range(data_depth)]
        branch.extend(zero_tail)
        branch.append(len_chunk)
        proofs.append(SSZProof(
            index=int(i),
            gindex=(2 << limit_depth) + int(i),
            leaf=_words_to_bytes(leaves[row]),
            branch=tuple(branch)))
    return proofs


def verify_proof(proof: SSZProof, root: bytes) -> bool:
    """Host oracle check: spec-level branch verification of one emitted
    proof against a 32-byte list root (pure Python, no jax)."""
    from ..utils.ssz.gindex import is_valid_merkle_branch

    return is_valid_merkle_branch(proof.leaf, proof.branch, proof.depth,
                                  proof.index, root)


# --- the forest --------------------------------------------------------------


class MerkleForest:
    """Persistent device-resident merkleization state for one SSZ List.

    Holds every interior layer of the (power-of-two padded) data tree
    as flat device arrays; `update_async` re-hashes only the dirty
    root-to-leaf paths, `root_async`/`emit_proofs_async` settle through
    `serve.futures.DeviceFuture` handles (the sanctioned sync seam).

    `leaf_words` is the (n, 8) uint32 chunk-word array of the list's
    bottom layer (already packed: 4 uint64 per chunk for a balances
    list, one record root per chunk for the validator registry);
    `length` is the true SSZ element count for the length mix-in.
    """

    def __init__(self, leaf_words, limit_depth: int, length: int):
        leaf_words = np.asarray(leaf_words, dtype=np.uint32)
        n = leaf_words.shape[0]
        assert n <= (1 << limit_depth)
        d = max(n - 1, 0).bit_length()
        padded = np.zeros((1 << d, 8), dtype=np.uint32)
        padded[:n] = leaf_words
        self.data_depth = d
        self.limit_depth = limit_depth
        self.length = int(length)
        self.n_chunks = n
        # toggled by resilience.healing while a diverged stack rebuilds
        # (serving code must not emit roots/proofs from quarantined state)
        self.quarantined = False
        # attach point for a resilience.checkpoint.CheckpointManager:
        # while set, every update() also journals its leaf delta there
        self.checkpoint = None
        with telemetry.span("parallel.merkle_incr.build", depth=d):
            # cst: allow(recompile-unbucketed-dim): the static tree depth
            # keys the executable — log-bounded (<= limit_depth distinct
            # compiles), same contract as merkleize_words_jax
            self.layers = _build_layers(jnp.asarray(padded), d)
        costmodel.capture(f"merkle_build@d{d}", _build_layers,
                          (self.layers[0], d))

    @classmethod
    def from_layers(cls, layers, limit_depth: int, length: int,
                    n_chunks: int) -> "MerkleForest":
        """Reconstruct a forest from an already-computed layer stack
        with ZERO hashing — device puts only.  The checkpoint-restore
        path (`resilience.checkpoint`): the snapshot persisted every
        interior layer, so restore must not pay the O(N) re-merkleize
        `__init__` would.  Shapes are validated (each level halves);
        content correctness is the caller's checksum contract."""
        depth = len(layers) - 1
        assert depth >= 0 and layers[0].shape[0] == 1 << depth, (
            depth, layers[0].shape)
        for lvl, lay in enumerate(layers):
            assert tuple(lay.shape) == (1 << (depth - lvl), 8), (
                lvl, lay.shape)
        self = cls.__new__(cls)
        self.data_depth = depth
        self.limit_depth = int(limit_depth)
        self.length = int(length)
        self.n_chunks = int(n_chunks)
        assert self.n_chunks <= (1 << self.limit_depth)
        self.quarantined = False
        self.checkpoint = None
        with telemetry.span("parallel.merkle_incr.from_layers",
                            depth=depth):
            self.layers = tuple(
                jnp.asarray(np.asarray(lay, dtype=np.uint32),
                            dtype=jnp.uint32)
                for lay in layers)
        return self

    @property
    def capacity(self) -> int:
        """Leaf slots the persisted stack can address (padded pow2)."""
        return 1 << self.data_depth

    def update(self, dirty_idx, new_leaf_words) -> None:
        """Scatter `new_leaf_words` at `dirty_idx` (HOST-known leaf
        chunk indices, any order; duplicate indices are allowed ONLY
        when they carry identical leaf values — XLA scatter order for
        colliding rows is implementation-defined, so dedup divergent
        duplicates host-side first, as `dirty_chunks_from_validators`
        does) and re-hash the touched paths.  Indices >= `capacity`
        are the sentinel convention — those rows are dropped, so
        callers may pre-pad to a `_bucket` rung themselves via
        `pad_dirty_idx` (the flagship does, to keep its gathered leaf
        arrays on device).  `new_leaf_words` may be a host or device
        array; padding happens without a device fetch.  Pure dispatch:
        the layer stack is replaced with not-yet-materialized device
        arrays, no host sync."""
        m = len(dirty_idx)
        if m == 0:
            return
        idx = pad_dirty_idx(dirty_idx, self.capacity)
        rung = idx.shape[0]
        leaves = jnp.asarray(new_leaf_words, dtype=jnp.uint32)
        if leaves.shape[0] < rung:      # device-safe pad (no host fetch)
            leaves = jnp.concatenate(
                [leaves, jnp.zeros((rung - m, 8), dtype=jnp.uint32)])
        if self.checkpoint is not None:
            # leaf-delta journal (resilience.checkpoint): recorded
            # BEFORE the dispatch so snapshot+journal always covers
            # exactly the applied updates; the manager materializes the
            # delta host-side — the one sync checkpointing opts into
            self.checkpoint.on_update(self, idx, leaves)
        self.layers = update_dirty(self.layers, jnp.asarray(idx),
                                   leaves, self.data_depth)

    def root_async(self):
        """DeviceFuture settling to the (8,) uint32 words of the full
        List hash_tree_root (zero-ladder + length mix-in run host-side
        at result())."""
        from ..serve.futures import value_future

        d, limit, length = self.data_depth, self.limit_depth, self.length
        return value_future(
            self.layers[-1][0],
            convert=lambda host: _finish_root(host, d, limit, length))

    def root(self) -> np.ndarray:
        """Synchronous facade over `root_async` (the host API boundary
        of the incremental reduction)."""
        return self.root_async().result()

    def root_bytes(self) -> bytes:
        """The list root as the oracle's 32-byte form."""
        return _words_to_bytes(self.root())

    def emit_proofs_async(self, indices):
        """Batch-emit SSZ single-proofs for `indices` (leaf chunk
        positions).  Device work is one bucketed sibling-path gather;
        the zero-ladder tail and length chunk are appended host-side at
        settle.  Settles to a list of `SSZProof`."""
        from ..serve.futures import DeviceFuture, value_future

        indices = [int(i) for i in indices]
        if not indices:
            return DeviceFuture.settled([])
        assert max(indices) < self.n_chunks, (
            "proof index beyond the list's real chunk count")
        rung = _bucket(len(indices))
        idx = np.zeros((rung,), dtype=np.uint32)
        idx[:len(indices)] = indices
        gathered = gather_proof_paths(self.layers, jnp.asarray(idx),
                                      self.data_depth)
        d, limit, length = self.data_depth, self.limit_depth, self.length
        return value_future(
            gathered,
            convert=lambda host: _assemble_proofs(host, indices, d,
                                                  limit, length))

    def emit_proofs(self, indices) -> list[SSZProof]:
        """Synchronous facade over `emit_proofs_async`."""
        return self.emit_proofs_async(indices).result()


# --- module-level async facades (the serve executor's dispatch shape) --------


def merkleize_dirty_async(forest: MerkleForest, dirty_idx,
                          new_leaf_words):
    """Apply a dirty-set update and return the root future — the
    deferred-result entry point the flagship step and the serve
    executor consume (`host-sync-outside-settle` contract: dispatch
    here, block only at `result()`)."""
    with telemetry.span("parallel.merkle_incr.merkleize_dirty",
                        dirty=len(dirty_idx)):
        forest.update(dirty_idx, new_leaf_words)
        return forest.root_async()


def merkleize_dirty(forest: MerkleForest, dirty_idx,
                    new_leaf_words) -> np.ndarray:
    """Synchronous facade over `merkleize_dirty_async`."""
    return merkleize_dirty_async(forest, dirty_idx, new_leaf_words).result()


def emit_proofs_async(forest: MerkleForest, indices):
    """Module-level facade over `MerkleForest.emit_proofs_async` (the
    serve executor's proof-request dispatch target)."""
    return forest.emit_proofs_async(indices)


def emit_proofs(forest: MerkleForest, indices) -> list[SSZProof]:
    """Synchronous facade over `emit_proofs_async`."""
    return emit_proofs_async(forest, indices).result()


# --- mesh-sharded forests ----------------------------------------------------


def _top_tree_levels(shard_roots: np.ndarray) -> list[np.ndarray]:
    """All levels of the replicated top tree (log S host hashes — the
    'top join' of the sharded mode), shard-root level first: the proof
    assembly's sibling source above the per-shard stacks, and
    `[-1][0]` is the global data root (`_fold_shard_roots`)."""
    levels = [np.asarray(shard_roots, dtype=np.uint32)]
    while levels[-1].shape[0] > 1:
        levels.append(_host_sha256_64B(levels[-1].reshape(-1, 16)))
    return levels


def _fold_shard_roots(shard_roots: np.ndarray) -> np.ndarray:
    """(S, 8) per-shard data-subtree roots -> (8,) global data root —
    the ONE top-join fold, shared with the sibling levels
    `emit_proofs` assembles from."""
    return _top_tree_levels(shard_roots)[-1][0]


class ShardedMerkleForest:
    """Mesh-sharded `MerkleForest`: per-shard subtree layer stacks, each
    resident on its OWN device, plus a small replicated top tree.

    The global 2**data_depth-leaf data tree splits at level
    `local_depth` into `n_shards` contiguous subtrees; shard i's full
    layer stack (every interior level of its subtree) lives on device
    i.  `update` and `emit_proofs` stay shard-local — a dirty set only
    dispatches to the shards it touches, and a proof gather reads one
    shard's layers — until the top join: the log(n_shards) host hashes
    that fold the per-shard data roots into the global root (then the
    zero-subtree ladder and the SSZ length mix-in, exactly like the
    single-chip forest).

    Root parity contract: bit-exact vs `MerkleForest` over the same
    leaves (and hence vs the SSZ oracle) — the tree is identical, only
    the storage is split at `local_depth`
    (`tests/test_partition.py`)."""

    def __init__(self, leaf_words, limit_depth: int, length: int,
                 n_shards: int | None = None, device_ids=None):
        import jax as _jax

        from .partition import build_mesh, mesh_rung

        leaf_words = np.asarray(leaf_words, dtype=np.uint32)
        n = leaf_words.shape[0]
        assert n <= (1 << limit_depth)
        # device placement comes from the shared mesh builder (one
        # device list for the whole sharded path)
        mesh = build_mesh(n_devices=n_shards, device_ids=device_ids)
        devices = list(mesh.devices.flat)
        if n_shards is None and device_ids is None:
            devices = devices[:mesh_rung(len(devices))]
        s = len(devices)
        assert s >= 1 and s & (s - 1) == 0, (
            f"sharded forest needs a power-of-two shard count, got {s} "
            f"(quantize with mesh_rung)")
        self.shard_depth = (s - 1).bit_length()
        d = max(max(n - 1, 0).bit_length(), self.shard_depth)
        self.data_depth = d
        self.local_depth = d - self.shard_depth
        self.limit_depth = int(limit_depth)
        self.length = int(length)
        self.n_chunks = n
        self.n_shards = s
        self.devices = devices
        padded = np.zeros((1 << d, 8), dtype=np.uint32)
        padded[:n] = leaf_words
        local = 1 << self.local_depth
        self.shard_layers = []
        with telemetry.span("parallel.merkle_incr.sharded_build",
                            depth=d, shards=s):
            for i, dev in enumerate(devices):
                sl = _jax.device_put(padded[i * local:(i + 1) * local],
                                     dev)
                # cst: allow(recompile-unbucketed-dim): the static local
                # tree depth keys the executable — log-bounded, same
                # contract as MerkleForest.__init__
                self.shard_layers.append(
                    _build_layers(sl, self.local_depth))
        costmodel.capture(f"merkle_build@d{self.local_depth}",
                          _build_layers,
                          (self.shard_layers[0][0], self.local_depth))

    @property
    def capacity(self) -> int:
        return 1 << self.data_depth

    @property
    def shard_capacity(self) -> int:
        return 1 << self.local_depth

    def update(self, dirty_idx, new_leaf_words) -> None:
        """Scatter `new_leaf_words` at GLOBAL leaf indices `dirty_idx`
        and re-hash the touched paths, shard-locally: each touched
        shard gets one `update_dirty` dispatch on its own device (its
        local indices padded to the `_bucket` rung), untouched shards
        dispatch nothing.  The top tree is not materialized here — it
        re-folds lazily at `root()` from the (replaced) shard roots."""
        import jax as _jax

        idx = np.asarray(dirty_idx, dtype=np.uint32)
        if idx.shape[0] == 0:
            return
        leaves = np.asarray(new_leaf_words, dtype=np.uint32)
        assert leaves.shape[0] >= idx.shape[0], (leaves.shape, idx.shape)
        # rung-padded callers (the MerkleForest.update convention) may
        # hand leaves LONGER than the index set — the extra rows pair
        # with sentinel indices and must not desync the boolean mask
        leaves = leaves[:idx.shape[0]]
        shard_of = idx >> np.uint32(self.local_depth)
        with telemetry.span("parallel.merkle_incr.sharded_update",
                            dirty=int(idx.shape[0]),
                            shards=self.n_shards):
            for s in range(self.n_shards):
                hit = shard_of == s
                if not hit.any():
                    continue
                local_idx = idx[hit] & np.uint32(self.shard_capacity - 1)
                dev = self.devices[s]
                padded_idx = pad_dirty_idx(local_idx, self.shard_capacity)
                rung = padded_idx.shape[0]
                shard_leaves = np.zeros((rung, 8), dtype=np.uint32)
                shard_leaves[:local_idx.shape[0]] = leaves[hit]
                self.shard_layers[s] = update_dirty(
                    self.shard_layers[s],
                    _jax.device_put(padded_idx, dev),
                    _jax.device_put(shard_leaves, dev),
                    self.local_depth)

    def _shard_roots_dev(self):
        return tuple(layers[-1][0] for layers in self.shard_layers)

    def root_async(self):
        """DeviceFuture settling to the (8,) uint32 words of the full
        List hash_tree_root: the per-shard data roots cross to the host
        at result(), where the replicated top tree, zero ladder, and
        length mix-in finish the root (all log-bounded)."""
        from ..serve.futures import value_future

        d, limit, length = self.data_depth, self.limit_depth, self.length

        def finish(host_roots):
            data_root = _fold_shard_roots(np.stack(host_roots))
            return _finish_root(data_root, d, limit, length)

        return value_future(self._shard_roots_dev(), convert=finish)

    def root(self) -> np.ndarray:
        """Synchronous facade over `root_async`."""
        return self.root_async().result()

    def root_bytes(self) -> bytes:
        return _words_to_bytes(self.root())

    def emit_proofs_async(self, indices):
        """Batch-emit SSZ single-proofs for GLOBAL leaf indices: one
        shard-local sibling-path gather per touched shard (on that
        shard's device), then the host settle appends the top-tree
        siblings (shard-root levels), the zero-subtree ladder, and the
        length chunk.  Settles to a list of `SSZProof` in input
        order."""
        import jax as _jax

        from ..serve.futures import DeviceFuture, value_future

        indices = [int(i) for i in indices]
        if not indices:
            return DeviceFuture.settled([])
        assert max(indices) < self.n_chunks, (
            "proof index beyond the list's real chunk count")
        by_shard: dict[int, list[int]] = {}
        for i in indices:
            by_shard.setdefault(i >> self.local_depth, []).append(i)
        gathers = {}
        with telemetry.span("parallel.merkle_incr.sharded_proofs",
                            batch=len(indices),
                            shards=len(by_shard)):
            for s, idxs in sorted(by_shard.items()):
                local = [i & (self.shard_capacity - 1) for i in idxs]
                rung = _bucket(len(local))
                arr = np.zeros((rung,), dtype=np.uint32)
                arr[:len(local)] = local
                gathers[s] = gather_proof_paths(
                    self.shard_layers[s],
                    _jax.device_put(arr, self.devices[s]),
                    self.local_depth)
        d, limit, length = self.data_depth, self.limit_depth, self.length
        local_depth, shard_depth = self.local_depth, self.shard_depth
        shard_order = sorted(by_shard)
        payload = (tuple(gathers[s] for s in shard_order),
                   self._shard_roots_dev())

        def finish(host):
            shard_gathers, shard_roots = host
            top = _top_tree_levels(np.stack(shard_roots))
            proofs_by_index = {}
            for pos, s in enumerate(shard_order):
                leaves_h, paths_h = shard_gathers[pos]
                for row, g in enumerate(by_shard[s]):
                    branch = [_words_to_bytes(paths_h[row, lvl])
                              for lvl in range(local_depth)]
                    for lvl in range(shard_depth):
                        sib = (s >> lvl) ^ 1
                        branch.append(_words_to_bytes(top[lvl][sib]))
                    branch.extend(
                        _words_to_bytes(ZERO_HASH_WORDS[lvl])
                        for lvl in range(d, limit))
                    branch.append(_length_chunk(length))
                    proofs_by_index[g] = SSZProof(
                        index=g, gindex=(2 << limit) + g,
                        leaf=_words_to_bytes(leaves_h[row]),
                        branch=tuple(branch))
            return [proofs_by_index[i] for i in indices]

        return value_future(payload, convert=finish)

    def emit_proofs(self, indices) -> list[SSZProof]:
        """Synchronous facade over `emit_proofs_async`."""
        return self.emit_proofs_async(indices).result()


def sharded_balances_forest(balances, length, limit_depth: int = 38,
                            n_shards: int | None = None,
                            device_ids=None) -> ShardedMerkleForest:
    """Sharded forest over `List[uint64, 2**40]` from a host uint64
    balances array (the flagship's multi-chip balances-tree mode)."""
    from . import require_x64
    require_x64()
    chunks = np.asarray(pack_u64_chunks(jnp.asarray(balances)))
    return ShardedMerkleForest(chunks, limit_depth, length,
                               n_shards=n_shards, device_ids=device_ids)


# --- flagship glue: registry-scale forests over the sweep arrays -------------


def balances_forest(balances, length, limit_depth: int = 38) -> MerkleForest:
    """Forest over `List[uint64, 2**40]` (4 values per 32-byte chunk,
    limit 2**38 chunks) from a host uint64 balances array."""
    from . import require_x64
    require_x64()
    chunks = np.asarray(pack_u64_chunks(jnp.asarray(balances)))
    return MerkleForest(chunks, limit_depth, length)


def registry_forest(record_roots, length,
                    limit_depth: int = 40) -> MerkleForest:
    """Forest over `List[Validator, 2**40]` from per-record root words
    ((n, 8) uint32, e.g. `merkle.validator_records_root` output).  Pad
    rows beyond `length` must already be zero chunks (SSZ pads the leaf
    level with zero chunks, not zero-validator roots)."""
    return MerkleForest(record_roots, limit_depth, length)


def dirty_chunks_from_validators(dirty_validator_idx) -> np.ndarray:
    """Dirty balance-chunk indices for a set of dirty validator
    indices (4 uint64 per chunk; host-side, deduplicated, sorted)."""
    return np.unique(np.asarray(dirty_validator_idx,
                                dtype=np.uint64) >> np.uint64(2)
                     ).astype(np.uint32)


@jax.jit
def _gather_balance_chunks(balances, chunk_idx):
    """((N,) uint64 balances, (M,) chunk indices) -> (M, 8) uint32
    chunk words: gather each dirty chunk's 4 values and pack them with
    the SSZ little-endian layout."""
    flat = (chunk_idx.astype(jnp.uint64)[:, None] * jnp.uint64(4)
            + jnp.arange(4, dtype=jnp.uint64)[None, :]).reshape(-1)
    vals = balances[jnp.minimum(flat,
                                jnp.uint64(balances.shape[0] - 1))]
    # beyond-end gathers clamp; zero them so pad chunks stay SSZ zero
    vals = jnp.where(flat < jnp.uint64(balances.shape[0]), vals,
                     jnp.uint64(0))
    return pack_u64_chunks(vals)


def dirty_balance_leaves(balances, chunk_idx):
    """Instrumented facade over the dirty-chunk gather/pack kernel —
    the flagship's bridge from a swept balances array to
    `update_dirty` leaf words."""
    from . import require_x64
    require_x64()
    m = int(chunk_idx.shape[0])
    with telemetry.span("parallel.merkle_incr.dirty_balance_leaves",
                        rung=m):
        out = _gather_balance_chunks(balances, chunk_idx)
    costmodel.capture(f"merkle_leafpack@{m}", _gather_balance_chunks,
                      (balances, chunk_idx))
    return out
