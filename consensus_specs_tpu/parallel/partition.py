"""Partition-rule registry — one declarative table shards the flagship.

The mesh story before this module was piecemeal: `batch_verify_sharded`
built its own `Mesh` inside the kernel factory, `parallel.make_mesh`
built another for the epoch step, `resilience.mesh` plumbed raw
`device_ids` tuples between them, and every new sharded surface
re-decided by hand which arrays ride the `data` axis.  This module
centralizes both decisions behind the `match_partition_rules` pattern
(SNIPPETS.md [2], the fmengine/EasyLM regex-path registry):

- `match_partition_rules(rules, tree)` maps every path-named leaf of a
  pytree to a `jax.sharding.PartitionSpec`: scalar leaves are never
  partitioned, the FIRST matching `(regex, spec)` rule wins, and an
  unmatched non-scalar path is a HARD error — a new epoch-state array
  cannot silently land replicated and eat n_devices times its memory.
- `EPOCH_STATE_RULES` is the default table for the flagship epoch
  state: every validator-indexed array (balances, registry fields,
  participation flags, sweep masks, per-validator leaf words) shards
  over the mesh's `data` axis; small per-epoch scalars replicate.
- `build_mesh` is THE mesh builder (n_devices prefix, or an explicit
  `device_ids` subset — the resilience layer's surviving-device form),
  shared by the epoch step, the sharded MerkleForest, and
  `ops.bls_batch`'s sharded RLC/MSM kernels.
- `shard_tree`/`gather_tree` place/fetch a pytree according to the
  matched specs (device_put with `NamedSharding`, one host fetch).
- `sharded_epoch_step` / `partitioned_epoch_step` wire the registry
  into `shard_map`: the step's `in_specs` are DERIVED from the rule
  table (via `epoch_step_specs`), not hand-written per call site, and
  `partitioned_epoch_step` accepts a `device_ids` subset so the
  flagship step composes with `resilience.mesh.MeshVerifier`'s
  recovery ladder (a lost chip re-buckets the SAME epoch state over
  the surviving power-of-two subset — `mesh_rung`).

`mesh_rung(n)` is the mesh-width ladder: the largest power of two <= n.
The sharded merkle reduction and the registry-tree fold both need a
power-of-two device axis, and quantizing device counts through one
sanctioned function also bounds executable churn — the analyzer's
recompile-hazard rule treats device-count reads like raw `len()` dims
and accepts `mesh_rung` as the laundering seam (like `_bucket` for
batch shapes).
"""

from __future__ import annotations

import functools
import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import telemetry
from .epoch import EpochParams, EpochScalars, RegistryArrays, epoch_sweep

DATA_AXIS = "data"


# --- rule matching -----------------------------------------------------------


def _is_namedtuple(node) -> bool:
    return isinstance(node, tuple) and hasattr(node, "_fields")


def named_tree_leaves(tree, sep: str = "/") -> list[tuple[str, object]]:
    """[(path, leaf)] pairs with human-readable path names: NamedTuple
    fields and dict keys by name, list/tuple positions by index.  The
    manual walk (instead of `jax.tree_util` key-paths) keeps the names
    stable across jax versions and containers."""
    out: list[tuple[str, object]] = []

    def walk(prefix, node):
        if _is_namedtuple(node):
            for name, sub in zip(node._fields, node):
                walk(prefix + [name], sub)
        elif isinstance(node, dict):
            for key in node:
                walk(prefix + [str(key)], node[key])
        elif isinstance(node, (list, tuple)):
            for i, sub in enumerate(node):
                walk(prefix + [str(i)], sub)
        else:
            out.append((sep.join(prefix), node))

    walk([], tree)
    return out


def _leaf_is_scalar(leaf) -> bool:
    shape = getattr(leaf, "shape", ())
    return len(shape) == 0 or int(np.prod(shape)) == 1


def match_partition_rules(rules, tree, sep: str = "/"):
    """Pytree of `PartitionSpec`s for `tree` under `rules`.

    `rules` is an ordered sequence of `(regex, PartitionSpec)` pairs;
    the FIRST rule whose regex `re.search`-matches a leaf's `/`-joined
    path wins (put specific rules above catch-alls).  Scalar leaves
    (0-d or single-element) are never partitioned, whatever the rules
    say.  A non-scalar leaf that no rule matches raises `ValueError`
    naming the path — sharding decisions are explicit, never a silent
    replicate-by-default."""

    def spec_for(name: str, leaf):
        if _leaf_is_scalar(leaf):
            return P()
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        raise ValueError(
            f"no partition rule matches leaf {name!r} "
            f"(shape {getattr(leaf, 'shape', None)}) — add a row to the "
            f"rule table (see README 'Mesh sharding')")

    def walk(prefix, node):
        if _is_namedtuple(node):
            return type(node)(*(walk(prefix + [f], s)
                                for f, s in zip(node._fields, node)))
        if isinstance(node, dict):
            return {k: walk(prefix + [str(k)], v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            vals = [walk(prefix + [str(i)], s) for i, s in enumerate(node)]
            return vals if isinstance(node, list) else tuple(vals)
        return spec_for(sep.join(prefix), node)

    return walk([], tree)


def epoch_state_rules(axis: str = DATA_AXIS):
    """The default rule table for the flagship epoch state pytree.

    Every validator-indexed array shards on the mesh's data axis; the
    per-epoch scalars replicate (they are 0-d, so the scalar skip
    already covers them — the explicit row documents intent and keeps
    a (1,)-shaped scalar from hitting the unmatched-path error)."""
    return (
        # RegistryArrays: the struct-of-arrays validator registry
        (r"(^|/)(balance|effective_balance|slashed"
         r"|activation_eligibility_epoch|activation_epoch|exit_epoch"
         r"|withdrawable_epoch|is_source|is_target|is_head"
         r"|inclusion_delay|proposer_index)$", P(axis)),
        # per-validator static leaf words + merkle leaf arrays
        (r"(^|/)(pubkey_root|credentials|record_roots|leaf_words"
         r"|balances)$", P(axis)),
        # sweep masks / dirty-set arrays ride with the validators
        (r"(^|/)(mask|sweep_mask|dirty_mask|dirty_idx|chunk_idx)$",
         P(axis)),
        # per-epoch scalars are replicated
        (r"(^|/)(current_epoch|finality_delay|slashings_sum|length)$",
         P()),
    )


EPOCH_STATE_RULES = epoch_state_rules()


# --- mesh building (the ONE builder) -----------------------------------------


def mesh_rung(n: int) -> int:
    """Largest power of two <= n — the mesh-width ladder.  The sharded
    merkle reductions need a power-of-two axis, and quantizing device
    counts here bounds per-topology executable churn (the analyzer
    accepts this as the device-count laundering seam)."""
    assert n >= 1, n
    return 1 << (int(n).bit_length() - 1)


def available_devices() -> int:
    """Device-pool size (the one `jax.devices()` probe the sharded
    surfaces and `resilience.mesh` share)."""
    return len(jax.devices())


def build_mesh(n_devices: int | None = None, axis: str = DATA_AXIS,
               device_ids=None, require_pow2: bool = False) -> Mesh:
    """The shared 1-axis mesh builder.

    `device_ids` (a tuple of `jax.devices()` indices) builds the mesh
    from exactly those devices — the resilience layer's surviving-set
    form after a `device_loss`; otherwise the first `n_devices` (all,
    when None).  `require_pow2` asserts the width is a power of two
    (the sharded merkle reductions need it; quantize with
    `mesh_rung`)."""
    devs = jax.devices()
    if device_ids is not None:
        device_ids = tuple(int(i) for i in device_ids)
        assert device_ids and max(device_ids) < len(devs), device_ids
        devs = [devs[i] for i in device_ids]
    elif n_devices is not None:
        assert 1 <= n_devices <= len(devs), (n_devices, len(devs))
        devs = devs[:n_devices]
    n = len(devs)
    if require_pow2:
        assert n & (n - 1) == 0, (
            f"mesh must be a power of two for the sharded merkle "
            f"reduction, got {n} devices (quantize with mesh_rung)")
    return Mesh(np.array(devs), (axis,))


# --- shard / gather helpers --------------------------------------------------


def shard_tree(mesh: Mesh, tree, rules=EPOCH_STATE_RULES):
    """device_put every leaf of `tree` with the `NamedSharding` its
    matched rule names (replicated for scalars).  Returns the same
    container type with device arrays."""
    specs = match_partition_rules(rules, tree)
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        tree, specs)


def gather_tree(tree):
    """Fetch every leaf back to host numpy (the one blocking transfer
    of a shard/compute/gather round)."""
    return jax.tree_util.tree_map(lambda leaf: np.asarray(leaf), tree)


# --- the registry-driven sharded epoch step ----------------------------------


def epoch_step_specs(axis: str = DATA_AXIS):
    """`shard_map` in/out specs for the flagship epoch step, DERIVED
    from the rule table (a template tree per argument) instead of
    hand-written per call site.

    Returns (in_specs, out_specs) for
    f(reg: RegistryArrays, sc: EpochScalars, length, pubkey_root,
      credentials) -> (new_bal, new_eff, balances_root, registry_root).
    """
    rules = epoch_state_rules(axis)
    dummy = np.zeros((2,), np.uint64)
    reg_specs = match_partition_rules(
        rules, RegistryArrays(*([dummy] * len(RegistryArrays._fields))))
    sc_specs = match_partition_rules(
        rules, EpochScalars(*([np.uint64(0)] * len(EpochScalars._fields))))
    leaf_specs = match_partition_rules(
        rules, {"pubkey_root": np.zeros((2, 8), np.uint32),
                "credentials": np.zeros((2, 8), np.uint32)})
    in_specs = (reg_specs, sc_specs, P(), leaf_specs["pubkey_root"],
                leaf_specs["credentials"])
    out_specs = (P(axis), P(axis), P(), P())
    return in_specs, out_specs


def sharded_epoch_step(mesh: Mesh, params: EpochParams,
                       axis: str = DATA_AXIS):
    """Mesh-sharded full flagship step: sweep with psum totals +
    cross-shard proposer-reward scatter + sharded balances/registry
    merkle roots, with the shard_map specs coming from the partition
    registry.  Inputs are sharded (N,) arrays (N divisible by the mesh
    size, power of two); outputs (new_bal, new_eff, balances_root,
    registry_root) with the roots replicated."""
    from . import require_x64
    from ..utils.jaxtools import shard_map_compat
    from .merkle import (ValidatorLeaves, balances_list_root,
                         validator_records_root, validator_registry_root)

    require_x64()

    def _step(reg: RegistryArrays, sc: EpochScalars, length,
              pubkey_root, credentials):
        new_bal, new_eff = epoch_sweep(reg, sc, params, axis_name=axis)
        bal_root = balances_list_root(new_bal, length, axis_name=axis)
        rec_roots = validator_records_root(
            ValidatorLeaves(pubkey_root, credentials), new_eff,
            reg.slashed, reg.activation_eligibility_epoch,
            reg.activation_epoch, reg.exit_epoch, reg.withdrawable_epoch)
        reg_root = validator_registry_root(rec_roots, length,
                                           axis_name=axis)
        return new_bal, new_eff, bal_root, reg_root

    in_specs, out_specs = epoch_step_specs(axis)
    sharded = shard_map_compat(_step, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs)
    return jax.jit(sharded)


@functools.lru_cache(maxsize=16)
def _partitioned_epoch_step_cached(params: EpochParams,
                                   n_devices: int | None,
                                   device_ids: tuple | None,
                                   axis: str):
    mesh = build_mesh(n_devices=n_devices, device_ids=device_ids,
                      axis=axis, require_pow2=True)
    return sharded_epoch_step(mesh, params, axis=axis)


def partitioned_epoch_step(params: EpochParams,
                           n_devices: int | None = None,
                           device_ids: tuple | None = None,
                           axis: str = DATA_AXIS):
    """`sharded_epoch_step` keyed by mesh topology: the first
    `n_devices` (all, when None), or an explicit `device_ids` subset —
    the resilience layer's surviving-set form, so the flagship step
    re-buckets onto a shrunken mesh exactly like the sharded RLC batch.
    One executable per (params, topology) — the positional-normalizing
    facade keeps keyword/default spellings on ONE lru cache key; device
    counts are quantized through `mesh_rung` by the callers that derive
    them from a pool probe."""
    from ..telemetry import costmodel

    telemetry.count("parallel.partition.step_topologies")
    # cost seam presence for the per-topology executable: the step's
    # own kernels record through their spans; the watermark sample
    # keeps the topology build visible to CST_COSTMODEL rounds
    costmodel.sample_watermark("parallel.partition.step")
    if device_ids is not None:
        device_ids = tuple(int(i) for i in device_ids)
    return _partitioned_epoch_step_cached(params, n_devices,
                                          device_ids, axis)


def epoch_step_dispatcher(params: EpochParams, axis: str = DATA_AXIS):
    """A `resilience.mesh.MeshVerifier`-shaped dispatch function for
    the flagship epoch step: `dispatch(payload, rng, device_ids)`
    re-shards the SAME epoch state over the given device subset
    (trimmed to the `mesh_rung` power of two) and returns a
    `DeviceFuture` settling to the host (new_bal, new_eff,
    balances_root, registry_root) tuple.  Pair it with
    `MeshVerifier(dispatch_fn=..., result_cast=None)` — see
    `resilience.mesh.sharded_epoch_verifier` — and the `device_ids`-
    subset fallback covers the epoch step, not just the RLC batch."""
    from ..serve.futures import value_future

    def dispatch(payload, rng, device_ids):
        del rng                      # epoch steps draw no randomness
        reg, sc, length, pubkey_root, credentials = payload
        ids = tuple(int(i) for i in device_ids)
        ids = ids[:mesh_rung(len(ids))]
        with telemetry.span("parallel.partition.epoch_dispatch",
                            devices=len(ids)):
            step = partitioned_epoch_step(params, device_ids=ids,
                                          axis=axis)
            mesh = build_mesh(device_ids=ids, axis=axis,
                              require_pow2=True)
            rules = epoch_state_rules(axis)
            reg_s = shard_tree(mesh, reg, rules)
            leaves = shard_tree(mesh, {"pubkey_root": pubkey_root,
                                       "credentials": credentials}, rules)
            out = step(reg_s, sc, length, leaves["pubkey_root"],
                       leaves["credentials"])
        return value_future(out)

    return dispatch
