"""TPU-native epoch processing: the per-validator sweep as one fused XLA
program over a struct-of-arrays registry, sharded across a device mesh.

This is the TPU redesign of the reference's epoch pipeline
(`specs/phase0/beacon-chain.md:1410-1850`: `get_attestation_deltas`,
`process_rewards_and_penalties`, `process_slashings`,
`process_effective_balance_updates`).  The reference walks Python lists of
`Validator` objects per epoch; here the registry lives as flat uint64/bool
arrays, the whole sweep is elementwise + a handful of reductions, and under a
`jax.sharding.Mesh` the reductions become `psum` over the `data` axis so the
1M-validator sweep scales across chips.

Exactness contract: all arithmetic is uint64 (requires jax x64) and matches
the spec's integer semantics bit-for-bit — verified by
`tests/test_parallel_epoch.py` against the executable spec.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import telemetry

# x64 (uint64 spec arithmetic) is enabled once, in parallel/__init__ — this
# module is only reachable through that package import.

U64 = jnp.uint64


class EpochParams(NamedTuple):
    """Preset/config constants the sweep needs (python ints; closed over as
    compile-time constants — they never change within a preset)."""

    base_reward_factor: int
    base_rewards_per_epoch: int
    proposer_reward_quotient: int
    inactivity_penalty_quotient: int
    min_epochs_to_inactivity_penalty: int
    effective_balance_increment: int
    max_effective_balance: int
    hysteresis_quotient: int
    hysteresis_downward_multiplier: int
    hysteresis_upward_multiplier: int
    epochs_per_slashings_vector: int
    proportional_slashing_multiplier: int

    @classmethod
    def from_spec(cls, spec) -> "EpochParams":
        return cls(
            base_reward_factor=int(spec.BASE_REWARD_FACTOR),
            base_rewards_per_epoch=int(spec.BASE_REWARDS_PER_EPOCH),
            proposer_reward_quotient=int(spec.PROPOSER_REWARD_QUOTIENT),
            inactivity_penalty_quotient=int(spec.INACTIVITY_PENALTY_QUOTIENT),
            min_epochs_to_inactivity_penalty=int(
                spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY),
            effective_balance_increment=int(spec.EFFECTIVE_BALANCE_INCREMENT),
            max_effective_balance=int(spec.MAX_EFFECTIVE_BALANCE),
            hysteresis_quotient=int(spec.HYSTERESIS_QUOTIENT),
            hysteresis_downward_multiplier=int(
                spec.HYSTERESIS_DOWNWARD_MULTIPLIER),
            hysteresis_upward_multiplier=int(
                spec.HYSTERESIS_UPWARD_MULTIPLIER),
            epochs_per_slashings_vector=int(spec.EPOCHS_PER_SLASHINGS_VECTOR),
            proportional_slashing_multiplier=int(
                spec.PROPORTIONAL_SLASHING_MULTIPLIER),
        )


class RegistryArrays(NamedTuple):
    """Struct-of-arrays view of the validator registry + participation for
    one epoch transition.  All shapes (N,); shardable on the leading axis."""

    balance: jnp.ndarray             # uint64 Gwei
    effective_balance: jnp.ndarray   # uint64 Gwei
    slashed: jnp.ndarray             # bool
    activation_eligibility_epoch: jnp.ndarray  # uint64
    activation_epoch: jnp.ndarray    # uint64
    exit_epoch: jnp.ndarray          # uint64
    withdrawable_epoch: jnp.ndarray  # uint64
    # previous-epoch participation (already committee-expanded on host from
    # PendingAttestations / participation flags)
    is_source: jnp.ndarray           # bool — attested with matching source
    is_target: jnp.ndarray           # bool — …and matching target
    is_head: jnp.ndarray             # bool — …and matching head
    inclusion_delay: jnp.ndarray     # uint64 — min delay (1 if none)
    proposer_index: jnp.ndarray      # int32 — proposer of min-delay att (0 if none)


class EpochScalars(NamedTuple):
    """Per-epoch scalar inputs (traced; uint64 0-d arrays)."""

    current_epoch: jnp.ndarray
    finality_delay: jnp.ndarray      # previous_epoch - finalized.epoch
    slashings_sum: jnp.ndarray       # sum(state.slashings)


def _isqrt_u64(n):
    """Exact integer sqrt for n < 2**63 (float64 seed + correction)."""
    x = jnp.floor(jnp.sqrt(n.astype(jnp.float64))).astype(U64)
    # one Newton step guards seeds that overshoot, then exact ±1 correction
    x = jnp.where(x > 0, jnp.minimum(x, (x + n // jnp.maximum(x, 1)) // 2), x)
    x = jnp.where(x * x > n, x - 1, x)
    x = jnp.where((x + 1) * (x + 1) <= n, x + 1, x)
    return x


def _total(x, axis_name: str | None):
    """Global sum of a (N,) shard — psum across the mesh axis if sharded.

    `axis_name` is annotated static: the branch below is a host-side
    sharding decision, not data-dependent control flow (the analyzer's
    recompile-traced-branch rule keys off the annotation)."""
    s = jnp.sum(x)
    if axis_name is not None:
        s = lax.psum(s, axis_name)
    return s


def epoch_sweep(reg: RegistryArrays, sc: EpochScalars, params: EpochParams,
                axis_name: str | None = None):
    """One epoch's rewards/penalties + slashings + effective-balance sweep.

    Returns (new_balance, new_effective_balance), both (N,) uint64.
    Pure function of its inputs; jit/shard_map it at the call site.

    The body runs under `jax.named_scope` (the sweep shows up as one
    block in XLA device profiles) and a telemetry span — under jit the
    span fires per TRACE, so its wall time is the Python tracing cost,
    not the device step."""
    with telemetry.span("parallel.epoch_sweep.trace",
                        n=int(reg.balance.shape[0])), \
            jax.named_scope("cst.epoch_sweep"):
        return _epoch_sweep_impl(reg, sc, params, axis_name)


def _epoch_sweep_impl(reg: RegistryArrays, sc: EpochScalars,
                      params: EpochParams, axis_name: str | None = None):
    p = params
    one = jnp.uint64(1)
    prev_epoch = jnp.maximum(sc.current_epoch, one) - one

    active_cur = ((reg.activation_epoch <= sc.current_epoch)
                  & (sc.current_epoch < reg.exit_epoch))
    active_prev = ((reg.activation_epoch <= prev_epoch)
                   & (prev_epoch < reg.exit_epoch))
    eligible = active_prev | (reg.slashed
                              & (prev_epoch + one < reg.withdrawable_epoch))

    incr = jnp.uint64(p.effective_balance_increment)
    total_active = jnp.maximum(
        incr, _total(jnp.where(active_cur, reg.effective_balance, 0), axis_name))
    sqrt_total = _isqrt_u64(total_active)

    # get_base_reward (beacon-chain.md): eff * BRF // isqrt(total) // BRPE
    base_reward = (reg.effective_balance * jnp.uint64(p.base_reward_factor)
                   // sqrt_total // jnp.uint64(p.base_rewards_per_epoch))
    proposer_reward = base_reward // jnp.uint64(p.proposer_reward_quotient)

    in_leak = sc.finality_delay > jnp.uint64(p.min_epochs_to_inactivity_penalty)

    unslashed = ~reg.slashed
    rewards = jnp.zeros_like(reg.balance)
    penalties = jnp.zeros_like(reg.balance)

    # -- source/target/head component deltas (get_attestation_component_deltas)
    for flag in (reg.is_source & unslashed,
                 reg.is_target & unslashed,
                 reg.is_head & unslashed):
        attesting_balance = jnp.maximum(
            incr, _total(jnp.where(flag, reg.effective_balance, 0), axis_name))
        participation_reward = (base_reward * (attesting_balance // incr)
                                // (total_active // incr))
        comp_reward = jnp.where(in_leak, base_reward, participation_reward)
        rewards += jnp.where(eligible & flag, comp_reward, 0)
        penalties += jnp.where(eligible & ~flag, base_reward, 0)

    # -- inclusion-delay micro rewards (get_inclusion_delay_deltas)
    src = reg.is_source & unslashed
    max_attester_reward = base_reward - proposer_reward
    rewards += jnp.where(
        src, max_attester_reward // jnp.maximum(reg.inclusion_delay, one), 0)
    # proposer micro-reward: scatter-add to the proposer of each attester's
    # earliest-included attestation.  Under sharding the proposer may live on
    # another shard: scatter into a global-length accumulator and psum it.
    prop_contrib = jnp.where(src, proposer_reward, 0)
    if axis_name is None:
        rewards = rewards.at[reg.proposer_index].add(
            prop_contrib, mode="drop")
    else:
        n_local = reg.balance.shape[0]
        n_dev = lax.psum(1, axis_name)
        global_acc = jnp.zeros((n_local * n_dev,), dtype=U64)
        global_acc = global_acc.at[reg.proposer_index].add(
            prop_contrib, mode="drop")
        # reduce-scatter: each shard receives exactly its own reduced slice
        # (no full-array broadcast back as psum would do)
        rewards += lax.psum_scatter(
            global_acc, axis_name, scatter_dimension=0, tiled=True)

    # -- inactivity-leak penalties (get_inactivity_penalty_deltas)
    leak_base = (jnp.uint64(p.base_rewards_per_epoch) * base_reward
                 - proposer_reward)
    leak_extra = (reg.effective_balance * sc.finality_delay
                  // jnp.uint64(p.inactivity_penalty_quotient))
    tgt = reg.is_target & unslashed
    penalties += jnp.where(in_leak & eligible, leak_base, 0)
    penalties += jnp.where(in_leak & eligible & ~tgt, leak_extra, 0)

    # -- apply deltas (process_rewards_and_penalties; saturating decrease)
    is_genesis = sc.current_epoch == 0
    bal = reg.balance + jnp.where(is_genesis, 0, rewards)
    pen = jnp.where(is_genesis, 0, penalties)
    bal = jnp.where(pen > bal, 0, bal - pen)

    # -- process_slashings (correlated slashing penalty sweep)
    adj_slashing = jnp.minimum(
        sc.slashings_sum * jnp.uint64(p.proportional_slashing_multiplier),
        total_active)
    hits = reg.slashed & (
        sc.current_epoch + jnp.uint64(p.epochs_per_slashings_vector // 2)
        == reg.withdrawable_epoch)
    slash_pen = ((reg.effective_balance // incr) * adj_slashing
                 // total_active * incr)
    slash_pen = jnp.where(hits, slash_pen, 0)
    bal = jnp.where(slash_pen > bal, 0, bal - slash_pen)

    # -- process_effective_balance_updates (hysteresis)
    hyst_incr = incr // jnp.uint64(p.hysteresis_quotient)
    down = hyst_incr * jnp.uint64(p.hysteresis_downward_multiplier)
    up = hyst_incr * jnp.uint64(p.hysteresis_upward_multiplier)
    candidate = jnp.minimum(bal - bal % incr,
                            jnp.uint64(p.max_effective_balance))
    move = ((bal + down < reg.effective_balance)
            | (reg.effective_balance + up < bal))
    new_eff = jnp.where(move, candidate, reg.effective_balance)

    return bal, new_eff
