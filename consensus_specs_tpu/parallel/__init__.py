"""jax.sharding mesh layouts + the sharded epoch step.

The scale axes of this domain (SURVEY.md §5.7) are validator count and
attestation count; both shard on one `data` mesh axis.  Which array
rides that axis is decided ONCE, by the partition-rule registry
(`parallel.partition`: regex path -> PartitionSpec over the epoch state
pytree, the `match_partition_rules` pattern).  `sharded_epoch_step` is
the "full training step" of this framework: the per-validator epoch
sweep (rewards, slashings, effective balances) fused with the balances-
and registry-list merkleization, `shard_map`ped over the mesh with
psum / all_gather collectives over ICI — its in_specs come from the
rule table, and `partition.partitioned_epoch_step` re-buckets the same
step onto a `device_ids` subset for the mesh-resilience ladder.
"""

from __future__ import annotations

import jax

from .bridge import (  # noqa: F401
    pad_pow2,
    participation_from_pending,
    registry_arrays_from_state,
    validator_static_leaf_words,
)
from .epoch import EpochParams, EpochScalars, RegistryArrays, epoch_sweep  # noqa: F401
from .incremental import (  # noqa: F401
    MerkleForest,
    ShardedMerkleForest,
    SSZProof,
    balances_forest,
    dirty_balance_leaves,
    dirty_chunks_from_validators,
    emit_proofs,
    emit_proofs_async,
    merkleize_dirty,
    merkleize_dirty_async,
    pad_dirty_idx,
    registry_forest,
    sharded_balances_forest,
    verify_proof,
)
from .merkle import (  # noqa: F401
    ValidatorLeaves,
    balances_list_root,
    pack_u64_chunks,
    u64_leaf_words,
    validator_records_root,
    validator_registry_root,
)
from .partition import (  # noqa: F401
    DATA_AXIS,
    EPOCH_STATE_RULES,
    available_devices,
    build_mesh,
    epoch_state_rules,
    epoch_step_dispatcher,
    epoch_step_specs,
    gather_tree,
    match_partition_rules,
    mesh_rung,
    named_tree_leaves,
    partitioned_epoch_step,
    shard_tree,
    sharded_epoch_step,
)


def require_x64() -> None:
    """The sweep/merkle kernels carry Gwei balances and epochs as uint64;
    without `jax_enable_x64` JAX silently downcasts them to uint32.  The
    flag is process-wide, so it is set by *entry points* (bench.py,
    __graft_entry__, tests/conftest.py) — flipping it at import time here
    would retroactively change dtypes under any host application."""
    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "consensus_specs_tpu.parallel needs uint64: enable x64 first "
            '(jax.config.update("jax_enable_x64", True) at process start, '
            "or JAX_ENABLE_X64=1)")

__all__ = [
    "EpochParams", "EpochScalars", "RegistryArrays", "ValidatorLeaves",
    "epoch_sweep", "balances_list_root", "validator_records_root",
    "validator_registry_root", "make_mesh", "shard_registry",
    "make_epoch_step", "make_sharded_epoch_step",
    "registry_arrays_from_state", "validator_static_leaf_words",
    "participation_from_pending", "pad_pow2",
    "MerkleForest", "SSZProof", "balances_forest", "registry_forest",
    "merkleize_dirty", "merkleize_dirty_async", "emit_proofs",
    "emit_proofs_async", "dirty_balance_leaves",
    "dirty_chunks_from_validators", "pad_dirty_idx", "verify_proof",
    # partition-rule registry (parallel.partition)
    "DATA_AXIS", "EPOCH_STATE_RULES", "available_devices", "build_mesh",
    "epoch_state_rules", "epoch_step_dispatcher", "epoch_step_specs",
    "gather_tree", "match_partition_rules", "mesh_rung",
    "named_tree_leaves", "partitioned_epoch_step", "shard_tree",
    "sharded_epoch_step", "ShardedMerkleForest",
    "sharded_balances_forest",
]


def make_mesh(n_devices: int | None = None, axis: str = DATA_AXIS):
    """1-axis device mesh (delegates to `partition.build_mesh`, the one
    mesh builder).  Power-of-two width enforced: the sharded merkle
    reduction needs it (quantize with `mesh_rung`)."""
    return build_mesh(n_devices=n_devices, axis=axis, require_pow2=True)


def shard_registry(mesh, reg: RegistryArrays, axis: str = DATA_AXIS):
    """Place each (N,) registry array sharded on the mesh's data axis —
    the placements come from the partition-rule registry, not per-field
    code."""
    return shard_tree(mesh, reg, epoch_state_rules(axis))


def make_epoch_step(params: EpochParams):
    """Single-device jitted epoch step: sweep + balances root.

    Returns f(reg: RegistryArrays, sc: EpochScalars, length)
         -> (new_bal, new_eff, balances_root_words).
    Registry arrays must be pre-padded to a power-of-two length; `length`
    is the true validator count (for the SSZ length mix-in).
    """
    require_x64()

    @jax.jit
    def step(reg: RegistryArrays, sc: EpochScalars, length):
        new_bal, new_eff = epoch_sweep(reg, sc, params, axis_name=None)
        root = balances_list_root(new_bal, length, axis_name=None)
        return new_bal, new_eff, root

    return step


def make_sharded_epoch_step(mesh, params: EpochParams,
                            axis: str = DATA_AXIS):
    """Mesh-sharded full step (facade over
    `partition.sharded_epoch_step`; the shard_map specs come from the
    partition-rule registry).

    Inputs are sharded (N,) arrays (N divisible by mesh size, power of
    two); `pubkey_root`/`credentials` are the (N, 8) static leaf words.
    Outputs: (new_bal, new_eff, balances_root, registry_root) with the
    roots replicated.
    """
    return sharded_epoch_step(mesh, params, axis=axis)
