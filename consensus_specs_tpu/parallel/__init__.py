"""jax.sharding mesh layouts + the sharded epoch step.

The scale axes of this domain (SURVEY.md §5.7) are validator count and
attestation count; both shard on one `data` mesh axis.  `sharded_epoch_step`
is the "full training step" of this framework: the per-validator epoch sweep
(rewards, slashings, effective balances) fused with the balances- and
registry-list merkleization, `shard_map`ped over the mesh with psum /
all_gather collectives over ICI.
"""

from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from .bridge import (  # noqa: E402, F401
    pad_pow2,
    participation_from_pending,
    registry_arrays_from_state,
    validator_static_leaf_words,
)
from .epoch import EpochParams, EpochScalars, RegistryArrays, epoch_sweep  # noqa: E402, F401
from .merkle import (  # noqa: E402, F401
    ValidatorLeaves,
    balances_list_root,
    pack_u64_chunks,
    u64_leaf_words,
    validator_records_root,
    validator_registry_root,
)

__all__ = [
    "EpochParams", "EpochScalars", "RegistryArrays", "ValidatorLeaves",
    "epoch_sweep", "balances_list_root", "validator_records_root",
    "validator_registry_root", "make_mesh", "shard_registry",
    "make_epoch_step", "make_sharded_epoch_step",
    "registry_arrays_from_state", "validator_static_leaf_words",
    "participation_from_pending", "pad_pow2",
]


def make_mesh(n_devices: int | None = None, axis: str = "data") -> Mesh:
    import numpy as np
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def shard_registry(mesh: Mesh, reg: RegistryArrays, axis: str = "data"):
    """Place each (N,) registry array sharded on the mesh's data axis."""
    sh = NamedSharding(mesh, P(axis))
    return RegistryArrays(*(jax.device_put(a, sh) for a in reg))


def make_epoch_step(params: EpochParams):
    """Single-device jitted epoch step: sweep + balances root.

    Returns f(reg: RegistryArrays, sc: EpochScalars, length)
         -> (new_bal, new_eff, balances_root_words).
    Registry arrays must be pre-padded to a power-of-two length; `length`
    is the true validator count (for the SSZ length mix-in).
    """

    @jax.jit
    def step(reg: RegistryArrays, sc: EpochScalars, length):
        new_bal, new_eff = epoch_sweep(reg, sc, params, axis_name=None)
        root = balances_list_root(new_bal, length, axis_name=None)
        return new_bal, new_eff, root

    return step


def make_sharded_epoch_step(mesh: Mesh, params: EpochParams,
                            axis: str = "data"):
    """Mesh-sharded full step: sweep with psum totals + cross-shard
    proposer-reward scatter + sharded balances/registry merkle roots.

    Inputs are sharded (N,) arrays (N divisible by mesh size, power of two);
    `pubkey_root`/`credentials` are the (N, 8) static leaf words.  Outputs:
    (new_bal, new_eff, balances_root, registry_root) with the roots
    replicated.
    """
    from jax import shard_map

    def _step(reg: RegistryArrays, sc: EpochScalars, length,
              pubkey_root, credentials):
        new_bal, new_eff = epoch_sweep(reg, sc, params, axis_name=axis)
        bal_root = balances_list_root(new_bal, length, axis_name=axis)
        rec_roots = validator_records_root(
            ValidatorLeaves(pubkey_root, credentials), new_eff, reg.slashed,
            reg.activation_eligibility_epoch, reg.activation_epoch,
            reg.exit_epoch, reg.withdrawable_epoch)
        reg_root = validator_registry_root(rec_roots, length, axis_name=axis)
        return new_bal, new_eff, bal_root, reg_root

    data = P(axis)
    repl = P()
    sharded = shard_map(
        _step, mesh=mesh,
        in_specs=(RegistryArrays(*([data] * len(RegistryArrays._fields))),
                  EpochScalars(*([repl] * len(EpochScalars._fields))),
                  repl, data, data),
        out_specs=(data, data, repl, repl),
        check_vma=False)
    return jax.jit(sharded)
