"""jax.sharding mesh layouts + the sharded epoch step.

The scale axes of this domain (SURVEY.md §5.7) are validator count and
attestation count; both shard on one `data` mesh axis.  `sharded_epoch_step`
is the "full training step" of this framework: the per-validator epoch sweep
(rewards, slashings, effective balances) fused with the balances- and
registry-list merkleization, `shard_map`ped over the mesh with psum /
all_gather collectives over ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .bridge import (  # noqa: F401
    pad_pow2,
    participation_from_pending,
    registry_arrays_from_state,
    validator_static_leaf_words,
)
from .epoch import EpochParams, EpochScalars, RegistryArrays, epoch_sweep  # noqa: F401
from .incremental import (  # noqa: F401
    MerkleForest,
    SSZProof,
    balances_forest,
    dirty_balance_leaves,
    dirty_chunks_from_validators,
    emit_proofs,
    emit_proofs_async,
    merkleize_dirty,
    merkleize_dirty_async,
    pad_dirty_idx,
    registry_forest,
    verify_proof,
)
from .merkle import (  # noqa: F401
    ValidatorLeaves,
    balances_list_root,
    pack_u64_chunks,
    u64_leaf_words,
    validator_records_root,
    validator_registry_root,
)


def require_x64() -> None:
    """The sweep/merkle kernels carry Gwei balances and epochs as uint64;
    without `jax_enable_x64` JAX silently downcasts them to uint32.  The
    flag is process-wide, so it is set by *entry points* (bench.py,
    __graft_entry__, tests/conftest.py) — flipping it at import time here
    would retroactively change dtypes under any host application."""
    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "consensus_specs_tpu.parallel needs uint64: enable x64 first "
            '(jax.config.update("jax_enable_x64", True) at process start, '
            "or JAX_ENABLE_X64=1)")

__all__ = [
    "EpochParams", "EpochScalars", "RegistryArrays", "ValidatorLeaves",
    "epoch_sweep", "balances_list_root", "validator_records_root",
    "validator_registry_root", "make_mesh", "shard_registry",
    "make_epoch_step", "make_sharded_epoch_step",
    "registry_arrays_from_state", "validator_static_leaf_words",
    "participation_from_pending", "pad_pow2",
    "MerkleForest", "SSZProof", "balances_forest", "registry_forest",
    "merkleize_dirty", "merkleize_dirty_async", "emit_proofs",
    "emit_proofs_async", "dirty_balance_leaves",
    "dirty_chunks_from_validators", "pad_dirty_idx", "verify_proof",
]


def make_mesh(n_devices: int | None = None, axis: str = "data") -> Mesh:
    import numpy as np
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    assert n & (n - 1) == 0, (
        f"mesh must be a power of two for the sharded merkle reduction, "
        f"got {n} devices (pass n_devices=<largest pow2>)")
    return Mesh(np.array(devs), (axis,))


def shard_registry(mesh: Mesh, reg: RegistryArrays, axis: str = "data"):
    """Place each (N,) registry array sharded on the mesh's data axis."""
    sh = NamedSharding(mesh, P(axis))
    return RegistryArrays(*(jax.device_put(a, sh) for a in reg))


def make_epoch_step(params: EpochParams):
    """Single-device jitted epoch step: sweep + balances root.

    Returns f(reg: RegistryArrays, sc: EpochScalars, length)
         -> (new_bal, new_eff, balances_root_words).
    Registry arrays must be pre-padded to a power-of-two length; `length`
    is the true validator count (for the SSZ length mix-in).
    """
    require_x64()

    @jax.jit
    def step(reg: RegistryArrays, sc: EpochScalars, length):
        new_bal, new_eff = epoch_sweep(reg, sc, params, axis_name=None)
        root = balances_list_root(new_bal, length, axis_name=None)
        return new_bal, new_eff, root

    return step


def make_sharded_epoch_step(mesh: Mesh, params: EpochParams,
                            axis: str = "data"):
    """Mesh-sharded full step: sweep with psum totals + cross-shard
    proposer-reward scatter + sharded balances/registry merkle roots.

    Inputs are sharded (N,) arrays (N divisible by mesh size, power of two);
    `pubkey_root`/`credentials` are the (N, 8) static leaf words.  Outputs:
    (new_bal, new_eff, balances_root, registry_root) with the roots
    replicated.
    """
    require_x64()
    from ..utils.jaxtools import shard_map_compat

    def _step(reg: RegistryArrays, sc: EpochScalars, length,
              pubkey_root, credentials):
        new_bal, new_eff = epoch_sweep(reg, sc, params, axis_name=axis)
        bal_root = balances_list_root(new_bal, length, axis_name=axis)
        rec_roots = validator_records_root(
            ValidatorLeaves(pubkey_root, credentials), new_eff, reg.slashed,
            reg.activation_eligibility_epoch, reg.activation_epoch,
            reg.exit_epoch, reg.withdrawable_epoch)
        reg_root = validator_registry_root(rec_roots, length, axis_name=axis)
        return new_bal, new_eff, bal_root, reg_root

    data = P(axis)
    repl = P()
    sharded = shard_map_compat(
        _step, mesh=mesh,
        in_specs=(RegistryArrays(*([data] * len(RegistryArrays._fields))),
                  EpochScalars(*([repl] * len(EpochScalars._fields))),
                  repl, data, data),
        out_specs=(data, data, repl, repl))
    return jax.jit(sharded)
