"""Device kernels for the proto-array fork-choice store.

LMD-GHOST is two segment-shaped reductions over flat arrays:

apply (``_apply_kernel``)
    a batch of latest-message candidates (validator, target epoch,
    block index) folds into the per-validator latest-message table and
    the per-block weight array in ONE dispatch: a scatter-max picks
    each validator's in-batch winner (highest target epoch, earliest
    arrival on ties — exactly the spec's sequential
    ``update_latest_messages`` outcome), an accept mask applies the
    strictly-greater epoch rule, and the weight deltas (-balance at the
    old vote block, +balance at the new one) land as one scatter-add
    segment-sum.  The strictly-greater rule makes the whole dispatch
    IDEMPOTENT: re-applying a batch after a retry changes nothing,
    which is what lets the serve executor's recovery ladder re-dispatch
    a failed fc batch safely.

head (``_head_kernel``)
    subtree weights via fixed-depth pointer jumping on the parent
    array: with R the parent relation (R[i,j]=1 iff parent[j]==i) and
    w the per-block vote weights (+ proposer boost at the boosted
    block), the subtree sum is sum_{m>=0} R^m w, and

        sum_{m < 2^(k+1)} R^m  =  (sum_{m < 2^k} R^m) (I + R^(2^k))

    so log2(rung) rounds of  ``s += scatter_add(s -> 2^k-th ancestor)``
    with ancestor-pointer squaring settle every subtree sum at once.
    Viability (the spec's ``filter_block_tree``) is the same doubling
    with max: leaf-viability (voting-source epoch + finalized-descent
    checks, evaluated per node on device) ORs up the tree, restricted
    to LEAVES exactly like the reference's recursion.  Best-child
    selection is a masked segment-argmax per parent refined over
    (subtree weight, then the 8 big-endian u32 root limbs — the spec's
    lexicographic tie-break), and the head is the fixpoint of
    pointer-doubling on the best-child functional graph.

Blocks, validators and attestation batches each ride their own
``fc_rung`` ladder so sustained traffic reuses a handful of compiled
shapes (the analyzer's sanctioned compile-key launderer, like
``_bucket``/``mesh_rung``/``das_rung``).  Every array slot ladder
carries ONE extra dummy slot (index == rung) that absorbs masked-out
scatters; it is zeroed between jump rounds and never read.
"""

from __future__ import annotations

import functools

# batch-shape ladders: blocks (a client's protoarray holds hundreds to
# a few thousand unfinalized blocks), validators (committee-scale tests
# up to the mainnet million-validator regime), attestation batches
# (per-pump aggregates)
FC_BLOCK_STEPS = (64, 1024, 16384)
FC_VALIDATOR_STEPS = (256, 4096, 65536, 1048576)
FC_BATCH_STEPS = (64, 1024, 16384)


def fc_rung(n: int, steps=FC_BLOCK_STEPS) -> int:
    """Padded shape for n live rows on the given ladder (the compile-key
    launderer the analyzer recognizes, like `_bucket`/`das_rung`)."""
    b = 1 if n <= 1 else 1 << (n - 1).bit_length()
    for step in steps:
        if b <= step:
            return step
    return b


def _jnp():
    import jax.numpy as jnp
    return jnp


@functools.lru_cache(maxsize=8)
def _apply_kernel(batch: int, v_pad: int, nb_pad: int):
    """Jitted latest-message + weight-delta fold for one padded
    attestation batch.

    Inputs (device):
      val_idx (B,) i32   attesting validator, padded rows -> v_pad
      att_epoch (B,) i64 target epoch, padded rows -> -1
      att_block (B,) i32 vote block index, padded rows -> nb_pad
      lm_epoch (V+1,) i64 / lm_block (V+1,) i32  the latest-message
                         table (-1 == no message); slot V is the dummy
      balance (V+1,) i64 weight-eligible effective balance (zero for
                         inactive/slashed/equivocating validators)
      can_update (V+1,) bool  False for equivocators (their messages
                         freeze, per the spec's update skip)
      node_weight (NB+1,) i64  per-block vote weights; slot NB dummy

    Returns the new (lm_epoch, lm_block, node_weight, accept_mask).
    """
    import jax
    jnp = _jnp()

    def run(val_idx, att_epoch, att_block, lm_epoch, lm_block,
            balance, can_update, node_weight):
        pos = jnp.arange(batch, dtype=jnp.int64)
        # composite in-batch winner key: higher epoch wins, earlier
        # arrival wins ties — the sequential-processing outcome of the
        # spec's strictly-greater update rule
        key = att_epoch * batch + (batch - 1 - pos)
        best = jnp.full(v_pad + 1, -1, dtype=jnp.int64) \
            .at[val_idx].max(key)
        winner = best[val_idx] == key
        accept = (winner
                  & (att_epoch >= 0)
                  & (att_epoch > lm_epoch[val_idx])
                  & can_update[val_idx])
        # at most ONE accepted row per validator (the winner), so the
        # masked set-scatter has no live duplicates; losers write the
        # dummy slot
        tgt = jnp.where(accept, val_idx, v_pad)
        new_lm_epoch = lm_epoch.at[tgt].set(att_epoch)
        new_lm_block = lm_block.at[tgt].set(att_block)
        # weight deltas as one segment-sum: -balance at the old vote
        # block (when one exists), +balance at the new one
        bal = balance[val_idx]
        old_block = lm_block[val_idx]
        sub_tgt = jnp.where(accept & (old_block >= 0), old_block, nb_pad)
        add_tgt = jnp.where(accept, att_block, nb_pad)
        new_weight = node_weight.at[sub_tgt].add(-bal).at[add_tgt].add(bal)
        new_weight = new_weight.at[nb_pad].set(0)
        return new_lm_epoch, new_lm_block, new_weight, accept

    return jax.jit(run)


@functools.lru_cache(maxsize=8)
def _refresh_kernel(v_pad: int, nb_pad: int):
    """Jitted full weight rebuild: node_weight[b] = sum of balances of
    validators whose latest message sits at b — one segment-sum over
    the validator table (the balance/equivocation-change path and the
    degraded-mode device re-sync)."""
    import jax
    jnp = _jnp()

    def run(lm_block, balance):
        has = lm_block >= 0
        tgt = jnp.where(has, lm_block, nb_pad)
        val = jnp.where(has, balance, 0)
        return jnp.zeros(nb_pad + 1, dtype=jnp.int64).at[tgt].add(val)

    return jax.jit(run)


@functools.lru_cache(maxsize=8)
def _head_kernel(nb_pad: int):
    """Jitted LMD-GHOST head selection over one padded block rung.

    Inputs (device, all length NB+1 unless noted):
      parent i32         parent index; anchor and padded rows -> NB
      node_weight i64    per-block vote weights (the apply fold's)
      boost_idx/boost_amt  proposer boost (idx NB + amt 0 when unset)
      real bool          live-row mask
      slots i64, block_epoch i64
      je i64             block state's justified-checkpoint epoch
      uje i64            unrealized (pulled-up) justification epoch
      fin_ok bool        host-maintained finalized-descent flag
      limbs (NB+1, 8) u32  big-endian root words (the tie-break key)
      sj/sf/cur i64 scalars  store justified/finalized/current epochs
      justified_idx i32  walk start

    Returns the head's block index (i32 scalar).
    """
    import jax
    jnp = _jnp()
    rounds = max(int(nb_pad).bit_length() - 1, 1)

    def run(parent, node_weight, boost_idx, boost_amt, real, slots,
            block_epoch, je, uje, fin_ok, limbs, sj, sf, cur,
            justified_idx):
        del slots   # kept in the signature for costmodel symmetry
        w = node_weight.at[boost_idx].add(boost_amt)
        w = jnp.where(real, w, 0)

        # subtree weight sums: s += scatter(s -> 2^k-th ancestor),
        # ancestor pointers square each round; the dummy slot absorbs
        # the past-the-root flow and is re-zeroed so it cannot overflow
        s = w
        ptr = parent
        for _ in range(rounds):
            s = s.at[ptr].add(s)
            s = s.at[nb_pad].set(0)
            ptr = ptr[ptr]

        # leaf viability (filter_block_tree's leaf predicate), then the
        # same doubling with max = subtree-OR over the LEAVES below
        vs = jnp.where(block_epoch < cur, uje, je)
        vs_ok = (sj == 0) | (vs == sj) | (vs + 2 >= cur)
        f_ok = (sf == 0) | fin_ok
        has_child = jnp.zeros(nb_pad + 1, dtype=jnp.int32) \
            .at[parent].max(real.astype(jnp.int32))
        leaf_pred = (vs_ok & f_ok & real
                     & (has_child == 0)).astype(jnp.int32)
        vsub = leaf_pred
        ptr = parent
        for _ in range(rounds):
            vsub = vsub.at[ptr].max(vsub)
            ptr = ptr[ptr]

        # best child per parent: segment-argmax refined over subtree
        # weight then the 8 big-endian root limbs (the lexicographic
        # tie-break); after refinement at most one candidate per parent
        # survives (roots are distinct)
        cand = real & (vsub > 0) & (parent < nb_pad)
        mx = jnp.full(nb_pad + 1, -1, dtype=jnp.int64) \
            .at[jnp.where(cand, parent, nb_pad)].max(s)
        cand = cand & (s == mx[parent])
        for limb in range(8):
            lv = limbs[:, limb].astype(jnp.int64)
            ml = jnp.full(nb_pad + 1, -1, dtype=jnp.int64) \
                .at[jnp.where(cand, parent, nb_pad)].max(lv)
            cand = cand & (lv == ml[parent])

        idx = jnp.arange(nb_pad + 1, dtype=jnp.int32)
        best_child = idx.at[jnp.where(cand, parent, nb_pad)].set(idx)
        # head = fixpoint of pointer-doubling on best_child (child
        # indices strictly exceed their parent's, so the graph only
        # walks down and 2^rounds jumps cover any chain in the rung)
        bc = best_child
        for _ in range(rounds):
            bc = bc[bc]
        return bc[justified_idx]

    return jax.jit(run)
