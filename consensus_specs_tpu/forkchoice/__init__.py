"""Fork-choice subsystem — device-batched LMD-GHOST on a proto-array
store.

The fourth heavy consensus workload on the device path (after state
transition, KZG/blob verification and PeerDAS cells): per-attestation
latest-message folding and head selection as flat-array segment
reductions.

    kernels   the `fc_rung` shape ladder + the three jitted kernels
              (latest-message/weight fold, full weight refresh,
              pointer-jumping head selection)
    store     `ProtoArrayStore` — device arrays + bit-equivalent host
              mirror, async facades through `serve.futures`
    oracle    the phase0 executable-spec referee (`spec_get_head` over
              a synthesized Store) — parity target and the serve
              executor's degraded-mode fallback
    bridge    executable-spec Store -> proto store projection (the
              fork-choice vector generator's seam)

Serving: `ServeExecutor.submit_attestation_batch` (queued batches fold
into ONE device dispatch per pump) and `submit_head_request` (the
`head` request kind); loadgen drives them at `CST_FC_ATTS_PER_SLOT`.
Bench: `bench.py --worker forkchoice` sweeps `CST_FC_MATRIX`, emitting
`forkchoice::*` benchwatch records gated by the `fc-speedup` /
`fc-head-throughput` threshold rows (`make fc-smoke` pins the CPU
contract).
"""

from .kernels import (
    FC_BATCH_STEPS,
    FC_BLOCK_STEPS,
    FC_VALIDATOR_STEPS,
    fc_rung,
)
from .store import ProtoArrayStore

__all__ = [
    "FC_BATCH_STEPS",
    "FC_BLOCK_STEPS",
    "FC_VALIDATOR_STEPS",
    "ProtoArrayStore",
    "fc_rung",
]
