"""The proto-array fork-choice store — flat device arrays + host mirror.

``ProtoArrayStore`` is the device-resident LMD-GHOST state every client
runs per attestation (the Lighthouse proto-array layout, device-shaped):

per-block arrays (appended in insertion order, so a parent's index is
always below its children's — the property the head kernel's
pointer-jumping relies on):
    parent index, slot, block epoch, justified epoch (the block
    state's), unrealized justified epoch (the pulled-up tip), the
    8 big-endian u32 root words (the tie-break key), and the
    host-maintained finalized-descent flag;

per-validator arrays:
    the latest-message table (target epoch, vote block index),
    weight-eligible balances, and the can-update mask (equivocators
    freeze);

plus the per-block vote-weight array the apply fold maintains.

Two routes, one state:

device  ``apply_attestations_async`` / ``get_head_async`` dispatch the
        ``forkchoice.kernels`` segment reductions and settle through
        `serve.futures.DeviceFuture` (the sanctioned settle seam).
host    ``apply_attestations_host`` / ``get_head_host`` answer on the
        HOST mirror — head selection runs the actual phase0 spec
        oracle's ``get_head`` over a Store synthesized from the mirror
        (`forkchoice.oracle`), which makes this route both the parity
        referee and the serve executor's degraded-mode fallback when
        the fork-choice breaker is open.

Consistency contract: the host mirror plus the pending-batch queue is
always bit-equivalent to the device arrays (the numpy fold in
``_fold_host`` implements the exact kernel rule, pinned by
tests/test_forkchoice.py), so the store can rebuild its device state
from the mirror at any time — after a rung regrowth, after degraded-
mode host applies, or after a poisoned device dispatch.  The
strictly-greater update rule makes re-applying a batch a no-op, so the
serve executor's retry ladder can re-dispatch a failed fc batch
without double-counting weights.
"""

from __future__ import annotations

import hashlib

import numpy as np

from .. import telemetry
from ..resilience import faults
from ..serve.futures import DeviceFuture, value_future
from ..telemetry import costmodel
from .kernels import (
    FC_BATCH_STEPS,
    FC_BLOCK_STEPS,
    FC_VALIDATOR_STEPS,
    _apply_kernel,
    _head_kernel,
    _refresh_kernel,
    fc_rung,
)

_GENESIS_EPOCH = 0


def _root_limbs(root: bytes) -> np.ndarray:
    """32-byte root -> 8 big-endian u32 words (lexicographic compare
    over the words == bytes compare over the root)."""
    return np.frombuffer(root, dtype=">u4").astype(np.uint32)


class ProtoArrayStore:
    """See the module docstring.  ``preset`` names the spec namespace
    the host oracle route builds lazily (`forkchoice.oracle`); the
    device path itself is spec-build-free."""

    def __init__(self, anchor_root: bytes, anchor_slot: int = 0, *,
                 justified_epoch: int = 0, finalized_epoch: int = 0,
                 slots_per_epoch: int = 32, proposer_boost_pct: int = 40,
                 effective_balance_increment: int = 10 ** 9,
                 preset: str = "mainnet"):
        anchor_root = bytes(anchor_root)
        assert len(anchor_root) == 32
        self.slots_per_epoch = int(slots_per_epoch)
        self.proposer_boost_pct = int(proposer_boost_pct)
        self.effective_balance_increment = int(effective_balance_increment)
        self.preset = preset

        # per-block host state (python lists; pushed to device on demand)
        self.roots: list[bytes] = [anchor_root]
        self.root_index: dict[bytes, int] = {anchor_root: 0}
        self.parent: list[int] = [-1]
        self.slots: list[int] = [int(anchor_slot)]
        self.je: list[int] = [int(justified_epoch)]
        self.uje: list[int] = [int(justified_epoch)]

        # checkpoints + clock
        self.justified_epoch = int(justified_epoch)
        self.justified_root = anchor_root
        self.finalized_epoch = int(finalized_epoch)
        self.finalized_root = anchor_root
        self.current_epoch = int(anchor_slot) // self.slots_per_epoch
        self.proposer_boost_root: bytes | None = None

        # per-validator host state (empty until set_validators)
        self._eb = np.zeros(0, dtype=np.int64)
        self._active = np.zeros(0, dtype=bool)
        self._slashed = np.zeros(0, dtype=bool)
        self._equiv = np.zeros(0, dtype=bool)
        self._lm_epoch = np.zeros(0, dtype=np.int64)
        self._lm_block = np.zeros(0, dtype=np.int32)

        # pending device-applied batches not yet folded into the mirror
        self._pending: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []

        self._fin_ok = [True]       # finalized-descent flags, per block
        self._recompute_finalized_ok()

        # device state (built lazily; None == stale)
        self._dev = None            # dict of device arrays
        self._blk_dev = None        # dict of per-block device arrays

    # --- host-side structure mutation ---------------------------------------

    @property
    def n_blocks(self) -> int:
        return len(self.roots)

    @property
    def n_validators(self) -> int:
        return int(self._eb.shape[0])

    def add_block(self, root: bytes, parent_root: bytes, slot: int,
                  justified_epoch: int,
                  unrealized_justified_epoch: int | None = None) -> int:
        """Append one block (parents must already be present, children
        arrive after their parents — the on_block arrival order).
        Returns the block's index."""
        root = bytes(root)
        parent_root = bytes(parent_root)
        assert root not in self.root_index, "duplicate block root"
        pidx = self.root_index[parent_root]
        slot = int(slot)
        assert slot > self.slots[pidx], \
            "child slot must exceed its parent's"
        idx = len(self.roots)
        old_rung = fc_rung(idx, FC_BLOCK_STEPS)
        self.roots.append(root)
        self.root_index[root] = idx
        self.parent.append(pidx)
        self.slots.append(slot)
        self.je.append(int(justified_epoch))
        self.uje.append(int(justified_epoch
                            if unrealized_justified_epoch is None
                            else unrealized_justified_epoch))
        self._fin_ok.append(self._fin_ok_for(idx))
        self._blk_dev = None
        if fc_rung(idx + 1, FC_BLOCK_STEPS) != old_rung:
            # the weight array must re-pad: rebuild from the mirror
            self._dev = None
        elif self._dev is not None:
            # same rung: the existing weight array already covers idx
            pass
        return idx

    def set_validators(self, effective_balances, active=None,
                       slashed=None, equivocating=None) -> None:
        """(Re)bind the validator set — effective balances in Gwei plus
        the activity/slashing/equivocation masks the spec's weight
        accumulation reads from the justified-checkpoint state.
        Existing latest messages survive up to min(old, new) size."""
        eb = np.asarray(effective_balances, dtype=np.int64)
        n = int(eb.shape[0])

        def mask(m, default):
            if m is None:
                return np.full(n, default, dtype=bool)
            m = np.asarray(m, dtype=bool)
            assert m.shape == (n,)
            return m.copy()

        self._sync_pending()
        keep = min(n, self.n_validators)
        lm_e = np.full(n, -1, dtype=np.int64)
        lm_b = np.full(n, -1, dtype=np.int32)
        lm_e[:keep] = self._lm_epoch[:keep]
        lm_b[:keep] = self._lm_block[:keep]
        self._eb = eb.copy()
        self._active = mask(active, True)
        self._slashed = mask(slashed, False)
        self._equiv = mask(equivocating, False)
        self._lm_epoch = lm_e
        self._lm_block = lm_b
        self._dev = None

    def mark_equivocators(self, indices) -> None:
        """Freeze the given validators' latest messages and remove
        their weight (the on_attester_slashing consequence)."""
        self._sync_pending()
        idx = np.asarray(list(indices), dtype=np.int64)
        if idx.size:
            self._equiv[idx] = True
            self._dev = None

    def set_checkpoints(self, justified_epoch: int, justified_root: bytes,
                        finalized_epoch: int,
                        finalized_root: bytes) -> None:
        self.justified_epoch = int(justified_epoch)
        self.justified_root = bytes(justified_root)
        self.finalized_epoch = int(finalized_epoch)
        self.finalized_root = bytes(finalized_root)
        self._recompute_finalized_ok()
        self._blk_dev = None

    def set_current_epoch(self, epoch: int) -> None:
        self.current_epoch = int(epoch)

    def set_proposer_boost(self, root: bytes | None) -> None:
        self.proposer_boost_root = bytes(root) if root else None

    def proposer_score(self) -> int:
        """The spec's get_proposer_score over the bound validator set:
        (total active balance / SLOTS_PER_EPOCH) * boost% / 100, with
        the EFFECTIVE_BALANCE_INCREMENT floor of
        get_total_active_balance."""
        total = int(self._eb[self._active].sum())
        total = max(self.effective_balance_increment, total)
        return (total // self.slots_per_epoch
                * self.proposer_boost_pct) // 100

    # --- finalized-descent maintenance --------------------------------------

    def _fin_ok_for(self, idx: int) -> bool:
        """The spec's get_checkpoint_block(root, finalized_epoch) ==
        finalized_root check, resolved incrementally: the ancestor at
        the finalized boundary slot is the node itself when its slot is
        at or below the boundary, else its parent's ancestor."""
        fin_idx = self.root_index.get(self.finalized_root)
        if fin_idx is None:
            return False
        fin_slot = self.finalized_epoch * self.slots_per_epoch
        j = idx
        while self.slots[j] > fin_slot:
            j = self.parent[j]
            if j < 0:
                return False
        return j == fin_idx

    def _recompute_finalized_ok(self) -> None:
        fin_idx = self.root_index.get(self.finalized_root)
        fin_slot = self.finalized_epoch * self.slots_per_epoch
        out = [False] * len(self.roots)
        anc = [0] * len(self.roots)
        for i in range(len(self.roots)):
            if self.slots[i] <= fin_slot or self.parent[i] < 0:
                anc[i] = i
            else:
                anc[i] = anc[self.parent[i]]
            out[i] = fin_idx is not None and anc[i] == fin_idx
        self._fin_ok = out

    # --- the host mirror (the kernel rule in numpy) -------------------------

    def _weight_balance(self) -> np.ndarray:
        """Per-validator weight-eligible balance: active, unslashed,
        non-equivocating — the spec's get_weight filter."""
        return np.where(self._active & ~self._slashed & ~self._equiv,
                        self._eb, 0).astype(np.int64)

    def _fold_host(self, idx: np.ndarray, ep: np.ndarray,
                   blk: np.ndarray) -> int:
        """Fold one batch into the mirror with the EXACT kernel rule
        (in-batch winner by (epoch, earliest position), then the
        strictly-greater update); returns the accepted count."""
        b = int(idx.shape[0])
        if b == 0:
            return 0
        pos = np.arange(b, dtype=np.int64)
        key = ep * b + (b - 1 - pos)
        best = np.full(self.n_validators, -1, dtype=np.int64)
        np.maximum.at(best, idx, key)
        winner = best[idx] == key
        accept = (winner & (ep > self._lm_epoch[idx])
                  & ~self._equiv[idx])
        self._lm_epoch[idx[accept]] = ep[accept]
        self._lm_block[idx[accept]] = blk[accept]
        return int(np.count_nonzero(accept))

    def _sync_pending(self) -> None:
        pending, self._pending = self._pending, []
        for idx, ep, blk in pending:
            self._fold_host(idx, ep, blk)

    def node_weights_host(self) -> np.ndarray:
        """Per-block vote weights recomputed from the mirror (the
        refresh kernel's rule)."""
        self._sync_pending()
        w = np.zeros(self.n_blocks, dtype=np.int64)
        has = self._lm_block >= 0
        np.add.at(w, self._lm_block[has],
                  self._weight_balance()[has])
        return w

    def fingerprint(self) -> bytes:
        """Canonical digest of the full host state (the conftest memo
        key for repeated spec-oracle head evaluations)."""
        self._sync_pending()
        h = hashlib.sha256()
        h.update(b"".join(self.roots))
        h.update(np.asarray(self.parent, dtype=np.int64).tobytes())
        h.update(np.asarray(self.slots, dtype=np.int64).tobytes())
        h.update(np.asarray(self.je, dtype=np.int64).tobytes())
        h.update(np.asarray(self.uje, dtype=np.int64).tobytes())
        h.update(np.asarray(self._fin_ok, dtype=bool).tobytes())
        for arr in (self._eb, self._active, self._slashed, self._equiv,
                    self._lm_epoch, self._lm_block):
            h.update(arr.tobytes())
        h.update(repr((self.justified_epoch, self.justified_root,
                       self.finalized_epoch, self.finalized_root,
                       self.current_epoch, self.proposer_boost_root,
                       self.slots_per_epoch, self.proposer_boost_pct,
                       self.effective_balance_increment,
                       self.preset)).encode())
        return h.digest()

    # --- device state --------------------------------------------------------

    def _v_pad(self) -> int:
        return fc_rung(self.n_validators, FC_VALIDATOR_STEPS)

    def _nb_pad(self) -> int:
        return fc_rung(self.n_blocks, FC_BLOCK_STEPS)

    def _ensure_device(self) -> None:
        """(Re)build the validator/weight device arrays from the host
        mirror when stale — after construction, a rung regrowth, a
        validator rebind, or a degraded-mode host apply."""
        if self._dev is not None:
            return
        import jax.numpy as jnp

        self._sync_pending()
        v_pad, nb_pad = self._v_pad(), self._nb_pad()
        lm_e = np.full(v_pad + 1, -1, dtype=np.int64)
        lm_b = np.full(v_pad + 1, -1, dtype=np.int32)
        bal = np.zeros(v_pad + 1, dtype=np.int64)
        can = np.zeros(v_pad + 1, dtype=bool)
        n = self.n_validators
        lm_e[:n] = self._lm_epoch
        lm_b[:n] = self._lm_block
        bal[:n] = self._weight_balance()
        can[:n] = ~self._equiv
        d_lm_b = jnp.asarray(lm_b)
        d_bal = jnp.asarray(bal)
        with telemetry.span("fc.refresh", validators=n, padded=v_pad):
            telemetry.count("fc.refresh.calls")
            kfn = _refresh_kernel(v_pad, nb_pad)
            weight = kfn(d_lm_b, d_bal)
        costmodel.capture(f"fc_refresh@v{v_pad}", kfn, (d_lm_b, d_bal))
        self._dev = {
            "lm_epoch": jnp.asarray(lm_e), "lm_block": d_lm_b,
            "balance": d_bal, "can_update": jnp.asarray(can),
            "weight": weight, "v_pad": v_pad, "nb_pad": nb_pad,
        }

    def _ensure_block_device(self) -> None:
        if self._blk_dev is not None \
                and self._blk_dev["nb_pad"] == self._nb_pad():
            return
        import jax.numpy as jnp

        nb_pad = self._nb_pad()
        n = self.n_blocks
        parent = np.full(nb_pad + 1, nb_pad, dtype=np.int32)
        par = np.asarray(self.parent, dtype=np.int32)
        parent[:n] = np.where(par >= 0, par, nb_pad)
        real = np.zeros(nb_pad + 1, dtype=bool)
        real[:n] = True
        slots = np.zeros(nb_pad + 1, dtype=np.int64)
        slots[:n] = self.slots
        bep = np.zeros(nb_pad + 1, dtype=np.int64)
        bep[:n] = np.asarray(self.slots, dtype=np.int64) \
            // self.slots_per_epoch
        je = np.zeros(nb_pad + 1, dtype=np.int64)
        je[:n] = self.je
        uje = np.zeros(nb_pad + 1, dtype=np.int64)
        uje[:n] = self.uje
        fin = np.zeros(nb_pad + 1, dtype=bool)
        fin[:n] = self._fin_ok
        limbs = np.zeros((nb_pad + 1, 8), dtype=np.uint32)
        limbs[:n] = np.stack([_root_limbs(r) for r in self.roots])
        self._blk_dev = {
            "parent": jnp.asarray(parent), "real": jnp.asarray(real),
            "slots": jnp.asarray(slots), "block_epoch": jnp.asarray(bep),
            "je": jnp.asarray(je), "uje": jnp.asarray(uje),
            "fin_ok": jnp.asarray(fin), "limbs": jnp.asarray(limbs),
            "nb_pad": nb_pad,
        }

    # --- the device route ----------------------------------------------------

    def _parse_batch(self, validator_indices, target_epochs, block_roots):
        idx = np.asarray(list(validator_indices), dtype=np.int32)
        ep = np.asarray(list(target_epochs), dtype=np.int64)
        assert idx.shape == ep.shape and idx.ndim == 1
        assert idx.size == len(block_roots)
        if idx.size and (idx.min() < 0 or idx.max() >= self.n_validators):
            raise KeyError("attesting validator index out of range")
        assert not idx.size or ep.min() >= 0, "negative target epoch"
        blk = np.asarray([self.root_index[bytes(r)] for r in block_roots],
                         dtype=np.int32)
        return idx, ep, blk

    def apply_attestations_async(self, validator_indices, target_epochs,
                                 block_roots) -> DeviceFuture:
        """Batched latest-message updates + weight deltas as ONE device
        dispatch.  Settles to the live accept mask (numpy bool, one per
        message) — the serve executor splits it per request; the sync
        facade folds it to a count.  Unknown roots / out-of-range
        validators raise eagerly (the executor poisons exactly that
        handle)."""
        idx, ep, blk = self._parse_batch(validator_indices, target_epochs,
                                         block_roots)
        self._ensure_device()
        import jax.numpy as jnp

        b_live = int(idx.size)
        rung = fc_rung(b_live, FC_BATCH_STEPS)
        v_pad, nb_pad = self._dev["v_pad"], self._dev["nb_pad"]
        if faults.active():
            faults.maybe_inject("dispatch", f"fc_weights@b{rung}v{v_pad}")
        pad = rung - b_live
        d_idx = jnp.asarray(np.concatenate(
            [idx, np.full(pad, v_pad, dtype=np.int32)]))
        d_ep = jnp.asarray(np.concatenate(
            [ep, np.full(pad, -1, dtype=np.int64)]))
        d_blk = jnp.asarray(np.concatenate(
            [blk, np.full(pad, nb_pad, dtype=np.int32)]))
        with telemetry.span("fc.apply", messages=b_live, padded=rung):
            telemetry.count("fc.apply.calls")
            telemetry.count("fc.apply.messages", b_live)
            telemetry.count("fc.apply.padded", rung)
            kfn = _apply_kernel(rung, v_pad, nb_pad)
            args = (d_idx, d_ep, d_blk, self._dev["lm_epoch"],
                    self._dev["lm_block"], self._dev["balance"],
                    self._dev["can_update"], self._dev["weight"])
            lm_e, lm_b, weight, accept = kfn(*args)
        costmodel.capture(f"fc_weights@b{rung}v{v_pad}", kfn, args)
        # the store advances immediately (no sync); the mirror catches
        # up lazily via the pending queue
        self._dev["lm_epoch"] = lm_e
        self._dev["lm_block"] = lm_b
        self._dev["weight"] = weight
        self._pending.append((idx, ep, blk))
        return value_future(
            accept, convert=lambda m: np.asarray(m)[:b_live])

    def apply_attestations(self, validator_indices, target_epochs,
                           block_roots) -> int:
        """Synchronous facade: the number of accepted updates."""
        mask = self.apply_attestations_async(
            validator_indices, target_epochs, block_roots).result()
        return int(np.count_nonzero(mask))

    def get_head_async(self) -> DeviceFuture:
        """LMD-GHOST head over the viable tree, one device dispatch;
        settles to the head's 32-byte root."""
        if self.justified_root not in self.root_index:
            raise KeyError("justified root not in the store")
        self._ensure_device()
        self._ensure_block_device()
        import jax.numpy as jnp

        nb_pad = self._blk_dev["nb_pad"]
        if self._dev["nb_pad"] != nb_pad:
            self._dev = None
            self._ensure_device()
        if faults.active():
            faults.maybe_inject("dispatch", f"fc_head@{nb_pad}")
        boost_idx = nb_pad
        boost_amt = 0
        if self.proposer_boost_root is not None \
                and self.proposer_boost_root in self.root_index:
            boost_idx = self.root_index[self.proposer_boost_root]
            boost_amt = self.proposer_score()
        bd = self._blk_dev
        with telemetry.span("fc.head", blocks=self.n_blocks,
                            padded=nb_pad):
            telemetry.count("fc.head.calls")
            kfn = _head_kernel(nb_pad)
            args = (bd["parent"], self._dev["weight"],
                    jnp.int32(boost_idx), jnp.int64(boost_amt),
                    bd["real"], bd["slots"], bd["block_epoch"],
                    bd["je"], bd["uje"], bd["fin_ok"], bd["limbs"],
                    jnp.int64(self.justified_epoch),
                    jnp.int64(self.finalized_epoch),
                    jnp.int64(self.current_epoch),
                    jnp.int32(self.root_index[self.justified_root]))
            head_idx = kfn(*args)
        costmodel.capture(f"fc_head@{nb_pad}", kfn, args)
        return value_future(head_idx,
                            convert=lambda h: self.roots[int(h)])

    def get_head(self) -> bytes:
        """Synchronous facade over `get_head_async`."""
        return self.get_head_async().result()

    # --- the host (spec-oracle) route ----------------------------------------

    def apply_attestations_host(self, validator_indices, target_epochs,
                                block_roots) -> int:
        """Degraded-mode message application: folds into the host
        mirror only (exact kernel rule) and marks the device arrays
        stale, so the next healthy device dispatch rebuilds from the
        mirror."""
        idx, ep, blk = self._parse_batch(validator_indices, target_epochs,
                                         block_roots)
        self._sync_pending()
        accepted = self._fold_host(idx, ep, blk)
        self._dev = None
        return accepted

    def get_head_host(self) -> bytes:
        """Head by the actual phase0 spec oracle's get_head over a
        Store synthesized from the host mirror (`forkchoice.oracle`) —
        the parity referee and the breaker's degraded mode."""
        from . import oracle

        self._sync_pending()
        return oracle.spec_get_head(self)
