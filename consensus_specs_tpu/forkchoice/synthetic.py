"""Synthetic fork-choice workloads — the ONE builder the bench worker
and the serve loadgen share.

Both drive the same shape of traffic (a seeded random block tree, an
all-active 32 ETH validator set, and an attestation stream whose target
epochs climb one per batch so latest-message updates keep being
accepted at sustained load); keeping a single implementation means a
change to the store's constructor or the fold's accept semantics can
never skew one workload silently while the other is fixed.
"""

from __future__ import annotations

import numpy as np

from .store import ProtoArrayStore


def synthetic_store(n_blocks: int, n_validators: int, seed: int = 29,
                    slots_per_epoch: int = 32,
                    preset: str = "mainnet"):
    """(store, roots): a seeded random tree (every non-anchor block's
    parent drawn uniformly among its predecessors, child slot =
    parent slot + 1) over an all-active 32 ETH validator set, with the
    clock one epoch past the newest block."""
    rng = np.random.RandomState(seed)
    anchor = b"\x41" + b"\x00" * 31
    store = ProtoArrayStore(anchor, 0, slots_per_epoch=slots_per_epoch,
                            preset=preset)
    roots = [anchor]
    for i in range(1, n_blocks):
        parent = roots[rng.randint(0, i)]
        slot = store.slots[store.root_index[parent]] + 1
        root = bytes(rng.randint(0, 256, 32, dtype=np.uint8))
        store.add_block(root, parent, slot, 0, 0)
        roots.append(root)
    store.set_validators(np.full(n_validators, 32 * 10 ** 9,
                                 dtype=np.int64))
    store.set_current_epoch(max(store.slots) // slots_per_epoch + 1)
    return store, roots


def attestation_stream(roots, n_validators: int, batch: int,
                       seed: int = 29):
    """Infinite (validator_indices, target_epochs, block_roots) batch
    stream: uniform validators and vote blocks, epochs climbing one
    per batch (so the strictly-greater rule keeps accepting)."""
    rng = np.random.RandomState(seed + 1)
    epoch = 1
    while True:
        idx = rng.randint(0, n_validators, batch)
        blk = [roots[rng.randint(0, len(roots))] for _ in range(batch)]
        yield (idx.tolist(), [epoch] * batch, blk)
        epoch += 1
