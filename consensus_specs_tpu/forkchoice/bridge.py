"""Spec-store -> proto-array adapter (the vector generator's seam).

The fork-choice test suites drive the executable spec's event-sourced
``Store`` (on_tick / on_block / on_attestation with real blocks and
real state transitions).  ``proto_from_spec_store`` projects that Store
into a device ``ProtoArrayStore`` — blocks in parent-before-child
order, the justified-checkpoint validator set, the latest-message
table, checkpoints, clock and proposer boost — and ``device_head``
answers ``get_head`` on the device path for it.

``tests/phase0/fork_choice/test_device_store.py`` uses this to emit
reference-format fork-choice vectors whose head checks are the DEVICE
store's decisions, each asserted bit-identical to the spec oracle's
``get_head`` before it is written — so a vector consumer replays
device-made decisions that the oracle co-signed.
"""

from __future__ import annotations

import numpy as np


def proto_from_spec_store(spec, store):
    """Project an executable-spec Store into a ProtoArrayStore (one
    shot; rebuild per head check — the vector suites' trees are small,
    and a fresh projection cannot drift from the Store)."""
    from .store import ProtoArrayStore

    ordered = sorted(store.blocks.items(),
                     key=lambda kv: (int(kv[1].slot), bytes(kv[0])))
    anchors = [(root, blk) for root, blk in ordered
               if spec.Root(blk.parent_root) not in store.blocks]
    assert len(anchors) == 1, "expected exactly one anchor block"
    anchor_root, anchor_block = anchors[0]

    def _uje(root):
        return int(store.unrealized_justifications[root].epoch)

    def _je(root):
        return int(store.block_states[root]
                   .current_justified_checkpoint.epoch)

    proto = ProtoArrayStore(
        bytes(anchor_root), int(anchor_block.slot),
        justified_epoch=_je(anchor_root),
        slots_per_epoch=int(spec.SLOTS_PER_EPOCH),
        proposer_boost_pct=int(spec.config.PROPOSER_SCORE_BOOST),
        effective_balance_increment=int(spec.EFFECTIVE_BALANCE_INCREMENT),
        preset=str(spec.config.PRESET_BASE),
    )
    proto.uje[0] = _uje(anchor_root)
    for root, blk in ordered:
        if root == anchor_root:
            continue
        proto.add_block(bytes(root), bytes(blk.parent_root),
                        int(blk.slot), _je(root), _uje(root))

    # the justified-checkpoint state is the weight source (the spec's
    # get_weight reads balances + the active set off it); synthesize it
    # the way store_target_checkpoint_state would when an attestation
    # has not pinned it yet
    cp = store.justified_checkpoint
    state = store.checkpoint_states.get(cp)
    if state is None:
        state = store.block_states[cp.root].copy()
        boundary = spec.compute_start_slot_at_epoch(cp.epoch)
        if state.slot < boundary:
            spec.process_slots(state, boundary)
    epoch = spec.get_current_epoch(state)
    n = len(state.validators)
    eb = np.zeros(n, dtype=np.int64)
    active = np.zeros(n, dtype=bool)
    slashed = np.zeros(n, dtype=bool)
    equiv = np.zeros(n, dtype=bool)
    for i, v in enumerate(state.validators):
        eb[i] = int(v.effective_balance)
        active[i] = spec.is_active_validator(v, epoch)
        slashed[i] = bool(v.slashed)
    for i in store.equivocating_indices:
        if int(i) < n:
            equiv[int(i)] = True
    proto.set_validators(eb, active=active, slashed=slashed,
                         equivocating=equiv)

    proto.set_checkpoints(int(cp.epoch), bytes(cp.root),
                          int(store.finalized_checkpoint.epoch),
                          bytes(store.finalized_checkpoint.root))
    proto.set_current_epoch(int(spec.get_current_store_epoch(store)))
    boost = bytes(store.proposer_boost_root)
    proto.set_proposer_boost(boost if any(boost) else None)

    # replay the latest-message table as one batch (the fold's accept
    # rule is a no-op filter here: the table is already per-validator
    # latest)
    items = sorted(store.latest_messages.items(), key=lambda kv: int(kv[0]))
    if items:
        proto.apply_attestations(
            [int(v) for v, _ in items],
            [int(m.epoch) for _, m in items],
            [bytes(m.root) for _, m in items])
    return proto


def device_head(spec, store) -> bytes:
    """The DEVICE store's head for an executable-spec Store."""
    return proto_from_spec_store(spec, store).get_head()
