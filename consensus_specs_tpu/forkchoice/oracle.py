"""The spec-oracle route: phase0 ``get_head`` over a synthesized Store.

The proto-array store's host mirror carries exactly the facts the spec
oracle's fork choice reads — blocks (slot, parent, root), the
latest-message table, the justified-checkpoint validator set, the
checkpoint/boost state.  ``spec_store_for`` lifts that mirror into a
genuine ``spec.Store`` (the executable-spec dataclass from
`models/phase0/fork_choice.py`) and ``spec_get_head`` runs THE SPEC'S
``get_head`` on it — the heaviest-possible referee: every weight, every
viability filter and every tie-break decision comes from the oracle
code path, not a re-implementation.  This is the parity target of
tests/test_forkchoice.py and the serve executor's degraded-mode
fallback for the ``head`` request kind.

Synthesis notes:

- ``store.blocks`` is keyed by the proto store's root BYTES; the spec's
  walk never re-hashes blocks, it follows ``parent_root`` through the
  dict — so lightweight ``spec.BeaconBlock(slot, parent_root)`` rows
  suffice.
- ``block_states`` entries only serve ``get_voting_source`` (the
  current-epoch branch reads ``current_justified_checkpoint``), so each
  is a minimal shim carrying that one checkpoint; the justified
  CHECKPOINT state is a real ``spec.BeaconState`` (``get_weight`` and
  ``get_proposer_score`` read balances and the active set off it).
- ``update_latest_messages`` (the spec's message fold) is exposed via
  ``spec_apply_messages`` so the tests can pin the store's batched
  fold against the oracle's sequential rule message-for-message.

``spec_get_head`` is the seam the tier-1 conftest memoizes (keyed on
``ProtoArrayStore.fingerprint()``): randomized parity suites re-evaluate
identical stores across tests, and one oracle evaluation per distinct
store state keeps the budget flat.
"""

from __future__ import annotations

import hashlib
from types import SimpleNamespace


def _build_spec(proto):
    from ..models.builder import build_spec

    spec = build_spec("phase0", proto.preset)
    assert int(spec.SLOTS_PER_EPOCH) == proto.slots_per_epoch, \
        (f"preset {proto.preset} has SLOTS_PER_EPOCH="
         f"{int(spec.SLOTS_PER_EPOCH)}, the store was built with "
         f"{proto.slots_per_epoch}")
    assert int(spec.config.PROPOSER_SCORE_BOOST) \
        == proto.proposer_boost_pct
    assert int(spec.EFFECTIVE_BALANCE_INCREMENT) \
        == proto.effective_balance_increment
    return spec


def _checkpoint_state(spec, proto):
    """A real BeaconState at the justified boundary whose validator
    registry reproduces the store's (balance, active, slashed) rows."""
    validators = []
    far = spec.FAR_FUTURE_EPOCH
    for eb, act, sl in zip(proto._eb, proto._active, proto._slashed):
        validators.append(spec.Validator(
            effective_balance=int(eb),
            slashed=bool(sl),
            activation_eligibility_epoch=0,
            activation_epoch=0 if act else far,
            exit_epoch=far,
            withdrawable_epoch=far,
        ))
    return spec.BeaconState(
        slot=spec.compute_start_slot_at_epoch(
            spec.Epoch(proto.justified_epoch)),
        validators=validators,
    )


def spec_store_for(proto, spec=None):
    """(spec, Store) — the executable-spec Store synthesized from the
    proto store's host mirror (pending device-applied batches fold in
    first, so the synthesis always sees the full message table)."""
    proto._sync_pending()
    if spec is None:
        spec = _build_spec(proto)
    seconds = int(spec.config.SECONDS_PER_SLOT)
    justified = spec.Checkpoint(
        epoch=spec.Epoch(proto.justified_epoch),
        root=spec.Root(proto.justified_root))
    finalized = spec.Checkpoint(
        epoch=spec.Epoch(proto.finalized_epoch),
        root=spec.Root(proto.finalized_root))
    blocks = {}
    block_states = {}
    unrealized = {}
    # the anchor's parent must point OUTSIDE the store (a zero parent
    # would alias an all-zero anchor root and make the anchor its own
    # child in filter_block_tree's children index)
    outside = bytes(32)
    while outside in proto.root_index:
        outside = hashlib.sha256(outside + proto.roots[0]).digest()
    for i, root in enumerate(proto.roots):
        parent = proto.roots[proto.parent[i]] if proto.parent[i] >= 0 \
            else outside
        blocks[spec.Root(root)] = spec.BeaconBlock(
            slot=spec.Slot(proto.slots[i]),
            parent_root=spec.Root(parent))
        block_states[spec.Root(root)] = SimpleNamespace(
            current_justified_checkpoint=spec.Checkpoint(
                epoch=spec.Epoch(proto.je[i])))
        unrealized[spec.Root(root)] = spec.Checkpoint(
            epoch=spec.Epoch(proto.uje[i]))
    store = spec.Store(
        time=spec.uint64(proto.current_epoch * proto.slots_per_epoch
                         * seconds),
        genesis_time=spec.uint64(0),
        justified_checkpoint=justified,
        finalized_checkpoint=finalized,
        unrealized_justified_checkpoint=justified,
        unrealized_finalized_checkpoint=finalized,
        proposer_boost_root=spec.Root(proto.proposer_boost_root or
                                      b"\x00" * 32),
        equivocating_indices={
            spec.ValidatorIndex(int(v))
            for v in range(proto.n_validators) if proto._equiv[v]},
        blocks=blocks,
        block_states=block_states,
        checkpoint_states={justified: _checkpoint_state(spec, proto)},
        unrealized_justifications=unrealized,
    )
    store.latest_messages = {
        spec.ValidatorIndex(int(v)): spec.LatestMessage(
            epoch=spec.Epoch(int(proto._lm_epoch[v])),
            root=spec.Root(proto.roots[int(proto._lm_block[v])]))
        for v in range(proto.n_validators) if proto._lm_block[v] >= 0}
    return spec, store


def spec_get_head(proto) -> bytes:
    """THE SPEC's ``get_head`` over the synthesized store (memoized by
    the tier-1 conftest on the store fingerprint)."""
    spec, store = spec_store_for(proto)
    return bytes(spec.get_head(store))


def spec_apply_messages(proto, validator_indices, target_epochs,
                        block_roots):
    """Run the spec oracle's ``update_latest_messages`` sequentially
    over the message stream against a synthesized store; returns the
    resulting {validator: (epoch, root)} table.  The parity pin for the
    store's batched fold rule."""
    spec, store = spec_store_for(proto)
    for v, e, r in zip(validator_indices, target_epochs, block_roots):
        att = SimpleNamespace(data=SimpleNamespace(
            target=spec.Checkpoint(epoch=spec.Epoch(int(e))),
            beacon_block_root=spec.Root(bytes(r))))
        spec.update_latest_messages(store, [spec.ValidatorIndex(int(v))],
                                    att)
    return {int(v): (int(m.epoch), bytes(m.root))
            for v, m in store.latest_messages.items()}
