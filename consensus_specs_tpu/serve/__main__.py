"""CLI: `python -m consensus_specs_tpu.serve` — run the sustained-load
attestation-verification service harness and print the serve block.

Flags mirror the CST_SERVE_* env knobs (flags win); stdout is one JSON
object (the `"serve"` block `bench_serve.py` embeds in its metric
lines), the human summary goes to stderr.  `JAX_PLATFORMS=cpu` runs the
whole thing on the host backend (the CI smoke shape)."""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m consensus_specs_tpu.serve",
        description="Sustained-load verification service harness "
                    "(deferred-result futures + batching executor).")
    parser.add_argument("--duration", type=float, default=None,
                        help="measured load duration in seconds "
                             "(CST_SERVE_DURATION_S)")
    parser.add_argument("--rate", type=float, default=None,
                        help="arrival-rate multiple of mainnet per-slot "
                             "traffic; <= 0 = closed-loop capacity mode "
                             "(CST_SERVE_RATE)")
    parser.add_argument("--pool", type=int, default=None,
                        help="distinct precomputed statements "
                             "(CST_SERVE_POOL)")
    parser.add_argument("--committee", type=int, default=None,
                        help="keys aggregated per statement "
                             "(CST_SERVE_COMMITTEE)")
    parser.add_argument("--windows", type=int, default=None,
                        help="throughput windows (CST_SERVE_WINDOWS)")
    parser.add_argument("--max-batch", type=int, default=None,
                        help="statements per RLC dispatch "
                             "(CST_SERVE_MAX_BATCH)")
    parser.add_argument("--depth", type=int, default=None,
                        help="in-flight batch pipeline depth "
                             "(CST_SERVE_DEPTH)")
    args = parser.parse_args(argv)

    import jax

    jax.config.update("jax_enable_x64", True)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from consensus_specs_tpu.utils.jaxtools import enable_compile_cache

    enable_compile_cache()

    from consensus_specs_tpu.serve.loadgen import (
        LoadConfig,
        config_from_env,
        run_load,
    )

    base = config_from_env()
    overrides = {"duration_s": args.duration, "rate": args.rate,
                 "pool": args.pool, "committee": args.committee,
                 "windows": args.windows, "max_batch": args.max_batch,
                 "depth": args.depth}
    # Rebuild through the dataclass so flag overrides pass the same
    # __post_init__ clamps the env path gets (--windows 0 must not
    # divide-by-zero in run_load).
    cfg = LoadConfig(**{f: (v if v is not None else getattr(base, f))
                        for f, v in overrides.items()})

    print(f"serve: {cfg}", file=sys.stderr, flush=True)
    block = run_load(cfg)
    print(json.dumps(block), flush=True)
    print(f"serve: {block['verifies_per_s']} verifies/s "
          f"(steady={block['steady']}), p50 {block['p50_ms']} ms / "
          f"p99 {block['p99_ms']} ms over {block['settled']} settled "
          f"({block['mode']} loop, {block['duration_s']}s)",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
