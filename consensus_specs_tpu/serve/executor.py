"""Batching serve executor — queue → topological device batches → settle.

`ServeExecutor` is the serving counterpart of the block executor's
`DeferredBatch`: requests (`submit_*`) enqueue and immediately return a
`DeviceFuture`; `pump()` drains the queue into device batches on the
`_bucket` shape ladder (so sustained traffic reuses the same AOT-warmed
executables instead of compiling per batch size) and settles futures in
arrival order WITHIN each request kind (batches themselves dispatch in
fixed `KINDS` order per pump, so cross-kind ordering is not preserved).

Pipelining contract (the "double-buffered host→device transfer"): the
executor keeps up to `depth` dispatched batches in flight and settles
the oldest only once newer work has been dispatched — so the host-side
prep of batch N+1 (point→limb conversion, RLC coefficient draws,
transfers) overlaps the device execution of batch N, and a `result()`
on any handle finds the answer already materializing instead of
stalling a cold pipeline.  `drain()` settles everything.

Request kinds and their device paths:

    verify     FastAggregateVerify-style statements, BATCHED: up to
               `max_batch` statements per RLC dispatch
               (`bls_batch.batch_verify_async`).  A batch verdict of
               True settles every statement True; False triggers a
               per-statement recheck (`pairing_check_device`) so each
               handle gets its own verdict — all-or-nothing is a block
               semantics, not a serving one.
    pairing    one pairing-product check (`pairing_check_device_async`)
    msm        one G1 MSM (`g1_multi_exp_device_async`)
    sha256     one Merkle-root reduction (`merkleize_words_jax_async`)
    fr         one barycentric evaluation (`barycentric_eval_async`)
    proof      one batched SSZ single-proof emission from a persistent
               `parallel.incremental.MerkleForest`
               (`incremental.emit_proofs_async`) — the stateless-client
               proof-serving workload riding the same futures pipeline

A device batch that RAISES settles the exception into every pending
handle of that batch (callers see it at `result()`), and the executor
keeps serving — one poisoned batch must not take the service down.

Telemetry (env-gated like everything else): `serve.queue_depth` and
`serve.inflight_batches` gauges (exported as Chrome-trace counter
tracks next to the device-memory ones), spans per pump/settle, and
submitted/settled/failed/recheck counters.  Queue-depth and latency
accounting for the bench contract is kept independently in plain
members (`stats()`, `latencies_s`) so the serve block never depends on
CST_TELEMETRY.
"""

from __future__ import annotations

import time
from collections import deque

from .. import telemetry
from .futures import DeviceFuture

KINDS = ("verify", "pairing", "msm", "sha256", "fr", "proof")

# batched-kind dispatchers resolve lazily: importing the executor must
# not pull jax/numpy-heavy ops modules until the first dispatch


def _ops_bls_batch():
    from ..ops import bls_batch
    return bls_batch


class _Request:
    __slots__ = ("kind", "payload", "future", "t_enqueue")

    def __init__(self, kind, payload, future):
        self.kind = kind
        self.payload = payload
        self.future = future
        self.t_enqueue = time.perf_counter()


class _Batch:
    __slots__ = ("kind", "future", "reqs", "t_dispatch")

    def __init__(self, kind, future, reqs):
        self.kind = kind
        self.future = future
        self.reqs = reqs
        self.t_dispatch = time.perf_counter()


def _depth_bucket(n: int) -> str:
    """Histogram label: 0 or the next power of two (1, 2, 4, 8, ...)."""
    return "0" if n <= 0 else str(1 << (n - 1).bit_length())


class ServeExecutor:
    """See the module docstring.  `max_batch` caps statements per RLC
    dispatch (a `_bucket` ladder rung keeps executables shared);
    `depth` is the number of in-flight batches the pipeline holds
    before settling the oldest."""

    def __init__(self, max_batch: int = 512, depth: int = 2):
        assert max_batch >= 1 and depth >= 1
        self.max_batch = max_batch
        self.depth = depth
        self._queue: deque[_Request] = deque()
        self._inflight: deque[_Batch] = deque()
        self.latencies_s: list[float] = []
        self._submitted = 0
        self._settled = 0
        self._failed = 0
        self._rechecks = 0
        self._dispatched_batches = 0
        self._queue_hist: dict[str, int] = {}
        self._queue_max = 0
        self._inflight_max = 0

    # --- submission ---------------------------------------------------------

    def _submit(self, kind: str, payload) -> DeviceFuture:
        assert kind in KINDS, kind
        fut = DeviceFuture(waiter=self._settle_until)
        self._queue.append(_Request(kind, payload, fut))
        self._submitted += 1
        telemetry.count("serve.submitted")
        self._note_queue_depth()
        return fut

    def submit_verify_task(self, task) -> DeviceFuture:
        """One pre-parsed FastAggregateVerify statement
        (g1_pubkey_jacobian, message_bytes, g2_sig_jacobian) — the
        `batch_verify` task shape.  Returns a bool handle."""
        return self._submit("verify", task)

    def submit_fast_aggregate_verify(self, pubkeys, message,
                                     signature) -> DeviceFuture:
        """Wire-format FastAggregateVerify: inputs validate eagerly
        (same boundary as `DeferredBatch.record`), the pairing defers.
        Invalid inputs settle False immediately."""
        from ..ops.bls.ciphersuite import parse_fast_aggregate_task

        task = parse_fast_aggregate_task(pubkeys, message, signature)
        if task is None:
            telemetry.count("serve.rejected_eager")
            return DeviceFuture.settled(False)
        return self.submit_verify_task(task)

    def submit_pairing(self, pairs) -> DeviceFuture:
        """One product-of-pairings check (sync-aggregate shape)."""
        return self._submit("pairing", pairs)

    def submit_msm(self, points, scalars) -> DeviceFuture:
        """One G1 multiscalar multiplication; settles to an oracle
        Jacobian point."""
        return self._submit("msm", (points, scalars))

    def submit_sha256_root(self, words, limit_depth: int) -> DeviceFuture:
        """One Merkle-root reduction; settles to (8,) uint32 words."""
        return self._submit("sha256", (words, limit_depth))

    def submit_barycentric(self, poly_ints, roots_brp_ints,
                           z_int) -> DeviceFuture:
        """One evaluation-form polynomial evaluation; settles to int."""
        return self._submit("fr", (poly_ints, roots_brp_ints, z_int))

    def submit_proof_request(self, forest, indices) -> DeviceFuture:
        """Batched SSZ single-proof emission from a persistent
        `parallel.incremental.MerkleForest` (the stateless-client
        serving workload): one bucketed sibling-path gather rides the
        pipeline; settles to `list[SSZProof]`.  Out-of-range indices
        fail eagerly at dispatch and poison only their own handle."""
        return self._submit("proof", (forest, list(indices)))

    # --- pipeline -----------------------------------------------------------

    def pump(self, settle_all: bool = False) -> None:
        """Dispatch everything queued, then settle in-flight batches
        down to the pipeline depth (all of them with `settle_all`)."""
        with telemetry.span("serve.pump", queued=len(self._queue),
                            inflight=len(self._inflight)):
            self._dispatch_queued()
            self._settle_ready(settle_all)

    def drain(self) -> None:
        """Dispatch and settle everything; the queue and pipeline are
        empty afterwards."""
        self.pump(settle_all=True)

    def outstanding(self) -> int:
        """Requests submitted but not yet settled."""
        return len(self._queue) + sum(len(b.reqs) for b in self._inflight)

    # --- internals ----------------------------------------------------------

    def _note_queue_depth(self) -> None:
        n = len(self._queue)
        self._queue_hist[_depth_bucket(n)] = \
            self._queue_hist.get(_depth_bucket(n), 0) + 1
        if n > self._queue_max:
            self._queue_max = n
        telemetry.gauge("serve.queue_depth", n)

    def _note_inflight(self) -> None:
        n = len(self._inflight)
        if n > self._inflight_max:
            self._inflight_max = n
        telemetry.gauge("serve.inflight_batches", n)

    def _dispatch_one(self, kind: str, reqs: list[_Request]) -> None:
        try:
            bb = _ops_bls_batch()
            # block=False: the pipelined-dispatch contract — on
            # instrumented rounds the telemetry seam must not
            # block_until_ready between batches (see bls_batch._dispatch)
            if kind == "verify":
                fut = bb.batch_verify_async([r.payload for r in reqs],
                                            block=False)
            elif kind == "pairing":
                fut = bb.pairing_check_device_async(reqs[0].payload,
                                                    block=False)
            elif kind == "msm":
                fut = bb.g1_multi_exp_device_async(*reqs[0].payload,
                                                   block=False)
            elif kind == "sha256":
                from ..ops.sha256_jax import merkleize_words_jax_async
                fut = merkleize_words_jax_async(*reqs[0].payload)
            elif kind == "fr":
                from ..ops.fr_batch import barycentric_eval_async
                fut = barycentric_eval_async(*reqs[0].payload)
            else:   # proof
                from ..parallel.incremental import emit_proofs_async
                fut = emit_proofs_async(*reqs[0].payload)
        except Exception as exc:
            # host prep can fail before the batch ever reaches the
            # device (malformed payload); the keep-serving contract is
            # the same as a failed device batch — fail THESE handles,
            # keep dispatching the rest
            for req in reqs:
                req.future.set_exception(exc)
            self._failed += len(reqs)
            telemetry.count("serve.failed", len(reqs))
            return
        self._inflight.append(_Batch(kind, fut, reqs))
        self._dispatched_batches += 1
        telemetry.count(f"serve.dispatch.{kind}")
        self._note_inflight()

    def _dispatch_queued(self) -> None:
        if not self._queue:
            return
        # partition the queue by kind, preserving arrival order within
        # each kind (the topological batches the futures settle in)
        by_kind: dict[str, list[_Request]] = {}
        while self._queue:
            req = self._queue.popleft()
            by_kind.setdefault(req.kind, []).append(req)
        self._note_queue_depth()
        for kind in KINDS:
            reqs = by_kind.get(kind)
            if not reqs:
                continue
            if kind == "verify":
                for i in range(0, len(reqs), self.max_batch):
                    self._dispatch_one(kind, reqs[i:i + self.max_batch])
            else:
                for req in reqs:
                    self._dispatch_one(kind, [req])

    def _settle_ready(self, settle_all: bool) -> None:
        while self._inflight and (settle_all
                                  or len(self._inflight) > self.depth):
            self._settle_batch(self._inflight.popleft())
            self._note_inflight()

    def _settle_until(self, fut: DeviceFuture) -> None:
        """Waiter hook for request handles: pump until `fut` settles
        (its batch may be queued, in flight, or already done)."""
        self._dispatch_queued()
        while self._inflight and not fut.done():
            self._settle_batch(self._inflight.popleft())
            self._note_inflight()

    def _verify_single(self, task) -> bool:
        """Per-statement verdict for a failed RLC batch (attribution)."""
        from ..ops.bls.ciphersuite import fast_aggregate_pairs

        return _ops_bls_batch().pairing_check_device(
            fast_aggregate_pairs(task))

    def _settle_batch(self, batch: _Batch) -> None:
        with telemetry.span("serve.settle_batch", kind=batch.kind,
                            requests=len(batch.reqs)):
            try:
                out = batch.future.result()
                if batch.kind == "verify" and len(batch.reqs) > 1:
                    if out:
                        results = [True] * len(batch.reqs)
                    else:
                        self._rechecks += 1
                        telemetry.count("serve.batch_recheck")
                        results = [self._verify_single(r.payload)
                                   for r in batch.reqs]
                else:
                    results = [out] * len(batch.reqs)
            except Exception as exc:
                # a failed device batch — or a failed per-statement
                # recheck dispatch — fails EVERY pending handle; the
                # executor itself keeps serving
                for req in batch.reqs:
                    req.future.set_exception(exc)
                self._failed += len(batch.reqs)
                telemetry.count("serve.failed", len(batch.reqs))
                return
            now = time.perf_counter()
            for req, value in zip(batch.reqs, results):
                req.future.set_result(value)
                self.latencies_s.append(now - req.t_enqueue)
            self._settled += len(batch.reqs)
            telemetry.count("serve.settled", len(batch.reqs))

    # --- accounting ---------------------------------------------------------

    def stats(self) -> dict:
        """Plain-dict accounting for the bench `"serve"` block (does not
        depend on CST_TELEMETRY)."""
        return {
            "submitted": self._submitted,
            "settled": self._settled,
            "failed": self._failed,
            "rechecks": self._rechecks,
            "batches": self._dispatched_batches,
            "queue_depth": {"max": self._queue_max,
                            "hist": dict(self._queue_hist)},
            "inflight_max": self._inflight_max,
        }
