"""Batching serve executor — queue → topological device batches → settle.

`ServeExecutor` is the serving counterpart of the block executor's
`DeferredBatch`: requests (`submit_*`) enqueue and immediately return a
`DeviceFuture`; `pump()` drains the queue into device batches on the
`_bucket` shape ladder (so sustained traffic reuses the same AOT-warmed
executables instead of compiling per batch size) and settles futures in
arrival order WITHIN each request kind (batches themselves dispatch in
fixed `KINDS` order per pump, so cross-kind ordering is not preserved).

Pipelining contract (the "double-buffered host→device transfer"): the
executor keeps up to `depth` dispatched batches in flight and settles
the oldest only once newer work has been dispatched — so the host-side
prep of batch N+1 (point→limb conversion, RLC coefficient draws,
transfers) overlaps the device execution of batch N, and a `result()`
on any handle finds the answer already materializing instead of
stalling a cold pipeline.  `drain()` settles everything.

Request kinds and their device paths:

    verify     FastAggregateVerify-style statements, BATCHED: up to
               `max_batch` statements per RLC dispatch
               (`bls_batch.batch_verify_async`).  A batch verdict of
               True settles every statement True; False triggers a
               per-statement recheck (`pairing_check_device`) so each
               handle gets its own verdict — all-or-nothing is a block
               semantics, not a serving one.
    pairing    one pairing-product check (`pairing_check_device_async`)
    msm        one G1 MSM (`g1_multi_exp_device_async`)
    sha256     one Merkle-root reduction (`merkleize_words_jax_async`)
    fr         one barycentric evaluation (`barycentric_eval_async`)
    proof      one batched SSZ single-proof emission from a persistent
               `parallel.incremental.MerkleForest`
               (`incremental.emit_proofs_async`) — the stateless-client
               proof-serving workload riding the same futures pipeline
    das        data-column sampling checks, CROSS-SAMPLE BATCHED: every
               sample queued at pump time folds into ONE RLC pairing
               equation (`das.sampling.verify_sample_group_async` —
               host inclusion walks per sample, then all the samples'
               cell statements as a single device batch; a failed batch
               verdict rechecks per sample, so each request keeps its
               own answer)
    fc_atts    fork-choice attestation batches (`forkchoice
               .ProtoArrayStore.apply_attestations_async`): every batch
               queued at pump time for the same store folds into ONE
               latest-message/weight-delta dispatch; each request
               settles to ITS OWN accepted count (the device accept
               mask is split per request).  Idempotent under retry —
               the strictly-greater epoch rule makes re-applying a
               batch a no-op.
    head       one LMD-GHOST head poll (`ProtoArrayStore
               .get_head_async`); settles to the head's 32-byte root.
               The breaker's degraded mode answers on the actual phase0
               spec oracle (`get_head_host`), and degraded-mode
               `fc_atts` applies land on the store's host mirror, from
               which the device arrays rebuild when the breaker
               re-closes.

Failure semantics are LAYERED (PR 8, the resilience layer):

- Base contract (always on): a device batch that RAISES settles the
  exception into every pending handle of that batch — and ONLY that
  batch — and the executor keeps serving.  One poisoned batch must not
  take the service down.
- `retry=RetryPolicy(...)`: a failed batch re-dispatches with capped
  exponential backoff before the failure is final.
- `breakers=BreakerRegistry(...)`: consecutive failures per
  (kind, rung) trip a circuit breaker; while OPEN, matching batches
  route to the PURE-PYTHON ORACLE fallback (`_oracle_compute` —
  bit-identical results, orders of magnitude slower: the degraded mode
  that keeps answers correct while the device path is sick), and
  half-open probes re-close the breaker once the device recovers.
  Kinds without an oracle (`proof`) keep trying the device.
- `deadline_ms` (default `CST_SERVE_DEADLINE_MS`): queued requests
  older than the deadline are shed at the next pump with a typed
  `resilience.DeadlineExceeded` — oldest first, so overload degrades
  into explicit failures instead of unbounded queue growth.
- `mesh=MeshVerifier(...)` (PR 9): verify batches dispatch sharded
  over the device mesh with per-shard loss recovery — a dead device
  re-buckets the batch over the survivors inside the mesh layer, so
  the retry/breaker ladder here only sees failures the mesh could not
  absorb (`resilience.mesh`; its counters ride `stats()["mesh"]`).

Fault injection (`resilience.faults`, OFF by default): the
`serve_pump` seam fires inside `_dispatch_one`'s try block, so an
injected fault has exactly a real host-prep failure's blast radius.

Telemetry (env-gated like everything else): `serve.queue_depth` and
`serve.inflight_batches` gauges (exported as Chrome-trace counter
tracks next to the device-memory ones), spans per pump/settle, and
submitted/settled/failed/recheck/retry/fallback/shed counters.
Queue-depth and latency accounting for the bench contract is kept
independently in plain members (`stats()`, `latencies_s`) so the serve
block never depends on CST_TELEMETRY.
"""

from __future__ import annotations

import json
import os
import sys
import time
from collections import deque

from .. import telemetry
from ..resilience import faults
from ..resilience.policies import DeadlineExceeded
from ..telemetry import flightrec, occupancy, reqtrace
from .futures import DeviceFuture, FutureTimeout

KINDS = ("verify", "pairing", "msm", "sha256", "fr", "proof", "das",
         "recover", "fc_atts", "head")

# batched-kind dispatchers resolve lazily: importing the executor must
# not pull jax/numpy-heavy ops modules until the first dispatch


def _ops_bls_batch():
    from ..ops import bls_batch
    return bls_batch


class _Request:
    __slots__ = ("kind", "payload", "future", "t_enqueue", "ctx")

    def __init__(self, kind, payload, future, ctx=None):
        self.kind = kind
        self.payload = payload
        self.future = future
        self.ctx = ctx          # reqtrace.RequestContext (None when off)
        self.t_enqueue = time.perf_counter()


class _Batch:
    __slots__ = ("kind", "future", "reqs", "t_dispatch", "attempt",
                 "occ")

    def __init__(self, kind, future, reqs, attempt=1, occ=None):
        self.kind = kind
        self.future = future
        self.reqs = reqs
        self.t_dispatch = time.perf_counter()
        self.attempt = attempt
        self.occ = occ          # occupancy.BatchSpan (None when off)


def _depth_bucket(n: int) -> str:
    """Histogram label: 0 or the next power of two (1, 2, 4, 8, ...)."""
    return "0" if n <= 0 else str(1 << (n - 1).bit_length())


def _breaker_key(kind: str, n: int) -> str:
    """Per-(kind, rung) breaker key: a verify batch of 100 and one of
    128 share executables (the `_bucket` ladder) and share health."""
    return f"{kind}@{_depth_bucket(n)}"


# --- pure-Python oracle fallback (degraded mode) -----------------------------
#
# One oracle per kind that has one; results are BIT-IDENTICAL to the
# device path (pinned by tests/test_resilience.py), just slow.  The
# verify oracle memoizes on the statement's canonical serialization:
# sustained traffic cycles a finite statement pool, so a tripped
# breaker costs one pure-Python pairing check per DISTINCT statement,
# not per request.

_ORACLE_VERIFY_CACHE: dict = {}
_ORACLE_VERIFY_CACHE_MAX = 4096


def _oracle_verify(task) -> bool:
    from ..ops.bls.ciphersuite import _pairing_check, fast_aggregate_pairs
    from ..ops.bls.curve import g1_to_bytes, g2_to_bytes

    pk, msg, sig = task
    try:
        key = (g1_to_bytes(pk), bytes(msg), g2_to_bytes(sig))
    except (TypeError, ValueError):
        key = None      # unserializable point: verify uncached
    if key is not None and key in _ORACLE_VERIFY_CACHE:
        telemetry.count("resilience.fallback.verify_cache_hit")
        return _ORACLE_VERIFY_CACHE[key]
    ok = _pairing_check(fast_aggregate_pairs(task))
    if key is not None:
        if len(_ORACLE_VERIFY_CACHE) >= _ORACLE_VERIFY_CACHE_MAX:
            _ORACLE_VERIFY_CACHE.clear()
        _ORACLE_VERIFY_CACHE[key] = ok
    return ok


def _oracle_barycentric(poly_ints, roots_brp_ints, z_int) -> int:
    """The closed-form host evaluation `fr_batch` mirrors: f(z) =
    (z^W - 1)/W * sum_i f_i * w_i / (z - w_i) mod r, with the in-domain
    short-circuit."""
    from ..ops.fr_batch import R_MODULUS as r

    width = len(poly_ints)
    z = int(z_int) % r
    roots = [int(w) % r for w in roots_brp_ints]
    poly = [int(f) % r for f in poly_ints]
    for f, w in zip(poly, roots):
        if (z - w) % r == 0:
            return f
    total = 0
    for f, w in zip(poly, roots):
        total = (total + f * w % r * pow((z - w) % r, r - 2, r)) % r
    factor = (pow(z, width, r) - 1) % r
    inv_width = pow(width, r - 2, r)
    return total * factor % r * inv_width % r


def _oracle_compute(kind: str, payload):
    """Dispatch one request on the pure-Python oracle.  Raises KeyError
    for kinds without an oracle (`proof`)."""
    if kind == "verify":
        return _oracle_verify(payload)
    if kind == "pairing":
        from ..ops.bls.ciphersuite import _pairing_check

        return _pairing_check(payload)
    if kind == "sha256":
        import numpy as np

        from ..ops.sha256_np import merkleize_words

        words, limit_depth = payload
        return merkleize_words(np.asarray(words, dtype=np.uint32),
                               limit_depth)
    if kind == "fr":
        return _oracle_barycentric(*payload)
    if kind == "msm":
        from ..ops.bls import curve as pycurve

        points, scalars = payload
        acc = pycurve.g1.infinity()
        for p, s in zip(points, scalars):
            acc = pycurve.g1.add(acc, pycurve.g1.mul(p, int(s)
                                                     % pycurve.R))
        return acc
    if kind == "das":
        from ..das.sampling import verify_sample_host

        return verify_sample_host(payload)
    if kind == "recover":
        from ..das.recover import recover_cells_and_kzg_proofs_host

        return recover_cells_and_kzg_proofs_host(*payload)
    if kind == "fc_atts":
        # host-mirror fold (the exact kernel rule); the store rebuilds
        # its device arrays from the mirror when the breaker re-closes
        store, idx, epochs, roots = payload
        return store.apply_attestations_host(idx, epochs, roots)
    if kind == "head":
        # the actual phase0 spec oracle's get_head over the mirror
        return payload.get_head_host()
    raise KeyError(f"no oracle fallback for request kind {kind!r}")


ORACLE_KINDS = frozenset({"verify", "pairing", "msm", "sha256", "fr",
                          "das", "recover", "fc_atts", "head"})


class ServeExecutor:
    """See the module docstring.  `max_batch` caps statements per RLC
    dispatch (a `_bucket` ladder rung keeps executables shared);
    `depth` is the number of in-flight batches the pipeline holds
    before settling the oldest.  `retry`/`breakers`/`deadline_ms` arm
    the resilience policies (all off by default; `deadline_ms` falls
    back to the CST_SERVE_DEADLINE_MS knob)."""

    def __init__(self, max_batch: int = 512, depth: int = 2,
                 retry=None, breakers=None,
                 deadline_ms: float | None = None, mesh=None):
        assert max_batch >= 1 and depth >= 1
        self.max_batch = max_batch
        self.depth = depth
        self.retry = retry
        self.breakers = breakers
        # a resilience.mesh.MeshVerifier: verify batches dispatch over
        # the device mesh with the per-shard recovery ladder (a lost
        # device re-buckets the batch over the survivors before the
        # retry/breaker ladder here ever sees a failure)
        self.mesh = mesh
        if deadline_ms is None:
            try:
                deadline_ms = float(
                    os.environ.get("CST_SERVE_DEADLINE_MS", "0")) or None
            except ValueError:
                deadline_ms = None
        self.deadline_s = deadline_ms / 1e3 if deadline_ms else None
        self._queue: deque[_Request] = deque()
        self._inflight: deque[_Batch] = deque()
        self.latencies_s: list[float] = []
        self._submitted = 0
        self._settled = 0
        self._failed = 0
        self._rechecks = 0
        self._dispatched_batches = 0
        self._retries = 0
        self._fallbacks = 0
        self._shed = 0
        self._poisoned_batches = 0
        self._poison_dumped = False
        self._queue_hist: dict[str, int] = {}
        self._queue_max = 0
        self._inflight_max = 0
        self._t_start = time.perf_counter()
        # live ops snapshot: CST_SERVE_STATUS_EVERY seconds > 0 dumps
        # status() as one JSON line on stderr from inside pump(), so a
        # sustained round is observable while it runs (on-demand reads
        # call status() directly)
        try:
            self._status_every = float(
                os.environ.get("CST_SERVE_STATUS_EVERY", "0") or 0)
        except ValueError:
            self._status_every = 0.0
        self._status_last = time.perf_counter()

    # --- submission ---------------------------------------------------------

    def _submit(self, kind: str, payload) -> DeviceFuture:
        assert kind in KINDS, kind
        ctx = reqtrace.mint(kind)
        fut = DeviceFuture(waiter=self._settle_until)
        if ctx is not None:
            fut.ctx = ctx       # the context rides the handle too
            ctx.mark_enqueue()
        self._queue.append(_Request(kind, payload, fut, ctx))
        self._submitted += 1
        telemetry.count("serve.submitted")
        self._note_queue_depth()
        return fut

    def submit_verify_task(self, task) -> DeviceFuture:
        """One pre-parsed FastAggregateVerify statement
        (g1_pubkey_jacobian, message_bytes, g2_sig_jacobian) — the
        `batch_verify` task shape.  Returns a bool handle."""
        return self._submit("verify", task)

    def submit_fast_aggregate_verify(self, pubkeys, message,
                                     signature) -> DeviceFuture:
        """Wire-format FastAggregateVerify: inputs validate eagerly
        (same boundary as `DeferredBatch.record`), the pairing defers.
        Invalid inputs settle False immediately."""
        from ..ops.bls.ciphersuite import parse_fast_aggregate_task

        task = parse_fast_aggregate_task(pubkeys, message, signature)
        if task is None:
            telemetry.count("serve.rejected_eager")
            return DeviceFuture.settled(False)
        return self.submit_verify_task(task)

    def submit_pairing(self, pairs) -> DeviceFuture:
        """One product-of-pairings check (sync-aggregate shape)."""
        return self._submit("pairing", pairs)

    def submit_msm(self, points, scalars) -> DeviceFuture:
        """One G1 multiscalar multiplication; settles to an oracle
        Jacobian point."""
        return self._submit("msm", (points, scalars))

    def submit_sha256_root(self, words, limit_depth: int) -> DeviceFuture:
        """One Merkle-root reduction; settles to (8,) uint32 words."""
        return self._submit("sha256", (words, limit_depth))

    def submit_barycentric(self, poly_ints, roots_brp_ints,
                           z_int) -> DeviceFuture:
        """One evaluation-form polynomial evaluation; settles to int."""
        return self._submit("fr", (poly_ints, roots_brp_ints, z_int))

    def submit_proof_request(self, forest, indices) -> DeviceFuture:
        """Batched SSZ single-proof emission from a persistent
        `parallel.incremental.MerkleForest` (the stateless-client
        serving workload): one bucketed sibling-path gather rides the
        pipeline; settles to `list[SSZProof]`.  Out-of-range indices
        fail eagerly at dispatch and poison only their own handle."""
        return self._submit("proof", (forest, list(indices)))

    def submit_das_sample(self, sample) -> DeviceFuture:
        """One data-column sampling check (`das.sampling.DasSample`):
        host inclusion walk, then the cell proofs ride the pump's
        cross-sample RLC batch (every das sample queued at pump time
        folds into ONE device dispatch).  Settles to bool; a
        structurally broken or inclusion-failing sample settles False
        without touching the device."""
        return self._submit("das", sample)

    def submit_recover_request(self, cell_indices, cells) -> DeviceFuture:
        """One damaged-blob reconstruction (the super-node lane): >= 64
        surviving cells in, ALL 128 cells + FK20 proofs out — the
        device coset decode + re-prove (`das.recover`).  Settles to
        (cells, proofs); malformed input (too few cells, duplicates,
        bad sizes) fails at dispatch and poisons only its own handle.
        The breaker's degraded route is the pure-Python spec oracle."""
        return self._submit("recover", (list(cell_indices),
                                        [bytes(c) for c in cells]))

    def submit_attestation_batch(self, store, validator_indices,
                                 target_epochs,
                                 block_roots) -> DeviceFuture:
        """One fork-choice attestation batch against a
        `forkchoice.ProtoArrayStore` (validator index, target epoch,
        vote-block root per message — the post-verification facts the
        fork choice consumes; signature checking is the `verify`
        lane's job).  Batches queued for the same store fold into ONE
        device dispatch per pump; settles to this request's accepted
        latest-message count."""
        n = len(validator_indices)
        assert n == len(target_epochs) == len(block_roots)
        return self._submit("fc_atts", (store, list(validator_indices),
                                        list(target_epochs),
                                        list(block_roots)))

    def submit_head_request(self, store) -> DeviceFuture:
        """One LMD-GHOST head poll against a
        `forkchoice.ProtoArrayStore`; settles to the head's 32-byte
        root."""
        return self._submit("head", store)

    # --- pipeline -----------------------------------------------------------

    def pump(self, settle_all: bool = False) -> None:
        """Shed aged-out requests, dispatch everything queued, then
        settle in-flight batches down to the pipeline depth (all of
        them with `settle_all`)."""
        with telemetry.span("serve.pump", queued=len(self._queue),
                            inflight=len(self._inflight)):
            self._shed_expired()
            self._dispatch_queued()
            self._settle_ready(settle_all)
        self._maybe_dump_status()

    def drain(self) -> None:
        """Dispatch and settle everything; the queue and pipeline are
        empty afterwards."""
        self.pump(settle_all=True)

    def outstanding(self) -> int:
        """Requests submitted but not yet settled."""
        return len(self._queue) + sum(len(b.reqs) for b in self._inflight)

    # --- internals ----------------------------------------------------------

    def _note_queue_depth(self) -> None:
        n = len(self._queue)
        self._queue_hist[_depth_bucket(n)] = \
            self._queue_hist.get(_depth_bucket(n), 0) + 1
        if n > self._queue_max:
            self._queue_max = n
        telemetry.gauge("serve.queue_depth", n)

    def _note_inflight(self) -> None:
        n = len(self._inflight)
        if n > self._inflight_max:
            self._inflight_max = n
        telemetry.gauge("serve.inflight_batches", n)

    def _shed_expired(self) -> None:
        """The deadline policy: fail queued requests older than the
        per-request deadline with a typed `DeadlineExceeded`, OLDEST
        first (the queue is FIFO, so the head is always the oldest) —
        an overloaded service sheds explicitly instead of letting the
        queue grow without bound."""
        if self.deadline_s is None or not self._queue:
            return
        now = time.perf_counter()
        while self._queue:
            age = now - self._queue[0].t_enqueue
            if age <= self.deadline_s:
                break
            req = self._queue.popleft()
            trace_id = req.ctx.trace_id if req.ctx is not None else None
            req.future.set_exception(
                DeadlineExceeded(req.kind, age, self.deadline_s,
                                 trace_id=trace_id))
            if req.ctx is not None:
                # the whole shed lifetime is queue wait — there was no
                # dispatch, no settle
                req.ctx.complete("shed", final_component="queue_wait")
            self._shed += 1
            self._failed += 1
            telemetry.count("serve.shed")
        self._note_queue_depth()

    def _dispatch_one(self, kind: str, reqs: list[_Request],
                      attempt: int = 1) -> None:
        key = _breaker_key(kind, len(reqs))
        if self.breakers is not None and kind in ORACLE_KINDS \
                and not self.breakers.get(key).allow():
            self._serve_fallback(kind, reqs)
            return
        # request tracing: every member context closes its queue-wait
        # (or retry-detour) interval and learns its batch id — the
        # N-requests → 1-dispatch lineage the flow events render
        ctxs = [r.ctx for r in reqs if r.ctx is not None]
        batch_id = reqtrace.new_batch_id() if ctxs else None
        for ctx in ctxs:
            ctx.mark_dispatch(batch_id)
        # occupancy ledger: the span opens in host-prep now; the device
        # busy interval opens at mark_dispatch below and closes when
        # _settle_batch fetches the answer
        occ = occupancy.begin_batch(kind)
        try:
            # resilience seam: an injected fault here has exactly a real
            # host-prep failure's blast radius (THESE handles, no others)
            if faults.active():
                faults.maybe_inject("serve_pump", kind)
            bb = _ops_bls_batch()
            # block=False: the pipelined-dispatch contract — on
            # instrumented rounds the telemetry seam must not
            # block_until_ready between batches (see bls_batch._dispatch)
            if kind == "verify":
                if self.mesh is not None:
                    fut = self.mesh.verify_async(
                        [r.payload for r in reqs])
                else:
                    fut = bb.batch_verify_async(
                        [r.payload for r in reqs], block=False)
            elif kind == "pairing":
                fut = bb.pairing_check_device_async(reqs[0].payload,
                                                    block=False)
            elif kind == "msm":
                fut = bb.g1_multi_exp_device_async(*reqs[0].payload,
                                                   block=False)
            elif kind == "sha256":
                from ..ops.sha256_jax import merkleize_words_jax_async
                fut = merkleize_words_jax_async(*reqs[0].payload)
            elif kind == "fr":
                from ..ops.fr_batch import barycentric_eval_async
                fut = barycentric_eval_async(*reqs[0].payload)
            elif kind == "das":
                from ..das.sampling import verify_sample_group_async
                # cross-sample batching: every queued sample's cell
                # statements fold into ONE RLC device batch (device
                # route always — the breaker's oracle fallback is the
                # host route)
                fut = verify_sample_group_async(
                    [r.payload for r in reqs])
            elif kind == "recover":
                from ..das.recover import \
                    recover_cells_and_kzg_proofs_async
                # one reconstruction per dispatch (the payload is a
                # whole damaged blob); the zero-poly FFT goes out now,
                # decode + FK20 re-prove run at settle
                fut = recover_cells_and_kzg_proofs_async(
                    *reqs[0].payload, device=True)
            elif kind == "fc_atts":
                # cross-request batching: every queued batch for this
                # store folds into ONE latest-message/weight dispatch;
                # the settle splits the accept mask per request
                store = reqs[0].payload[0]
                idx: list = []
                epochs: list = []
                roots: list = []
                for r in reqs:
                    idx.extend(r.payload[1])
                    epochs.extend(r.payload[2])
                    roots.extend(r.payload[3])
                fut = store.apply_attestations_async(idx, epochs, roots)
            elif kind == "head":
                fut = reqs[0].payload.get_head_async()
            else:   # proof
                from ..parallel.incremental import emit_proofs_async
                fut = emit_proofs_async(*reqs[0].payload)
        except Exception as exc:
            # host prep can fail before the batch ever reaches the
            # device (malformed payload, injected fault); same recovery
            # ladder as a failed device batch
            if occ is not None:
                occ.abandon()
            self._batch_failed(kind, reqs, exc, attempt, key)
            return
        for ctx in ctxs:
            ctx.mark_inflight()
        if batch_id is not None:
            reqtrace.note_batch(batch_id, kind,
                                [c.trace_id for c in ctxs], attempt,
                                len(reqs))
        if occ is not None:
            occ.mark_dispatch()
        self._inflight.append(_Batch(kind, fut, reqs, attempt=attempt,
                                     occ=occ))
        self._dispatched_batches += 1
        telemetry.count(f"serve.dispatch.{kind}")
        self._note_inflight()

    def _dispatch_queued(self) -> None:
        if not self._queue:
            return
        # partition the queue by kind, preserving arrival order within
        # each kind (the topological batches the futures settle in)
        by_kind: dict[str, list[_Request]] = {}
        while self._queue:
            req = self._queue.popleft()
            by_kind.setdefault(req.kind, []).append(req)
        self._note_queue_depth()
        for kind in KINDS:
            reqs = by_kind.get(kind)
            if not reqs:
                continue
            if kind in ("verify", "das"):
                # batched kinds: up to max_batch requests per device
                # dispatch (das folds the samples' cell statements into
                # one RLC batch)
                for i in range(0, len(reqs), self.max_batch):
                    self._dispatch_one(kind, reqs[i:i + self.max_batch])
            elif kind == "fc_atts":
                # one merged dispatch per TARGET STORE, arrival order
                # preserved within each group
                groups: dict[int, list[_Request]] = {}
                for req in reqs:
                    groups.setdefault(id(req.payload[0]), []).append(req)
                for group in groups.values():
                    self._dispatch_one(kind, group)
            else:
                for req in reqs:
                    self._dispatch_one(kind, [req])

    def _settle_ready(self, settle_all: bool) -> None:
        while self._inflight and (settle_all
                                  or len(self._inflight) > self.depth):
            self._settle_batch(self._inflight.popleft())
            self._note_inflight()

    def _settle_until(self, fut: DeviceFuture, timeout=None) -> None:
        """Waiter hook for request handles: pump until `fut` settles
        (its batch may be queued, in flight, or already done).  With a
        `timeout` the wait is bounded: batch settles use the remaining
        budget and an exhausted budget returns with `fut` still pending
        (the future raises the typed `FutureTimeout`)."""
        deadline = None if timeout is None \
            else time.perf_counter() + float(timeout)
        self._shed_expired()
        self._dispatch_queued()
        while self._inflight and not fut.done():
            remaining = None
            if deadline is not None:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    return
            if not self._settle_batch(self._inflight.popleft(),
                                      timeout=remaining):
                return          # batch itself timed out (re-queued)
            self._note_inflight()

    def _verify_single(self, task) -> bool:
        """Per-statement verdict for a failed RLC batch (attribution)."""
        from ..ops.bls.ciphersuite import fast_aggregate_pairs

        return _ops_bls_batch().pairing_check_device(
            fast_aggregate_pairs(task))

    def _serve_fallback(self, kind: str, reqs: list[_Request]) -> None:
        """Degraded mode: answer on the pure-Python oracle (correct but
        slow) while the breaker holds the device path open.  Each
        request settles independently — an oracle failure poisons only
        its own handle."""
        with telemetry.span("serve.fallback", kind=kind,
                            requests=len(reqs)):
            for req in reqs:
                if req.ctx is not None:
                    req.ctx.mark_fallback_begin()
            now_latencies = []
            for req in reqs:
                try:
                    value = _oracle_compute(kind, req.payload)
                except Exception as exc:
                    req.future.set_exception(exc)
                    if req.ctx is not None:
                        req.ctx.complete("poisoned",
                                         final_component="detour")
                    self._failed += 1
                    telemetry.count("serve.failed")
                    continue
                req.future.set_result(value)
                if req.ctx is not None:
                    # oracle compute time is a resilience detour
                    req.ctx.complete("fallback",
                                     final_component="detour")
                now_latencies.append(req.t_enqueue)
                self._settled += 1
            now = time.perf_counter()
            self.latencies_s.extend(now - t for t in now_latencies)
            self._fallbacks += len(reqs)
            telemetry.count(f"serve.fallback.{kind}", len(reqs))

    def _batch_failed(self, kind: str, reqs: list[_Request],
                      exc: Exception, attempt: int, key: str) -> None:
        """The recovery ladder for one failed batch: record the breaker
        failure, retry with backoff while the policy allows, then
        degrade to the oracle when the breaker is open — poisoning the
        handles only when no recovery path remains."""
        telemetry.count("serve.batch_failed")
        # the failed attempt's wall is a detour; an injected fault marks
        # its victims so the chaos harness can pin the blast radius to
        # exactly these trace ids
        faulted = isinstance(exc, faults.FaultInjected)
        for req in reqs:
            if req.ctx is not None:
                req.ctx.mark_attempt_failed(faulted=faulted)
        breaker = self.breakers.get(key) if self.breakers is not None \
            else None
        if breaker is not None:
            breaker.record_failure()
        if self.retry is not None and self.retry.should_retry(attempt):
            time.sleep(self.retry.backoff_s(attempt))
            self._retries += 1
            telemetry.count("serve.retry")
            self._dispatch_one(kind, reqs, attempt=attempt + 1)
            return
        if breaker is not None and breaker.state != "closed" \
                and kind in ORACLE_KINDS:
            self._serve_fallback(kind, reqs)
            return
        for req in reqs:
            req.future.set_exception(exc)
            if req.ctx is not None:
                req.ctx.complete("poisoned")
        self._failed += len(reqs)
        telemetry.count("serve.failed", len(reqs))
        # flight recorder: a poisoned batch is an incident event, and a
        # poison STORM (CST_FLIGHTREC_POISON_N) freezes the evidence
        # once — the bundle carries the fault plan and breaker arc that
        # explain it
        self._poisoned_batches += 1
        flightrec.record("batch_poisoned", batch_kind=kind,
                         requests=len(reqs), attempt=attempt,
                         error=f"{type(exc).__name__}: {exc}")
        n = flightrec.poison_dump_threshold()
        if n and self._poisoned_batches >= n \
                and not self._poison_dumped:
            self._poison_dumped = True
            try:
                flightrec.dump_bundle(reason="poison-storm")
                telemetry.count("serve.incident_bundles")
            except Exception:   # cst: allow(exc-swallow-device): evidence dump is best-effort — a failed incident write must never worsen the incident (the failure is counted)
                telemetry.count("serve.incident_dump_failed")

    def _settle_batch(self, batch: _Batch, timeout=None) -> bool:
        """Settle one in-flight batch; returns False (re-queueing the
        batch at the pipeline head) when a bounded wait ran out before
        the device answered."""
        with telemetry.span("serve.settle_batch", kind=batch.kind,
                            requests=len(batch.reqs)):
            key = _breaker_key(batch.kind, len(batch.reqs))
            ctxs = [r.ctx for r in batch.reqs if r.ctx is not None]
            try:
                out = batch.future.result() if timeout is None \
                    else batch.future.result(timeout=timeout)
                if batch.occ is not None:
                    batch.occ.mark_answer()
                for ctx in ctxs:
                    ctx.mark_device_done()
                if batch.kind == "verify" and len(batch.reqs) > 1:
                    if out:
                        results = [True] * len(batch.reqs)
                    else:
                        self._rechecks += 1
                        telemetry.count("serve.batch_recheck")
                        results = [self._verify_single(r.payload)
                                   for r in batch.reqs]
                        # the per-statement recheck wall is a detour,
                        # and the outcome label upgrades to "recheck"
                        for ctx in ctxs:
                            ctx.note_recheck()
                elif batch.kind == "das":
                    # the group future settles to per-sample verdicts
                    results = list(out)
                    assert len(results) == len(batch.reqs)
                elif batch.kind == "fc_atts":
                    # split the merged dispatch's accept mask back into
                    # per-request accepted counts
                    import numpy as np

                    mask = np.asarray(out)
                    results = []
                    off = 0
                    for req in batch.reqs:
                        n = len(req.payload[1])
                        results.append(int(np.count_nonzero(
                            mask[off:off + n])))
                        off += n
                else:
                    results = [out] * len(batch.reqs)
            except FutureTimeout:
                for ctx in ctxs:
                    ctx.note_timeout()      # provisional: still pending
                self._inflight.appendleft(batch)
                return False
            except Exception as exc:
                # a failed device batch — or a failed per-statement
                # recheck dispatch — walks the recovery ladder; the
                # executor itself keeps serving
                if batch.occ is not None:
                    batch.occ.abandon()
                self._batch_failed(batch.kind, batch.reqs, exc,
                                   batch.attempt, key)
                return True
            if self.breakers is not None:
                self.breakers.get(key).record_success()
            now = time.perf_counter()
            for req, value in zip(batch.reqs, results):
                req.future.set_result(value)
                if req.ctx is not None:
                    # outcome auto-resolves: recheck > retry > ok
                    req.ctx.complete()
                self.latencies_s.append(now - req.t_enqueue)
            self._settled += len(batch.reqs)
            telemetry.count("serve.settled", len(batch.reqs))
            if batch.occ is not None:
                batch.occ.mark_settled()
            return True

    # --- accounting ---------------------------------------------------------

    def status(self) -> dict:
        """Live ops snapshot as one JSON-able dict: queue depths (total
        + per kind + oldest age), in-flight batches/requests, the
        lifecycle counters, breaker states, and — on traced rounds
        (CST_TRACE_REQUESTS) — per-kind rolling p50/p99 with mean
        component attribution.  Dumped periodically from `pump()` when
        CST_SERVE_STATUS_EVERY > 0; callable on demand any time."""
        now = time.perf_counter()
        queue_by_kind: dict[str, int] = {}
        for req in self._queue:
            queue_by_kind[req.kind] = queue_by_kind.get(req.kind, 0) + 1
        inflight_by_kind: dict[str, int] = {}
        inflight_reqs = 0
        for batch in self._inflight:
            inflight_by_kind[batch.kind] = \
                inflight_by_kind.get(batch.kind, 0) + 1
            inflight_reqs += len(batch.reqs)
        out = {
            "ts": time.time(),
            "uptime_s": round(now - self._t_start, 3),
            "queue": {
                "depth": len(self._queue),
                "by_kind": queue_by_kind,
                "oldest_age_s": (round(now - self._queue[0].t_enqueue, 4)
                                 if self._queue else None),
            },
            "inflight": {
                "batches": len(self._inflight),
                "requests": inflight_reqs,
                "by_kind": inflight_by_kind,
            },
            "counters": {
                "submitted": self._submitted,
                "settled": self._settled,
                "failed": self._failed,
                "rechecks": self._rechecks,
                "batches": self._dispatched_batches,
                "retries": self._retries,
                "fallbacks": self._fallbacks,
                "shed": self._shed,
            },
            "tracing": reqtrace.enabled(),
        }
        if self.breakers is not None:
            out["breakers"] = self.breakers.states()
        if reqtrace.enabled():
            out["latency"] = reqtrace.rolling_summary()
        occ = occupancy.live_summary()
        if occ is not None:
            out["occupancy"] = {
                "device_busy_frac": occ["busy_frac"],
                "bubble_seconds": occ["bubbles_s"],
                "by_device": occ["devices"],
            }
        return out

    def _maybe_dump_status(self) -> None:
        """The CST_SERVE_STATUS_EVERY hook: at most one status line per
        interval, as `serve_status: {...}` on stderr (stdout stays the
        benches' one-JSON-line-per-metric contract)."""
        if self._status_every <= 0:
            return
        now = time.perf_counter()
        if now - self._status_last < self._status_every:
            return
        self._status_last = now
        telemetry.count("serve.status_dump")
        print("serve_status: " + json.dumps(self.status()),
              file=sys.stderr, flush=True)

    def stats(self) -> dict:
        """Plain-dict accounting for the bench `"serve"` block (does not
        depend on CST_TELEMETRY)."""
        out = {
            "submitted": self._submitted,
            "settled": self._settled,
            "failed": self._failed,
            "rechecks": self._rechecks,
            "batches": self._dispatched_batches,
            "retries": self._retries,
            "fallbacks": self._fallbacks,
            "shed": self._shed,
            "queue_depth": {"max": self._queue_max,
                            "hist": dict(self._queue_hist)},
            "inflight_max": self._inflight_max,
        }
        if self.breakers is not None:
            out["breakers"] = self.breakers.states()
        if self.mesh is not None:
            out["mesh"] = self.mesh.block()
        return out
