"""Serving subsystem — deferred-result futures + the batching executor.

The spine of a serving system (ROADMAP: "Async deferred-result device
API" + "sustained-load attestation-verification service"): every device
result in this repo — pairing bools, MSM points, sha256 roots, fr_batch
field elements — is available as a `DeviceFuture` handle
(`serve.futures`), and `ServeExecutor` (`serve.executor`) drains a
request queue into AOT-warmed executables on the `_bucket` shape
ladder, settling futures in topological batches while the host keeps
preparing the next batch (double-buffered: batch N settles only after
batch N+1 has been dispatched).

`serve.loadgen` drives the executor at (multiples of) mainnet per-slot
rates and reports steady-state verifies/sec plus p50/p99 batch latency;
`python -m consensus_specs_tpu.serve` is the CLI, `bench_serve.py` the
benchwatch-emitting harness.

Import discipline: this package init imports ONLY `futures` eagerly —
the ops device modules import `serve.futures` for their async APIs, and
`serve.executor` imports the ops modules, so the executor/loadgen names
resolve lazily to keep the import graph acyclic.
"""

from __future__ import annotations

from . import futures
from .futures import DeviceFuture, FutureError, bool_future, value_future

__all__ = [
    "DeviceFuture", "FutureError", "ServeExecutor", "bool_future",
    "futures", "run_load", "value_future",
]

_LAZY = {
    "ServeExecutor": ("executor", "ServeExecutor"),
    "executor": ("executor", None),
    "loadgen": ("loadgen", None),
    "run_load": ("loadgen", "run_load"),
}


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(name)
    import importlib

    module = importlib.import_module(f".{entry[0]}", __name__)
    return module if entry[1] is None else getattr(module, entry[1])
