"""Deferred-result futures — the device→host settle seam.

Every device computation in this repo used to end with a blocking
coercion at its API boundary (`bool(out)`, `np.asarray(out)` — the
`host-sync-*` seams the analyzer inventoried through PR 3).  This module
replaces that pattern with ONE contract: device entry points return a
`DeviceFuture` handle, callers keep issuing work (jax dispatch is
asynchronous — the device keeps executing while Python runs ahead), and
the blocking transfer happens exactly once, at `result()` time, HERE.

This file is the analyzer's sanctioned settle seam: the
`host-sync-outside-settle` rule fails `make lint` on any new blocking
fetch added to a device module outside it, so the serialization points
the ROADMAP's async item asked to retire cannot silently grow back.

Three flavors of future, one class:

- device-backed   (`value_future`, `bool_future`): wraps a live device
                  value plus an optional host-side `convert`; `result()`
                  fetches (the only sync), converts, caches.
- immediate       (`DeviceFuture.settled` / `.failed`): degenerate paths
                  that never reached a kernel still hand back the same
                  handle type, so callers never branch on "was this
                  deferred?".
- externally settled (`DeviceFuture(waiter=...)`): the serve executor's
                  per-request handles — `set_result`/`set_exception`
                  settle them in topological batches; a `result()` call
                  on a still-pending handle invokes the waiter (which
                  pumps the owning executor) instead of deadlocking.

Bounded waits: `result(timeout=...)` / `exception(timeout=...)` raise a
typed `FutureTimeout` instead of blocking forever — a wedged executor
(or a device fetch that never completes) was the one un-boundable wait
in the serve path.  A device-backed fetch under a timeout runs on a
daemon thread: the caller gets `FutureTimeout` when the budget runs
out, the fetch keeps going, and a later `result()` joins the SAME
fetch (never a second transfer).  Timeout-aware waiters (the serve
executor's `_settle_until`) receive the remaining budget; a plain
single-argument waiter is invoked untimed (best effort) and the
timeout contract still raises if it returns without settling.  A
timeout never settles the future — retrying is always legal.

Exception propagation is part of the contract: a failed device batch
settles every pending handle with the exception, and `result()`
re-raises it for each caller (`exception()` reads it without raising).

Fault-injection seam (`resilience.faults`, OFF by default): the
device-backed settle is the `future_settle` site — an injected fault
settles THIS future with the typed `FaultInjected`, exactly like a real
failed transfer.

Imports numpy only — never jax (fetching goes through `np.asarray`,
which blocks on the device value's readiness via the array protocol),
so importing this module can never initialize a backend.
"""

from __future__ import annotations

import numpy as np

from ..resilience import faults
from ..telemetry import occupancy

_UNSET = object()

PENDING = "pending"
DONE = "done"


class FutureError(RuntimeError):
    """A future was used against its lifecycle (unsettled result() with
    no waiter, double set_result, ...)."""


class FutureTimeout(FutureError, TimeoutError):
    """A bounded `result(timeout=...)` ran out before the future
    settled.  The future stays PENDING — the caller may retry."""


def _fetch(value):
    """Device value -> host numpy, recursing through point tuples.  The
    one blocking transfer of the futures contract lives here."""
    if isinstance(value, (tuple, list)):
        return tuple(_fetch(v) for v in value)
    return np.asarray(value)


def _waiter_accepts_timeout(waiter) -> bool:
    import inspect

    try:
        sig = inspect.signature(waiter)
    except (TypeError, ValueError):
        return False
    for p in sig.parameters.values():
        if p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD):
            return True
        if p.name == "timeout":
            return True
    return False


class DeviceFuture:
    """Handle for a deferred device result.  See the module docstring
    for the three construction flavors.  `ctx` is the request-tracing
    context (telemetry.reqtrace) the serve executor attaches to its
    per-request handles — a bounded wait that runs out stamps it with
    the provisional `timeout` outcome, so an abandoned handle stays
    attributable even though nothing ever settles it."""

    __slots__ = ("_state", "_value", "_exc", "_device", "_convert",
                 "_waiter", "_fetcher", "ctx")

    def __init__(self, device=_UNSET, convert=None, waiter=None):
        self._state = PENDING
        self._value = None
        self._exc = None
        self._device = device
        self._convert = convert
        self._waiter = waiter
        self._fetcher = None
        self.ctx = None

    # --- construction helpers -----------------------------------------------

    @classmethod
    def settled(cls, value) -> "DeviceFuture":
        """An already-resolved future (degenerate paths that never
        dispatched)."""
        fut = cls()
        fut._state = DONE
        fut._value = value
        return fut

    @classmethod
    def failed(cls, exc: BaseException) -> "DeviceFuture":
        fut = cls()
        fut._state = DONE
        fut._exc = exc
        return fut

    # --- settling (executor side) -------------------------------------------

    def set_result(self, value) -> None:
        if self._state is not PENDING:
            raise FutureError("future already settled")
        self._value = value
        self._state = DONE
        self._waiter = None      # release the executor/batch closure
        self._convert = None

    def set_exception(self, exc: BaseException) -> None:
        if self._state is not PENDING:
            raise FutureError("future already settled")
        self._exc = exc
        self._state = DONE
        self._waiter = None
        self._convert = None

    # --- reading (caller side) ----------------------------------------------

    def done(self) -> bool:
        return self._state is DONE

    def exception(self, timeout: float | None = None) -> BaseException | None:
        """The settling exception, without raising; resolves a pending
        device-backed future first (same as result()).  A handle that
        cannot settle at all (no value, no waiter, or a waiter that
        returns without settling) re-raises the lifecycle FutureError —
        returning None there would misreport the future as succeeded —
        and a `timeout` that runs out re-raises the `FutureTimeout`
        (the future is still pending: there IS no outcome to read)."""
        if self._state is PENDING:
            try:
                self.result(timeout=timeout)
            except FutureError:
                if self._state is PENDING:
                    raise
            # cst: allow(exc-swallow-device): the settling exception was
            # already stored in _exc by result(); this read-side probe
            # must report it via the return value, not re-raise it
            except BaseException:
                pass
        return self._exc

    # --- the device-backed settle (the ONE blocking transfer) ---------------

    def _settle_from_device(self) -> None:
        try:
            # resilience seam: an injected settle fault poisons exactly
            # this future, like a real failed transfer
            if faults.active():
                faults.maybe_inject("future_settle", "device")
            host = _fetch(self._device)
            self._value = (self._convert(host)
                           if self._convert is not None else host)
        except BaseException as exc:
            self._exc = exc
        finally:
            self._state = DONE
            self._device = None      # release the device ref
            self._convert = None
            # occupancy ledger: a device→host settle means everything
            # enqueued before it on this device's in-order stream has
            # executed — close the open kernel busy spans
            occupancy.note_settled()

    def result(self, timeout: float | None = None):
        """The host value.  Device-backed futures fetch-and-convert on
        first call (the blocking transfer); externally settled futures
        invoke their waiter until settled.  Cached thereafter; a failed
        future re-raises its exception on every call.  With `timeout`
        (seconds) the wait is bounded by the typed `FutureTimeout`."""
        if self._state is PENDING:
            if self._fetcher is not None or self._device is not _UNSET:
                self._await_device(timeout)
            elif self._waiter is not None:
                if timeout is None:
                    self._waiter(self)
                    if self._state is PENDING:
                        raise FutureError(
                            "waiter returned without settling the future")
                else:
                    import time

                    t0 = time.perf_counter()
                    if _waiter_accepts_timeout(self._waiter):
                        self._waiter(self, timeout=float(timeout))
                    else:
                        self._waiter(self)
                    if self._state is PENDING:
                        # a waiter that gave back with budget LEFT hit
                        # the lifecycle wall (nothing can ever settle
                        # this handle) — FutureTimeout there would send
                        # retry loops spinning on a dead future
                        if time.perf_counter() - t0 + 1e-3 \
                                >= float(timeout):
                            if self.ctx is not None:
                                self.ctx.note_timeout()
                            raise FutureTimeout(
                                f"future still pending after {timeout}s")
                        raise FutureError(
                            "waiter returned without settling the future")
            else:
                raise FutureError(
                    "future is pending and has no device value or "
                    "waiter — settle it via the serve executor")
        if self._exc is not None:
            raise self._exc
        return self._value

    def _await_device(self, timeout: float | None) -> None:
        """Settle a device-backed future, optionally within `timeout`
        seconds.  The bounded path moves the fetch to a daemon thread
        so an unready device value cannot wedge the caller; repeated
        calls join the same in-flight fetch."""
        if timeout is None and self._fetcher is None:
            self._settle_from_device()
            return
        if self._fetcher is None:
            import threading

            self._fetcher = threading.Thread(
                target=self._settle_from_device, daemon=True)
            self._fetcher.start()
        self._fetcher.join(timeout)
        if self._state is PENDING:
            raise FutureTimeout(
                f"device fetch still pending after {timeout}s")
        self._fetcher = None


def value_future(device_value, convert=None) -> DeviceFuture:
    """Future over a device value; `convert` runs host-side on the
    fetched numpy value(s) at settle time."""
    return DeviceFuture(device=device_value, convert=convert)


def _as_bool(host) -> bool:
    return bool(host)


def bool_future(device_value) -> DeviceFuture:
    """Future over a device predicate; `result()` is a python bool."""
    return DeviceFuture(device=device_value, convert=_as_bool)
