"""Deferred-result futures — the device→host settle seam.

Every device computation in this repo used to end with a blocking
coercion at its API boundary (`bool(out)`, `np.asarray(out)` — the
`host-sync-*` seams the analyzer inventoried through PR 3).  This module
replaces that pattern with ONE contract: device entry points return a
`DeviceFuture` handle, callers keep issuing work (jax dispatch is
asynchronous — the device keeps executing while Python runs ahead), and
the blocking transfer happens exactly once, at `result()` time, HERE.

This file is the analyzer's sanctioned settle seam: the
`host-sync-outside-settle` rule fails `make lint` on any new blocking
fetch added to a device module outside it, so the serialization points
the ROADMAP's async item asked to retire cannot silently grow back.

Three flavors of future, one class:

- device-backed   (`value_future`, `bool_future`): wraps a live device
                  value plus an optional host-side `convert`; `result()`
                  fetches (the only sync), converts, caches.
- immediate       (`DeviceFuture.settled` / `.failed`): degenerate paths
                  that never reached a kernel still hand back the same
                  handle type, so callers never branch on "was this
                  deferred?".
- externally settled (`DeviceFuture(waiter=...)`): the serve executor's
                  per-request handles — `set_result`/`set_exception`
                  settle them in topological batches; a `result()` call
                  on a still-pending handle invokes the waiter (which
                  pumps the owning executor) instead of deadlocking.

Exception propagation is part of the contract: a failed device batch
settles every pending handle with the exception, and `result()`
re-raises it for each caller (`exception()` reads it without raising).

Imports numpy only — never jax (fetching goes through `np.asarray`,
which blocks on the device value's readiness via the array protocol),
so importing this module can never initialize a backend.
"""

from __future__ import annotations

import numpy as np

_UNSET = object()

PENDING = "pending"
DONE = "done"


class FutureError(RuntimeError):
    """A future was used against its lifecycle (unsettled result() with
    no waiter, double set_result, ...)."""


def _fetch(value):
    """Device value -> host numpy, recursing through point tuples.  The
    one blocking transfer of the futures contract lives here."""
    if isinstance(value, (tuple, list)):
        return tuple(_fetch(v) for v in value)
    return np.asarray(value)


class DeviceFuture:
    """Handle for a deferred device result.  See the module docstring
    for the three construction flavors."""

    __slots__ = ("_state", "_value", "_exc", "_device", "_convert",
                 "_waiter")

    def __init__(self, device=_UNSET, convert=None, waiter=None):
        self._state = PENDING
        self._value = None
        self._exc = None
        self._device = device
        self._convert = convert
        self._waiter = waiter

    # --- construction helpers -----------------------------------------------

    @classmethod
    def settled(cls, value) -> "DeviceFuture":
        """An already-resolved future (degenerate paths that never
        dispatched)."""
        fut = cls()
        fut._state = DONE
        fut._value = value
        return fut

    @classmethod
    def failed(cls, exc: BaseException) -> "DeviceFuture":
        fut = cls()
        fut._state = DONE
        fut._exc = exc
        return fut

    # --- settling (executor side) -------------------------------------------

    def set_result(self, value) -> None:
        if self._state is not PENDING:
            raise FutureError("future already settled")
        self._state = DONE
        self._value = value
        self._waiter = None      # release the executor/batch closure
        self._convert = None

    def set_exception(self, exc: BaseException) -> None:
        if self._state is not PENDING:
            raise FutureError("future already settled")
        self._state = DONE
        self._exc = exc
        self._waiter = None
        self._convert = None

    # --- reading (caller side) ----------------------------------------------

    def done(self) -> bool:
        return self._state is DONE

    def exception(self) -> BaseException | None:
        """The settling exception, without raising; resolves a pending
        device-backed future first (same as result()).  A handle that
        cannot settle at all (no value, no waiter, or a waiter that
        returns without settling) re-raises the lifecycle FutureError —
        returning None there would misreport the future as succeeded."""
        if self._state is PENDING:
            try:
                self.result()
            except FutureError:
                if self._state is PENDING:
                    raise
            except BaseException:
                pass
        return self._exc

    def result(self):
        """The host value.  Device-backed futures fetch-and-convert on
        first call (the blocking transfer); externally settled futures
        invoke their waiter until settled.  Cached thereafter; a failed
        future re-raises its exception on every call."""
        if self._state is PENDING:
            if self._device is not _UNSET:
                try:
                    host = _fetch(self._device)
                    self._value = (self._convert(host)
                                   if self._convert is not None else host)
                except BaseException as exc:
                    self._exc = exc
                finally:
                    self._state = DONE
                    self._device = None      # release the device ref
                    self._convert = None
            elif self._waiter is not None:
                self._waiter(self)
                if self._state is PENDING:
                    raise FutureError(
                        "waiter returned without settling the future")
            else:
                raise FutureError(
                    "future is pending and has no device value or "
                    "waiter — settle it via the serve executor")
        if self._exc is not None:
            raise self._exc
        return self._value


def value_future(device_value, convert=None) -> DeviceFuture:
    """Future over a device value; `convert` runs host-side on the
    fetched numpy value(s) at settle time."""
    return DeviceFuture(device=device_value, convert=convert)


def _as_bool(host) -> bool:
    return bool(host)


def bool_future(device_value) -> DeviceFuture:
    """Future over a device predicate; `result()` is a python bool."""
    return DeviceFuture(device=device_value, convert=_as_bool)
