"""Sustained-load generator — mainnet-rate traffic against ServeExecutor.

Models the steady traffic a production verifier faces (ROADMAP's
"sustained-load attestation-verification service benchmark"): ~1M
validators' attestations arrive per epoch as per-slot aggregate
statements, alongside one sync-committee aggregate, blob-KZG
evaluations, and state-root merkleizations.  The generator feeds that
mix — at a multiple of the mainnet arrival rate, or in closed-loop mode
at whatever rate the device sustains — through one `ServeExecutor` and
measures windowed throughput until it reaches steady state.

Arrival model (per mainnet slot, 12 s):

    64  attestation aggregate statements (MAX_COMMITTEES_PER_SLOT —
        1,048,576 validators / 32 slots / ~512-strong committees)
     1  sync-committee aggregate (pairing check)
     6  blob-KZG barycentric evaluations (BASELINE config #5's blobs)
     1  state-root sha256 merkleization
     2  batched SSZ single-proof emissions from a persistent
        `parallel.incremental.MerkleForest` (`submit_proof_request` —
        the stateless-client proof queries light clients issue)
     2  data-column sampling checks (`submit_das_sample` — the PeerDAS
        custody columns a node re-verifies per slot; samples queued in
        the same pump fold into ONE RLC cell-proof equation;
        CST_DAS_SAMPLES_PER_SLOT overrides, 0 disables the lane)
     2  fork-choice attestation batches + 1 LMD-GHOST head poll
        (`submit_attestation_batch`/`submit_head_request` against a
        synthetic proto-array store — the per-attestation bookkeeping
        every client runs; CST_FC_ATTS_PER_SLOT overrides, 0 disables
        the lane and its head poll)
     0  damaged-blob reconstructions (`submit_recover_request` — the
        super-node path: erasure-decode a >= 50%-surviving cell set and
        FK20 re-prove it on device; the heaviest single request, so
        OPT-IN via CST_DAS_RECOVER_PER_SLOT, with CST_DAS_RECOVER_COLS
        surviving cells per ingest)

`rate <= 0` switches to closed-loop mode: the generator keeps
`max_batch * (depth + 1)` requests outstanding and the measured rate IS
the device's sustained capacity — the mode the CPU smoke uses, since a
fixed open-loop rate on an arbitrary CI host would either idle or grow
the queue without bound.

Steady state: windowed verifies/sec, steady when the last 3 windows sit
within ±20% of their mean; the run extends past the configured window
count (up to 3x) until that holds, so "reaches steady state" is a
measured property, not an assumption.  Kernel warmup (AOT precompile of
the `_bucket` rungs the load will hit) happens before the clock starts.

Knobs (all `CST_SERVE_*`, see README "Serving"): duration, rate
multiple, statement-pool size, committee size, window count, max batch,
pipeline depth.
"""

from __future__ import annotations

import itertools
import os
import time
from dataclasses import dataclass

from .. import telemetry
from ..telemetry import metrics_export, monitor, occupancy, reqtrace
from .executor import ServeExecutor

SLOT_SECONDS = 12.0
MAINNET_VALIDATORS = 1_048_576          # the Wonderboom million-scale regime
ATT_STATEMENTS_PER_SLOT = 64            # MAX_COMMITTEES_PER_SLOT aggregates
SYNC_STATEMENTS_PER_SLOT = 1
KZG_EVALS_PER_SLOT = 6
SHA_ROOTS_PER_SLOT = 1
PROOF_REQUESTS_PER_SLOT = 2             # stateless-client proof queries


# an unparseable value fails loudly at import, like every other
# CST_SERVE_* knob — a typo'd "disable" must not silently run the lane
DAS_SAMPLES_PER_SLOT = max(
    0, int(os.environ.get("CST_DAS_SAMPLES_PER_SLOT", 2)))
# fork-choice lane: attestation batches feeding the proto-array store
# per slot (each batch carries FC_BATCH_MESSAGES latest-message
# updates) plus one LMD-GHOST head poll; 0 disables the lane
FC_ATTS_PER_SLOT = max(
    0, int(os.environ.get("CST_FC_ATTS_PER_SLOT", 2)))
HEAD_POLLS_PER_SLOT = 1 if FC_ATTS_PER_SLOT else 0
FC_BATCH_MESSAGES = 64
# super-node lane: damaged-blob reconstructions per slot (ingest a
# >= 50%-surviving cell set, reconstruct + FK20 re-prove on device,
# re-serve; the breaker degrades to the pure-Python oracle).  A full
# reconstruction is the heaviest single request the executor carries,
# so the lane is OPT-IN (default 0); CST_DAS_RECOVER_COLS sets how many
# cells survive each damaged ingest (default 64 — exactly half, the
# worst recoverable case)
RECOVER_PER_SLOT = max(
    0, int(os.environ.get("CST_DAS_RECOVER_PER_SLOT", 0)))
RECOVER_COLS = min(128, max(
    64, int(os.environ.get("CST_DAS_RECOVER_COLS", 64))))
STATEMENTS_PER_SLOT = (ATT_STATEMENTS_PER_SLOT + SYNC_STATEMENTS_PER_SLOT
                       + KZG_EVALS_PER_SLOT + SHA_ROOTS_PER_SLOT
                       + PROOF_REQUESTS_PER_SLOT + DAS_SAMPLES_PER_SLOT
                       + FC_ATTS_PER_SLOT + HEAD_POLLS_PER_SLOT
                       + RECOVER_PER_SLOT)
STEADY_TOL = 0.2


@dataclass
class LoadConfig:
    duration_s: float = 45.0
    rate: float = 4.0        # multiple of the mainnet arrival rate; <= 0
                             # switches to closed-loop (device-capacity) mode
    pool: int = 32           # distinct precomputed statements to cycle
    committee: int = 64      # aggregated keys per attestation statement
    windows: int = 6         # throughput windows inside duration_s
    max_batch: int = 128     # statements per RLC dispatch (ladder rung)
    depth: int = 2           # in-flight batches (double-buffer default)

    def __post_init__(self):
        # Steady-state needs 3 windows; the clamp lives here so every
        # construction path (env, CLI flags, tests) gets it.
        self.windows = max(3, int(self.windows))
        self.pool = max(1, int(self.pool))
        self.max_batch = max(1, int(self.max_batch))
        self.depth = max(1, int(self.depth))


def config_from_env() -> LoadConfig:
    """LoadConfig with CST_SERVE_* overrides applied to the defaults."""
    d = LoadConfig()
    return LoadConfig(
        duration_s=float(os.environ.get("CST_SERVE_DURATION_S",
                                        d.duration_s)),
        rate=float(os.environ.get("CST_SERVE_RATE", d.rate)),
        pool=int(os.environ.get("CST_SERVE_POOL", d.pool)),
        committee=int(os.environ.get("CST_SERVE_COMMITTEE", d.committee)),
        windows=int(os.environ.get("CST_SERVE_WINDOWS", d.windows)),
        max_batch=int(os.environ.get("CST_SERVE_MAX_BATCH", d.max_batch)),
        depth=int(os.environ.get("CST_SERVE_DEPTH", d.depth)),
    )


def steady_state(rates, tol: float = STEADY_TOL) -> bool:
    """True when the last 3 window rates sit within ±tol of their mean."""
    if len(rates) < 3:
        return False
    last = rates[-3:]
    mean = sum(last) / 3.0
    if mean <= 0:
        return False
    return all(abs(r - mean) <= tol * mean for r in last)


def percentile_ms(latencies_s, q: float) -> float | None:
    """q-th percentile of a latency sample, in milliseconds (None on
    empty input).  Delegates to `reqtrace._percentile` — ONE
    nearest-rank implementation, so the serve block's p50/p99 and the
    attribution engine's per-kind percentiles can never diverge on the
    same round's data."""
    if not latencies_s:
        return None
    return round(reqtrace._percentile(sorted(latencies_s), q) * 1e3, 3)


WORST_EXEMPLARS = 5     # exemplar traces retained in latency_attribution


def latency_block(ex) -> tuple[float | None, float | None, dict | None]:
    """(p50_ms, p99_ms, latency_attribution) for one finished drive.

    Traced rounds (CST_TRACE_REQUESTS) compute the percentiles from the
    per-request lifecycle records — submit→complete, answered requests
    only — and attach `reqtrace.attribution()` (per-kind p50/p90/p99
    decomposed into queue_wait/batch_form/device_wall/settle/detour,
    worst-N exemplars).  Untraced rounds return the executor's
    enqueue→settle sample and no attribution.  ONE implementation so
    `run_load` and the chaos harness cannot diverge on latency
    semantics."""
    if not reqtrace.enabled():
        return (percentile_ms(ex.latencies_s, 0.50),
                percentile_ms(ex.latencies_s, 0.99), None)
    recs = reqtrace.records()
    answered = [r["e2e_s"] for r in recs
                if r.get("e2e_s") is not None
                and r.get("outcome") in reqtrace.ANSWERED]
    return (percentile_ms(answered, 0.50),
            percentile_ms(answered, 0.99),
            reqtrace.attribution(recs, worst_n=WORST_EXEMPLARS))


# --- request payload pools ---------------------------------------------------


def build_statement_pool(n_tasks: int, keys_per_task: int,
                         seed_base: int = 7000):
    """Valid FastAggregateVerify statements as (agg_pk, msg, sig) oracle
    points — the aggregate-secret-key shortcut (one scalar mult per
    side), identical in shape to real per-key aggregation."""
    from ..ops.bls import ciphersuite as cs
    from ..ops.bls.curve import g1, g2
    from ..ops.bls.hash_to_curve import DST_G2, hash_to_g2

    tasks = []
    for t in range(n_tasks):
        msg = (seed_base + t).to_bytes(32, "little")
        h = hash_to_g2(msg, DST_G2)
        agg_sk = sum(seed_base + t * keys_per_task + i + 1
                     for i in range(keys_per_task))
        tasks.append((g1.mul(cs.G1_GEN, agg_sk), msg, g2.mul(h, agg_sk)))
    return tasks


def _pairing_payload(task):
    """A sync-aggregate-shaped pairing check for one pool statement —
    the shared FastAggregateVerify identity."""
    from ..ops.bls.ciphersuite import fast_aggregate_pairs

    return fast_aggregate_pairs(task)


def _fr_payload(width: int = 4):
    """A width-W barycentric evaluation (minimal-preset blob shape)."""
    from ..ops.fr_batch import R_MODULUS

    g = pow(7, (R_MODULUS - 1) // width, R_MODULUS)
    roots = [pow(g, i, R_MODULUS) for i in range(width)]
    poly = [(3 * i + 2) % R_MODULUS for i in range(width)]
    return (poly, roots, 0x1234567)


def _sha_payload():
    import numpy as np

    return (np.arange(64, dtype=np.uint32).reshape(8, 8), 3)


def _das_payloads(n_blobs: int = 2, columns=(0, 17)):
    """A tiny closed-form sampling matrix cut into per-column
    `DasSample`s (cycled by the das lane) — real pairing statements,
    zero MSM setup cost (`das.ciphersuite.closed_form_matrix`)."""
    from ..das.ciphersuite import closed_form_matrix
    from ..das.sampling import sample_from_matrix

    matrix = closed_form_matrix(n_blobs, columns=columns)
    return [sample_from_matrix(*matrix, column) for column in columns]


def _fc_payload(n_blocks: int = 48, n_validators: int = 256,
                batch: int = FC_BATCH_MESSAGES):
    """A synthetic proto-array store plus an infinite attestation-batch
    stream — the `submit_attestation_batch`/`submit_head_request`
    lane's payload (`forkchoice.synthetic`, the same builder the bench
    worker sweeps)."""
    from ..forkchoice.synthetic import attestation_stream, synthetic_store

    store, roots = synthetic_store(n_blocks, n_validators, seed=53)
    return store, attestation_stream(roots, n_validators, batch,
                                     seed=53)


def _recover_payloads(n_patterns: int = 3, survive: int = RECOVER_COLS,
                      seed: int = 4100):
    """Damaged-blob ingests for the super-node lane: one low-degree
    (closed-form) blob's full cell set, cut down to `survive` cells
    under `n_patterns` distinct damage patterns (cycled by the lane).
    The blob is degree-65 so building the ground-truth cells costs two
    host FFTs, not an MSM."""
    import random

    from ..das import ciphersuite as dcs
    from ..das import compute as dc

    roots = dcs.roots_of_unity(dcs.FIELD_ELEMENTS_PER_BLOB)
    evals = []
    for i in range(dcs.FIELD_ELEMENTS_PER_BLOB):
        x = roots[dcs.reverse_bits(i, dcs.FIELD_ELEMENTS_PER_BLOB)]
        evals.append((seed * pow(x, 65, dcs.BLS_MODULUS)
                      + (seed + 1) * pow(x, 64, dcs.BLS_MODULUS)
                      + seed + 2) % dcs.BLS_MODULUS)
    cells = dc.compute_cells(dcs._encode_evals(evals), device=False)
    rng = random.Random(seed)
    out = []
    for _ in range(n_patterns):
        keep = sorted(rng.sample(range(dcs.CELLS_PER_EXT_BLOB),
                                 survive))
        out.append((keep, [cells[k] for k in keep]))
    return out


def _proof_payload(n_leaves: int = 256, batch: int = 16):
    """A persistent `MerkleForest` plus one index batch — the
    `submit_proof_request` payload shape (the forest is built once and
    shared across every proof request of the run, exactly the
    stateless-client serving posture)."""
    import numpy as np

    from ..parallel.incremental import MerkleForest

    rng = np.random.RandomState(31)
    words = rng.randint(0, 2**32, (n_leaves, 8),
                        dtype=np.uint64).astype(np.uint32)
    forest = MerkleForest(words, 10, n_leaves)
    return (forest, [int(i) for i in rng.choice(n_leaves, batch,
                                                replace=False)])


# self-scrape artifact (written whenever the CST_METRICS_PORT endpoint
# is live during a measured load): the exposition text exactly as an
# external Prometheus would have seen it, validated line-by-line by
# bench_smoke's serve round.  The round scrapes mid-load and again
# after the drain; the kept snapshot is the latest one, so the
# artifact carries every served kind as a labeled series
SCRAPE_ARTIFACT = "out/metrics_scrape.txt"


def scrape_live_endpoint() -> str | None:
    """One GET against the process's own exposition endpoint — the
    mid-round scrape.  Returns the exposition text, or None when the
    endpoint is down (never raises: a failed scrape must not fail the
    measured load)."""
    port = metrics_export.serving_port()
    if port is None:
        return None
    import urllib.request

    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            return resp.read().decode("utf-8")
    except Exception as exc:
        # recorded, not raised: a failed scrape must not fail the round
        telemetry.count("serve.scrape_failed")
        telemetry.add_event("serve.scrape_failed", 0.0,
                            error=type(exc).__name__)
        return None


def write_scrape_artifact(text: str, path: str = SCRAPE_ARTIFACT) -> str:
    dirname = os.path.dirname(path)
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    with open(path, "w") as f:
        f.write(text)
    return path


# --- the load loop -----------------------------------------------------------


def make_submitter(ex, pool, payloads, track=None):
    """The ONE implementation of the mainnet per-slot arrival mix (see
    module docstring): returns `(submit_next, kinds_submitted)` where
    each `submit_next()` call submits the next request of the cycled
    slot schedule to `ex`.  `track(kind, future)`, when given, sees
    every submitted handle — the chaos harness's correctness-tracking
    hook.  Shared by `run_load` and `resilience.chaos.run_chaos_load`
    so the two drives cannot diverge on the traffic shape."""
    schedule = itertools.cycle(
        ["verify"] * ATT_STATEMENTS_PER_SLOT
        + ["pairing"] * SYNC_STATEMENTS_PER_SLOT
        + ["fr"] * KZG_EVALS_PER_SLOT
        + ["sha256"] * SHA_ROOTS_PER_SLOT
        + ["proof"] * PROOF_REQUESTS_PER_SLOT
        + ["das"] * DAS_SAMPLES_PER_SLOT
        + ["fc_atts"] * FC_ATTS_PER_SLOT
        + ["head"] * HEAD_POLLS_PER_SLOT
        + ["recover"] * RECOVER_PER_SLOT)
    pool_iter = itertools.cycle(pool)
    das_iter = itertools.cycle(payloads["das"]) if payloads.get("das") \
        else None
    recover_iter = itertools.cycle(payloads["recover"]) \
        if payloads.get("recover") else None
    fc_store, fc_batches = payloads["fc"] if payloads.get("fc") \
        else (None, None)
    kinds_submitted = {k: 0 for k in ("verify", "pairing", "fr",
                                      "sha256", "proof", "das",
                                      "recover", "fc_atts", "head")}

    def submit_next():
        kind = next(schedule)
        kinds_submitted[kind] += 1
        if kind == "verify":
            fut = ex.submit_verify_task(next(pool_iter))
        elif kind == "pairing":
            fut = ex.submit_pairing(payloads["pairing"])
        elif kind == "fr":
            fut = ex.submit_barycentric(*payloads["fr"])
        elif kind == "sha256":
            fut = ex.submit_sha256_root(*payloads["sha256"])
        elif kind == "das":
            fut = ex.submit_das_sample(next(das_iter))
        elif kind == "recover":
            fut = ex.submit_recover_request(*next(recover_iter))
        elif kind == "fc_atts":
            fut = ex.submit_attestation_batch(fc_store,
                                              *next(fc_batches))
        elif kind == "head":
            fut = ex.submit_head_request(fc_store)
        else:
            fut = ex.submit_proof_request(*payloads["proof"])
        if track is not None:
            track(kind, fut)

    return submit_next, kinds_submitted


def drive_closed_loop(ex, submit_next, target_outstanding: int,
                      window_end: float) -> None:
    """One closed-loop drive window: keep `target_outstanding`
    requests outstanding and pump until `window_end`
    (`time.perf_counter()` deadline) — the device-capacity mode both
    the CPU smoke and the chaos phases measure."""
    while time.perf_counter() < window_end:
        while ex.outstanding() < target_outstanding:
            submit_next()
        ex.pump()


def _warm_kernels(cfg: LoadConfig, pool, payloads) -> float:
    """AOT-compile every executable the load will hit, OUTSIDE the
    measured window; returns the warmup wall."""
    from ..ops.bls_batch import (
        _BUCKET_STEPS,
        _bucket,
        batch_verify_async,
        pairing_check_device_async,
    )
    from ..ops.fr_batch import barycentric_eval_async
    from ..ops.sha256_jax import merkleize_words_jax_async
    from ..parallel.incremental import emit_proofs_async

    t0 = time.perf_counter()
    # verify chunks are `max_batch`-sized plus one arbitrary remainder,
    # so EVERY ladder rung up to _bucket(max_batch) is reachable inside
    # the measured window — warm them all (power-of-two rungs past the
    # ladder top for oversized max_batch), or the first chunk landing
    # on a cold rung pays XLA compile inside a throughput window
    top = _bucket(cfg.max_batch)
    rungs = {s for s in _BUCKET_STEPS if s <= top} | {top}
    r = max(_BUCKET_STEPS)
    while r < top:
        r <<= 1
        rungs.add(r)
    for rung in sorted(rungs):
        batch_verify_async([pool[0]] * rung).result()
    pairing_check_device_async(payloads["pairing"]).result()
    barycentric_eval_async(*payloads["fr"]).result()
    merkleize_words_jax_async(*payloads["sha256"]).result()
    emit_proofs_async(*payloads["proof"]).result()
    if payloads.get("das"):
        from ..das.sampling import verify_sample_async

        verify_sample_async(payloads["das"][0], device=True).result()
    if payloads.get("recover"):
        from ..das.recover import recover_cells_and_kzg_proofs_async

        recover_cells_and_kzg_proofs_async(
            *payloads["recover"][0], device=True).result()
    if payloads.get("fc"):
        fc_store, fc_batches = payloads["fc"]
        fc_store.apply_attestations_async(*next(fc_batches)).result()
        fc_store.get_head_async().result()
    return time.perf_counter() - t0


def _default_executor(cfg: LoadConfig) -> ServeExecutor:
    """The load's executor.  With a fault plan active
    (`resilience.faults`), the recovery policies arm automatically —
    retry with backoff plus per-(kind, rung) breakers routing to the
    oracle fallback — so a faulted `make serve-smoke` degrades to
    correct-but-slow answers instead of poisoning requests.  Without a
    plan the executor keeps the plain fail-fast shape (zero resilience
    machinery on the healthy path)."""
    from ..resilience import faults

    retry = breakers = None
    if faults.active():
        from ..resilience.chaos import CHAOS_BREAKER, CHAOS_RETRY
        from ..resilience.policies import BreakerRegistry, RetryPolicy

        retry = RetryPolicy(**CHAOS_RETRY)
        breakers = BreakerRegistry(**CHAOS_BREAKER)
    return ServeExecutor(max_batch=cfg.max_batch, depth=cfg.depth,
                         retry=retry, breakers=breakers)


def run_load(cfg: LoadConfig | None = None, executor=None) -> dict:
    """Drive the serve executor with the configured load; returns the
    bench `"serve"` block (schema pinned by
    `telemetry.export.validate_serve_block`).

    `CST_SERVE_CHAOS=1` delegates to the chaos harness
    (`resilience.chaos.run_chaos_load`: baseline → fault plan live →
    recovery-to-steady), whose block additionally carries the
    `"resilience"` sub-object."""
    cfg = cfg if cfg is not None else config_from_env()
    if executor is None \
            and os.environ.get("CST_SERVE_CHAOS", "0") not in ("", "0"):
        from ..resilience.chaos import run_chaos_load

        return run_chaos_load(cfg)
    pool = build_statement_pool(cfg.pool, cfg.committee)
    payloads = {"pairing": _pairing_payload(pool[0]),
                "fr": _fr_payload(), "sha256": _sha_payload(),
                "proof": _proof_payload(),
                "das": (_das_payloads() if DAS_SAMPLES_PER_SLOT else []),
                "recover": (_recover_payloads() if RECOVER_PER_SLOT
                            else []),
                "fc": (_fc_payload() if FC_ATTS_PER_SLOT else None)}
    warm_s = _warm_kernels(cfg, pool, payloads)
    # a CST_FAULTS plan goes live only AFTER warmup: AOT precompile is
    # setup, not served traffic — the plan's fault budget must land on
    # the measured load (where the executor's recovery ladder answers),
    # not crash the warmup's direct kernel settles
    from ..resilience import faults

    faults.install_from_env()
    ex = executor if executor is not None else _default_executor(cfg)
    # request tracing (CST_TRACE_REQUESTS): scope the lifecycle-record
    # registry to THIS measured load — warmup settles and any earlier
    # run's records must not pollute the attribution
    if reqtrace.enabled():
        reqtrace.reset()
    # occupancy ledger (CST_OCCUPANCY): same scoping rule — the busy /
    # bubble attribution must cover the measured load only, so warmup
    # dispatch stamps are discarded here
    if occupancy.enabled():
        occupancy.reset()
    # live monitoring arms with the measured load (same placement rule
    # as the fault plan: warmup is setup, not served traffic) — the
    # CST_METRICS_PORT endpoint starts scraping this executor's status
    # and the CST_SLO_RULES watchdog begins its tick
    watchdog = monitor.install_from_env(status_provider=ex.status)
    # deterministic per-slot arrival mix (see module docstring)
    submit_next, kinds_submitted = make_submitter(ex, pool, payloads)

    closed_loop = cfg.rate <= 0
    rate_per_s = cfg.rate * STATEMENTS_PER_SLOT / SLOT_SECONDS
    target_outstanding = cfg.max_batch * (cfg.depth + 1)
    window_s = cfg.duration_s / cfg.windows

    rates: list[float] = []
    t0 = time.perf_counter()
    settled_prev = 0
    arrived = 0
    scrape_text = None
    for wi in range(3 * cfg.windows):       # extend (≤3x) until steady
        # Anchor each window at its actual start and divide by the wall
        # it really spanned: a single pump that overruns the nominal
        # boundary (one full RLC settle can) must not fabricate a
        # zero-rate window that defeats the steady-state check.
        win_t0 = time.perf_counter()
        window_end = win_t0 + window_s
        if closed_loop:
            drive_closed_loop(ex, submit_next, target_outstanding,
                              window_end)
        else:
            while time.perf_counter() < window_end:
                due = (time.perf_counter() - t0) * rate_per_s
                while arrived < due:
                    submit_next()
                    arrived += 1
                ex.pump()
                time.sleep(0.002)
        win_elapsed = time.perf_counter() - win_t0
        settled_now = ex.stats()["settled"]
        rates.append((settled_now - settled_prev) / win_elapsed)
        settled_prev = settled_now
        # the mid-round scrape: once, after traffic has flowed for half
        # the configured windows — the exposition snapshot an external
        # scraper would see while the service is under load
        if scrape_text is None and wi + 1 >= max(1, cfg.windows // 2):
            scrape_text = scrape_live_endpoint()
        if wi + 1 >= cfg.windows and steady_state(rates):
            break
    measured_s = time.perf_counter() - t0
    ex.drain()
    # close the occupancy window AFTER the drain so the post-load tail
    # shows up as the `drain` bubble cause instead of vanishing
    occ_block = (occupancy.block(window=(t0, time.perf_counter()),
                                 depth=cfg.depth)
                 if occupancy.enabled() else None)
    # a final live scrape supersedes the mid-round one when it lands:
    # the endpoint and status provider are still wired, and with the
    # queue drained every served kind has completed — so the artifact
    # always carries the full per-kind `cst_serve_requests_total`
    # series set (bench_smoke asserts exactly that; a slow-to-warm
    # kind can be absent from the mid-round snapshot)
    scrape_text = scrape_live_endpoint() or scrape_text
    if scrape_text is not None:
        write_scrape_artifact(scrape_text)
    # finalize the watchdog BEFORE tearing down the status provider so
    # its last tick still sees the live executor
    slo_block = monitor.clear() if watchdog is not None else None
    metrics_export.set_status_provider(None)

    st = ex.stats()
    steady = steady_state(rates)
    steady_rate = (sum(rates[-3:]) / 3.0 if len(rates) >= 3
                   else (st["settled"] / measured_s if measured_s else 0.0))
    # latency basis (the serve-block schema's `latency_source` field):
    # on traced rounds the percentiles are PER-REQUEST, submit→complete
    # from the RequestContext timestamps — the batch-settle-granularity
    # numbers understate the request tail by collapsing every member of
    # a batch onto one settle stamp (and miss retry/fallback detours
    # entirely).  Untraced rounds keep the executor's enqueue→settle
    # sample so the metric never goes dark.
    p50_ms, p99_ms, latency_attribution = latency_block(ex)
    block = {
        "verifies_per_s": round(steady_rate, 2),
        "p50_ms": p50_ms,
        "p99_ms": p99_ms,
        "latency_source": ("reqtrace" if latency_attribution is not None
                          else "executor"),
        "steady": steady,
        "windows": [round(r, 2) for r in rates],
        "window_s": round(window_s, 3),
        "duration_s": round(measured_s, 3),
        "warmup_s": round(warm_s, 3),
        "mode": "closed" if closed_loop else "open",
        "rate_multiple": cfg.rate,
        "offered_per_s": None if closed_loop else round(rate_per_s, 3),
        "pool": cfg.pool,
        "committee": cfg.committee,
        "max_batch": cfg.max_batch,
        "depth": cfg.depth,
        "kinds": kinds_submitted,
        "submitted": st["submitted"],
        "settled": st["settled"],
        "failed": st["failed"],
        "rechecks": st["rechecks"],
        "batches": st["batches"],
        "queue_depth": st["queue_depth"],
        "inflight_max": st["inflight_max"],
    }
    if latency_attribution is not None:
        block["latency_attribution"] = latency_attribution
    if occ_block is not None:
        block["occupancy"] = occ_block
    if slo_block is not None:
        block["slo"] = slo_block
    return block
