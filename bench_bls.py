"""BLS batch benchmarks — BASELINE.md configs #2 and #3.

#2: 128 aggregate-attestation verifications (FastAggregateVerify-style
    statements, 64-strong committees) — device RLC batch (129 pairings
    through ONE shared Fq12 Miller accumulator, one final
    exponentiation, message hash-to-curve on device) vs the pure-Python
    oracle loop.
#3: one 512-member sync-committee aggregate (eth_fast_aggregate_verify
    hot path) — device pairing check with host-precomputed fixed-argument
    Miller lines vs oracle.

Prints one JSON line per metric:
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}

Oracle costs are measured from ONE representative verify and scaled
(each verify is an independent 2-pairing check; the loop is linear), and
persisted in bench_bls_baseline.json next to this file.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import jax

jax.config.update("jax_enable_x64", True)
# the image's sitecustomize pins the platform to the pooled TPU through
# live config; let an explicit JAX_PLATFORMS env override it (CPU smoke)
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from consensus_specs_tpu.utils.jaxtools import enable_compile_cache  # noqa: E402

enable_compile_cache()

BASELINE_FILE = Path(__file__).resolve().parent / "bench_bls_baseline.json"

# env knobs let the smoke path run on CPU; the measured configs are the
# defaults (BASELINE.md #2/#3 shapes) on the real chip
N_ATTESTATIONS = int(os.environ.get("CST_BLS_BENCH_N", 128))
COMMITTEE_SIZE = int(os.environ.get("CST_BLS_BENCH_COMMITTEE", 64))
SYNC_COMMITTEE_SIZE = int(os.environ.get("CST_BLS_BENCH_SYNC", 512))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _build_tasks(n_tasks: int, keys_per_task: int, seed_base: int):
    """Valid FastAggregateVerify statements as (agg_pk, msg, sig) points."""
    from consensus_specs_tpu.ops.bls import ciphersuite as cs
    from consensus_specs_tpu.ops.bls.curve import g1, g2
    from consensus_specs_tpu.ops.bls.hash_to_curve import DST_G2, hash_to_g2

    tasks = []
    raw = []
    for t in range(n_tasks):
        msg = (seed_base + t).to_bytes(32, "little")
        h = hash_to_g2(msg, DST_G2)
        # aggregate secret key -> one scalar mult for pk and sig each;
        # statements are identical in shape to real per-key aggregation
        agg_sk = sum(seed_base + t * keys_per_task + i + 1
                     for i in range(keys_per_task))
        pk = g1.mul(cs.G1_GEN, agg_sk)
        sig = g2.mul(h, agg_sk)
        tasks.append((pk, msg, sig))
        raw.append((cs.g1_to_bytes(pk), msg, cs.g2_to_bytes(sig)))
    return tasks, raw


def _measure_oracle_single(raw_task) -> float:
    from consensus_specs_tpu.ops.bls import ciphersuite as cs

    pk_b, msg, sig_b = raw_task
    t0 = time.perf_counter()
    assert cs.FastAggregateVerify([pk_b], msg, sig_b)
    return time.perf_counter() - t0


def _baselines() -> dict:
    if BASELINE_FILE.exists() and not os.environ.get("CST_BENCH_REMEASURE"):
        return json.loads(BASELINE_FILE.read_text())
    log("measuring oracle baselines (one verify each)...")
    _, raw_att = _build_tasks(1, COMMITTEE_SIZE, seed_base=1000)
    att_single = _measure_oracle_single(raw_att[0])
    _, raw_sync = _build_tasks(1, SYNC_COMMITTEE_SIZE, seed_base=2000)
    sync_single = _measure_oracle_single(raw_sync[0])
    data = {
        "oracle_seconds_per_fast_aggregate_verify": att_single,
        "oracle_seconds_per_sync_aggregate_verify": sync_single,
        "measured_at": time.strftime("%Y-%m-%d"),
    }
    try:
        BASELINE_FILE.write_text(json.dumps(data, indent=2) + "\n")
    except OSError as e:
        log(f"baseline not persisted: {e}")
    return data


def main():
    from consensus_specs_tpu.ops.bls_batch import (
        batch_verify, pairing_check_device)
    from consensus_specs_tpu.ops.bls import ciphersuite as cs
    from consensus_specs_tpu.ops.bls.curve import g1
    from consensus_specs_tpu.ops.bls.hash_to_curve import DST_G2, hash_to_g2

    base = _baselines()

    # config #2: attestation batch
    tasks, _ = _build_tasks(N_ATTESTATIONS, COMMITTEE_SIZE, seed_base=1000)
    t0 = time.perf_counter()
    assert batch_verify(tasks)
    log(f"attestation batch compile+first: {time.perf_counter() - t0:.1f}s")
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        assert batch_verify(tasks)
    dt = (time.perf_counter() - t0) / iters
    baseline = (base["oracle_seconds_per_fast_aggregate_verify"]
                * N_ATTESTATIONS)
    print(json.dumps({
        "metric": f"attestation_batch_{N_ATTESTATIONS}x"
                  f"{COMMITTEE_SIZE}_verify_wall",
        "value": round(dt, 4),
        "unit": "s",
        "vs_baseline": round(baseline / dt, 1),
    }), flush=True)

    # config #3: sync aggregate (one 512-member statement)
    sync_tasks, _ = _build_tasks(1, SYNC_COMMITTEE_SIZE, seed_base=2000)
    pk, msg, sig = sync_tasks[0]
    h = hash_to_g2(msg, DST_G2)
    pairs = [(pk, h), (g1.neg(cs.G1_GEN), sig)]
    t0 = time.perf_counter()
    assert pairing_check_device(pairs)
    log(f"sync aggregate compile+first: {time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    for _ in range(iters):
        assert pairing_check_device(pairs)
    dt = (time.perf_counter() - t0) / iters
    baseline = base["oracle_seconds_per_sync_aggregate_verify"]
    print(json.dumps({
        "metric": f"sync_aggregate_{SYNC_COMMITTEE_SIZE}_verify_wall",
        "value": round(dt, 4),
        "unit": "s",
        "vs_baseline": round(baseline / dt, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
