"""BLS batch benchmarks — BASELINE.md configs #2 and #3.

#2: 128 aggregate-attestation verifications (FastAggregateVerify-style
    statements, 64-strong committees) — device RLC batch (129 pairings
    through ONE shared Fq12 Miller accumulator, one final
    exponentiation, message hash-to-curve on device) vs the pure-Python
    oracle loop.
#3: one 512-member sync-committee aggregate (eth_fast_aggregate_verify
    hot path) — device pairing check with host-precomputed fixed-argument
    Miller lines vs oracle.

Prints one JSON line per metric:
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}

Oracle costs are measured from ONE representative verify and scaled
(each verify is an independent 2-pairing check; the loop is linear), and
persisted in bench_bls_baseline.json next to this file.

With CST_TELEMETRY=1 each metric line also carries a `"telemetry"`
sub-object (compile_s/run_s split, bucket-padding waste, MSM + h2c
routing counts — `consensus_specs_tpu.telemetry.bench_block`), and a
third metric probes the G1 MSM host/device break-even
(`_MSM_DEVICE_MIN`): host-oracle vs device-kernel wall at the sizes in
CST_BLS_BENCH_MSM_SIZES (default "6,16" — config #5's size-6 MSMs and
the current routing threshold), the ROADMAP's open routing question.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import jax

jax.config.update("jax_enable_x64", True)
# the image's sitecustomize pins the platform to the pooled TPU through
# live config; let an explicit JAX_PLATFORMS env override it (CPU smoke)
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from consensus_specs_tpu import telemetry  # noqa: E402
from consensus_specs_tpu.telemetry import history as benchwatch  # noqa: E402
from consensus_specs_tpu.utils.jaxtools import enable_compile_cache  # noqa: E402

enable_compile_cache()

BASELINE_FILE = Path(__file__).resolve().parent / "bench_bls_baseline.json"

# env knobs let the smoke path run on CPU; the measured configs are the
# defaults (BASELINE.md #2/#3 shapes) on the real chip
N_ATTESTATIONS = int(os.environ.get("CST_BLS_BENCH_N", 128))
COMMITTEE_SIZE = int(os.environ.get("CST_BLS_BENCH_COMMITTEE", 64))
SYNC_COMMITTEE_SIZE = int(os.environ.get("CST_BLS_BENCH_SYNC", 512))
# MSM break-even probe sizes; "" disables the probe
MSM_PROBE_SIZES = tuple(
    int(s) for s in os.environ.get("CST_BLS_BENCH_MSM_SIZES",
                                   "6,16").split(",") if s.strip())


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _build_tasks(n_tasks: int, keys_per_task: int, seed_base: int):
    """Valid FastAggregateVerify statements as (agg_pk, msg, sig) points."""
    from consensus_specs_tpu.ops.bls import ciphersuite as cs
    from consensus_specs_tpu.ops.bls.curve import g1, g2
    from consensus_specs_tpu.ops.bls.hash_to_curve import DST_G2, hash_to_g2

    tasks = []
    raw = []
    for t in range(n_tasks):
        msg = (seed_base + t).to_bytes(32, "little")
        h = hash_to_g2(msg, DST_G2)
        # aggregate secret key -> one scalar mult for pk and sig each;
        # statements are identical in shape to real per-key aggregation
        agg_sk = sum(seed_base + t * keys_per_task + i + 1
                     for i in range(keys_per_task))
        pk = g1.mul(cs.G1_GEN, agg_sk)
        sig = g2.mul(h, agg_sk)
        tasks.append((pk, msg, sig))
        raw.append((cs.g1_to_bytes(pk), msg, cs.g2_to_bytes(sig)))
    return tasks, raw


def _measure_oracle_single(raw_task) -> float:
    from consensus_specs_tpu.ops.bls import ciphersuite as cs

    pk_b, msg, sig_b = raw_task
    t0 = time.perf_counter()
    assert cs.FastAggregateVerify([pk_b], msg, sig_b)
    return time.perf_counter() - t0


def _baselines() -> dict:
    if BASELINE_FILE.exists() and not os.environ.get("CST_BENCH_REMEASURE"):
        return json.loads(BASELINE_FILE.read_text())
    log("measuring oracle baselines (one verify each)...")
    _, raw_att = _build_tasks(1, COMMITTEE_SIZE, seed_base=1000)
    att_single = _measure_oracle_single(raw_att[0])
    _, raw_sync = _build_tasks(1, SYNC_COMMITTEE_SIZE, seed_base=2000)
    sync_single = _measure_oracle_single(raw_sync[0])
    data = {
        "oracle_seconds_per_fast_aggregate_verify": att_single,
        "oracle_seconds_per_sync_aggregate_verify": sync_single,
        "measured_at": time.strftime("%Y-%m-%d"),
    }
    try:
        BASELINE_FILE.write_text(json.dumps(data, indent=2) + "\n")
    except OSError as e:
        log(f"baseline not persisted: {e}")
    return data


def _emit(record: dict) -> None:
    """Print one metric line, with the per-config `"telemetry"`
    sub-object embedded on telemetry rounds.  When
    CST_BENCHWATCH_HISTORY names a path, the same record also lands in
    the longitudinal store as a normalized history record
    (`telemetry.history`) — the stdout contract is unchanged."""
    record = telemetry.embed_bench_block(record)
    benchwatch.append_emission(record, ts=time.time())
    print(json.dumps(record), flush=True)


def msm_breakeven_probe(sizes=MSM_PROBE_SIZES, iters: int = 3):
    """Host-oracle vs device-kernel G1 MSM wall per batch size, plus the
    route `ops.bls.multi_exp` actually takes at that size — the data the
    ROADMAP's `_MSM_DEVICE_MIN = 16` open item asks for.  Returns the
    per-size detail dict (empty when disabled via
    CST_BLS_BENCH_MSM_SIZES="")."""
    from consensus_specs_tpu.ops import bls
    from consensus_specs_tpu.ops.bls import ciphersuite as cs
    from consensus_specs_tpu.ops.bls.curve import g1
    from consensus_specs_tpu.ops.bls.fields import R
    from consensus_specs_tpu.ops.bls_batch import g1_multi_exp_device

    detail = {}
    for n in sizes:
        pts = [g1.mul(cs.G1_GEN, 3 * i + 2) for i in range(n)]
        ks = [pow(5, i + 1, R) for i in range(n)]
        tagged = [(1, p) for p in pts]

        t0 = time.perf_counter()
        host_out = cs.multi_exp(tagged, ks)
        host_dt = time.perf_counter() - t0

        t0 = time.perf_counter()
        dev_out = g1_multi_exp_device(pts, ks)
        compile_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(iters):
            dev_out = g1_multi_exp_device(pts, ks)
        dev_dt = (time.perf_counter() - t0) / iters
        assert g1.eq_points(host_out[1], dev_out), f"MSM mismatch at n={n}"

        # where the facade's threshold actually routes this size, read
        # back from the routing counters the call just incremented (one
        # source of truth with the telemetry block); the global backend
        # is restored — the probe must not change what any later
        # measurement runs on
        prev_backend = bls.backend_name()
        dev_before = telemetry.counter_value("msm.route.device")
        try:
            bls.use_backend("jax")
            bls.multi_exp(tagged, ks)
        finally:
            bls.use_backend(prev_backend)
        dev_after = telemetry.counter_value("msm.route.device")
        # counters are the source of truth when collecting; without
        # telemetry (counters frozen) fall back to the threshold itself
        routed_dev = (dev_after > dev_before if telemetry.enabled()
                      else n >= bls._MSM_DEVICE_MIN)
        detail[str(n)] = {
            "host_s": round(host_dt, 4),
            "device_s": round(dev_dt, 4),
            "device_compile_first_s": round(compile_dt, 4),
            # ratio from the UNROUNDED walls: at sub-ms device times the
            # 4-dp display rounding would distort the number the
            # _MSM_DEVICE_MIN decision rides on
            "host_over_device": round(host_dt / dev_dt, 2) if dev_dt
            else None,
            "routed": "device" if routed_dev else "host",
        }
        log(f"msm probe n={n}: host {host_dt:.4f}s device {dev_dt:.4f}s "
            f"(compile+first {compile_dt:.1f}s) -> routed "
            f"{detail[str(n)]['routed']}")
    return detail


def msm_probe_record() -> dict:
    """Run the break-even probe and shape it as one bench metric record
    (metric/value/unit/vs_baseline + per-size detail) — the ONE shape
    this metric has, whether emitted standalone here or embedded in
    bench.py's extras."""
    from consensus_specs_tpu.ops import bls

    detail = msm_breakeven_probe()
    smallest = str(min(MSM_PROBE_SIZES))
    d = detail[smallest]
    return {
        "metric": f"g1_msm_breakeven_probe_n{smallest}",
        "value": d["device_s"],
        "unit": "s",
        # >1.0 means the device kernel beats the host oracle at the
        # smallest probed size => _MSM_DEVICE_MIN should drop
        "vs_baseline": d["host_over_device"],
        "detail": detail,
        "msm_device_min": bls._MSM_DEVICE_MIN,
    }


def costmodel_kernel_sweep():
    """Tiny-shape exercises of the device kernels that do NOT sit on
    this bench's measured path — the sha256 merkle reduction and the
    KZG barycentric evaluator — so a CST_COSTMODEL round's Utilization
    table covers the whole kernel surface, not just the BLS configs.
    Cost records are per-process facts (they survive the per-config
    telemetry resets), so running this during setup is free for the
    measured configs."""
    import numpy as np

    from consensus_specs_tpu.ops import fr_batch, sha256_jax

    words = np.arange(8 * 8, dtype=np.uint32).reshape(8, 8)
    sha256_jax.merkleize_words_jax(words, 3)
    roots = [pow(5, i, fr_batch.R_MODULUS) for i in range(4)]
    fr_batch.barycentric_eval([1, 2, 3, 4], roots, 7)
    telemetry.costmodel.sample_watermark("bench_bls.cost_sweep")


def main():
    from consensus_specs_tpu.ops.bls_batch import (
        batch_verify, pairing_check_device)
    from consensus_specs_tpu.ops.bls import ciphersuite as cs
    from consensus_specs_tpu.ops.bls.curve import g1
    from consensus_specs_tpu.ops.bls.hash_to_curve import DST_G2, hash_to_g2

    base = _baselines()
    if telemetry.costmodel.enabled():
        costmodel_kernel_sweep()
    if telemetry.enabled():
        telemetry.reset()   # drop setup-phase counters; per-config blocks

    # config #2: attestation batch
    tasks, _ = _build_tasks(N_ATTESTATIONS, COMMITTEE_SIZE, seed_base=1000)
    t0 = time.perf_counter()
    assert batch_verify(tasks)
    log(f"attestation batch compile+first: {time.perf_counter() - t0:.1f}s")
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        assert batch_verify(tasks)
    dt = (time.perf_counter() - t0) / iters
    baseline = (base["oracle_seconds_per_fast_aggregate_verify"]
                * N_ATTESTATIONS)
    _emit({
        "metric": f"attestation_batch_{N_ATTESTATIONS}x"
                  f"{COMMITTEE_SIZE}_verify_wall",
        "value": round(dt, 4),
        "unit": "s",
        "vs_baseline": round(baseline / dt, 1),
    })

    # config #3: sync aggregate (one 512-member statement)
    sync_tasks, _ = _build_tasks(1, SYNC_COMMITTEE_SIZE, seed_base=2000)
    pk, msg, sig = sync_tasks[0]
    h = hash_to_g2(msg, DST_G2)
    pairs = [(pk, h), (g1.neg(cs.G1_GEN), sig)]
    t0 = time.perf_counter()
    assert pairing_check_device(pairs)
    log(f"sync aggregate compile+first: {time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    for _ in range(iters):
        assert pairing_check_device(pairs)
    dt = (time.perf_counter() - t0) / iters
    baseline = base["oracle_seconds_per_sync_aggregate_verify"]
    _emit({
        "metric": f"sync_aggregate_{SYNC_COMMITTEE_SIZE}_verify_wall",
        "value": round(dt, 4),
        "unit": "s",
        "vs_baseline": round(baseline / dt, 1),
    })

    # MSM break-even probe (telemetry rounds only: it exists to produce
    # routing data, and keeping it out of the default path holds the
    # CST_TELEMETRY-unset bench wall identical to the pre-telemetry one)
    if telemetry.enabled() and MSM_PROBE_SIZES:
        _emit(msm_probe_record())


if __name__ == "__main__":
    main()
