"""Benchmark: mainnet-preset epoch-processing sweep @ 1M validators.

North-star config #4 (BASELINE.md): the per-validator epoch pipeline
(rewards/penalties + slashings + effective-balance updates) plus the
registry-scale merkleization (balances list root + validator registry root).

- TPU path: `parallel.epoch_sweep` + device merkle kernels, one fused XLA
  program over a 2**20-validator struct-of-arrays registry.
- Baseline: the executable spec's pure-Python pipeline + SSZ engine
  hash_tree_root, measured on a 1024-validator mainnet state and scaled
  linearly (the pipeline is O(N); sorting terms are negligible).  The
  measured per-validator cost is persisted in `bench_baseline.json` (checked
  in) so the driver run does not re-pay ~95s of pure-Python sweeps; delete
  the file to re-measure.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Budget design (round-4 fix): baseline is read from disk (<1ms), the XLA
compile is amortized through a persistent compilation cache in
`.jax_cache/`, and the JSON line is printed immediately after the five
measured steps — nothing optional runs before it.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import jax
import numpy as np

# entry points own the process-wide uint64 switch (parallel.require_x64)
jax.config.update("jax_enable_x64", True)
# the image's sitecustomize pins the platform to the pooled TPU through
# live config; let an explicit JAX_PLATFORMS env override it (CPU smoke)
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

# persistent compilation cache: the ~70s XLA compile of the fused step is
# paid once per machine, not once per run
from consensus_specs_tpu.utils.jaxtools import enable_compile_cache  # noqa: E402

enable_compile_cache()

BASELINE_FILE = Path(__file__).resolve().parent / "bench_baseline.json"


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _measure_baseline(n: int = 1024, repeats: int = 3) -> dict:
    """Pure-Python spec pipeline + SSZ HTR, per validator."""
    from consensus_specs_tpu.models.builder import build_spec
    from consensus_specs_tpu.testlib.context import (
        default_activation_threshold)
    from consensus_specs_tpu.testlib.helpers.attestations import (
        prepare_state_with_attestations)
    from consensus_specs_tpu.testlib.helpers.genesis import (
        create_genesis_state)
    from consensus_specs_tpu.utils.ssz.ssz_impl import hash_tree_root

    spec = build_spec("phase0", "mainnet")
    balances = [spec.MAX_EFFECTIVE_BALANCE] * n
    state = create_genesis_state(
        spec, balances, default_activation_threshold(spec))
    prepare_state_with_attestations(spec, state)

    best = float("inf")
    for _ in range(repeats):
        st = state.copy()
        t0 = time.perf_counter()
        spec.process_justification_and_finalization(st)
        spec.process_rewards_and_penalties(st)
        spec.process_slashings(st)
        spec.process_effective_balance_updates(st)
        hash_tree_root(st.balances)
        hash_tree_root(st.validators)
        best = min(best, time.perf_counter() - t0)
    return {
        "seconds_per_validator": best / n,
        "validators_measured": n,
        "repeats": repeats,
        "host_fingerprint": _host_fingerprint(),
        "measured_at": time.strftime("%Y-%m-%d"),
        "pipeline": ("process_justification_and_finalization + "
                     "process_rewards_and_penalties + process_slashings + "
                     "process_effective_balance_updates + "
                     "hash_tree_root(balances) + hash_tree_root(validators)"),
    }


def _host_fingerprint() -> str:
    import platform

    return f"{platform.machine()}/{os.cpu_count()}cpu"


def baseline_cpu_seconds_per_validator() -> float:
    if BASELINE_FILE.exists() and not os.environ.get("CST_BENCH_REMEASURE"):
        data = json.loads(BASELINE_FILE.read_text())
        if data.get("host_fingerprint", _host_fingerprint()) \
                != _host_fingerprint():
            log(f"baseline host mismatch ({data['host_fingerprint']} vs "
                f"{_host_fingerprint()}): re-measuring")
        else:
            log(f"baseline (persisted {data['measured_at']}): "
                f"{data['seconds_per_validator'] * 1e6:.1f} us/validator "
                f"@ {data['validators_measured']} validators")
            return data["seconds_per_validator"]
    data = _measure_baseline()
    try:
        BASELINE_FILE.write_text(json.dumps(data, indent=2) + "\n")
        log(f"baseline (measured, persisted to {BASELINE_FILE.name}): "
            f"{data['seconds_per_validator'] * 1e6:.1f} us/validator")
    except OSError as e:  # persisting is an optimization, never fatal
        log(f"baseline measured but not persisted: {e}")
    return data["seconds_per_validator"]


def tpu_seconds_per_step(n: int) -> float:
    from consensus_specs_tpu.models.builder import build_spec
    from consensus_specs_tpu.parallel import (
        EpochParams, EpochScalars, ValidatorLeaves, balances_list_root,
        epoch_sweep, validator_records_root, validator_registry_root)

    from __graft_entry__ import _synthetic_registry

    t0 = time.perf_counter()
    dev = jax.devices()[0]
    log(f"device claim: {time.perf_counter() - t0:.1f}s -> {dev}")

    params = EpochParams.from_spec(build_spec("phase0", "mainnet"))
    reg = _synthetic_registry(n)
    sc = EpochScalars(current_epoch=np.uint64(100_000),
                      finality_delay=np.uint64(2),
                      slashings_sum=np.uint64(32_000_000_000))
    rng = np.random.RandomState(7)
    pk_root = rng.randint(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32)
    cred = rng.randint(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32)

    @jax.jit
    def step(reg, sc, length, pk_root, cred):
        new_bal, new_eff = epoch_sweep(reg, sc, params, axis_name=None)
        bal_root = balances_list_root(new_bal, length)
        rec = validator_records_root(
            ValidatorLeaves(pk_root, cred), new_eff, reg.slashed,
            reg.activation_eligibility_epoch, reg.activation_epoch,
            reg.exit_epoch, reg.withdrawable_epoch)
        reg_root = validator_registry_root(rec, length)
        return new_bal, new_eff, bal_root, reg_root

    args = (reg, sc, np.uint64(n), pk_root, cred)
    t0 = time.perf_counter()
    jax.block_until_ready(step(*args))
    log(f"compile+first run {time.perf_counter() - t0:.1f}s")
    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jax.block_until_ready(step(*args))
    dt = (time.perf_counter() - t0) / iters
    log(f"{dt * 1e3:.1f} ms/step @ {n} validators "
        f"(root {np.asarray(out[3])[:2]})")
    return dt


def main():
    n = 1 << 20
    per_val_cpu = baseline_cpu_seconds_per_validator()
    baseline_s = per_val_cpu * n
    tpu_s = tpu_seconds_per_step(n)
    print(json.dumps({
        "metric": "mainnet_epoch_sweep_1m_validators_wall",
        "value": round(tpu_s, 4),
        "unit": "s",
        "vs_baseline": round(baseline_s / tpu_s, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
