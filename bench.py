"""Benchmark: mainnet-preset epoch-processing sweep @ 1M validators.

North-star config #4 (BASELINE.md): the per-validator epoch pipeline
(rewards/penalties + slashings + effective-balance updates) plus the
registry-scale merkleization (balances list root + validator registry
root), with BLS batch (configs #2/#3) extras folded into the same JSON
line when the time budget allows.

- TPU path: `parallel.epoch_sweep` + device merkle kernels, one fused XLA
  program over a 2**20-validator struct-of-arrays registry.
- Baseline: the executable spec's pure-Python pipeline + SSZ engine
  hash_tree_root, measured on a 1024-validator mainnet state and scaled
  linearly (the pipeline is O(N)).  The measured per-validator cost is
  persisted in `bench_baseline.json` (checked in) so the driver run does
  not re-pay ~95s of pure-Python sweeps; delete the file to re-measure.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ..., ...}

Robustness design (round-5 fix — rounds 3/4 produced no number):
- every measurement runs in a fresh subprocess: a failed TPU backend init
  poisons the parent process's jax state, so retries must not share one;
- bounded retries (3) for the flagship metric; the second attempt disables
  the persistent compile cache (CST_NO_COMPILE_CACHE=1) to rule out a
  poisoned cache entry, the third also waits out transient pool pressure;
- the compile cache itself is keyed by host fingerprint
  (`utils/jaxtools.host_cache_key`) so cross-machine XLA:CPU AOT entries
  can never be loaded — the round-4 failure mode;
- if every TPU attempt fails, a CPU-platform fallback still lands a
  measured number (flagged `"platform": "cpu-fallback"` + `"error"`), and
  if even that fails the JSON line carries `"value": null` and the error —
  the driver always parses *something*.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

# stdlib-only (never initializes a backend in the parent process)
from consensus_specs_tpu.telemetry import history as benchwatch

HERE = Path(__file__).resolve().parent
BASELINE_FILE = HERE / "bench_baseline.json"

N_VALIDATORS = int(os.environ.get("CST_BENCH_N", 1 << 20))
ATTEMPT_TIMEOUT = int(os.environ.get("CST_BENCH_ATTEMPT_TIMEOUT", 420))
# an extras worker (merkle / bls / kzg / spec) only starts while elapsed
# < this, so the flagship line cannot be lost to an external driver timeout
EXTRAS_DEADLINE = int(os.environ.get("CST_BENCH_EXTRAS_DEADLINE", 420))


def _merkle_fracs() -> list[float]:
    """The dirty-fraction sweep (CST_MERKLE_DIRTY_FRAC, comma list).
    The FIRST value is also the flagship's incremental dirty fraction."""
    raw = os.environ.get("CST_MERKLE_DIRTY_FRAC", "0.01,0.1,1.0")
    fracs = [float(f) for f in raw.split(",") if f.strip()]
    assert fracs and all(0.0 < f <= 1.0 for f in fracs), raw
    return fracs


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# CPU baselines (pure-Python spec pipeline; persisted, no jax involved)
# ---------------------------------------------------------------------------

def _host_fingerprint() -> str:
    import platform

    return f"{platform.machine()}/{os.cpu_count()}cpu"


def _measure_baseline(n: int = 1024, repeats: int = 3) -> dict:
    """Pure-Python spec pipeline + SSZ HTR, per validator."""
    from consensus_specs_tpu.models.builder import build_spec
    from consensus_specs_tpu.testlib.context import (
        default_activation_threshold)
    from consensus_specs_tpu.testlib.helpers.attestations import (
        prepare_state_with_attestations)
    from consensus_specs_tpu.testlib.helpers.genesis import (
        create_genesis_state)
    from consensus_specs_tpu.utils.ssz.ssz_impl import hash_tree_root

    spec = build_spec("phase0", "mainnet")
    balances = [spec.MAX_EFFECTIVE_BALANCE] * n
    state = create_genesis_state(
        spec, balances, default_activation_threshold(spec))
    prepare_state_with_attestations(spec, state)

    best = float("inf")
    for _ in range(repeats):
        st = state.copy()
        t0 = time.perf_counter()
        spec.process_justification_and_finalization(st)
        spec.process_rewards_and_penalties(st)
        spec.process_slashings(st)
        spec.process_effective_balance_updates(st)
        hash_tree_root(st.balances)
        hash_tree_root(st.validators)
        best = min(best, time.perf_counter() - t0)
    return {
        "seconds_per_validator": best / n,
        "validators_measured": n,
        "repeats": repeats,
        "host_fingerprint": _host_fingerprint(),
        "measured_at": time.strftime("%Y-%m-%d"),
        "pipeline": ("process_justification_and_finalization + "
                     "process_rewards_and_penalties + process_slashings + "
                     "process_effective_balance_updates + "
                     "hash_tree_root(balances) + hash_tree_root(validators)"),
    }


def baseline_cpu_seconds_per_validator() -> float:
    if BASELINE_FILE.exists() and not os.environ.get("CST_BENCH_REMEASURE"):
        data = json.loads(BASELINE_FILE.read_text())
        if data.get("host_fingerprint",
                    _host_fingerprint()) != _host_fingerprint():
            log(f"baseline host mismatch ({data['host_fingerprint']} vs "
                f"{_host_fingerprint()}): re-measuring")
        else:
            log(f"baseline (persisted {data.get('measured_at')}): "
                f"{data['seconds_per_validator'] * 1e6:.1f} us/validator "
                f"@ {data['validators_measured']} validators")
            return data["seconds_per_validator"]
    data = _measure_baseline()
    try:
        BASELINE_FILE.write_text(json.dumps(data, indent=2) + "\n")
        log(f"baseline (measured, persisted to {BASELINE_FILE.name}): "
            f"{data['seconds_per_validator'] * 1e6:.1f} us/validator")
    except OSError as e:  # persisting is an optimization, never fatal
        log(f"baseline measured but not persisted: {e}")
    return data["seconds_per_validator"]


# ---------------------------------------------------------------------------
# workers (run in fresh subprocesses; print one JSON line on success)
# ---------------------------------------------------------------------------

def _worker_setup_jax():
    import jax

    jax.config.update("jax_enable_x64", True)
    # the image's sitecustomize pins the platform to the pooled TPU through
    # live config; let an explicit JAX_PLATFORMS env override it (CPU smoke)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from consensus_specs_tpu.utils.jaxtools import enable_compile_cache

    enable_compile_cache()

    # CST_PROFILE=<dir>: capture a jax profiler trace of the worker
    # (TensorBoard-loadable; the tracing hook SURVEY §5.1 calls for)
    profile_dir = os.environ.get("CST_PROFILE")
    if profile_dir:
        import atexit

        jax.profiler.start_trace(profile_dir)
        log(f"profiler trace -> {profile_dir}")
        # atexit alone would lose the trace when the driver's subprocess
        # timeout kills the worker — workers also call
        # _stop_profile_trace() right after their measured section
        atexit.register(_stop_profile_trace)
    return jax


_profile_stopped = False


def _stop_profile_trace():
    """Flush the CST_PROFILE trace (idempotent; no-op when disabled)."""
    global _profile_stopped
    if not os.environ.get("CST_PROFILE") or _profile_stopped:
        return
    _profile_stopped = True
    import jax

    jax.profiler.stop_trace()


def worker_epoch(n: int) -> None:
    """Config #4, rewired through incremental merkleization: the epoch
    sweep's balance/effective-balance deltas apply to a host-known
    dirty subset (CST_MERKLE_DIRTY_FRAC's first value), the persisted
    layer-stack forests (`parallel.incremental.MerkleForest`) re-hash
    only the dirty root-to-leaf paths, and the roots settle through
    `merkleize_dirty_async` futures — O(dirty · log N) sha256 per step
    instead of the full O(N) rebuild (which is also what the reference
    pays: remerkleable's pointer tree only re-hashes changed paths).
    Full-rebuild parity is asserted against `balances_list_root` /
    `validator_registry_root` every CST_MERKLE_PARITY_EVERY steps.

    With CST_TELEMETRY=1 the JSON carries a `"telemetry"` sub-object
    splitting the flagship wall into compile_s (trace + XLA compile +
    initial forest builds, measured from the first call) vs run_s."""
    import numpy as np

    from consensus_specs_tpu import telemetry

    jax = _worker_setup_jax()
    import jax.numpy as jnp
    from consensus_specs_tpu.models.builder import build_spec
    from consensus_specs_tpu.parallel import (
        EpochParams, EpochScalars, ValidatorLeaves, balances_list_root,
        epoch_sweep, incremental, validator_records_root,
        validator_registry_root)

    from __graft_entry__ import _synthetic_registry

    t0 = time.perf_counter()
    dev = jax.devices()[0]
    log(f"device claim: {time.perf_counter() - t0:.1f}s -> {dev}")

    assert n & (n - 1) == 0, f"flagship wants a pow2 registry, got {n}"
    params = EpochParams.from_spec(build_spec("phase0", "mainnet"))
    reg = _synthetic_registry(n)
    sc = EpochScalars(current_epoch=np.uint64(100_000),
                      finality_delay=np.uint64(2),
                      slashings_sum=np.uint64(32_000_000_000))
    rng = np.random.RandomState(7)
    pk_root = rng.randint(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32)
    cred = rng.randint(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32)
    # resident once: steps must not re-upload the ~100-byte-per-validator
    # registry every iteration
    reg = jax.device_put(reg)
    sc = jax.device_put(sc)
    pk_root = jnp.asarray(pk_root)
    cred = jnp.asarray(cred)

    frac = _merkle_fracs()[0]
    parity_every = max(1, int(os.environ.get("CST_MERKLE_PARITY_EVERY", 5)))
    m = max(1, int(frac * n))
    dirty_val = np.sort(rng.choice(n, m, replace=False)).astype(np.uint32)
    mask = np.zeros(n, dtype=bool)
    mask[dirty_val] = True
    chunk_idx = incremental.dirty_chunks_from_validators(dirty_val)

    _pad_idx = incremental.pad_dirty_idx

    @jax.jit
    def sweep_step(reg, sc, mask):
        new_bal, new_eff = epoch_sweep(reg, sc, params, axis_name=None)
        return (jnp.where(mask, new_bal, reg.balance),
                jnp.where(mask, new_eff, reg.effective_balance))

    def record_roots_all(eff, slashed):
        return validator_records_root(
            ValidatorLeaves(pk_root, cred), eff, slashed,
            reg.activation_eligibility_epoch, reg.activation_epoch,
            reg.exit_epoch, reg.withdrawable_epoch)

    @jax.jit
    def dirty_record_roots(eff, slashed, aee, ae, ee, we, pk, cr, idx):
        safe = jnp.minimum(idx, jnp.uint32(eff.shape[0] - 1))
        return validator_records_root(
            ValidatorLeaves(pk[safe], cr[safe]), eff[safe], slashed[safe],
            aee[safe], ae[safe], ee[safe], we[safe])

    t0 = time.perf_counter()
    with telemetry.span("bench.epoch.compile_first", n=n):
        # initial full builds: the persisted layer stacks the steps
        # re-hash incrementally (paid once, attributed to compile+first)
        rec_all = record_roots_all(reg.effective_balance, reg.slashed)
        bal_forest = incremental.balances_forest(reg.balance, n)
        reg_forest = incremental.registry_forest(np.asarray(rec_all), n)
        chunk_idx_p = _pad_idx(chunk_idx, bal_forest.capacity)
        val_idx_p = _pad_idx(dirty_val, reg_forest.capacity)
        chunk_idx_dev = jnp.asarray(chunk_idx_p)
        val_idx_dev = jnp.asarray(val_idx_p)
        mask_dev = jnp.asarray(mask)

        def step():
            """One epoch step: masked sweep -> dirty leaf gather ->
            dirty-path re-hash on both forests -> root futures (the
            only host syncs of the step)."""
            bal, eff = sweep_step(reg, sc, mask_dev)
            leaves = incremental.dirty_balance_leaves(bal, chunk_idx_dev)
            rec = dirty_record_roots(
                eff, reg.slashed, reg.activation_eligibility_epoch,
                reg.activation_epoch, reg.exit_epoch,
                reg.withdrawable_epoch, pk_root, cred, val_idx_dev)
            bal_fut = incremental.merkleize_dirty_async(
                bal_forest, chunk_idx_p, leaves)
            reg_fut = incremental.merkleize_dirty_async(
                reg_forest, val_idx_p, rec)
            return bal, eff, bal_fut.result(), reg_fut.result()

        out = step()
    compile_dt = time.perf_counter() - t0
    log(f"compile+first run {compile_dt:.1f}s "
        f"(incl. forest builds; dirty_frac={frac}, {m} validators)")
    # flagship cost record (CST_COSTMODEL rounds): the sweep's XLA
    # flop/byte budget + a device-memory watermark sample — no-op flag
    # checks otherwise (the merkle_incr@/merkle_build@ kernels record
    # their own entries through the incremental module's seams).  Keyed
    # `epoch_sweep` — the analyzed program is the sweep kernel alone,
    # and its run_s comes from the capture-time probe; the composite
    # step wall (sweep + dirty re-hash + root settles) is observed
    # under `epoch_step`, which deliberately has NO cost record so the
    # roofline join never divides sweep-only flops by the step wall
    telemetry.costmodel.capture(f"epoch_sweep@{n}", sweep_step,
                                (reg, sc, mask_dev))
    telemetry.costmodel.sample_watermark("bench.epoch.compile_first")

    full_bal_root = jax.jit(lambda bal: balances_list_root(
        bal, jnp.uint64(n)))
    full_reg_root = jax.jit(lambda rec: validator_registry_root(
        rec, jnp.uint64(n)))

    def parity_check(bal, eff):
        """Full-rebuild parity: the incremental roots must be bit-exact
        vs the classic O(N) kernels on the same arrays."""
        want_b = np.asarray(full_bal_root(bal))
        got_b = bal_forest.root()
        assert np.array_equal(want_b, got_b), (want_b, got_b)
        rec = record_roots_all(eff, reg.slashed)
        want_r = np.asarray(full_reg_root(rec))
        got_r = reg_forest.root()
        assert np.array_equal(want_r, got_r), (want_r, got_r)

    iters = 5
    steps_done = 1
    parity_checks = 0
    dt_sum = 0.0
    with telemetry.span("bench.epoch.steady", n=n, iters=iters):
        for _ in range(iters):
            t1 = time.perf_counter()
            out = step()
            dt_sum += time.perf_counter() - t1
            steps_done += 1
            # parity rides between timed steps so the flagship number
            # stays a pure incremental-step wall
            if steps_done % parity_every == 0:
                parity_check(out[0], out[1])
                parity_checks += 1
    dt = dt_sum / iters
    if not parity_checks:       # never skip parity entirely
        parity_check(out[0], out[1])
        parity_checks += 1
    # the composite step wall (no cost record joins it — see the
    # epoch_sweep capture above); the watermark is sampled here while
    # the step outputs are still resident so the high-water mark
    # reflects the working set, not an idle device
    telemetry.observe(f"kernel.epoch_step@{n}.run_s", dt)
    telemetry.costmodel.sample_watermark("bench.epoch.steady")
    log(f"{dt * 1e3:.1f} ms/step @ {n} validators "
        f"({parity_checks} parity check(s) ok, root {out[3][:2]})")
    _stop_profile_trace()
    result = {"seconds": dt, "platform": dev.platform,
              "dirty_frac": frac, "dirty_validators": int(m),
              "parity_checks": parity_checks}
    if telemetry.enabled():
        result["telemetry"] = telemetry.bench_block(
            compile_s=compile_dt, run_s=dt)
    print(json.dumps(result), flush=True)


def worker_merkle() -> None:
    """Dirty-fraction sweep of the incremental merkleization kernels:
    one `merkle_incr::update@frac<f>` record per CST_MERKLE_DIRTY_FRAC
    value (incremental update+root wall, `vs_baseline` = speedup over a
    full re-merkleize of the same CST_MERKLE_N-leaf tree) plus a
    `merkle_incr::proofs@<batch>` record for batched SSZ single-proof
    emission.  Every fraction's root is parity-checked against a fresh
    full build, and one emitted proof batch is verified against the
    host SSZ oracle's branch check."""
    import numpy as np

    from consensus_specs_tpu import telemetry

    jax = _worker_setup_jax()
    from consensus_specs_tpu.parallel import incremental

    n = int(os.environ.get("CST_MERKLE_N", 1 << 20))
    fracs = _merkle_fracs()
    proof_batch = int(os.environ.get("CST_MERKLE_PROOF_BATCH", 1024))
    proof_batch = max(1, min(proof_batch, n))
    dev = jax.devices()[0]
    rng = np.random.RandomState(11)
    words = rng.randint(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32)

    t0 = time.perf_counter()
    forest = incremental.MerkleForest(words, 38, n)
    root0 = forest.root()
    log(f"forest build @ {n} leaves: {time.perf_counter() - t0:.1f}s")

    # full-rebuild baseline: the pre-incremental O(N) path — the
    # device depth-d reduction over a leaf array that is resident ONCE
    # outside the clock, root fetched per call (exactly what the
    # incremental loop pays at `root()`).  The `merkleize_words_jax`
    # facade is NOT timed here: it ingests host numpy (pad + upload
    # per call), which would bill a full-tree transfer to the baseline
    # and inflate the reported speedup — the very ratio the
    # merkle-incremental-speedup threshold row gates on
    import jax.numpy as jnp
    from consensus_specs_tpu.ops.sha256_jax import merkle_root_pow2
    d = max(n - 1, 0).bit_length()
    padded = np.zeros((1 << d, 8), dtype=np.uint32)   # pow2 pad, once
    padded[:n] = words
    words_dev = jnp.asarray(padded)
    iters = 3
    t0 = time.perf_counter()
    np.asarray(merkle_root_pow2(words_dev, d))
    log(f"full re-merkleize compile+first: {time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    for _ in range(iters):
        np.asarray(merkle_root_pow2(words_dev, d))
    full_dt = (time.perf_counter() - t0) / iters
    log(f"full re-merkleize: {full_dt:.3f}s")

    out = {}
    cur = words
    for frac in fracs:
        m = max(1, int(frac * n))
        idx = np.sort(rng.choice(n, m, replace=False)).astype(np.uint32)
        new_leaves = rng.randint(0, 2**32, (m, 8),
                                 dtype=np.uint64).astype(np.uint32)
        forest.update(idx, new_leaves)
        forest.root()                      # warm this rung's executables
        t0 = time.perf_counter()
        for _ in range(iters):
            forest.update(idx, new_leaves)
            root = forest.root()
        dt = (time.perf_counter() - t0) / iters
        # parity: a fresh full build over the mutated leaves must land
        # the identical root
        cur = cur.copy()
        cur[idx] = new_leaves
        want = incremental.MerkleForest(cur, 38, n).root()
        assert np.array_equal(root, want), (frac, root, want)
        rung = incremental._bucket(m)
        log(f"dirty frac={frac:g} ({m} leaves, rung {rung}): {dt:.4f}s "
            f"({full_dt / dt:.1f}x vs full)")
        out[f"merkle_incr::update@frac{frac:g}"] = {
            "value": round(dt, 4), "unit": "s",
            "vs_baseline": round(full_dt / dt, 1),
            "detail": {"n_leaves": n, "dirty": m, "rung": rung,
                       "full_remerkleize_s": round(full_dt, 4)},
        }

    # batched proof emission from the persisted layers (the stateless-
    # client / light-client serving workload)
    indices = list(range(0, n, max(1, n // proof_batch)))[:proof_batch]
    forest.emit_proofs(indices)            # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        proofs = forest.emit_proofs(indices)
    proof_dt = (time.perf_counter() - t0) / iters
    root_bytes = forest.root_bytes()
    assert all(incremental.verify_proof(p, root_bytes)
               for p in proofs[:8]), "emitted proof failed oracle check"
    log(f"proofs x{len(indices)}: {proof_dt:.4f}s "
        f"({proof_dt / len(indices) * 1e6:.1f} us/proof)")
    out[f"merkle_incr::proofs@{len(indices)}"] = {
        "value": round(proof_dt, 4), "unit": "s",
        "vs_baseline": None,
        "detail": {"n_leaves": n, "batch": len(indices),
                   "us_per_proof": round(proof_dt / len(indices) * 1e6, 1)},
    }
    _ = root0
    if telemetry.enabled():
        out = {k: telemetry.embed_bench_block(dict(v))
               for k, v in out.items()}
        # one block per line is enough — keep the superset line small
        for k in list(out)[1:]:
            out[k].pop("telemetry", None)
    out["platform"] = dev.platform
    _stop_profile_trace()
    print(json.dumps(out), flush=True)


def worker_scaling() -> None:
    """Mesh-sharded flagship rungs (the ROADMAP scale-out item): the
    partition-registry epoch step (`parallel.partition`: sweep with
    psum totals + sharded balances/registry merkle roots, shard_map
    specs from the rule table) measured at 2M/8M/16M validators, each
    rung gated on the device count keeping the per-chip shard at or
    under the single-chip flagship's 2**21 validators.

    Per rung the worker measures the sharded step wall over the full
    mesh AND a single-chip reference at the same per-chip shard size
    (weak scaling), so the record carries per-chip throughput and the
    scaling efficiency the `scaling-efficiency` benchwatch row gates
    (>= 70% retention at the full mesh).  An 8M+ rung that completes
    flips `ok_8m` — the `flagship-8m` no-OOM gate.

    Knobs: CST_SHARD_RUNGS (comma list of validator counts, default
    2M,8M,16M), CST_SHARD_DEVICES (cap the mesh width; quantized to a
    power of two via `mesh_rung`), CST_SHARD_ITERS (steady-state
    iterations per rung)."""
    import numpy as np

    from consensus_specs_tpu import telemetry

    jax = _worker_setup_jax()
    from consensus_specs_tpu.models.builder import build_spec
    from consensus_specs_tpu.parallel import (
        EpochParams, EpochScalars, partition)

    from __graft_entry__ import _synthetic_registry

    raw = os.environ.get("CST_SHARD_RUNGS",
                         f"{1 << 21},{1 << 23},{1 << 24}")
    rungs = [int(r) for r in raw.split(",") if r.strip()]
    assert rungs and all(r & (r - 1) == 0 for r in rungs), (
        f"CST_SHARD_RUNGS wants power-of-two validator counts: {raw}")
    iters = max(1, int(os.environ.get("CST_SHARD_ITERS", 3)))
    cap = int(os.environ.get("CST_SHARD_DEVICES", 0)) or None

    dev = jax.devices()[0]
    pool = partition.available_devices()
    n_dev = partition.mesh_rung(min(pool, cap) if cap else pool)
    # per-chip shard cap: the single-chip flagship shape (2**21 on the
    # real chip; tiny smoke rungs always pass)
    per_chip_cap = max(1 << 21, rungs[0])
    params = EpochParams.from_spec(build_spec("phase0", "mainnet"))
    sc = EpochScalars(current_epoch=np.uint64(100_000),
                      finality_delay=np.uint64(2),
                      slashings_sum=np.uint64(32_000_000_000))
    sc = jax.device_put(sc)

    def measure(step, reg_s, length, pk_s, cred_s):
        t0 = time.perf_counter()
        out = jax.block_until_ready(step(reg_s, sc, length, pk_s, cred_s))
        compile_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jax.block_until_ready(
                step(reg_s, sc, length, pk_s, cred_s))
        return (time.perf_counter() - t0) / iters, compile_dt, out

    def build_inputs(n, mesh):
        rng = np.random.RandomState(7)
        reg = _synthetic_registry(n)
        pk = rng.randint(0, 2**32, (n, 8),
                         dtype=np.uint64).astype(np.uint32)
        cred = rng.randint(0, 2**32, (n, 8),
                           dtype=np.uint64).astype(np.uint32)
        rules = partition.epoch_state_rules()
        reg_s = partition.shard_tree(mesh, reg, rules)
        leaves = partition.shard_tree(
            mesh, {"pubkey_root": pk, "credentials": cred}, rules)
        return reg_s, leaves["pubkey_root"], leaves["credentials"]

    block = {"n_devices": n_dev, "rungs": [], "ok_8m": None}
    # single-chip reference per distinct per-chip shard size (weak
    # scaling baseline: same step machinery on a 1-device mesh)
    single_cache: dict[int, float] = {}
    mesh1 = partition.build_mesh(n_devices=1, require_pow2=True)
    step1 = partition.sharded_epoch_step(mesh1, params)
    mesh = partition.build_mesh(n_devices=n_dev, require_pow2=True)
    step = partition.sharded_epoch_step(mesh, params)
    # the worker must hand back whatever it measured instead of eating
    # the whole extras budget: stop ADDING rungs once ~60% of the
    # per-attempt timeout is gone (a timed-out subprocess would lose
    # every completed rung AND starve the later extras workers)
    worker_t0 = time.perf_counter()
    rung_deadline = 0.6 * ATTEMPT_TIMEOUT
    for n in rungs:
        needed = max(1, n // per_chip_cap)
        if n_dev < needed:
            log(f"rung {n}: skipped (needs >= {needed} devices, "
                f"have {n_dev})")
            continue
        if block["rungs"] and \
                time.perf_counter() - worker_t0 > rung_deadline:
            log(f"rung {n}: skipped (scaling budget "
                f"{rung_deadline:.0f}s spent)")
            break
        try:
            n_local = n // n_dev
            if n_local not in single_cache:
                r1, p1, c1 = build_inputs(n_local, mesh1)
                dt1, cdt1, _ = measure(step1, r1, np.uint64(n_local),
                                       p1, c1)
                single_cache[n_local] = dt1
                log(f"single-chip reference @ {n_local}: {dt1 * 1e3:.1f} "
                    f"ms/step (compile+first {cdt1:.1f}s)")
            dt1 = single_cache[n_local]
            reg_s, pk_s, cred_s = build_inputs(n, mesh)
            dt, cdt, out = measure(step, reg_s, np.uint64(n),
                                   pk_s, cred_s)
            per_chip = n / dt / n_dev
            single_vps = n_local / dt1
            eff = per_chip / single_vps if single_vps > 0 else 0.0
            log(f"rung {n} @ {n_dev} devices: {dt * 1e3:.1f} ms/step "
                f"(compile+first {cdt:.1f}s), {per_chip:.0f} "
                f"validators/s/chip, efficiency {eff * 100:.0f}% "
                f"(root {np.asarray(out[2])[:2]})")
            rung = {"n_validators": n, "n_devices": n_dev,
                    "wall_s": round(dt, 5),
                    "per_chip_vps": round(per_chip, 1),
                    "total_vps": round(n / dt, 1),
                    "single_chip_wall_s": round(dt1, 5),
                    "single_chip_vps": round(single_vps, 1),
                    "efficiency": round(eff, 4)}
            block["rungs"].append(rung)
            if n >= (1 << 23):
                block["ok_8m"] = True
        except Exception as e:               # OOM / compile failure
            log(f"rung {n} FAILED: {type(e).__name__}: {e}")
            if n >= (1 << 23) and block["ok_8m"] is None:
                block["ok_8m"] = False
            break
    assert block["rungs"], "no scaling rung completed"
    telemetry.costmodel.sample_watermark("bench.scaling")
    top = block["rungs"][-1]
    _stop_profile_trace()
    out = {"flagship_scaling": {
        "value": top["per_chip_vps"], "unit": "validators/s/chip",
        "vs_baseline": top["efficiency"], "scaling": block}}
    if telemetry.enabled():
        out["flagship_scaling"] = telemetry.embed_bench_block(
            out["flagship_scaling"])
    out["platform"] = dev.platform
    print(json.dumps(out), flush=True)


def worker_das() -> None:
    """The PeerDAS workload: batched cell-proof verification over a
    full sampling matrix (CST_DAS_MATRIX, default 128x2 and 128x8 —
    128 columns x N blobs, the largest device batch in the repo; the
    old config #5 verified six blobs).  Per matrix the device route
    (`das.verify`: one fr_batch coset-interpolation dispatch, Pippenger
    MSMs, one multi-pairing) is measured steady-state and compared
    against the pure-Python fulu oracle
    (`spec.verify_cell_kzg_proof_batch`), which pays a Lagrange
    interpolation per cell — the oracle wall is measured on
    CST_DAS_ORACLE_CELLS cells (default 16) and scaled linearly, the
    same subset-scaling the flagship baseline uses.

    The matrix rows are closed-form degree-65 polynomials
    (`das.ciphersuite.closed_form_matrix`): real, distinct commitments
    and non-infinity proofs from three scalar multiplications per row,
    so matrix construction never dominates the measured verification.
    Each sweep also runs the mixed-invalid isolation arc (one bad cell
    fails the RLC batch, the per-statement recheck isolates exactly
    it) and the coset-barycentric evaluation cross-check."""
    from consensus_specs_tpu import telemetry

    _worker_setup_jax()
    from consensus_specs_tpu.das import ciphersuite as das_cs
    from consensus_specs_tpu.das import verify as das_verify
    from consensus_specs_tpu.models.builder import build_spec
    from consensus_specs_tpu.ops import bls

    import jax

    dev = jax.devices()[0]
    raw = os.environ.get("CST_DAS_MATRIX", "128x2,128x8")
    shapes = []
    for part in raw.split(","):
        if not part.strip():
            continue
        cols, blobs = part.lower().split("x")
        shapes.append((int(cols), int(blobs)))
    assert shapes and all(1 <= c <= 128 and b >= 1 for c, b in shapes), raw
    oracle_cells = max(1, int(os.environ.get("CST_DAS_ORACLE_CELLS", 16)))
    iters = 3

    spec = build_spec("fulu", "mainnet")
    prev_active = bls.bls_active
    bls.bls_active = True
    out = {}
    try:
        max_cols = max(c for c, _ in shapes)
        max_blobs = max(b for _, b in shapes)
        t0 = time.perf_counter()
        matrix = das_cs.closed_form_matrix(
            max_blobs, columns=range(max_cols))
        log(f"closed-form matrix {max_cols}x{max_blobs}: "
            f"{time.perf_counter() - t0:.1f}s")

        def cut(cols, blobs):
            # the matrix is row-major: entry r * max_cols + c is
            # (row r, column c)
            com, idx, cells, proofs = matrix
            keep = [r * max_cols + c
                    for r in range(blobs) for c in range(cols)]
            return ([com[k] for k in keep], [idx[k] for k in keep],
                    [cells[k] for k in keep], [proofs[k] for k in keep])

        # ONE oracle measurement (per-cell cost is shape-independent),
        # scaled per matrix below — the pure-python interpolation makes
        # a full-matrix oracle run minutes-to-hours
        com, idx, cells, proofs = cut(max_cols, max_blobs)
        n_o = min(oracle_cells, len(idx))
        bls.use_backend("py")
        t0 = time.perf_counter()
        assert spec.verify_cell_kzg_proof_batch(
            com[:n_o], idx[:n_o],
            [spec.Cell(c) for c in cells[:n_o]], proofs[:n_o])
        oracle_sub = time.perf_counter() - t0
        log(f"oracle verify @ {n_o} cells: {oracle_sub:.1f}s")

        if telemetry.enabled():
            telemetry.reset()   # count only the device-backend phase
        for cols, blobs in shapes:
            com, idx, cells, proofs = cut(cols, blobs)
            n = len(idx)
            t0 = time.perf_counter()
            assert das_verify.verify_cell_proof_batch(
                com, idx, cells, proofs, device=True)
            compile_first = time.perf_counter() - t0
            log(f"das {cols}x{blobs} compile+first: {compile_first:.1f}s")
            t0 = time.perf_counter()
            for _ in range(iters):
                assert das_verify.verify_cell_proof_batch(
                    com, idx, cells, proofs, device=True)
            wall = (time.perf_counter() - t0) / iters
            oracle_wall = oracle_sub / n_o * n
            speedup = oracle_wall / wall
            log(f"das {cols}x{blobs} ({n} cells): {wall:.2f}s device "
                f"vs {oracle_wall:.1f}s oracle ({speedup:.1f}x)")

            # mixed-invalid isolation arc on a small slice (rung 16)
            s_com, s_idx, s_cells, s_proofs = (com[:8], idx[:8],
                                               list(cells[:8]),
                                               proofs[:8])
            bad = 3
            s_cells[bad] = s_cells[bad][:-32] + int.to_bytes(
                7, 32, "big")
            batch_ok, per = das_verify.verify_and_isolate(
                s_com, s_idx, s_cells, s_proofs, device=True)
            isolated = (not batch_ok
                        and [i for i, v in enumerate(per) if not v]
                        == [bad])
            # coset-evaluation cross-check: device barycentric over the
            # shifted domain vs the host interpolant
            z = 0xDA5_0001
            crosscheck = (das_verify.evaluate_cells_at(
                cells[:4], idx[:4], z, device=True)
                == das_verify.evaluate_cells_at(
                    cells[:4], idx[:4], z, device=False))

            block = {
                "matrix": {"columns": cols, "blobs": blobs, "cells": n},
                "verify_wall_s": round(wall, 4),
                "cells_per_s": round(n / wall, 1),
                "oracle_wall_s": round(oracle_wall, 2),
                "oracle_cells_measured": n_o,
                "speedup": round(speedup, 1),
                "rung": das_verify.das_rung(n),
                "compile_first_s": round(compile_first, 2),
                "batch_verdict": True,
                "isolate": {"bad_cells": 1, "isolated": isolated},
                "eval_crosscheck": bool(crosscheck),
            }
            rec = {"value": round(wall, 4), "unit": "s",
                   "vs_baseline": round(speedup, 1), "das": block}
            if telemetry.enabled():
                rec = telemetry.embed_bench_block(rec)
            out[f"das_cell_proof_batch_{cols}x{blobs}_verify_wall"] = rec

        # --- FK20 producer + erasure recovery (the super-node path) --
        # The producer measures the FK20 pipeline steady-state against
        # the D_u partial route it replaced; the D_u wall is
        # subset-scaled (CST_DAS_DU_MSMS of its 63 wide MSMs measured,
        # the rest scaled by their pad rung — a full D_u run is ~40
        # device-minutes).  Recovery measures the device decode +
        # FK20 re-prove against the pure-Python oracle with the
        # oracle's 128 per-coset proofs subset-scaled the same way
        # (CST_DAS_RECOVER_ORACLE_COSETS measured).  Parity rides a
        # degree-65 closed-form blob: its recovered cells and proofs
        # are known without any oracle run.
        from consensus_specs_tpu.das import compute as das_compute
        from consensus_specs_tpu.das import recover as das_recover
        from consensus_specs_tpu.models.builder import build_spec as _bs
        from consensus_specs_tpu.ops.bls_batch import _bucket

        produce_iters = max(1, int(os.environ.get(
            "CST_DAS_PRODUCE_ITERS", 2)))
        du_msms = max(1, int(os.environ.get("CST_DAS_DU_MSMS", 2)))
        oracle_cosets = max(1, int(os.environ.get(
            "CST_DAS_RECOVER_ORACLE_COSETS", 1)))
        n_ext = das_cs.CELLS_PER_EXT_BLOB
        m_blob = das_cs.FIELD_ELEMENTS_PER_BLOB
        p_mod = das_cs.BLS_MODULUS

        c2, c1, c0 = 90001, 80001, 70001
        roots = das_cs.roots_of_unity(m_blob)
        evals = [(c2 * pow(roots[das_cs.reverse_bits(i, m_blob)], 65,
                           p_mod)
                  + c1 * pow(roots[das_cs.reverse_bits(i, m_blob)], 64,
                             p_mod) + c0) % p_mod
                 for i in range(m_blob)]
        blob = das_cs._encode_evals(evals)
        _, per_cell = das_cs.closed_form_row(c2, c1, c0, range(n_ext))
        true_cells = [per_cell[k][0] for k in range(n_ext)]
        true_proofs = [per_cell[k][1] for k in range(n_ext)]

        t0 = time.perf_counter()
        fk_cells, fk_proofs = das_compute.compute_cells_and_kzg_proofs(
            blob, device=True, route="fk20")
        produce_first = time.perf_counter() - t0
        parity = (fk_cells == true_cells and fk_proofs == true_proofs)
        log(f"fk20 compile+setup+first: {produce_first:.1f}s "
            f"(closed-form parity: {parity})")
        t0 = time.perf_counter()
        for _ in range(produce_iters):
            das_compute.compute_cells_and_kzg_proofs(
                blob, device=True, route="fk20")
        produce_wall = (time.perf_counter() - t0) / produce_iters

        # D_u baseline, subset-scaled by pad rung: sizes M - 64u for
        # u = 1..63 (the wide partials) plus 128 rung-64 column MSMs
        coeffs = das_compute.poly_coefficients(blob, device=True)
        wide_pts = [das_cs.setup_g1_point(t) for t in range(m_blob - 64)]
        das_compute._msm(wide_pts, coeffs[64:], True)      # warm
        t0 = time.perf_counter()
        for _ in range(du_msms):
            das_compute._msm(wide_pts, coeffs[64:], True)
        t_wide = (time.perf_counter() - t0) / du_msms
        das_compute._msm(wide_pts[:63], coeffs[:63], True)  # warm rung 64
        t0 = time.perf_counter()
        das_compute._msm(wide_pts[:63], coeffs[:63], True)
        t_narrow = time.perf_counter() - t0
        sizes = [m_blob - 64 * u for u in range(1, m_blob // 64)]
        rung_scale = sum(_bucket(s) for s in sizes) / _bucket(sizes[0])
        du_wall = t_wide * rung_scale + n_ext * t_narrow
        producer_speedup = du_wall / produce_wall
        log(f"fk20 produce: {produce_wall:.1f}s vs D_u {du_wall:.1f}s "
            f"({producer_speedup:.1f}x; wide MSM {t_wide:.1f}s x "
            f"{rung_scale:.1f} rung-scaled, measured {du_msms})")

        # recovery: exactly half the cells survive (worst recoverable)
        keep = [k for k in range(n_ext) if k % 2 == 0]
        kept_cells = [true_cells[k] for k in keep]
        t0 = time.perf_counter()
        rc_cells, rc_proofs = das_recover.recover_cells_and_kzg_proofs(
            keep, kept_cells, device=True)
        recover_first = time.perf_counter() - t0
        roundtrip = (rc_cells == true_cells and rc_proofs == true_proofs)
        t0 = time.perf_counter()
        das_recover.recover_cells_and_kzg_proofs(keep, kept_cells,
                                                 device=True)
        recover_wall = time.perf_counter() - t0
        log(f"device recover first: {recover_first:.1f}s, steady: "
            f"{recover_wall:.1f}s (closed-form roundtrip: {roundtrip})")

        # oracle baseline: full pure-Python decode, subset-scaled
        # per-coset re-prove
        fulu = _bs("fulu", "mainnet")
        o_evals = [fulu.cell_to_coset_evals(c) for c in kept_cells]
        t0 = time.perf_counter()
        o_coeffs = fulu.recover_polynomialcoeff(keep, o_evals)
        decode_oracle = time.perf_counter() - t0
        t0 = time.perf_counter()
        for k in range(oracle_cosets):
            fulu.compute_kzg_proof_multi_impl(
                o_coeffs, fulu.coset_for_cell(fulu.CellIndex(k)))
        prove_oracle = (time.perf_counter() - t0) / oracle_cosets
        recover_oracle_wall = decode_oracle + n_ext * prove_oracle
        recover_speedup = recover_oracle_wall / recover_wall
        log(f"oracle recover: decode {decode_oracle:.1f}s + 128 x "
            f"{prove_oracle:.1f}s/coset = {recover_oracle_wall:.1f}s "
            f"({recover_speedup:.1f}x, measured {oracle_cosets} cosets)")

        producer_block = {
            "produce_wall_s": round(produce_wall, 3),
            "produce_first_s": round(produce_first, 2),
            "proofs_per_s": round(n_ext / produce_wall, 2),
            "du_wall_s": round(du_wall, 2),
            "du_msms_measured": du_msms,
            "producer_speedup": round(producer_speedup, 1),
            "parity": parity,
            "recover": {
                "cells_in": len(keep),
                "missing": n_ext - len(keep),
                "wall_s": round(recover_wall, 3),
                "oracle_wall_s": round(recover_oracle_wall, 2),
                "oracle_cosets_measured": oracle_cosets,
                "speedup": round(recover_speedup, 1),
                "roundtrip": roundtrip,
            },
        }
        rec = {"value": round(produce_wall, 4), "unit": "s",
               "vs_baseline": round(producer_speedup, 1),
               "das_producer": producer_block}
        if telemetry.enabled():
            rec = telemetry.embed_bench_block(rec)
        out["das_fk20_produce_wall"] = rec
    finally:
        bls.bls_active = prev_active
    out["platform"] = dev.platform
    _stop_profile_trace()
    print(json.dumps(out), flush=True)


def worker_forkchoice() -> None:
    """The fork-choice workload: device LMD-GHOST over proto-array
    stores (CST_FC_MATRIX, default 256x16384 and 1024x262144 —
    <blocks>x<validators> tree shapes).  Per shape the device route
    (`forkchoice.store`: batched latest-message folds + the
    pointer-jumping head kernel) is measured steady-state — apply wall
    per attestation batch, head wall per poll, heads/s — and compared
    against the phase0 spec oracle's `get_head`, which walks every
    active validator per child in pure Python: the oracle wall is
    measured on a CST_FC_ORACLE_VALIDATORS-validator store over the
    SAME block tree (the per-poll cost is linear in the validator
    count — the active-set loop dominates) and scaled linearly, the
    same subset-scaling the DAS and flagship baselines use.  The
    oracle store also pins bit-exact parity: the device head at the
    measured subset size must equal the spec oracle's."""
    from consensus_specs_tpu import telemetry

    _worker_setup_jax()
    from consensus_specs_tpu.forkchoice import (
        FC_BATCH_STEPS,
        FC_BLOCK_STEPS,
        FC_VALIDATOR_STEPS,
        fc_rung,
    )

    import jax

    dev = jax.devices()[0]
    raw = os.environ.get("CST_FC_MATRIX", "256x16384,1024x262144")
    shapes = []
    for part in raw.split(","):
        if not part.strip():
            continue
        blocks, validators = part.lower().split("x")
        shapes.append((int(blocks), int(validators)))
    assert shapes and all(b >= 2 and v >= 8 for b, v in shapes), raw
    oracle_v = max(8, int(os.environ.get("CST_FC_ORACLE_VALIDATORS",
                                         2048)))
    iters = 5
    n_batches = 8

    def build_store(n_blocks, n_validators, seed=29):
        """The shared synthetic workload (`forkchoice.synthetic` —
        same builder the serve loadgen's fc lane drives), with the
        first `n_batches` of its attestation stream materialized."""
        import itertools

        from consensus_specs_tpu.forkchoice.synthetic import (
            attestation_stream,
            synthetic_store,
        )

        store, roots = synthetic_store(n_blocks, n_validators,
                                       seed=seed)
        batch = 1024 if n_validators >= 4096 else 64
        batches = list(itertools.islice(
            attestation_stream(roots, n_validators, batch, seed=seed),
            n_batches))
        return store, batches

    out = {}
    if telemetry.enabled():
        telemetry.reset()
    for n_blocks, n_validators in shapes:
        store, batches = build_store(n_blocks, n_validators)
        n_msgs = sum(len(b[0]) for b in batches)

        t0 = time.perf_counter()
        store.apply_attestations(*batches[0])
        head = store.get_head()
        compile_first = time.perf_counter() - t0
        log(f"forkchoice {n_blocks}x{n_validators} compile+first: "
            f"{compile_first:.1f}s")

        apply_wall = head_wall = 0.0
        polls = 0
        for _ in range(iters):
            for b in batches:
                t0 = time.perf_counter()
                store.apply_attestations(*b)
                apply_wall += time.perf_counter() - t0
                t0 = time.perf_counter()
                head = store.get_head()
                head_wall += time.perf_counter() - t0
                polls += 1
        apply_wall /= iters * n_batches
        head_wall = max(head_wall / polls, 1e-9)
        heads_per_s = 1.0 / head_wall

        # the spec-oracle baseline + bit-exact parity, at the measured
        # subset size over the SAME tree (per-poll oracle cost is
        # linear in the validator count)
        v_o = min(oracle_v, n_validators)
        o_store, o_batches = build_store(n_blocks, v_o)
        for b in o_batches:
            o_store.apply_attestations(*b)
        dev_head = o_store.get_head()
        # untimed oracle warmup: the first get_head_host of the
        # process pays the one-time spec-namespace build, which must
        # not land in the scaled baseline (the device route's
        # compile+first is likewise measured separately)
        oracle_head = o_store.get_head_host()
        t0 = time.perf_counter()
        oracle_head = o_store.get_head_host()
        oracle_sub = time.perf_counter() - t0
        parity = dev_head == oracle_head
        assert parity, (dev_head.hex(), oracle_head.hex())
        oracle_wall = oracle_sub * n_validators / v_o
        speedup = oracle_wall / head_wall
        log(f"forkchoice {n_blocks}x{n_validators}: head "
            f"{head_wall * 1e3:.2f}ms device vs {oracle_wall:.2f}s "
            f"oracle ({speedup:.1f}x), apply {apply_wall * 1e3:.2f}ms")

        block = {
            "tree": {"blocks": n_blocks, "validators": n_validators,
                     "messages": n_msgs},
            "apply_wall_s": round(apply_wall, 6),
            "head_wall_s": round(head_wall, 6),
            "heads_per_s": round(heads_per_s, 1),
            "oracle_head_wall_s": round(oracle_wall, 4),
            "oracle_validators_measured": v_o,
            "speedup": round(speedup, 1),
            "rungs": {"blocks": fc_rung(n_blocks, FC_BLOCK_STEPS),
                      "validators": fc_rung(n_validators,
                                            FC_VALIDATOR_STEPS),
                      "batch": fc_rung(len(batches[0][0]),
                                       FC_BATCH_STEPS)},
            "compile_first_s": round(compile_first, 2),
            "parity": bool(parity),
        }
        rec = {"value": round(head_wall, 6), "unit": "s",
               "vs_baseline": round(speedup, 1), "forkchoice": block}
        if telemetry.enabled():
            rec = telemetry.embed_bench_block(rec)
        out[f"forkchoice_lmd_ghost_{n_blocks}x{n_validators}"
            f"_head_wall"] = rec
    out["platform"] = dev.platform
    _stop_profile_trace()
    print(json.dumps(out), flush=True)


def worker_bls() -> None:
    """Configs #2/#3: attestation RLC batch + sync-aggregate pairing.
    With CST_TELEMETRY=1 each metric carries per-config compile/run,
    padding, and routing telemetry."""
    from consensus_specs_tpu import telemetry

    _worker_setup_jax()
    import bench_bls

    base = bench_bls._baselines()
    n_att = bench_bls.N_ATTESTATIONS
    committee = bench_bls.COMMITTEE_SIZE
    sync_n = bench_bls.SYNC_COMMITTEE_SIZE

    from consensus_specs_tpu.ops.bls import ciphersuite as cs
    from consensus_specs_tpu.ops.bls.curve import g1
    from consensus_specs_tpu.ops.bls.hash_to_curve import DST_G2, hash_to_g2
    from consensus_specs_tpu.ops.bls_batch import (
        batch_verify, pairing_check_device)

    _tel = telemetry.embed_bench_block

    if telemetry.costmodel.enabled():
        bench_bls.costmodel_kernel_sweep()
    if telemetry.enabled():
        telemetry.reset()
    tasks, _ = bench_bls._build_tasks(n_att, committee, seed_base=1000)
    t0 = time.perf_counter()
    assert batch_verify(tasks)
    log(f"attestation batch compile+first: {time.perf_counter() - t0:.1f}s")
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        assert batch_verify(tasks)
    att_dt = (time.perf_counter() - t0) / iters
    att_base = base["oracle_seconds_per_fast_aggregate_verify"] * n_att
    att = _tel({"value": round(att_dt, 4), "unit": "s",
                "vs_baseline": round(att_base / att_dt, 1)})

    sync_tasks, _ = bench_bls._build_tasks(1, sync_n, seed_base=2000)
    pk, msg, sig = sync_tasks[0]
    h = hash_to_g2(msg, DST_G2)
    pairs = [(pk, h), (g1.neg(cs.G1_GEN), sig)]
    t0 = time.perf_counter()
    assert pairing_check_device(pairs)
    log(f"sync aggregate compile+first: {time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    for _ in range(iters):
        assert pairing_check_device(pairs)
    sync_dt = (time.perf_counter() - t0) / iters
    sync_base = base["oracle_seconds_per_sync_aggregate_verify"]
    sync = _tel({"value": round(sync_dt, 4), "unit": "s",
                 "vs_baseline": round(sync_base / sync_dt, 1)})

    out = {
        f"attestation_batch_{n_att}x{committee}_verify_wall": att,
        f"sync_aggregate_{sync_n}_verify_wall": sync,
    }
    # the ROADMAP's _MSM_DEVICE_MIN break-even question rides along on
    # telemetry rounds (host-vs-device MSM wall + routing per size),
    # same record shape as bench_bls.py's standalone emission.  A probe
    # failure (e.g. its kernel-vs-oracle assert) must not cost the two
    # already-measured config metrics — report it as a field instead.
    if telemetry.enabled() and bench_bls.MSM_PROBE_SIZES:
        try:
            probe = _tel(bench_bls.msm_probe_record())
            out[probe.pop("metric")] = probe
        except Exception as e:
            out["g1_msm_breakeven_probe_error"] = repr(e)[:300]

    _stop_profile_trace()
    print(json.dumps(out), flush=True)


def worker_kzg() -> None:
    """Config #5: deneb `verify_blob_kzg_proof_batch` over 6 mainnet
    blobs — KZG pairings/MSM on device (jax backend) vs the pure-python
    oracle.  The telemetry block's `routing` counts show how many of the
    batch's G1 MSMs the `_MSM_DEVICE_MIN` threshold kept on the host —
    the ROADMAP's open question for this config."""
    from consensus_specs_tpu import telemetry

    _worker_setup_jax()

    from consensus_specs_tpu.models.builder import build_spec
    from consensus_specs_tpu.ops import bls

    spec = build_spec("deneb", "mainnet")
    modulus = int(spec.BLS_MODULUS)
    n_fe = int(spec.FIELD_ELEMENTS_PER_BLOB)
    blobs = [
        spec.Blob(b"".join(
            int.to_bytes(pow(2 + i, j + 256, modulus), 32, "big")
            for j in range(n_fe)))
        for i in range(6)
    ]
    # setup on the device backend: 12 x 4096-point MSMs would eat the
    # extras deadline on the pure-python path
    bls.use_backend("jax")
    t0 = time.perf_counter()
    commitments = [spec.blob_to_kzg_commitment(b) for b in blobs]
    proofs = [spec.compute_blob_kzg_proof(b, c)
              for b, c in zip(blobs, commitments)]
    log(f"kzg setup (6 commitments+proofs): "
        f"{time.perf_counter() - t0:.1f}s")

    def measure(iters=3):
        t0 = time.perf_counter()
        for _ in range(iters):
            assert spec.verify_blob_kzg_proof_batch(blobs, commitments,
                                                    proofs)
        return (time.perf_counter() - t0) / iters

    bls.use_backend("py")
    py_dt = measure(iters=1)
    log(f"kzg batch py oracle: {py_dt:.2f}s")
    bls.use_backend("jax")
    if telemetry.enabled():
        telemetry.reset()   # count only the device-backend phase
    first = time.perf_counter()
    assert spec.verify_blob_kzg_proof_batch(blobs, commitments, proofs)
    log(f"kzg batch device compile+first: "
        f"{time.perf_counter() - first:.1f}s")
    dev_dt = measure()

    _stop_profile_trace()
    kzg = telemetry.embed_bench_block(
        {"value": round(dev_dt, 4), "unit": "s",
         "vs_baseline": round(py_dt / dev_dt, 1)})
    print(json.dumps({
        "blob_kzg_proof_batch_6_verify_wall": kzg,
    }), flush=True)


def worker_spec() -> None:
    """Config #1: minimal-preset phase0 `state_transition` on 64
    validators with signatures ON — full-spec wall per signed block,
    device (jax) backend vs the pure-python oracle."""
    from consensus_specs_tpu import telemetry

    _worker_setup_jax()

    from consensus_specs_tpu.models.builder import build_spec
    from consensus_specs_tpu.ops import bls
    from consensus_specs_tpu.testlib.helpers.block import (
        build_empty_block_for_next_slot, sign_block)
    from consensus_specs_tpu.testlib.helpers.genesis import (
        create_genesis_state)

    spec = build_spec("phase0", "minimal")
    bls.bls_active = True
    state = create_genesis_state(
        spec, [int(spec.MAX_EFFECTIVE_BALANCE)] * 64,
        int(spec.MAX_EFFECTIVE_BALANCE))

    def transition_one(st):
        block = build_empty_block_for_next_slot(spec, st)
        shadow = st.copy()
        spec.process_slots(shadow, block.slot)
        spec.process_block(shadow, block)
        block.state_root = spec.hash_tree_root(shadow)
        signed = sign_block(spec, st.copy(), block)
        spec.state_transition(st, signed)

    def measure(iters=3):
        st = state.copy()
        t0 = time.perf_counter()
        for _ in range(iters):
            transition_one(st)
        return (time.perf_counter() - t0) / iters

    bls.use_backend("py")
    py_dt = measure()
    log(f"state_transition py oracle: {py_dt:.2f}s/block")
    bls.use_backend("jax")
    if telemetry.enabled():
        telemetry.reset()   # count only the device-backend phase
    transition_one(state.copy())  # compile
    dev_dt = measure()

    _stop_profile_trace()
    rec = telemetry.embed_bench_block(
        {"value": round(dev_dt, 4), "unit": "s",
         "vs_baseline": round(py_dt / dev_dt, 1)})
    print(json.dumps({
        "minimal_phase0_state_transition_signed_block_wall": rec,
    }), flush=True)


# ---------------------------------------------------------------------------
# driver (parent process: never initializes a jax backend)
# ---------------------------------------------------------------------------

def _run_worker(mode: str, timeout: float, extra_env: dict | None = None):
    """Run `python bench.py --worker <mode>` and parse its last stdout line.
    Returns (dict | None, error_string)."""
    env = dict(os.environ)
    env.update(extra_env or {})
    try:
        proc = subprocess.run(
            [sys.executable, str(HERE / "bench.py"), "--worker", mode],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=str(HERE))
    except subprocess.TimeoutExpired:
        return None, f"{mode} worker timed out after {timeout:.0f}s"
    if proc.stderr:
        sys.stderr.write(proc.stderr[-4000:])
        sys.stderr.flush()
    if proc.returncode != 0:
        tail = " | ".join((proc.stderr or "").strip().splitlines()[-2:])
        return None, (f"{mode} worker rc={proc.returncode}: "
                      + tail[-300:])
    for line in reversed((proc.stdout or "").strip().splitlines()):
        try:
            return json.loads(line), ""
        except json.JSONDecodeError:
            continue
    return None, f"{mode} worker produced no JSON"


def main():
    start = time.time()
    per_val_cpu = baseline_cpu_seconds_per_validator()
    baseline_s = per_val_cpu * N_VALIDATORS

    attempts = [
        ("tpu attempt 1 (persistent cache)", {}),
        ("tpu attempt 2 (cache disabled)", {"CST_NO_COMPILE_CACHE": "1"}),
        ("tpu attempt 3 (cache disabled, after backoff)",
         {"CST_NO_COMPILE_CACHE": "1"}),
    ]
    result, errors = None, []
    for i, (label, env) in enumerate(attempts):
        if i == 2:
            log("backing off 30s before final attempt...")
            time.sleep(30)
        log(f"--- {label} ---")
        result, err = _run_worker("epoch", ATTEMPT_TIMEOUT, env)
        if result is not None:
            break
        errors.append(err)
        log(f"FAILED: {err}")

    platform = None
    if result is None:
        log("--- cpu fallback (TPU unavailable) ---")
        result, err = _run_worker(
            "epoch", ATTEMPT_TIMEOUT,
            {"JAX_PLATFORMS": "cpu", "CST_NO_COMPILE_CACHE": "1"})
        if result is not None:
            platform = "cpu-fallback"
        else:
            errors.append(err)

    out = {
        "metric": "mainnet_epoch_sweep_1m_validators_wall",
        "value": None,
        "unit": "s",
        "vs_baseline": None,
    }
    if result is not None:
        out["value"] = round(result["seconds"], 4)
        out["vs_baseline"] = round(baseline_s / result["seconds"], 1)
        out["platform"] = platform or result.get("platform", "tpu")
        if "dirty_frac" in result:   # the incremental-flagship contract
            out["dirty_frac"] = result["dirty_frac"]
            out["parity_checks"] = result.get("parity_checks")
        if "telemetry" in result:    # CST_TELEMETRY=1 rounds: the
            out["telemetry"] = result["telemetry"]  # compile/run split
    if errors:
        out["error"] = "; ".join(errors)

    # the flagship line goes out FIRST so an external driver timeout during
    # the extras can never lose it (the rounds-3/4 failure mode); the same
    # record is appended to the benchwatch store when
    # CST_BENCHWATCH_HISTORY is set — incrementally, for the same reason
    print(json.dumps(out), flush=True)
    benchwatch.append_emission(out, ts=time.time())

    # extras — the mesh-sharded flagship scaling rungs (scaling), the
    # incremental-merkleization dirty-fraction sweep (merkle), then
    # BASELINE configs #2/#3 (bls), #5 (kzg blob batch), #1 (minimal
    # full transition): each runs only while comfortably inside the
    # budget and only when the flagship ran on the real chip; each
    # success re-prints a superset JSON line (drivers parsing the
    # first or the last line both see the flagship metric)
    for mode in ("scaling", "merkle", "das", "forkchoice", "bls", "kzg",
                 "spec"):
        elapsed = time.time() - start
        if (result is None or platform is not None
                or elapsed >= EXTRAS_DEADLINE):
            break
        log(f"--- {mode} extras (elapsed {elapsed:.0f}s) ---")
        extras, err = _run_worker(mode, ATTEMPT_TIMEOUT)
        if extras is not None:
            out.setdefault("extra", {}).update(extras)
            print(json.dumps(out), flush=True)
            for name, rec in extras.items():
                if isinstance(rec, dict) and "value" in rec:
                    benchwatch.append_emission(
                        dict(rec, metric=name), ts=time.time())
        else:
            log(f"{mode} extras skipped: {err}")

    sys.exit(0 if result is not None else 1)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        if sys.argv[2] == "epoch":
            worker_epoch(N_VALIDATORS)
        elif sys.argv[2] == "scaling":
            worker_scaling()
        elif sys.argv[2] == "merkle":
            worker_merkle()
        elif sys.argv[2] == "das":
            worker_das()
        elif sys.argv[2] == "forkchoice":
            worker_forkchoice()
        elif sys.argv[2] == "bls":
            worker_bls()
        elif sys.argv[2] == "kzg":
            worker_kzg()
        elif sys.argv[2] == "spec":
            worker_spec()
        else:
            raise SystemExit(f"unknown worker {sys.argv[2]!r}")
    else:
        main()
