"""Benchmark: mainnet-preset epoch-processing sweep @ 1M validators.

North-star config #4 (BASELINE.md): the per-validator epoch pipeline
(rewards/penalties + slashings + effective-balance updates) plus the
registry-scale merkleization (balances list root + validator registry root).

- TPU path: `parallel.epoch_sweep` + device merkle kernels, one fused XLA
  program over a 2**20-validator struct-of-arrays registry.
- Baseline: the executable spec's pure-Python pipeline + SSZ engine
  hash_tree_root, measured on a 1024-validator mainnet state and scaled
  linearly (the pipeline is O(N); sorting terms are negligible).

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
"""

from __future__ import annotations

import json
import sys
import time

import jax
import numpy as np

# entry points own the process-wide uint64 switch (parallel.require_x64)
jax.config.update("jax_enable_x64", True)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def baseline_cpu_seconds_per_validator() -> float:
    """Pure-Python spec pipeline + SSZ HTR, per validator."""
    from consensus_specs_tpu.models.builder import build_spec
    from consensus_specs_tpu.testlib.context import (
        default_activation_threshold)
    from consensus_specs_tpu.testlib.helpers.attestations import (
        prepare_state_with_attestations)
    from consensus_specs_tpu.testlib.helpers.genesis import (
        create_genesis_state)
    from consensus_specs_tpu.utils.ssz.ssz_impl import hash_tree_root

    spec = build_spec("phase0", "mainnet")
    n = 1024
    balances = [spec.MAX_EFFECTIVE_BALANCE] * n
    state = create_genesis_state(
        spec, balances, default_activation_threshold(spec))
    prepare_state_with_attestations(spec, state)

    best = float("inf")
    for _ in range(3):
        st = state.copy()
        t0 = time.perf_counter()
        spec.process_justification_and_finalization(st)
        spec.process_rewards_and_penalties(st)
        spec.process_slashings(st)
        spec.process_effective_balance_updates(st)
        hash_tree_root(st.balances)
        hash_tree_root(st.validators)
        best = min(best, time.perf_counter() - t0)
    log(f"baseline: {best:.3f}s @ {n} validators "
        f"({best / n * 1e6:.1f} us/validator)")
    return best / n


def tpu_seconds_per_step(n: int) -> float:
    import jax

    from consensus_specs_tpu.models.builder import build_spec
    from consensus_specs_tpu.parallel import (
        EpochParams, EpochScalars, ValidatorLeaves, balances_list_root,
        epoch_sweep, validator_records_root, validator_registry_root)

    from __graft_entry__ import _synthetic_registry

    params = EpochParams.from_spec(build_spec("phase0", "mainnet"))
    reg = _synthetic_registry(n)
    sc = EpochScalars(current_epoch=np.uint64(100_000),
                      finality_delay=np.uint64(2),
                      slashings_sum=np.uint64(32_000_000_000))
    rng = np.random.RandomState(7)
    pk_root = rng.randint(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32)
    cred = rng.randint(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32)

    @jax.jit
    def step(reg, sc, length, pk_root, cred):
        new_bal, new_eff = epoch_sweep(reg, sc, params, axis_name=None)
        bal_root = balances_list_root(new_bal, length)
        rec = validator_records_root(
            ValidatorLeaves(pk_root, cred), new_eff, reg.slashed,
            reg.activation_eligibility_epoch, reg.activation_epoch,
            reg.exit_epoch, reg.withdrawable_epoch)
        reg_root = validator_registry_root(rec, length)
        return new_bal, new_eff, bal_root, reg_root

    args = (reg, sc, np.uint64(n), pk_root, cred)
    t0 = time.perf_counter()
    jax.block_until_ready(step(*args))
    log(f"tpu: compile+first run {time.perf_counter() - t0:.1f}s "
        f"on {jax.devices()[0]}")
    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jax.block_until_ready(step(*args))
    dt = (time.perf_counter() - t0) / iters
    log(f"tpu: {dt * 1e3:.1f} ms/step @ {n} validators "
        f"(root {np.asarray(out[3])[:2]})")
    return dt


def main():
    n = 1 << 20
    per_val_cpu = baseline_cpu_seconds_per_validator()
    baseline_s = per_val_cpu * n
    tpu_s = tpu_seconds_per_step(n)
    print(json.dumps({
        "metric": "mainnet_epoch_sweep_1m_validators_wall",
        "value": round(tpu_s, 4),
        "unit": "s",
        "vs_baseline": round(baseline_s / tpu_s, 1),
    }))


if __name__ == "__main__":
    main()
