"""CPU smoke for the benchmark harnesses (`make bench-smoke`).

Runs tiny-shape configurations of bench.py (epoch worker) and
bench_bls.py on the CPU platform and asserts the JSON output contract
the external driver parses — so bench bit-rot (import errors, schema
drift, kernel regressions that crash at trace time) is caught without a
TPU.  The kzg worker is excluded: its mainnet 4096-wide blob shapes have
no tiny-shape knob and would dominate the lane's wall time.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

HERE = Path(__file__).resolve().parent


def _run(cmd, env_extra, timeout):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_extra)
    print(f"--- {' '.join(cmd)} ---", file=sys.stderr, flush=True)
    proc = subprocess.run([sys.executable] + cmd, capture_output=True,
                          text=True, timeout=timeout, env=env,
                          cwd=str(HERE))
    if proc.stderr:
        sys.stderr.write(proc.stderr[-2000:])
        sys.stderr.flush()
    if proc.returncode != 0:
        raise SystemExit(f"{cmd}: rc={proc.returncode}")
    parsed = []
    for line in (proc.stdout or "").splitlines():
        if not line.strip():
            continue
        try:
            parsed.append(json.loads(line))
        except json.JSONDecodeError:
            raise SystemExit(f"{cmd}: non-JSON stdout line: {line!r}")
    if not parsed:
        raise SystemExit(f"{cmd}: produced no JSON line")
    return parsed


def main():
    out = _run(["bench.py", "--worker", "epoch"],
               {"CST_BENCH_N": "1024", "CST_NO_COMPILE_CACHE": "1"},
               timeout=900)
    last = out[-1]
    assert isinstance(last.get("seconds"), (int, float)) \
        and last["seconds"] > 0, last
    print("bench.py epoch worker JSON OK:", json.dumps(last))

    out = _run(["bench_bls.py"],
               {"CST_BLS_BENCH_N": "2", "CST_BLS_BENCH_COMMITTEE": "2",
                "CST_BLS_BENCH_SYNC": "4"},
               timeout=1800)
    metrics = [o for o in out if "metric" in o]
    assert len(metrics) == 2, out
    for m in metrics:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(m), m
        assert isinstance(m["value"], (int, float)), m
    print("bench_bls.py JSON OK:", json.dumps(metrics))
    print("bench smoke: PASS")


if __name__ == "__main__":
    main()
