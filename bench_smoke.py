"""CPU smoke for the benchmark harnesses (`make bench-smoke`).

Runs tiny-shape configurations of bench.py (epoch worker) and
bench_bls.py on the CPU platform and asserts the JSON output contract
the external driver parses — so bench bit-rot (import errors, schema
drift, kernel regressions that crash at trace time) is caught without a
TPU.  The kzg worker is excluded: its mainnet 4096-wide blob shapes have
no tiny-shape knob and would dominate the lane's wall time.

The sub-benches run with CST_TELEMETRY=1 so the `"telemetry"` sub-object
(compile_s/run_s split, padding waste, MSM/h2c routing — see
`consensus_specs_tpu.telemetry`) is asserted present and schema-valid on
every metric line: the bench contract cannot silently drop it.  The
bench_bls run also sets CST_TRACE_FILE and checks the emitted Chrome
trace is loadable trace-event JSON, and probes the MSM break-even at one
tiny size (n=4) to keep the probe path exercised.

Both sub-benches additionally run with CST_COSTMODEL=1 and assert the
cost-model contract: the telemetry block carries a `costmodel` block
with nonzero flops/bytes for the flagship kernel (the fused epoch step;
the BLS round must cover the pairing/MSM/h2c/sha256 kernel surface),
and the benchwatch store round-trips the new `costmodel` record kind.

A third round runs bench_serve.py closed-loop on tiny shapes with
request tracing armed (CST_TRACE_REQUESTS=1) and asserts the serving
contract: a steady-state `"serve"` sub-object (verifies/sec,
per-request p50/p99, queue-depth histogram — `validate_serve_block`),
the `latency_attribution` tail decomposition (every served kind
present, exemplar components summing to end-to-end within 1ms), the
`serve::*` + `latency::*` benchwatch history records, the queue-depth
/ in-flight gauge counter tracks AND the per-request flow arrows
(submit → batch → settle, one per kind) in the Chrome trace, the
report's "Tail latency" section, and the worst-N exemplar artifact
(`out/serve_exemplars.json`).

`bench_smoke.py --chaos` (the `make chaos-smoke` / CI chaos-smoke
lane) runs ONLY the chaos round: bench_serve.py under
CST_SERVE_CHAOS=1 with a canned fault plan injecting dispatch failures
into the RLC kernel, asserting the resilience contract end to end —
zero wrong results, breaker trip → oracle fallback → re-close, finite
recovery latency, a schema-valid `"resilience"` block
(`validate_resilience_block`), the `resilience::*` history-record
round-trip, and the benchwatch report's Resilience section +
`chaos-recovery` threshold row rendering from those records.  Since
PR 9 the round also carries the checkpoint kill-and-resurrect segment
(restore+replay ≥5x over a full rebuild, root parity, the
`checkpoint::*` records and `checkpoint-restore` threshold row), the
flagship breaker arc (`flagship::degraded_steps`), and the heal path
record (`heal["path"] == "checkpoint"` — recovery restored from the
snapshot, not the O(N) rebuild).

`bench_smoke.py --das` (the `make das-smoke` lane) runs the PeerDAS
cell-proof sweep at the 128x8 sampling matrix on CPU: the `"das"`
block schema (`validate_das_block`), the >= 2x das-speedup acceptance
criterion vs the pure-Python oracle (shape-bound — the oracle pays a
per-cell Lagrange interpolation), the mixed-invalid isolation arc, the
coset-barycentric cross-check, the `das::*` history round-trip, and
the report's DAS section + threshold-row wiring.

`bench_smoke.py --forkchoice` (the `make fc-smoke` lane) runs the
device LMD-GHOST sweep on a tiny CPU tree (64 blocks x 1024
validators): the `"forkchoice"` block schema
(`validate_forkchoice_block`), the >= 2x fc-speedup acceptance
criterion vs the phase0 spec oracle's `get_head` (shape-bound — the
oracle walks every active validator per child in pure Python),
bit-exact head parity, the `forkchoice::*` history round-trip, and
the report's Fork choice section + threshold-row wiring.

`bench_smoke.py --chaos-mesh` (the `make chaos-mesh-smoke` lane) runs
the same round with CST_CHAOS_MESH=1 on the simulated 8-host-device
CPU mesh (XLA_FLAGS=--xla_force_host_platform_device_count=8): a
`device_loss` fault into `batch_verify_sharded` must re-bucket the
lost shard's statements over the surviving devices — zero wrong or
dropped statements, an invalid statement still rejected while
degraded, the half-open probe re-admitting the full mesh — and the
`mesh::*` records must round-trip with the `mesh-recovery` /
`mesh-lost-statements` threshold rows PASSing.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

from consensus_specs_tpu.telemetry import validate_bench_block
from consensus_specs_tpu.telemetry import history as benchwatch

HERE = Path(__file__).resolve().parent


def _run(cmd, env_extra, timeout):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_extra)
    print(f"--- {' '.join(cmd)} ---", file=sys.stderr, flush=True)
    proc = subprocess.run([sys.executable] + cmd, capture_output=True,
                          text=True, timeout=timeout, env=env,
                          cwd=str(HERE))
    if proc.stderr:
        sys.stderr.write(proc.stderr[-2000:])
        sys.stderr.flush()
    if proc.returncode != 0:
        raise SystemExit(f"{cmd}: rc={proc.returncode}")
    parsed = []
    for line in (proc.stdout or "").splitlines():
        if not line.strip():
            continue
        try:
            parsed.append(json.loads(line))
        except json.JSONDecodeError:
            raise SystemExit(f"{cmd}: non-JSON stdout line: {line!r}")
    if not parsed:
        raise SystemExit(f"{cmd}: produced no JSON line")
    return parsed


def _check_telemetry(record, where: str) -> dict:
    tel = record.get("telemetry")
    problems = validate_bench_block(tel)
    if problems:
        raise SystemExit(f"{where}: bad telemetry block {problems}: "
                         f"{json.dumps(tel)[:500]}")
    return tel


def _check_costmodel(tel, where: str, expect_substrings=()) -> dict:
    """Assert the `costmodel` block exists, is schema-valid, carries
    nonzero flops/bytes for at least one kernel matching each expected
    substring, and has a coherent watermark summary."""
    from consensus_specs_tpu.telemetry import validate_costmodel_block

    cm = tel.get("costmodel")
    problems = validate_costmodel_block(cm)
    if problems:
        raise SystemExit(f"{where}: bad costmodel block {problems}: "
                         f"{json.dumps(cm)[:500]}")
    kernels = cm["kernels"]
    good = {k: v for k, v in kernels.items() if "error" not in v}
    for sub in expect_substrings:
        hits = [k for k in good if sub in k]
        assert hits, (where, sub, sorted(kernels))
        k = hits[0]
        assert good[k]["flops"] > 0 and good[k]["bytes_accessed"] > 0, \
            (where, k, good[k])
        assert good[k]["bound"] in ("compute", "memory", "launch"), \
            (where, k, good[k])
    assert cm["watermarks"], (where, "no watermark samples")
    for dev, wm in cm["watermarks"].items():
        assert wm["high_water_bytes"] >= wm["last_bytes"] >= 0, (dev, wm)
    return cm


def main():
    out = _run(["bench.py", "--worker", "epoch"],
               {"CST_BENCH_N": "1024", "CST_NO_COMPILE_CACHE": "1",
                "CST_TELEMETRY": "1", "CST_COSTMODEL": "1"},
               timeout=900)
    last = out[-1]
    assert isinstance(last.get("seconds"), (int, float)) \
        and last["seconds"] > 0, last
    tel = _check_telemetry(last, "epoch worker")
    assert tel["compile_s"] > 0, tel   # the fused step DID compile
    # the flagship kernel's cost record: nonzero XLA flop/byte budget
    # the incremental-flagship contract: the rewired step reports its
    # dirty fraction and at least one passed full-rebuild parity check
    assert isinstance(last.get("dirty_frac"), float) \
        and 0 < last["dirty_frac"] <= 1, last
    assert last.get("parity_checks", 0) >= 1, last
    cm = _check_costmodel(tel, "epoch worker",
                          expect_substrings=("epoch_sweep", "merkle_build",
                                             "merkle_incr"))
    print("bench.py epoch worker JSON OK:",
          json.dumps({k: v for k, v in last.items() if k != "telemetry"}),
          f"(telemetry: compile {tel['compile_s']}s run {tel['run_s']}s; "
          f"costmodel: {len(cm['kernels'])} kernel(s))")

    trace_file = HERE / "out" / "smoke_trace.json"
    trace_file.parent.mkdir(exist_ok=True)
    if trace_file.exists():
        trace_file.unlink()
    # CST_BENCHWATCH_HISTORY makes every emitted metric line also land
    # in the longitudinal store; default to a scratch file so a local
    # smoke run does not pollute out/bench_history.jsonl, but let CI
    # point it AT the real store (its benchwatch job reports over it).
    # Only the scratch default is ever deleted — an externally named
    # store is longitudinal data this smoke must append to, not wipe.
    hist_env = os.environ.get("CST_BENCHWATCH_HISTORY")
    hist_file = Path(hist_env) if hist_env \
        else HERE / "out" / "smoke_history.jsonl"
    if not hist_env and hist_file.exists():
        hist_file.unlink()
    run_t0 = time.time()
    out = _run(["bench_bls.py"],
               {"CST_BLS_BENCH_N": "2", "CST_BLS_BENCH_COMMITTEE": "2",
                "CST_BLS_BENCH_SYNC": "4",
                "CST_TELEMETRY": "1", "CST_COSTMODEL": "1",
                "CST_BLS_BENCH_MSM_SIZES": "4",
                "CST_TRACE_FILE": str(trace_file),
                "CST_BENCHWATCH_HISTORY": str(hist_file)},
               timeout=1800)
    metrics = [o for o in out if "metric" in o]
    assert len(metrics) == 3, out    # configs #2, #3 + the MSM probe
    for m in metrics:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(m), m
        assert isinstance(m["value"], (int, float)), m
        _check_telemetry(m, m["metric"])
    probe = [m for m in metrics
             if m["metric"].startswith("g1_msm_breakeven_probe")]
    assert probe and probe[0].get("detail", {}).get("4"), probe
    # the cost-model kernel surface: RLC (device h2c), pairing, MSM,
    # sha256 merkle + barycentric from the cost sweep — cost records
    # are per-process, so the last metric line carries them all
    _check_costmodel(metrics[-1]["telemetry"], "bench_bls",
                     expect_substrings=("rlc", "pairing", "msm",
                                        "sha256", "barycentric"))
    print("bench_bls.py JSON OK:", json.dumps(
        [{k: v for k, v in m.items() if k != "telemetry"}
         for m in metrics]))

    # the benchwatch history-record contract: every metric line this run
    # emitted must have landed in the store as one schema-valid record,
    # platform-stamped "cpu" (the smoke pin).  Assertions apply to THIS
    # run's records (ts >= run start, with clock slack) — a pre-existing
    # external store may hold anything
    hist_records, skipped, hist_warns = benchwatch.load_history(hist_file)
    if not hist_env:     # we created the scratch file fresh
        assert not skipped and not hist_warns, (skipped, hist_warns)
    fresh = [r for r in hist_records
             if isinstance(r.get("ts"), (int, float))
             and r["ts"] >= run_t0 - 5]
    stored = {r["metric"]: r for r in fresh}
    assert {m["metric"] for m in metrics} <= set(stored), (
        sorted(stored), metrics)
    # the bench metric lines land as bench_emit; the same run also
    # appends costmodel-kind records (checked in depth below) — every
    # fresh record of either kind must be schema-valid and cpu-stamped
    for m in metrics:
        assert stored[m["metric"]]["source"] == "bench_emit", \
            stored[m["metric"]]
    for rec in fresh:
        problems = benchwatch.validate_record(rec)
        assert not problems, (problems, rec)
        assert rec["source"] in ("bench_emit", "costmodel"), rec
        assert rec["platform"] == "cpu", rec
    probe_rec = [r for r in fresh
                 if r["metric"].startswith("g1_msm_breakeven_probe")]
    assert probe_rec and probe_rec[0].get("detail", {}).get("4"), probe_rec
    print(f"benchwatch history OK: {len(fresh)} records this run -> "
          f"{hist_file}")

    # the new `costmodel` record kind round-trips: one schema-valid
    # record per captured kernel plus the per-device memory high-water
    # marks, all re-loadable through the same history reader
    cost_recs = [r for r in hist_records if r.get("source") == "costmodel"]
    cost_kernels = [r for r in cost_recs
                    if r["metric"].startswith("costmodel::")]
    wm_recs = [r for r in cost_recs
               if r["metric"].startswith("device_mem_high_water::")]
    assert cost_kernels, [r["metric"] for r in hist_records]
    assert wm_recs, [r["metric"] for r in cost_recs]
    for rec in cost_recs:
        assert not benchwatch.validate_record(rec), rec
    names = {r["metric"] for r in cost_kernels}
    for sub in ("rlc", "pairing", "msm", "sha256", "barycentric"):
        assert any(sub in n for n in names), (sub, sorted(names))
    for rec in cost_kernels:
        cm = rec.get("costmodel")
        assert isinstance(cm, dict) and cm.get("flops", 0) > 0, rec
    print(f"costmodel history OK: {len(cost_kernels)} kernel record(s), "
          f"{len(wm_recs)} watermark record(s)")

    # CST_TRACE_FILE must have produced loadable Chrome trace-event JSON
    trace = json.loads(trace_file.read_text())
    events = trace["traceEvents"]
    spans = [e for e in events if e.get("ph") == "X"]
    assert spans, "trace file has no complete ('X') events"
    for e in spans:
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e), e
    names = {e["name"] for e in spans}
    assert "bls.batch_verify" in names, sorted(names)
    # cost-model counter track: watermark samples + per-kernel cost
    # records ride as 'C' (counter) events alongside the span track
    counters = [e for e in events if e.get("ph") == "C"]
    counter_names = {e["name"] for e in counters}
    assert "device_memory_bytes" in counter_names, sorted(counter_names)
    assert any(n.startswith("cost.") for n in counter_names), \
        sorted(counter_names)
    print(f"chrome trace OK: {len(spans)} spans + {len(counters)} "
          f"counter events -> {trace_file}")

    # the incremental-merkleization dirty-fraction round (ROADMAP
    # "Incremental merkleization for the flagship"): the acceptance
    # shape — 2**20 leaves on CPU, incremental update at 1% dirty vs a
    # full re-merkleize — emitting the merkle_incr::* records the
    # benchwatch `merkle-incremental-speedup` threshold row evaluates.
    # The parent appends the records (the worker only prints), stamped
    # with the worker's platform so the TPU-only regression rule never
    # sees a CPU smoke as a TPU round.
    merkle_t0 = time.time()
    out = _run(["bench.py", "--worker", "merkle"],
               {"CST_MERKLE_N": str(1 << 20),
                "CST_MERKLE_DIRTY_FRAC": "0.01,1.0",
                "CST_MERKLE_PROOF_BATCH": "64",
                "CST_TELEMETRY": "1"},
               timeout=1800)
    merkle = out[-1]
    platform = merkle.get("platform", "cpu")
    upd = merkle.get("merkle_incr::update@frac0.01")
    assert isinstance(upd, dict), sorted(merkle)
    assert {"value", "unit", "vs_baseline", "detail"} <= set(upd), upd
    assert upd["unit"] == "s" and upd["value"] > 0, upd
    assert upd["detail"]["n_leaves"] == 1 << 20, upd
    # the ROADMAP target is >= 5x at 1% dirty (threshold row); the smoke
    # gate is a loose sanity floor so a slow CI host cannot flake it
    assert upd["vs_baseline"] >= 2.0, upd
    _check_telemetry(upd, "merkle worker")
    full_upd = merkle.get("merkle_incr::update@frac1")
    assert isinstance(full_upd, dict) and full_upd["value"] > 0, merkle
    proofs = [v for k, v in merkle.items()
              if k.startswith("merkle_incr::proofs@")]
    assert proofs and proofs[0]["detail"]["us_per_proof"] > 0, merkle
    prev_hist = os.environ.get("CST_BENCHWATCH_HISTORY")
    os.environ["CST_BENCHWATCH_HISTORY"] = str(hist_file)
    try:
        for name, rec in merkle.items():
            if isinstance(rec, dict) and "value" in rec:
                benchwatch.append_emission(
                    dict(rec, metric=name, platform=platform),
                    ts=time.time())
    finally:
        if prev_hist is None:
            os.environ.pop("CST_BENCHWATCH_HISTORY", None)
        else:
            os.environ["CST_BENCHWATCH_HISTORY"] = prev_hist
    hist_records, _, _ = benchwatch.load_history(hist_file)
    fresh = {r["metric"]: r for r in hist_records
             if isinstance(r.get("ts"), (int, float))
             and r["ts"] >= merkle_t0 - 5}
    mrec = fresh.get("merkle_incr::update@frac0.01")
    assert mrec is not None, sorted(fresh)
    assert not benchwatch.validate_record(mrec), mrec
    assert mrec["platform"] == platform, mrec
    print(f"merkle incremental OK: {upd['vs_baseline']}x vs full "
          f"re-merkleize @ 1% dirty @ 2**20 leaves "
          f"({proofs[0]['detail']['us_per_proof']} us/proof)")

    # the serving subsystem's sustained-load round: closed-loop (the
    # measured rate is this host's capacity — an open-loop mainnet-rate
    # clock on an arbitrary CI box would idle or diverge), tiny pool /
    # committee / rung shapes, long-enough windows that batch-settle
    # granularity doesn't defeat the ±20% steady-state check.  Asserts
    # the `"serve"` bench sub-object contract, the serve::* history
    # record round-trip, and the gauge counter tracks in the trace.
    from consensus_specs_tpu.telemetry import validate_serve_block

    serve_trace = HERE / "out" / "smoke_serve_trace.json"
    if serve_trace.exists():
        serve_trace.unlink()
    exemplar_file = HERE / "out" / "serve_exemplars.json"
    if exemplar_file.exists():
        exemplar_file.unlink()
    scrape_file = HERE / "out" / "metrics_scrape.txt"
    if scrape_file.exists():
        scrape_file.unlink()
    slo_file = HERE / "out" / "slo_breaches.json"
    if slo_file.exists():
        slo_file.unlink()
    # an ephemeral port for the live exposition endpoint (bind/release:
    # CI runners share the host, a fixed port would collide)
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        metrics_port = sock.getsockname()[1]
    serve_t0 = time.time()
    # CST_TRACE_REQUESTS=1: the round runs with request tracing armed —
    # per-request percentile semantics, the latency_attribution block,
    # flow events in the trace, latency::* records, and the exemplar
    # artifact are all asserted below (the acceptance arc of the
    # request-tracing PR).  CST_METRICS_PORT + CST_SLO_RULES arm the
    # live-monitoring arc: the loadgen self-scrapes the exposition
    # endpoint mid-round (validated line-by-line below) and the SLO
    # watchdog runs sane-bound rules the round must end CLEAN on
    out = _run(["bench_serve.py"],
               {"CST_SERVE_DURATION_S": "12", "CST_SERVE_RATE": "0",
                "CST_SERVE_POOL": "4", "CST_SERVE_COMMITTEE": "4",
                "CST_SERVE_MAX_BATCH": "8", "CST_SERVE_WINDOWS": "3",
                "CST_TELEMETRY": "1", "CST_TRACE_REQUESTS": "1",
                "CST_TRACE_FILE": str(serve_trace),
                "CST_METRICS_PORT": str(metrics_port),
                "CST_SLO_RULES": ("serve.p99_ms<100000:name=p99-sane; "
                                  "serve.queue_depth<100000"
                                  ":name=queue-sane"),
                "CST_OCCUPANCY": "1",
                "CST_BENCHWATCH_HISTORY": str(hist_file)},
               timeout=900)
    serve_lines = [o for o in out if o.get("metric") == "serve_sustained_load"]
    assert len(serve_lines) == 1, out
    sl = serve_lines[0]
    assert sl["unit"] == "verifies/s" and sl["value"] > 0, sl
    block = sl.get("serve")
    problems = validate_serve_block(block)
    assert not problems, (problems, json.dumps(block)[:500])
    assert block["steady"], ("no steady state", block["windows"])
    assert block["settled"] == block["submitted"] > 0, block
    assert block["failed"] == 0, block
    assert block["p50_ms"] is not None and block["p99_ms"] is not None, block
    assert block["queue_depth"]["hist"], block
    assert block["mode"] == "closed", block
    # the stateless-client lane: `submit_proof_request` rode the same
    # futures pipeline (and settled — failed==0 covers it above)
    assert block["kinds"].get("proof", 0) >= 1, block["kinds"]
    _check_telemetry(sl, "serve bench")

    # request-tracing contract: per-request percentile basis, a
    # schema-valid latency_attribution with one entry per served kind,
    # and components that sum to each exemplar's end-to-end within 1ms
    from consensus_specs_tpu.telemetry import validate_latency_attribution
    served_kinds = {k for k, n in block["kinds"].items() if n > 0}

    assert block.get("latency_source") == "reqtrace", block.get(
        "latency_source")
    la = block.get("latency_attribution")
    problems = validate_latency_attribution(la)
    assert not problems, (problems, json.dumps(la)[:500])
    assert served_kinds <= set(la["kinds"]), (served_kinds,
                                              sorted(la["kinds"]))
    assert la["answered"] == block["settled"], (la["answered"], block)
    for ex_rec in la["worst"]:
        total = sum(ex_rec["components_ms"].values())
        assert abs(total - ex_rec["e2e_ms"]) <= 1.0, ex_rec
    for kind, blk in la["kinds"].items():
        assert sum(blk["outcomes"].values()) == blk["count"], (kind, blk)
    print(f"latency attribution OK: {len(la['kinds'])} kind(s), p99 "
          f"queue frac {la['p99_queue_frac']}, {len(la['worst'])} "
          f"exemplar(s)")

    # device-occupancy contract (CST_OCCUPANCY=1): the serve block
    # carries a schema-valid occupancy sub-object whose busy wall plus
    # the four bubble causes partition the measured wall EXACTLY (the
    # same contiguity discipline as the reqtrace components), and at
    # depth>=2 the prep-overlap score is computable
    from consensus_specs_tpu.telemetry import validate_occupancy_block
    occ = block.get("occupancy")
    assert occ is not None, "CST_OCCUPANCY=1 but no occupancy block"
    problems = validate_occupancy_block(occ)
    assert not problems, (problems, json.dumps(occ)[:500])
    assert occ["busy_s"] > 0, occ
    occ_total = occ["busy_s"] + sum(occ["bubbles_s"].values())
    assert abs(occ_total - occ["wall_s"]) <= 1e-6 * occ["wall_s"], \
        (occ_total, occ["wall_s"], occ["bubbles_s"])
    if (occ.get("depth") or 0) >= 2:
        assert occ["overlap"]["score"] is not None, occ["overlap"]
    print(f"occupancy OK: busy_frac {occ['busy_frac']}, bubbles "
          + json.dumps({k: round(v, 3)
                        for k, v in occ["bubbles_s"].items()})
          + f", overlap score {occ['overlap']['score']}")
    # the worst-N exemplar artifact bench_serve writes for CI upload
    exemplars = json.loads(exemplar_file.read_text())
    assert exemplars["worst"] == la["worst"], exemplar_file

    # live-monitoring arc, scrape side: the loadgen self-scraped the
    # CST_METRICS_PORT endpoint mid-round and wrote the exposition text
    # verbatim — re-parse it LINE BY LINE with the strict parser and
    # assert every served kind appears as a labeled lifetime series
    from consensus_specs_tpu.telemetry import metrics_export
    assert scrape_file.exists(), \
        "loadgen never wrote the mid-round scrape artifact"
    scrape = metrics_export.parse_exposition(scrape_file.read_text())
    scraped_kinds = {lb["kind"] for lb, _ in
                     scrape.get("cst_serve_requests_total", [])}
    assert served_kinds <= scraped_kinds, (sorted(served_kinds),
                                           sorted(scraped_kinds))
    assert scrape.get("cst_serve_live_queue_depth"), sorted(scrape)
    # the watchdog publishes its own rule-labeled families
    slo_rules_scraped = {lb.get("rule") for lb, _ in
                         scrape.get("cst_slo_breaching", [])}
    assert slo_rules_scraped == {"p99-sane", "queue-sane"}, \
        slo_rules_scraped
    assert scrape.get("cst_slo_ticks_total", [({}, 0.0)])[0][1] > 0, \
        scrape.get("cst_slo_ticks_total")
    # the occupancy families publish live: the rolling busy fraction
    # and the cause-labeled bubble accumulators
    assert scrape.get("cst_serve_device_busy_frac"), sorted(scrape)
    bubble_causes = {lb["cause"] for lb, _ in
                     scrape.get("cst_serve_bubble_seconds_total", [])}
    assert bubble_causes == {"host_prep", "queue_starved",
                             "settle_serialized", "drain"}, bubble_causes
    print(f"metrics scrape OK: {len(scrape)} families, kinds "
          f"{sorted(scraped_kinds)} -> {scrape_file}")

    # live-monitoring arc, watchdog side: a healthy round ends CLEAN —
    # zero breaches over a positive tick count, schema-valid, and the
    # breach-evidence artifact rides along for CI upload
    from consensus_specs_tpu.telemetry import validate_slo_block
    slo = block.get("slo")
    assert slo is not None, "CST_SLO_RULES armed but no slo block"
    assert not validate_slo_block(slo), validate_slo_block(slo)
    assert slo["ticks"] > 0, slo
    assert slo["breaches"] == 0 and slo["clean"], slo
    assert {r["name"] for r in slo["rules"]} == {"p99-sane",
                                                 "queue-sane"}, slo
    assert json.loads(slo_file.read_text())["slo"]["clean"], slo_file
    print(f"slo watchdog OK: clean round, {slo['ticks']} tick(s), "
          f"evidence -> {slo_file}")

    print("bench_serve.py JSON OK:", json.dumps(
        {k: v for k, v in sl.items() if k not in ("telemetry", "serve")}),
        f"({block['verifies_per_s']} verifies/s, steady over "
        f"{len(block['windows'])} windows)")

    # serve history round-trip: the emission must land as the
    # bench_emit line PLUS serve-source serve::* records (throughput
    # carrying the compacted block, latency percentiles standalone)
    # PLUS the latency-source attribution records the traced round mines
    hist_records, _, _ = benchwatch.load_history(hist_file)
    fresh = [r for r in hist_records
             if isinstance(r.get("ts"), (int, float))
             and r["ts"] >= serve_t0 - 5]
    by_metric = {r["metric"]: r for r in fresh}
    assert "serve_sustained_load" in by_metric, sorted(by_metric)
    assert by_metric["serve_sustained_load"]["source"] == "bench_emit"
    for name in ("serve::verifies_per_s", "serve::p50_ms",
                 "serve::p99_ms"):
        rec = by_metric.get(name)
        assert rec is not None, (name, sorted(by_metric))
        assert rec["source"] == "serve" and rec["platform"] == "cpu", rec
        assert not benchwatch.validate_record(rec), rec
    vrec = by_metric["serve::verifies_per_s"]
    assert vrec["serve"]["queue_depth"]["hist"], vrec
    assert isinstance(vrec["serve"]["steady"], bool), vrec
    assert vrec["serve"]["latency_source"] == "reqtrace", vrec
    for kind in sorted(served_kinds):
        rec = by_metric.get(f"latency::p99_ms@{kind}")
        assert rec is not None, (kind, sorted(by_metric))
        assert rec["source"] == "latency", rec
        assert not benchwatch.validate_record(rec), rec
        comp = rec["latency"]["p99_components_ms"]
        assert set(comp) == {"queue_wait", "batch_form", "device_wall",
                             "settle", "detour"}, comp
    qrec = by_metric.get("latency::p99_queue_frac")
    assert qrec is not None and qrec["source"] == "latency", \
        sorted(by_metric)
    assert qrec["latency"]["worst"], qrec
    # the slo record kinds land too: zero breaches carrying the compact
    # block, and the clean-round 0/1 the threshold row gates
    brec = by_metric.get("slo::breaches")
    assert brec is not None and brec["source"] == "slo", sorted(by_metric)
    assert not benchwatch.validate_record(brec), brec
    assert brec["value"] == 0 and brec["slo"]["ticks"] > 0, brec
    crec = by_metric.get("slo::clean_round")
    assert crec is not None and crec["value"] == 1.0, crec
    assert not benchwatch.validate_record(crec), crec
    # the pipeline-source occupancy records land: busy_frac carrying
    # the compact block, one bubble record per cause
    orec = by_metric.get("pipeline::busy_frac")
    assert orec is not None and orec["source"] == "pipeline", \
        sorted(by_metric)
    assert not benchwatch.validate_record(orec), orec
    assert orec["value"] == occ["busy_frac"], (orec, occ["busy_frac"])
    for cause in ("host_prep", "queue_starved", "settle_serialized",
                  "drain"):
        assert f"pipeline::bubble@{cause}" in by_metric, sorted(by_metric)
    print(f"serve history OK: {len(fresh)} records this run "
          f"(incl. {sum(1 for m in by_metric if m.startswith('latency::'))} "
          f"latency:: records)")

    # the serve pipeline's gauges ride the Chrome trace as 'C' counter
    # tracks (queue depth + in-flight batches breathing against the
    # span timeline, same mechanism as device_memory_bytes)
    trace = json.loads(serve_trace.read_text())
    counter_names = {e["name"] for e in trace["traceEvents"]
                     if e.get("ph") == "C"}
    assert "serve.queue_depth" in counter_names, sorted(counter_names)
    assert "serve.inflight_batches" in counter_names, sorted(counter_names)
    assert any(n.startswith("pipeline.device_busy.")
               for n in counter_names), sorted(counter_names)
    span_names = {e["name"] for e in trace["traceEvents"]
                  if e.get("ph") == "X"}
    assert "serve.pump" in span_names, sorted(span_names)
    # request-tracing flow events: every served kind must have at least
    # one submit→…→settle flow arrow ('s' and matching 'f' by id), and
    # request/batch lifecycle spans ride the per-kind request tracks
    flow_s = [e for e in trace["traceEvents"] if e.get("ph") == "s"]
    flow_f = [e for e in trace["traceEvents"] if e.get("ph") == "f"]
    s_names = {e["name"] for e in flow_s}
    for kind in sorted(served_kinds):
        assert f"req.{kind}" in s_names, (kind, sorted(s_names))
    s_ids = {e["id"] for e in flow_s}
    f_ids = {e["id"] for e in flow_f}
    assert s_ids and s_ids == f_ids, (len(s_ids), len(f_ids))
    assert any(n.startswith("req.") for n in span_names), span_names
    assert any(n.startswith("batch.") for n in span_names), span_names
    print(f"serve trace OK: gauge counter tracks + {len(flow_s)} "
          f"request flow arrows -> {serve_trace}")

    # the report renders the Tail latency section from the latency::*
    # records; the serve-p99-queue-frac advisory row stays TPU-gated
    # ('no data' on this CPU round)
    from consensus_specs_tpu.telemetry import report as bw_report

    serve_report = HERE / "out" / "smoke_serve_report.md"
    rc = bw_report.main(["--repo", str(HERE), "--history",
                         str(hist_file), "--out", str(serve_report),
                         "--no-update"])
    assert rc == 0, f"benchwatch report exited {rc}"
    text = serve_report.read_text()
    assert "## Tail latency (request tracing)" in text, text[:2000]
    assert "`verify`" in text and "Worst exemplar traces:" in text
    result = bw_report.build_report(
        repo=HERE, history_path=hist_file, snapshots=[],
        durations_path=None, top_n=5, strict=False,
        max_regress_pct=0.0, update_history=False)
    rows = {t["id"]: t for t in result["thresholds"]}
    assert rows["serve-p99-queue-frac"]["status"] == "no data", \
        rows["serve-p99-queue-frac"]
    # the watchdog section renders and the clean-round row gates green
    # on this zero-breach round
    assert "## SLO (live watchdog)" in text, text[:2000]
    assert rows["slo-clean-round"]["status"] == "PASS", \
        rows["slo-clean-round"]
    # the occupancy section renders from the pipeline:: records; the
    # serve-occupancy floor stays TPU-gated on this CPU round
    assert "## Pipeline occupancy" in text, text[:2000]
    assert rows["serve-occupancy"]["status"] == "no data", \
        rows["serve-occupancy"]
    print(f"tail-latency report OK: section rendered, TPU-gated "
          f"queue-frac row reads 'no data' on CPU, slo-clean-round "
          f"PASS -> {serve_report}")

    # telemetry-OFF contract: the default path (what a non-telemetry
    # TPU round runs) must emit the plain 2-metric lines — no
    # "telemetry" key, no probe.  Same shapes as the run above, so the
    # persistent compile cache makes this re-run cheap.
    out = _run(["bench_bls.py"],
               {"CST_BLS_BENCH_N": "2", "CST_BLS_BENCH_COMMITTEE": "2",
                "CST_BLS_BENCH_SYNC": "4",
                "CST_TELEMETRY": "", "CST_TRACE_FILE": "",
                "CST_COSTMODEL": ""},
               timeout=1800)
    metrics = [o for o in out if "metric" in o]
    assert len(metrics) == 2, out
    for m in metrics:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(m), m
        assert "telemetry" not in m, m
    print("bench_bls.py telemetry-off JSON OK:", json.dumps(metrics))
    print("bench smoke: PASS")


def chaos_main(mesh: bool = False):
    """The chaos-smoke lane (see module docstring): one bench_serve.py
    chaos round on tiny CPU shapes under a canned fault plan, then the
    resilience record/report contract checks.  `mesh=True` (the
    chaos-mesh lane) additionally arms the simulated-mesh shard-loss
    segment and asserts its contract."""
    from consensus_specs_tpu.telemetry import validate_resilience_block

    hist_env = os.environ.get("CST_BENCHWATCH_HISTORY")
    hist_file = Path(hist_env) if hist_env \
        else HERE / "out" / "smoke_chaos_history.jsonl"
    hist_file.parent.mkdir(exist_ok=True)
    if not hist_env and hist_file.exists():
        hist_file.unlink()
    chaos_slo_file = HERE / "out" / "chaos_slo_breaches.json"
    if chaos_slo_file.exists():
        chaos_slo_file.unlink()
    incidents_dir = HERE / "out" / "smoke_incidents"
    if incidents_dir.exists():
        import shutil
        shutil.rmtree(incidents_dir)
    chaos_t0 = time.time()
    # the canned plan: deterministic dispatch failures into the RLC
    # verify kernel (the acceptance shape — resilience.chaos's default,
    # spelled out here so the smoke pins the spec-string form too).
    # `key=rlc_h*` matches the single-chip RLC kernels (rlc_h2c /
    # rlc_host_hash) but NOT rlc_sharded@… — the mesh segment owns its
    # own device_loss plan and must not eat the serve round's faults.
    env = {"CST_SERVE_CHAOS": "1",
           "CST_FAULTS": "seed=1234;dispatch:raise:key=rlc_h*:count=4",
           "CST_SERVE_DURATION_S": "9", "CST_SERVE_RATE": "0",
           "CST_SERVE_POOL": "4", "CST_SERVE_COMMITTEE": "4",
           "CST_SERVE_MAX_BATCH": "8", "CST_SERVE_WINDOWS": "3",
           "CST_TELEMETRY": "1",
           "CST_FLIGHTREC_ON_BREACH": "1",
           "CST_FLIGHTREC_DIR": str(incidents_dir),
           "CST_BENCHWATCH_HISTORY": str(hist_file)}
    if mesh:
        env["CST_CHAOS_MESH"] = "1"
        env.setdefault(
            "XLA_FLAGS", os.environ.get("XLA_FLAGS")
            or "--xla_force_host_platform_device_count=8")
    out = _run(["bench_serve.py"], env, timeout=1800 if mesh else 1200)
    lines = [o for o in out if o.get("metric") == "serve_sustained_load"]
    assert len(lines) == 1, out
    sl = lines[0]
    assert "error" not in sl, sl.get("error")
    res = sl.get("resilience")
    problems = validate_resilience_block(res)
    assert not problems, (problems, json.dumps(res)[:500])
    # the acceptance arc: faults fired, zero wrong answers, the breaker
    # tripped into oracle-fallback degraded mode and re-closed, the
    # service returned to steady state with a finite recovery latency,
    # and the diverged Merkle forest healed back to the oracle root
    assert res["faults_injected"] >= 1, res
    assert res["injected_sites"].get("dispatch", 0) >= 1, res
    assert res["wrong_results"] == 0, res
    assert res["failed_requests"] == 0, res
    assert res["checked_results"] > 0, res
    assert res["fallbacks"] >= 1 and res["retries"] >= 1, res
    br = res["breaker"]
    assert br["trips"] >= 1, br
    tos = [t["to"] for t in br["transitions"]]
    assert "open" in tos and "half_open" in tos and "closed" in tos, br
    # every breaker that saw post-fault traffic re-closed — usually via
    # the half-open probe (half_open → closed), but a batch dispatched
    # BEFORE the trip that settles successfully after it closes the
    # breaker directly (open → closed): the pipeline keeps `depth`
    # batches in flight, and their success is real device health.  A
    # rung the closed-loop batching never revisited after the fault
    # window keeps its open breaker (no probe traffic) — that is not a
    # failed recovery, which the recovery-latency/steady asserts pin
    reclosed = [t["key"] for t in br["transitions"]
                if t["to"] == "closed"
                and t["from"] in ("half_open", "open")]
    assert reclosed, br
    assert any(s == "closed" for s in br["states"].values()), br
    assert res["recovered"] and res["recovery_latency_s"] is not None, res
    assert 0 < res["recovery_latency_s"] < 300, res
    assert res["heal"]["diverged"] and res["heal"]["detected"], res
    assert res["heal"]["recovery_s"] > 0, res
    # the heal routed through checkpoint restore (snapshot valid), not
    # the O(N) rebuild floor
    assert res["heal"]["path"] == "checkpoint", res["heal"]
    # checkpoint kill-and-resurrect: root parity held and restore+replay
    # beat the full rebuild (the >=5x gate is the threshold row below)
    cp = res["checkpoint"]
    assert cp["parity"], cp
    assert cp["restore_s"] > 0 and cp["rebuild_s"] > 0, cp
    assert cp["journal_entries"] >= 1 and cp["snapshot_bytes"] > 0, cp
    assert cp["journal_frac"] <= 0.01, cp
    assert cp["speedup"] is not None and cp["speedup"] >= 5.0, cp
    # flagship breaker arc: the settle degraded onto the spec oracle
    # (trip + open settle), answered correctly, and re-closed
    fl = res["flagship"]
    assert fl["degraded_steps"] >= 2, fl
    assert fl["wrong_results"] == 0 and fl["checked_settles"] >= 4, fl
    assert fl["recovered"], fl
    assert fl["breaker"]["trips"] >= 1, fl
    serve = sl["serve"]
    assert serve["steady"], serve["windows"]
    assert serve["failed"] == 0, serve
    # request tracing is armed for every chaos round: per-request
    # latency semantics plus the fault→victim correlation — the blast
    # radius must be exactly the retried/fallback-answered/poisoned
    # handles (a fault victim can never settle with a clean 'ok')
    from consensus_specs_tpu.telemetry import validate_latency_attribution
    assert serve.get("latency_source") == "reqtrace", serve.get(
        "latency_source")
    la = serve.get("latency_attribution")
    assert not validate_latency_attribution(la), la
    assert "verify" in la["kinds"], sorted(la["kinds"])
    fv = res["fault_victims"]
    assert fv["count"] >= 1, fv
    assert fv["trace_ids"], fv
    assert fv["clean_ok"] == 0, fv
    assert sum(fv["outcomes"].values()) == fv["count"], fv
    assert set(fv["outcomes"]) <= {"retry", "fallback", "poisoned",
                                   "recheck", "timeout"}, fv
    # the arc recovered every victim: zero poisoned handles (matches
    # failed_requests == 0 above)
    assert fv["outcomes"].get("poisoned", 0) == 0, fv
    print("fault victims OK:", json.dumps(fv["outcomes"]),
          f"({fv['count']} victim(s))")
    # the SLO watchdog's deterministic chaos arc: the injected-fault
    # counter rule breached while the plan was live and the breach
    # CLEARED after recovery — the transition proven in both directions
    from consensus_specs_tpu.telemetry import validate_slo_block
    slo = serve.get("slo")
    assert slo is not None, "chaos round must arm the SLO watchdog"
    assert not validate_slo_block(slo), validate_slo_block(slo)
    assert slo["ticks"] > 0, slo
    assert slo["breaches"] >= 1 and not slo["clean"], slo
    assert any(r["name"] == "chaos-fault-injections"
               for r in slo["rules"]), slo["rules"]
    arc = res["slo_arc"]
    assert arc["rule"] == "chaos-fault-injections", arc
    assert arc["breached_in_fault_window"], arc
    assert arc["cleared_after_recovery"], arc
    # the breach evidence artifact landed (the CI upload)
    assert chaos_slo_file.exists(), chaos_slo_file
    slo_art = json.loads(chaos_slo_file.read_text())["slo"]
    assert slo_art["breaches"] >= 1, slo_art
    print(f"slo chaos arc OK: {slo['breaches']} breach(es) over "
          f"{slo['ticks']} tick(s), breach->clear both ways, "
          f"evidence -> {chaos_slo_file}")

    # incident flight-recorder arc (CST_FLIGHTREC_ON_BREACH=1): each
    # breached rule froze exactly ONE self-contained bundle — the fault
    # plan, the breach events, the breaker arc, and the exemplars must
    # all be readable from the bundle directory alone (plain json, no
    # live process) so a post-mortem needs nothing but the CI artifact
    from consensus_specs_tpu.telemetry import flightrec
    breached_rules = {r["name"] for r in slo["rules"]
                      if r["breaches"] >= 1}
    incidents = slo["incidents"]
    assert len(incidents) == len(breached_rules) >= 1, \
        (incidents, breached_rules)
    dumped_rules = set()
    for inc in incidents:
        bundle = Path(inc)
        if not bundle.is_absolute():
            bundle = HERE / bundle
        assert bundle.is_dir(), bundle
        manifest = json.loads((bundle / "manifest.json").read_text())
        problems = flightrec.validate_manifest(manifest)
        assert not problems, (problems, bundle)
        assert manifest["rule"] in breached_rules, manifest
        dumped_rules.add(manifest["rule"])
        fp = manifest["fault_plan"]
        assert fp is not None and fp["seed"] == 1234 and fp["faults"], fp
        events = [json.loads(ln) for ln in
                  (bundle / "events.jsonl").read_text().splitlines()]
        kinds = {e["kind"] for e in events}
        assert "slo_breach" in kinds, sorted(kinds)
        assert "fault_injected" in kinds, sorted(kinds)
        # the breaker arc up to the freeze: the chaos trip is in the ring
        trips = [e for e in events if e["kind"] == "breaker_transition"]
        assert any(e["to"] == "open" for e in trips), sorted(kinds)
        exemplars = json.loads((bundle / "exemplars.json").read_text())
        assert "worst" in exemplars, bundle
        json.loads((bundle / "state.json").read_text())
    assert dumped_rules == breached_rules, (dumped_rules, breached_rules)
    print(f"incident bundles OK: {len(incidents)} bundle(s) for "
          f"breached rule(s) {sorted(breached_rules)} -> {incidents_dir}")
    if mesh:
        mb = res["mesh"]
        assert "skipped" not in mb, mb
        assert mb["devices"] >= 2, mb
        assert mb["device_lost_events"] >= 1, mb
        assert mb["redispatches"] >= 1, mb
        assert mb["readmissions"] >= 1 and mb["readmitted"], mb
        assert mb["lost_statements"] == 0, mb
        assert mb["wrong_results"] == 0 and mb["checked_statements"] > 0, mb
        assert mb["recovery_latency_s"] is not None, mb
        assert mb["max_degraded_lanes"] >= 1, mb
        assert mb["recovered"], mb
        print("mesh segment OK:", json.dumps(mb))
    print("chaos round OK:", json.dumps(
        {k: res[k] for k in ("faults_injected", "wrong_results",
                             "fallbacks", "retries",
                             "recovery_latency_s",
                             "degraded_verifies_per_s",
                             "baseline_verifies_per_s")}))
    print("checkpoint segment OK:", json.dumps(cp))
    print("flagship segment OK:", json.dumps(
        {k: fl[k] for k in ("degraded_steps", "wrong_results",
                            "recovered")}))

    # resilience history round-trip: the emission lands as resilience-
    # source records, schema-valid, with the compact block riding the
    # recovery-latency record
    hist_records, _, _ = benchwatch.load_history(hist_file)
    fresh = {r["metric"]: r for r in hist_records
             if isinstance(r.get("ts"), (int, float))
             and r["ts"] >= chaos_t0 - 5}
    for name in ("resilience::recovery_latency_s",
                 "resilience::wrong_results",
                 "resilience::degraded_verifies_per_s",
                 "resilience::faults_injected",
                 "resilience::breaker_transitions",
                 "resilience::merkle_heal_s"):
        rec = fresh.get(name)
        assert rec is not None, (name, sorted(fresh))
        assert rec["source"] == "resilience", rec
        assert not benchwatch.validate_record(rec), rec
    rrec = fresh["resilience::recovery_latency_s"]
    assert rrec["value"] > 0 and rrec["resilience"]["recovered"], rrec
    assert fresh["resilience::wrong_results"]["value"] == 0
    # the fault-victim correlation rides the compact resilience block
    assert rrec["resilience"]["fault_victims"]["count"] >= 1, rrec
    # the chaos round's traced latency records land too
    lrec = fresh.get("latency::p99_ms@verify")
    assert lrec is not None and lrec["source"] == "latency", \
        sorted(fresh)
    assert not benchwatch.validate_record(lrec), lrec
    # the heal record carries the taken recovery path
    assert fresh["resilience::merkle_heal_s"]["heal_path"] == "checkpoint"
    # the checkpoint record kind round-trips: restore wall with the
    # restore-vs-rebuild speedup riding as vs_baseline
    crec = fresh.get("checkpoint::restore")
    assert crec is not None, sorted(fresh)
    assert crec["source"] == "checkpoint", crec
    assert not benchwatch.validate_record(crec), crec
    assert crec["value"] > 0 and crec["vs_baseline"] >= 5.0, crec
    assert crec["checkpoint"]["parity"], crec
    for name in ("checkpoint::journal_entries",
                 "checkpoint::snapshot_bytes"):
        rec = fresh.get(name)
        assert rec is not None and rec["source"] == "checkpoint", \
            (name, sorted(fresh))
    # the flagship degraded-steps record
    frec = fresh.get("resilience::flagship_degraded_steps")
    assert frec is not None and frec["value"] >= 2, frec
    assert frec["flagship"]["wrong_results"] == 0, frec
    # the SLO arc record the chaos-slo-arc row gates on, plus the
    # per-rule breach count; a breaching round must NOT mint the
    # clean-round record (that gate is for quiet rounds only)
    arec = fresh.get("resilience::slo_arc_ok")
    assert arec is not None and arec["value"] == 1.0, arec
    assert not benchwatch.validate_record(arec), arec
    srec = fresh.get("slo::breaches@chaos-fault-injections")
    assert srec is not None and srec["value"] >= 1, sorted(fresh)
    assert "slo::clean_round" not in fresh, fresh["slo::clean_round"]
    if mesh:
        for name in ("mesh::recovery_latency_s", "mesh::recovered",
                     "mesh::lost_statements",
                     "mesh::wrong_results", "mesh::degraded_lanes",
                     "mesh::device_lost_events", "mesh::readmissions"):
            rec = fresh.get(name)
            assert rec is not None, (name, sorted(fresh))
            assert rec["source"] == "mesh", rec
            assert not benchwatch.validate_record(rec), rec
        mrec = fresh["mesh::recovery_latency_s"]
        assert mrec["value"] is not None and mrec["value"] > 0, mrec
        assert mrec["mesh"]["device_lost_events"] >= 1, mrec
        assert fresh["mesh::lost_statements"]["value"] == 0
        assert fresh["mesh::wrong_results"]["value"] == 0
        assert fresh["mesh::recovered"]["value"] == 1.0
    print(f"resilience history OK: {len(fresh)} records this run -> "
          f"{hist_file}")

    # the report renders the Resilience section and evaluates the
    # chaos-recovery / chaos-correctness threshold rows from the store
    from consensus_specs_tpu.telemetry import report as bw_report

    report_md = HERE / "out" / "smoke_chaos_report.md"
    rc = bw_report.main(["--repo", str(HERE), "--history", str(hist_file),
                         "--out", str(report_md), "--no-update"])
    assert rc == 0, f"benchwatch report exited {rc}"
    text = report_md.read_text()
    assert "## Resilience (chaos rounds)" in text, text[:2000]
    assert "`resilience::recovery_latency_s`" in text
    assert "Latest chaos round:" in text
    assert "Blast radius (request tracing):" in text
    assert "## Tail latency (request tracing)" in text, text[:2000]
    result = bw_report.build_report(
        repo=HERE, history_path=hist_file, snapshots=[],
        durations_path=None, top_n=5, strict=False,
        max_regress_pct=0.0, update_history=False)
    rows = {t["id"]: t for t in result["thresholds"]}
    assert rows["chaos-recovery"]["status"] == "PASS", rows["chaos-recovery"]
    assert rows["chaos-recovered"]["status"] == "PASS", \
        rows["chaos-recovered"]
    assert rows["chaos-correctness"]["status"] == "PASS", \
        rows["chaos-correctness"]
    assert rows["checkpoint-restore"]["status"] == "PASS", \
        rows["checkpoint-restore"]
    assert rows["chaos-slo-arc"]["status"] == "PASS", rows["chaos-slo-arc"]
    assert "## SLO (live watchdog)" in text, text[:2000]
    assert "Latest checkpoint restore:" in text
    if mesh:
        for row_id in ("mesh-recovered", "mesh-recovery",
                       "mesh-lost-statements", "mesh-wrong-results"):
            assert rows[row_id]["status"] == "PASS", rows[row_id]
        assert "Latest mesh segment:" in text
        print("mesh report OK: mesh-recovered + mesh-recovery + "
              "mesh-lost-statements + mesh-wrong-results PASS")
    print(f"chaos report OK: chaos-recovery + chaos-correctness + "
          f"checkpoint-restore PASS -> {report_md}")
    print("chaos smoke: PASS")


def shard_main():
    """The shard-smoke lane (`make shard-smoke` / CI): a tiny
    mesh-sharded flagship scaling round on the simulated 8-host-device
    mesh, asserting the `"scaling"` block schema, the `scaling::*`
    history-record round-trip, and the benchwatch report's Scaling
    section + threshold rows ('no data' on CPU — the
    scaling-efficiency / flagship-8m gates are TPU acceptance
    criteria, so the smoke pins the plumbing, not the number)."""
    from consensus_specs_tpu.telemetry import validate_scaling_block

    hist_env = os.environ.get("CST_BENCHWATCH_HISTORY")
    hist_file = Path(hist_env) if hist_env \
        else HERE / "out" / "smoke_shard_history.jsonl"
    hist_file.parent.mkdir(exist_ok=True)
    if not hist_env and hist_file.exists():
        hist_file.unlink()
    shard_t0 = time.time()
    out = _run(["bench.py", "--worker", "scaling"],
               {"CST_SHARD_RUNGS": "4096,8192", "CST_SHARD_ITERS": "2",
                "CST_NO_COMPILE_CACHE": "1", "CST_TELEMETRY": "1",
                "XLA_FLAGS": os.environ.get("XLA_FLAGS")
                or "--xla_force_host_platform_device_count=8"},
               timeout=900)
    last = out[-1]
    fs = last.get("flagship_scaling")
    assert isinstance(fs, dict) and fs.get("value", 0) > 0, last
    assert fs["unit"] == "validators/s/chip", fs
    block = fs.get("scaling")
    problems = validate_scaling_block(block)
    assert not problems, (problems, json.dumps(block)[:500])
    assert block["n_devices"] == 8, block
    assert len(block["rungs"]) == 2, block
    for rung in block["rungs"]:
        assert rung["n_devices"] == 8 and rung["wall_s"] > 0, rung
        assert 0 < rung["efficiency"], rung
    # no 8M rung attempted at smoke shapes: the flagship-8m gate must
    # read 'no data', not a stale PASS/FAIL
    assert block["ok_8m"] is None, block
    _check_telemetry(fs, "scaling worker")
    print("scaling worker JSON OK:", json.dumps(
        {k: v for k, v in fs.items() if k != "telemetry"}))

    # the scaling record kind round-trips through the store: per-rung
    # flagship + efficiency records and the efficiency summary, all
    # schema-valid, cpu-stamped, mined from the ONE metric line (the
    # parent appends, like the driver does for extras workers)
    prev_hist = os.environ.get("CST_BENCHWATCH_HISTORY")
    os.environ["CST_BENCHWATCH_HISTORY"] = str(hist_file)
    try:
        benchwatch.append_emission(
            dict(fs, metric="flagship_scaling",
                 platform=last.get("platform", "cpu")),
            ts=time.time())
    finally:
        if prev_hist is None:
            os.environ.pop("CST_BENCHWATCH_HISTORY", None)
        else:
            os.environ["CST_BENCHWATCH_HISTORY"] = prev_hist
    hist_records, skipped, warns = benchwatch.load_history(hist_file)
    fresh = {r["metric"]: r for r in hist_records
             if isinstance(r.get("ts"), (int, float))
             and r["ts"] >= shard_t0 - 5}
    for name in ("flagship_scaling", "scaling::flagship@4096",
                 "scaling::flagship@8192", "scaling::efficiency@4096",
                 "scaling::efficiency@8192", "scaling::efficiency"):
        rec = fresh.get(name)
        assert rec is not None, (name, sorted(fresh))
        assert not benchwatch.validate_record(rec), rec
        assert rec["platform"] == "cpu", rec
        if name.startswith("scaling::"):
            assert rec["source"] == "scaling", rec
    srec = fresh["scaling::flagship@8192"]
    assert srec["scaling"]["n_devices"] == 8, srec
    assert srec["value"] > 0, srec
    # the summary efficiency record carries the LARGEST rung's block
    erec = fresh["scaling::efficiency"]
    assert erec["scaling"]["n_validators"] == 8192, erec
    assert "scaling::flagship_8m_ok" not in fresh, sorted(fresh)
    print(f"scaling history OK: {len(fresh)} records this run -> "
          f"{hist_file}")

    # the report renders the Scaling section (per-n_devices trend
    # table) and the TPU-gated threshold rows read 'no data' on CPU
    from consensus_specs_tpu.telemetry import report as bw_report

    report_md = HERE / "out" / "smoke_shard_report.md"
    rc = bw_report.main(["--repo", str(HERE), "--history",
                         str(hist_file), "--out", str(report_md),
                         "--no-update"])
    assert rc == 0, f"benchwatch report exited {rc}"
    text = report_md.read_text()
    assert "## Scaling (mesh-sharded flagship)" in text, text[:2000]
    assert "| 8192 | 8 |" in text, text
    assert "Latest full-mesh efficiency:" in text
    result = bw_report.build_report(
        repo=HERE, history_path=hist_file, snapshots=[],
        durations_path=None, top_n=5, strict=False,
        max_regress_pct=0.0, update_history=False)
    rows = {t["id"]: t for t in result["thresholds"]}
    assert rows["scaling-efficiency"]["status"] == "no data", \
        rows["scaling-efficiency"]
    assert rows["flagship-8m"]["status"] == "no data", rows["flagship-8m"]
    print(f"shard report OK: Scaling section rendered, TPU-gated rows "
          f"read 'no data' on CPU -> {report_md}")
    print("shard smoke: PASS")


def das_main():
    """The das-smoke lane (`make das-smoke` / CI): the PeerDAS
    cell-proof sweep at the 128x8 sampling matrix on CPU, asserting
    the `"das"` block schema, the `das::*` history-record round-trip,
    the report's DAS section render, and the threshold-row wiring —
    `das-speedup` must PASS on CPU (the >= 2x acceptance criterion is
    shape-bound: the oracle pays a per-cell Lagrange interpolation the
    device route never does), `das-throughput` must read 'no data'
    (a chip number).  The same worker run also covers the FK20
    producer + damaged-matrix recover round: the `"das_producer"`
    block schema, byte-parity vs the closed form, the >= 4x
    `das-producer-speedup` floor vs the D_u MSM route and the >= 2x
    `das-recover-speedup` floor vs the pure-Python recover oracle —
    both shape-bound, so they PASS on CPU too."""
    from consensus_specs_tpu.telemetry import (validate_das_block,
                                               validate_das_producer_block)

    hist_env = os.environ.get("CST_BENCHWATCH_HISTORY")
    hist_file = Path(hist_env) if hist_env \
        else HERE / "out" / "smoke_das_history.jsonl"
    hist_file.parent.mkdir(exist_ok=True)
    if not hist_env and hist_file.exists():
        hist_file.unlink()
    das_t0 = time.time()
    out = _run(["bench.py", "--worker", "das"],
               {"CST_DAS_MATRIX": "128x8", "CST_DAS_ORACLE_CELLS": "8",
                "CST_DAS_PRODUCE_ITERS": "1", "CST_DAS_DU_MSMS": "1",
                "CST_DAS_RECOVER_ORACLE_COSETS": "1",
                "CST_NO_COMPILE_CACHE": "1", "CST_TELEMETRY": "1"},
               timeout=3600)
    last = out[-1]
    rec = last.get("das_cell_proof_batch_128x8_verify_wall")
    assert isinstance(rec, dict) and rec.get("value", 0) > 0, last
    block = rec.get("das")
    problems = validate_das_block(block)
    assert not problems, (problems, json.dumps(block)[:500])
    assert block["matrix"] == {"columns": 128, "blobs": 8,
                               "cells": 1024}, block
    assert block["rung"] == 1024, block
    # the acceptance criterion: >= 2x over the pure-Python oracle at
    # the 128x8 matrix, on this CPU
    assert block["speedup"] >= 2.0, block
    assert rec["vs_baseline"] == block["speedup"], rec
    # the mixed-invalid arc isolated exactly the bad cell, and the
    # coset-barycentric evaluation cross-check agreed
    assert block["isolate"]["isolated"] is True, block
    assert block["eval_crosscheck"] is True, block
    _check_telemetry(rec, "das worker")
    print("das worker JSON OK:", json.dumps(
        {k: v for k, v in rec.items() if k != "telemetry"}))

    # the FK20 producer + damaged-matrix recover round: block schema,
    # byte-parity/roundtrip, and the two CPU-evaluable speedup floors
    prec = last.get("das_fk20_produce_wall")
    assert isinstance(prec, dict) and prec.get("value", 0) > 0, last
    pblock = prec.get("das_producer")
    problems = validate_das_producer_block(pblock)
    assert not problems, (problems, json.dumps(pblock)[:500])
    assert pblock["parity"] is True, pblock
    # the acceptance criteria: >= 4x vs the D_u MSM route for the
    # producer, >= 2x vs the pure-Python oracle for recovery
    assert pblock["producer_speedup"] >= 4.0, pblock
    assert prec["vs_baseline"] == pblock["producer_speedup"], prec
    assert pblock["recover"]["roundtrip"] is True, pblock
    assert pblock["recover"]["speedup"] >= 2.0, pblock
    print("das producer JSON OK:", json.dumps(
        {k: v for k, v in prec.items() if k != "telemetry"}))

    # the das record kind round-trips through the store (the parent
    # appends, like the driver does for extras workers)
    prev_hist = os.environ.get("CST_BENCHWATCH_HISTORY")
    os.environ["CST_BENCHWATCH_HISTORY"] = str(hist_file)
    try:
        benchwatch.append_emission(
            dict(rec, metric="das_cell_proof_batch_128x8_verify_wall",
                 platform=last.get("platform", "cpu")),
            ts=time.time())
        benchwatch.append_emission(
            dict(prec, metric="das_fk20_produce_wall",
                 platform=last.get("platform", "cpu")),
            ts=time.time())
    finally:
        if prev_hist is None:
            os.environ.pop("CST_BENCHWATCH_HISTORY", None)
        else:
            os.environ["CST_BENCHWATCH_HISTORY"] = prev_hist
    hist_records, skipped, warns = benchwatch.load_history(hist_file)
    fresh = {r["metric"]: r for r in hist_records
             if isinstance(r.get("ts"), (int, float))
             and r["ts"] >= das_t0 - 5}
    for name in ("das_cell_proof_batch_128x8_verify_wall",
                 "das::verify_wall@128x8", "das::speedup",
                 "das::cells_per_s",
                 "das_fk20_produce_wall", "das::produce_wall",
                 "das::producer_speedup", "das::proofs_per_s",
                 "das::recover_wall", "das::recover_speedup"):
        hrec = fresh.get(name)
        assert hrec is not None, (name, sorted(fresh))
        assert not benchwatch.validate_record(hrec), hrec
        assert hrec["platform"] == "cpu", hrec
        if name.startswith("das::"):
            assert hrec["source"] == "das", hrec
    wrec = fresh["das::verify_wall@128x8"]
    assert wrec["das"]["matrix"]["cells"] == 1024, wrec
    assert wrec["vs_baseline"] >= 2.0, wrec
    pwrec = fresh["das::produce_wall"]
    assert pwrec["das_producer"]["parity"] is True, pwrec
    assert pwrec["vs_baseline"] >= 4.0, pwrec
    rwrec = fresh["das::recover_wall"]
    assert rwrec["das_recover"]["roundtrip"] is True, rwrec
    assert rwrec["vs_baseline"] >= 2.0, rwrec
    print(f"das history OK: {len(fresh)} records this run -> "
          f"{hist_file}")

    # the report renders the DAS section and the threshold rows wire
    # up: das-speedup PASSes from the CPU record, das-throughput (a
    # chip number) reads 'no data'
    from consensus_specs_tpu.telemetry import report as bw_report

    report_md = HERE / "out" / "smoke_das_report.md"
    rc = bw_report.main(["--repo", str(HERE), "--history",
                         str(hist_file), "--out", str(report_md),
                         "--no-update"])
    assert rc == 0, f"benchwatch report exited {rc}"
    text = report_md.read_text()
    assert "## DAS (PeerDAS cell-proof sampling)" in text, text[:2000]
    assert "| 128x8 | 1024 |" in text, text
    assert "Latest speedup over the pure-Python oracle:" in text
    assert "FK20 producer:" in text, text
    assert "Erasure recovery:" in text, text
    assert "Latest producer throughput:" in text, text
    result = bw_report.build_report(
        repo=HERE, history_path=hist_file, snapshots=[],
        durations_path=None, top_n=5, strict=False,
        max_regress_pct=0.0, update_history=False)
    rows = {t["id"]: t for t in result["thresholds"]}
    assert rows["das-speedup"]["status"] == "PASS", rows["das-speedup"]
    assert rows["das-producer-speedup"]["status"] == "PASS", \
        rows["das-producer-speedup"]
    assert rows["das-recover-speedup"]["status"] == "PASS", \
        rows["das-recover-speedup"]
    assert rows["das-throughput"]["status"] == "no data", \
        rows["das-throughput"]
    print(f"das report OK: DAS section rendered, das-speedup + "
          f"das-producer-speedup + das-recover-speedup PASS, "
          f"TPU-gated das-throughput reads 'no data' on CPU -> "
          f"{report_md}")
    print("das smoke: PASS")


def forkchoice_main():
    """The fc-smoke lane (`make fc-smoke` / CI): the device LMD-GHOST
    sweep on a tiny CPU tree, asserting the `"forkchoice"` block
    schema, the >= 2x `fc-speedup` acceptance vs the phase0 spec
    oracle (shape-bound: the oracle walks every active validator per
    child in pure Python), bit-exact head parity, the `forkchoice::*`
    history-record round-trip, and the report's Fork choice section —
    `fc-speedup` must PASS on CPU, `fc-head-throughput` (a chip
    number) must read 'no data'."""
    from consensus_specs_tpu.telemetry import validate_forkchoice_block

    hist_env = os.environ.get("CST_BENCHWATCH_HISTORY")
    hist_file = Path(hist_env) if hist_env \
        else HERE / "out" / "smoke_fc_history.jsonl"
    hist_file.parent.mkdir(exist_ok=True)
    if not hist_env and hist_file.exists():
        hist_file.unlink()
    fc_t0 = time.time()
    out = _run(["bench.py", "--worker", "forkchoice"],
               {"CST_FC_MATRIX": "64x1024",
                "CST_FC_ORACLE_VALIDATORS": "256",
                "CST_NO_COMPILE_CACHE": "1", "CST_TELEMETRY": "1"},
               timeout=900)
    last = out[-1]
    rec = last.get("forkchoice_lmd_ghost_64x1024_head_wall")
    assert isinstance(rec, dict) and rec.get("value", 0) > 0, last
    block = rec.get("forkchoice")
    problems = validate_forkchoice_block(block)
    assert not problems, (problems, json.dumps(block)[:500])
    assert block["tree"]["blocks"] == 64, block
    assert block["tree"]["validators"] == 1024, block
    assert block["rungs"]["blocks"] == 64, block
    # the acceptance criteria: >= 2x over the spec oracle on this CPU,
    # with the device head bit-identical to the oracle's
    assert block["speedup"] >= 2.0, block
    assert block["parity"] is True, block
    assert rec["vs_baseline"] == block["speedup"], rec
    _check_telemetry(rec, "forkchoice worker")
    print("forkchoice worker JSON OK:", json.dumps(
        {k: v for k, v in rec.items() if k != "telemetry"}))

    # the forkchoice record kind round-trips through the store (the
    # parent appends, like the driver does for extras workers)
    prev_hist = os.environ.get("CST_BENCHWATCH_HISTORY")
    os.environ["CST_BENCHWATCH_HISTORY"] = str(hist_file)
    try:
        benchwatch.append_emission(
            dict(rec, metric="forkchoice_lmd_ghost_64x1024_head_wall",
                 platform=last.get("platform", "cpu")),
            ts=time.time())
    finally:
        if prev_hist is None:
            os.environ.pop("CST_BENCHWATCH_HISTORY", None)
        else:
            os.environ["CST_BENCHWATCH_HISTORY"] = prev_hist
    hist_records, skipped, warns = benchwatch.load_history(hist_file)
    fresh = {r["metric"]: r for r in hist_records
             if isinstance(r.get("ts"), (int, float))
             and r["ts"] >= fc_t0 - 5}
    for name in ("forkchoice_lmd_ghost_64x1024_head_wall",
                 "forkchoice::head_wall@64x1024", "forkchoice::speedup",
                 "forkchoice::heads_per_s"):
        hrec = fresh.get(name)
        assert hrec is not None, (name, sorted(fresh))
        assert not benchwatch.validate_record(hrec), hrec
        assert hrec["platform"] == "cpu", hrec
        if name.startswith("forkchoice::"):
            assert hrec["source"] == "forkchoice", hrec
    wrec = fresh["forkchoice::head_wall@64x1024"]
    assert wrec["forkchoice"]["tree"]["blocks"] == 64, wrec
    assert wrec["vs_baseline"] >= 2.0, wrec
    print(f"forkchoice history OK: {len(fresh)} records this run -> "
          f"{hist_file}")

    # the report renders the Fork choice section and the threshold
    # rows wire up: fc-speedup PASSes from the CPU record,
    # fc-head-throughput (a chip number) reads 'no data'
    from consensus_specs_tpu.telemetry import report as bw_report

    report_md = HERE / "out" / "smoke_fc_report.md"
    rc = bw_report.main(["--repo", str(HERE), "--history",
                         str(hist_file), "--out", str(report_md),
                         "--no-update"])
    assert rc == 0, f"benchwatch report exited {rc}"
    text = report_md.read_text()
    assert "## Fork choice (device LMD-GHOST)" in text, text[:2000]
    assert "| 64x1024 |" in text, text
    assert "Latest head speedup over the phase0 spec oracle:" in text
    result = bw_report.build_report(
        repo=HERE, history_path=hist_file, snapshots=[],
        durations_path=None, top_n=5, strict=False,
        max_regress_pct=0.0, update_history=False)
    rows = {t["id"]: t for t in result["thresholds"]}
    assert rows["fc-speedup"]["status"] == "PASS", rows["fc-speedup"]
    assert rows["fc-head-throughput"]["status"] == "no data", \
        rows["fc-head-throughput"]
    print(f"forkchoice report OK: Fork choice section rendered, "
          f"fc-speedup PASS, TPU-gated fc-head-throughput reads "
          f"'no data' on CPU -> {report_md}")
    print("forkchoice smoke: PASS")


if __name__ == "__main__":
    if "--chaos-mesh" in sys.argv:
        chaos_main(mesh=True)
    elif "--chaos" in sys.argv:
        chaos_main()
    elif "--shard" in sys.argv:
        shard_main()
    elif "--das" in sys.argv:
        das_main()
    elif "--forkchoice" in sys.argv:
        forkchoice_main()
    else:
        main()
